"""Independent-key lifting — the batch axis of the framework.

Mirrors ``jepsen/independent.clj``: a test of one register lifts to a
map of keys to registers by wrapping op values in ``(k, v)`` tuples,
partitioning the history per key, and checking each subhistory with a
base checker (``independent.clj:252-300``).

TPU-native twist: when the base checker is :class:`~.checkers.Linearizable`,
all per-key subhistories are packed against ONE shared memoized model and
checked as a single vmapped (optionally mesh-sharded) device launch
(:mod:`comdb2_tpu.checker.batch`) instead of one JVM ``check`` per key —
this is BASELINE config 5, the per-key data parallelism of SURVEY §2.5
item 5 moved onto the device batch axis.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence

from ..ops.kv import KVTuple, is_tuple, tuple_
from ..ops.op import Op
from .checkers import Checker, Linearizable, check_safe, merge_valid


def wrap_keyed_history(history: Iterable[Op]) -> List[Op]:
    """Re-tag 2-element tuple values as :class:`KVTuple`. EDN histories
    (e.g. from the C register driver) carry ``[k v]`` vectors with no
    type marker; call this when a history is known to be keyed."""
    out = []
    for op in history:
        v = op.value
        if (isinstance(v, (tuple, list)) and len(v) == 2
                and not isinstance(v, KVTuple)):
            op = op.with_(value=KVTuple(v[0], v[1]))
        out.append(op)
    return out


def history_keys(history: Iterable[Op]) -> List[Any]:
    """Distinct keys in first-appearance order
    (``independent.clj:227-238``)."""
    seen: Dict[Any, None] = {}
    for op in history:
        if is_tuple(op.value):
            seen.setdefault(op.value.key, None)
    return list(seen)


def subhistory(k, history: Iterable[Op]) -> List[Op]:
    """All ops without a differing key, tuples unwrapped — un-keyed ops
    (nemesis infos, logging) appear in every subhistory
    (``independent.clj:240-250``)."""
    out = []
    for op in history:
        v = op.value
        if not is_tuple(v):
            out.append(op)
        elif v.key == k:
            out.append(op.with_(value=v.value))
    return out


class IndependentChecker(Checker):
    """Lift a base checker over keyed histories: valid iff valid for
    every key's subhistory; per-key results under ``"results"``, invalid
    keys under ``"failures"`` (``independent.clj:252-300``)."""

    def __init__(self, base: Checker, batch_frontier: int = 256,
                 mesh=None):
        self.base = base
        self.batch_frontier = batch_frontier
        self.mesh = mesh

    def check(self, test, model, history, opts=None):
        import os

        from ..harness.store import artifact_dir

        ks = history_keys(history)
        subs = {k: subhistory(k, history) for k in ks}
        # per-key artifact routing: a failing base checker writes its
        # counterexample under independent/<k>/ (the reference's per-key
        # store layout) instead of every key clobbering one linear.svg
        base_dir = artifact_dir(test, opts)

        def key_opts(k):
            if base_dir is None:
                return opts
            return {**(opts or {}),
                    "dir": os.path.join(base_dir, "independent", str(k))}

        # honor an explicit host backend: fault-heavy harness histories
        # have retirement-inflated process counts whose one-off device
        # shapes cost minutes of compile for milliseconds of work
        device_ok = not (isinstance(self.base, Linearizable)
                         and getattr(self.base, "backend", None) == "host")
        if isinstance(self.base, Linearizable) and len(ks) > 1 \
                and device_ok:
            results = self._check_linearizable_batch(model, subs,
                                                     key_opts)
        else:
            results = {k: check_safe(self.base, test, model, subs[k],
                                     key_opts(k))
                       for k in ks}
        self._write_artifacts(test, subs, results, opts)
        # false > unknown > true, like compose; only definitively-invalid
        # keys are failures (the reference treats :unknown as truthy,
        # independent.clj:288-295)
        valid = merge_valid([r.get("valid?") for r in results.values()])
        failures = [k for k, r in results.items()
                    if r.get("valid?") is False]
        return {"valid?": valid, "results": results, "failures": failures}

    def _write_artifacts(self, test, subs, results, opts) -> None:
        """Persist per-key results.edn + history.edn under
        ``independent/<k>/`` in the test's store dir when one exists
        (``independent.clj:272-283``); best-effort."""
        import os

        from ..harness.store import artifact_dir

        base = artifact_dir(test, opts)
        if base is None:
            return
        from ..harness.store import _edn_safe
        from ..ops.edn import write_edn
        from ..ops.history import history_to_edn

        try:
            for k, r in results.items():
                d = os.path.join(base, "independent", str(k))
                os.makedirs(d, exist_ok=True)
                with open(os.path.join(d, "results.edn"), "w") as fh:
                    fh.write(write_edn(_edn_safe(r)))
                with open(os.path.join(d, "history.edn"), "w") as fh:
                    fh.write(history_to_edn(subs[k]))
        except Exception:
            # genuinely best-effort: an unserializable payload must not
            # turn an already-computed verdict into :unknown
            pass

    def _check_linearizable_batch(self, model, subs: Dict[Any, List[Op]],
                                  key_opts=lambda k: None
                                  ) -> Dict[Any, dict]:
        """One device launch for all keys; unknowns (frontier overflow)
        and packing failures fall back to the per-key escalating path."""
        from ..ops.packed import pack_history
        from . import batch as B
        from . import linear_jax as LJ

        ks = list(subs)
        try:
            packeds = [pack_history(list(subs[k])) for k in ks]
            pb = B.pack_batch(packeds, model)
            status, fail_at, _ = B.check_batch(pb, F=self.batch_frontier,
                                               mesh=self.mesh)
        except Exception:
            return {k: check_safe(self.base, {}, model, subs[k],
                                  key_opts(k))
                    for k in ks}
        results: Dict[Any, dict] = {}
        for i, k in enumerate(ks):
            st = int(status[i])
            if st == LJ.VALID:
                results[k] = {"valid?": True, "backend": "device-batch"}
            else:
                # invalid or overflow: re-check solo for an exact verdict
                # with escalation and a decoded counterexample
                results[k] = check_safe(self.base, {}, model, subs[k],
                                        key_opts(k))
        return results


def checker(base: Checker, **kw) -> IndependentChecker:
    return IndependentChecker(base, **kw)
