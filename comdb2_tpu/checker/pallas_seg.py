"""Fully-fused Pallas TPU engine for the segmented frontier search.

The XLA engines (:mod:`.linear_jax`) express one closure iteration as
~40 small device ops; on a 50k-op history the per-op fixed overhead —
not the arithmetic — dominates (measured ~45 us/iteration on v5e while
the same data fits one vector register). This engine instead runs the
ENTIRE segment loop inside one Pallas kernel per 1024-segment chunk:

- The frontier is an ``(8, 128)`` int32 key-pair buffer — exactly one
  vreg per word — living in VMEM scratch that persists across the
  sequential grid. Row 0 holds the F=128 config frontier; rows 1..P
  hold the P candidate chunks of a closure expansion (hence P <= 7).
- A config is a packed (hi, lo) key, fields as in
  ``linear_jax.KeyLayout``: P slot fields (0=linearized, 1=idle,
  t+2=pending transition t) then the state field. Invalid lanes hold a
  sentinel (hi = 1<<30) that sorts after every valid key.
- Dedup = full 1024-lane bitonic sort over the flattened buffer (55
  compare-exchange stages, each ~a dozen single-vreg VPU ops), duplicate
  marking via neighbour compare, then a second sort to compact
  survivors into row 0. Exact, like the XLA engines' sort-adjacency
  dedup — never hash-fingerprint ordering.
- The memoized successor table rides in VMEM as a flat (8, 128) block;
  ``succ[s, t]`` is an unrolled row-broadcast + per-lane
  ``take_along_axis`` gather (Mosaic supports same-shape lane gathers),
  so the whole model step stays in-kernel. Tables up to 4096 entries
  ride a (32, 128) VMEM block (bucketed to bound recompiles).
- The segment stream (ok_proc, depth, invokes) is a scalar-prefetch
  array; SMEM bounds it to ~1.5k segments per call, so the host jits a
  ``lax.scan`` over 1024-segment chunks, carrying the frontier buffers
  and (status, fail, n) between calls.

Semantics match ``check_device_seg`` exactly: per ok-op segment, apply
invokes, run the linearization closure at most ``depth`` iterations
(stopping at a fixed point), keep configs whose ok-slot linearized,
empty frontier => INVALID at that segment, >128 unique configs =>
UNKNOWN (the reference's OOM-abort contract, ``linear.clj:318-326``).
Falls back unavailable (see :func:`spec_for`) when P, the key budget,
or the table don't fit — the driver then uses the XLA engines.
"""

from __future__ import annotations

import functools
import logging
from typing import NamedTuple, Optional

import numpy as np

from ..obs import trace as _obs
from ..utils import next_pow2 as _next_pow2

logger = logging.getLogger(__name__)

ROWS, LANES = 8, 128
N = ROWS * LANES          # flat sort width
F = LANES                 # frontier capacity (row 0)
CHUNK = 1024              # segments per kernel call. SMEM-bounded in
                          # TWO ways: the scalar-prefetch array
                          # (~14336 int32) AND a per-grid-step SMEM
                          # cost (~500 B/step toward the 1 MB space) —
                          # a 2048-step grid fails Mosaic compile with
                          # "Exceeded smem capacity" even at width 4,
                          # while 1408 steps compile. 1024 is the
                          # known-good cap; raising it bought ~noise
                          # (+1.5% on the 50k bench, within tunnel
                          # variance) before hitting the wall.
CHUNK_INTERPRET = 16      # interpret mode unrolls the grid at trace
                          # time — a 1024-step chunk would trace 1024
                          # kernel bodies
MAX_STREAM_B = 2048       # histories per streamed call (VMEM-bounded:
                          # two (B,128) int32 result blocks = 2 MB)

SENT_HI = np.int32(1 << 30)
SENT_LO = np.int32(0)

# status codes (match linear_jax)
VALID, INVALID, UNKNOWN = 0, 1, 2


MAX_TABLE = 8 * N          # successor-table entries the kernel serves
                           # (64 VMEM rows; the gather unrolls per
                           # row, so big tables pay compile+run cost
                           # only in specs that need them)

import os as _os

# Pallas interpret mode: runs the kernel as plain XLA ops on ANY
# backend — the only way to execute the kernel's exact semantics on
# the CPU test mesh (Mosaic is TPU-only; round-3 VERDICT #3: the
# production kernel was never validated on a sharded mesh anywhere
# but single-chip TPU). Enabled explicitly (use_interpret) or via
# COMDB2_TPU_PALLAS_INTERPRET=1 — NOT auto-enabled: per-spec
# interpret compiles cost ~40 s each on CPU, which would swamp the
# test suite.
_INTERPRET = _os.environ.get("COMDB2_TPU_PALLAS_INTERPRET") == "1"


def interpret_active() -> bool:
    return _INTERPRET


def use_interpret(on: bool = True) -> None:
    """Toggle interpret mode; clears the compiled-call and
    availability caches (specs differ: interpret chunks are short)."""
    global _INTERPRET
    if _INTERPRET == on:
        return
    _INTERPRET = on
    _chunk_call.cache_clear()
    _chunk_jit.cache_clear()
    _scan_fn.cache_clear()
    _sharded_scan_fn.cache_clear()
    _reset_fn.cache_clear()
    _CARRY_POOL.clear()
    available.cache_clear()


# Carry donation (continuous-batching round): the streamed scan's
# carry buffers (frontier words, stat row, results block) are marked
# donate_argnums so XLA aliases them into the scan outputs instead of
# allocating a second copy, and finished carries are RECYCLED through
# a per-(spec, b_pad, device) pool — the next dispatch of a hot bucket
# re-initializes the previous dispatch's device buffers with a tiny
# on-device ``carry_reset`` program instead of re-uploading initial
# values over the ~25 MB/s tunnel. Gated (env or use_carry_donation)
# so the donated and non-donated paths can be bit-compared.
_DONATE = _os.environ.get("COMDB2_TPU_DONATE_CARRIES", "1") != "0"

#: carries re-initialized on device instead of re-uploaded — the
#: serving metrics mirror this next to MOSAIC_BUILDS
CARRY_REUSES = 0

#: recycled (ws_tuple, stat) carry sets per (spec, b_pad, device) —
#: bounded per key; entries are device arrays from finished dispatches
_CARRY_POOL: dict = {}
_CARRY_POOL_CAP = 4


def donation_active() -> bool:
    return _DONATE


def use_carry_donation(on: bool = True) -> None:
    """Toggle carry donation + pooling (the parity tests compare the
    two paths bit-for-bit). Disabling drops the pooled device buffers;
    the jitted scan variants are cached per flag, so no recompiles."""
    global _DONATE
    if _DONATE == on:
        return
    _DONATE = on
    _CARRY_POOL.clear()


class SegKernelSpec(NamedTuple):
    """Static key layout + table geometry for one compiled kernel.

    Deliberately does NOT carry the exact (n_states, n_transitions):
    the table stride is a runtime scalar and ``table_rows`` is
    pow2-bucketed, so all memo shapes with the same log-scale field
    widths share ONE compiled kernel. Per-shape Mosaic compiles are
    slow and can OOM LLVM (CLAUDE.md); production ``analysis()`` loops
    see many slightly-different shapes (ADVICE r1)."""
    P: int                 # slot count (<= rows - 1)
    K: int                 # max invokes per segment
    slot_bits: int
    state_bits: int
    # (word, shift) per slot q, and for the state field; word 0 is the
    # LEAST significant sort key, word n_words-1 the most significant
    slot_pos: tuple
    state_pos: tuple
    table_rows: int        # pow2 bucket of ceil(S*T / LANES)
    chunk: int             # segments per kernel call (SMEM-bounded)
    table_rows_pad: int    # table buffer rows (bucketed: 8 or 32)
    rows: int              # buffer rows: 8 (P<=7) or 16 (P<=15)
    n_words: int           # int32 key words per config (2 or 3)


def spec_for(n_states: int, n_transitions: int, P: int,
             K: int) -> Optional[SegKernelSpec]:
    """Build the static spec, or None when this shape can't run in the
    fused kernel (caller falls back to the XLA engines).

    P <= 7 runs the classic (8,128) one-vreg-per-word geometry; P <= 15
    a (16,128) buffer (candidate chunks live in rows 1..P) with up to
    THREE key words — the round-3 VERDICT #2 extension that serves the
    reference register test's concurrency 10 (comdb2/core.clj:567-613)
    on the production kernel."""
    if K > 8:
        return None
    rows = ROWS if P <= ROWS - 1 else 2 * ROWS
    if P > rows - 1:
        return None
    if n_states * n_transitions > MAX_TABLE:
        return None
    slot_bits = max(int(np.ceil(np.log2(max(n_transitions + 2, 2)))), 1)
    state_bits = max(int(np.ceil(np.log2(max(n_states, 2)))), 1)
    pos = []
    word, shift = 0, 0
    for width in [slot_bits] * P + [state_bits]:
        if width > 29:
            return None
        if shift + width > 31:
            word, shift = word + 1, 0
        pos.append((word, shift))
        shift += width
    # the most significant word must keep bits 29/30 free (the okp
    # flag has no kernel analog, but the sentinel 1<<30 must sort
    # after every valid key); spill to a fresh word when the last
    # field crosses bit 30
    n_words = word + 1
    if shift > 30:
        n_words += 1
    if n_words > 3:
        return None
    table_rows = _next_pow2(-(-(n_states * n_transitions) // LANES))
    table_rows_pad = (ROWS if table_rows <= ROWS
                      else (4 * ROWS if table_rows <= 4 * ROWS
                            else 8 * ROWS))
    # SMEM holds the scalar-prefetch stream: keep chunk * width under
    # ~56KB (measured limit ~60KB on v5e), in multiples of 128
    width = 2 + 2 * K
    chunk = min(CHUNK, (14336 // width) // 128 * 128)
    if _INTERPRET:
        chunk = CHUNK_INTERPRET
    return SegKernelSpec(P, K, slot_bits, state_bits,
                         tuple(pos[:P]), pos[P],
                         table_rows, chunk, table_rows_pad,
                         rows, n_words)


#: small-delta chunk rungs for the STREAMING kernel rung only (the
#: batch/driver path always scans full chunks): a 16-op append on a
#: spec.chunk=1024 program pays the whole 1024-step grid — these
#: spec._replace(chunk=...) variants keep the carry geometry (rows,
#: n_words are chunk-independent) while shrinking the grid, at the
#: price of at most len(STREAM_CHUNKS) extra Mosaic builds per base
#: spec. Closed ladder: PROGRAMS.md stream-delta declares them.
STREAM_CHUNKS = (64, 256)


def delta_spec(spec: SegKernelSpec, n_segments: int) -> SegKernelSpec:
    """The smallest declared chunk rung serving an ``n_segments``
    delta (the base spec when none is smaller — interpret mode's
    chunk=16 already undercuts the ladder and passes through)."""
    for c in STREAM_CHUNKS:
        if c >= n_segments and c < spec.chunk:
            return spec._replace(chunk=c)
    return spec


def pack_table(succ: np.ndarray, rows: int = ROWS) -> np.ndarray:
    """Flatten the successor table into a (rows, 128) int32 block
    (row-major, padded with -1)."""
    flat = np.full(rows * LANES, -1, np.int32)
    flat[:succ.size] = np.ascontiguousarray(succ, np.int32).reshape(-1)
    return flat.reshape(rows, LANES)


def initial_frontier(spec: SegKernelSpec):
    """List of ``n_words`` (rows,128) host arrays (least-significant
    word first): lane 0 of row 0 = the empty config (all slots idle,
    state 0), everything else sentinel."""
    ws = [np.full((spec.rows, LANES),
                  SENT_HI if w == spec.n_words - 1 else SENT_LO,
                  np.int32)
          for w in range(spec.n_words)]
    for w, v in enumerate(_root_key(spec)):
        ws[w][0, 0] = v
    return ws


def _init_stat() -> np.ndarray:
    """Initial (1, 128) stat row: [status, fail, n, hist-counter] in
    lanes 0..3 — the layout the kernel's sstat load/flush assumes."""
    stat0 = np.zeros((1, LANES), np.int32)
    stat0[0, 0] = VALID
    stat0[0, 1] = -1
    stat0[0, 2] = 1
    stat0[0, 3] = -1
    return stat0


# --- kernel body helpers (traced; all shapes static) ------------------------
#
# Keys are lists ``ws`` of int32 word planes, least-significant word
# FIRST (ws[-1] is the primary sort key and carries the sentinel).

def _iotas(rows: int = ROWS):
    import jax.numpy as jnp
    from jax import lax

    row = lax.broadcasted_iota(jnp.int32, (rows, LANES), 0)
    lane = lax.broadcasted_iota(jnp.int32, (rows, LANES), 1)
    return row, lane, row * LANES + lane


def _fetch(x, j, lane, rows: int = ROWS):
    """Values at flat positions f+j and f-j (circular over the
    (rows,128) row-major order). j is a static power of two."""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    if j % LANES == 0:
        r = j // LANES
        return (pltpu.roll(x, rows - r, 0), pltpu.roll(x, r, 0))
    a = pltpu.roll(x, LANES - j, 1)          # (i, l) <- (i, (l+j)%128)
    b = pltpu.roll(a, rows - 1, 0)           # <- (i+1, (l+j)%128)
    plus = jnp.where(lane + j < LANES, a, b)
    c = pltpu.roll(x, j, 1)                  # (i, l) <- (i, (l-j)%128)
    d = pltpu.roll(c, 1, 0)                  # <- (i-1, ...)
    minus = jnp.where(lane - j >= 0, c, d)
    return plus, minus


def _ws_less(ws, pws):
    """Lexicographic < of key lists (most significant word last)."""
    less = None
    eq = None
    for w, pw in zip(reversed(ws), reversed(pws)):
        if less is None:
            less = w < pw
            eq = w == pw
        else:
            less = less | (eq & (w < pw))
            eq = eq & (w == pw)
    return less


def _ws_eq(ws, pws):
    eq = None
    for w, pw in zip(ws, pws):
        eq = (w == pw) if eq is None else (eq & (w == pw))
    return eq


def _cmp_exchange(ws, pws, take_min):
    """One bitonic compare-exchange: keep the lexicographic min or max
    of ``ws`` vs the partner ``pws`` per lane."""
    import jax.numpy as jnp

    mine_less = _ws_less(ws, pws)
    return [jnp.where(take_min == mine_less, w, pw)
            for w, pw in zip(ws, pws)]


def _sort_flat(ws, rows: int = ROWS):
    """Full ascending bitonic sort of the rows*128 flat keys."""
    import jax.numpy as jnp

    n = rows * LANES
    _, lane, flat = _iotas(rows)
    k = 2
    while k <= n:
        j = k // 2
        while j >= 1:
            is_low = (flat & j) == 0
            asc = (flat & k) == 0 if k < n else (flat >= 0)
            pws = []
            for w in ws:
                wp, wm = _fetch(w, j, lane, rows)
                pws.append(jnp.where(is_low, wp, wm))
            ws = _cmp_exchange(ws, pws, is_low == asc)
            j //= 2
        k *= 2
    return ws


def _sort_row(ws, rows: int = ROWS):
    """Ascending bitonic sort of the 128 lanes of EVERY row
    independently (lane rolls only — pairs never cross rows). Used by
    the mini tier, where the whole frontier+candidates fit one row."""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    _, lane, _ = _iotas(rows)
    k = 2
    while k <= LANES:
        j = k // 2
        while j >= 1:
            is_low = (lane & j) == 0
            asc = (lane & k) == 0 if k < LANES else (lane >= 0)
            pws = [jnp.where(is_low, pltpu.roll(w, LANES - j, 1),
                             pltpu.roll(w, j, 1)) for w in ws]
            ws = _cmp_exchange(ws, pws, is_low == asc)
            j //= 2
        k *= 2
    return ws


def _mini_width(P: int) -> int:
    """Frontier size served by the single-row tier: the 128 lanes
    split into P+1 equal groups (frontier + one per candidate chunk) —
    e.g. 42 configs at P=2, 18 at P=6, 11 at P=10."""
    return LANES // (P + 1)


def _sentinel(ws, cond):
    """Replace keys where ``cond`` with the sentinel."""
    import jax.numpy as jnp

    out = [jnp.where(cond, SENT_LO, w) for w in ws[:-1]]
    out.append(jnp.where(cond, SENT_HI, ws[-1]))
    return out


def _dedup_count_row(ws, rows: int):
    """Row-0 dedup after a row sort: sentinel the duplicate neighbours,
    count unique valid keys in row 0."""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    row, lane, _ = _iotas(rows)
    prev = [pltpu.roll(w, 1, 1) for w in ws]
    valid = ws[-1] < SENT_HI
    dup = valid & _ws_eq(ws, prev) & (lane > 0)
    keep = valid & ~dup
    n = jnp.sum((keep & (row == 0)).astype(jnp.int32))
    return _sentinel(ws, ~keep), n


def _mini_expand(spec, table, stride, ws):
    """Single-row expansion: frontier in lanes 0..M-1 of row 0
    (M = _mini_width(P)); candidate chunk q lands at lanes
    [M*(q+1), M*(q+2)). All rows compute in lockstep; only row 0 is
    meaningful. ``stride`` is the runtime table row stride
    (= the model's exact n_transitions)."""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    M = _mini_width(spec.P)
    _, lane, _ = _iotas(spec.rows)
    group = lane // M
    fvalid = (ws[-1] < SENT_HI) & (lane < M)
    s = _field(spec, ws, spec.state_pos, spec.state_bits)
    sbase = s * stride               # loop-invariant row base
    out = list(ws)
    for q in range(spec.P):
        tq = _field(spec, ws, spec.slot_pos[q], spec.slot_bits)
        pending = tq >= 2
        idx = sbase + jnp.maximum(tq - 2, 0)
        s2 = _gather_table(table, idx, spec.table_rows, spec.rows)
        ok = fvalid & pending & (s2 >= 0)
        cand = _field_add(spec, ws, spec.slot_pos[q], -tq)
        cand = _field_add(spec, cand, spec.state_pos, s2 - s)
        cand = _sentinel(cand, ~ok)
        m = group == q + 1
        out = [jnp.where(m, pltpu.roll(c, M * (q + 1), 1), o)
               for c, o in zip(cand, out)]
    pad = group > spec.P           # unused groups when P < rows-1
    return _sentinel(out, pad)


def _dedup_count(ws, rows: int):
    """After a sort: mark duplicate neighbours, return (ws', n) with
    dups sentinelled and n = number of unique valid keys."""
    import jax.numpy as jnp

    _, lane, flat = _iotas(rows)
    # previous element = fetch at flat position -1
    prev = [_fetch(w, 1, lane, rows)[1] for w in ws]
    valid = ws[-1] < SENT_HI
    dup = valid & _ws_eq(ws, prev) & (flat > 0)
    keep = valid & ~dup
    n = jnp.sum(keep.astype(jnp.int32))
    return _sentinel(ws, ~keep), n


def _field(spec, ws, pos, bits):
    word, sh = pos
    return (ws[word] >> sh) & ((1 << bits) - 1)


def _field_add(spec, ws, pos, delta):
    """Add a (vector) delta into a field; caller guarantees the field
    stays in range so no borrow crosses field boundaries."""
    word, sh = pos
    out = list(ws)
    out[word] = out[word] + (delta << sh)
    return out


def _gather_table(table, idx, table_rows, rows: int = ROWS):
    """Flat-indexed gather from a (table_rows_pad, 128) block:
    out[e] = table_flat[idx[e]], idx < table_rows*128. Unrolled
    row-broadcast + lane gather."""
    import jax.numpy as jnp

    out = jnp.full((rows, LANES), -1, jnp.int32)
    r = idx >> 7
    c = idx & 127
    for rr in range(table_rows):
        rowv = jnp.broadcast_to(table[rr:rr + 1, :], (rows, LANES))
        g = jnp.take_along_axis(rowv, c, axis=1)
        out = jnp.where(r == rr, g, out)
    return out


def _expand(spec, table, stride, ws):
    """Rows 1..P <- candidates (slot q of each frontier config
    linearized), rows P+1.. <- sentinel. Row 0 (the frontier) is kept.
    ``stride`` is the runtime table row stride."""
    import jax.numpy as jnp

    row, _, _ = _iotas(spec.rows)
    f = [jnp.broadcast_to(w[0:1, :], (spec.rows, LANES)) for w in ws]
    fvalid = f[-1] < SENT_HI
    s = _field(spec, f, spec.state_pos, spec.state_bits)
    sbase = s * stride               # loop-invariant row base
    out = list(ws)
    for q in range(spec.P):
        tq = _field(spec, f, spec.slot_pos[q], spec.slot_bits)
        pending = tq >= 2
        idx = sbase + jnp.maximum(tq - 2, 0)
        s2 = _gather_table(table, idx, spec.table_rows, spec.rows)
        ok = fvalid & pending & (s2 >= 0)
        cand = _field_add(spec, f, spec.slot_pos[q], -tq)
        cand = _field_add(spec, cand, spec.state_pos, s2 - s)
        cand = _sentinel(cand, ~ok)
        m = row == (q + 1)
        out = [jnp.where(m, c, o) for c, o in zip(cand, out)]
    return _sentinel(out, row > spec.P)


def _slot_field_runtime(spec, ws, p):
    """Extract slot p where p is a runtime scalar (unrolled select)."""
    import jax.numpy as jnp

    out = jnp.zeros((spec.rows, LANES), jnp.int32)
    for q in range(spec.P):
        out = jnp.where(p == q,
                        _field(spec, ws, spec.slot_pos[q],
                               spec.slot_bits),
                        out)
    return out


def _slot_add_runtime(spec, ws, p, delta, mask):
    """Add delta to slot p (runtime scalar) on lanes where mask."""
    import jax.numpy as jnp

    for q in range(spec.P):
        cand = _field_add(spec, ws, spec.slot_pos[q], delta)
        m = mask & (p == q)
        ws = [jnp.where(m, c, w) for c, w in zip(cand, ws)]
    return ws


RESET = -2     # ok_proc marker: flush current history, start the next


def _root_key(spec):
    """Per-word ints (least significant first) of the empty config
    (all slots IDLE, state 0)."""
    words = [0] * spec.n_words
    for q in range(spec.P):
        w, sh = spec.slot_pos[q]
        words[w] |= 1 << sh
    return words


def _build_kernel(spec: SegKernelSpec):
    """The chunk kernel. Grid = (chunk,); scalar-prefetch args:
    seg[chunk, 2+2K] (ok_proc, depth, inv_proc.., inv_tr..) and
    off[1] (global segment offset). Inputs: n_words key-word carries
    (rows,128), carry_stat (1,128) [status, fail, n, hist-counter],
    results (B_pad, 128), table (rows,128). Outputs: the same carries.

    A segment with ok_proc == RESET is a history boundary in a
    multi-history stream: the current history's (status, fail, n) row
    is stored at results[counter], the counter advances, and the
    frontier/status reset to the initial state. Single-history runs
    simply have no RESET segments and ignore the results buffer."""
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    P, K, W, rows = spec.P, spec.K, spec.n_words, spec.rows

    root = _root_key(spec)

    def kernel(seg_ref, off_ref, *refs):
        # refs: W word carries in, st_in, res_in, tab_ref,
        #       W word carries out, st_out, res_out,
        #       W VMEM word scratch, sstat SMEM
        ws_in = refs[:W]
        st_in, res_in, tab_ref = refs[W], refs[W + 1], refs[W + 2]
        ws_out = refs[W + 3:2 * W + 3]
        st_out, res_out = refs[2 * W + 3], refs[2 * W + 4]
        wsc = refs[2 * W + 5:3 * W + 5]
        sstat = refs[3 * W + 5]
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            for w in range(W):
                wsc[w][:] = ws_in[w][:]
            res_out[:] = res_in[:]
            sstat[0] = st_in[0, 0]      # status
            sstat[1] = st_in[0, 1]      # fail seg (global)
            sstat[2] = st_in[0, 2]      # frontier count
            sstat[6] = st_in[0, 3]      # history counter (stream mode)

        ok_p = seg_ref[i, 0]
        depth = seg_ref[i, 1]

        @pl.when(ok_p == RESET)
        def _():
            row, lane, _ = _iotas(rows)
            cnt = sstat[6]

            @pl.when(cnt >= 0)
            def _():
                stat_row = jnp.where(
                    lane[0:1, :] == 0, sstat[0],
                    jnp.where(lane[0:1, :] == 1, sstat[1],
                              jnp.where(lane[0:1, :] == 2, sstat[2],
                                        0)))
                res_out[pl.ds(cnt, 1), :] = stat_row

            sstat[6] = cnt + 1
            sstat[0] = VALID
            sstat[1] = -1
            sstat[2] = 1
            at_root = (row == 0) & (lane == 0)
            for w in range(W):
                sent = SENT_HI if w == W - 1 else SENT_LO
                wsc[w][:] = jnp.where(at_root, root[w], sent)

        live = (sstat[0] == VALID) & (ok_p >= 0)

        @pl.when(live)
        def _():
            row, lane, _ = _iotas(rows)
            ws = [wsc[w][:] for w in range(W)]
            table = tab_ref[:]
            stride = off_ref[1]      # runtime table row stride
            frow = row == 0
            # --- invokes: slot p IDLE(1) -> tr+2 (delta tr+1) --------
            for k in range(K):
                p = seg_ref[i, 2 + k]
                tr = seg_ref[i, 2 + K + k]
                m = frow & (ws[-1] < SENT_HI) & (p >= 0)
                ws = _slot_add_runtime(spec, ws, p, tr + 1, m)

            # --- lazy compaction (round 5): the ok filter no longer
            # sorts survivors forward every segment — the frontier may
            # enter SCATTERED across row 0. The full tier is
            # scatter-proof (masked broadcast); only the mini tier
            # needs the lanes-0..M-1 window, so compact exactly when a
            # mini-sized frontier would otherwise miss it. In mini
            # steady state (frontier stayed within the window) this
            # removes one 28-stage sort per segment.
            M = _mini_width(P)
            extent = jnp.max(jnp.where(
                frow & (ws[-1] < SENT_HI), lane + 1, 0))
            ws = list(lax.cond(
                (sstat[2] <= M) & (extent > M),
                lambda a: tuple(_sort_row(list(a), rows)),
                lambda a: a, tuple(ws)))

            # --- closure: bounded fixed point ------------------------
            # sstat[3]: continue flag, sstat[4]: overflow, sstat[5]: n
            sstat[3] = 1
            sstat[4] = 0
            sstat[5] = sstat[2]

            def body(it, carry):
                cws = list(carry)

                def run(args):
                    cws = list(args)

                    def full(args):
                        ews = _expand(spec, table, stride, list(args))
                        ews = _sort_flat(ews, rows)
                        ews, n2 = _dedup_count(ews, rows)
                        # flat extent of the deduped survivors: when
                        # they all sit in row 0 already, the next full
                        # iteration (masked row-0 broadcast) needs no
                        # compaction sort
                        _, _, flat = _iotas(rows)
                        ext = jnp.max(jnp.where(ews[-1] < SENT_HI,
                                                flat + 1, 0))
                        return tuple(ews) + (n2, ext)

                    def mini(args):
                        # frontier fits one lane group (128/(P+1)
                        # lanes): the whole iteration stays in row 0
                        # and the sorts are 28 lane-only stages
                        # instead of the full flat ones. Extent LANES+1
                        # forces the (row) compaction: dedup holes may
                        # leave survivors beyond the M-lane window the
                        # next mini read needs.
                        ews = _mini_expand(spec, table, stride,
                                           list(args))
                        ews = _sort_row(ews, rows)
                        ews, n2 = _dedup_count_row(ews, rows)
                        ews = _sentinel(ews, row > 0)
                        return tuple(ews) + (n2,
                                             jnp.int32(LANES + 1))

                    use_mini = sstat[5] <= M
                    out = lax.cond(use_mini, mini, full, tuple(cws))
                    ews, n2, ext = list(out[:W]), out[W], out[W + 1]
                    ovf = (n2 > F).astype(jnp.int32)
                    changed = (n2 > sstat[5]).astype(jnp.int32)
                    sstat[4] = sstat[4] | ovf
                    sstat[3] = changed & (1 - ovf)
                    sstat[5] = n2

                    def compact2(args):
                        was_mini = args[W]
                        return lax.cond(
                            was_mini,
                            lambda a: tuple(_sort_row(list(a), rows)),
                            lambda a: tuple(_sort_flat(list(a), rows)),
                            args[:W])

                    # no growth => the deduped union IS the previous
                    # frontier; restore it. Growth with every survivor
                    # already in row 0 (full tier, ext <= LANES) =>
                    # skip the compaction sort too — the next full
                    # iteration and the ok filter are both
                    # sentinel-mask-based over row 0
                    need_sort = (changed == 1) & \
                        (use_mini | (ext > LANES))
                    return lax.cond(
                        need_sort, compact2,
                        lambda a: lax.cond(changed == 1,
                                           lambda b: b[:W],
                                           lambda b: tuple(cws),
                                           a),
                        tuple(ews) + (use_mini,))

                return lax.cond(sstat[3] == 1, run, lambda a: a,
                                tuple(cws))

            ws = list(lax.fori_loop(0, depth, body, tuple(ws)))

            # --- ok filter: keep configs whose ok-slot linearized ----
            tq_ok = _slot_field_runtime(spec, ws, ok_p)
            returned = frow & (ws[-1] < SENT_HI) & (tq_ok == 0)
            # clear the slot back to IDLE (LIN=0 -> +1)
            ws = _slot_add_runtime(spec, ws, ok_p, 1, returned)
            ws = _sentinel(ws, frow & ~returned)
            n2 = jnp.sum(returned.astype(jnp.int32))
            # survivors stay SCATTERED in row 0 — the next segment
            # compacts lazily only if its mini tier needs the window
            # (see the closure-entry cond above); unconditional
            # re-sorting here cost 28 stages on every segment

            ovf = sstat[4] == 1
            st_new = jnp.where(ovf, UNKNOWN,
                               jnp.where(n2 == 0, INVALID, VALID))
            sstat[1] = jnp.where(st_new == VALID, sstat[1],
                                 off_ref[0] + i)
            sstat[0] = st_new
            sstat[2] = n2
            for w in range(W):
                wsc[w][:] = ws[w]

        @pl.when(i == pl.num_programs(0) - 1)
        def _():
            for w in range(W):
                ws_out[w][:] = wsc[w][:]
            _, lane0, _ = _iotas(rows)
            stat_row = jnp.where(
                lane0[0:1, :] == 0, sstat[0],
                jnp.where(lane0[0:1, :] == 1, sstat[1],
                          jnp.where(lane0[0:1, :] == 2, sstat[2],
                                    jnp.where(lane0[0:1, :] == 3,
                                              sstat[6], 0))))
            st_out[:] = stat_row

    return kernel


#: fused-kernel programs built this process — one Mosaic compile per
#: distinct (spec, b_pad); the compile-surface guard diffs it the way
#: bench_txn diffs closure_jax.DISPATCHES (utils/compile_guard.py)
MOSAIC_BUILDS = 0

#: streamed-kernel dispatches this process: one per
#: :func:`stream_dispatch` (single device) and one per
#: :func:`stream_dispatch_sharded` (ONE fused dispatch covering every
#: shard of a slice). The mesh parity suite and bench_multichip assert
#: the single-dispatch-per-shard-per-slice discipline on it.
DISPATCHES = 0


@functools.lru_cache(maxsize=32)
def _chunk_call(spec: SegKernelSpec, b_pad: int = 8):
    """b_pad: rows of the per-history results buffer (multi-history
    streams); single-history runs pass a dummy 8-row buffer."""
    global MOSAIC_BUILDS
    MOSAIC_BUILDS += 1
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel = _build_kernel(spec)
    W, rows = spec.n_words, spec.rows
    word_spec = pl.BlockSpec((rows, LANES), lambda i, *s: (0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(spec.chunk,),
        in_specs=[word_spec] * W + [
            pl.BlockSpec((1, LANES), lambda i, *s: (0, 0)),
            pl.BlockSpec((b_pad, LANES), lambda i, *s: (0, 0)),
            pl.BlockSpec((spec.table_rows_pad, LANES),
                         lambda i, *s: (0, 0)),
        ],
        out_specs=[word_spec] * W + [
            pl.BlockSpec((1, LANES), lambda i, *s: (0, 0)),
            pl.BlockSpec((b_pad, LANES), lambda i, *s: (0, 0)),
        ],
        scratch_shapes=[pltpu.VMEM((rows, LANES), jnp.int32)] * W
        + [pltpu.SMEM((8,), jnp.int32)])

    word_shape = jax.ShapeDtypeStruct((rows, LANES), jnp.int32)

    def call(seg, off, ws, stat, res, table):
        """``ws`` is the list/tuple of word carries; returns
        (ws_out_tuple, stat, res)."""
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=[word_shape] * W + [
                jax.ShapeDtypeStruct((1, LANES), jnp.int32),
                jax.ShapeDtypeStruct((b_pad, LANES), jnp.int32)],
            interpret=_INTERPRET,
        )(seg, off, *ws, stat, res, table)
        return tuple(out[:W]), out[W], out[W + 1]

    return call


def pack_segments(segs, spec: SegKernelSpec) -> np.ndarray:
    """SegmentStream -> (n_chunks, chunk, 2+2K) scalar array, padded
    with dead segments (ok_proc = -1)."""
    S = segs.ok_proc.shape[0]
    K, chunk = spec.K, spec.chunk
    n_chunks = max(-(-S // chunk), 1)
    W = 2 + 2 * K
    out = np.zeros((n_chunks, chunk, W), np.int32)
    out[:, :, 0] = -1
    flat = out.reshape(n_chunks * chunk, W)
    flat[:S, 0] = segs.ok_proc
    flat[:S, 1] = segs.depth
    k_in = segs.inv_proc.shape[1]
    flat[:S, 2:2 + k_in] = segs.inv_proc
    flat[:S, 2 + K:2 + K + k_in] = segs.inv_tr
    if k_in < K:
        flat[:S, 2 + k_in:2 + K] = -1
    return out


@functools.lru_cache(maxsize=32)
def _scan_fn(spec: SegKernelSpec, b_pad: int = 8,
             stream: bool = False, donate: bool = False):
    """Jitted scan over chunk calls. ``stream=False`` short-circuits
    dead chunks once the (single) history failed; stream mode always
    runs every chunk (later histories are still live) and threads the
    per-history results buffer through the scan. ``donate`` marks the
    carry buffers (ws0/stat0/res0) donated — XLA aliases them into the
    scan outputs instead of holding both copies live; callers must not
    reuse the donated input arrays (``stream_dispatch`` builds or
    recycles them fresh per call)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    call = _chunk_call(spec, b_pad)

    def run(seg_chunks, ws0, stat0, res0, table, stride):
        n_chunks = seg_chunks.shape[0]

        def step(carry, x):
            ws, stat, res = carry
            seg, off = x

            def live(_):
                return call(seg, off, ws, stat, res, table)

            if stream:
                out = live(None)
            else:
                out = lax.cond(stat[0, 0] == VALID, live,
                               lambda _: (ws, stat, res), None)
            return out, None

        starts = (jnp.arange(n_chunks, dtype=jnp.int32)
                  * jnp.int32(spec.chunk)).reshape(n_chunks, 1)
        offs = jnp.concatenate(
            [starts, jnp.full((n_chunks, 1), jnp.int32(stride))], axis=1)
        (ws, stat, res), _ = lax.scan(
            step, (tuple(ws0), stat0, res0), (seg_chunks, offs))
        return ws, stat, res

    if donate:
        return jax.jit(run, donate_argnums=(1, 2, 3))
    return jax.jit(run)


def check_device_pallas(succ: np.ndarray, segs, *, n_states: int,
                        n_transitions: int, P: int):
    """Run the fused-kernel search. Returns (status, fail_seg, n) as
    Python ints, or None when the shape can't run fused."""
    import jax.numpy as jnp

    prep = _prepare(succ, segs, n_states, n_transitions, P)
    if prep is None:
        return None
    spec, seg_chunks, ws0, stat0, table = prep
    run = _scan_fn(spec)
    res0 = jnp.zeros((8, LANES), jnp.int32)      # unused: no RESETs
    _, stat, _ = run(jnp.asarray(seg_chunks), tuple(ws0), stat0,
                     res0, table, n_transitions)
    stat = np.asarray(stat)
    return int(stat[0, 0]), int(stat[0, 1]), int(stat[0, 2])


@functools.lru_cache(maxsize=32)
def _chunk_jit(spec: SegKernelSpec):
    import jax

    return jax.jit(_chunk_call(spec))


def pack_stream(segs_list, spec: SegKernelSpec):
    """Concatenate per-history segment streams into one chunked stream
    with RESET markers: [R][h0][R][h1]...[R]. The first R starts
    history 0 (the counter begins at -1, so nothing is flushed); each
    later R flushes the previous history; the trailing R flushes the
    last. Returns (chunks[n,chunk,W], starts[B]) where starts[b] is
    history b's first segment's global stream index."""
    B = len(segs_list)
    W = 2 + 2 * spec.K
    sizes = [s.ok_proc.shape[0] for s in segs_list]
    total = sum(sizes) + B + 1
    chunk = spec.chunk
    n_chunks = max(-(-total // chunk), 1)
    flat = np.zeros((n_chunks * chunk, W), np.int32)
    flat[:, 0] = -1                       # default: dead padding
    starts = np.zeros(B, np.int64)
    pos = 0
    for b, segs in enumerate(segs_list):
        flat[pos, 0] = RESET
        pos += 1
        starts[b] = pos
        S = sizes[b]
        k_in = segs.inv_proc.shape[1]
        flat[pos:pos + S, 0] = segs.ok_proc
        flat[pos:pos + S, 1] = segs.depth
        flat[pos:pos + S, 2:2 + k_in] = segs.inv_proc
        if k_in < spec.K:
            flat[pos:pos + S, 2 + k_in:2 + spec.K] = -1
        flat[pos:pos + S, 2 + spec.K:2 + spec.K + k_in] = segs.inv_tr
        pos += S
    flat[pos, 0] = RESET                  # trailing flush
    return flat.reshape(n_chunks, chunk, W), starts


def check_device_pallas_stream(succ: np.ndarray, segs_list, *,
                               n_states: int, n_transitions: int,
                               P: int, devices=None):
    """Check MANY independent histories as one streamed kernel scan —
    the device form of ``independent/checker``'s per-key partitioning
    (``independent.clj:252-300``). One dispatch for the whole batch;
    per-history verdicts come back in the results buffer. Returns a
    list of (status, fail_seg_local, n) or None when the shape can't
    run fused. Every history gets its own verdict: one history's
    INVALID/UNKNOWN never stops the others (the RESET marker restores
    a live frontier).

    A row-parallel tier (8 history streams per kernel scan, one per
    buffer row) lived here through round 4 (commit b57bf53) and was
    REMOVED in round 5: it measured strictly slower on v5e at every
    real shape (256x800-event batch 73k -> 58k ops/s; 4096x2k
    97k -> 76k) because the lockstep closure iterates to the MAX depth
    of the 8 co-scheduled segments, per-row SMEM bookkeeping costs as
    much as the vector work at these shapes, and mini-frontier
    (M=128/(P+1)) overflows pay a second full-width pass — structural
    costs, not tuning gaps (round-4 VERDICT Weak #7).

    ``devices``: optional list of jax devices to spread the batch over
    (e.g. ``mesh.devices.flat``) — each device streams its own slice of
    whole histories, all dispatches in flight concurrently."""
    import jax.numpy as jnp

    K = max((s.inv_proc.shape[1] for s in segs_list), default=1)
    spec = spec_for(n_states, n_transitions, P, K + (K & 1))
    if spec is None:
        return None
    B = len(segs_list)
    if B == 0:
        return []
    # slice the batch: the results buffer is VMEM-resident (2 copies:
    # carry in + out) so each dispatch is capped at MAX_STREAM_B
    # histories; with multiple devices the slices also spread across
    # them (one independent dispatch per device, all in flight at
    # once — data parallelism with zero cross-device communication)
    devs = list(devices) if devices else [None]
    plan = plan_stream_slices(B, len(devs) if devs[0] is not None
                              else 0)
    pending = []
    for start, end, dev_ix in plan:
        dev = devs[dev_ix] if devs[0] is not None else None
        pending.append(stream_dispatch(succ, segs_list[start:end],
                                       spec, n_states, n_transitions,
                                       dev))
    out = []
    try:
        for (res, starts), (start, end, _) in zip(pending, plan):
            res = np.asarray(res)   # blocks on THIS slice's device only
            out.extend(merge_stream_slice(res, starts, end - start))
    except Exception:
        clear_carry_pool()          # recycled-at-dispatch carries of a
        raise                       # failed scan must not be reused
    return out


def plan_stream_slices(B: int, n_devices: int,
                       max_stream_b: Optional[int] = None):
    """Pure slice assignment for the streamed kernel (unit-testable on
    CPU — round-2 Weak #2: this logic previously ran with >1 device
    exactly nowhere). Returns ``[(start, end, device_index), ...]``
    covering ``range(B)`` in order: slices are capped at
    ``max_stream_b`` histories (VMEM results-buffer bound) and, when
    ``n_devices`` > 0, also sized to spread the whole batch across the
    devices round-robin."""
    cap = MAX_STREAM_B if max_stream_b is None else max_stream_b
    group = min(cap, -(-B // n_devices)) if n_devices > 0 else cap
    return [(i, min(i + group, B),
             ((i // group) % n_devices) if n_devices > 0 else 0)
            for i in range(0, B, group)]


def plan_shard_slices(B: int, D: int,
                      max_stream_b: Optional[int] = None):
    """Pure slice assignment for the SHARD_MAP stream path: ``B``
    (a positive multiple of ``D`` — callers pad with sentinel
    histories) splits into ``[(start, end), ...]`` slices whose width
    is always a multiple of ``D``. Within a slice, shard ``d`` owns
    the contiguous sub-range ``[start + d*g, start + (d+1)*g)`` with
    ``g = (end - start) // D`` — ONE fused dispatch covers all D
    shards per slice. Per-shard slice width is capped at
    ``max_stream_b`` (VMEM results-buffer bound)."""
    cap = MAX_STREAM_B if max_stream_b is None else max_stream_b
    if D <= 0 or B % D != 0:
        raise ValueError(f"B={B} must be a positive multiple of D={D}")
    step = min(cap, max(B // D, 1)) * D
    return [(i, min(i + step, B)) for i in range(0, B, step)]


def merge_stream_slice(res: np.ndarray, starts, n: int):
    """Pure per-slice verdict unpacking: the kernel reports fail
    segments in slice-global coordinates; callers need them history-
    local. Returns ``[(status, fail_seg_local, n_final), ...]``."""
    out = []
    for b in range(n):
        st = int(res[b, 0])
        fail_g = int(res[b, 1])
        fail_local = fail_g - int(starts[b]) if fail_g >= 0 else -1
        out.append((st, fail_local, int(res[b, 2])))
    return out


def merge_stream_shards(res: np.ndarray, starts, n: int, D: int):
    """Pure verdict unpacking for ONE sharded dispatch: ``res`` is the
    ``(D, b_pad, 128)`` results stack, ``starts[d]`` shard d's
    per-history stream offsets. Returns the slice's ``n`` verdicts in
    slice order (shard d owns the contiguous sub-range
    ``[d*g, (d+1)*g)``, matching :func:`plan_shard_slices`)."""
    g = n // D
    out = []
    for d in range(D):
        out.extend(merge_stream_slice(res[d], starts[d], g))
    return out


@functools.lru_cache(maxsize=32)
def _reset_fn(spec: SegKernelSpec, b_pad: int):
    """On-device carry re-initialization for the recycle pool: takes a
    finished dispatch's (ws, stat) device buffers DONATED, returns
    them re-filled with the initial frontier/stat constants plus a
    fresh zero results block — pure device compute, so a hot bucket's
    next dispatch ships no initial-carry bytes over the tunnel."""
    import jax
    import jax.numpy as jnp

    ws_init = tuple(np.asarray(w) for w in initial_frontier(spec))
    stat_init = _init_stat()

    def carry_reset(ws, stat):
        del ws, stat            # donated: only their buffers survive
        return (tuple(jnp.asarray(w) for w in ws_init),
                jnp.asarray(stat_init),
                jnp.zeros((b_pad, LANES), jnp.int32))

    return jax.jit(carry_reset, donate_argnums=(0, 1))


def _carry_recycle(key, ws, stat) -> None:
    """Return a finished dispatch's carry buffers to the pool (bounded
    per key; the results block is NOT pooled — the caller still owns
    its readback)."""
    pool = _CARRY_POOL.setdefault(key, [])
    if len(pool) < _CARRY_POOL_CAP:
        pool.append((ws, stat))


def clear_carry_pool() -> None:
    """Drop every pooled carry. Recycling happens at DISPATCH time
    (JAX is async — a device-side failure only surfaces at the
    caller's readback), so a failed dispatch's carries are already
    pooled when the error arrives; the readback sites call this on
    failure, or the poisoned buffers would re-enter every following
    same-key dispatch until restart."""
    _CARRY_POOL.clear()


def stream_dispatch(succ, segs_list, spec, n_states, n_transitions,
                    device=None):
    """Dispatch one streamed kernel call asynchronously (optionally
    pinned to ``device``); returns (res_device_array, starts). The
    caller owns the readback (``np.asarray(res)``) — the pipelined
    batch path (``checker.batch``) packs/stages the NEXT slice on the
    host while this one runs on the device.

    With carry donation on (:func:`use_carry_donation`, the default)
    the frontier/stat/results carries are donated into the scan and
    the finished (ws, stat) buffers are recycled through the carry
    pool: a hot bucket's next dispatch resets them ON DEVICE
    (:func:`_reset_fn`) instead of re-uploading initial values —
    ``CARRY_REUSES`` counts the hits."""
    import jax
    import jax.numpy as jnp

    global DISPATCHES, CARRY_REUSES
    B = len(segs_list)
    b_pad = 8                 # pow2 buckets bound kernel recompiles
    while b_pad < B:
        b_pad *= 2
    chunks, starts = pack_stream(segs_list, spec)
    table = pack_table(succ[:n_states, :n_transitions],
                       spec.table_rows_pad)

    def put(a):
        return (jax.device_put(a, device) if device is not None
                else jnp.asarray(a))

    key = (spec, b_pad, device)
    pool = _CARRY_POOL.get(key) if _DONATE else None
    if pool:
        ws_t, stat0, res0 = _reset_fn(spec, b_pad)(*pool.pop())
        CARRY_REUSES += 1
    else:
        ws_t = tuple(put(w) for w in initial_frontier(spec))
        stat0 = put(_init_stat())
        res0 = put(np.zeros((b_pad, LANES), np.int32))
    run = _scan_fn(spec, b_pad=b_pad, stream=True, donate=_DONATE)
    ws, stat, res = run(put(chunks), ws_t, stat0, res0, put(table),
                        n_transitions)
    DISPATCHES += 1
    if _DONATE:
        # ws/stat are never read back by stream callers — recycle them
        # for the next same-shape dispatch (res joins the pool only
        # implicitly, via the allocator, after the caller's readback)
        _carry_recycle(key, ws, stat)
    return res, starts


@functools.lru_cache(maxsize=32)
def _sharded_scan_fn(spec: SegKernelSpec, b_pad: int, mesh,
                     batch_axis: str):
    """shard_map-wrapped streamed scan: ONE jitted program
    (``run_sharded`` — the name the compile-surface guard keys on)
    whose per-shard body is the SAME fused kernel scan as the
    single-device path (``_scan_fn`` → ``_chunk_call``, so the Mosaic
    program is compiled once and shared — MOSAIC_BUILDS must not grow
    with D). Pure data parallelism over the mesh's batch axis: zero
    cross-shard collectives, each shard streams whole histories. The
    carry buffers (frontier words, stat row, results) are DONATED so a
    rerun/escalation resumes in place per shard without a second
    buffer allocation."""
    import jax
    from jax.sharding import PartitionSpec as P_

    if hasattr(jax, "shard_map"):                    # jax >= 0.6
        shard_map, check_kw = jax.shard_map, {"check_vma": False}
    else:                                            # 0.4.x spelling
        from jax.experimental.shard_map import shard_map
        check_kw = {"check_rep": False}
    run = _scan_fn(spec, b_pad=b_pad, stream=True)
    W = spec.n_words

    def body(seg, ws, stat, res, table, stride):
        out_ws, out_stat, out_res = run(
            seg[0], tuple(w[0] for w in ws), stat[0], res[0], table,
            stride)
        return (tuple(w[None] for w in out_ws), out_stat[None],
                out_res[None])

    sh = P_(batch_axis)
    sm = shard_map(
        body, mesh=mesh,
        in_specs=(sh, tuple(sh for _ in range(W)), sh, sh, P_(),
                  P_()),
        out_specs=(tuple(sh for _ in range(W)), sh, sh),
        # no collectives anywhere in the kernel scan — each shard is a
        # closed computation (same reasoning as
        # linear_jax.check_device_keys_sharded)
        **check_kw)

    def run_sharded(seg, ws, stat, res, table, stride):
        return sm(seg, ws, stat, res, table, stride)

    return jax.jit(run_sharded, donate_argnums=(1, 2, 3))


def stream_dispatch_sharded(succ, segs_list, spec, n_states,
                            n_transitions, mesh,
                            batch_axis: str = "batch"):
    """Dispatch ONE fused sharded kernel call for a slice of B
    histories split D ways over ``mesh``'s ``batch_axis`` (B % D == 0
    — callers pad with sentinel histories; see
    :func:`plan_shard_slices` for the shard sub-range layout). Every
    shard runs the per-shard single-dispatch discipline: its whole
    sub-range rides one kernel scan inside the one fused program.
    Returns ``(res, starts)`` with ``res`` the ``(D, b_pad, 128)``
    device results stack and ``starts`` the per-shard stream offsets —
    decode with :func:`merge_stream_shards`. The caller owns the
    readback (``np.asarray``), so slice i+1's host pack overlaps this
    slice's device run exactly like the single-device path."""
    global DISPATCHES
    import jax.numpy as jnp

    D = int(mesh.shape[batch_axis])
    B = len(segs_list)
    if D <= 0 or B % D != 0:
        raise ValueError(f"B={B} must be a multiple of D={D}")
    g = B // D
    b_pad = 8                 # pow2 buckets bound kernel recompiles
    while b_pad < g:
        b_pad *= 2
    packs = [pack_stream(segs_list[d * g:(d + 1) * g], spec)
             for d in range(D)]
    # histories differ in segment count, so shard chunk stacks pad to
    # a common scan length with dead segments (ok_proc = -1: no-ops)
    n_chunks = max(c.shape[0] for c, _ in packs)
    chunks = np.zeros((D, n_chunks) + packs[0][0].shape[1:], np.int32)
    chunks[:, :, :, 0] = -1
    for d, (c, _) in enumerate(packs):
        chunks[d, :c.shape[0]] = c
    starts = [s for _, s in packs]
    ws0 = initial_frontier(spec)
    ws = tuple(jnp.asarray(np.broadcast_to(w, (D,) + w.shape).copy())
               for w in ws0)
    stat = jnp.asarray(np.broadcast_to(_init_stat(),
                                       (D, 1, LANES)).copy())
    res = jnp.asarray(np.zeros((D, b_pad, LANES), np.int32))
    table = jnp.asarray(pack_table(succ[:n_states, :n_transitions],
                                   spec.table_rows_pad))
    run = _sharded_scan_fn(spec, b_pad, mesh, batch_axis)
    _, _, out_res = run(jnp.asarray(chunks), ws, stat, res, table,
                        n_transitions)
    DISPATCHES += 1
    return out_res, starts


def _prepare(succ, segs, n_states, n_transitions, P):
    """Shared entry-point setup: spec gate, chunked segment stream,
    initial frontier + stat row (status/fail/n in lanes 0..2 — must
    match the kernel's sstat indices), packed table. Returns None when
    the shape can't run fused."""
    import jax.numpy as jnp

    K = segs.inv_proc.shape[1]
    spec = spec_for(n_states, n_transitions, P, K)
    if spec is None:
        return None
    seg_chunks = pack_segments(segs, spec)
    ws = [jnp.asarray(a) for a in initial_frontier(spec)]
    table = jnp.asarray(pack_table(succ[:n_states, :n_transitions],
                                   spec.table_rows_pad))
    return (spec, seg_chunks, ws, jnp.asarray(_init_stat()), table)


def check_device_pallas_chunked(succ: np.ndarray, segs, *,
                                n_states: int, n_transitions: int,
                                P: int, progress=None,
                                progress_interval_s: float = 5.0,
                                s_real: Optional[int] = None,
                                return_boundary: bool = False):
    """Chunk-at-a-time variant: returns to the host between kernel
    calls so ``progress(done, total, frontier_n)`` can fire (the
    reference's 5-second reporter cadence, ``linear.clj:273-297``).

    With ``return_boundary`` the result gains a 4th element
    ``(ws, done)``: the packed frontier word list at the last chunk
    boundary BEFORE the failure and the number of segments consumed up
    to it — the seed for bounded counterexample reconstruction (decode
    with :func:`decode_frontier`)."""
    import jax.numpy as jnp

    prep = _prepare(succ, segs, n_states, n_transitions, P)
    if prep is None:
        return None
    spec, seg_chunks, ws, stat, table = prep
    call = _chunk_jit(spec)
    ws = tuple(ws)
    res = jnp.zeros((8, LANES), jnp.int32)       # unused: no RESETs
    s_real = s_real if s_real is not None else segs.ok_proc.shape[0]
    t_run = _obs.monotonic()
    last = t_run
    prev_ws, done = ws, 0
    visited = 0
    for c in range(seg_chunks.shape[0]):
        off = np.array([c * spec.chunk, n_transitions], np.int32)
        ws, stat, res = call(jnp.asarray(seg_chunks[c]),
                             jnp.asarray(off), ws, stat, res, table)
        st = np.asarray(stat)
        visited += int(st[0, 2]) * spec.chunk
        if int(st[0, 0]) != VALID:
            break
        prev_ws, done = ws, (c + 1) * spec.chunk
        now = _obs.monotonic()
        if progress is not None and now - last >= progress_interval_s:
            from .linear_jax import estimated_cost

            cfgs = decode_frontier(
                spec, [np.asarray(w) for w in ws], spec.P)
            pend = [sum(1 for t in sl if t >= 0) for _, sl in cfgs]
            el = max(now - t_run, 1e-9)
            progress(min((c + 1) * spec.chunk, s_real), s_real,
                     int(st[0, 2]),
                     {"visited_per_s": visited / el,
                      "segs_per_s": done / el,
                      "est_cost": estimated_cost(pend)})
            last = now
    st = np.asarray(stat)
    out = (int(st[0, 0]), int(st[0, 1]), int(st[0, 2]))
    if return_boundary:
        return out + (([np.asarray(w) for w in prev_ws],
                       min(done, s_real)),)
    return out


def decode_frontier(spec: SegKernelSpec, ws, P: int):
    """Decode a kernel frontier (packed key word list, row 0) into host
    configs ``(state, slots)`` in the :mod:`~.linear_host` encoding:
    the slot field stores LIN=0 / IDLE=1 / tr+2, so subtracting 2 maps
    straight to LIN=-2 / IDLE=-1 / tr. Padding slots beyond ``P`` are
    dropped (always IDLE)."""
    def field(pos, bits):
        word, sh = pos
        return (ws[word][0] >> sh) & ((1 << bits) - 1)

    state = field(spec.state_pos, spec.state_bits)
    slots = [field(spec.slot_pos[q], spec.slot_bits)
             for q in range(min(P, spec.P))]
    out = set()
    for lane in np.flatnonzero(ws[-1][0] < SENT_HI):
        out.add((int(state[lane]),
                 tuple(int(slots[q][lane]) - 2
                       for q in range(min(P, spec.P)))))
    return out


@functools.lru_cache(maxsize=1)
def available() -> bool:
    """Probe once whether the fused kernel compiles and runs here.

    An unavailable kernel demotes the production path to the ~6x-slower
    XLA engines, so the reason is logged loudly (once) instead of
    swallowed — a silent Mosaic regression was round 1's Weak #4."""
    try:
        from .linear_jax import make_segments
        from ..ops.packed import pack_history
        from ..ops import op as O

        h = [O.invoke(0, "w", 1), O.ok(0, "w", 1)]
        packed = pack_history(h)
        segs = make_segments(packed)
        succ = np.array([[0]], np.int32)
        r = check_device_pallas(succ, segs, n_states=1,
                                n_transitions=1, P=1)
        if r is None or r[0] != VALID:
            logger.warning(
                "fused Pallas kernel unavailable (probe returned %r) — "
                "falling back to the XLA engines (~6x slower)", r)
            return False
        if _INTERPRET:
            logger.warning(
                "fused Pallas kernel executing in interpret mode "
                "(exact kernel semantics as plain XLA ops — for "
                "non-TPU validation, not performance)")
        return True
    except Exception as e:
        logger.warning(
            "fused Pallas kernel unavailable (%s: %s) — falling back "
            "to the XLA engines (~6x slower)", type(e).__name__, e)
        return False
