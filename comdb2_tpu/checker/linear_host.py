"""Host reference implementation of just-in-time linearization.

Implements the semantics of the reference's primary checker —
``knossos/linear.clj`` (Lowe's JIT-linearization algorithm) — over the
memoized model graph, in the exact representation the TPU engine uses,
so the two can be cross-validated row for row.

A *config* is ``(state_id, slots)``:

- ``state_id`` — node in the memoized transition graph
  (:class:`~comdb2_tpu.models.memo.MemoizedModel`).
- ``slots`` — one entry per process: ``IDLE`` (-1), ``LIN`` (-2: this
  process's current call is linearized but hasn't returned), or a
  transition id ≥ 0 (process is calling that transition). This is the
  fixed-width tensor form of the reference's ``ArrayProcesses`` packed
  int array (``knossos/linear/config.clj:157-295``); which *op* a busy
  process is running is recoverable from the history prefix, so storing
  the transition id loses nothing and dedups strictly more configs.

Per history op (``linear.clj:218-271``):

- ``invoke`` (unless the op is known to fail): set the process's slot to
  the op's transition id in every config (``t-call``).
- ``ok``: close the config set under single-call linearization — for any
  config and any calling process ``q``, if ``succ[state, slot[q]]`` is
  consistent, add the config with ``q`` marked ``LIN`` — then keep only
  configs where the returning process is ``LIN`` and idle it
  (``t-lin``/``t-ret``). Empty result ⇒ not linearizable at this op.
  The closure is the fixed point of the reference's per-``ok`` DFS over
  pending-call orders (``jit-linearizations``, ``linear.clj:66-99``);
  closing under *all* pending calls (not only those ending with the
  returning op) only adds configs that a later return point would have
  produced anyway, so the set stays exactly the reachable-config set.
- ``fail`` / ``info``: no-op (failed invokes never entered; info calls
  stay pending forever and remain linearizable in later closures —
  ``history.clj:127-145``, ``linear.clj:226``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Set, Tuple

from ..models.memo import MemoizedModel
from ..ops.op import INVOKE, OK
from ..ops.packed import PackedHistory

IDLE = -1
LIN = -2

Config = Tuple[int, Tuple[int, ...]]


class FrontierOverflow(Exception):
    """Config set exceeded the cap — analysis result is :unknown,
    mirroring the reference's low-memory abort (``linear.clj:318-326``)."""


@dataclass
class HostResult:
    valid: bool
    op_index: Optional[int] = None      # history index where search died
    configs: List[Config] = field(default_factory=list)  # frontier sample
    final_count: int = 0
    max_frontier: int = 0               # peak |configs| over the run
    # on failure: the frontier JUST BEFORE the dying ok's closure — the
    # seeds for final-path reconstruction (``linear.clj:180-212``)
    pre_configs: List[Config] = field(default_factory=list)


def closure(configs: Set[Config], succ,
            max_configs: Optional[int] = None) -> Set[Config]:
    """Close ``configs`` under linearizing any one pending call. The cap
    is enforced *during* expansion so an adversarial history aborts to
    :unknown instead of exhausting memory."""
    seen = set(configs)
    frontier = list(configs)
    while frontier:
        new = []
        for (s, slots) in frontier:
            row = succ[s]
            for q, t in enumerate(slots):
                if t >= 0:
                    s2 = int(row[t])
                    if s2 >= 0:
                        c2 = (s2, slots[:q] + (LIN,) + slots[q + 1:])
                        if c2 not in seen:
                            seen.add(c2)
                            new.append(c2)
                            if max_configs and len(seen) > max_configs:
                                raise FrontierOverflow(
                                    f"config set exceeds {max_configs}")
        frontier = new
    return seen


def check(memo: MemoizedModel, packed: PackedHistory,
          max_configs: int = 1 << 22, start_index: int = 0,
          init_configs: Optional[Set[Config]] = None) -> HostResult:
    """Run the search over a packed history. Raises
    :class:`FrontierOverflow` if the config set ever exceeds
    ``max_configs``.

    ``start_index``/``init_configs`` resume the search mid-history from
    a known frontier (e.g. a device scan's chunk-boundary carry) — the
    bounded counterexample-reconstruction path replays at most one
    chunk on host instead of the whole history."""
    P = len(packed.process_table)
    succ = memo.succ
    configs: Set[Config] = (set(init_configs) if init_configs is not None
                            else {(0, (IDLE,) * P)})
    peak = len(configs)
    for i in range(start_index, len(packed)):
        t = int(packed.type[i])
        if t == INVOKE:
            if packed.fails[i]:
                continue
            p = int(packed.process[i])
            tr = int(packed.trans[i])
            configs = {(s, slots[:p] + (tr,) + slots[p + 1:])
                       for (s, slots) in configs}
        elif t == OK:
            p = int(packed.process[i])
            pre = configs
            closed = closure(configs, succ, max_configs)
            peak = max(peak, len(closed))
            configs = {(s, slots[:p] + (IDLE,) + slots[p + 1:])
                       for (s, slots) in closed if slots[p] == LIN}
            if not configs:
                return HostResult(valid=False, op_index=i,
                                  configs=sorted(closed)[:16],
                                  final_count=0, max_frontier=peak,
                                  pre_configs=sorted(pre)[:16])
        # fail / info: no-op
    return HostResult(valid=True, final_count=len(configs),
                      configs=sorted(configs)[:16], max_frontier=peak)


def describe_config(memo: MemoizedModel, packed: PackedHistory,
                    config: Config) -> dict:
    """Decode a config back to model state + per-process status, for
    counterexample reports (the role of ``final-paths``,
    ``linear.clj:180-212``)."""
    s, slots = config
    pending = {}
    for p, t in enumerate(slots):
        name = packed.process_table[p]
        if t == LIN:
            pending[name] = "linearized"
        elif t >= 0:
            f_id, v_id = packed.transition_table[t]
            pending[name] = (packed.f_table[f_id], packed.value_table[v_id])
    return {"model": memo.states[s].describe(), "pending": pending}
