"""Linearizability checking engines.

- :mod:`comdb2_tpu.checker.brute` — tiny exhaustive WGL-style search used
  as an independent oracle in tests.
- :mod:`comdb2_tpu.checker.linear_host` — host (NumPy/Python) reference
  implementation of just-in-time linearization over a memoized model
  (the semantics of ``knossos/linear.clj``).
- :mod:`comdb2_tpu.checker.linear_jax` — the batched, TPU-native frontier
  search (the core deliverable).
- :mod:`comdb2_tpu.checker.linear` — unified :func:`analysis` entry point
  mirroring ``knossos.linear/analysis`` (``linear.clj:299``).
"""

from .linear import analysis, Analysis
from .checkers import (Checker, check_safe, compose, merge_valid,
                       linearizable, Linearizable, serializable,
                       Serializable, unbridled_optimism,
                       queue, set_checker, total_queue, counter)
from . import independent, workloads, wgl

__all__ = ["analysis", "Analysis", "Checker", "check_safe", "compose",
           "merge_valid", "linearizable", "Linearizable",
           "serializable", "Serializable",
           "unbridled_optimism", "queue", "set_checker", "total_queue",
           "counter", "independent", "workloads", "wgl"]
