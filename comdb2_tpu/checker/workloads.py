"""Workload-specific checkers from the comdb2 test suite and Adya.

- :class:`BankChecker` — total-balance invariant over reads
  (``comdb2/core.clj:152-177``)
- :class:`DirtyReadsChecker` — a failed write's value must never become
  visible to a read (``comdb2/core.clj:492-523``)
- :class:`G2Checker` — Adya G2 anti-dependency cycles: at most one
  insert may succeed per key (``jepsen/adya.clj:57-83``)
"""

from __future__ import annotations

from typing import Any, Dict

from .checkers import UNKNOWN, Checker
from .independent import is_tuple


def freeze_value(x: Any) -> Any:
    """Coerce a (possibly nested) op value to a hashable form: lists
    and tuples become tuples, sets frozensets, dicts sorted pair
    tuples. Mirrors ``ops.history._plain`` for values that did NOT
    arrive through the EDN reader — a driver handing the checker raw
    lists must not crash the set membership test."""
    if isinstance(x, (list, tuple)):
        return tuple(freeze_value(e) for e in x)
    if isinstance(x, (set, frozenset)):
        return frozenset(freeze_value(e) for e in x)
    if isinstance(x, dict):
        return tuple(sorted(((freeze_value(k), freeze_value(v))
                             for k, v in x.items()), key=repr))
    return x


class BankChecker(Checker):
    """Balances must all be present and sum to the model's total. The
    model here is a plain dict ``{"n": accounts, "total": sum}``
    (``comdb2/core.clj:152-177``)."""

    def check(self, test, model, history, opts=None):
        n = model["n"]
        total = model["total"]
        bad_reads = []
        for op in history:
            if op.type != "ok" or op.f != "read" or op.value is None:
                continue
            balances = list(op.value)
            if len(balances) != n:
                bad_reads.append({"type": "wrong-n", "expected": n,
                                  "found": len(balances), "op": op})
            elif sum(balances) != total:
                bad_reads.append({"type": "wrong-total", "expected": total,
                                  "found": sum(balances), "op": op})
        return {"valid?": not bad_reads, "bad-reads": bad_reads}


bank_checker = BankChecker()


class DirtyReadsChecker(Checker):
    """Looks for a failed write's value visible to some read; also
    reports reads whose per-node values disagree
    (``comdb2/core.clj:492-523``: read values are sequences of the row
    as seen from each node).

    This is the parity oracle for the device ``wl-dirty`` family
    (``comdb2_tpu.checker.wl``), so it must be exact: values are
    frozen to hashable tuples before set membership (a raw-list
    payload used to raise ``TypeError`` out of the set build), and a
    read whose value is a scalar or a ``str`` — which would silently
    iterate per CHARACTER — is rejected with a ``malformed-reads``
    cause instead of producing a wrong verdict."""

    def check(self, test, model, history, opts=None):
        failed_writes = {freeze_value(op.value) for op in history
                         if op.type == "fail" and op.f == "write"}
        reads = []
        malformed = []
        for i, op in enumerate(history):
            if op.type != "ok" or op.f != "read" or op.value is None:
                continue
            if isinstance(op.value, (str, bytes)) \
                    or not isinstance(op.value, (list, tuple)):
                malformed.append(i if op.index is None else op.index)
                continue
            reads.append(tuple(freeze_value(x) for x in op.value))
        inconsistent = [v for v in reads if len(set(v)) > 1]
        filthy = [v for v in reads
                  if any(x in failed_writes for x in v)]
        out = {"valid?": not filthy,
               "inconsistent-reads": inconsistent,
               "dirty-reads": filthy}
        if malformed:
            out["valid?"] = UNKNOWN
            out["malformed-reads"] = malformed
        return out


dirty_reads_checker = DirtyReadsChecker()


class G2Checker(Checker):
    """At most one :insert completes successfully for any given key.
    Op values are ``(key, [a-id, b-id])`` tuples from the independent
    generator (``adya.clj:57-83``)."""

    def check(self, test, model, history, opts=None):
        counts: Dict[Any, int] = {}
        for op in history:
            if op.f != "insert" or op.value is None:
                continue
            v = op.value
            k = v.key if is_tuple(v) else v[0]
            counts.setdefault(k, 0)
            if op.type == "ok":
                counts[k] += 1
        insert_count = sum(1 for c in counts.values() if c > 0)
        illegal = {k: c for k, c in sorted(counts.items(), key=repr)
                   if c > 1}
        return {"valid?": not illegal,
                "key-count": len(counts),
                "legal-count": insert_count - len(illegal),
                "illegal-count": len(illegal),
                "illegal": illegal}


g2_checker = G2Checker()


# --- dependency-graph second opinions ---------------------------------------
#
# The bespoke checkers above each pattern-match ONE anomaly shape;
# the txn dependency-graph checker (comdb2_tpu.txn) re-derives the
# same verdicts from first principles (ww/wr/rw cycles, G1a). The
# composed forms run both and merge by verdict priority — on the
# seeded negative-control histories the two must agree, which is
# exactly what tests/test_txn_cluster.py asserts.

def _graph_second_opinion(adapter_name: str):
    from ..txn import adapters
    from .checkers import Serializable

    return Serializable(backend="host",
                        adapter=getattr(adapters, adapter_name))


def g2_composed():
    """Adya count shortcut + dependency-graph view of the same run."""
    from .checkers import compose

    return compose({"adya": g2_checker,
                    "graph": _graph_second_opinion("g2_as_txns")})


def dirty_reads_composed():
    """Visible-failed-write scan + graph G1a view of the same run."""
    from .checkers import compose

    return compose({"dirty": dirty_reads_checker,
                    "graph": _graph_second_opinion(
                        "dirty_reads_as_txns")})
