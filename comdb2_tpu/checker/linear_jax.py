"""TPU-native linearizability search — batched frontier expansion.

The device form of :mod:`comdb2_tpu.checker.linear_host` (which itself
carries the semantics of the reference's ``knossos/linear.clj``). Design:

- The config set becomes a *fixed-capacity frontier*: ``states:int32[F]``,
  ``slots:int32[F,P]``, ``valid:bool[F]``. ``slots`` is the tensor form of
  the reference's packed ``ArrayProcesses`` int arrays
  (``knossos/linear/config.clj:157-295``).
- The history becomes three device arrays (``kind/proc/tr``) consumed by
  one ``lax.scan``; each step switches on op kind. No Python control flow
  depends on data — the 50k-op scan is a single XLA computation.
- An ``ok`` op runs the linearization *closure* as a bounded
  ``lax.while_loop``: one iteration linearizes any single pending call in
  every config at once — an ``[F,P]`` gather into the memoized successor
  table (``succ``) — then dedups frontier ∪ candidates by sorting rows
  into an exact lexicographic order and compacting survivors to the
  front. This replaces the reference's per-op DFS + hash-set dedup
  (``linear.clj:66-129``, ``SetConfigSet``) with sort/segment primitives
  XLA maps well onto TPU.
- Frontier overflow ⇒ verdict ``:unknown`` — the semantics of the
  reference's low-memory abort (``linear.clj:318-326``). The driver
  (:mod:`.linear`) escalates capacity and retries, so small histories pay
  small sorts (the analog of the reference's 128-config pmap threshold,
  ``linear.clj:214-216``).

Dedup is exact: rows sort by their full contents, so every duplicate is
adjacent to its twin and merged (hash-fingerprint ordering is *not*
sound here — colliding non-identical rows can interleave between equal
rows and break adjacency, ballooning the frontier into spurious
overflow). The closure loop is additionally capped at P iterations
(closure depth is bounded by the number of pending calls), so
termination never depends on the heuristic change detector.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

IDLE = -1
LIN = -2

# op kinds in the precompiled step stream
K_SKIP = 0     # fail/info completions, failing invokes, padding
K_INVOKE = 1
K_OK = 2

# result status codes
VALID = 0
INVALID = 1
UNKNOWN = 2    # frontier overflow


class StepStream(NamedTuple):
    """Host-precompiled per-op step metadata (see :func:`make_stream`).
    Kept as host numpy arrays — jit transfers them once at check time;
    eagerly device_putting here costs a tunnel round-trip per array
    (and another one back for batch packing)."""
    kind: np.ndarray   # int32[n]
    proc: np.ndarray   # int32[n]
    tr: np.ndarray     # int32[n]


def make_stream(packed, n_pad: Optional[int] = None) -> StepStream:
    """Compile a PackedHistory into the device step stream. ``n_pad``
    pads with no-op steps so histories of similar length share one
    compiled program."""
    from ..ops.op import INVOKE, OK
    n = len(packed)
    n_pad = n_pad or n
    kind = np.zeros(n_pad, np.int32)
    proc = np.zeros(n_pad, np.int32)
    tr = np.zeros(n_pad, np.int32)
    for i in range(n):
        t = int(packed.type[i])
        if t == INVOKE and not packed.fails[i]:
            kind[i] = K_INVOKE
            proc[i] = packed.process[i]
            tr[i] = packed.trans[i]
        elif t == OK:
            kind[i] = K_OK
            proc[i] = packed.process[i]
    return StepStream(kind, proc, tr)


def estimated_cost(pending_counts) -> float:
    """Σ n·n! over configs — the reference's search-cost estimate by
    pending-call count (``knossos/linear/config.clj:374-393``): each
    config with n pending calls can spawn up to n·Γ(n+1) orders."""
    import math

    return float(sum(n * math.factorial(min(int(n), 12))
                     for n in pending_counts))


def estimated_cost_hist(hist) -> float:
    """:func:`estimated_cost` from a pending-count histogram
    (``hist[k]`` = configs with k pending calls)."""
    import math

    return float(sum(int(c) * k * math.factorial(min(k, 12))
                     for k, c in enumerate(hist)))


@functools.partial(jax.jit, static_argnames=("P",))
def pending_histogram(slots, valid, *, P: int):
    """Per-config pending-call counts bucketed on device: the progress
    telemetry needs only P+1 ints back over the (slow) tunnel, not the
    whole (F, P) frontier."""
    pend = jnp.sum(slots >= 0, axis=1)
    return jnp.bincount(pend, weights=valid.astype(jnp.int32),
                        length=P + 1)


def pad_succ(succ: np.ndarray, s_pad: Optional[int] = None,
             t_pad: Optional[int] = None) -> np.ndarray:
    """Pad the successor table to bucketed shapes (recompile avoidance).
    Padding states/transitions are all-inconsistent (-1)."""
    S, T = succ.shape
    s_pad, t_pad = s_pad or S, t_pad or T
    out = np.full((s_pad, t_pad), -1, np.int32)
    out[:S, :T] = succ
    return out


def _greedy_split(widths):
    """Simulate the packers' greedy fill (lo from the field list's end,
    hi takes the rest); returns (lo_bits, hi_bits). Fields never
    straddle words, so the budget must be checked per word — summing
    total bits alone misses fragmentation and would let fields shift
    past bit 31, aliasing distinct configs."""
    lo_bits = 0
    i = len(widths) - 1
    while i >= 0 and lo_bits + widths[i] <= 31:
        lo_bits += widths[i]
        i -= 1
    return lo_bits, sum(widths[:i + 1])


def pack_bits(n_states: int, n_transitions: int, P: int):
    """Bit budget for packing one config (state + P slots) into two
    int32 words. Returns (state_bits, slot_bits, fits); fits is False
    when the greedy per-word split overflows (the engines then pack
    into MORE words — see :class:`PackPlan` — never a lossy key).
    Slot values live in [-2, T), stored as slot+2. hi must stay below
    bit 30: the invalid sentinel is 1<<30 and must sort after every
    valid key."""
    state_bits = max(int(np.ceil(np.log2(max(n_states, 2)))), 1)
    slot_bits = max(int(np.ceil(np.log2(max(n_transitions + 2, 2)))), 1)
    _, hi_bits = _greedy_split([state_bits] + [slot_bits] * P)
    fits = hi_bits <= 29 and state_bits <= 29 and slot_bits <= 29
    return state_bits, slot_bits, fits


class PackPlan(NamedTuple):
    """Exact lossless packing of one config (state + P slots) into
    ``n_words`` int32 sort keys — the wide-P generalization of the
    two-word budget (round-3 VERDICT #1: ``ArrayProcesses`` has no
    width limit, ``knossos/linear/config.clj:157-295``, and the
    reference CLI defaults to concurrency 30, ``cli.clj:52-91``).

    ``assign[i]`` is the (word, shift) of field i, fields =
    [state, slot_0, .., slot_{P-1}], filled greedily from the END of
    the list into word 0 (the least-significant sort key), then word
    1, ... Words hold <= 31 bits (values stay non-negative int32); the
    TOP word keeps bits 29/30 free for the okp-order flag and the
    invalid sentinel. Dedup sorts by all words (top = primary), so
    equal configs are adjacent — exact for ANY P, at W = ceil(bits/31)
    sort keys instead of the P+2 full-lexsort passes whose compile
    explodes at F >= 1024 (CLAUDE.md "STILL OPEN", now closed)."""
    state_bits: int
    slot_bits: int
    P: int
    assign: tuple          # ((word, shift), ...) per field
    n_words: int


def make_pack_plan(n_states: int, n_transitions: int,
                   P: int) -> Optional[PackPlan]:
    """Build the multi-word plan, or None when a single field exceeds
    29 bits (then only the full row lexsort is exact)."""
    state_bits = max(int(np.ceil(np.log2(max(n_states, 2)))), 1)
    slot_bits = max(int(np.ceil(np.log2(max(n_transitions + 2, 2)))), 1)
    widths = [state_bits] + [slot_bits] * P
    if max(widths) > 29:
        return None
    assign: list = [None] * len(widths)
    word, used = 0, 0
    for i in range(len(widths) - 1, -1, -1):
        if used + widths[i] > 31:
            word, used = word + 1, 0
        assign[i] = (word, used)
        used += widths[i]
    if used > 29:
        word += 1              # flags get a fresh top word
    return PackPlan(state_bits, slot_bits, P, tuple(assign), word + 1)


def _pack_plan_words(states, slots, plan: PackPlan):
    """Pack each config row into ``plan.n_words`` int32 words
    (word 0 least significant)."""
    fields = [states] + [slots[:, q] + 2 for q in range(plan.P)]
    words = [jnp.zeros_like(states) for _ in range(plan.n_words)]
    for f, (w, sh) in zip(fields, plan.assign):
        words[w] = words[w] | (f << sh)
    return words


def _dedup_compact(states, slots, valid, F, plan=None, okp=None):
    """Sort rows into an exact order (valid first) so identical configs
    are guaranteed adjacent; drop duplicates.
    Returns (states[F], slots[F,P], valid[F], n_unique, overflow).

    With a :class:`PackPlan`, rows pack losslessly into ``plan.n_words``
    int32 words — a W-key sort instead of P+2 stable sort passes;
    otherwise falls back to the full lexicographic sort. Both are exact:
    hash-fingerprint ordering is NOT sound here (colliding non-identical
    rows can interleave between equal rows and break adjacency).

    ``okp`` (a traced scalar process id) additionally orders rows whose
    slot ``okp`` is linearized (LIN) *before* all others. Equal rows
    share that predicate, so dedup adjacency is unaffected; the adaptive
    engine relies on it to keep the post-ok frontier a contiguous
    prefix (see :func:`check_device_seg2`)."""
    P = slots.shape[1]
    if okp is not None:
        not_ret = (jnp.take_along_axis(
            slots, jnp.full((slots.shape[0], 1), okp, jnp.int32),
            axis=1)[:, 0] != LIN).astype(jnp.int32)
    if plan is not None:
        words = _pack_plan_words(states, slots, plan)
        top = words[-1]
        if okp is not None:
            # the top word stays < 2^29 by the plan budget; bit 29 is
            # free and below the invalid sentinel (1 << 30)
            top = top | (not_ret << 29)
        top = jnp.where(valid, top, jnp.int32(1) << 30)  # invalid last
        words[-1] = top
        order = jnp.lexsort(tuple(words))
        ws = [w[order] for w in words]
        va = valid[order]
        pad = jnp.zeros(1, bool)
        eq = ws[0][1:] == ws[0][:-1]
        for w in ws[1:]:
            eq = eq & (w[1:] == w[:-1])
        same = jnp.concatenate([pad, eq & va[:-1]])
    else:
        # lexsort: last key is primary — valid rows first, full row order
        keys = tuple(slots[:, q] for q in range(P - 1, -1, -1)) \
            + (states,)
        if okp is not None:
            keys = keys + (not_ret,)
        keys = keys + (~valid,)
        order = jnp.lexsort(keys)
        st0, sl0, va = states[order], slots[order], valid[order]
        pad = jnp.zeros(1, bool)
        same = jnp.concatenate([pad, (st0[1:] == st0[:-1])
                                & jnp.all(sl0[1:] == sl0[:-1], axis=1)
                                & va[:-1]])
    keep = va & ~same
    n = jnp.sum(keep)
    # measured on v5e: a second small argsort beats cumsum+scatter
    # compaction here (~9.5k vs ~7.3k ops/s on the 50k bench); the
    # flat-batch engines use scatter because their row counts are
    # larger and block-structured
    order2 = jnp.argsort(~keep, stable=True)[:F]
    sel = order[order2]
    return states[sel], slots[sel], keep[order2], n, n > F


def _expand(succ, states, slots, valid):
    """One linearization step applied to every (config, pending call):
    returns F*P candidate rows (the vmapped ``t-lin``)."""
    F, P = slots.shape
    calling = slots >= 0
    s2 = succ[states[:, None], jnp.maximum(slots, 0)]          # [F,P]
    cand_valid = (valid[:, None] & calling & (s2 >= 0)).reshape(F * P)
    cand_slots = jnp.broadcast_to(slots[:, None, :], (F, P, P))
    cand_slots = cand_slots.at[:, jnp.arange(P), jnp.arange(P)].set(LIN)
    return s2.reshape(F * P), cand_slots.reshape(F * P, P), cand_valid


def _closure(succ, states, slots, valid, n_valid, F, P, plan,
             max_iter=None, okp=None):
    """Fixed point of single-call linearization with dedup.
    ``max_iter`` bounds iterations exactly (= pending-call count, the
    longest possible linearization chain); defaults to the loose P+1
    bound. ``okp`` orders returning rows first in every dedup (see
    :func:`_dedup_compact`)."""
    if max_iter is None:
        max_iter = P + 1

    def cond(c):
        _, _, _, _, changed, overflow, it = c
        return changed & ~overflow & (it < max_iter)

    def body(c):
        st, sl, va, n, _, _, it = c
        c_st, c_sl, c_va = _expand(succ, st, sl, va)
        all_st = jnp.concatenate([st, c_st])
        all_sl = jnp.concatenate([sl, c_sl])
        all_va = jnp.concatenate([va, c_va])
        st2, sl2, va2, n2, ovf = _dedup_compact(all_st, all_sl, all_va,
                                                F, plan=plan, okp=okp)
        return st2, sl2, va2, n2, n2 > n, ovf, it + 1

    init = body((states, slots, valid, n_valid,
                 jnp.bool_(True), jnp.bool_(False), jnp.int32(0)))
    st, sl, va, n, _, ovf, _ = lax.while_loop(cond, body, init)
    return st, sl, va, n, ovf


def _make_step(succ, F, P, bits):
    def step(carry, op):
        states, slots, valid, n, status, fail_at = carry
        kind, proc, tr, idx = op

        def do_invoke(_):
            return (states, slots.at[:, proc].set(tr), valid, n,
                    status, fail_at)

        def do_ok(_):
            st, sl, va, _, ovf = _closure(succ, states, slots, valid, n,
                                          F, P, bits)
            returned = va & (sl[:, proc] == LIN)
            sl2 = sl.at[:, proc].set(IDLE)
            n2 = jnp.sum(returned)
            st_new = jnp.where(ovf, UNKNOWN,
                               jnp.where(n2 == 0, INVALID, VALID))
            return (st, sl2, returned, n2, st_new.astype(jnp.int32),
                    jnp.where(st_new == VALID, fail_at, idx))

        def dispatch(_):
            return lax.switch(kind, [lambda _: carry, do_invoke, do_ok], None)

        carry2 = lax.cond(status == VALID, dispatch, lambda _: carry, None)
        return carry2, None

    return step


def _check_impl(succ, kind, proc, tr, F: int, P: int,
                bits=None):
    n_ops = kind.shape[0]
    states = jnp.zeros(F, jnp.int32)
    slots = jnp.full((F, P), IDLE, jnp.int32)
    valid = jnp.zeros(F, bool).at[0].set(True)
    carry = (states, slots, valid, jnp.int32(1), jnp.int32(VALID),
             jnp.int32(-1))
    ops = (kind, proc, tr, jnp.arange(n_ops, dtype=jnp.int32))
    step = _make_step(succ, F, P, bits)
    (states, slots, valid, n, status, fail_at), _ = lax.scan(
        step, carry, ops)
    return status, fail_at, n


def _bits_for(n_states, n_transitions, P):
    """Static :class:`PackPlan` for the multi-word packed dedup, or
    None (→ full row lexsort) when the true memo sizes are unknown or
    a single field won't fit a word."""
    if n_states is None or n_transitions is None:
        return None
    return make_pack_plan(n_states, n_transitions, P)


@functools.partial(jax.jit, static_argnames=("F", "P", "n_states",
                                             "n_transitions"))
def check_device(succ, kind, proc, tr, *, F: int, P: int,
                 n_states=None, n_transitions=None):
    """Run the full search for one history on device.

    Returns ``(status, fail_index, n_final)`` — status is VALID/INVALID/
    UNKNOWN; fail_index is the history index of the op at which the
    frontier died (or overflowed). Passing the true (unpadded)
    ``n_states``/``n_transitions`` enables the multi-word packed dedup
    (see :class:`PackPlan`; any P whose fields fit 29 bits)."""
    bits = _bits_for(n_states, n_transitions, P)
    return _check_impl(succ, kind, proc, tr, F, P, bits)


# --- segmented stream: one device step per ok-op ---------------------------

class SegmentStream(NamedTuple):
    """Host-precompiled segments (see :func:`make_segments`): segment i
    carries the invokes since the previous ok (padded to K) plus the
    ok's process. ``seg_index`` maps segment → history index of its ok
    (host-side, for decoding fail_at). ``depth`` is the number of
    pending calls at the ok — the exact closure-iteration bound (a
    linearization chain can't be longer than the pending set)."""
    inv_proc: np.ndarray   # int32[S, K], -1 padding
    inv_tr: np.ndarray     # int32[S, K]
    ok_proc: np.ndarray    # int32[S]
    seg_index: np.ndarray  # int64[S] (host side only)
    depth: np.ndarray      # int32[S]


def make_segments(packed, s_pad: Optional[int] = None,
                  k_pad: Optional[int] = None) -> SegmentStream:
    """Compress a history into per-ok segments.

    The per-row scan spends a sequential step on every history row;
    but only ok-ops change the frontier's validity — invokes just set a
    slot, and fail/info rows are no-ops (``linear.clj:226``). Folding
    each run of invokes into its following ok yields one device step
    per ok-op (~3x fewer sequential steps). Invokes after the final ok
    are dropped: a pending call can only *add* linearization orders,
    never empty a non-empty frontier.

    Columnar since the host-ingest rebuild (one stable argsort for the
    per-process pending-discipline check, cumsums for depths, one
    scatter for the (S, K) fill — bit-identical to the per-op walk,
    which survives one release behind ``COMDB2_TPU_LEGACY_PACK=1``)."""
    from ..ops.packed import legacy_pack_enabled

    if legacy_pack_enabled():
        return make_segments_legacy(packed, s_pad=s_pad, k_pad=k_pad)
    from ..ops.op import INVOKE, OK, FAIL

    from ..ops.columnar import _per_process_prev

    t = np.asarray(packed.type)
    proc = np.asarray(packed.process)
    tra = np.asarray(packed.trans)
    fl = np.asarray(packed.fails)
    n = t.shape[0]
    vinv = (t == INVOKE) & ~fl
    okm = t == OK
    failm = t == FAIL
    removal = np.zeros(n, bool)
    sel = np.flatnonzero(vinv | okm | failm)
    if sel.size:
        # per-process event chains: pending_p is {0,1} (add on a
        # non-failing invoke, clear on ok/fail), so "p was pending" ==
        # "p's previous selected event was a non-failing invoke"
        srt, vflag, prev_v, _ = _per_process_prev(proc, sel, vinv)
        dbl = vflag & prev_v
        if dbl.any():
            i = int(srt[dbl].min())
            raise ValueError(
                f"process {int(proc[i])} invokes at row {i} while an "
                "earlier invocation is still pending — malformed "
                "history")
        removal[srt[~vflag & prev_v]] = True
    ok_idx = np.flatnonzero(okm)
    S = ok_idx.size
    cum_rem = np.cumsum(removal)
    depth_vals = (np.cumsum(vinv)[ok_idx]
                  - (cum_rem[ok_idx] - removal[ok_idx]))
    cum_ok_excl = np.cumsum(okm) - okm
    inv_rows = np.flatnonzero(vinv)
    seg_of = cum_ok_excl[inv_rows]
    keep = seg_of < S              # invokes after the final ok drop
    inv_rows, seg_of = inv_rows[keep], seg_of[keep]
    if inv_rows.size:
        kpos = (np.arange(inv_rows.size)
                - np.searchsorted(seg_of, seg_of, side="left"))
        K = int(np.bincount(seg_of).max()) or 1
    else:
        kpos = seg_of
        K = 1
    k_pad = max(k_pad or 0, K)
    s_pad = max(s_pad or 0, S)
    inv_proc = np.full((s_pad, k_pad), -1, np.int32)
    inv_tr = np.zeros((s_pad, k_pad), np.int32)
    inv_proc[seg_of, kpos] = proc[inv_rows]
    inv_tr[seg_of, kpos] = tra[inv_rows]
    ok_proc = np.full(s_pad, -1, np.int32)   # -1 = padding segment
    seg_index = np.zeros(s_pad, np.int64)
    depth = np.zeros(s_pad, np.int32)
    ok_proc[:S] = proc[ok_idx]
    seg_index[:S] = ok_idx
    depth[:S] = depth_vals
    return SegmentStream(inv_proc, inv_tr, ok_proc, seg_index, depth)


def make_segments_legacy(packed, s_pad: Optional[int] = None,
                         k_pad: Optional[int] = None) -> SegmentStream:
    """The original per-op segment walk (see :func:`make_segments`)."""
    from ..ops.op import INVOKE, OK, FAIL
    n = len(packed)
    segs: list = []
    cur: list = []
    pending: set = set()
    # plain lists: per-element numpy scalar indexing is ~10x slower
    # and this loop runs over every row of every history in a batch
    types = packed.type.tolist()
    procs = packed.process.tolist()
    transs = packed.trans.tolist()
    failss = packed.fails.tolist()
    for i in range(n):
        t = types[i]
        p = procs[i]
        if t == INVOKE and not failss[i]:
            if p in pending:
                # the fused kernel applies invokes as relative deltas on
                # an IDLE slot and the XLA engines as absolute sets — a
                # double-pending process would silently diverge between
                # them, so reject it here (history.complete already
                # raises on the public path; this guards direct callers)
                raise ValueError(
                    f"process {p} invokes at row {i} while an earlier "
                    "invocation is still pending — malformed history")
            cur.append((p, transs[i]))
            pending.add(p)
        elif t == OK:
            segs.append((cur, p, i, len(pending)))
            pending.discard(p)
            cur = []
        elif t == FAIL:
            pending.discard(p)
    S = len(segs)
    K = max((len(c) for c, _, _, _ in segs), default=1) or 1
    # pads are FLOORS: callers bucketing many histories into one fixed
    # shape pass the bucket's (S, K); the actual maxima still win so
    # padding can never truncate a real segment
    k_pad = max(k_pad or 0, K)
    s_pad = max(s_pad or 0, S)
    inv_proc = np.full((s_pad, k_pad), -1, np.int32)
    inv_tr = np.zeros((s_pad, k_pad), np.int32)
    ok_proc = np.full(s_pad, -1, np.int32)   # -1 = padding segment
    seg_index = np.zeros(s_pad, np.int64)
    depth = np.zeros(s_pad, np.int32)
    for s, (calls, okp, idx, dep) in enumerate(segs):
        for k, (p, tr) in enumerate(calls):
            inv_proc[s, k] = p
            inv_tr[s, k] = tr
        ok_proc[s] = okp
        seg_index[s] = idx
        depth[s] = dep
    return SegmentStream(inv_proc, inv_tr, ok_proc, seg_index, depth)


def remap_slots(segs: SegmentStream, with_maps: bool = False):
    """Rename process ids in a segment stream to a minimal pool of
    reusable SLOTS. A process occupies a slot only while its call is
    open (invoke .. ok); the assignment is determined by the history
    alone — identical for every config — so renaming is a pure
    relabeling: verdicts, fail segments, and frontier sizes are
    unchanged. The effective slot count becomes the maximum number of
    CONCURRENT open calls, not the process count, which is what gates
    the fused kernel's tiers (``pallas_seg.spec_for``): a concurrency-10
    register history with <=6 calls in flight runs the (8,128)/2-word
    tier instead of the ~45%-slower (16,128)/3-word one, and histories
    with hundreds of processes but bounded concurrency become
    kernel-eligible at all. The reference's ``ArrayProcesses`` packs
    per-process cells the same dense way but never reuses them
    (``knossos/linear/config.clj:157-295``); reuse is safe here because
    an ok'd slot is IDLE in every surviving config before the stream
    can reassign it.

    Allocation is lowest-free-first within each segment's invoke list,
    releases happen after the segment's ok — so a slot freed by segment
    s is reusable from segment s+1 on. :info invokes never complete and
    hold their slot for the rest of the stream (process retirement —
    the retired id never invokes again, ``core.clj:178-200``).

    Returns ``(segs', P_eff)``, plus ``proc_of_slot`` (int32[S, P_eff];
    row s = which ORIGINAL process owns each slot after segment s, -1
    when free) when ``with_maps`` — the inverse needed to decode a
    device frontier back into process-indexed configs
    (:func:`comdb2_tpu.checker.counterexample.reconstruct`).
    """
    import heapq

    S, K = segs.inv_proc.shape
    ip = segs.inv_proc.tolist()
    okl = segs.ok_proc.tolist()
    out_ip = [row[:] for row in ip]
    out_ok = list(okl)
    slot_of: dict = {}
    free: list = []
    n_slots = 0
    maps = [] if with_maps else None
    owners: list = []
    for s in range(S):
        row = ip[s]
        orow = out_ip[s]
        for k in range(K):
            p = row[k]
            if p < 0:
                continue
            if p in slot_of:
                raise ValueError(
                    f"process {p} invokes in segment {s} while an "
                    "earlier invocation is still open")
            if free:
                sl = heapq.heappop(free)
            else:
                sl = n_slots
                n_slots += 1
                owners.append(-1)
            slot_of[p] = sl
            owners[sl] = p
            orow[k] = sl
        o = okl[s]
        if o >= 0:
            sl = slot_of.pop(o, None)
            if sl is None:
                # ok without an open invocation: the process's slot is
                # IDLE in every config, so the ok filter empties the
                # frontier (INVALID at this segment). Any free slot is
                # IDLE everywhere too — map to one to preserve exactly
                # that instead of rejecting the stream.
                if free:
                    out_ok[s] = free[0]
                else:
                    out_ok[s] = n_slots
                    n_slots += 1
                    owners.append(-1)
                    heapq.heappush(free, out_ok[s])
            else:
                out_ok[s] = sl
                owners[sl] = -1
                heapq.heappush(free, sl)
        if with_maps:
            maps.append(owners[:])
    P_eff = n_slots
    segs2 = SegmentStream(
        np.asarray(out_ip, np.int32).reshape(S, K),
        segs.inv_tr, np.asarray(out_ok, np.int32),
        segs.seg_index, segs.depth)
    if with_maps:
        pos = np.full((S, max(P_eff, 1)), -1, np.int32)
        for s, row in enumerate(maps):
            if row:
                pos[s, :len(row)] = row
        return segs2, P_eff, pos
    return segs2, P_eff


def remap_slots_batch(streams):
    """Batched :func:`remap_slots` over many SegmentStreams at once —
    the batch ingest path's form (``checker.batch._stream_segments``).
    Returns ``(streams', p_effs)`` with outputs BIT-IDENTICAL to
    per-history ``remap_slots`` (golden parity tests).

    The per-history pass is inherently sequential (lowest-free-first
    allocation with out-of-order release), but every history advances
    its segment clock independently — so the loop runs over SEGMENT
    POSITIONS with all histories as one vector lane each: state is a
    (B, n_procs) slot map plus a (B, P) in-use mask, and each step is
    a handful of numpy ops instead of B iterations of Python. The
    lowest-free rule maps onto ``argmax(~used)`` exactly: slots are
    allocated contiguously, so the smallest unused index is min(free
    heap) when the heap is non-empty and the fresh index otherwise."""
    B = len(streams)
    if B == 0:
        return [], []
    S_max = max(s.ok_proc.shape[0] for s in streams)
    K_max = max(s.inv_proc.shape[1] for s in streams)
    if S_max == 0 or all(int(s.ok_proc.shape[0]) == 0 for s in streams):
        return list(streams), [0] * B
    ip = np.full((B, S_max, K_max), -1, np.int32)
    okp = np.full((B, S_max), -1, np.int32)
    for b, s in enumerate(streams):
        sb, kb = s.inv_proc.shape
        ip[b, :sb, :kb] = s.inv_proc
        okp[b, :sb] = s.ok_proc
    npc = int(max(ip.max(initial=-1), okp.max(initial=-1), 0)) + 1
    slot_of = np.full((B, max(npc, 1)), -1, np.int32)
    # conservative live-slot bound (every ok treated as a release);
    # unmatched-ok edge allocations can exceed it — grown on demand
    opens = np.cumsum((ip >= 0).sum(axis=2), axis=1)
    rel = np.cumsum(okp >= 0, axis=1)
    p_cap = int(max((opens[:, 1:] - rel[:, :-1]).max(initial=0),
                    opens[:, 0].max(initial=0), 1)) + 1
    used = np.zeros((B, p_cap), bool)
    n_slots = np.zeros(B, np.int32)
    out_ip = ip.copy()
    out_ok = okp.copy()
    bidx = np.arange(B)
    for s in range(S_max):
        for k in range(K_max):
            p = ip[:, s, k]
            m = p >= 0
            if not m.any():
                continue
            pc = np.where(m, p, 0)
            if np.any(m & (slot_of[bidx, pc] >= 0)):
                b = int(np.flatnonzero(m & (slot_of[bidx, pc] >= 0))[0])
                raise ValueError(
                    f"process {int(p[b])} invokes in segment {s} while "
                    "an earlier invocation is still open")
            while np.any(m & used.all(axis=1)):
                used = np.pad(used, ((0, 0), (0, used.shape[1])))
            sl = np.argmax(~used, axis=1).astype(np.int32)
            out_ip[m, s, k] = sl[m]
            used[bidx[m], sl[m]] = True
            slot_of[bidx[m], pc[m]] = sl[m]
            n_slots = np.maximum(n_slots, np.where(m, sl + 1, 0))
        o = okp[:, s]
        m = o >= 0
        if not m.any():
            continue
        oc = np.where(m, o, 0)
        sl = slot_of[bidx, oc]
        matched = m & (sl >= 0)
        out_ok[matched, s] = sl[matched]
        used[bidx[matched], sl[matched]] = False
        slot_of[bidx[matched], oc[matched]] = -1
        un = m & ~matched
        if un.any():
            # ok with no open invocation: any free slot is IDLE in
            # every config — reference one (fresh if none), leaving it
            # free, exactly like the per-history path
            while np.any(un & used.all(axis=1)):
                used = np.pad(used, ((0, 0), (0, used.shape[1])))
            fs = np.argmax(~used, axis=1).astype(np.int32)
            out_ok[un, s] = fs[un]
            n_slots = np.maximum(n_slots, np.where(un, fs + 1, 0))
    out = []
    for b, s in enumerate(streams):
        sb, kb = s.inv_proc.shape
        out.append(SegmentStream(
            np.ascontiguousarray(out_ip[b, :sb, :kb]), s.inv_tr,
            np.ascontiguousarray(out_ok[b, :sb]),
            s.seg_index, s.depth))
    return out, [int(x) for x in n_slots]


def _make_seg_step(succ, F, P, K, bits, Fs=None):
    """One scan step over a segment. With ``Fs`` set (adaptive
    two-tier, see :func:`check_device_seg2`) the closure first runs at
    the small capacity and escalates to ``F`` per segment on overflow;
    without it the closure always runs at ``F``."""
    pad_f = F - Fs if Fs else 0

    def step(carry, seg):
        states, slots, valid, n, status, fail_at = carry
        inv_proc, inv_tr, ok_proc, sidx, depth = seg

        def run(_):
            sl = slots
            for k in range(K):      # unrolled: K is small and static
                p = inv_proc[k]
                sl = jnp.where(p >= 0,
                               sl.at[:, jnp.maximum(p, 0)]
                               .set(inv_tr[k]),
                               sl)

            def big(_):
                return _closure(succ, states, sl, valid, n, F, P, bits,
                                max_iter=depth, okp=ok_proc)

            if Fs is None:
                st, sl2, va, _, ovf = big(None)
            else:
                # the small tier runs unconditionally (its cost is what
                # the tiering saves; on segments it can't serve, the
                # result is discarded), then ONE cond selects the big
                # closure — so each closure body is compiled exactly
                # once. The big retry starts from the same pre-closure
                # frontier: whenever n <= Fs, rows Fs..F are invalid,
                # so `big` sees the identical config set.
                cst, csl, cva, cn, covf = _closure(
                    succ, states[:Fs], sl[:Fs], valid[:Fs], n, Fs,
                    P, bits, max_iter=depth, okp=ok_proc)

                def use_small(_):
                    return (jnp.concatenate(
                                [cst, jnp.zeros(pad_f, jnp.int32)]),
                            jnp.concatenate(
                                [csl, jnp.zeros((pad_f, P),
                                                jnp.int32)]),
                            jnp.concatenate(
                                [cva, jnp.zeros(pad_f, bool)]),
                            cn, jnp.bool_(False))

                need_big = (n > Fs) | covf
                st, sl2, va, _, ovf = lax.cond(need_big, big,
                                               use_small, None)

            returned = va & (sl2[:, ok_proc] == LIN)
            sl3 = sl2.at[:, ok_proc].set(IDLE)
            n2 = jnp.sum(returned)
            st_new = jnp.where(ovf, UNKNOWN,
                               jnp.where(n2 == 0, INVALID, VALID))
            return (st, sl3, returned, n2, st_new.astype(jnp.int32),
                    jnp.where(st_new == VALID, fail_at, sidx))

        live = (status == VALID) & (ok_proc >= 0)
        carry2 = lax.cond(live, run, lambda _: carry, None)
        return carry2, None

    return step


def _check_impl_seg(succ, inv_proc, inv_tr, ok_proc, depth, F: int,
                    P: int, bits=None):
    S, K = inv_proc.shape
    carry = init_seg_carry(F, P)
    segs = (inv_proc, inv_tr, ok_proc,
            jnp.arange(S, dtype=jnp.int32), depth)
    step = _make_seg_step(succ, F, P, K, bits)
    (states, slots, valid, n, status, fail_at), _ = lax.scan(
        step, carry, segs)
    return status, fail_at, n


@functools.partial(jax.jit, static_argnames=("F", "P", "n_states",
                                             "n_transitions"))
def check_device_seg(succ, inv_proc, inv_tr, ok_proc, depth, *, F: int,
                     P: int, n_states=None, n_transitions=None):
    """Segmented single-history search: one sequential device step per
    ok-op. ``fail_at`` is a *segment* index — map through
    ``SegmentStream.seg_index`` on host."""
    bits = _bits_for(n_states, n_transitions, P)
    return _check_impl_seg(succ, inv_proc, inv_tr, ok_proc, depth, F, P,
                           bits)


def init_seg_carry(F: int, P: int):
    """Initial scan carry for the chunked segmented search."""
    states = jnp.zeros(F, jnp.int32)
    slots = jnp.full((F, P), IDLE, jnp.int32)
    valid = jnp.zeros(F, bool).at[0].set(True)
    return (states, slots, valid, jnp.int32(1), jnp.int32(VALID),
            jnp.int32(-1))


def expand_seg_carry(carry, F_new: int):
    """Widen a GOOD chunk-boundary carry to a larger frontier capacity:
    in-place escalation resumes the search at the overflowing chunk
    instead of restarting the whole history at the next ladder level
    (each restart repays every chunk already checked). Status/fail are
    reset — the carry must come from before the overflow."""
    states, slots, valid, count, _status, _fail = carry
    pad = F_new - states.shape[0]
    if pad < 0:
        raise ValueError("carry wider than target capacity")
    states = jnp.pad(states, (0, pad))
    slots = jnp.pad(slots, ((0, pad), (0, 0)), constant_values=IDLE)
    valid = jnp.pad(valid, (0, pad))
    return (states, slots, valid, count, jnp.int32(VALID),
            jnp.int32(-1))


def expand_seg_carry_slots(carry, P_new: int):
    """Widen a carry's SLOT axis in place (streaming sessions whose
    effective concurrency grows mid-stream): new slots pad IDLE, which
    leaves every config's semantics unchanged — a relabeled key layout
    is still exact, and the renamed segment streams only ever address
    slots below the renamer's running P_eff. Status/fail/count are
    preserved: this is a mid-stream widening, not a capacity
    escalation.

    HOST numpy on purpose (like ``mxu.expand_carry``): widenings are
    rare, and an eager device pad here would compile an infra program
    outside the declared compile surface per carry shape — the next
    delta's jit transfers the widened carry instead."""
    states, slots, valid, count, status, fail = (np.asarray(x)
                                                 for x in carry)
    pad = P_new - slots.shape[1]
    if pad < 0:
        raise ValueError("carry has more slots than target width")
    if pad:
        slots = np.pad(slots, ((0, 0), (0, pad)),
                       constant_values=IDLE)
    return (states, slots, valid, count, status, fail)


@functools.partial(jax.jit, static_argnames=("F", "P", "n_states",
                                             "n_transitions"))
def check_device_seg_chunk(succ, inv_proc, inv_tr, ok_proc, depth,
                           seg_offset, carry, *, F: int, P: int,
                           n_states=None, n_transitions=None):
    """One chunk of the segmented search: consumes ``carry`` (from
    :func:`init_seg_carry` or a previous chunk) and returns the updated
    carry. Chunking lets the host report progress between device calls
    — the role of the reference's 5-second reporter threads
    (``knossos/linear.clj:273-297``). ``seg_offset`` biases the segment
    indices recorded in ``fail_at``."""
    bits = _bits_for(n_states, n_transitions, P)
    S = inv_proc.shape[0]
    segs = (inv_proc, inv_tr, ok_proc,
            seg_offset + jnp.arange(S, dtype=jnp.int32), depth)
    step = _make_seg_step(succ, F, P, inv_proc.shape[1], bits)
    carry2, _ = lax.scan(step, carry, segs)
    return carry2


@functools.partial(jax.jit, static_argnames=("F", "P", "n_states",
                                             "n_transitions"))
def check_device_seg_batch(succ, inv_proc, inv_tr, ok_proc, depth, *,
                           F: int, P: int, n_states=None,
                           n_transitions=None):
    bits = _bits_for(n_states, n_transitions, P)
    fn = functools.partial(_check_impl_seg, F=F, P=P, bits=bits)
    return jax.vmap(lambda a, b, c, d: fn(succ, a, b, c, d))(
        inv_proc, inv_tr, ok_proc, depth)


# --- adaptive two-tier segmented engine ------------------------------------
#
# The dedup sort dominates a closure iteration, and its cost scales with
# the frontier capacity — but capacity is sized for the *worst* segment
# while typical segments need a fraction of it (measured on the 50k
# register bench: p50 closed-frontier = 8 configs, 96% <= 32, max 88).
# So each segment first runs the closure at a small capacity ``Fs`` and
# escalates to the full ``F`` only when Fs overflows — a per-segment
# lax.cond, the device analog of the reference's parallel-threshold
# laddering (linear.clj:214-216).
#
# Slicing the first Fs rows is sound because the engine maintains the
# invariant that valid configs form a contiguous prefix: every dedup
# compacts valid rows to the front, and ordering rows whose ok-slot is
# linearized first (okp in _dedup_compact) makes the post-ok surviving
# set a prefix too.

def _seg2_tier(Fs, F):
    """Small-tier capacity actually used: None (big-only) when the
    requested tier can't sit strictly below F."""
    return Fs if (Fs is not None and 0 < Fs < F) else None


@functools.partial(jax.jit, static_argnames=("F", "Fs", "P", "n_states",
                                             "n_transitions"))
def check_device_seg2(succ, inv_proc, inv_tr, ok_proc, depth, *, F: int,
                      P: int, Fs: int = 32, n_states=None,
                      n_transitions=None):
    """Adaptive segmented search: small-capacity closure with
    per-segment escalation to ``F``. Same inputs/outputs as
    :func:`check_device_seg`. A ``Fs`` that can't sit below ``F``
    degrades to the big-only engine instead of failing."""
    bits = _bits_for(n_states, n_transitions, P)
    S, K = inv_proc.shape
    carry = init_seg_carry(F, P)
    segs = (inv_proc, inv_tr, ok_proc, jnp.arange(S, dtype=jnp.int32),
            depth)
    step = _make_seg_step(succ, F, P, K, bits, Fs=_seg2_tier(Fs, F))
    (st, sl, va, n, status, fail_at), _ = lax.scan(step, carry, segs)
    return status, fail_at, n


@functools.partial(jax.jit, static_argnames=("F", "Fs", "P", "n_states",
                                             "n_transitions"))
def check_device_seg2_chunk(succ, inv_proc, inv_tr, ok_proc, depth,
                            seg_offset, carry, *, F: int, P: int,
                            Fs: int = 32, n_states=None,
                            n_transitions=None):
    """Chunked adaptive search (see :func:`check_device_seg_chunk`)."""
    bits = _bits_for(n_states, n_transitions, P)
    S = inv_proc.shape[0]
    segs = (inv_proc, inv_tr, ok_proc,
            seg_offset + jnp.arange(S, dtype=jnp.int32), depth)
    step = _make_seg_step(succ, F, P, inv_proc.shape[1], bits,
                          Fs=_seg2_tier(Fs, F))
    carry2, _ = lax.scan(step, carry, segs)
    return carry2


# --- flat-batch engine: B histories, one frontier tensor, no vmap ----------
#
# vmapping _check_impl lowers poorly on TPU (batched gathers/sorts cost
# ~20x per lane); instead the B frontiers live in ONE flat (B*F)-row
# tensor with the batch id packed into the top bits of the sort key.
# Every step is then plain big-array ops: one 2-key sort, one cumsum,
# gathers/scatters — exactly what the hardware is good at. Batch
# boundaries after the sort are *fixed* (each batch contributes exactly
# F*(P+1) rows, valid or not), so per-batch compaction is arithmetic on
# row indices, not segmented reductions.

def flat_pack_bits(B: int, n_states: int, n_transitions: int, P: int):
    """Bit budget including the batch id + invalid flag. Returns
    (batch_bits, state_bits, slot_bits, fits); simulates the same
    greedy word split as :func:`_flat_sort_keys` so per-word overflow
    (fragmentation) is caught, not just the total."""
    batch_bits = max(int(np.ceil(np.log2(max(B, 2)))), 1)
    state_bits = max(int(np.ceil(np.log2(max(n_states, 2)))), 1)
    slot_bits = max(int(np.ceil(np.log2(max(n_transitions + 2, 2)))), 1)
    widths = [batch_bits, 1, state_bits] + [slot_bits] * P
    _, hi_bits = _greedy_split(widths)
    fits = hi_bits <= 30 and all(b <= 30 for b in widths)
    return batch_bits, state_bits, slot_bits, fits


def _flat_sort_keys(batch, states, slots, valid, bits):
    """(hi, lo) int32 keys: batch | invalid | state | slots, split so
    each word stays below 31 bits. Invalid rows' state/slot fields are
    zeroed: an invalid candidate carries state -1 from the expansion,
    and a negative field would sign-corrupt the batch bits — pushing
    the row across block boundaries and shifting other batches' valid
    rows out of their fixed blocks."""
    batch_bits, state_bits, slot_bits = bits
    P = slots.shape[1]
    st_f = jnp.where(valid, states, 0)
    fields = [(batch, batch_bits), ((~valid).astype(jnp.int32), 1),
              (st_f, state_bits)] + \
        [(jnp.where(valid, slots[:, q] + 2, 0), slot_bits)
         for q in range(P)]
    lo = jnp.zeros_like(states)
    lo_bits = 0
    i = len(fields) - 1
    while i >= 0 and lo_bits + fields[i][1] <= 31:
        lo = lo | (fields[i][0] << lo_bits)
        lo_bits += fields[i][1]
        i -= 1
    hi = jnp.zeros_like(states)
    hi_bits = 0
    while i >= 0:
        hi = hi | (fields[i][0] << hi_bits)
        hi_bits += fields[i][1]
        i -= 1
    return hi, lo


def _flat_dedup_compact(batch, states, slots, valid, B, F, bits):
    """Sort all rows by (batch, validity, config); dedup adjacent equal
    configs; compact each batch's survivors into its F-row block.
    Row count R per batch is fixed, so batch b owns sorted rows
    [b*R, (b+1)*R). Returns (states, slots, valid, n_per_batch[B],
    overflow[B]) with frontier shape (B*F, ...)."""
    R = states.shape[0] // B
    hi, lo = _flat_sort_keys(batch, states, slots, valid, bits)
    order = jnp.lexsort((lo, hi))
    h, l = hi[order], lo[order]
    va = valid[order]
    st = states[order]
    sl = slots[order]
    pad = jnp.zeros(1, bool)
    same = jnp.concatenate([pad, (h[1:] == h[:-1]) & (l[1:] == l[:-1])
                            & va[:-1]])
    keep = va & ~same
    c = jnp.cumsum(keep)                    # inclusive
    e = c - keep                            # exclusive
    row = jnp.arange(states.shape[0])
    block = row // R
    base = e.reshape(B, R)[:, 0]            # kept-count before each block
    rank = e - base[block]
    n_b = c.reshape(B, R)[:, -1] - base     # kept rows per batch
    target = jnp.where(keep & (rank < F), block * F + rank, B * F)
    out_st = jnp.zeros(B * F + 1, jnp.int32).at[target].set(st,
                                                            mode="drop")
    P = slots.shape[1]
    out_sl = jnp.zeros((B * F + 1, P), jnp.int32).at[target].set(
        sl, mode="drop")
    slot_row = jnp.arange(B * F)
    out_va = (slot_row % F) < jnp.minimum(n_b, F)[slot_row // F]
    return (out_st[:B * F], out_sl[:B * F], out_va,
            jnp.minimum(n_b, F), n_b > F)


def _flat_closure(succ, batch, states, slots, valid, n_b, B, F, P, bits,
                  max_iter=None):
    """Fixed point of single-call linearization over the flat frontier.
    All batches iterate in lockstep; the loop exits when no batch's
    frontier grew (or the exact pending-depth bound is reached)."""
    if max_iter is None:
        max_iter = P + 1
    cand_batch = jnp.arange(B * F * P, dtype=jnp.int32) // (F * P)
    all_batch = jnp.concatenate([batch, cand_batch])

    def cond(c):
        _, _, _, _, _, changed, it = c
        return changed & (it < max_iter)

    def body(c):
        st, sl, va, n, ovf_sticky, _, it = c
        c_st, c_sl, c_va = _expand(succ, st, sl, va)
        all_st = jnp.concatenate([st, c_st])
        all_sl = jnp.concatenate([sl, c_sl])
        all_va = jnp.concatenate([va, c_va])
        st2, sl2, va2, n2, ovf = _flat_dedup_compact(
            all_batch, all_st, all_sl, all_va, B, F, bits)
        # overflow is sticky: a truncated frontier stays unsound for
        # this batch even if later iterations fit again
        ovf2 = ovf_sticky | ovf
        changed = jnp.any(n2 > n) | jnp.any(ovf)
        return st2, sl2, va2, n2, ovf2, changed, it + 1

    init = body((states, slots, valid, n_b,
                 jnp.zeros(B, bool), jnp.bool_(True), jnp.int32(0)))
    st, sl, va, n, ovf, _, _ = lax.while_loop(cond, body, init)
    return st, sl, va, n, ovf


def _make_flat_step(succ, B, F, P, K, bits):
    rows = jnp.arange(B * F, dtype=jnp.int32)
    batch = rows // F

    def step(carry, seg):
        states, slots, valid, n_b, status, fail_at = carry
        # (B,K),(B,K),(B,),(),()
        inv_proc, inv_tr, ok_proc, sidx, depth = seg

        live_b = (status == VALID) & (ok_proc >= 0)
        live_row = live_b[batch]

        sl = slots
        for k in range(K):                       # K static, unrolled
            p_row = inv_proc[batch, k]
            tr_row = inv_tr[batch, k]
            set_mask = live_row & (p_row >= 0)
            col = jnp.maximum(p_row, 0)
            sl = jnp.where(set_mask[:, None],
                           sl.at[rows, col].set(
                               jnp.where(set_mask, tr_row,
                                         sl[rows, col])),
                           sl)

        st2, sl2, va2, n2, ovf = _flat_closure(
            succ, batch, states, sl, valid, n_b, B, F, P, bits,
            max_iter=depth)
        okp_row = jnp.maximum(ok_proc, 0)[batch]
        returned = va2 & (sl2[rows, okp_row] == LIN)
        sl3 = sl2.at[rows, okp_row].set(
            jnp.where(returned, IDLE, sl2[rows, okp_row]))
        n3 = jnp.sum(returned.reshape(B, F), axis=1)

        st_new = jnp.where(ovf, UNKNOWN,
                           jnp.where(n3 == 0, INVALID, VALID)
                           ).astype(jnp.int32)
        status2 = jnp.where(live_b, st_new, status)
        fail2 = jnp.where(live_b & (st_new != VALID), sidx, fail_at)

        keep_row = live_row & (status2[batch] == VALID)
        states_o = jnp.where(keep_row, st2, states)
        slots_o = jnp.where(keep_row[:, None], sl3, slots)
        valid_o = jnp.where(keep_row, returned, valid)
        n_o = jnp.where(live_b & (status2 == VALID), n3, n_b)
        return (states_o, slots_o, valid_o, n_o, status2, fail2), None

    return step


@functools.partial(jax.jit, static_argnames=("B", "F", "P", "n_states",
                                             "n_transitions"))
def check_device_flat(succ, inv_proc, inv_tr, ok_proc, depth, *,
                      B: int, F: int, P: int, n_states: int,
                      n_transitions: int):
    """Check B histories as one flat device computation.

    seg arrays: inv_proc/inv_tr (S, B, K), ok_proc (S, B); returns
    per-batch (status[B], fail_segment[B], n_final[B]). Requires the
    packed-key budget to fit (see :func:`flat_pack_bits`)."""
    bb, sb, tb, fits = flat_pack_bits(B, n_states, n_transitions, P)
    assert fits, "flat engine requires the packed-key budget to fit"
    bits = (bb, sb, tb)
    S = inv_proc.shape[0]
    K = inv_proc.shape[2]
    rows = B * F
    states = jnp.zeros(rows, jnp.int32)
    slots = jnp.full((rows, P), IDLE, jnp.int32)
    valid = (jnp.arange(rows) % F) == 0
    carry = (states, slots, valid, jnp.ones(B, jnp.int32),
             jnp.full(B, VALID, jnp.int32), jnp.full(B, -1, jnp.int32))
    segs = (inv_proc, inv_tr, ok_proc, jnp.arange(S, dtype=jnp.int32),
            depth)
    step = _make_flat_step(succ, B, F, P, K, bits)
    (states, slots, valid, n_b, status, fail_at), _ = lax.scan(
        step, carry, segs)
    return status, fail_at, n_b


# --- key-packed flat engine: the frontier IS the sort key ------------------
#
# The fastest form: each config is ONLY its packed (hi, lo) int32 pair
# — state and slots are bit fields, never materialized as arrays.
# Invoking, linearizing, and returning ops are field arithmetic
# (deltas shifted into place); deduplication sorts the keys themselves.
# This removes the (rows, P, P) candidate materialization that
# dominates the explicit-tensor engines (measured ~3x the cost of the
# sort) and shrinks frontier memory from (P+1) words/row to 2.
#
# Field layout, LSB→MSB: slot_0 .. slot_{P-1}, state, invalid, batch —
# split across lo (bits 0..30) then hi. Slot values: 0 = linearized
# (LIN), 1 = idle (IDLE), t+2 = pending transition t. No field ever
# crosses the word boundary; field deltas never borrow into neighbors
# because every mutation keeps the field in range.

class KeyLayout:
    """Static (word, shift) assignment for each field."""

    def __init__(self, B: int, n_states: int, n_transitions: int,
                 P: int):
        self.P = P
        self.slot_bits = max(int(np.ceil(
            np.log2(max(n_transitions + 2, 2)))), 1)
        self.state_bits = max(int(np.ceil(
            np.log2(max(n_states, 2)))), 1)
        self.batch_bits = max(int(np.ceil(np.log2(max(B, 2)))), 1)
        fields = ([("slot", q, self.slot_bits) for q in range(P)]
                  + [("state", 0, self.state_bits),
                     ("invalid", 0, 1),
                     ("batch", 0, self.batch_bits)])
        self.pos = {}
        word, shift = 0, 0
        for name, idx, width in fields:
            if shift + width > 31:
                word, shift = word + 1, 0
            if width > 31 or word > 1:
                self.fits = False
                return
            self.pos[(name, idx)] = (word, shift)
            shift += width
        self.fits = True
        self.single_word = all(w == 0 for w, _ in self.pos.values())

    def get(self, hi, lo, name, idx=0):
        word, shift = self.pos[(name, idx)]
        width = {"slot": self.slot_bits, "state": self.state_bits,
                 "invalid": 1, "batch": self.batch_bits}[name]
        src = lo if word == 0 else hi
        return (src >> shift) & ((1 << width) - 1)

    def add(self, hi, lo, name, idx, delta):
        """Add a (possibly negative, data-dependent) delta to a field."""
        word, shift = self.pos[(name, idx)]
        if word == 0:
            return hi, lo + (delta << shift)
        return hi + (delta << shift), lo

    def slot_dynamic(self, hi, lo, p):
        """Extract slot p where p is a per-row tensor."""
        out = jnp.zeros_like(lo)
        for q in range(self.P):
            out = jnp.where(p == q, self.get(hi, lo, "slot", q), out)
        return out

    def add_slot_dynamic(self, hi, lo, p, delta):
        for q in range(self.P):
            h2, l2 = self.add(hi, lo, "slot", q, delta)
            hi = jnp.where(p == q, h2, hi)
            lo = jnp.where(p == q, l2, lo)
        return hi, lo


import os as _os

# optional Pallas bitonic sort for the dedup (correct and ~at parity
# with XLA's variadic sort on v5e; kept opt-in until it wins clearly)
_USE_PALLAS_SORT = _os.environ.get("COMDB2_TPU_PALLAS_SORT") == "1"


def _batch_contig_perm(B, F, R):
    """Row permutation gathering each batch's rows (frontier + P
    candidate chunks, each F-blocked per batch) into contiguous
    (B, R) blocks."""
    idx = jnp.arange(B * R)
    b = idx // R
    rem = idx % R
    c = rem // F
    r = rem % F
    return c * (B * F) + b * F + r


def _k_dedup(hi, lo, valid, inv_hi, inv_lo, B, F, single_word: bool):
    """Sort keys (invalid rows replaced by their batch's sentinel so
    they stay in their block), dedup adjacent, compact per batch."""
    R = hi.shape[0] // B
    h = jnp.where(valid, hi, inv_hi)
    l = jnp.where(valid, lo, inv_lo)
    n_rows = hi.shape[0]
    use_pallas = False
    if (_USE_PALLAS_SORT and not single_word
            and n_rows % B == 0 and (R & (R - 1)) == 0):
        from . import pallas_sort as PS

        use_pallas = PS.sort_pairs_available()   # cached probe
    if use_pallas:
        # per-block bitonic sort in VMEM; validity rides in the keys
        # (sentinels sort to each block's tail), so sorting values
        # directly replaces the argsort+gather pair
        # the per-block sort needs batch-contiguous rows; the concat
        # layout interleaves batches (frontier + P candidate chunks,
        # each F-blocked), so gather into (B, R) blocks first
        perm = _batch_contig_perm(B, F, R)
        hs2, ls2 = PS.sort_pairs(h[perm].reshape(B, R),
                                 l[perm].reshape(B, R))
        hs, ls = hs2.reshape(-1), ls2.reshape(-1)
        # recover validity: valid keys can never equal the sentinel
        # (their invalid bit is clear); sentinel of sorted block b is
        # inv_hi[b*F] (inv_hi is F-blocked by batch)
        sent_h = jnp.repeat(inv_hi[:B * F].reshape(B, F)[:, 0], R)
        sent_l = jnp.repeat(inv_lo[:B * F].reshape(B, F)[:, 0], R)
        va = ~((hs == sent_h) & (ls == sent_l))
    else:
        if single_word:
            order = jnp.argsort(l)
        else:
            order = jnp.lexsort((l, h))
        hs, ls = h[order], l[order]
        va = valid[order]
    pad = jnp.zeros(1, bool)
    same = jnp.concatenate([pad, (hs[1:] == hs[:-1])
                            & (ls[1:] == ls[:-1]) & va[:-1]])
    keep = va & ~same
    c = jnp.cumsum(keep)
    e = c - keep
    row = jnp.arange(hi.shape[0])
    block = row // R
    base = e.reshape(B, R)[:, 0]
    rank = e - base[block]
    n_b = c.reshape(B, R)[:, -1] - base
    target = jnp.where(keep & (rank < F), block * F + rank, B * F)
    out_hi = jnp.zeros(B * F + 1, jnp.int32).at[target].set(hs,
                                                            mode="drop")
    out_lo = jnp.zeros(B * F + 1, jnp.int32).at[target].set(ls,
                                                            mode="drop")
    slot_row = jnp.arange(B * F)
    out_va = (slot_row % F) < jnp.minimum(n_b, F)[slot_row // F]
    return (out_hi[:B * F], out_lo[:B * F], out_va,
            jnp.minimum(n_b, F), n_b > F)


def _k_expand(succ, lay: KeyLayout, hi, lo, valid):
    """Candidate keys: for each pending slot q, linearize it — set the
    slot field to LIN (0) and step the state field. Pure field
    arithmetic; only the succ gather touches memory."""
    s = lay.get(hi, lo, "state")
    c_hi, c_lo, c_va = [], [], []
    for q in range(lay.P):
        tq = lay.get(hi, lo, "slot", q)
        pending = tq >= 2
        s2 = succ[s, jnp.maximum(tq - 2, 0)]
        ok = valid & pending & (s2 >= 0)
        h2, l2 = lay.add(hi, lo, "slot", q, -tq)       # slot -> LIN
        h2, l2 = lay.add(h2, l2, "state", 0, s2 - s)
        c_hi.append(h2)
        c_lo.append(l2)
        c_va.append(ok)
    return (jnp.concatenate(c_hi), jnp.concatenate(c_lo),
            jnp.concatenate(c_va))


def _k_closure(succ, lay, hi, lo, valid, n_b, inv_hi_all, inv_lo_all,
               B, F, max_iter=None):
    P = lay.P
    if max_iter is None:
        max_iter = P + 1

    def cond(c):
        return c[5] & (c[6] < max_iter)

    def body(c):
        hi, lo, va, n, ovf_sticky, _, it = c
        c_hi, c_lo, c_va = _k_expand(succ, lay, hi, lo, va)
        a_hi = jnp.concatenate([hi, c_hi])
        a_lo = jnp.concatenate([lo, c_lo])
        a_va = jnp.concatenate([va, c_va])
        hi2, lo2, va2, n2, ovf = _k_dedup(
            a_hi, a_lo, a_va, inv_hi_all, inv_lo_all, B, F,
            lay.single_word)
        ovf2 = ovf_sticky | ovf
        changed = jnp.any(n2 > n) | jnp.any(ovf)
        return hi2, lo2, va2, n2, ovf2, changed, it + 1

    init = body((hi, lo, valid, n_b, jnp.zeros(B, bool),
                 jnp.bool_(True), jnp.int32(0)))
    hi, lo, va, n, ovf, _, _ = lax.while_loop(cond, body, init)
    return hi, lo, va, n, ovf


@functools.partial(jax.jit, static_argnames=("B", "F", "P", "n_states",
                                             "n_transitions"))
def check_device_keys(succ, inv_proc, inv_tr, ok_proc, depth, *,
                      B: int, F: int, P: int, n_states: int,
                      n_transitions: int):
    """The key-packed flat engine: B histories, frontier = (hi, lo)
    int32 pairs, one sort per closure iteration. Same inputs/outputs as
    :func:`check_device_flat`."""
    lay = KeyLayout(B, n_states, n_transitions, P)
    assert lay.fits, "key layout must fit 62 bits"
    S, _, K = inv_proc.shape
    rows = jnp.arange(B * F, dtype=jnp.int32)
    batch = rows // F

    # per-row constants: the batch field and the invalid sentinel
    bword, bshift = lay.pos[("batch", 0)]
    ivword, ivshift = lay.pos[("invalid", 0)]
    zero = jnp.zeros_like(rows)
    if bword == 1:
        base_hi, base_lo = batch << bshift, zero
    else:
        base_hi, base_lo = zero, batch << bshift
    inv_hi_row = base_hi + ((1 << ivshift) if ivword == 1 else 0)
    inv_lo_row = base_lo + ((1 << ivshift) if ivword == 0 else 0)
    # candidates inherit row i -> frontier row i // P... but expansion
    # concatenates per-q chunks: candidate chunk q holds rows 0..B*F in
    # order, so its batch layout equals the frontier's, tiled P times
    inv_hi_all = jnp.concatenate([inv_hi_row] * (P + 1))
    inv_lo_all = jnp.concatenate([inv_lo_row] * (P + 1))

    # initial frontier: one empty config per batch (all slots IDLE=1)
    idle_lo = 0
    idle_hi = 0
    for q in range(P):
        w, sh = lay.pos[("slot", q)]
        if w == 0:
            idle_lo |= 1 << sh
        else:
            idle_hi |= 1 << sh
    hi0 = base_hi + idle_hi
    lo0 = base_lo + idle_lo
    valid0 = (jnp.arange(B * F) % F) == 0

    def step(carry, seg):
        hi, lo, va, n_b, status, fail_at = carry
        inv_p, inv_t, ok_p, sidx, depth = seg

        live_b = (status == VALID) & (ok_p >= 0)
        live_row = live_b[batch]

        h, l = hi, lo
        for k in range(K):
            p_row = inv_p[batch, k]
            tr_row = inv_t[batch, k]
            m = live_row & (p_row >= 0)
            # slot p: IDLE (1) -> tr+2; delta = tr+1
            h2, l2 = lay.add_slot_dynamic(h, l, jnp.maximum(p_row, 0),
                                          tr_row + 1)
            h = jnp.where(m, h2, h)
            l = jnp.where(m, l2, l)

        h2, l2, va2, n2, ovf = _k_closure(succ, lay, h, l, va, n_b,
                                          inv_hi_all, inv_lo_all, B, F,
                                          max_iter=depth)
        okp_row = jnp.maximum(ok_p, 0)[batch]
        slot_ok = lay.slot_dynamic(h2, l2, okp_row)
        returned = va2 & (slot_ok == 0)                 # LIN
        h3, l3 = lay.add_slot_dynamic(h2, l2, okp_row,
                                      jnp.where(returned, 1, 0))
        n3 = jnp.sum(returned.reshape(B, F), axis=1)

        st_new = jnp.where(ovf, UNKNOWN,
                           jnp.where(n3 == 0, INVALID, VALID)
                           ).astype(jnp.int32)
        status2 = jnp.where(live_b, st_new, status)
        fail2 = jnp.where(live_b & (st_new != VALID), sidx, fail_at)
        keep_row = live_row & (status2[batch] == VALID)
        hi_o = jnp.where(keep_row, h3, hi)
        lo_o = jnp.where(keep_row, l3, lo)
        va_o = jnp.where(keep_row, returned, va)
        n_o = jnp.where(live_b & (status2 == VALID), n3, n_b)
        return (hi_o, lo_o, va_o, n_o, status2, fail2), None

    carry = (hi0, lo0, valid0, jnp.ones(B, jnp.int32),
             jnp.full(B, VALID, jnp.int32), jnp.full(B, -1, jnp.int32))
    segs = (inv_proc, inv_tr, ok_proc, jnp.arange(S, dtype=jnp.int32),
            depth)
    (hi, lo, va, n_b, status, fail_at), _ = lax.scan(step, carry, segs)
    return status, fail_at, n_b


# --- batched (independent histories) ---------------------------------------

#: sharded keys/flat dispatches this process — one per
#: :func:`check_device_keys_sharded` call (ONE fused program covering
#: every shard); ``scripts/bench_multichip.py`` asserts the
#: single-dispatch-per-batch discipline on the measured delta, the
#: way ``txn.closure_jax.DISPATCHES`` is asserted
DISPATCHES = 0

@functools.partial(jax.jit, static_argnames=("F", "P", "n_states",
                                             "n_transitions"))
def check_device_batch(succ, kind, proc, tr, *, F: int, P: int,
                       n_states=None, n_transitions=None):
    """vmap over a batch of histories sharing one successor table — the
    TPU analog of ``independent/checker``'s per-key partitioning
    (``independent.clj:252-300``): thousands of per-key histories check
    in one launch."""
    bits = _bits_for(n_states, n_transitions, P)
    fn = functools.partial(_check_impl, succ, F=F, P=P, bits=bits)
    return jax.vmap(fn)(kind, proc, tr)


@functools.lru_cache(maxsize=64)
def _sharded_keys_fn(mesh, batch_axis: str, engine: str, B: int,
                     F: int, P: int, n_states: int,
                     n_transitions: int):
    """One NAMED jitted shard_map program per (mesh, engine, shape)
    class — the compile-surface guard keys observed lowerings by jit
    name, and an eagerly-applied shard_map would log an anonymous
    wrapper (same reason ``txn.closure_jax._jitted`` uses a named
    wrapper). The per-shard body is the keys/flat engine at B/D."""
    from jax.sharding import PartitionSpec as PS

    D = mesh.shape[batch_axis]
    base = check_device_keys if engine == "keys" else check_device_flat
    fn = functools.partial(base, B=B // D, F=F, P=P, n_states=n_states,
                           n_transitions=n_transitions)
    if hasattr(jax, "shard_map"):                    # jax >= 0.6
        shard_map, check_kw = jax.shard_map, {"check_vma": False}
    else:                                            # 0.4.x spelling
        from jax.experimental.shard_map import shard_map
        check_kw = {"check_rep": False}
    sm = shard_map(
        lambda s, ip, it, op, dp: fn(s, ip, it, op, dp),
        mesh=mesh,
        in_specs=(PS(), PS(None, batch_axis, None),
                  PS(None, batch_axis, None), PS(None, batch_axis),
                  PS()),
        out_specs=(PS(batch_axis), PS(batch_axis), PS(batch_axis)),
        # no collectives anywhere in the engines — each shard is a
        # closed computation, so the varying-axis bookkeeping check
        # (which trips on scan carries initialized from constants)
        # is unnecessary
        **check_kw)

    def check_device_keys_sharded(s, ip, it, op, dp):
        return sm(s, ip, it, op, dp)

    return jax.jit(check_device_keys_sharded)


def check_device_keys_sharded(mesh, succ, inv_proc, inv_tr, ok_proc,
                              depth, *, B: int, F: int, P: int,
                              n_states: int, n_transitions: int,
                              batch_axis: str = "batch",
                              engine: str = "keys"):
    """shard_map the keys/flat engine over the mesh's batch axis: each
    device runs its own flat batch of B/D histories — pure data
    parallelism over ICI, zero cross-device collectives (the device
    form of ``independent/checker``'s per-key partitioning,
    ``independent.clj:252-300``; SURVEY §2.5 item 8).

    Round 1 routed every mesh run to the vmapped per-lane engine
    (~20x worse per lane); this keeps the fast flat engines under
    sharding. B must be divisible by the mesh axis size (callers pad
    with sentinel histories — ``checker.batch`` pads B to a pow2
    multiple of D so per-shard shapes stay inside the bucketed
    program inventory)."""
    global DISPATCHES
    D = mesh.shape[batch_axis]
    assert B % D == 0, (B, D)
    fn = _sharded_keys_fn(mesh, batch_axis, engine, B, F, P,
                          n_states, n_transitions)
    DISPATCHES += 1
    return fn(succ, inv_proc, inv_tr, ok_proc, depth)


def check_sharded(mesh, succ, kind, proc, tr, *, F: int, P: int,
                  n_states=None, n_transitions=None,
                  batch_axis: str = "batch"):
    """TEST ORACLE ONLY — the vmap engine sharded over a device mesh.

    Removed from the production batch path (round 7): vmap lowers ~20x
    worse per lane than the flat-batch encodings (CLAUDE.md), so
    sharding it scales a pessimized program; ``check_batch`` routes
    mesh traffic through the stream/keys/flat sharded engines instead
    and degrades to SINGLE-device vmap when nothing else fits. This
    stays as an independent cross-check for the mesh parity suite (a
    second sharded code path with unrelated lowering). The
    ``vmap-sharded-oracle`` analysis rule flags any non-test caller.

    The batch axis rides data parallelism over ICI; each device runs
    whole (sub)histories — no intra-search communication (SURVEY §2.5
    item 8)."""
    from jax.sharding import NamedSharding, PartitionSpec as Pspec
    batch_sh = NamedSharding(mesh, Pspec(batch_axis))
    repl = NamedSharding(mesh, Pspec())
    kind = jax.device_put(kind, batch_sh)
    proc = jax.device_put(proc, batch_sh)
    tr = jax.device_put(tr, batch_sh)
    succ = jax.device_put(succ, repl)
    return check_device_batch(succ, kind, proc, tr, F=F, P=P,
                              n_states=n_states,
                              n_transitions=n_transitions)
