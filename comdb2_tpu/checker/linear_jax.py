"""TPU-native linearizability search — batched frontier expansion.

The device form of :mod:`comdb2_tpu.checker.linear_host` (which itself
carries the semantics of the reference's ``knossos/linear.clj``). Design:

- The config set becomes a *fixed-capacity frontier*: ``states:int32[F]``,
  ``slots:int32[F,P]``, ``valid:bool[F]``. ``slots`` is the tensor form of
  the reference's packed ``ArrayProcesses`` int arrays
  (``knossos/linear/config.clj:157-295``).
- The history becomes three device arrays (``kind/proc/tr``) consumed by
  one ``lax.scan``; each step switches on op kind. No Python control flow
  depends on data — the 50k-op scan is a single XLA computation.
- An ``ok`` op runs the linearization *closure* as a bounded
  ``lax.while_loop``: one iteration linearizes any single pending call in
  every config at once — an ``[F,P]`` gather into the memoized successor
  table (``succ``) — then dedups frontier ∪ candidates by sorting rows
  into an exact lexicographic order and compacting survivors to the
  front. This replaces the reference's per-op DFS + hash-set dedup
  (``linear.clj:66-129``, ``SetConfigSet``) with sort/segment primitives
  XLA maps well onto TPU.
- Frontier overflow ⇒ verdict ``:unknown`` — the semantics of the
  reference's low-memory abort (``linear.clj:318-326``). The driver
  (:mod:`.linear`) escalates capacity and retries, so small histories pay
  small sorts (the analog of the reference's 128-config pmap threshold,
  ``linear.clj:214-216``).

Dedup is exact: rows sort by their full contents, so every duplicate is
adjacent to its twin and merged (hash-fingerprint ordering is *not*
sound here — colliding non-identical rows can interleave between equal
rows and break adjacency, ballooning the frontier into spurious
overflow). The closure loop is additionally capped at P iterations
(closure depth is bounded by the number of pending calls), so
termination never depends on the heuristic change detector.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

IDLE = -1
LIN = -2

# op kinds in the precompiled step stream
K_SKIP = 0     # fail/info completions, failing invokes, padding
K_INVOKE = 1
K_OK = 2

# result status codes
VALID = 0
INVALID = 1
UNKNOWN = 2    # frontier overflow


class StepStream(NamedTuple):
    """Host-precompiled per-op step metadata (see :func:`make_stream`)."""
    kind: jnp.ndarray   # int32[n]
    proc: jnp.ndarray   # int32[n]
    tr: jnp.ndarray     # int32[n]


def make_stream(packed, n_pad: Optional[int] = None) -> StepStream:
    """Compile a PackedHistory into the device step stream. ``n_pad``
    pads with no-op steps so histories of similar length share one
    compiled program."""
    from ..ops.op import INVOKE, OK
    n = len(packed)
    n_pad = n_pad or n
    kind = np.zeros(n_pad, np.int32)
    proc = np.zeros(n_pad, np.int32)
    tr = np.zeros(n_pad, np.int32)
    for i in range(n):
        t = int(packed.type[i])
        if t == INVOKE and not packed.fails[i]:
            kind[i] = K_INVOKE
            proc[i] = packed.process[i]
            tr[i] = packed.trans[i]
        elif t == OK:
            kind[i] = K_OK
            proc[i] = packed.process[i]
    return StepStream(jnp.asarray(kind), jnp.asarray(proc), jnp.asarray(tr))


def pad_succ(succ: np.ndarray, s_pad: Optional[int] = None,
             t_pad: Optional[int] = None) -> np.ndarray:
    """Pad the successor table to bucketed shapes (recompile avoidance).
    Padding states/transitions are all-inconsistent (-1)."""
    S, T = succ.shape
    s_pad, t_pad = s_pad or S, t_pad or T
    out = np.full((s_pad, t_pad), -1, np.int32)
    out[:S, :T] = succ
    return out


def _dedup_compact(states, slots, valid, F):
    """Sort rows into an exact lexicographic order (valid first), so
    identical configs are guaranteed adjacent; drop duplicates.
    Returns (states[F], slots[F,P], valid[F], n_unique, overflow)."""
    P = slots.shape[1]
    # lexsort: last key is primary — valid rows first, then by full row
    keys = tuple(slots[:, q] for q in range(P - 1, -1, -1)) \
        + (states, ~valid)
    order = jnp.lexsort(keys)
    st, sl, va = states[order], slots[order], valid[order]
    pad = jnp.zeros(1, bool)
    same = jnp.concatenate([pad, (st[1:] == st[:-1])
                            & jnp.all(sl[1:] == sl[:-1], axis=1)
                            & va[:-1]])
    keep = va & ~same
    n = jnp.sum(keep)
    order2 = jnp.argsort(~keep, stable=True)[:F]
    return st[order2], sl[order2], keep[order2], n, n > F


def _expand(succ, states, slots, valid):
    """One linearization step applied to every (config, pending call):
    returns F*P candidate rows (the vmapped ``t-lin``)."""
    F, P = slots.shape
    calling = slots >= 0
    s2 = succ[states[:, None], jnp.maximum(slots, 0)]          # [F,P]
    cand_valid = (valid[:, None] & calling & (s2 >= 0)).reshape(F * P)
    cand_slots = jnp.broadcast_to(slots[:, None, :], (F, P, P))
    cand_slots = cand_slots.at[:, jnp.arange(P), jnp.arange(P)].set(LIN)
    return s2.reshape(F * P), cand_slots.reshape(F * P, P), cand_valid


def _closure(succ, states, slots, valid, n_valid, F, P):
    """Fixed point of single-call linearization with dedup."""
    def cond(c):
        _, _, _, _, changed, overflow, it = c
        return changed & ~overflow & (it <= P)

    def body(c):
        st, sl, va, n, _, _, it = c
        c_st, c_sl, c_va = _expand(succ, st, sl, va)
        all_st = jnp.concatenate([st, c_st])
        all_sl = jnp.concatenate([sl, c_sl])
        all_va = jnp.concatenate([va, c_va])
        st2, sl2, va2, n2, ovf = _dedup_compact(all_st, all_sl, all_va, F)
        return st2, sl2, va2, n2, n2 > n, ovf, it + 1

    init = body((states, slots, valid, n_valid,
                 jnp.bool_(True), jnp.bool_(False), jnp.int32(0)))
    st, sl, va, n, _, ovf, _ = lax.while_loop(cond, body, init)
    return st, sl, va, n, ovf


def _make_step(succ, F, P):
    def step(carry, op):
        states, slots, valid, n, status, fail_at = carry
        kind, proc, tr, idx = op

        def do_invoke(_):
            return (states, slots.at[:, proc].set(tr), valid, n,
                    status, fail_at)

        def do_ok(_):
            st, sl, va, _, ovf = _closure(succ, states, slots, valid, n, F, P)
            returned = va & (sl[:, proc] == LIN)
            sl2 = sl.at[:, proc].set(IDLE)
            n2 = jnp.sum(returned)
            st_new = jnp.where(ovf, UNKNOWN,
                               jnp.where(n2 == 0, INVALID, VALID))
            return (st, sl2, returned, n2, st_new.astype(jnp.int32),
                    jnp.where(st_new == VALID, fail_at, idx))

        def dispatch(_):
            return lax.switch(kind, [lambda _: carry, do_invoke, do_ok], None)

        carry2 = lax.cond(status == VALID, dispatch, lambda _: carry, None)
        return carry2, None

    return step


def _check_impl(succ, kind, proc, tr, F: int, P: int):
    n_ops = kind.shape[0]
    states = jnp.zeros(F, jnp.int32)
    slots = jnp.full((F, P), IDLE, jnp.int32)
    valid = jnp.zeros(F, bool).at[0].set(True)
    carry = (states, slots, valid, jnp.int32(1), jnp.int32(VALID),
             jnp.int32(-1))
    ops = (kind, proc, tr, jnp.arange(n_ops, dtype=jnp.int32))
    step = _make_step(succ, F, P)
    (states, slots, valid, n, status, fail_at), _ = lax.scan(
        step, carry, ops)
    return status, fail_at, n


@functools.partial(jax.jit, static_argnames=("F", "P"))
def check_device(succ, kind, proc, tr, *, F: int, P: int):
    """Run the full search for one history on device.

    Returns ``(status, fail_index, n_final)`` — status is VALID/INVALID/
    UNKNOWN; fail_index is the history index of the op at which the
    frontier died (or overflowed)."""
    return _check_impl(succ, kind, proc, tr, F, P)


# --- batched (independent histories) ---------------------------------------

@functools.partial(jax.jit, static_argnames=("F", "P"))
def check_device_batch(succ, kind, proc, tr, *, F: int, P: int):
    """vmap over a batch of histories sharing one successor table — the
    TPU analog of ``independent/checker``'s per-key partitioning
    (``independent.clj:252-300``): thousands of per-key histories check
    in one launch."""
    fn = functools.partial(_check_impl, succ, F=F, P=P)
    return jax.vmap(fn)(kind, proc, tr)


def check_sharded(mesh, succ, kind, proc, tr, *, F: int, P: int,
                  batch_axis: str = "batch"):
    """Shard a batch of independent histories across a device mesh: the
    batch axis rides data parallelism over ICI; each device runs whole
    (sub)histories — no intra-search communication (SURVEY §2.5 item 8).
    """
    from jax.sharding import NamedSharding, PartitionSpec as Pspec
    batch_sh = NamedSharding(mesh, Pspec(batch_axis))
    repl = NamedSharding(mesh, Pspec())
    kind = jax.device_put(kind, batch_sh)
    proc = jax.device_put(proc, batch_sh)
    tr = jax.device_put(tr, batch_sh)
    succ = jax.device_put(succ, repl)
    return check_device_batch(succ, kind, proc, tr, F=F, P=P)
