"""Exhaustive linearizability oracle for tiny histories.

An independent implementation (WGL-style: pick linearization orders
directly from call intervals) used only to cross-validate the real
checkers in tests. Mirrors the *definition* of linearizability the
reference's searches implement (``knossos/core.clj:82-145`` explores the
same space via world permutations) without sharing any code with them.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import List, Optional

from ..models.model import Model, step
from ..ops import history as hist
from ..ops.op import Op


class _Call:
    __slots__ = ("inv", "ret", "f", "value", "required")

    def __init__(self, inv, ret, f, value, required):
        self.inv, self.ret = inv, ret
        self.f, self.value = f, value
        self.required = required


def brute_valid(model: Model, history: List[Op]) -> bool:
    """True iff some linearization of the history's completed calls (with
    info calls optionally interleaved anywhere after their invocation) is
    legal under ``model``. History need not be completed/indexed."""
    h = hist.complete(history, index=True)
    calls: List[_Call] = []
    inflight = {}
    for op in h:
        if op.type == "invoke":
            inflight[op.process] = op
        elif op.type == "ok":
            inv = inflight.pop(op.process)
            calls.append(_Call(inv.index, op.index, inv.f, inv.value, True))
        elif op.type == "fail":
            inflight.pop(op.process, None)  # known failure: never happened
        elif op.type == "info":
            # completion unknown: may take effect at any point after invoke
            inv = inflight.pop(op.process, None)
            if inv is not None:
                calls.append(_Call(inv.index, math.inf, inv.f, inv.value,
                                   False))
    # processes still in flight at end of history are also indeterminate
    for inv in inflight.values():
        calls.append(_Call(inv.index, math.inf, inv.f, inv.value, False))

    n = len(calls)

    @lru_cache(maxsize=None)
    def dfs(remaining: frozenset, model_state) -> bool:
        req = [i for i in remaining if calls[i].required]
        if not req:
            return True
        for i in remaining:
            c = calls[i]
            # c may be linearized next iff no other unlinearized *required*
            # call returned before c was invoked
            if any(calls[j].ret < c.inv for j in req if j != i):
                continue
            m2 = step(model_state, c.f, c.value)
            if m2 is not None and dfs(remaining - {i}, m2):
                return True
        return False

    return dfs(frozenset(range(n)), model)
