"""Bounded counterexample reconstruction for INVALID verdicts.

The role of the reference's ``final-paths`` (``knossos/linear.clj:
180-212``): turn "the frontier died at op i" into concrete failed
linearization orders a human can read. Round 1 re-ran the ENTIRE
history through the host engine to decode counterexamples — on a
50k-op history that resurrects the very CPU path the TPU replaced.

Here the work is bounded:

1. Re-scan the history on device in chunks (the adaptive segmented
   engine, :func:`~.linear_jax.check_device_seg2_chunk`), keeping the
   carry at the last chunk boundary BEFORE the frontier died. The
   carry's ``(states, slots, valid)`` triple decodes directly into
   host configs.
2. Replay at most one chunk of segments on host from that frontier
   (:func:`~.linear_host.check` with ``start_index``/``init_configs``)
   to recover the exact dying op, the closed frontier at death, and
   the pre-closure frontier.
3. DFS the pre-closure frontier's pending-call orders against the
   memoized model graph to produce ``final paths`` — each path is a
   sequence of (op, resulting model state) ending in the step that
   made the model inconsistent.

Device scan cost equals the original check's; host replay touches at
most ``chunk`` segments at frontier width <= F.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Set

import numpy as np

from ..models.memo import MemoizedModel
from ..ops.packed import PackedHistory
from ..utils import next_pow2 as _next_pow2
from . import linear_host
from .linear_host import IDLE, LIN, Config


@dataclass
class Counterexample:
    op_index: int                      # history index where search died
    configs: List[dict]                # decoded closed frontier at death
    paths: List[list] = field(default_factory=list)  # final paths
    raw_configs: List[Config] = field(default_factory=list)
    replayed_segments: int = 0         # host-replay bound (diagnostics)


def _unmap_configs(cfgs, owners_row, P: int) -> Set[Config]:
    """Map configs decoded in renamed-slot space (see
    :func:`~.linear_jax.remap_slots`) back to process-indexed slots of
    width ``P``. ``owners_row`` is the slot -> original-process map at
    the decoded segment boundary; a non-IDLE slot (pending OR
    linearized-but-not-returned) always has an owner there — the map
    only frees a slot at its ok."""
    out: Set[Config] = set()
    for (st, sl) in cfgs:
        slots = [IDLE] * P
        for q, t in enumerate(sl):
            if t == IDLE:
                continue
            p = int(owners_row[q]) if q < len(owners_row) else -1
            if p < 0:
                raise ValueError(
                    f"occupied slot {q} has no owning process at the "
                    "decoded boundary — owner map out of sync")
            slots[p] = t
        out.add((int(st), tuple(slots)))
    return out


def _carry_configs(carry, P: int) -> Set[Config]:
    """Decode a device seg-scan carry (states, slots, valid, ...) into
    host configs. Slot encoding is shared with the host engine
    (IDLE/LIN/transition-id); padding slots beyond P are always IDLE."""
    states = np.asarray(carry[0])
    slots = np.asarray(carry[1])
    valid = np.asarray(carry[2])
    return {(int(states[i]), tuple(int(x) for x in slots[i][:P]))
            for i in np.flatnonzero(valid)}


def reconstruct(mm: MemoizedModel, packed: PackedHistory,
                F: int = 256, chunk: int = 2048,
                max_paths: int = 10,
                max_host_configs: int = 1 << 16
                ) -> Optional[Counterexample]:
    """Reconstruct the counterexample for a history the device engines
    judged INVALID. Returns None when the re-scan does not reproduce
    the failure (e.g. the verdict came from a different engine setup).
    """
    from . import linear_jax as LJ

    P = len(packed.process_table)
    sizes = {"n_states": mm.n_states, "n_transitions": mm.n_transitions}
    # the same shape buckets AND slot renaming as
    # linear._analyze_device so the re-scan reuses the verdict path's
    # compiled programs instead of compiling fresh ones per raw (S, K).
    # The device frontier decodes in renamed-slot space; ``owners``
    # (slot -> original process, per segment) maps it back before the
    # host replay, which speaks process-indexed slots.
    segs = LJ.make_segments(packed)
    S = segs.ok_proc.shape[0]
    segs = LJ.make_segments(
        packed, s_pad=_next_pow2(S, 64),
        k_pad=_next_pow2(segs.inv_proc.shape[1], 2))
    segs, P_eff, owners = LJ.remap_slots(segs, with_maps=True)
    Pe = max(P_eff, 1)
    P2 = max(Pe + (Pe & 1), 2)

    # fast path: the fused kernel's chunked scan (~6x the XLA engine)
    # hands back the packed boundary frontier directly
    boundary = _pallas_boundary(mm, segs, P2 if P2 <= 7 else Pe, sizes)
    if boundary is not None:
        raw_cfgs, done, fail_seg = boundary
        boundary_cfgs = _unmap_configs(
            raw_cfgs, owners[done - 1] if done > 0 else (), P)
    else:
        # XLA fallback: chunked seg2 scan, decode the carry
        succ = LJ.pad_succ(mm.succ, _next_pow2(mm.succ.shape[0]),
                           _next_pow2(mm.succ.shape[1]))
        # chunk 2048 matches the progress path's chunking (shared
        # compile) and keeps the scan round-trip count low: a dispatch+
        # readback round-trip costs ~100 ms through the tunnel
        chunk = max(_next_pow2(min(chunk, max(S, 1))), 64)
        carry = LJ.init_seg_carry(F, P2)
        done = 0
        fail_seg = -1
        while done < S:
            end = min(done + chunk, S)
            pad = chunk - (end - done)
            ip = np.pad(segs.inv_proc[done:end], ((0, pad), (0, 0)),
                        constant_values=-1)
            it = np.pad(segs.inv_tr[done:end], ((0, pad), (0, 0)))
            op_ = np.pad(segs.ok_proc[done:end], (0, pad),
                         constant_values=-1)
            dp = np.pad(segs.depth[done:end], (0, pad))
            carry2 = LJ.check_device_seg2_chunk(
                succ, ip, it, op_, dp, done, carry, F=F, Fs=32, P=P2,
                **sizes)
            if int(carry2[4]) == LJ.INVALID:
                fail_seg = int(carry2[5])
                break
            if int(carry2[4]) != LJ.VALID:   # UNKNOWN: not decodable
                return None
            carry = carry2
            done = end
        if fail_seg < 0:
            return None
        # on the INVALID break ``carry`` still holds the last boundary
        # BEFORE the failing chunk — one frontier readback here instead
        # of one per chunk (each device->host round-trip is ~100 ms on
        # the tunnel)
        boundary_cfgs = _unmap_configs(
            _carry_configs(carry, Pe),
            owners[done - 1] if done > 0 else (), P)

    # host replay: from the history row after the boundary's last ok
    start_index = (int(segs.seg_index[done - 1]) + 1) if done > 0 else 0
    r = linear_host.check(mm, packed, max_configs=max_host_configs,
                          start_index=start_index,
                          init_configs=boundary_cfgs)
    if r.valid or r.op_index is None:
        return None                           # replay didn't reproduce
    cfgs = [linear_host.describe_config(mm, packed, c)
            for c in r.configs[:10]]
    paths = final_paths(mm, packed, r.pre_configs, r.op_index,
                        max_paths=max_paths)
    return Counterexample(op_index=r.op_index, configs=cfgs,
                          paths=paths, raw_configs=r.configs[:10],
                          replayed_segments=max(fail_seg - done + 1, 0))


def _pallas_boundary(mm, segs, P_k: int, sizes):
    """Run the fused kernel's chunked scan and return
    ``(boundary_configs, done, fail_seg)``, or None when the kernel
    can't serve this shape / didn't reproduce the INVALID."""
    from . import pallas_seg as PSEG

    if not PSEG.available():
        return None
    r = PSEG.check_device_pallas_chunked(
        mm.succ, segs, P=P_k, return_boundary=True, **sizes)
    if r is None or r[0] != PSEG.INVALID:
        return None
    status, fail_seg, _n, (ws, done) = r
    spec = PSEG.spec_for(sizes["n_states"], sizes["n_transitions"],
                         P_k, segs.inv_proc.shape[1])
    return PSEG.decode_frontier(spec, ws, P_k), done, fail_seg


def _op_desc(packed: PackedHistory, q: int, t: int) -> dict:
    """Human-readable pending call: process + (f, value)."""
    f_id, v_id = packed.transition_table[t]
    return {"process": packed.process_table[q],
            "f": packed.f_table[f_id],
            "value": packed.value_table[v_id]}


def final_paths(mm: MemoizedModel, packed: PackedHistory,
                configs: List[Config], op_index: int,
                max_paths: int = 10) -> List[list]:
    """Concrete failed linearization orders (``linear.clj:180-212``).

    For each seed config (the frontier just before the dying ok's
    closure), walk orders of pending calls through the memoized model
    graph; every branch ends in the step that made the model
    inconsistent. Each path is a list of ``{"op": ..., "model": ...}``
    entries whose last model is ``"inconsistent"``."""
    succ = mm.succ
    paths: List[list] = []

    def dfs(s: int, slots, acc) -> None:
        if len(paths) >= max_paths:
            return
        pend = [q for q, t in enumerate(slots) if t >= 0]
        if not pend:
            # every call linearized yet the config died — only possible
            # for malformed input; record it rather than drop the path
            paths.append(acc + [{"op": "(nothing pending)",
                                 "model": "returning process never "
                                          "linearized"}])
            return
        for q in pend:
            if len(paths) >= max_paths:
                return
            t = slots[q]
            s2 = int(succ[s][t])
            opd = _op_desc(packed, q, t)
            if s2 < 0:
                paths.append(acc + [{"op": opd,
                                     "model": "inconsistent"}])
            else:
                dfs(s2, slots[:q] + (LIN,) + slots[q + 1:],
                    acc + [{"op": opd,
                            "model": mm.states[s2].describe()}])

    ok_p = int(packed.process[op_index])
    for (s, slots) in configs:
        if len(paths) >= max_paths:
            break
        # paths that linearize the returning call and survive would
        # contradict the INVALID verdict, so the DFS only ever emits
        # dead ends; seed with the config's current model state
        dfs(int(s), tuple(slots),
            [{"op": "(state before %r returns)"
                    % (packed.process_table[ok_p],),
              "model": mm.states[int(s)].describe()}])
    return paths[:max_paths]
