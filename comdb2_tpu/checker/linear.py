"""Unified linearizability analysis — the ``knossos.linear/analysis``
equivalent (``linear.clj:299-355``).

Pipeline (mirroring the reference's): ``complete`` → ``index`` → pack →
``memo`` → frontier search → decoded verdict. Small histories run on the
host engine (the analog of staying single-threaded below the reference's
128-config pmap threshold, ``linear.clj:214-216``); larger ones run the
device engine with escalating frontier capacity, where overflow at the
largest capacity yields ``:unknown`` exactly like the reference's
low-memory abort (``linear.clj:318-326``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Union

from ..models.memo import MemoOverflow, MemoizedModel, memo as make_memo
from ..models.model import Model
from ..ops.op import Op
from ..ops.packed import PackedHistory, pack_history
from ..utils import next_pow2 as _next_pow2
from . import linear_host

UNKNOWN = "unknown"


@dataclass
class Analysis:
    """Checker verdict. ``valid`` is ``True``, ``False``, or
    ``"unknown"`` (search gave up — same tri-state as the reference's
    ``:valid?``)."""

    valid: Union[bool, str]
    op: Optional[Op] = None            # op at which the search died
    op_index: Optional[int] = None
    configs: List[dict] = field(default_factory=list)  # frontier sample
    final_count: int = 0
    info: dict = field(default_factory=dict)

    def to_map(self) -> dict:
        m = {"valid?": self.valid}
        if self.op is not None:
            m["op"] = self.op
            m["op-index"] = self.op_index
            m["configs"] = self.configs
        m.update(self.info)
        return m


def analysis(model: Model,
             history: Union[Sequence[Op], PackedHistory],
             backend: str = "auto",
             capacities: Sequence[int] = (64, 1024, 8192, 65536),
             host_threshold: int = 128,
             max_states: int = 1 << 20,
             max_host_configs: int = 1 << 22) -> Analysis:
    """Check ``history`` against ``model`` for linearizability.

    backend: "auto" | "host" | "device".
    capacities: device frontier sizes tried in order; overflow escalates,
    overflow at the last yields :unknown.
    """
    t0 = time.monotonic()
    packed = (history if isinstance(history, PackedHistory)
              else pack_history(list(history)))
    n = len(packed)
    P = len(packed.process_table)
    if n == 0 or P == 0:
        return Analysis(valid=True, info={"backend": "trivial"})

    try:
        mm = make_memo(model, packed, max_states=max_states)
    except MemoOverflow as e:
        return Analysis(valid=UNKNOWN, info={"cause": str(e)})

    if backend == "host" or (backend == "auto" and n < host_threshold):
        return _analyze_host(mm, packed, max_host_configs, t0)
    return _analyze_device(mm, packed, capacities, t0)


def _analyze_host(mm: MemoizedModel, packed: PackedHistory,
                  max_configs: int, t0: float) -> Analysis:
    try:
        r = linear_host.check(mm, packed, max_configs=max_configs)
    except linear_host.FrontierOverflow as e:
        return Analysis(valid=UNKNOWN, info={"cause": str(e),
                                             "backend": "host"})
    info = {"backend": "host", "max_frontier": r.max_frontier,
            "time_s": time.monotonic() - t0}
    if r.valid:
        return Analysis(valid=True, final_count=r.final_count, info=info)
    op = packed.ops[r.op_index]
    cfgs = [linear_host.describe_config(mm, packed, c)
            for c in r.configs[:10]]
    return Analysis(valid=False, op=op, op_index=r.op_index,
                    configs=cfgs, info=info)


def _analyze_device(mm: MemoizedModel, packed: PackedHistory,
                    capacities: Sequence[int], t0: float) -> Analysis:
    from . import linear_jax as LJ

    P = len(packed.process_table)
    succ = LJ.pad_succ(mm.succ, _next_pow2(mm.succ.shape[0]),
                       _next_pow2(mm.succ.shape[1]))
    segs = LJ.make_segments(packed)
    segs = LJ.make_segments(
        packed, s_pad=_next_pow2(segs.ok_proc.shape[0], 64),
        k_pad=_next_pow2(segs.inv_proc.shape[1], 2))
    info: dict = {"backend": "device", "n_states": mm.n_states,
                  "n_transitions": mm.n_transitions}
    for F in capacities:
        status, fail_seg, n_final = LJ.check_device_seg(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
            F=F, P=_next_pow2(P, 2),
            n_states=mm.n_states, n_transitions=mm.n_transitions)
        status = int(status)
        info["frontier_capacity"] = F
        if status != LJ.UNKNOWN:
            break
    info["time_s"] = time.monotonic() - t0
    fail_at = (int(segs.seg_index[int(fail_seg)])
               if int(fail_seg) >= 0 else -1)
    if status == LJ.VALID:
        return Analysis(valid=True, final_count=int(n_final), info=info)
    if status == LJ.UNKNOWN:
        return Analysis(valid=UNKNOWN, op_index=fail_at,
                        info={**info, "cause": "frontier overflow"})
    # invalid: decode counterexample context on host (the final-paths
    # role, linear.clj:180-212); bounded so it can't explode
    op_index = fail_at
    op = packed.ops[op_index]
    cfgs: List[dict] = []
    try:
        r = linear_host.check(mm, packed, max_configs=1 << 16)
        if not r.valid:
            cfgs = [linear_host.describe_config(mm, packed, c)
                    for c in r.configs[:10]]
            op_index = r.op_index
            op = packed.ops[op_index]
    except linear_host.FrontierOverflow:
        pass
    return Analysis(valid=False, op=op, op_index=op_index, configs=cfgs,
                    info=info)
