"""Unified linearizability analysis — the ``knossos.linear/analysis``
equivalent (``linear.clj:299-355``).

Pipeline (mirroring the reference's): ``complete`` → ``index`` → pack →
``memo`` → frontier search → decoded verdict. Small histories run on the
host engine (the analog of staying single-threaded below the reference's
128-config pmap threshold, ``linear.clj:214-216``); larger ones run the
device engine with escalating frontier capacity, where overflow at the
largest capacity yields ``:unknown`` exactly like the reference's
low-memory abort (``linear.clj:318-326``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Union

from ..models.memo import MemoOverflow, MemoizedModel, memo as make_memo
from ..models.model import Model
from ..obs import trace as _obs
from ..ops.op import Op
from ..ops.packed import PackedHistory, pack_history
from ..utils import next_pow2 as _next_pow2
from . import linear_host

UNKNOWN = "unknown"


@dataclass
class Analysis:
    """Checker verdict. ``valid`` is ``True``, ``False``, or
    ``"unknown"`` (search gave up — same tri-state as the reference's
    ``:valid?``)."""

    valid: Union[bool, str]
    op: Optional[Op] = None            # op at which the search died
    op_index: Optional[int] = None
    configs: List[dict] = field(default_factory=list)  # frontier sample
    final_count: int = 0
    info: dict = field(default_factory=dict)

    def to_map(self) -> dict:
        m = {"valid?": self.valid}
        if self.op is not None:
            m["op"] = self.op
            m["op-index"] = self.op_index
            m["configs"] = self.configs
        m.update(self.info)
        return m


# histories with more padded segments than this always run the chunked
# engine (XLA compile time scales with scan length; see _analyze_device)
CHUNKED_S_THRESHOLD = 4096


@_obs.traced("linear.analysis")
def analysis(model: Model,
             history: Union[Sequence[Op], PackedHistory],
             backend: str = "auto",
             capacities: Sequence[int] = (256, 1024, 8192, 65536),
             host_threshold: int = 128,
             max_states: int = 1 << 20,
             max_host_configs: int = 1 << 22,
             progress=None,
             progress_interval_s: float = 5.0) -> Analysis:
    """Check ``history`` against ``model`` for linearizability.

    backend: "auto" | "host" | "device".
    capacities: device frontier sizes tried in order; overflow escalates,
    overflow at the last yields :unknown. The MXU arm (wide P) buckets
    each entry up to its own pow2 rung set (``mxu.CAPACITIES``) so its
    program surface stays closed — the ladder still starts and stops
    where the caller's bounds say.
    progress: optional callback ``progress(done_segments, total_segments,
    frontier_count, stats)`` invoked between device chunks at roughly
    ``progress_interval_s`` cadence — the role of the reference's
    5-second reporter threads (``linear.clj:273-297``). ``stats`` is a
    dict with ``visited_per_s`` (configs stepped per second),
    ``segs_per_s``, and ``est_cost`` (the Σ n·n! pending-count cost
    model of ``linear/config.clj:374-393``). When given, the device
    path runs chunked.
    """
    t0 = _obs.monotonic()
    packed = (history if isinstance(history, PackedHistory)
              else pack_history(list(history)))
    n = len(packed)
    P = len(packed.process_table)
    if n == 0 or P == 0:
        return Analysis(valid=True, info={"backend": "trivial"})

    try:
        mm = make_memo(model, packed, max_states=max_states)
    except MemoOverflow as e:
        return Analysis(valid=UNKNOWN, info={"cause": str(e)})
    # pack+memo attribution for the offline trace (filetest --trace)
    _obs.record("linear.pack", t0, _obs.monotonic(), n=n, P=P)

    if backend == "host" or (backend == "auto" and n < host_threshold):
        return _analyze_host(mm, packed, max_host_configs, t0)
    return _analyze_device(mm, packed, capacities, t0,
                           progress=progress,
                           progress_interval_s=progress_interval_s)


@_obs.traced("linear.host")
def _analyze_host(mm: MemoizedModel, packed: PackedHistory,
                  max_configs: int, t0: float) -> Analysis:
    try:
        r = linear_host.check(mm, packed, max_configs=max_configs)
    except linear_host.FrontierOverflow as e:
        return Analysis(valid=UNKNOWN, info={"cause": str(e),
                                             "backend": "host"})
    info = {"backend": "host", "max_frontier": r.max_frontier,
            "time_s": _obs.monotonic() - t0}
    if r.valid:
        return Analysis(valid=True, final_count=r.final_count, info=info)
    op = packed.ops[r.op_index]
    cfgs = [linear_host.describe_config(mm, packed, c)
            for c in r.configs[:10]]
    # final paths on the host path too — the reference's analysis
    # always carries them on invalid (linear.clj:251-265); without
    # this, small (below-host-threshold) histories rendered
    # counterexample SVGs with no linearization orders at all
    try:
        from .counterexample import final_paths
        info["paths"] = final_paths(mm, packed, r.pre_configs,
                                    r.op_index)
    except Exception as e:
        # decoration never destroys the verdict — but a silently
        # dropped decoration is how the no-orders-in-SVG bug hid;
        # leave a diagnosable trace in the report
        info["paths_error"] = repr(e)
    return Analysis(valid=False, op=op, op_index=r.op_index,
                    configs=cfgs, info=info)


@_obs.traced("linear.device")
def _analyze_device(mm: MemoizedModel, packed: PackedHistory,
                    capacities: Sequence[int], t0: float,
                    progress=None,
                    progress_interval_s: float = 5.0) -> Analysis:
    import numpy as np

    from . import linear_jax as LJ

    import jax

    P = len(packed.process_table)
    # ship the successor table once — chunked runs and capacity
    # escalation reuse the same device buffer
    succ = jax.device_put(LJ.pad_succ(mm.succ,
                                      _next_pow2(mm.succ.shape[0]),
                                      _next_pow2(mm.succ.shape[1])))
    segs = LJ.make_segments(packed)
    s_real = segs.ok_proc.shape[0]
    segs = LJ.make_segments(
        packed, s_pad=_next_pow2(s_real, 64),
        k_pad=_next_pow2(segs.inv_proc.shape[1], 2))
    # slot renaming: processes map to a minimal pool of reusable
    # slots, so every engine's slot axis scales with the history's
    # max CONCURRENT open calls instead of its process count (a
    # concurrency-10 register history with <=6 calls in flight runs
    # the fused kernel's fast (8,128)/2-word tier; a 30-process
    # cluster history with bounded in-flight depth becomes
    # kernel-eligible at all). Pure relabeling — verdicts and fail
    # segments are unchanged (see LJ.remap_slots).
    segs, P_eff = LJ.remap_slots(segs)
    P = max(P_eff, 1)
    info: dict = {"backend": "device", "n_states": mm.n_states,
                  "n_transitions": mm.n_transitions,
                  "effective_slots": P}
    sizes = {"n_states": mm.n_states, "n_transitions": mm.n_transitions}
    # bucket the slot axis to the next even value, not pow2: candidate
    # rows scale with P, so pow2 padding costs up to ~25% extra work
    # per closure iteration (measured 9.5k -> 11.4k ops/s on the bench
    # shape); even-bucketing keeps recompiles bounded
    P2 = P + (P & 1)
    P2 = max(P2, 2)
    # fused-kernel fast path: the whole segment loop runs inside one
    # Pallas kernel per 1024-segment chunk (checker/pallas_seg.py),
    # ~4x the XLA engines on a real TPU. F is fixed at 128 there;
    # overflow (UNKNOWN) falls through to the XLA capacity ladder, any
    # other unavailability (CPU backend, key budget, table size,
    # P > 15) falls back silently — check_device_pallas* return None
    # when spec_for rejects the shape.
    from . import pallas_seg as PSEG

    # even-bucket the kernel's slot count only while it stays in the
    # (8,128) tier; the (16,128) tier keys are wide enough that a pad
    # slot can cost a whole extra key word
    P_k = P2 if P2 <= PSEG.ROWS - 1 else P
    r = None
    # available() probes Mosaic support once per process; past that
    # gate, errors are real bugs (or a raising progress callback) and
    # must propagate, not silently rerun on the XLA path
    if P_k <= 2 * PSEG.ROWS - 1 and PSEG.available():
        if progress is None:
            r = PSEG.check_device_pallas(
                mm.succ, segs, n_states=mm.n_states,
                n_transitions=mm.n_transitions, P=P_k)
        else:
            r = PSEG.check_device_pallas_chunked(
                mm.succ, segs, n_states=mm.n_states,
                n_transitions=mm.n_transitions, P=P_k,
                progress=progress,
                progress_interval_s=progress_interval_s,
                s_real=s_real)
    if r is not None:
        status, fail_seg, n_final = r
        info["engine"] = "pallas-fused"
        info["frontier_capacity"] = PSEG.F
        if status != LJ.UNKNOWN:
            info["time_s"] = _obs.monotonic() - t0
            return _device_verdict(mm, packed, segs, status, fail_seg,
                                   n_final, info)
        # kernel overflow: record the attempt, then escalate — the
        # final artifact must say WHICH engine produced the verdict
        # and what was tried on the way (a wide-P UNKNOWN used to be
        # indistinguishable from a capacity abort in filetest output)
        _note_tried(info, "pallas-fused", PSEG.F)

    # MXU frontier engine: P past the fused kernel's tiers but with
    # bounded in-flight (remap_slots makes P the max CONCURRENT open
    # calls) rides BFS-as-matmul expansion with the exact packed-key
    # dedup — its capacity ladder tops out at 2x the XLA ladder's, so
    # wide-P workloads that overflowed 65536 now get verdicts
    # (docs/architecture.md "The engine ladder").
    from . import mxu as MXU

    if MXU.serves(mm.n_states, mm.n_transitions, P2):
        return _analyze_mxu(mm, packed, segs, succ, P2, t0, info,
                            capacities=capacities,
                            progress=progress,
                            progress_interval_s=progress_interval_s,
                            s_real=s_real)

    # the adaptive engine's small tier: most segments' closed frontiers
    # are tiny (p50 ~ 8 configs on the register bench), so each segment
    # first runs at Fs and escalates to F per-segment on overflow (the
    # engine degrades to big-only when F is too small for the tier)
    info["engine"] = "xla-seg2"
    Fs = 32
    # Large histories ALWAYS run chunked, progress callback or not:
    # XLA compile time scales with the scan length, and a monolithic
    # 65536-segment program takes >10 min to compile per ladder level
    # where the 2048-chunk program compiles in ~35 s and streams the
    # remaining chunks in seconds (measured on a 117k-event P=18
    # history). Small histories keep the single-dispatch form — per-
    # chunk dispatch overhead would dominate them on the tunnel.
    chunked = (progress is not None
               or segs.ok_proc.shape[0] > CHUNKED_S_THRESHOLD)
    if not chunked:
        for F in capacities:
            status, fail_seg, n_final = LJ.check_device_seg2(
                succ, segs.inv_proc, segs.inv_tr, segs.ok_proc,
                segs.depth, F=F, Fs=Fs, P=P2, **sizes)
            status = int(status)
            info["frontier_capacity"] = F
            if status != LJ.UNKNOWN:
                break
    else:
        # chunked, with IN-PLACE capacity escalation: an overflow
        # re-runs only the overflowing chunk with the boundary carry
        # widened to the next ladder level — a restart would repay
        # every already-checked chunk per level (on a 117k-event
        # history each full pass is ~40 s even warm)
        S = segs.ok_proc.shape[0]
        chunk = max(_next_pow2(min(S, 2048)), 64)
        cap_ix = 0
        F = capacities[cap_ix]
        carry = LJ.init_seg_carry(F, P2)
        t_run = _obs.monotonic()
        last = t_run
        done = 0
        visited = 0
        while done < S:
            end = min(done + chunk, S)
            pad = chunk - (end - done)
            ip = np.pad(segs.inv_proc[done:end],
                        ((0, pad), (0, 0)), constant_values=-1)
            it = np.pad(segs.inv_tr[done:end], ((0, pad), (0, 0)))
            op_ = np.pad(segs.ok_proc[done:end], (0, pad),
                         constant_values=-1)
            dp = np.pad(segs.depth[done:end], (0, pad))
            new_carry = LJ.check_device_seg2_chunk(
                succ, ip, it, op_, dp, done, carry, F=F, Fs=Fs,
                P=P2, **sizes)
            st = int(new_carry[4])
            if st == LJ.UNKNOWN and cap_ix + 1 < len(capacities):
                cap_ix += 1
                F = capacities[cap_ix]
                carry = LJ.expand_seg_carry(carry, F)
                continue            # same chunk, wider frontier
            carry = new_carry
            visited += int(carry[3]) * (end - done)
            done = end
            if st != LJ.VALID:
                break
            now = _obs.monotonic()
            if progress is not None and \
                    now - last >= progress_interval_s:
                # pending counts from the carry: telemetry parity
                # with the reference's visited/s + estimated-cost
                # reporters (core.clj:442-460, config.clj:374-393).
                # Bucketed on device so only P+1 ints ride the
                # (slow) tunnel per tick, never the (F, P) frontier
                hist = np.asarray(LJ.pending_histogram(
                    carry[1], carry[2], P=P2))
                el = max(now - t_run, 1e-9)
                progress(min(done, s_real), s_real, int(carry[3]),
                         {"visited_per_s": visited / el,
                          "segs_per_s": done / el,
                          "est_cost": LJ.estimated_cost_hist(hist)})
                last = now
        status, fail_seg, n_final = (int(carry[4]), carry[5],
                                     carry[3])
        info["frontier_capacity"] = F
    info["time_s"] = _obs.monotonic() - t0
    return _device_verdict(mm, packed, segs, status, fail_seg, n_final,
                           info)


def _note_tried(info: dict, engine: str, capacity) -> None:
    """Record an engine attempt that did NOT produce the verdict —
    the artifact's ``engines_tried`` trail makes a wide-P UNKNOWN
    distinguishable from a capacity abort (each entry names the
    engine and the frontier capacity it gave up at)."""
    info.setdefault("engines_tried", []).append(
        {"engine": engine, "frontier_capacity": capacity})


@_obs.traced("linear.mxu")
def _analyze_mxu(mm: MemoizedModel, packed: PackedHistory, segs, succ,
                 P: int, t0: float, info: dict,
                 capacities: Sequence[int] = None, progress=None,
                 progress_interval_s: float = 5.0,
                 s_real: int = None) -> Analysis:
    """The MXU frontier engine's driver arm: capacity ladder over
    ``mxu.CAPACITIES`` with the same chunked / in-place-escalation
    discipline as the XLA arm (an overflow widens the PRE-chunk carry
    and re-runs only the overflowing chunk). Terminal for the shapes
    it serves: its top rung is 2x the XLA ladder's, so there is no
    wider engine to fall through to — overflow past it is the honest
    UNKNOWN, attributed to this engine in the artifact.

    ``capacities`` is the caller's ``analysis(capacities=...)`` bound:
    each entry buckets UP to the smallest ``mxu.CAPACITIES`` rung that
    holds it (the program surface stays closed on the declared rungs)
    and the ladder runs only those rungs — a caller bounding device
    work can force an early UNKNOWN here exactly like on the XLA arm.
    """
    import numpy as np

    from . import linear_jax as LJ
    from . import mxu as MXU

    if capacities is None:
        ladder = tuple(MXU.CAPACITIES)
    else:
        ladder = tuple(sorted({MXU.bucket_F(f) for f in capacities}))

    info["engine"] = "mxu-frontier"
    sizes = {"n_states": mm.n_states, "n_transitions": mm.n_transitions}
    S = segs.ok_proc.shape[0]
    if s_real is None:
        s_real = S
    chunked = (progress is not None or S > CHUNKED_S_THRESHOLD)
    if not chunked:
        for F in ladder:
            status, fail_seg, n_final = MXU.check_device_mxu(
                succ, segs.inv_proc, segs.inv_tr, segs.ok_proc,
                segs.depth, F=F, P=P, **sizes)
            status = int(status)
            info["frontier_capacity"] = F
            if status != LJ.UNKNOWN:
                break
    else:
        chunk = max(_next_pow2(min(S, MXU.CHUNK)), 64)
        cap_ix = 0
        F = ladder[cap_ix]
        carry = MXU.init_carry(1, F, P, **sizes)
        t_run = _obs.monotonic()
        last = t_run
        done = 0
        visited = 0
        while done < S:
            end = min(done + chunk, S)
            pad = chunk - (end - done)
            ip = np.pad(segs.inv_proc[done:end],
                        ((0, pad), (0, 0)), constant_values=-1)
            it = np.pad(segs.inv_tr[done:end], ((0, pad), (0, 0)))
            op_ = np.pad(segs.ok_proc[done:end], (0, pad),
                         constant_values=-1)
            dp = np.pad(segs.depth[done:end], (0, pad))
            new_carry = MXU.check_device_mxu_chunk(
                succ, ip, it, op_, dp, done, carry, F=F, P=P, **sizes)
            st = int(new_carry[3][0])
            if st == LJ.UNKNOWN and cap_ix + 1 < len(ladder):
                cap_ix += 1
                F = ladder[cap_ix]
                carry = MXU.expand_carry(carry, F)
                continue            # same chunk, wider frontier
            carry = new_carry
            visited += int(carry[2][0]) * (end - done)
            done = end
            if st != LJ.VALID:
                break
            now = _obs.monotonic()
            if progress is not None and \
                    now - last >= progress_interval_s:
                hist = np.asarray(MXU.pending_histogram(
                    carry[0], carry[1], P=P, **sizes))
                el = max(now - t_run, 1e-9)
                # report against the REAL segment count like the XLA
                # arm — S here is the pow2-padded axis
                progress(min(done, s_real), s_real, int(carry[2][0]),
                         {"visited_per_s": visited / el,
                          "segs_per_s": done / el,
                          "est_cost": LJ.estimated_cost_hist(hist)})
                last = now
        status, fail_seg, n_final = (int(carry[3][0]), carry[4][0],
                                     carry[2][0])
        info["frontier_capacity"] = F
    info["time_s"] = _obs.monotonic() - t0
    return _device_verdict(mm, packed, segs, status, fail_seg, n_final,
                           info)


def _device_verdict(mm, packed, segs, status, fail_seg, n_final,
                    info) -> Analysis:
    """Decode an engine's (status, fail_segment, n) into an Analysis."""
    from . import linear_jax as LJ

    fail_at = (int(segs.seg_index[int(fail_seg)])
               if int(fail_seg) >= 0 else -1)
    if status == LJ.VALID:
        return Analysis(valid=True, final_count=int(n_final), info=info)
    if status == LJ.UNKNOWN:
        # attribute the give-up: which engine, at what capacity (plus
        # the engines_tried trail) — a wide-P overflow and a kernel
        # capacity abort used to render identically in the artifact
        cause = (f"frontier overflow (engine="
                 f"{info.get('engine', '?')}, capacity="
                 f"{info.get('frontier_capacity', '?')})")
        return Analysis(valid=UNKNOWN, op_index=fail_at,
                        info={**info, "cause": cause})
    # invalid: bounded counterexample reconstruction (the final-paths
    # role, linear.clj:180-212) — device re-scan to the failing chunk,
    # host replay of at most one chunk from the boundary carry, then
    # concrete failed linearization orders. Never re-runs the whole
    # history on host (round-1 Weak #3).
    op_index = fail_at
    op = packed.ops[op_index]
    cfgs: List[dict] = []
    try:
        from . import counterexample as CE
        # F >= the verdict's capacity: a larger frontier can't change
        # an INVALID verdict (overflow would have been UNKNOWN), and
        # the 256 floor shares compiles with the capacity ladder. The
        # re-scan runs the XLA chunk engine, whose ladder tops out at
        # 65536 — an MXU verdict from the 131072 rung clamps down
        # rather than compiling a frontier width the XLA engine never
        # otherwise sees (compile time scales with F; a re-scan
        # overflow at the clamp degrades to an undecorated INVALID)
        ce = CE.reconstruct(mm, packed,
                            F=max(256, min(info.get(
                                "frontier_capacity", 256), 65536)))
        if ce is not None:
            cfgs = ce.configs
            op_index = ce.op_index
            op = packed.ops[op_index]
            info = {**info, "paths": ce.paths}
    except Exception as e:
        # decoration must never destroy an already-decided verdict: a
        # reconstruction failure (frontier overflow, compile error, …)
        # degrades to an un-annotated INVALID
        import logging

        logging.getLogger(__name__).warning(
            "counterexample reconstruction failed (%s: %s) — "
            "returning undecorated INVALID", type(e).__name__, e)
    return Analysis(valid=False, op=op, op_index=op_index, configs=cfgs,
                    info=info)
