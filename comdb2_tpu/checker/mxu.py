"""MXU frontier engine — BFS-as-matmul closure for wide-P histories.

The fused Pallas kernel (:mod:`.pallas_seg`) serves P <= 15 and the
two-word key engines cap out around the same width; genuinely
concurrent P >= 16 closures are 2^P frontiers that overflow the XLA
ladder's 65536 cap and come back honest UNKNOWN. This engine converts
that workload class to verdicts, borrowing the tensor-core-BFS pattern
(PAPERS.md: *Graph Traversal on Tensor Cores*, *BLEST*) our txn
matrix-closure engine already proved out (:mod:`comdb2_tpu.txn
.closure_jax`): when the frontier is wide, expansion as structured
matmul beats scalar/sort pipelines.

Design:

- **Configs are bit-packed, never explicit tensors.** A config (state +
  P slots) packs losslessly into ``PackPlan.n_words`` int32 words
  (:class:`~.linear_jax.PackPlan`, the round-4 wide-P key plan whose
  per-word budgets come from ``_greedy_split``). The frontier is W
  word-columns of ``B*F`` rows — at P=30 that is ~5 words/config
  instead of the 31 an explicit ``(F, P)`` slot tensor costs, which is
  what makes frontier capacities past 65536 affordable at all. Slot
  mutation (invoke / linearize / return) is single-word field
  arithmetic; no field straddles a word by the plan's construction.
- **Expansion rides the MXU.** One closure step computes the successor
  state of EVERY (config, transition) pair at once: the frontier's
  one-hot config-by-state incidence ``[B*F, S]`` multiplies the
  successor table's value and validity planes ``[S, T]`` in two bf16
  matmuls with f32 accumulation — exact, the :mod:`~comdb2_tpu.txn
  .closure_jax` trick: operands are 0/1 one-hot rows against entries
  <= ``S_CAP``-1 = 255 (bf16 has 8 mantissa bits; integers to 256 are
  exact) and each output element has exactly one nonzero partial, so
  nothing can cancel or round.  Per-slot candidates then select their
  transition's lane from the ``[B*F, T]`` surface — a lane gather, not
  a 2-D table gather per (config, slot).
- **Dedup stays the exact sort-adjacency lexsort.** Candidate ∪
  frontier rows sort by their packed words plus one extra top key
  ``batch*2 + invalid`` (invalid rows zero their plan words and stay
  inside their batch's block, the :func:`~.linear_jax
  ._flat_dedup_compact` discipline); duplicates are adjacent by
  construction and compact per batch with the fixed-block-count
  arithmetic. Hash-fingerprint ordering stays banned — colliding
  non-identical rows would break adjacency exactly as everywhere else.
- **Capacity escalates in-place.** The chunked entry carries the
  frontier between calls like ``expand_seg_carry``: an overflow widens
  the PREVIOUS chunk boundary's carry to the next ladder rung and
  re-runs only the overflowing chunk. ``CAPACITIES`` tops out at
  131072 — the honest-UNKNOWN threshold for wide P is now 2x the XLA
  ladder's, and only past it does the driver report UNKNOWN.

Shape discipline: F and the memo dims ride the usual pow2 buckets
(``pad_succ``); P is even-bucketed by the driver like the XLA engines;
the batch form's tensors are the same ``(S, B, K)`` family the
keys/flat engines use, so the serving layer's closed-program-set
rules apply unchanged (PROGRAMS.md `mxu-frontier` site).

Crossover (docs/architecture.md has the arithmetic): below P = 16 the
fused kernel (P <= 15) and the 2-word key engines win — their whole
closure iteration is a handful of vreg ops, while a matmul step pays
the ``[B*F, S]`` one-hot build regardless of frontier occupancy. At
P >= 16 the explicit engines' per-iteration cost scales with P (P
gather/scatter passes, P+2 sort keys) while this engine's matmul is
P-independent and its key count grows only as ceil(bits/31).
"""

from __future__ import annotations

import functools
import os as _os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from . import linear_jax as LJ

VALID, INVALID, UNKNOWN = LJ.VALID, LJ.INVALID, LJ.UNKNOWN

#: driver crossover: the fused kernel serves P <= 15; this engine owns
#: wider P (bounded in-flight — remap_slots makes P the max CONCURRENT
#: open calls, so any history with bounded in-flight depth qualifies)
MIN_P = 16

#: past this the multi-word sort keys (W ~ P*slot_bits/31) stop paying
#: for themselves; the XLA seg2 ladder still serves such shapes
MAX_P = 32

#: successor-table caps: S_CAP keeps every succ entry <= 255 so ONE
#: bf16 value plane is exact (8 mantissa bits); T_CAP bounds the
#: matmul surface's lane axis
S_CAP = 256
T_CAP = 128

#: frontier ladder (pow2; in-place escalation). The top rung is the
#: new honest-UNKNOWN threshold — 2x the XLA ladder's 65536.
CAPACITIES = (1024, 8192, 131072)

#: single chunk of the chunked driver path (segments per dispatch)
CHUNK = 1024

#: engine dispatches this process, counted at the public entries
#: below (the ``closure_jax.closure_diag`` idiom: jitted cores keep
#: the compile-log names, thin wrappers count) — bench/fuzz scripts
#: assert the one-dispatch-per-call discipline on measured deltas
DISPATCHES = 0


def enabled() -> bool:
    """Escape hatch: ``COMDB2_TPU_MXU=0`` routes wide-P traffic back
    to the XLA ladder (read per call — tests flip it)."""
    return _os.environ.get("COMDB2_TPU_MXU", "1") != "0"


def bucket_F(F: int) -> int:
    """Bucket a caller frontier budget UP to the smallest
    ``CAPACITIES`` rung that holds it (the top rung when none does).
    Every dispatch site must route F through this: the rungs are the
    engine's declared program surface (PROGRAMS.md mxu-frontier F
    axis), and a raw caller F would compile an off-inventory program
    the guard can't see."""
    return next((c for c in CAPACITIES if c >= F), CAPACITIES[-1])


def fits(n_states: int, n_transitions: int, P: int) -> bool:
    """Shape-only capability gate (no driver policy): table inside the
    matmul caps, P inside the key budget, and a lossless
    :class:`~.linear_jax.PackPlan` exists."""
    if P < 1 or P > MAX_P:
        return False
    if n_states > S_CAP or n_transitions > T_CAP:
        return False
    return LJ.make_pack_plan(n_states, n_transitions, P) is not None


def serves(n_states: int, n_transitions: int, P: int) -> bool:
    """Driver policy: the engine owns P >= MIN_P (the fused kernel and
    the 2-word key engines win below the crossover)."""
    return enabled() and P >= MIN_P and fits(n_states, n_transitions, P)


# --- packed-field arithmetic ------------------------------------------------
#
# fields = [state, slot_0, .., slot_{P-1}] at plan.assign positions;
# slot values stored +2 (LIN=-2 -> 0, IDLE=-1 -> 1, pending t -> t+2)
# exactly like _pack_plan_words, so a dedup key here IS the key the
# single-history engines sort by.

def _get(plan, words, fi):
    w, sh = plan.assign[fi]
    width = plan.state_bits if fi == 0 else plan.slot_bits
    return (words[w] >> sh) & ((1 << width) - 1)


def _add(plan, words, fi, delta):
    """Add a (data-dependent) delta to field ``fi``; every mutation
    keeps the field in range, so no borrow can cross fields."""
    w, sh = plan.assign[fi]
    out = list(words)
    out[w] = out[w] + (delta << sh)
    return out


def _get_slot_dyn(plan, words, p):
    """Extract slot ``p`` where ``p`` is a per-row tensor (unrolled
    over the static P — the KeyLayout.slot_dynamic pattern)."""
    out = jnp.zeros_like(words[0])
    for q in range(plan.P):
        out = jnp.where(p == q, _get(plan, words, 1 + q), out)
    return out


def _add_slot_dyn(plan, words, p, delta):
    out = list(words)
    for q in range(plan.P):
        w, sh = plan.assign[1 + q]
        out[w] = out[w] + (jnp.where(p == q, delta, 0) << sh)
    return out


def _idle_words(plan) -> list:
    """Host ints: the packed initial config (state 0, all slots IDLE)."""
    vals = [0] * plan.n_words
    for q in range(plan.P):
        w, sh = plan.assign[1 + q]
        vals[w] |= 1 << sh                       # IDLE stores as 1
    return vals


# --- exact dedup: packed-key lexsort + per-batch block compaction -----------

def _dedup(plan, words, valid, B: int, F: int):
    """Sort rows by (plan words, batch*2+invalid top key — primary);
    duplicates are ADJACENT by exactness of the packed keys; compact
    each batch's survivors into its F-row block. Every chunk of the
    input contributes exactly B*F batch-major rows, so batch b owns
    sorted rows [b*R, (b+1)*R) — the fixed-block-count argument of
    ``_flat_dedup_compact``. Returns (words', valid', n_per_batch[B],
    overflow[B]) at frontier shape (B*F,)."""
    rows = words[0].shape[0]
    R = rows // B
    batch = (jnp.arange(rows, dtype=jnp.int32) % (B * F)) // F
    # invalid rows zero their fields (negative garbage would corrupt
    # the sort) but KEEP their batch id, so per-batch row counts stay
    # fixed; the invalid bit sorts them to their block's tail
    ws = [jnp.where(valid, w, 0) for w in words]
    top = batch * 2 + (~valid).astype(jnp.int32)
    order = jnp.lexsort(tuple(ws) + (top,))
    ws = [w[order] for w in ws]
    tops = top[order]
    va = valid[order]
    pad = jnp.zeros(1, bool)
    eq = tops[1:] == tops[:-1]                   # same batch, both valid
    for w in ws:
        eq = eq & (w[1:] == w[:-1])
    same = jnp.concatenate([pad, eq & va[:-1]])
    keep = va & ~same
    c = jnp.cumsum(keep)
    e = c - keep
    row = jnp.arange(rows)
    block = row // R
    base = e.reshape(B, R)[:, 0]
    rank = e - base[block]
    n_b = c.reshape(B, R)[:, -1] - base
    target = jnp.where(keep & (rank < F), block * F + rank, B * F)
    out = [jnp.zeros(B * F + 1, jnp.int32).at[target].set(w, mode="drop")
           [:B * F] for w in ws]
    slot_row = jnp.arange(B * F)
    out_va = (slot_row % F) < jnp.minimum(n_b, F)[slot_row // F]
    return out, out_va, jnp.minimum(n_b, F), n_b > F


# --- matmul expansion + closure ---------------------------------------------

def _succ_planes(succ):
    """Value and validity planes of the (padded) successor table as
    bf16 matmul operands. Entries are < S_CAP = 256, so the value
    plane is bf16-EXACT on its own (no byte slicing needed)."""
    val = jnp.maximum(succ, 0).astype(jnp.bfloat16)
    ok = (succ >= 0).astype(jnp.bfloat16)
    return val, ok


def _expand_surface(plan, succ_val, succ_ok, words):
    """The MXU step: one-hot config-by-state incidence times the
    successor planes -> per-(config, transition) successor state and
    validity surfaces, ``[rows, T]`` each. Exact: 0/1 one-hot rows,
    entries <= 255, f32 accumulation, exactly one nonzero partial per
    output element (the closure_jax trick)."""
    S = succ_val.shape[0]
    states = _get(plan, words, 0)
    oh = (states[:, None] == jnp.arange(S, dtype=jnp.int32)[None, :]
          ).astype(jnp.bfloat16)
    s2 = jnp.einsum("rs,st->rt", oh, succ_val,
                    preferred_element_type=jnp.float32)
    ok = jnp.einsum("rs,st->rt", oh, succ_ok,
                    preferred_element_type=jnp.float32)
    return s2.astype(jnp.int32), ok > 0.5


def _closure(plan, succ_val, succ_ok, words, valid, n_b, B: int,
             F: int, max_iter):
    """Fixed point of single-call linearization over the packed
    frontier: MXU expansion, packed-key dedup, sticky per-batch
    overflow, the exact pending-depth iteration bound."""
    P = plan.P

    def cond(c):
        return c[4] & (c[5] < max_iter)

    def body(c):
        ws, va, n, ovf_sticky, _, it = c
        s2_all, ok_all = _expand_surface(plan, succ_val, succ_ok, ws)
        states = _get(plan, ws, 0)
        cand_ws = [[w] for w in ws]
        cand_va = [va]
        for q in range(P):
            tq = _get(plan, ws, 1 + q)           # stored encoding
            pending = tq >= 2
            t_id = jnp.maximum(tq - 2, 0)
            s2 = jnp.take_along_axis(s2_all, t_id[:, None],
                                     axis=1)[:, 0]
            okq = jnp.take_along_axis(ok_all, t_id[:, None],
                                      axis=1)[:, 0]
            w2 = _add(plan, ws, 1 + q, -tq)      # slot -> LIN (0)
            w2 = _add(plan, w2, 0, s2 - states)
            for i in range(plan.n_words):
                cand_ws[i].append(w2[i])
            cand_va.append(va & pending & okq)
        all_ws = [jnp.concatenate(cw) for cw in cand_ws]
        all_va = jnp.concatenate(cand_va)
        ws2, va2, n2, ovf = _dedup(plan, all_ws, all_va, B, F)
        ovf2 = ovf_sticky | ovf                  # truncation is final
        # an overflowed batch can never recover a trustworthy verdict
        # (its frontier is truncated and the verdict is pinned UNKNOWN
        # by the sticky flag) — excluding it from the progress test
        # stops the loop re-running full expansion+lexsort passes on
        # the ladder rungs that exist to overflow before escalation
        changed = jnp.any((n2 > n) & ~ovf2)
        return ws2, va2, n2, ovf2, changed, it + 1

    init = body((words, valid, n_b, jnp.zeros(B, bool),
                 jnp.bool_(True), jnp.int32(0)))
    ws, va, n, ovf, _, _ = lax.while_loop(cond, body, init)
    return ws, va, n, ovf


# --- the segment step --------------------------------------------------------

def _make_step(plan, succ_val, succ_ok, B: int, F: int, K: int):
    rows = jnp.arange(B * F, dtype=jnp.int32)
    batch = rows // F

    def step(carry, seg):
        words, va, n_b, status, fail_at = carry
        inv_p, inv_t, ok_p, sidx, depth = seg    # (B,K),(B,K),(B,),(),()

        live_b = (status == VALID) & (ok_p >= 0)
        live_row = live_b[batch]

        ws = list(words)
        for k in range(K):                       # K static, unrolled
            p_row = inv_p[batch, k]
            tr_row = inv_t[batch, k]
            m = live_row & (p_row >= 0)
            col = jnp.maximum(p_row, 0)
            cur = _get_slot_dyn(plan, ws, col)
            # absolute set (slot -> tr+2), like the XLA engines
            ws = _add_slot_dyn(plan, ws, col,
                               jnp.where(m, tr_row + 2 - cur, 0))

        ws2, va2, _n2, ovf = _closure(plan, succ_val, succ_ok, ws, va,
                                      n_b, B, F, depth)
        okp_row = jnp.maximum(ok_p, 0)[batch]
        slot_ok = _get_slot_dyn(plan, ws2, okp_row)
        returned = va2 & (slot_ok == 0)          # LIN
        ws3 = _add_slot_dyn(plan, ws2, okp_row,
                            jnp.where(returned, 1, 0))   # LIN -> IDLE
        n3 = jnp.sum(returned.reshape(B, F), axis=1)

        st_new = jnp.where(ovf, UNKNOWN,
                           jnp.where(n3 == 0, INVALID, VALID)
                           ).astype(jnp.int32)
        status2 = jnp.where(live_b, st_new, status)
        fail2 = jnp.where(live_b & (st_new != VALID), sidx, fail_at)
        keep_row = live_row & (status2[batch] == VALID)
        words_o = tuple(jnp.where(keep_row, a, b)
                        for a, b in zip(ws3, words))
        va_o = jnp.where(keep_row, returned, va)
        n_o = jnp.where(live_b & (status2 == VALID), n3, n_b)
        return (words_o, va_o, n_o, status2, fail2), None

    return step


@functools.partial(jax.jit, static_argnames=("P", "n_states",
                                             "n_transitions"))
def pending_histogram(words, valid, *, P: int, n_states: int,
                      n_transitions: int):
    """Per-config pending-call counts bucketed on device (the MXU form
    of :func:`~.linear_jax.pending_histogram`): only P+1 ints ride the
    tunnel per progress tick, never the packed frontier."""
    plan = _plan_for(n_states, n_transitions, P)
    pend = jnp.zeros_like(words[0])
    for q in range(P):
        pend = pend + (_get(plan, words, 1 + q) >= 2).astype(jnp.int32)
    return jnp.bincount(pend, weights=valid.astype(jnp.int32),
                        length=P + 1)


def _plan_for(n_states: int, n_transitions: int, P: int):
    assert n_states <= S_CAP and n_transitions <= T_CAP, \
        (n_states, n_transitions, "outside the MXU table caps")
    plan = LJ.make_pack_plan(n_states, n_transitions, P)
    assert plan is not None, "no lossless PackPlan for this shape"
    return plan


def init_carry(B: int, F: int, P: int, n_states: int,
               n_transitions: int):
    """Host-side initial carry (numpy — the chunked entry takes it as
    a real input and the jit transfers it; eager device_puts here
    would cost tunnel round-trips): one empty config per batch, all
    slots IDLE."""
    plan = _plan_for(n_states, n_transitions, P)
    idle = _idle_words(plan)
    words = tuple(np.full(B * F, v, np.int32) for v in idle)
    valid = np.zeros(B * F, bool)
    valid[::F] = True
    return (words, valid, np.ones(B, np.int32),
            np.full(B, VALID, np.int32), np.full(B, -1, np.int32))


def _device_carry(plan, B: int, F: int):
    """The same initial carry built inside the trace (broadcasts, not
    baked B*F-row literal constants)."""
    idle = _idle_words(plan)
    words = tuple(jnp.full(B * F, v, jnp.int32) for v in idle)
    valid = (jnp.arange(B * F) % F) == 0
    return (words, valid, jnp.ones(B, jnp.int32),
            jnp.full(B, VALID, jnp.int32), jnp.full(B, -1, jnp.int32))


def expand_carry(carry, F_new: int):
    """Widen a GOOD chunk-boundary carry to a larger capacity — the
    in-place escalation of ``expand_seg_carry``: resume at the
    overflowing chunk instead of restarting the history. B is
    recovered from the status row; each batch's F-block pads in
    place. Status/fail reset — the carry must predate the overflow."""
    words, valid, n_b, status, fail = carry
    words = tuple(np.asarray(w) for w in words)
    valid = np.asarray(valid)
    B = np.asarray(status).shape[0]
    F_old = valid.shape[0] // B
    pad = F_new - F_old
    if pad < 0:
        raise ValueError("carry wider than target capacity")
    words = tuple(
        np.pad(w.reshape(B, F_old), ((0, 0), (0, pad))).reshape(-1)
        for w in words)
    valid = np.pad(valid.reshape(B, F_old),
                   ((0, 0), (0, pad))).reshape(-1)
    return (words, valid, np.asarray(n_b),
            np.full(B, VALID, np.int32), np.full(B, -1, np.int32))


def _scan(succ, inv_proc, inv_tr, ok_proc, depth, carry, seg_offset,
          B: int, F: int, P: int, n_states: int, n_transitions: int):
    plan = _plan_for(n_states, n_transitions, P)
    succ_val, succ_ok = _succ_planes(succ)
    S, _, K = inv_proc.shape
    segs = (inv_proc, inv_tr, ok_proc,
            seg_offset + jnp.arange(S, dtype=jnp.int32), depth)
    step = _make_step(plan, succ_val, succ_ok, B, F, K)
    carry2, _ = lax.scan(step, carry, segs)
    return carry2


@functools.partial(jax.jit, static_argnames=("B", "F", "P", "n_states",
                                             "n_transitions"))
def check_device_mxu_batch(succ, inv_proc, inv_tr, ok_proc, depth, *,
                           B: int, F: int, P: int, n_states: int,
                           n_transitions: int):
    """The batched MXU engine: B histories, packed-word frontier,
    matmul expansion. Same tensors and outputs as
    :func:`~.linear_jax.check_device_flat` — seg arrays
    inv_proc/inv_tr (S, B, K), ok_proc (S, B); returns per-batch
    ``(status[B], fail_segment[B], n_final[B])``."""
    carry = _device_carry(_plan_for(n_states, n_transitions, P), B, F)
    _, _, n_b, status, fail_at = _scan(
        succ, inv_proc, inv_tr, ok_proc, depth, carry, jnp.int32(0),
        B, F, P, n_states, n_transitions)
    return status, fail_at, n_b


@functools.partial(jax.jit, static_argnames=("F", "P", "n_states",
                                             "n_transitions"))
def check_device_mxu(succ, inv_proc, inv_tr, ok_proc, depth, *,
                     F: int, P: int, n_states: int,
                     n_transitions: int):
    """Single-history form (the driver's non-chunked path): seg arrays
    as :func:`~.linear_jax.check_device_seg` takes them; returns
    scalar ``(status, fail_segment, n_final)``."""
    S, K = inv_proc.shape
    st, fa, n = _batch_jit(
        succ, inv_proc.reshape(S, 1, K), inv_tr.reshape(S, 1, K),
        ok_proc.reshape(S, 1), depth, B=1, F=F, P=P,
        n_states=n_states, n_transitions=n_transitions)
    return st[0], fa[0], n[0]


@functools.partial(jax.jit, static_argnames=("F", "P", "n_states",
                                             "n_transitions"))
def check_device_mxu_chunk(succ, inv_proc, inv_tr, ok_proc, depth,
                           seg_offset, carry, *, F: int, P: int,
                           n_states: int, n_transitions: int):
    """One chunk of the single-history search (B=1 carry from
    :func:`init_carry` / :func:`expand_carry`); ``seg_offset`` biases
    the segment indices recorded in ``fail_at``. The driver escalates
    in place: on UNKNOWN it widens the PRE-chunk carry with
    :func:`expand_carry` and re-runs only this chunk."""
    S, K = inv_proc.shape
    return _scan(succ, inv_proc.reshape(S, 1, K),
                 inv_tr.reshape(S, 1, K), ok_proc.reshape(S, 1),
                 depth, carry, seg_offset, 1, F, P, n_states,
                 n_transitions)


@functools.partial(jax.jit, static_argnames=("F", "P", "n_states",
                                             "n_transitions"))
def check_device_mxu_megabatch(succs, inv_proc, inv_tr, ok_proc,
                               depth, seg_offset, carries, *, F: int,
                               P: int, n_states: int,
                               n_transitions: int):
    """B session-lanes of the chunk form fused into ONE program (the
    stream megabatch, docs/streaming.md "Megabatched advance"):
    ``succs``/``carries`` are B-tuples (each session owns its memo
    table and resident B=1 carry), delta tensors are lane-major
    ``(B, S, K)`` / ``(B, S)``, ``seg_offset`` is ``(B,)``. The lane
    body IS the chunk scan — vmap of its deterministic integer ops is
    elementwise-identical to B solo dispatches (padding lanes and
    dead ``ok_proc=-1`` segments select the old carry), so the fused
    carries are bit-equal to the per-session path. Returns a B-tuple
    of updated carries."""
    plan = _plan_for(n_states, n_transitions, P)
    succ_b = jnp.stack(succs)
    carry_b = jax.tree.map(lambda *xs: jnp.stack(xs), *carries)

    def lane(succ_l, ip, it, okp, dp, off, carry):
        sv, so = _succ_planes(succ_l)
        S, K = ip.shape
        segs = (ip.reshape(S, 1, K), it.reshape(S, 1, K),
                okp.reshape(S, 1),
                off + jnp.arange(S, dtype=jnp.int32), dp)
        step = _make_step(plan, sv, so, 1, F, K)
        carry2, _ = lax.scan(step, carry, segs)
        return carry2

    out = jax.vmap(lane)(succ_b, inv_proc, inv_tr, ok_proc, depth,
                         seg_offset, carry_b)
    return tuple(jax.tree.map(lambda x: x[i], out)
                 for i in range(len(carries)))


# --- counted public entries -------------------------------------------------
#
# The jitted cores above keep the public names — the compile log (and
# so the compile-surface guard) keys programs by the jit name — and the
# module attributes are rebound to thin wrappers that count DISPATCHES
# (the ``closure_jax.closure_diag`` idiom), so bench/fuzz deltas
# measure real engine dispatches, not call-site bookkeeping.

_batch_jit = check_device_mxu_batch
_single_jit = check_device_mxu
_chunk_jit = check_device_mxu_chunk
_megabatch_jit = check_device_mxu_megabatch


def check_device_mxu_batch(succ, inv_proc, inv_tr, ok_proc, depth, *,
                           B: int, F: int, P: int, n_states: int,
                           n_transitions: int):
    """Counted dispatch of the batched engine (jitted core above)."""
    global DISPATCHES
    DISPATCHES += 1
    return _batch_jit(succ, inv_proc, inv_tr, ok_proc, depth, B=B,
                      F=F, P=P, n_states=n_states,
                      n_transitions=n_transitions)


def check_device_mxu(succ, inv_proc, inv_tr, ok_proc, depth, *,
                     F: int, P: int, n_states: int,
                     n_transitions: int):
    """Counted dispatch of the single-history engine (core above)."""
    global DISPATCHES
    DISPATCHES += 1
    return _single_jit(succ, inv_proc, inv_tr, ok_proc, depth, F=F,
                       P=P, n_states=n_states,
                       n_transitions=n_transitions)


def check_device_mxu_chunk(succ, inv_proc, inv_tr, ok_proc, depth,
                           seg_offset, carry, *, F: int, P: int,
                           n_states: int, n_transitions: int):
    """Counted dispatch of the chunk engine (jitted core above)."""
    global DISPATCHES
    DISPATCHES += 1
    return _chunk_jit(succ, inv_proc, inv_tr, ok_proc, depth,
                      seg_offset, carry, F=F, P=P, n_states=n_states,
                      n_transitions=n_transitions)


def check_device_mxu_megabatch(succs, inv_proc, inv_tr, ok_proc,
                               depth, seg_offset, carries, *, F: int,
                               P: int, n_states: int,
                               n_transitions: int):
    """Counted dispatch of the fused session-lane engine (core
    above) — ONE program regardless of lane count."""
    global DISPATCHES
    DISPATCHES += 1
    return _megabatch_jit(succs, inv_proc, inv_tr, ok_proc, depth,
                          seg_offset, carries, F=F, P=P,
                          n_states=n_states,
                          n_transitions=n_transitions)


__all__ = ["CAPACITIES", "CHUNK", "DISPATCHES", "MAX_P", "MIN_P",
           "S_CAP", "T_CAP", "check_device_mxu",
           "check_device_mxu_batch", "check_device_mxu_chunk",
           "check_device_mxu_megabatch", "enabled", "expand_carry",
           "fits", "init_carry", "serves"]
