"""Batched history packing — many independent histories, one launch.

The device analog of ``jepsen.independent``'s per-key partitioning
(``independent.clj:252-300``): N short histories (e.g. one per register
key) are checked as ONE vmapped/sharded device computation. This module
owns the host-side glue: interning every history's transitions into a
single shared table, memoizing the model once over that union, and
padding per-history step streams to a common length.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Union

import numpy as np

from ..models.memo import MemoizedModel, memoize_model, transitions_of
from ..models.model import Model
from ..obs import trace as _obs
from ..ops.op import FAIL, INVOKE, OK, Op
from ..ops.packed import PackedHistory, pack_history
from ..utils import next_pow2 as _next_pow2
from . import linear_jax as LJ
from . import mxu as MXU
from . import pallas_seg as PSEG

#: device->host verdict readback per history: status int32 + fail
#: index int64 + final count int32 (transfer-byte accounting — the
#: h2d side is summed from the actual staged tensors)
_D2H_BYTES_PER_LANE = 16


def _stream_nbytes(streams) -> int:
    """Host->device bytes of a list of SegmentStreams (the streamed
    kernel's per-slice payload)."""
    return sum(int(s.inv_proc.nbytes) + int(s.inv_tr.nbytes)
               + int(s.ok_proc.nbytes) + int(s.depth.nbytes)
               for s in streams)


@dataclass
class PackedBatch:
    """N histories compiled against one shared successor table."""

    packeds: List[PackedHistory]
    memo: MemoizedModel
    kind: np.ndarray   # int32[N, n_pad]
    proc: np.ndarray   # int32[N, n_pad]
    tr: np.ndarray     # int32[N, n_pad] — ids into the shared table
    P: int             # max process count (slot width)
    remaps: List[np.ndarray] = None  # per-history local→union trans ids

    def __len__(self) -> int:
        return len(self.packeds)


def _malformed(p: PackedHistory) -> bool:
    """True when some process invokes while an earlier invocation is
    still pending. The engines disagree on such input (relative-delta
    kernel vs absolute-set XLA), so batch paths isolate these histories
    and report them ``unknown`` — the reference wraps per-key checker
    exceptions the same way (``checker.clj:54-64`` check-safe; the
    analog raise lives in ``make_segments``).

    Vectorized via the shared per-process chain machinery
    (``ops.columnar._per_process_prev``): a non-failing invoke whose
    previous same-process event is also a non-failing invoke is a
    double-pending invocation. Cached per PackedHistory — check_batch
    and its segment helpers each consult it."""
    from ..ops.columnar import _per_process_prev

    cached = getattr(p, "_malformed_cache", None)
    if cached is not None:
        return cached
    t = np.asarray(p.type)
    inv = (t == INVOKE) & ~np.asarray(p.fails)
    sel = np.flatnonzero(inv | (t == OK) | (t == FAIL))
    if not sel.size:
        out = False
    else:
        _, inv_flag, prev_inv, _ = _per_process_prev(
            np.asarray(p.process), sel, inv)
        out = bool(np.any(inv_flag & prev_inv))
    try:
        p._malformed_cache = out
    except AttributeError:
        pass                      # slotted/frozen variants: recompute
    return out


def _empty_stream():
    """A 1-segment all-padding SegmentStream (engines yield VALID)."""
    return LJ.SegmentStream(
        np.full((1, 1), -1, np.int32), np.zeros((1, 1), np.int32),
        np.full(1, -1, np.int32), np.zeros(1, np.int64),
        np.zeros(1, np.int32))


def _segments_of(p, s_pad: int = 0, k_pad: int = 0):
    """``make_segments`` with a fast path: an exact stream cached on
    the PackedHistory (the serving layer's admission pass computes one
    per request to derive the shape bucket) is padded to the floors
    with cheap numpy pads instead of re-running the O(total-ops) host
    loop. Pad values match ``make_segments``' (dead segments/invokes
    are ``-1`` procs)."""
    segs = getattr(p, "_segments_exact", None)
    if segs is None:
        return LJ.make_segments(p, s_pad=s_pad or None,
                                k_pad=k_pad or None)
    S, K = segs.ok_proc.shape[0], segs.inv_proc.shape[1]
    ds, dk = max(s_pad - S, 0), max(k_pad - K, 0)
    if not ds and not dk:
        return segs
    return LJ.SegmentStream(
        np.pad(segs.inv_proc, ((0, ds), (0, dk)), constant_values=-1),
        np.pad(segs.inv_tr, ((0, ds), (0, dk))),
        np.pad(segs.ok_proc, (0, ds), constant_values=-1),
        np.pad(segs.seg_index, (0, ds)),
        np.pad(segs.depth, (0, ds)))


@_obs.traced("batch.pack")
def pack_batch(histories: Sequence[Union[Sequence[Op], PackedHistory]],
               model: Model,
               max_states: int = 1 << 20,
               n_pad: int = 0,
               build_streams: bool = True) -> PackedBatch:
    """Pack histories for :func:`~.linear_jax.check_device_batch` /
    :func:`~.linear_jax.check_device_keys_sharded`.

    Transition ids are re-interned into one union table so all histories
    share a single memoized model; the BFS depth bound is the max
    invocation count over the batch (exact per history — a history can't
    linearize more ops than it invoked; see ``memoize_model``).

    ``build_streams=False`` skips the dense per-op (N, n_pad) stream
    tensors that only the vmap fallback uses — at pod-scale batches
    (4096 × 2k ops) they cost hundreds of host MB the
    stream/keys/flat engines never read. Such a batch checks with
    ``engine="stream"``/``"keys"``/``"flat"``, and kernel UNKNOWNs
    still escalate through keys/flat (they re-segment from
    ``packeds``); only a vmap-path escalation is unavailable (those
    lanes then stay ``unknown``).
    """
    packeds = [h if isinstance(h, PackedHistory) else pack_history(list(h))
               for h in histories]
    union: List[tuple] = []
    ids = {}
    remaps = []
    for p in packeds:
        local = []
        for t in transitions_of(p):
            if t not in ids:
                ids[t] = len(union)
                union.append(t)
            local.append(ids[t])
        remaps.append(np.asarray(local, np.int32))
    n_inv = max((int(((p.type == INVOKE) & ~p.fails).sum())
                 for p in packeds), default=0)
    mm = memoize_model(model, union, max_states=max_states, max_depth=n_inv)

    P = max((len(p.process_table) for p in packeds), default=1)
    if not build_streams:
        empty = np.zeros((len(packeds), 0), np.int32)
        return PackedBatch(packeds=packeds, memo=mm, kind=empty,
                           proc=empty, tr=empty, P=P, remaps=remaps)
    n_pad = max(n_pad, _next_pow2(max((len(p) for p in packeds), default=1)))
    kinds, procs, trs = [], [], []
    for p, remap in zip(packeds, remaps):
        s = LJ.make_stream(p, n_pad=n_pad)
        kind = np.asarray(s.kind)
        tr = np.asarray(s.tr).copy()
        mask = kind == LJ.K_INVOKE
        if remap.size:
            tr[mask] = remap[tr[mask]]
        kinds.append(kind)
        procs.append(np.asarray(s.proc))
        trs.append(tr)
    return PackedBatch(packeds=packeds, memo=mm,
                       kind=np.stack(kinds), proc=np.stack(procs),
                       tr=np.stack(trs), P=P, remaps=remaps)


def pack_batch_masked(parent: PackedHistory, masks: Sequence,
                      memo: MemoizedModel) -> PackedBatch:
    """The shrink fast path: B sub-history candidates of ONE packed
    parent as a :class:`PackedBatch` WITHOUT re-packing or
    re-interning. Every candidate is a pair-closed row slice
    (:func:`~comdb2_tpu.ops.columnar.subset_packed`) whose id tables
    ARE the parent's, so the union transition table is the parent's
    and every remap is the identity — the O(ops·B) union pass of
    :func:`pack_batch` disappears, which is what lets a ddmin round
    test dozens of candidate sub-histories per dispatch.

    ``memo`` must be memoized over the parent's transitions with a
    depth bound >= the parent's invoke count (a candidate can't
    linearize more ops than the parent invoked, so one memo serves
    every round). Packed with the ``build_streams=False`` layout —
    candidates check through the stream/keys/flat engines."""
    from ..ops.columnar import subset_packed

    packeds = [subset_packed(parent, m) for m in masks]
    ident = np.arange(len(parent.transition_table), dtype=np.int32)
    empty = np.zeros((len(packeds), 0), np.int32)
    return PackedBatch(packeds=packeds, memo=memo, kind=empty,
                       proc=empty, tr=empty,
                       P=max(len(parent.process_table), 1),
                       remaps=[ident] * len(packeds))


@dataclass
class SegmentBatch:
    """Per-ok segment tensors for the flat engine: (S, B, K) layouts."""

    inv_proc: np.ndarray   # int32[S, B, K]
    inv_tr: np.ndarray     # int32[S, B, K] — union transition ids
    ok_proc: np.ndarray    # int32[S, B]
    seg_index: np.ndarray  # int64[B, S] — segment → history index
    depth: np.ndarray      # int32[S] — max pending depth across lanes


@_obs.traced("batch.segments")
def segment_batch(batch: PackedBatch,
                  streams: Optional[list] = None,
                  s_pad: int = 0, k_pad: int = 0) -> SegmentBatch:
    """Compile each history's per-ok segments (union transition ids),
    padded to a common (S, K). Malformed histories (double-pending
    process) get an empty stream; ``check_batch`` reports them
    ``unknown``. ``streams``: per-history SegmentStreams already
    union-remapped (and possibly slot-renamed — a pure relabeling the
    XLA engines accept unchanged), e.g. from ``_stream_segments`` when
    the kernel path rejected the batch — reusing them skips a second
    O(total-ops) host segment pass. ``s_pad``/``k_pad`` are FLOORS on
    the padded segment axes: a serving layer that buckets many batches
    into a fixed (S, K) shape pins the compiled program once instead
    of recompiling per batch (the actual maxima still win when they
    exceed the floor — padding never truncates)."""
    prebuilt = streams is not None
    segss = streams if prebuilt else [
        _empty_stream() if _malformed(p) else _segments_of(p)
        for p in batch.packeds]
    S = max(_next_pow2(max((s.ok_proc.shape[0] for s in segss),
                           default=1)), s_pad)
    K = max(_next_pow2(max((s.inv_proc.shape[1] for s in segss),
                           default=1), 2), k_pad)
    ips, its, ops, idxs, deps = [], [], [], [], []
    for remap, s in zip(batch.remaps, segss):
        ds, dk = S - s.ok_proc.shape[0], K - s.inv_proc.shape[1]
        inv_proc = np.pad(s.inv_proc, ((0, ds), (0, dk)),
                          constant_values=-1)
        tr = np.pad(s.inv_tr, ((0, ds), (0, dk)))
        mask = inv_proc >= 0
        if remap.size and not prebuilt:
            tr[mask] = remap[tr[mask]]
        ips.append(inv_proc)
        its.append(tr)
        ops.append(np.pad(s.ok_proc, (0, ds), constant_values=-1))
        idxs.append(np.pad(s.seg_index, (0, ds)))
        deps.append(np.pad(s.depth, (0, ds)))
    return SegmentBatch(
        inv_proc=np.stack(ips, axis=1),    # (S, B, K)
        inv_tr=np.stack(its, axis=1),
        ok_proc=np.stack(ops, axis=1),     # (S, B)
        seg_index=np.stack(idxs, axis=0),  # (B, S)
        depth=np.max(np.stack(deps, axis=0), axis=0),   # (S,)
    )


@_obs.traced("batch.remap")
def _build_streams(batch: PackedBatch, indices, s_pad: int = 0,
                   k_pad: int = 0):
    """Union-remapped, slot-renamed SegmentStreams for a SUBSET of the
    batch — the unit of the pipelined dispatch (``_stream_stage``
    builds slice i+1 on the host while the device runs slice i).
    Returns ``(streams, p_eff)``; slot renaming runs the batched
    :func:`~.linear_jax.remap_slots_batch` over every history without
    an admission-time cache (``COMDB2_TPU_LEGACY_PACK=1`` routes
    through per-history ``remap_slots``)."""
    from ..ops.packed import legacy_pack_enabled

    indices = list(indices)
    out: list = [None] * len(indices)
    p_eff = 1
    need: list = []
    raw: list = []
    for j, i in enumerate(indices):
        p = batch.packeds[i]
        malformed = _malformed(p)
        s = (_empty_stream() if malformed
             else _segments_of(p, s_pad=s_pad, k_pad=k_pad))
        remap = np.asarray(batch.remaps[i], np.int32)
        if remap.size:
            inv_tr = np.where(s.inv_proc >= 0, remap[s.inv_tr],
                              0).astype(np.int32)
        else:  # no successful invokes anywhere: nothing to remap
            inv_tr = np.zeros_like(s.inv_tr, np.int32)
        cached_remap = (None if malformed
                        else getattr(p, "_remap_cache", None))
        if cached_remap is not None:
            # slot renaming depends on (inv_proc, ok_proc) only, so an
            # admission-time pass (bucket_for) is reusable verbatim —
            # just pad its exact-shape proc arrays to this stream's
            rproc, rok, pe = cached_remap
            ds = s.ok_proc.shape[0] - rok.shape[0]
            dk = s.inv_proc.shape[1] - rproc.shape[1]
            out[j] = LJ.SegmentStream(
                np.pad(rproc, ((0, ds), (0, dk)), constant_values=-1),
                inv_tr,
                np.pad(rok, (0, ds), constant_values=-1),
                s.seg_index, s.depth)
            p_eff = max(p_eff, pe)
        else:
            need.append(j)
            raw.append(LJ.SegmentStream(
                s.inv_proc, inv_tr, s.ok_proc, s.seg_index, s.depth))
    if need:
        if legacy_pack_enabled():
            renamed = [LJ.remap_slots(r) for r in raw]
            streams2 = [r[0] for r in renamed]
            pes = [r[1] for r in renamed]
        else:
            streams2, pes = LJ.remap_slots_batch(raw)
        for j, s2, pe in zip(need, streams2, pes):
            out[j] = s2
            p_eff = max(p_eff, pe)
    return out, p_eff


def _stream_segments(batch: PackedBatch, s_pad: int = 0,
                     k_pad: int = 0):
    """Per-history SegmentStreams with transition ids remapped into the
    union table (the streamed kernel shares ONE table) and process ids
    renamed to minimal reusable slots (:func:`~.linear_jax.remap_slots_batch`
    — the kernel's slot axis then scales with each history's max
    concurrent open calls, not its process count). Malformed histories
    get an empty stream; ``check_batch`` reports them ``unknown``.
    Returns ``(streams, P_eff)`` with ``P_eff`` the max effective slot
    count over the batch (the spec the ONE shared kernel compiles for).
    ``s_pad``/``k_pad`` floor each stream's padded (S, K) like
    :func:`segment_batch`'s — bucketed serving keeps the streamed
    kernel's chunk count shape-stable across batches.
    Cached on the batch (keyed by the pads): the pass is O(total ops)
    of host work, and repeat checks of the same PackedBatch (capacity
    escalation, timed bench runs) would otherwise pay it every call.
    """
    cached = getattr(batch, "_stream_seg_cache", None)
    if cached is not None and cached[0] == (s_pad, k_pad):
        return cached[1]
    out, p_eff = _build_streams(batch, range(len(batch.packeds)),
                                s_pad=s_pad, k_pad=k_pad)
    batch._stream_seg_cache = ((s_pad, k_pad), (out, p_eff))
    return out, p_eff


#: histories per pipelined dispatch slice: small enough that slice
#: i+1's host pack overlaps slice i's device run on big batches, big
#: enough to amortize per-dispatch overhead (the 4096x bench packs 8
#: slices; a service-sized batch stays one slice and overlaps across
#: BUCKETS via the tick loop's double buffer instead)
PIPELINE_B = 512


def _kernel_P(p_eff: int) -> int:
    """The slot width the streamed kernel compiles for: even-bucketed
    (halves the spec space; matches ``linear._analyze_device``) while
    the fast (8,128) tier still serves it — P_eff 7 must NOT round to
    8 and fall off the ~45%-slower (16,128) tier."""
    p2 = max(p_eff, 1)
    p2 += p2 & 1
    return p2 if p2 <= PSEG.ROWS - 1 else max(p_eff, 1)


def _slice_spec(streams, sizes, p_eff_pad):
    """Kernel spec for ONE dispatch slice, derived from the renamed
    streams themselves (every allocated slot appears in the arrays, so
    max slot id + 1 IS the slice's effective P). Both the cold
    pipelined pass and the cached rerun derive specs through this one
    function — same slices, same streams, same compiled programs, so
    a warm rerun never triggers a fresh Mosaic compile."""
    pe, K = 0, 1
    for s in streams:
        K = max(K, s.inv_proc.shape[1])
        if s.inv_proc.size:
            pe = max(pe, int(s.inv_proc.max()) + 1)
        if s.ok_proc.size:
            pe = max(pe, int(s.ok_proc.max()) + 1)
    P = _kernel_P(max(pe, p_eff_pad))
    return PSEG.spec_for(sizes["n_states"], sizes["n_transitions"],
                         P, K + (K & 1))


def _slice_with_sentinels(streams, start, end, B):
    """Slice ``streams`` (the B real histories' renamed streams) for
    ``[start, end)``, appending sentinel empty streams for pad indices
    >= B. Sentinels are all-padding (every engine yields VALID on
    them) and are sliced off before any verdict/metric surfaces."""
    real = streams[start:min(end, B)]
    return real + [_empty_stream()] * ((end - start) - len(real))


def _stream_stage(batch: PackedBatch, succ, sizes, s_pad, k_pad,
                  p_eff_pad, mesh, B_pad: Optional[int] = None,
                  batch_axis: str = "batch"):
    """Stage the streamed-kernel dispatches WITHOUT blocking on
    results. On a cold batch the host segment/remap/pack pass runs
    slice-by-slice, dispatching each slice before building the next —
    JAX dispatch is async, so slice i's device run overlaps slice
    i+1's host pack (double-buffered staging; this container has one
    CPU, the overlap is host-compute vs device-compute). On a batch
    with cached streams (timed bench reruns, capacity escalation) the
    slices dispatch back-to-back from the cache.

    With a >1-device mesh the slices ride the first-class shard_map
    path: each slice is ONE fused ``stream_dispatch_sharded`` whose
    per-shard body is the production kernel scan — the host packs
    slice i+1's tensors while ALL shards run slice i. ``B_pad`` is the
    sentinel-padded batch width (D | B_pad); sentinel histories are
    excluded from verdicts and metrics by the caller's ``[:B]`` slice.

    Returns ``(pending, segs_list)``: ``pending`` is a list of
    ``(handle, start, end)`` entries for :func:`_stream_collect`
    (handle = ``(res, starts)`` single-device or
    ``(res, starts, D)`` sharded), or None when the shape can't run
    fused — ``segs_list`` is still complete then, so the XLA engines
    reuse the streams (`segment_batch(streams=...)`)."""
    B = len(batch)
    D = int(mesh.shape[batch_axis]) if mesh is not None else 0
    cap = min(PSEG.MAX_STREAM_B, PIPELINE_B)
    if D > 1:
        B_pad = B_pad if B_pad is not None else max(_next_pow2(B), D)
        plan = [(s, e, -1) for s, e in
                PSEG.plan_shard_slices(B_pad, D, max_stream_b=cap)]
        devs = [None]
        ndev = 0
    else:
        devices = (list(mesh.devices.flat) if mesh is not None
                   else None)
        ndev = len(devices) if devices else 0
        devs = devices if devices else [None]
        plan = PSEG.plan_stream_slices(B, ndev, max_stream_b=cap)
    cached = getattr(batch, "_stream_seg_cache", None)
    cached = cached[1] if cached is not None \
        and cached[0] == (s_pad, k_pad) else None

    def dispatch(streams, start, end):
        spec = _slice_spec(streams, sizes, p_eff_pad)
        if spec is None:
            return None
        with _obs.span("batch.dispatch", engine="stream",
                       start=start, end=end):
            if D > 1:
                res, starts = PSEG.stream_dispatch_sharded(
                    succ, streams, spec, sizes["n_states"],
                    sizes["n_transitions"], mesh,
                    batch_axis=batch_axis)
                return (res, starts, D)
            dix = plan_dix.get((start, end), 0)
            return PSEG.stream_dispatch(
                succ, streams, spec, sizes["n_states"],
                sizes["n_transitions"], devs[dix] if ndev else None)

    plan_dix = {(s, e): d for s, e, d in plan}
    pending: list = []
    if cached is not None:
        segs_list, _ = cached
        for start, end, _dix in plan:
            handle = dispatch(
                _slice_with_sentinels(segs_list, start, end, B),
                start, end)
            if handle is None:
                return None, segs_list
            pending.append((handle, start, end))
        return pending, segs_list
    all_streams: list = []
    p_eff_all = 1
    dead = False
    for start, end, _dix in plan:
        streams, pe = _build_streams(batch,
                                     range(start, min(end, B)),
                                     s_pad=s_pad, k_pad=k_pad)
        all_streams.extend(streams)
        p_eff_all = max(p_eff_all, pe)
        if dead:
            continue            # finish building the cacheable streams
        handle = dispatch(
            streams + [_empty_stream()] * ((end - start)
                                           - len(streams)),
            start, end)
        if handle is None:
            dead = True
            pending = []
            continue
        pending.append((handle, start, end))
    batch._stream_seg_cache = ((s_pad, k_pad),
                               (all_streams, p_eff_all))
    if dead:
        return None, all_streams
    return pending, all_streams


@_obs.traced("batch.collect")
def _stream_collect(pending, B):
    """Block on the staged dispatches in order and merge the
    per-slice verdicts (each ``np.asarray`` waits on that slice's
    device only). ``B`` is the PADDED batch width when the slices were
    sharded — the caller slices sentinel verdicts off before anything
    user-visible. A readback failure clears the donated-carry pool:
    the failed scan's carries were recycled at dispatch time and must
    not seed the next same-shape dispatch."""
    rs: list = [None] * B
    try:
        for handle, start, end in pending:
            if len(handle) == 3:      # sharded: (res, starts, D)
                res, starts, D = handle
                out = PSEG.merge_stream_shards(np.asarray(res),
                                               starts, end - start, D)
            else:
                res, starts = handle
                out = PSEG.merge_stream_slice(np.asarray(res), starts,
                                              end - start)
            rs[start:end] = out
    except Exception:
        PSEG.clear_carry_pool()
        raise
    return rs


def check_batch(batch: PackedBatch, F: int = 256, mesh=None,
                batch_axis: str = "batch", engine: str = "auto",
                info: Optional[dict] = None, s_pad: int = 0,
                k_pad: int = 0, n_states_pad: int = 0,
                n_transitions_pad: int = 0, p_eff_pad: int = 0):
    """Run the batched device search (see :func:`check_batch_async`);
    malformed histories (double-pending process) come back ``unknown``
    instead of poisoning the batch or diverging between engines.

    The ``*_pad`` arguments floor the padded segment axes and the
    declared memo-table sizes — a serving layer that buckets traffic
    (:mod:`comdb2_tpu.service`) pins every tensor shape and field
    width so all batches in a bucket share ONE compiled program.
    Oversizing is sound: states/transitions are ids below the real
    counts, ``pad_succ`` widens the table to match, and padding
    segments are no-ops to every engine."""
    return check_batch_async(
        batch, F=F, mesh=mesh, batch_axis=batch_axis, engine=engine,
        info=info, s_pad=s_pad, k_pad=k_pad,
        n_states_pad=n_states_pad,
        n_transitions_pad=n_transitions_pad, p_eff_pad=p_eff_pad)()


def check_batch_async(batch: PackedBatch, F: int = 256, mesh=None,
                      batch_axis: str = "batch", engine: str = "auto",
                      info: Optional[dict] = None, s_pad: int = 0,
                      k_pad: int = 0, n_states_pad: int = 0,
                      n_transitions_pad: int = 0, p_eff_pad: int = 0):
    """Stage the batched device search and return a zero-argument
    ``finalize()`` producing ``(status[N], fail_at[N], n_final[N])``
    NumPy arrays — fail_at in history-index terms.

    Between stage and finalize the DEVICE work proceeds asynchronously
    (JAX dispatch is async; only the finalize readback blocks), so a
    caller can pack the NEXT batch's host tensors while this one runs
    — the service tick loop double-buffers exactly this way. The big-
    batch stream path additionally pipelines within one call: the host
    segments/packs dispatch slice i+1 while the device runs slice i.

    engine: "stream" runs all histories through the fused Pallas
    kernel as a sliced sequence of streamed scans (fastest on TPU —
    measured ~6x the keys engine); "keys" keeps the frontier as packed
    int32 key pairs — config mutation is bit arithmetic, dedup one
    sort; "flat" folds all frontiers into one explicit tensor with the
    batch id as the top sort key; "vmap" is the per-lane fallback;
    "auto" picks the best available whose budget fits.

    info: optional dict — receives {"engine": name} for the path
    actually executed (observability; tests and bench assert on it);
    populated at stage time.
    """
    fin = _check_batch_begin(
        batch, F=F, mesh=mesh, batch_axis=batch_axis, engine=engine,
        info=info, s_pad=s_pad, k_pad=k_pad,
        n_states_pad=n_states_pad,
        n_transitions_pad=n_transitions_pad, p_eff_pad=p_eff_pad)

    def finalize():
        status, fail_at, n_final = fin()
        bad = [i for i, p in enumerate(batch.packeds)
               if _malformed(p)]
        if bad:
            status = np.array(status, np.int32)
            fail_at = np.array(fail_at, np.int64)
            n_final = np.array(n_final, np.int32)
            status[bad] = LJ.UNKNOWN
            fail_at[bad] = -1
            n_final[bad] = 0
        return status, fail_at, n_final

    return finalize


def _check_batch_begin(batch: PackedBatch, F: int, mesh,
                       batch_axis: str, engine: str,
                       info: Optional[dict], s_pad: int, k_pad: int,
                       n_states_pad: int, n_transitions_pad: int,
                       p_eff_pad: int):
    """Engine selection + host packing + async device dispatch;
    returns the finalize closure (readback, fail-index decode, kernel
    overflow escalation)."""
    # declared table sizes may be floored (bucketed) above the real
    # counts: ids stay below the real counts, so widening the fields
    # and the padded table is a pure relabeling of the key layout
    n_states = max(batch.memo.n_states, n_states_pad)
    n_transitions = max(batch.memo.n_transitions, n_transitions_pad)
    succ = LJ.pad_succ(batch.memo.succ, _next_pow2(n_states),
                       _next_pow2(n_transitions))
    P = _next_pow2(batch.P, 2)
    B = len(batch)
    sizes = {"n_states": n_states, "n_transitions": n_transitions}
    D = int(mesh.shape[batch_axis]) if mesh is not None else 1
    if D > 1 and (D & (D - 1)):
        raise ValueError(
            f"mesh axis {batch_axis!r} must be a power of two (got "
            f"{D}): per-shard shapes are B_pad/D and must stay inside "
            "the pow2 program inventory (PROGRAMS.md mesh_D ladder)")
    # sharded engines need D | B; the pad stays pow2 so per-shard
    # shapes remain bucketed (B_pad/D is the shape each shard
    # compiles for — the shard-extended PROGRAMS.md inventory). Pad
    # lanes are SENTINEL histories, excluded from every verdict and
    # metric by the [:B] slice — info records the factor so callers
    # can audit that dead work never surfaces in per-batch totals.
    B_pad = B
    if D > 1:
        B_pad = max(_next_pow2(B), D)
    if info is not None:
        info["batch"] = {"b": B, "b_pad": B_pad, "pad": B_pad - B,
                         "shards": max(D, 1)}

    def note(name: str) -> None:
        if info is not None:
            info["engine"] = name

    def pick_xla_engine(b=None):
        # under a mesh each device sees B_pad/D histories — the fits
        # budgets apply to the per-shard batch. ``b`` overrides the
        # batch size (escalated sub-batches are far smaller than the
        # full batch, so their budgets fit where the batch's don't).
        # Wide P goes to the MXU frontier engine first: past the
        # crossover (mxu.MIN_P) its matmul expansion is P-independent
        # while the keys/flat per-iteration cost scales with P — and
        # most wide-P shapes don't fit the 62-bit key budgets at all
        if b is None:
            b = B_pad // D if D > 1 else B
        if MXU.serves(sizes["n_states"], sizes["n_transitions"], P):
            return "mxu"
        if LJ.KeyLayout(b, sizes["n_states"],
                        sizes["n_transitions"], P).fits:
            return "keys"
        if LJ.flat_pack_bits(b, sizes["n_states"],
                             sizes["n_transitions"], P)[3]:
            return "flat"
        return "vmap"

    def stream_fits():
        # gate BEFORE the O(total-ops) segment pass so a shape that
        # can never run fused (table too big, K too wide — checked at
        # P=1, the minimum) skips the host work. P itself is NOT final
        # here: slot renaming in _stream_segments can shrink it below
        # the tier bound, so P-ineligible shapes still try the pass
        # when everything else fits.
        return (PSEG.spec_for(sizes["n_states"],
                              sizes["n_transitions"], 1, 8)
                is not None and PSEG.available())

    if engine == "auto":
        engine = "stream" if stream_fits() else pick_xla_engine()
    prebuilt_streams = None      # reused by keys/flat when the kernel
    if engine == "stream":       # path rejects an already-built batch
        pending = None
        if stream_fits():
            # the padded succ, not the raw memo table: the kernel's
            # flat-table stride is the declared n_transitions, which
            # may be floored above the real column count; p_eff_pad
            # floors the slot count so a serving layer bucketing by
            # effective concurrency compiles one kernel per bucket
            pending, segs_list = _stream_stage(
                batch, succ, sizes, s_pad, k_pad, p_eff_pad, mesh,
                B_pad=B_pad, batch_axis=batch_axis)
            prebuilt_streams = segs_list
        if pending is not None:
            # label by the route actually taken: a 1-device mesh rides
            # the plain single-device stream dispatch, not shard_map
            note("stream" if D <= 1 else "stream-sharded")
            if info is not None:
                # per-dispatch tunnel accounting (docs/observability
                # .md): the ~25 MB/s link makes bytes a first-class
                # cost — summed from the actual staged tensors
                info["transfer_bytes"] = {
                    "h2d": int(succ.nbytes)
                    + _stream_nbytes(segs_list),
                    "d2h": B * _D2H_BYTES_PER_LANE}

            @_obs.traced("batch.finalize")
            def finalize_stream():
                # sentinel-pad verdicts (always VALID) are sliced off
                # HERE, before escalation/metrics — a pad history can
                # never surface as a verdict, counterexample, or
                # shrink candidate
                rs = _stream_collect(pending,
                                     B_pad if D > 1 else B)[:B]
                status = np.array([r[0] for r in rs], np.int32)
                fail_at = np.array([
                    segs_list[b].seg_index[rs[b][1]] if rs[b][1] >= 0
                    else -1 for b in range(B)], np.int64)
                n_final = np.array([r[2] for r in rs], np.int32)
                # the kernel's frontier is fixed at 128: histories
                # that overflowed it get their requested budget F
                # through the XLA engines instead of surfacing
                # spurious UNKNOWNs
                unk = escalation_indices(status, F, PSEG.F)
                # the sub-batch is sized by the overflow count, so
                # pick the escalation engine from THAT size — at
                # pod-scale batches the full-B budgets never fit while
                # a handful of overflowed histories easily do
                sub_b = (-(-int(unk.size) // D) if D > 1
                         else int(unk.size))
                esc_engine = pick_xla_engine(max(sub_b, 1))
                if unk.size and batch.kind.shape[1] == 0 \
                        and esc_engine == "vmap":
                    # packed with build_streams=False and only the
                    # vmap path could take the overflow: those
                    # histories must stay unknown — record that
                    # escalation was REQUESTED but impossible so
                    # callers can tell this apart from "no overflow"
                    # (ADVICE r4)
                    if info is not None:
                        info["escalated"] = {"engine": None,
                                             "count": int(unk.size)}
                    unk = np.empty(0, np.int64)
                if unk.size:
                    sub = PackedBatch(
                        packeds=[batch.packeds[i] for i in unk],
                        memo=batch.memo,
                        kind=batch.kind[unk], proc=batch.proc[unk],
                        tr=batch.tr[unk], P=batch.P,
                        remaps=[batch.remaps[i] for i in unk])
                    sub_info: dict = {}
                    st2, fa2, n2 = check_batch(
                        sub, F=F, mesh=mesh, engine=esc_engine,
                        info=sub_info, s_pad=s_pad, k_pad=k_pad,
                        n_states_pad=n_states_pad,
                        n_transitions_pad=n_transitions_pad,
                        p_eff_pad=p_eff_pad)
                    status2, fail_at2, n_final2 = merge_escalation(
                        status, fail_at, n_final, unk, st2, fa2, n2)
                    if info is not None:  # the label must not claim
                        info["escalated"] = {   # the kernel checked
                            "engine": sub_info.get("engine"),  # all
                            "count": int(unk.size)}
                    return status2, fail_at2, n_final2
                return status, fail_at, n_final

            return finalize_stream
        engine = pick_xla_engine()
    if engine == "mxu":
        # the MXU frontier engine's batched form: packed-word frontier,
        # matmul expansion, exact packed-key dedup — same (S, B, K)
        # segment tensors as keys/flat. No shard_map form yet: a mesh
        # caller runs one device and says so (like the vmap fallback)
        assert MXU.fits(sizes["n_states"], sizes["n_transitions"],
                        P), \
            "mxu engine requires the table caps and a lossless " \
            "PackPlan (see mxu.fits)"
        note("mxu")
        if mesh is not None and info is not None:
            info["mesh_dropped"] = True
        # bucket the caller's F to the engine's CAPACITIES ladder —
        # the PROGRAMS.md mxu-frontier site declares F as a closed
        # enum, and F is jit-static but invisible in the input avals,
        # so per-caller F churn would compile unseen extra programs
        # (check_batch's default F=256 rounds up to the 1024 rung)
        F_mxu = MXU.bucket_F(F)
        if info is not None:
            info["frontier_capacity"] = F_mxu
        sb = segment_batch(batch, streams=prebuilt_streams,
                           s_pad=s_pad, k_pad=k_pad)
        if info is not None:
            info["transfer_bytes"] = {
                "h2d": int(succ.nbytes) + int(sb.inv_proc.nbytes)
                + int(sb.inv_tr.nbytes) + int(sb.ok_proc.nbytes)
                + int(sb.depth.nbytes),
                "d2h": B * _D2H_BYTES_PER_LANE}
        status_d, fail_seg_d, n_final_d = MXU.check_device_mxu_batch(
            succ, sb.inv_proc, sb.inv_tr, sb.ok_proc, sb.depth,
            B=B, F=F_mxu, P=P, **sizes)

        @_obs.traced("batch.finalize")
        def finalize_mxu():
            status = np.asarray(status_d)[:B]
            fail_seg = np.asarray(fail_seg_d)[:B]
            fail_at = np.array([
                sb.seg_index[b, fail_seg[b]] if fail_seg[b] >= 0
                else -1 for b in range(B)], np.int64)
            return status, fail_at, np.asarray(n_final_d)[:B]

        return finalize_mxu
    if engine in ("keys", "flat"):
        note(engine if mesh is None else engine + "-sharded")
        sb = segment_batch(batch, streams=prebuilt_streams,
                           s_pad=s_pad, k_pad=k_pad)
        if info is not None:
            info["transfer_bytes"] = {
                "h2d": int(succ.nbytes) + int(sb.inv_proc.nbytes)
                + int(sb.inv_tr.nbytes) + int(sb.ok_proc.nbytes)
                + int(sb.depth.nbytes),
                "d2h": B * _D2H_BYTES_PER_LANE}
        if mesh is not None:
            ip, it, op_, dp = _pad_batch_axis(sb, B_pad - B)
            status_d, fail_seg_d, n_final_d = \
                LJ.check_device_keys_sharded(
                    mesh, succ, ip, it, op_, dp, B=B_pad, F=F, P=P,
                    batch_axis=batch_axis, engine=engine, **sizes)
        else:
            fn = (LJ.check_device_keys if engine == "keys"
                  else LJ.check_device_flat)
            status_d, fail_seg_d, n_final_d = fn(
                succ, sb.inv_proc, sb.inv_tr, sb.ok_proc, sb.depth,
                B=B, F=F, P=P, **sizes)

        @_obs.traced("batch.finalize")
        def finalize_xla():
            status = np.asarray(status_d)[:B]
            fail_seg = np.asarray(fail_seg_d)[:B]
            fail_at = np.array([
                sb.seg_index[b, fail_seg[b]] if fail_seg[b] >= 0
                else -1 for b in range(B)], np.int64)
            return status, fail_at, np.asarray(n_final_d)[:B]

        return finalize_xla
    if batch.kind.shape[1] == 0:
        raise ValueError(
            "batch was packed with build_streams=False; the vmap path "
            "needs the dense step streams")
    # vmap is a SINGLE-DEVICE last resort only. The vmap-sharded route
    # (linear_jax.check_sharded) was removed from the production path:
    # vmap lowers ~20x worse per lane, so sharding it scales a
    # pessimized program — check_sharded survives as a test oracle and
    # the vmap-sharded-oracle analysis rule keeps serving traffic off
    # it. A mesh caller landing here runs one device and says so.
    note("vmap")
    if mesh is not None and info is not None:
        info["mesh_dropped"] = True
    if info is not None:
        info["transfer_bytes"] = {
            "h2d": int(succ.nbytes) + int(batch.kind.nbytes)
            + int(batch.proc.nbytes) + int(batch.tr.nbytes),
            "d2h": B * _D2H_BYTES_PER_LANE}
    out = LJ.check_device_batch(succ, batch.kind, batch.proc,
                                batch.tr, F=F, P=P, **sizes)
    return _obs.traced("batch.finalize")(
        lambda: tuple(np.asarray(x) for x in out))


def escalation_indices(status: np.ndarray, F: int,
                       kernel_f: int) -> np.ndarray:
    """Pure: which batch indices must re-run through the XLA engines.
    Only UNKNOWN verdicts escalate, and only when the caller's
    requested frontier budget actually EXCEEDS the fused kernel's
    fixed one — re-running at the same budget could only reproduce the
    overflow."""
    if F <= kernel_f:
        return np.empty(0, np.int64)
    return np.flatnonzero(np.asarray(status) == LJ.UNKNOWN)


def merge_escalation(status, fail_at, n_final, idx, st2, fa2, n2):
    """Pure: fold the escalated sub-batch's verdicts back into the
    full-batch arrays at ``idx`` (unit-testable on CPU — round-2 Weak
    #2)."""
    status = np.array(status, np.int32)
    fail_at = np.array(fail_at, np.int64)
    n_final = np.array(n_final, np.int32)
    status[idx] = st2
    fail_at[idx] = fa2
    n_final[idx] = n2
    return status, fail_at, n_final


def _pad_batch_axis(sb: SegmentBatch, extra: int):
    """Widen the segment tensors' batch axis with ``extra`` dead
    histories (all segments padding) so the sharded engines' B divides
    the mesh axis; dead histories come back VALID and are sliced off."""
    if extra == 0:
        return sb.inv_proc, sb.inv_tr, sb.ok_proc, sb.depth
    ip = np.pad(sb.inv_proc, ((0, 0), (0, extra), (0, 0)),
                constant_values=-1)
    it = np.pad(sb.inv_tr, ((0, 0), (0, extra), (0, 0)))
    op_ = np.pad(sb.ok_proc, ((0, 0), (0, extra)), constant_values=-1)
    return ip, it, op_, sb.depth
