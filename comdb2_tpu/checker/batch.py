"""Batched history packing — many independent histories, one launch.

The device analog of ``jepsen.independent``'s per-key partitioning
(``independent.clj:252-300``): N short histories (e.g. one per register
key) are checked as ONE vmapped/sharded device computation. This module
owns the host-side glue: interning every history's transitions into a
single shared table, memoizing the model once over that union, and
padding per-history step streams to a common length.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Union

import numpy as np

from ..models.memo import MemoizedModel, memoize_model, transitions_of
from ..models.model import Model
from ..ops.op import INVOKE, Op
from ..ops.packed import PackedHistory, pack_history
from ..utils import next_pow2 as _next_pow2
from . import linear_jax as LJ
from . import pallas_seg as PSEG


@dataclass
class PackedBatch:
    """N histories compiled against one shared successor table."""

    packeds: List[PackedHistory]
    memo: MemoizedModel
    kind: np.ndarray   # int32[N, n_pad]
    proc: np.ndarray   # int32[N, n_pad]
    tr: np.ndarray     # int32[N, n_pad] — ids into the shared table
    P: int             # max process count (slot width)
    remaps: List[np.ndarray] = None  # per-history local→union trans ids

    def __len__(self) -> int:
        return len(self.packeds)


def pack_batch(histories: Sequence[Union[Sequence[Op], PackedHistory]],
               model: Model,
               max_states: int = 1 << 20,
               n_pad: int = 0) -> PackedBatch:
    """Pack histories for :func:`~.linear_jax.check_device_batch` /
    :func:`~.linear_jax.check_sharded`.

    Transition ids are re-interned into one union table so all histories
    share a single memoized model; the BFS depth bound is the max
    invocation count over the batch (exact per history — a history can't
    linearize more ops than it invoked; see ``memoize_model``).
    """
    packeds = [h if isinstance(h, PackedHistory) else pack_history(list(h))
               for h in histories]
    union: List[tuple] = []
    ids = {}
    remaps = []
    for p in packeds:
        local = []
        for t in transitions_of(p):
            if t not in ids:
                ids[t] = len(union)
                union.append(t)
            local.append(ids[t])
        remaps.append(np.asarray(local, np.int32))
    n_inv = max((int(((p.type == INVOKE) & ~p.fails).sum())
                 for p in packeds), default=0)
    mm = memoize_model(model, union, max_states=max_states, max_depth=n_inv)

    n_pad = max(n_pad, _next_pow2(max((len(p) for p in packeds), default=1)))
    P = max((len(p.process_table) for p in packeds), default=1)
    kinds, procs, trs = [], [], []
    for p, remap in zip(packeds, remaps):
        s = LJ.make_stream(p, n_pad=n_pad)
        kind = np.asarray(s.kind)
        tr = np.asarray(s.tr).copy()
        mask = kind == LJ.K_INVOKE
        if remap.size:
            tr[mask] = remap[tr[mask]]
        kinds.append(kind)
        procs.append(np.asarray(s.proc))
        trs.append(tr)
    return PackedBatch(packeds=packeds, memo=mm,
                       kind=np.stack(kinds), proc=np.stack(procs),
                       tr=np.stack(trs), P=P, remaps=remaps)


@dataclass
class SegmentBatch:
    """Per-ok segment tensors for the flat engine: (S, B, K) layouts."""

    inv_proc: np.ndarray   # int32[S, B, K]
    inv_tr: np.ndarray     # int32[S, B, K] — union transition ids
    ok_proc: np.ndarray    # int32[S, B]
    seg_index: np.ndarray  # int64[B, S] — segment → history index
    depth: np.ndarray      # int32[S] — max pending depth across lanes


def segment_batch(batch: PackedBatch) -> SegmentBatch:
    """Compile each history's per-ok segments (union transition ids),
    padded to a common (S, K)."""
    segss = [LJ.make_segments(p) for p in batch.packeds]
    S = _next_pow2(max((s.ok_proc.shape[0] for s in segss), default=1))
    K = _next_pow2(max((s.inv_proc.shape[1] for s in segss),
                       default=1), 2)
    ips, its, ops, idxs, deps = [], [], [], [], []
    for remap, s in zip(batch.remaps, segss):
        ds, dk = S - s.ok_proc.shape[0], K - s.inv_proc.shape[1]
        inv_proc = np.pad(s.inv_proc, ((0, ds), (0, dk)),
                          constant_values=-1)
        tr = np.pad(s.inv_tr, ((0, ds), (0, dk)))
        mask = inv_proc >= 0
        if remap.size:
            tr[mask] = remap[tr[mask]]
        ips.append(inv_proc)
        its.append(tr)
        ops.append(np.pad(s.ok_proc, (0, ds), constant_values=-1))
        idxs.append(np.pad(s.seg_index, (0, ds)))
        deps.append(np.pad(s.depth, (0, ds)))
    return SegmentBatch(
        inv_proc=np.stack(ips, axis=1),    # (S, B, K)
        inv_tr=np.stack(its, axis=1),
        ok_proc=np.stack(ops, axis=1),     # (S, B)
        seg_index=np.stack(idxs, axis=0),  # (B, S)
        depth=np.max(np.stack(deps, axis=0), axis=0),   # (S,)
    )


def _stream_segments(batch: PackedBatch):
    """Per-history SegmentStreams with transition ids remapped into the
    union table (the streamed kernel shares ONE table)."""
    out = []
    for i, p in enumerate(batch.packeds):
        s = LJ.make_segments(p)
        remap = np.asarray(batch.remaps[i], np.int32)
        inv_tr = np.where(s.inv_proc >= 0, remap[s.inv_tr],
                          0).astype(np.int32)
        out.append(LJ.SegmentStream(s.inv_proc, inv_tr, s.ok_proc,
                                    s.seg_index, s.depth))
    return out


def check_batch(batch: PackedBatch, F: int = 256, mesh=None,
                batch_axis: str = "batch", engine: str = "auto"):
    """Run the batched device search; returns (status[N], fail_at[N],
    n_final[N]) NumPy arrays — fail_at in history-index terms. With
    ``mesh``, the batch axis is sharded across devices (data
    parallelism over ICI).

    engine: "stream" runs all histories through the fused Pallas
    kernel as one streamed scan (fastest on TPU — measured ~6x the
    keys engine); "keys" keeps the frontier as packed int32 key pairs
    — config mutation is bit arithmetic, dedup one sort; "flat" folds
    all frontiers into one explicit tensor with the batch id as the
    top sort key; "vmap" is the per-lane fallback; "auto" picks the
    best available whose budget fits.
    """
    succ = LJ.pad_succ(batch.memo.succ,
                       _next_pow2(batch.memo.succ.shape[0]),
                       _next_pow2(batch.memo.succ.shape[1]))
    P = _next_pow2(batch.P, 2)
    B = len(batch)
    sizes = {"n_states": batch.memo.n_states,
             "n_transitions": batch.memo.n_transitions}
    P_k = batch.P           # the kernel has no pow2 slot requirement

    def pick_xla_engine():
        if mesh is not None:
            return "vmap"
        if LJ.KeyLayout(B, sizes["n_states"], sizes["n_transitions"],
                        P).fits:
            return "keys"
        if LJ.flat_pack_bits(B, sizes["n_states"],
                             sizes["n_transitions"], P)[3]:
            return "flat"
        return "vmap"

    def stream_fits():
        # gate on the spec BEFORE the O(total-ops) segment pass so an
        # ineligible shape doesn't do the host work twice
        return (P_k <= 7
                and PSEG.spec_for(sizes["n_states"],
                                  sizes["n_transitions"], P_k, 8)
                is not None and PSEG.available())

    if engine == "auto":
        if mesh is None and stream_fits():
            engine = "stream"
        else:
            engine = pick_xla_engine()
    if engine == "stream":
        rs = None
        if stream_fits():
            segs_list = _stream_segments(batch)
            rs = PSEG.check_device_pallas_stream(
                batch.memo.succ, segs_list, P=P_k, **sizes)
        if rs is not None:
            status = np.array([r[0] for r in rs], np.int32)
            fail_at = np.array([
                segs_list[b].seg_index[rs[b][1]] if rs[b][1] >= 0
                else -1 for b in range(B)], np.int64)
            n_final = np.array([r[2] for r in rs], np.int32)
            # the kernel's frontier is fixed at 128: histories that
            # overflowed it get their requested budget F through the
            # XLA engines instead of surfacing spurious UNKNOWNs
            unk = np.flatnonzero(status == LJ.UNKNOWN)
            if unk.size and F > PSEG.F:
                sub = PackedBatch(
                    packeds=[batch.packeds[i] for i in unk],
                    memo=batch.memo,
                    kind=batch.kind[unk], proc=batch.proc[unk],
                    tr=batch.tr[unk], P=batch.P,
                    remaps=[batch.remaps[i] for i in unk])
                st2, fa2, n2 = check_batch(sub, F=F, mesh=mesh,
                                           engine=pick_xla_engine())
                status[unk] = st2
                fail_at[unk] = fa2
                n_final[unk] = n2
            return status, fail_at, n_final
        engine = pick_xla_engine()
    if engine in ("keys", "flat"):
        sb = segment_batch(batch)
        fn = (LJ.check_device_keys if engine == "keys"
              else LJ.check_device_flat)
        status, fail_seg, n_final = fn(
            succ, sb.inv_proc, sb.inv_tr, sb.ok_proc, sb.depth,
            B=B, F=F, P=P, **sizes)
        status = np.asarray(status)
        fail_seg = np.asarray(fail_seg)
        fail_at = np.array([
            sb.seg_index[b, fail_seg[b]] if fail_seg[b] >= 0 else -1
            for b in range(B)], np.int64)
        return status, fail_at, np.asarray(n_final)
    if mesh is not None:
        out = LJ.check_sharded(mesh, succ, batch.kind, batch.proc, batch.tr,
                               F=F, P=P, batch_axis=batch_axis, **sizes)
    else:
        out = LJ.check_device_batch(succ, batch.kind, batch.proc, batch.tr,
                                    F=F, P=P, **sizes)
    return tuple(np.asarray(x) for x in out)
