"""Batched history packing — many independent histories, one launch.

The device analog of ``jepsen.independent``'s per-key partitioning
(``independent.clj:252-300``): N short histories (e.g. one per register
key) are checked as ONE vmapped/sharded device computation. This module
owns the host-side glue: interning every history's transitions into a
single shared table, memoizing the model once over that union, and
padding per-history step streams to a common length.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence, Union

import numpy as np

from ..models.memo import MemoizedModel, memoize_model, transitions_of
from ..models.model import Model
from ..ops.op import INVOKE, Op
from ..ops.packed import PackedHistory, pack_history
from ..utils import next_pow2 as _next_pow2
from . import linear_jax as LJ


@dataclass
class PackedBatch:
    """N histories compiled against one shared successor table."""

    packeds: List[PackedHistory]
    memo: MemoizedModel
    kind: np.ndarray   # int32[N, n_pad]
    proc: np.ndarray   # int32[N, n_pad]
    tr: np.ndarray     # int32[N, n_pad] — ids into the shared table
    P: int             # max process count (slot width)

    def __len__(self) -> int:
        return len(self.packeds)


def pack_batch(histories: Sequence[Union[Sequence[Op], PackedHistory]],
               model: Model,
               max_states: int = 1 << 20,
               n_pad: int = 0) -> PackedBatch:
    """Pack histories for :func:`~.linear_jax.check_device_batch` /
    :func:`~.linear_jax.check_sharded`.

    Transition ids are re-interned into one union table so all histories
    share a single memoized model; the BFS depth bound is the max
    invocation count over the batch (exact per history — a history can't
    linearize more ops than it invoked; see ``memoize_model``).
    """
    packeds = [h if isinstance(h, PackedHistory) else pack_history(list(h))
               for h in histories]
    union: List[tuple] = []
    ids = {}
    remaps = []
    for p in packeds:
        local = []
        for t in transitions_of(p):
            if t not in ids:
                ids[t] = len(union)
                union.append(t)
            local.append(ids[t])
        remaps.append(np.asarray(local, np.int32))
    n_inv = max((int(((p.type == INVOKE) & ~p.fails).sum())
                 for p in packeds), default=0)
    mm = memoize_model(model, union, max_states=max_states, max_depth=n_inv)

    n_pad = max(n_pad, _next_pow2(max((len(p) for p in packeds), default=1)))
    P = max((len(p.process_table) for p in packeds), default=1)
    kinds, procs, trs = [], [], []
    for p, remap in zip(packeds, remaps):
        s = LJ.make_stream(p, n_pad=n_pad)
        kind = np.asarray(s.kind)
        tr = np.asarray(s.tr).copy()
        mask = kind == LJ.K_INVOKE
        if remap.size:
            tr[mask] = remap[tr[mask]]
        kinds.append(kind)
        procs.append(np.asarray(s.proc))
        trs.append(tr)
    return PackedBatch(packeds=packeds, memo=mm,
                       kind=np.stack(kinds), proc=np.stack(procs),
                       tr=np.stack(trs), P=P)


def check_batch(batch: PackedBatch, F: int = 256, mesh=None,
                batch_axis: str = "batch"):
    """Run the batched device search; returns (status[N], fail_at[N],
    n_final[N]) NumPy arrays. With ``mesh``, the batch axis is sharded
    across devices (data parallelism over ICI)."""
    succ = LJ.pad_succ(batch.memo.succ,
                       _next_pow2(batch.memo.succ.shape[0]),
                       _next_pow2(batch.memo.succ.shape[1]))
    P = _next_pow2(batch.P, 2)
    if mesh is not None:
        out = LJ.check_sharded(mesh, succ, batch.kind, batch.proc, batch.tr,
                               F=F, P=P, batch_axis=batch_axis)
    else:
        out = LJ.check_device_batch(succ, batch.kind, batch.proc, batch.tr,
                                    F=F, P=P)
    return tuple(np.asarray(x) for x in out)
