"""Web UI — browse the results store from a browser
(``jepsen/web.clj``): a table of runs with validity, per-run file
listings, artifact serving, and zip download of a whole run."""

from __future__ import annotations

import html
import io
import os
import threading
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import unquote

from ..ops.edn import read_edn_all
from . import store as store_ns

CONTENT_TYPES = {
    ".html": "text/html; charset=utf-8",
    ".svg": "image/svg+xml",
    ".edn": "text/plain; charset=utf-8",
    ".txt": "text/plain; charset=utf-8",
    ".log": "text/plain; charset=utf-8",
    ".json": "application/json",
}


def _runs(store_root: str):
    """(name, start-time, valid?) rows, newest first
    (``web.clj:36-76``)."""
    rows = []
    if not os.path.isdir(store_root):
        return rows
    for name in sorted(os.listdir(store_root)):
        d = os.path.join(store_root, name)
        if not os.path.isdir(d) or name == "latest":
            continue
        for t in store_ns.tests(name, store_root):
            valid = None
            rpath = os.path.join(d, t, "results.edn")
            if os.path.exists(rpath):
                try:
                    forms = read_edn_all(open(rpath).read())
                    if forms:
                        valid = forms[0].get("valid?")
                except Exception:
                    valid = "?"
            rows.append((name, t, valid))
    rows.sort(key=lambda r: r[1], reverse=True)
    return rows


def _index_html(store_root: str) -> str:
    rows = _runs(store_root)
    body = ["<html><head><title>comdb2_tpu store</title><style>",
            "body{font:14px monospace} table{border-collapse:collapse}",
            "td,th{border:1px solid #ccc;padding:4px 8px}",
            ".valid{background:#B7FFB7}.invalid{background:#FFD4D5}",
            ".unknown{background:#FEFFC1}",
            "</style></head><body><h1>test runs</h1><table>",
            "<tr><th>test</th><th>time</th><th>valid?</th><th></th></tr>"]
    for name, t, valid in rows:
        cls = ("valid" if valid is True
               else "invalid" if valid is False else "unknown")
        qn, qt = html.escape(name), html.escape(t)
        body.append(
            f'<tr class="{cls}"><td><a href="/files/{qn}/{qt}/">{qn}</a>'
            f"</td><td>{qt}</td><td>{html.escape(str(valid))}</td>"
            f'<td><a href="/zip/{qn}/{qt}">zip</a></td></tr>')
    body.append("</table>")
    # the verifier daemon's status artifact (store/service/ — written
    # by `python -m comdb2_tpu.service --store`; docs/service.md)
    svc = os.path.join(store_root, "service", "latest.json")
    if os.path.exists(svc):
        summary = ""
        try:
            import json as _json

            st = _json.loads(open(svc).read())
            summary = (f" — {st.get('completed', 0)} checked, "
                       f"{st.get('dispatches', 0)} dispatches, "
                       f"queue {st.get('queue_depth', 0)}")
        except Exception:
            pass
        links = ['<a href="/files/service/">verifier service</a>']
        # obs artifacts written by the daemon's artifact pass
        # (docs/observability.md): the latency/rate timeline and —
        # with --trace — the Perfetto span export
        for art in ("timeline.svg", "trace.json"):
            if os.path.exists(os.path.join(store_root, "service",
                                           art)):
                links.append(f'<a href="/files/service/{art}">'
                             f"{art}</a>")
        body.append(f"<p>{' · '.join(links)}"
                    f"{html.escape(summary)}</p>")
    body.append("</body></html>")
    return "".join(body)


def _listing_html(root: str, rel: str) -> str:
    d = os.path.join(root, rel)
    entries = sorted(os.listdir(d))
    body = [f"<html><body style='font:14px monospace'>"
            f"<h1>/{html.escape(rel)}</h1><ul>",
            '<li><a href="/">&larr; index</a></li>']
    for e in entries:
        q = html.escape(e)
        suffix = "/" if os.path.isdir(os.path.join(d, e)) else ""
        body.append(f'<li><a href="{q}{suffix}">{q}{suffix}</a></li>')
    body.append("</ul></body></html>")
    return "".join(body)


def _zip_run(root: str, rel: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.join(root, rel)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for dirpath, _dirs, files in os.walk(base):
            for f in files:
                full = os.path.join(dirpath, f)
                z.write(full, os.path.relpath(full, base))
    return buf.getvalue()


def _safe_rel(root: str, rel: str) -> Optional[str]:
    """Resolve a URL path inside the store root, rejecting traversal."""
    rel = unquote(rel).lstrip("/")
    full = os.path.realpath(os.path.join(root, rel))
    if not full.startswith(os.path.realpath(root) + os.sep) \
            and full != os.path.realpath(root):
        return None
    return rel


class _Handler(BaseHTTPRequestHandler):
    store_root = "store"

    def log_message(self, *args):
        pass

    def _send(self, code: int, content: bytes,
              ctype: str = "text/html; charset=utf-8"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(content)))
        self.end_headers()
        self.wfile.write(content)

    def do_GET(self):
        root = self.store_root
        try:
            if self.path in ("/", "/index.html"):
                self._send(200, _index_html(root).encode())
                return
            if self.path.startswith("/zip/"):
                rel = _safe_rel(root, self.path[len("/zip/"):])
                if rel is None or not os.path.isdir(
                        os.path.join(root, rel)):
                    self._send(404, b"not found")
                    return
                data = _zip_run(root, rel)
                name = rel.replace("/", "_") + ".zip"
                self.send_response(200)
                self.send_header("Content-Type", "application/zip")
                self.send_header("Content-Disposition",
                                 f'attachment; filename="{name}"')
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
                return
            if self.path.startswith("/files/"):
                rel = _safe_rel(root, self.path[len("/files/"):])
                if rel is None:
                    self._send(403, b"forbidden")
                    return
                full = os.path.join(root, rel)
                if os.path.isdir(full):
                    self._send(200, _listing_html(root, rel).encode())
                    return
                if os.path.isfile(full):
                    ext = os.path.splitext(full)[1]
                    ctype = CONTENT_TYPES.get(ext,
                                              "application/octet-stream")
                    with open(full, "rb") as fh:
                        self._send(200, fh.read(), ctype)
                    return
            self._send(404, b"not found")
        except BrokenPipeError:
            pass


def serve(store_root: str = "store", port: int = 8080,
          block: bool = True) -> Tuple[ThreadingHTTPServer, int]:
    """Serve the store browser; ``block=False`` runs it on a daemon
    thread and returns (server, port). Port 0 picks a free port."""
    handler = type("Handler", (_Handler,), {"store_root": store_root})
    srv = ThreadingHTTPServer(("127.0.0.1", port), handler)
    port = srv.server_address[1]
    if block:
        srv.serve_forever()
    else:
        threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, port
