"""Cluster provisioning: install, configure and cycle ``sut_node`` on
remote hosts through a :class:`~comdb2_tpu.control.remote.Remote`
transport — the role of the reference's ``scripts/newdb`` /
``scripts/setvars`` / ``scripts/addmach_comdb2db`` provisioning scripts
(machines m1..m5, ``scripts/setvars:7``) plus ``jepsen.db``'s
setup/teardown/cycle contract (``db.clj:4-25``; round-3 VERDICT
Missing #4: ``SSHRemote`` existed but nothing installed or configured
a SUT on fresh nodes).

A node name maps to (host, client_port) via ``layout``; the SUT's
replication mesh is wired from the same layout (``sut_node -n`` takes
``host:port`` entries since round 4). With every node on localhost and
a :class:`~comdb2_tpu.control.remote.LocalRemote` this provisions a
real cluster in CI; pointing the layout at real hosts with an
``SSHRemote`` is the same code path (the binary is uploaded, so hosts
need nothing pre-installed beyond libc).
"""

from __future__ import annotations

import shlex
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..control.remote import Remote, RemoteError
from . import db as db_ns


@dataclass
class NodeLayout:
    """Where each logical node lives: host + client/replication port.
    One process per node; all ports distinct when hosts collide
    (the localhost-CI case)."""

    host: str
    port: int


class SutNodeDB(db_ns.DB, db_ns.Primary, db_ns.LogFiles):
    """DB-protocol provisioner for the in-tree replicated SUT.

    ``setup`` uploads the binary (once per host), wipes the node's
    state dir, writes a config file recording the flags (the
    ``setvars`` role — the run is reproducible from the artifact), and
    starts the daemon with a pidfile; ``teardown`` kills it.
    ``cycle`` (teardown + setup, ``db.clj:17-25``) therefore gives
    every test run a fresh, freshly-configured cluster.
    """

    def __init__(self, remote: Remote, binary: str,
                 layout: Dict[str, NodeLayout],
                 base_dir: str = "/tmp/comdb2tpu-sut",
                 timeout_ms: int = 500, elect_ms: int = 500,
                 lease_ms: int = 300, persistent: bool = True,
                 flags: Sequence[str] = ()):
        self.remote = remote
        self.binary = binary
        self.layout = dict(layout)
        self.base_dir = base_dir
        self.timeout_ms = timeout_ms
        self.elect_ms = elect_ms
        self.lease_ms = lease_ms
        self.persistent = persistent
        self.flags = list(flags)
        self._installed: set = set()

    # -- paths ---------------------------------------------------------

    def _dir(self, node: str) -> str:
        return f"{self.base_dir}/{node}"

    def _bin(self, node: str) -> str:
        return f"{self._dir(node)}/sut_node"

    def _pidfile(self, node: str) -> str:
        return f"{self._dir(node)}/pid"

    def _logfile(self, node: str) -> str:
        return f"{self._dir(node)}/sut.log"

    def _peers(self, test: dict) -> str:
        """The ``-n host:port,...`` mesh, ordered by test node list."""
        return ",".join(
            f"{self.layout[n].host}:{self.layout[n].port}"
            for n in test["nodes"])

    def _node_id(self, test: dict, node: str) -> int:
        return list(test["nodes"]).index(node)

    # -- DB protocol ---------------------------------------------------

    def setup(self, test: dict, node: str) -> None:
        host = self.layout[node].host
        d = shlex.quote(self._dir(node))
        self.remote.execute(host,
                            f"mkdir -p {d} && rm -rf {d}/state")
        if (host, node) not in self._installed:
            self.remote.upload(host, self.binary, self._bin(node))
            self.remote.execute(
                host, f"chmod +x {shlex.quote(self._bin(node))}")
            self._installed.add((host, node))
        i = self._node_id(test, node)
        args = [self._bin(node), "-i", str(i), "-n", self._peers(test),
                "-t", str(self.timeout_ms),
                "-e", str(self.elect_ms), "-l", str(self.lease_ms)]
        if self.persistent:
            args += ["-d", f"{self._dir(node)}/state"]
        args += self.flags
        # quote each argv element: base dirs/node names/flags with
        # shell metacharacters must not corrupt the command line or
        # the config heredoc (ADVICE r4)
        cmd = " ".join(shlex.quote(a) for a in args)
        # the setvars role: the exact configuration is an artifact
        self.remote.execute(
            host,
            f"printf '%s\\n' {shlex.quote(cmd)} > {d}/config")
        self.remote.execute(
            host,
            f"nohup {cmd} > {shlex.quote(self._logfile(node))} 2>&1 & "
            f"echo $! > {shlex.quote(self._pidfile(node))}")
        self._await_ready(host, self.layout[node].port)

    def teardown(self, test: dict, node: str) -> None:
        host = self.layout[node].host
        pf = shlex.quote(self._pidfile(node))
        self.remote.execute(
            host, f"[ -f {pf} ] && kill -9 $(cat {pf}) 2>/dev/null; "
                  f"rm -f {pf}; true")

    def setup_primary(self, test: dict, node: str) -> None:
        """Elections pick the primary; wait until one exists so the
        first client op doesn't race the first election (persistent
        nodes always boot as replicas)."""
        self._await_primary(test)

    def log_files(self, test: dict, node: str) -> List[str]:
        return [self._logfile(node)]

    # -- readiness -----------------------------------------------------

    def _probe(self, host: str, port: int, req: str) -> str:
        """One request/reply through the transport (the control plane
        may be the only path to the node — client ports need not be
        reachable from the harness host)."""
        r = self.remote.execute(
            host,
            "timeout 1 bash -c 'exec 3<>/dev/tcp/127.0.0.1/%d; "
            "printf \"%s\\n\" >&3; head -n1 <&3' 2>/dev/null"
            % (port, req))
        return (r.out or "").strip()

    def _await_ready(self, host: str, port: int,
                     deadline_s: float = 10.0) -> None:
        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            if self._probe(host, port, "P") == "PONG":
                return
            time.sleep(0.1)
        raise RuntimeError(f"sut_node on {host}:{port} not ready")

    def _await_primary(self, test: dict,
                       deadline_s: float = 15.0) -> None:
        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            for n in test["nodes"]:
                lay = self.layout[n]
                info = self._probe(lay.host, lay.port, "I")
                if " primary " in f" {info} ":
                    return
            time.sleep(0.15)
        raise RuntimeError("no primary elected during setup")


def local_layout(nodes: Sequence[str],
                 ports: Sequence[int]) -> Dict[str, NodeLayout]:
    """All nodes on localhost with distinct ports — the CI shape."""
    return {n: NodeLayout("127.0.0.1", p)
            for n, p in zip(nodes, ports)}
