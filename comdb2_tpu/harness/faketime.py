"""libfaketime wrappers — divergent clock *rates* per node
(``jepsen/faketime.clj``): replace a SUT binary with a script that runs
it under faketime with an initial offset and a rate multiplier."""

from __future__ import annotations

from .. import control
from ..control import util as cutil


def script(cmd: str, init_offset_s: float, rate: float) -> str:
    """The wrapper script body (``faketime.clj:8-19``). Fractional
    offsets are preserved — faketime accepts them, and sub-second skew
    is a realistic drift magnitude."""
    sign = "-" if init_offset_s < 0 else "+"
    return (f'#!/bin/bash\n'
            f'faketime -m -f "{sign}{abs(init_offset_s):g}s x{rate:g}" '
            f'{cmd} "$@"\n')


def wrap(cmd: str, init_offset_s: float, rate: float) -> None:
    """Replace ``cmd`` on the current node with a faketime wrapper,
    moving the original to ``cmd.no-faketime``; idempotent
    (``faketime.clj:21-31``)."""
    orig = cmd + ".no-faketime"
    body = script(orig, init_offset_s, rate)
    if not cutil.exists(orig):
        control.exec_("mv", cmd, orig)
    control.exec_(control.lit(
        f"cat > {control.escape(cmd)} <<'FAKETIME_EOF'\n{body}FAKETIME_EOF"))
    control.exec_("chmod", "a+x", cmd)


def unwrap(cmd: str) -> None:
    """Restore the original binary."""
    orig = cmd + ".no-faketime"
    if cutil.exists(orig):
        control.exec_("mv", orig, cmd)
