"""OS preparation implementations (``jepsen/os/debian.clj``,
``os/smartos.clj``): hostname/hosts-file setup and package
installation over the control plane."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from .. import control
from . import db as db_ns


def setup_hostfile(node: str, node_ips: Optional[Dict[str, str]] = None
                   ) -> None:
    """Point /etc/hostname and /etc/hosts at the test's node names
    (``os/debian.clj:78-96``)."""
    control.su(control.lit(
        f"echo {control.escape(str(node))} > /etc/hostname"))
    lines = ["127.0.0.1 localhost",
             f"127.0.1.1 {node}"]
    for name, ip in (node_ips or {}).items():
        if name != node:
            lines.append(f"{ip} {name}")
    body = "\\n".join(lines)
    control.su(control.lit(f'printf "{body}\\n" > /etc/hosts'))


class DebianOS(db_ns.OS):
    """apt-based prep (``os/debian.clj``): noninteractive update +
    install of required packages."""

    def __init__(self, packages: Sequence[str] = (),
                 node_ips: Optional[Dict[str, str]] = None,
                 update: bool = True):
        self.packages = list(packages)
        self.node_ips = node_ips
        self.update = update

    def setup(self, test, node):
        setup_hostfile(node, self.node_ips)
        if self.update:
            control.su("env", "DEBIAN_FRONTEND=noninteractive",
                       "apt-get", "update", "-y", check=False)
        if self.packages:
            control.su("env", "DEBIAN_FRONTEND=noninteractive",
                       "apt-get", "install", "-y", *self.packages)

    def teardown(self, test, node):
        pass


class SmartOS(db_ns.OS):
    """pkgin-based prep (``os/smartos.clj``)."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def setup(self, test, node):
        if self.packages:
            control.su("pkgin", "-y", "install", *self.packages)

    def teardown(self, test, node):
        pass
