"""Cluster healing and coherency gates — the roles of the reference's
``scripts/heal``, ``scripts/blockcoherent.sh``, and the outer loop of
``jepsenloop.sh``: before each run, undo every partition/pause and wait
until the cluster reports itself coherent."""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

from .. import control


def heal_all(test: dict, processes: Sequence[str] = ()) -> None:
    """Flush iptables DROP rules and SIGCONT the given process names on
    every node (``scripts/heal:20-29``)."""
    def heal1(test_, node):
        control.su("iptables", "-F", "-w", check=False)
        control.su("iptables", "-X", "-w", check=False)
        for p in processes:
            control.su("killall", "-s", "CONT", p, check=False)
    control.on_nodes(test, heal1)


def await_fn(probe: Callable[[], bool], timeout: float = 60.0,
             interval: float = 1.0, desc: str = "condition") -> None:
    """Poll ``probe`` until true or timeout."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if probe():
            return
        time.sleep(interval)
    raise TimeoutError(f"timed out after {timeout}s waiting for {desc}")


def await_coherent(test: dict, coherent_probe: Callable[[dict], bool],
                   timeout: float = 120.0, interval: float = 2.0) -> None:
    """Block until the SUT reports no incoherent nodes — the contract of
    ``blockcoherent.sh:15-37`` (which polls the master's ``bdb cluster``
    status); the probe is SUT-specific."""
    await_fn(lambda: coherent_probe(test), timeout=timeout,
             interval=interval, desc="cluster coherency")


def test_loop(make_test: Callable[[], dict],
              run_fn: Callable[[dict], dict],
              pre: Optional[Callable[[dict], None]] = None,
              max_runs: Optional[int] = None) -> int:
    """The ``jepsenloop.sh`` driver: heal, gate, run, fail on invalid;
    loop. Returns the number of valid runs completed (stops on the first
    invalid/unknown or after max_runs)."""
    runs = 0
    while max_runs is None or runs < max_runs:
        test = make_test()
        if pre is not None:
            pre(test)
        result = run_fn(test)
        if (result.get("results") or {}).get("valid?") is not True:
            return runs
        runs += 1
    return runs
