"""Interactive helpers (``jepsen/repl.clj`` + ``jepsen/report.clj``):
reload the latest run and re-check it offline; capture stdout to a
file."""

from __future__ import annotations

import contextlib
from typing import Optional

from ..checker.checkers import check_safe
from . import store


def last_test(name: str, store_root: str = "store") -> Optional[dict]:
    """The most recent persisted run of a test (``repl.clj:6-13``)."""
    return store.latest(name, store_root)


def recheck(test: dict, checker, model=None) -> dict:
    """Re-run a checker over a reloaded test's history — analysis is
    replayable from the persisted artifact (``store.clj:159-165``)."""
    return check_safe(checker, test, model, test.get("history") or [])


@contextlib.contextmanager
def to_file(path: str):
    """Redirect stdout into a report file (``report.clj``)."""
    with open(path, "w") as fh, contextlib.redirect_stdout(fh):
        yield fh
