"""In-memory fake SUT + the noop test map — harness self-tests without
any cluster (``jepsen/tests.clj``)."""

from __future__ import annotations

import threading
from typing import Any

from ..checker import checkers
from ..models import model as M
from . import client as client_ns
from . import db as db_ns
from . import generator as gen


class AtomDB(db_ns.DB):
    """Wraps shared state as a database (``tests.clj:27-32``)."""

    def __init__(self, state):
        self.state = state

    def setup(self, test, node):
        self.state.reset(None)

    def teardown(self, test, node):
        self.state.reset("done")


class Atom:
    """A compare-and-swap cell (the Clojure atom)."""

    def __init__(self, value: Any = None):
        self.value = value
        self.lock = threading.Lock()

    def reset(self, v):
        with self.lock:
            self.value = v

    def deref(self):
        with self.lock:
            return self.value

    def cas(self, cur, new) -> bool:
        with self.lock:
            if self.value == cur:
                self.value = new
                return True
            return False


class AtomClient(client_ns.Client):
    """A linearizable CAS register over an atom (``tests.clj:34-56``) —
    the fake backend for exercising workers, nemesis, and checkers."""

    def __init__(self, state: Atom):
        self.state = state

    def invoke(self, test, op):
        f = op.get("f")
        if f == "write":
            self.state.reset(op.get("value"))
            return {**op, "type": "ok"}
        if f == "cas":
            cur, new = op.get("value")
            ok = self.state.cas(cur, new)
            return {**op, "type": "ok" if ok else "fail"}
        if f == "read":
            return {**op, "type": "ok", "value": self.state.deref()}
        raise ValueError(f"unknown f {f!r}")


def atom_db(state: Atom) -> AtomDB:
    return AtomDB(state)


def atom_client(state: Atom) -> AtomClient:
    return AtomClient(state)


def noop_test() -> dict:
    """Boring test stub, basis for real tests (``tests.clj:12-25``).
    Five nodes, noop os/db/client/nemesis, void generator, register
    model, linearizable checker."""
    return {
        "nodes": ["n1", "n2", "n3", "n4", "n5"],
        "name": "noop",
        "os": db_ns.noop_os,
        "db": db_ns.noop,
        "client": client_ns.noop,
        "nemesis": client_ns.noop_nemesis,
        "generator": gen.void,
        "model": M.register(),
        "checker": checkers.linearizable,
    }
