"""Command-line runner (``jepsen/cli.clj``).

``single_test_cmd(test_fn)`` builds an argparse CLI with the reference's
option surface (``cli.clj:52-98``: ``--node``, ``--concurrency`` default
30, ``--time-limit`` default 60, ssh credentials) and runs
``test_fn(opts)`` through :func:`comdb2_tpu.harness.core.run`, exiting
nonzero when the analysis is invalid.
"""

from __future__ import annotations

import argparse
import sys
from typing import Callable, List, Optional

from . import core


def parser(description: str = "comdb2_tpu test") -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-n", "--node", action="append", dest="nodes",
                   metavar="HOST",
                   help="node to run against (repeatable)")
    p.add_argument("--nodes-file", help="file with one node per line")
    p.add_argument("-c", "--concurrency", type=int, default=30,
                   help="number of worker processes (default 30)")
    p.add_argument("--time-limit", type=float, default=60.0,
                   help="seconds to run the workload (default 60)")
    p.add_argument("--test-count", type=int, default=1,
                   help="how many times to run the test")
    p.add_argument("--username", default="root", help="ssh username")
    p.add_argument("--password", default=None, help="ssh password")
    p.add_argument("--private-key-path", default=None,
                   help="ssh identity file")
    p.add_argument("--ssh-port", type=int, default=22)
    p.add_argument("--store-root", default="store",
                   help="directory for results (default store/)")
    return p


def opts_from_args(args: argparse.Namespace) -> dict:
    nodes: Optional[List[str]] = args.nodes
    if args.nodes_file:
        with open(args.nodes_file) as fh:
            nodes = (nodes or []) + [l.strip() for l in fh
                                     if l.strip()]
    return {
        "nodes": nodes if nodes is not None else [],
        "concurrency": args.concurrency,
        "time-limit": args.time_limit,
        "store-root": args.store_root,
        "ssh": {"username": args.username, "password": args.password,
                "private-key-path": args.private_key_path,
                "port": args.ssh_port},
    }


def single_test_cmd(test_fn: Callable[[dict], dict],
                    argv: Optional[List[str]] = None,
                    description: str = "comdb2_tpu test") -> int:
    """Parse args, build the test via ``test_fn(opts)``, run it
    ``--test-count`` times; returns a process exit code (0 iff all runs
    valid, 2 on unknown, 1 on invalid — invalid dominates)."""
    args = parser(description).parse_args(argv)
    opts = opts_from_args(args)
    saw_unknown = False
    for _ in range(args.test_count):
        test = core.run(test_fn(opts))
        valid = (test.get("results") or {}).get("valid?")
        if valid is True:
            continue
        if valid == "unknown":
            saw_unknown = True
        else:
            return 1            # invalid dominates; stop immediately
    return 2 if saw_unknown else 0


def main(test_fn: Callable[[dict], dict]) -> None:
    sys.exit(single_test_cmd(test_fn))
