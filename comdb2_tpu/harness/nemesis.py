"""Fault injection — nemeses are Clients routed to process ``nemesis``
(``jepsen/nemesis.clj``).

Grudge-based partitioners: a *grudge* maps each node to the set of nodes
it should drop traffic from (``nemesis.clj:21-27``). Grudges:
``complete_grudge`` (``:41-54``), ``bridge`` (``:56-66``),
``majorities_ring`` (``:105-119``). Plus clock scrambling
(``:167-187``), SIGSTOP/SIGCONT process pauses (``:189-240``), and
f-routed composition (``:127-165``).
"""

from __future__ import annotations

import math
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

from .. import control
from ..control import net as net_ns
from . import client as client_ns


# the noop nemesis returns ops unchanged (``nemesis.clj:9-14``) — same
# contract as the pass-through client
Noop = client_ns.PassThrough
noop = client_ns.noop_nemesis


# --- grudges ---------------------------------------------------------------

def bisect(coll: Sequence) -> List[List]:
    """Cut in half, smaller half first (``nemesis.clj:29-32``)."""
    coll = list(coll)
    mid = len(coll) // 2
    return [coll[:mid], coll[mid:]]


def split_one(coll: Sequence, loner=None) -> List[List]:
    """One node vs the rest (``nemesis.clj:34-39``)."""
    coll = list(coll)
    if loner is None:
        loner = random.choice(coll)
    return [[loner], [x for x in coll if x != loner]]


def complete_grudge(components: Sequence[Sequence]) -> Dict[Any, Set]:
    """No node may talk outside its component (``nemesis.clj:41-54``)."""
    comps = [set(c) for c in components]
    universe = set().union(*comps) if comps else set()
    grudge: Dict[Any, Set] = {}
    for comp in comps:
        for node in comp:
            grudge[node] = universe - comp
    return grudge


def bridge(nodes: Sequence) -> Dict[Any, Set]:
    """Two halves plus one node with unbroken connectivity to both
    (``nemesis.clj:56-66``)."""
    components = bisect(list(nodes))
    b = components[1][0]
    grudge = complete_grudge(components)
    grudge.pop(b, None)
    return {n: (s - {b}) for n, s in grudge.items()}


def majority(n: int) -> int:
    return n // 2 + 1


def majorities_ring(nodes: Sequence) -> Dict[Any, Set]:
    """Every node sees a majority, but no two nodes see the same one
    (``nemesis.clj:105-119``): shuffle into a ring, each node keeps the
    next m-1 neighbors, drops the rest."""
    U = set(nodes)
    ring = list(nodes)
    random.shuffle(ring)
    n = len(ring)
    m = majority(n)
    grudge = {}
    for i in range(n):
        maj = {ring[(i + j) % n] for j in range(m)}
        grudge[ring[i]] = U - maj
    return grudge


# --- partitioner -----------------------------------------------------------

def _net(test: dict) -> net_ns.Net:
    return test.get("net", net_ns.noop)


def partition(test: dict, grudge: Dict[Any, Set]) -> None:
    """Apply a grudge: every node drops traffic from its grudge set.
    Cumulative — does not heal first (``nemesis.clj:16-27``)."""
    net = _net(test)
    def snub(test_, node):
        for src in grudge.get(node, ()):
            net.drop(test_, src, node)
    control.on_nodes(test, snub)


class Partitioner(client_ns.Client):
    """start: cut links per ``grudge_fn(nodes)``; stop: heal
    (``nemesis.clj:68-86``)."""

    def __init__(self, grudge_fn: Callable[[Sequence], Dict[Any, Set]]):
        self.grudge_fn = grudge_fn

    def setup(self, test, node):
        _net(test).heal(test)
        return self

    def invoke(self, test, op):
        if op["f"] == "start":
            grudge = self.grudge_fn(test.get("nodes") or [])
            partition(test, grudge)
            return {**op, "value": f"Cut off {sorted_grudge_str(grudge)}"}
        if op["f"] == "stop":
            _net(test).heal(test)
            return {**op, "value": "fully connected"}
        raise ValueError(f"partitioner can't handle f={op['f']!r}")

    def teardown(self, test):
        _net(test).heal(test)


def sorted_grudge_str(grudge: Dict[Any, Set]) -> str:
    return "{" + ", ".join(f"{n}: {sorted(map(str, s))}"
                           for n, s in sorted(grudge.items(),
                                              key=lambda kv: str(kv[0]))) \
        + "}"


def partitioner(grudge_fn) -> Partitioner:
    return Partitioner(grudge_fn)


def partition_halves() -> Partitioner:
    """First-half/second-half split (``nemesis.clj:88-93``)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves() -> Partitioner:
    """Randomly-chosen halves — the comdb2 tests' nemesis
    (``nemesis.clj:95-98``)."""
    def g(nodes):
        ns = list(nodes)
        random.shuffle(ns)
        return complete_grudge(bisect(ns))
    return Partitioner(g)


def partition_random_node() -> Partitioner:
    """Isolate one random node (``nemesis.clj:100-103``)."""
    return Partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring() -> Partitioner:
    """Overlapping-majority ring partitions (``nemesis.clj:121-125``)."""
    return Partitioner(majorities_ring)


# --- composition -----------------------------------------------------------

class Compose(client_ns.Client):
    """Route ops to child nemeses by f (``nemesis.clj:127-165``).
    ``routes`` maps route-spec → nemesis, where a route-spec is either a
    set of fs (passed through unchanged) or a dict renaming outer f →
    inner f."""

    def __init__(self, routes):
        # routes: dict spec->nemesis, or (since dict/set specs aren't
        # hashable as keys) a sequence of (spec, nemesis) pairs
        pairs = routes.items() if isinstance(routes, dict) else routes
        self.routes = [(self._to_fn(spec), nem) for spec, nem in pairs]

    @staticmethod
    def _to_fn(spec):
        if isinstance(spec, (set, frozenset)):
            return lambda f: f if f in spec else None
        if isinstance(spec, dict):
            return lambda f: spec.get(f)
        if callable(spec):
            return spec
        raise TypeError(f"bad route spec {spec!r}")

    def setup(self, test, node):
        self.routes = [(fn, nem.setup(test, node))
                       for fn, nem in self.routes]
        return self

    def invoke(self, test, op):
        f = op.get("f")
        for fn, nem in self.routes:
            f2 = fn(f)
            if f2 is not None:
                out = nem.invoke(test, {**op, "f": f2})
                return {**out, "f": f}
        raise ValueError(f"no nemesis can handle {f!r}")

    def teardown(self, test):
        for _, nem in self.routes:
            nem.teardown(test)


def compose(routes) -> Compose:
    return Compose(routes)


# --- clock faults ----------------------------------------------------------

def set_time(t: float) -> str:
    """Set node time in POSIX seconds on the current session
    (``nemesis.clj:167-170``)."""
    return control.su("date", "+%s", "-s", f"@{int(t)}")


class ClockScrambler(client_ns.Client):
    """Randomizes node clocks within ±dt seconds
    (``nemesis.clj:172-187``)."""

    def __init__(self, dt: float):
        self.dt = dt

    def invoke(self, test, op):
        dt = self.dt
        def scramble(test_, node):
            return set_time(time.time() + random.uniform(-dt, dt))
        vals = control.on_nodes(test, scramble)
        return {**op, "value": vals}

    def teardown(self, test):
        def reset(test_, node):
            return set_time(time.time())
        try:
            control.on_nodes(test, reset)
        except Exception:
            pass


def clock_scrambler(dt: float) -> ClockScrambler:
    return ClockScrambler(dt)


# --- process pauses / node start-stop --------------------------------------

class NodeStartStopper(client_ns.Client):
    """start: run ``start_fn(test, node)`` on targeted nodes; stop: run
    ``stop_fn`` on the same nodes (``nemesis.clj:189-224``). The
    targeter picks fresh nodes each start."""

    def __init__(self, targeter, start_fn, stop_fn):
        self.targeter = targeter
        self.start_fn = start_fn
        self.stop_fn = stop_fn
        self._nodes: Optional[List] = None
        self._lock = threading.Lock()

    def invoke(self, test, op):
        with self._lock:
            if op["f"] == "start":
                targets = self.targeter(test.get("nodes") or [])
                if targets is None:
                    return {**op, "value": "no-target"}
                if not isinstance(targets, (list, tuple, set)):
                    targets = [targets]
                targets = list(targets)
                if self._nodes is not None:
                    return {**op, "value":
                            f"nemesis already disrupting {self._nodes}"}
                self._nodes = targets
                vals = control.on_many(test, targets, self.start_fn)
                return {**op, "value": vals}
            if op["f"] == "stop":
                if self._nodes is None:
                    return {**op, "value": "not-started"}
                vals = control.on_many(test, self._nodes, self.stop_fn)
                self._nodes = None
                return {**op, "value": vals}
            raise ValueError(f"can't handle f={op['f']!r}")


def node_start_stopper(targeter, start_fn, stop_fn) -> NodeStartStopper:
    return NodeStartStopper(targeter, start_fn, stop_fn)


def hammer_time(process: str, targeter=None) -> NodeStartStopper:
    """SIGSTOP/SIGCONT a process on random nodes
    (``nemesis.clj:226-240``)."""
    targeter = targeter or (lambda nodes: random.choice(list(nodes))
                            if nodes else None)

    def start(test, node):
        control.su("killall", "-s", "STOP", process)
        return ["paused", process]

    def stop(test, node):
        control.su("killall", "-s", "CONT", process)
        return ["resumed", process]

    return NodeStartStopper(targeter, start, stop)
