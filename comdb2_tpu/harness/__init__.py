"""Harness runtime: generator DSL, client/DB/OS protocols, worker &
nemesis loops with indeterminacy-driven process recycling, results
store, and CLI — the capabilities of ``jepsen/{core,generator,client,
db,os,store,cli,tests}.clj``."""

from . import generator
from . import client
from . import db
from . import core
from . import store
from . import fake
from . import cli
from . import nemesis
from . import nemesis_time
from . import cluster
from . import faketime
from . import killcluster
from . import web
from .core import run, run_case

__all__ = ["generator", "client", "db", "core", "store", "fake", "cli",
           "nemesis", "nemesis_time", "cluster", "faketime",
           "killcluster", "web", "run", "run_case"]
