"""Generator DSL — composable, thread-safe op sources.

The semantics of ``jepsen/generator.clj``: a generator yields operation
maps for processes until exhausted, at which point it yields ``None``.
Every plain object acts as a constant generator of itself; callables are
invoked with ``(test, process)`` (or no args); ``None`` is the empty
generator (``generator.clj:22-38``).

The dynamic ``*threads*`` binding (``generator.clj:40``) — the ordered
set of worker threads routed into a subtree, used by ``on``/``reserve``/
``synchronize`` — is a per-OS-thread binding stack here, since each
harness worker draws ops on its own thread.
"""

from __future__ import annotations

import inspect
import random
import threading
import time as _time
from typing import Any, Callable, List, Optional, Sequence

NEMESIS = "nemesis"

_tls = threading.local()


def current_threads() -> Optional[List]:
    return getattr(_tls, "threads", None)


class _ThreadsBinding:
    def __init__(self, threads):
        self.threads = list(threads)

    def __enter__(self):
        self.saved = getattr(_tls, "threads", None)
        _tls.threads = self.threads
        return self

    def __exit__(self, *exc):
        _tls.threads = self.saved


def with_threads(threads):
    """Bind the ordered thread collection for the current OS thread
    (``generator.clj:46-53``)."""
    return _ThreadsBinding(threads)


def process_to_thread(test: dict, process) -> Any:
    """process mod concurrency for integer processes; symbolic processes
    (the nemesis) map to themselves (``generator.clj:55-60``)."""
    if isinstance(process, int) and not isinstance(process, bool):
        return process % test["concurrency"]
    return process


def process_to_node(test: dict, process):
    thread = process_to_thread(test, process)
    nodes = test.get("nodes") or []
    if isinstance(thread, int) and nodes:
        return nodes[thread % len(nodes)]
    return None


def op(gen, test: dict, process):
    """Draw one operation from anything generator-like
    (``generator.clj:22-38``): Generator → its op; None → None;
    callable → call it; any other object → itself (a constant op)."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.op(test, process)
    if callable(gen):
        # decide arity by signature, not by catching TypeError — a
        # TypeError raised *inside* the fn must propagate, not trigger
        # a confusing zero-arg retry
        try:
            inspect.signature(gen).bind(test, process)
        except TypeError:
            return gen()
        return gen(test, process)
    return gen


class Generator:
    """Subclasses implement ``op(test, process) -> op-dict | None``."""

    def op(self, test: dict, process):
        raise NotImplementedError


class _Fn(Generator):
    def __init__(self, fn: Callable):
        self.fn = fn

    def op(self, test, process):
        return self.fn(test, process)


class Void(Generator):
    """Terminates immediately (``generator.clj:62-65``)."""

    def op(self, test, process):
        return None


void = Void()


class DelayFn(Generator):
    """Each op takes ``f()`` extra seconds (``generator.clj:90-96``)."""

    def __init__(self, f: Callable[[], float], gen):
        self.f = f
        self.gen = gen

    def op(self, test, process):
        _time.sleep(self.f())
        return op(self.gen, test, process)


def delay(dt: float, gen) -> DelayFn:
    return DelayFn(lambda: dt, gen)


def stagger(dt: float, gen) -> DelayFn:
    """Uniform random delay with mean dt, in [0, 2dt)
    (``generator.clj:137-141``)."""
    return DelayFn(lambda: random.uniform(0, 2 * dt), gen)


class DelayTil(Generator):
    """Emit ops as close as possible to multiples of dt seconds from an
    anchor — for triggering races (``generator.clj:112-135``)."""

    def __init__(self, dt: float, gen, precache: bool = True):
        self.dt = dt
        self.gen = gen
        self.precache = precache
        self.anchor = _time.monotonic()

    def _sleep_til_tick(self):
        now = _time.monotonic()
        since = (now - self.anchor) % self.dt
        _time.sleep(self.dt - since)

    def op(self, test, process):
        if self.precache:
            o = op(self.gen, test, process)
            self._sleep_til_tick()
            return o
        self._sleep_til_tick()
        return op(self.gen, test, process)


def delay_til(dt: float, gen, precache: bool = True) -> DelayTil:
    return DelayTil(dt, gen, precache)


def sleep(dt: float) -> DelayFn:
    """Takes dt seconds, always yields None (``generator.clj:143-146``)."""
    return delay(dt, void)


class Once(Generator):
    """Passes through the source exactly once (``generator.clj:148-156``)."""

    def __init__(self, source):
        self.source = source
        self._lock = threading.Lock()
        self._emitted = False

    def op(self, test, process):
        with self._lock:
            if self._emitted:
                return None
            self._emitted = True
        return op(self.source, test, process)


def once(source) -> Once:
    return Once(source)


class Log(Generator):
    """Logs a message every invocation, yields None
    (``generator.clj:158-164``)."""

    def __init__(self, msg, sink: Optional[Callable[[str], None]] = None):
        self.msg = msg
        self.sink = sink

    def op(self, test, process):
        import logging
        (self.sink or logging.getLogger("comdb2_tpu.harness").info)(self.msg)
        return None


def log_star(msg) -> Log:
    return Log(msg)


def log(msg) -> Once:
    """Logs once (``generator.clj:166-169``)."""
    return once(Log(msg))


class Each(Generator):
    """A fresh generator from ``gen_fn`` per distinct process
    (``generator.clj:171-186``)."""

    def __init__(self, gen_fn: Callable[[], Any]):
        self.gen_fn = gen_fn
        self._lock = threading.Lock()
        self._gens = {}

    def op(self, test, process):
        with self._lock:
            if process not in self._gens:
                self._gens[process] = self.gen_fn()
            g = self._gens[process]
        return op(g, test, process)


def each(gen_fn: Callable[[], Any]) -> Each:
    return Each(gen_fn)


class Seq(Generator):
    """One op from each generator in turn; a None moves to the next;
    exhausted when the sequence is (``generator.clj:188-200``)."""

    def __init__(self, coll):
        self._iter = iter(coll)
        self._lock = threading.Lock()
        self._cur = None
        self._done = False

    def op(self, test, process):
        while True:
            with self._lock:
                if self._done:
                    return None
                try:
                    self._cur = next(self._iter)
                except StopIteration:
                    self._done = True
                    return None
                g = self._cur
            o = op(g, test, process)
            if o is not None:
                return o


def seq(coll) -> Seq:
    return Seq(coll)


def start_stop(t1: float, t2: float) -> Seq:
    """start after t1 s, stop after t2 s more (``generator.clj:202-209``)."""
    return seq([sleep(t1), {"type": "info", "f": "start"},
                sleep(t2), {"type": "info", "f": "stop"}])


class Mix(Generator):
    """Uniform random choice between generators (``generator.clj:211-217``)."""

    def __init__(self, gens: Sequence):
        self.gens = list(gens)

    def op(self, test, process):
        return op(random.choice(self.gens), test, process)


def mix(gens) -> Mix:
    return Mix(gens)


def cas_gen(test=None, process=None):
    """Random read/write/cas invocations over ints < 5
    (``generator.clj:219-231``)."""
    r = random.random()
    if r > 0.66:
        return {"type": "invoke", "f": "read", "value": None}
    if r > 0.33:
        return {"type": "invoke", "f": "write", "value": random.randrange(5)}
    return {"type": "invoke", "f": "cas",
            "value": (random.randrange(5), random.randrange(5))}


class QueueGen(Generator):
    """Random enqueue (consecutive ints) / dequeue mix
    (``generator.clj:233-243``)."""

    def __init__(self):
        self._i = -1
        self._lock = threading.Lock()

    def op(self, test, process):
        if random.random() < 0.5:
            with self._lock:
                self._i += 1
                v = self._i
            return {"type": "invoke", "f": "enqueue", "value": v}
        return {"type": "invoke", "f": "dequeue", "value": None}


def queue_gen() -> QueueGen:
    return QueueGen()


class DrainQueue(Generator):
    """After the source is exhausted, emit enough dequeues to drain every
    attempted enqueue (``generator.clj:245-259``)."""

    def __init__(self, gen):
        self.gen = gen
        self._outstanding = 0
        self._lock = threading.Lock()

    def op(self, test, process):
        # draw + counter update under one lock: otherwise a thread that
        # sees the source exhausted can decrement before a concurrent
        # enqueue's increment lands and under-drain the queue
        with self._lock:
            o = op(self.gen, test, process)
            if o is not None:
                if o.get("f") == "enqueue":
                    self._outstanding += 1
                return o
            self._outstanding -= 1
            remaining = self._outstanding
        if remaining >= 0:
            return {"type": "invoke", "f": "dequeue", "value": None}
        return None


def drain_queue(gen) -> DrainQueue:
    return DrainQueue(gen)


class Limit(Generator):
    """Only n operations pass through (``generator.clj:261-267``)."""

    def __init__(self, n: int, gen):
        self.gen = gen
        self._life = n
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            if self._life <= 0:
                return None
            self._life -= 1
        return op(self.gen, test, process)


def limit(n: int, gen) -> Limit:
    return Limit(n, gen)


class TimeLimit(Generator):
    """Ops until dt seconds elapse, measured from the first draw
    (``generator.clj:269-279``)."""

    def __init__(self, dt: float, source):
        self.dt = dt
        self.source = source
        self._deadline = None
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            if self._deadline is None:
                self._deadline = _time.monotonic() + self.dt
        if _time.monotonic() <= self._deadline:
            return op(self.source, test, process)
        return None


def time_limit(dt: float, source) -> TimeLimit:
    return TimeLimit(dt, source)


class Filter(Generator):
    """Only ops satisfying pred; draws again otherwise
    (``generator.clj:281-290``)."""

    def __init__(self, pred, gen):
        self.pred = pred
        self.gen = gen

    def op(self, test, process):
        while True:
            o = op(self.gen, test, process)
            if o is None:
                return None
            if self.pred(o):
                return o


def filter_gen(pred, gen) -> Filter:
    return Filter(pred, gen)


class On(Generator):
    """Forward to the source iff ``f(thread)``; rebinds the visible
    thread set to the matching subset (``generator.clj:292-300``)."""

    def __init__(self, f, source):
        self.f = f
        self.source = source

    def op(self, test, process):
        thread = process_to_thread(test, process)
        if not self.f(thread):
            return None
        ts = current_threads()
        sub = [t for t in ts if self.f(t)] if ts is not None else None
        if sub is None:
            return op(self.source, test, process)
        with with_threads(sub):
            return op(self.source, test, process)


def on(f, source) -> On:
    return On(f, source)


class Reserve(Generator):
    """(reserve n1 gen1 n2 gen2 ... default): the first n1 threads draw
    from gen1, the next n2 from gen2, the rest from default; each subtree
    sees only its own threads (``generator.clj:302-339``)."""

    def __init__(self, *args):
        assert args, "reserve needs a default generator"
        *pairs, self.default = args
        assert len(pairs) % 2 == 0, "reserve takes count/gen pairs + default"
        self.ranges = []
        n = 0
        for i in range(0, len(pairs), 2):
            cnt, gen = pairs[i], pairs[i + 1]
            self.ranges.append((n, n + cnt, gen))
            n += cnt

    def op(self, test, process):
        threads = list(current_threads() or
                       range(test["concurrency"]))
        thread = process_to_thread(test, process)
        try:
            idx = threads.index(thread)
        except ValueError:
            idx = thread if isinstance(thread, int) else 0
        for lo, hi, gen in self.ranges:
            if idx < hi:
                with with_threads(threads[lo:hi]):
                    return op(gen, test, process)
        lo = self.ranges[-1][1] if self.ranges else 0
        with with_threads(threads[lo:]):
            return op(self.default, test, process)


def reserve(*args) -> Reserve:
    return Reserve(*args)


class Concat(Generator):
    """First non-None op from the sources, in order
    (``generator.clj:341-350``)."""

    def __init__(self, *sources):
        self.sources = sources

    def op(self, test, process):
        for s in self.sources:
            o = op(s, test, process)
            if o is not None:
                return o
        return None


def concat(*sources) -> Concat:
    return Concat(*sources)


def nemesis(nemesis_gen, client_gen=None):
    """Route the :nemesis process to nemesis_gen, others to client_gen
    (``generator.clj:352-360``)."""
    if client_gen is None:
        return on(lambda t: t == NEMESIS, nemesis_gen)
    return concat(on(lambda t: t == NEMESIS, nemesis_gen),
                  on(lambda t: t != NEMESIS, client_gen))


def clients(client_gen):
    """Only non-nemesis threads (``generator.clj:362-366``)."""
    return on(lambda t: t != NEMESIS, client_gen)


class Await(Generator):
    """Blocks (once) until fn returns, then defers to gen
    (``generator.clj:368-380``)."""

    def __init__(self, fn, gen=None):
        self.fn = fn
        self.gen = gen
        self._lock = threading.Lock()
        self._ready = False

    def op(self, test, process):
        with self._lock:
            if not self._ready:
                self.fn()
                self._ready = True
        return op(self.gen, test, process)


def await_fn(fn, gen=None) -> Await:
    return Await(fn, gen)


class Synchronize(Generator):
    """All routed threads must arrive before any proceeds; synchronizes
    once (``generator.clj:382-396``)."""

    def __init__(self, gen):
        self.gen = gen
        self._lock = threading.Lock()
        self._barrier = None
        self._clear = False

    def op(self, test, process):
        if not self._clear:
            with self._lock:
                if not self._clear and self._barrier is None:
                    n = len(current_threads() or [None])
                    def _clear_fn():
                        self._clear = True
                    self._barrier = threading.Barrier(n, action=_clear_fn)
                b = self._barrier
            if not self._clear and b is not None:
                b.wait()
        return op(self.gen, test, process)


def synchronize(gen) -> Synchronize:
    return Synchronize(gen)


def phases(*generators):
    """Like concat, but all threads finish each phase before the next
    begins (``generator.clj:402-424`` in spirit; barrier via
    :class:`Synchronize`)."""
    return concat(*[synchronize(g) for g in generators])


def then(a, b):
    """b, synchronize, then a — reads well under composition
    (``generator.clj:406-411``)."""
    return concat(b, synchronize(a))


class SingleThreaded(Generator):
    """Drawing an op requires an exclusive lock
    (``generator.clj:413-419``)."""

    def __init__(self, gen):
        self.gen = gen
        self._lock = threading.Lock()

    def op(self, test, process):
        with self._lock:
            return op(self.gen, test, process)


def singlethreaded(gen) -> SingleThreaded:
    return SingleThreaded(gen)


def barrier(gen):
    """When gen completes, synchronize, then None
    (``generator.clj:421-424``)."""
    return then(void, gen)
