"""Harness runtime — the test runner.

The semantics of ``jepsen/core.clj``: a test is a map; ``run`` sets up
OS/DB on every node, spawns ``concurrency`` single-threaded worker
processes plus a nemesis, draws ops from the generator, applies them
through clients, records invocations/completions into the history, then
checks the history (``core.clj:324-430``).

The load-bearing rule (``core.clj:178-200``): a worker whose op crashed
or returned ``info`` leaves the invocation pending forever and **retires
its process id** — the thread continues as ``process + concurrency``, so
the checker sees the old op as concurrent with everything after it.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from typing import Any, Callable, Dict, List, Optional

from ..checker.checkers import check_safe
from ..ops.op import Op
from . import client as client_ns
from . import db as db_ns
from . import generator as gen

log = logging.getLogger("comdb2_tpu.harness")

NEMESIS = gen.NEMESIS


class History:
    """Thread-safe op log (the reference's history atom)."""

    def __init__(self):
        self._ops: List[Op] = []
        self._lock = threading.Lock()

    def conj(self, op: Op) -> Op:
        with self._lock:
            self._ops.append(op)
        return op

    def snapshot(self) -> List[Op]:
        with self._lock:
            return list(self._ops)


def _op_from_dict(d: dict, process, t: int) -> Op:
    return Op(process=d.get("process", process),
              type=d["type"], f=d.get("f"), value=d.get("value"),
              time=t,
              extra={k: v for k, v in d.items()
                     if k not in ("process", "type", "f", "value", "time")})


def _as_dict(op: Any) -> dict:
    if isinstance(op, Op):
        d = {"type": op.type, "f": op.f, "value": op.value,
             "process": op.process}
        d.update(op.extra or {})
        return d
    return dict(op)


def log_op(op: Op) -> None:
    """Tab-separated op line (``util.clj:241-245``)."""
    log.info("%s\t%s\t%s\t%r", op.process, op.type, op.f, op.value)


class _Clock:
    """Relative wall-clock nanos from test start
    (``util.clj:227-239``)."""

    def __init__(self):
        self.t0 = _time.monotonic_ns()

    def __call__(self) -> int:
        return _time.monotonic_ns() - self.t0


def worker(test: dict, process: int, client: client_ns.Client,
           history: History, clock: _Clock) -> None:
    """One worker loop (``core.clj:141-201``)."""
    g = test["generator"]
    concurrency = test["concurrency"]
    # thread-local binding: each worker OS thread needs its own *threads*
    # (the reference's dynamic binding conveys into futures automatically;
    # threading.local does not)
    with gen.with_threads(_all_threads(test)):
        _worker_loop(test, g, concurrency, process, client, history, clock)


def _all_threads(test: dict) -> list:
    return [NEMESIS] + list(range(test["concurrency"]))


def _worker_loop(test, g, concurrency, process, client, history, clock):
    while True:
        d = gen.op(g, test, process)
        if d is None:
            return
        d = _as_dict(d)
        inv = _op_from_dict(d, process, clock())
        inv = inv.with_(process=process)
        log_op(inv)
        history.conj(inv)
        try:
            comp_d = _as_dict(client.invoke(test, _as_dict(inv)))
            comp = _op_from_dict(comp_d, process, clock())
            assert comp.process == inv.process, "client changed :process"
            assert comp.f == inv.f, "client changed :f"
            log_op(comp)
            history.conj(comp)
            if comp.type in ("ok", "fail"):
                continue            # process is free again
            process += concurrency  # hung: retire the process id
        except Exception as e:       # indeterminate — all bets off
            history.conj(inv.with_(
                type="info", time=clock(),
                extra={**inv.extra, "error": f"indeterminate: {e}"}))
            log.warning("process %s indeterminate: %s", process, e)
            process += concurrency


def nemesis_worker(test: dict, nemesis: client_ns.Client,
                   history: History, clock: _Clock) -> None:
    """The nemesis loop (``core.clj:203-248``): draws from the same
    generator as process :nemesis; ops must be type info; crashes are
    recorded, never fatal."""
    g = test["generator"]
    with gen.with_threads(_all_threads(test)):
        _nemesis_loop(test, g, nemesis, history, clock)


def _nemesis_loop(test, g, nemesis, history, clock):
    while True:
        d = gen.op(g, test, NEMESIS)
        if d is None:
            return
        d = _as_dict(d)
        inv = _op_from_dict(d, NEMESIS, clock()).with_(process=NEMESIS)
        history.conj(inv)
        try:
            log_op(inv)
            assert inv.type == "info", "nemesis ops must be :info"
            comp_d = _as_dict(nemesis.invoke(test, _as_dict(inv)))
            comp = _op_from_dict(comp_d, NEMESIS, clock())
            assert comp.f == inv.f and comp.process == NEMESIS
            assert comp.type == "info", \
                "nemesis completions must stay :info (can't affect the model)"
            log_op(comp)
            history.conj(comp)
        except Exception as e:
            history.conj(inv.with_(time=clock(),
                                   value=f"crashed: {e}"))
            log.warning("nemesis crashed evaluating %s: %s", inv, e)


def _on_nodes(test: dict, f: Callable[[dict, Any], None]) -> None:
    """Apply f(test, node) to every node in parallel, with each thread
    bound to that node's control session so DB/OS implementations can
    call control.exec_/su directly (``control.clj:310-319``)."""
    from .. import control

    if test.get("nodes"):
        control.on_nodes(test, f)


def run_case(test: dict) -> List[Op]:
    """Set up clients + nemesis, run workers to generator exhaustion,
    return the history (``core.clj:270-300``)."""
    history = History()
    clock = test["_clock"]
    concurrency = test["concurrency"]
    nodes = test.get("nodes") or []
    node_cycle = ([None] * concurrency if not nodes
                  else [nodes[i % len(nodes)] for i in range(concurrency)])

    clients = []
    try:
        for node in node_cycle:
            clients.append(test["client"].setup(test, node))
    except Exception:
        for c in clients:
            try:
                c.teardown(test)
            except Exception:
                pass
        raise

    nemesis = test.get("nemesis", client_ns.noop_nemesis).setup(test, None)
    try:
        nem_thread = threading.Thread(
            target=nemesis_worker, args=(test, nemesis, history, clock),
            name="nemesis", daemon=True)
        nem_thread.start()
        workers = []
        for pid, c in enumerate(clients):
            t = threading.Thread(target=worker,
                                 args=(test, pid, c, history, clock),
                                 name=f"worker {pid}", daemon=True)
            t.start()
            workers.append(t)
        for t in workers:
            t.join()
        nem_thread.join()
    finally:
        try:
            nemesis.teardown(test)
        finally:
            for c in clients:
                try:
                    c.teardown(test)
                except Exception:
                    pass
    return history.snapshot()


def snarf_logs(test: dict) -> None:
    """Download each node's SUT log files into the test's store dir
    (``core.clj:92-123``); best-effort."""
    from .. import control
    from . import store

    db = test.get("db")
    if not isinstance(db, db_ns.LogFiles) or not test.get("nodes"):
        return

    def snarf1(test_, node):
        for remote_path in db.log_files(test_, node):
            local = store.path_mkdirs(
                test_, str(node), remote_path.lstrip("/"))
            try:
                control.download(remote_path, local)
            except Exception as e:
                log.info("couldn't download %s from %s: %s",
                         remote_path, node, e)
    try:
        control.on_nodes(test, snarf1)
    except Exception as e:
        log.warning("log snarfing failed: %s", e)


def run(test: dict) -> dict:
    """Run a full test; returns the test map with ``history`` and
    ``results`` (``core.clj:324-430``). Lifecycle: os setup → db cycle →
    clients/nemesis/workers → history → log snarfing → teardown →
    check."""
    from . import store

    test = dict(test)
    test.setdefault("concurrency", max(len(test.get("nodes") or []), 1))
    test.setdefault("start-time", _time.strftime("%Y%m%dT%H%M%S"))
    test["_clock"] = _Clock()

    store.start_logging(test)
    try:
        os_ = test.get("os", db_ns.noop_os)
        db = test.get("db", db_ns.noop)
        _on_nodes(test, os_.setup)
        try:
            _on_nodes(test, lambda t, n: db_ns.cycle(db, t, n))
            if isinstance(db, db_ns.Primary) and test.get("nodes"):
                db.setup_primary(test, test["nodes"][0])
            try:
                threads = [NEMESIS] + list(range(test["concurrency"]))
                with gen.with_threads(threads):
                    history = run_case(test)
                test["history"] = history
            finally:
                # snarf before teardown, success or not — teardown can
                # kill/rotate the very logs needed to debug a failure
                snarf_logs(test)
                _on_nodes(test, db.teardown)
        finally:
            _on_nodes(test, os_.teardown)

        store.save_1(test)
        log.info("Analyzing")
        test["results"] = check_safe(test["checker"], test,
                                     test.get("model"), test["history"])
        log.info("Analysis complete")
        store.save_2(test)
        if test["results"].get("valid?") is True:
            log.info("Everything looks good!")
        else:
            log.info("Analysis invalid!")
        return test
    finally:
        test.pop("_clock", None)
        store.stop_logging(test)
