"""DB and OS lifecycle protocols (``jepsen/db.clj``, ``jepsen/os.clj``)."""

from __future__ import annotations

from typing import List, Optional


class DB:
    """Set up / tear down a database on a node (``db.clj:4-8``)."""

    def setup(self, test: dict, node) -> None:
        pass

    def teardown(self, test: dict, node) -> None:
        pass


class Primary:
    """One-time setup on a single (primary) node (``db.clj:10-11``)."""

    def setup_primary(self, test: dict, node) -> None:
        pass


class LogFiles:
    """Log paths to capture from a node at test end (``db.clj:13-14``)."""

    def log_files(self, test: dict, node) -> List[str]:
        return []


class NoopDB(DB):
    pass


noop = NoopDB()


def cycle(db: DB, test: dict, node) -> None:
    """Tear down (ignoring errors), then set up (``db.clj:17-25``)."""
    try:
        db.teardown(test, node)
    except Exception:
        pass
    db.setup(test, node)


class OS:
    """Operating-system prep/teardown on a node (``os.clj:4-8``)."""

    def setup(self, test: dict, node) -> None:
        pass

    def teardown(self, test: dict, node) -> None:
        pass


class NoopOS(OS):
    pass


noop_os = NoopOS()
