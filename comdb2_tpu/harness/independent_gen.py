"""Independent-key generators — lift single-key generators to keyed maps
(``jepsen/independent.clj:30-225``).

``sequential_generator``: one key at a time; when a key's generator is
exhausted, move to the next key.

``concurrent_generator``: n threads per key; the thread pool splits into
``thread_count // n`` groups, each group running one key's generator
with a rebound thread set (so per-key barriers work); when a group's
generator is exhausted, it takes the next key.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Iterator, Optional

from ..ops.kv import tuple_
from . import generator as gen


class SequentialGenerator(gen.Generator):
    """(``independent.clj:30-62``) — keys in order, values wrapped as
    (k, v) tuples."""

    def __init__(self, keys: Iterable, fgen: Callable[[Any], Any]):
        self._keys: Iterator = iter(keys)
        self.fgen = fgen
        self._lock = threading.Lock()
        self._cur_key = None
        self._cur_gen = None
        self._done = False
        self._advance()

    def _advance(self) -> None:
        try:
            self._cur_key = next(self._keys)
            self._cur_gen = self.fgen(self._cur_key)
        except StopIteration:
            self._done = True
            self._cur_gen = None

    def op(self, test, process):
        while True:
            with self._lock:
                if self._done:
                    return None
                k, g = self._cur_key, self._cur_gen
            o = gen.op(g, test, process)
            if o is not None:
                return {**o, "value": tuple_(k, o.get("value"))}
            with self._lock:
                if self._cur_key is k:      # nobody advanced before us
                    self._advance()


def sequential_generator(keys, fgen) -> SequentialGenerator:
    return SequentialGenerator(keys, fgen)


class ConcurrentGenerator(gen.Generator):
    """(``independent.clj:64-225``) — n threads per key, concurrent
    groups. Initializes lazily on the first call, asserting the visible
    thread set divides into groups of n; each group's subtree sees only
    its own threads (`*threads*` rebinding), so per-key synchronize
    barriers work."""

    def __init__(self, n: int, keys: Iterable,
                 fgen: Callable[[Any], Any]):
        assert n > 0 and int(n) == n
        self.n = int(n)
        self._keys: Iterator = iter(keys)
        self.fgen = fgen
        self._lock = threading.Lock()
        self._init = False
        self._threads: Optional[list] = None
        self._group_threads: Optional[list] = None
        self._active: Optional[list] = None   # per group: (k, gen) | None

    def _next_key(self):
        try:
            k = next(self._keys)
            return (k, self.fgen(k))
        except StopIteration:
            return None

    def _initialize(self, test) -> None:
        threads = [t for t in (gen.current_threads() or
                               range(test["concurrency"]))
                   if isinstance(t, int)]
        count = len(threads)
        assert count == test["concurrency"], (
            f"expected concurrency ({test['concurrency']}) integer "
            f"threads, got {count}")
        group_count = count // self.n
        assert group_count * self.n == count, (
            f"concurrent-generator has {count} threads but needs a "
            f"multiple of {self.n} to run {group_count} keys with "
            f"{self.n} threads apiece; adjust :concurrency")
        self._threads = threads
        self._group_threads = [threads[i * self.n:(i + 1) * self.n]
                               for i in range(group_count)]
        self._active = [self._next_key() for _ in range(group_count)]
        self._init = True

    def op(self, test, process):
        with self._lock:
            if not self._init:
                self._initialize(test)
        thread = gen.process_to_thread(test, process)
        assert isinstance(thread, int), (
            "only integer worker threads can draw from "
            f"concurrent-generator, not {thread!r}")
        group = self._threads.index(thread) // self.n
        while True:
            with self._lock:
                pair = self._active[group]
            if pair is None:
                return None
            k, g = pair
            with gen.with_threads(self._group_threads[group]):
                o = gen.op(g, test, process)
            if o is not None:
                return {**o, "value": tuple_(k, o.get("value"))}
            with self._lock:
                if self._active[group] is pair:   # don't race the swap
                    self._active[group] = self._next_key()


def concurrent_generator(n: int, keys, fgen) -> ConcurrentGenerator:
    return ConcurrentGenerator(n, keys, fgen)
