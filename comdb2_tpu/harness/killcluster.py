"""Kill-cluster diff-oracle test — crash-restart durability checking.

The reference (``killcluster/killclustertest.sh:36-84``) runs a scripted
2M-row transaction against the cluster while kill-9ing (or SIGSTOPing)
every node's SUT process mid-flight, then diffs the client's complete
output transcript against a deterministically generated oracle
(``generate_correct_out.py``). Re-designed SUT-agnostically: the
workload is any function producing a deterministic transcript through
retries; the disruptor kill-restarts the SUT on every node through the
control plane.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, List, Optional

from .. import control


def oracle(n_rows: int = 2_000_000) -> Iterable[str]:
    """The expected transcript of the scripted transaction — setup
    echoes, one line per row, commit echoes (the shape of
    ``generate_correct_out.py:1-16``)."""
    yield "[set transaction serializable] rc 0"
    yield "[begin] rc 0"
    for i in range(n_rows):
        yield f"(a={i})"
    yield "[commit] rc 0"


def scripted_workload(client, n_rows: int) -> Iterable[str]:
    """Default workload: drive ``client`` (a
    :class:`~comdb2_tpu.workloads.sqlish.Conn`) through the scripted
    transaction, emitting the oracle transcript only for work that
    actually committed; retries until it does."""
    from ..workloads.sqlish import with_txn_retries

    yield "[set transaction serializable] rc 0"
    yield "[begin] rc 0"

    def txn():
        with client.transaction() as t:
            existing = {r["a"] for r in t.select("killcluster")}
            for i in range(n_rows):
                if i not in existing:
                    t.insert("killcluster", {"a": i})

    with_txn_retries(txn)
    rows = [r["a"] for r in client.select("killcluster")]
    for a in sorted(rows)[:n_rows]:
        yield f"(a={a})"
    yield "[commit] rc 0"


def kill_restart_all(test: dict, process: str,
                     restart_cmd: Optional[str] = None,
                     stagger_s: float = 0.5) -> None:
    """kill -9 the SUT process on every node, then restart it
    (``killclustertest.sh:60``: restart under MALLOC_CHECK_)."""
    def kill1(test_, node):
        control.su("pkill", "-KILL", "-f", process, check=False)
        time.sleep(stagger_s)
        if restart_cmd:
            control.su(control.lit(restart_cmd), check=False)
    control.on_nodes(test, kill1)


def run(test: dict,
        workload: Callable[[], Iterable[str]],
        expected: Iterable[str],
        disrupt: Optional[Callable[[], None]] = None,
        disrupt_after_s: float = 1.0) -> dict:
    """Run the workload while (optionally) disrupting the cluster; diff
    the transcript against the oracle. Returns
    ``{"valid?", "diff": [first differing lines]}``."""
    lines: List[str] = []
    done = threading.Event()
    errors: List[BaseException] = []

    def drive():
        try:
            for line in workload():
                lines.append(line)
        except BaseException as e:
            errors.append(e)
        finally:
            done.set()

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    if disrupt is not None:
        time.sleep(disrupt_after_s)
        if not done.is_set():
            disrupt()
    t.join()

    diff = []
    expected = list(expected)
    for i in range(max(len(expected), len(lines))):
        want = expected[i] if i < len(expected) else "<missing>"
        got = lines[i] if i < len(lines) else "<missing>"
        if want != got:
            diff.append({"line": i, "expected": want, "got": got})
            if len(diff) >= 10:
                break
    out = {"valid?": not diff, "diff": diff,
           "lines": len(lines), "expected-lines": len(expected)}
    if errors:
        # a crashed client truncates the transcript, which always
        # diffs — that is not evidence of data loss; the verdict is
        # unknown either way, with the cause attached
        out["valid?"] = "unknown"
        out["error"] = repr(errors[0])
    return out
