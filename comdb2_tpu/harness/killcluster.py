"""Kill-cluster diff-oracle test — crash-restart durability checking.

The reference (``killcluster/killclustertest.sh:36-84``) runs a scripted
2M-row transaction against the cluster while kill-9ing (or SIGSTOPing)
every node's SUT process mid-flight, then diffs the client's complete
output transcript against a deterministically generated oracle
(``generate_correct_out.py``). Re-designed SUT-agnostically: the
workload is any function producing a deterministic transcript through
retries; the disruptor kill-restarts the SUT on every node through the
control plane.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, List, Optional

from .. import control


def oracle(n_rows: int = 2_000_000) -> Iterable[str]:
    """The expected transcript of the scripted transaction — setup
    echoes, one line per row, commit echoes (the shape of
    ``generate_correct_out.py:1-16``)."""
    yield "[set transaction serializable] rc 0"
    yield "[begin] rc 0"
    for i in range(n_rows):
        yield f"(a={i})"
    yield "[commit] rc 0"


def scripted_workload(client, n_rows: int) -> Iterable[str]:
    """Default workload: drive ``client`` (a
    :class:`~comdb2_tpu.workloads.sqlish.Conn`) through the scripted
    transaction, emitting the oracle transcript only for work that
    actually committed; retries until it does."""
    from ..workloads.sqlish import with_txn_retries

    yield "[set transaction serializable] rc 0"
    yield "[begin] rc 0"

    def txn():
        with client.transaction() as t:
            existing = {r["a"] for r in t.select("killcluster")}
            for i in range(n_rows):
                if i not in existing:
                    t.insert("killcluster", {"a": i})

    with_txn_retries(txn)
    rows = [r["a"] for r in client.select("killcluster")]
    for a in sorted(rows)[:n_rows]:
        yield f"(a={a})"
    yield "[commit] rc 0"


def kill_restart_all(test: dict, process: str,
                     restart_cmd: Optional[str] = None,
                     stagger_s: float = 0.5) -> None:
    """kill -9 the SUT process on every node, then restart it
    (``killclustertest.sh:60``: restart under MALLOC_CHECK_)."""
    def kill1(test_, node):
        control.su("pkill", "-KILL", "-f", process, check=False)
        time.sleep(stagger_s)
        if restart_cmd:
            control.su(control.lit(restart_cmd), check=False)
    control.on_nodes(test, kill1)


def run(test: dict,
        workload: Callable[[], Iterable[str]],
        expected: Iterable[str],
        disrupt: Optional[Callable[[], None]] = None,
        disrupt_after_s: float = 1.0) -> dict:
    """Run the workload while (optionally) disrupting the cluster; diff
    the transcript against the oracle. Returns
    ``{"valid?", "diff": [first differing lines]}``."""
    lines: List[str] = []
    done = threading.Event()
    errors: List[BaseException] = []

    def drive():
        try:
            for line in workload():
                lines.append(line)
        except BaseException as e:
            errors.append(e)
        finally:
            done.set()

    t = threading.Thread(target=drive, daemon=True)
    t.start()
    if disrupt is not None:
        time.sleep(disrupt_after_s)
        if not done.is_set():
            disrupt()
    t.join()

    diff = []
    expected = list(expected)
    for i in range(max(len(expected), len(lines))):
        want = expected[i] if i < len(expected) else "<missing>"
        got = lines[i] if i < len(lines) else "<missing>"
        if want != got:
            diff.append({"line": i, "expected": want, "got": got})
            if len(diff) >= 10:
                break
    out = {"valid?": not diff, "diff": diff,
           "lines": len(lines), "expected-lines": len(expected)}
    if errors:
        # a crashed client truncates the transcript, which always
        # diffs — that is not evidence of data loss; the verdict is
        # unknown either way, with the cause attached
        out["valid?"] = "unknown"
        out["error"] = repr(errors[0])
    return out


def cluster_kill_restart(procs, rounds: int = 2, pause_s: float = 0.3,
                         between_s: float = 1.5) -> Callable[[], None]:
    """Disruptor for the in-tree replicated SUT: kill -9 EVERY
    ``sut_node`` (no shutdown path — un-fsynced state dies), restart
    them from their state dirs, repeat. The killclustertest.sh:36-84
    shape against a :class:`~comdb2_tpu.workloads.tcp.ClusterProcs`."""
    def disrupt():
        for _ in range(rounds):
            procs.kill9_all()
            time.sleep(pause_s)
            procs.restart_all()
            time.sleep(between_s)
    return disrupt


def cluster_oracle(n_values: int) -> Iterable[str]:
    """Expected transcript of :func:`cluster_set_workload`: every add
    acknowledged exactly once, every acknowledged value present in the
    final committed read."""
    yield "[begin] rc 0"
    for i in range(n_values):
        yield f"[add {i}] rc 0"
    for i in range(n_values):
        yield f"(v={i})"
    yield "[commit] rc 0"


def cluster_set_workload(ports, n_values: int,
                         timeout_s: float = 0.5,
                         per_value_deadline_s: float = 20.0,
                         pace_s: float = 0.0):
    """Deterministic-transcript workload against a sut_node cluster:
    add values 0..n-1 through replay-nonce retries (each value is
    retried until one OK — exactly-once by dedup), then read the
    committed set back from the primary. A crash-restart in flight
    only delays an add; an add acked BEFORE a crash must still be in
    the final read — that is the durability contract under test."""
    import random as _random

    from ..workloads.tcp import ClusterControl, SutConnection

    session = _random.SystemRandom().getrandbits(32)

    def one_request(port, line):
        conn = SutConnection("127.0.0.1", port, timeout_s)
        try:
            conn.connect()
            return conn.request(line)
        finally:
            conn.close()

    def workload():
        yield "[begin] rc 0"
        ix = 0
        for i in range(n_values):
            nonce = (session << 24) | (i + 1)
            deadline = time.monotonic() + per_value_deadline_s
            rc = "?"
            while time.monotonic() < deadline:
                port = ports[ix % len(ports)]
                ix += 1
                try:
                    r = one_request(port, f"M {nonce} A {i}")
                except (TimeoutError, OSError):
                    time.sleep(0.05)
                    continue
                if r.startswith("OK"):
                    rc = "0"
                    break
                time.sleep(0.05)
            yield f"[add {i}] rc {rc}"
            if pace_s:
                # pace the stream so a disruptor's kill-restart lands
                # MID-RUN (a full-speed burst would finish before the
                # first kill and the test would exercise nothing)
                time.sleep(pace_s)
        # final committed read: wait for the cluster to settle, then
        # read the set from the current primary
        ctl = ClusterControl(ports, timeout_s=2.0)
        ctl.await_replicated(timeout_s=10.0)
        vals = []
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            # the primary's COMMITTED prefix must have caught up to
            # its applied log before the read counts (a freshly
            # elected post-restart primary commits the recovered tail
            # heartbeat-paced) — otherwise a correct cluster could
            # flakily read short and diff as a false loss
            pri_info = next((i for i in ctl.info()
                             if i["role"] == "primary"
                             and i.get("durable") == i.get("applied")),
                            None)
            if pri_info is None:
                time.sleep(0.1)
                continue
            try:
                r = one_request(ports[ctl.ports.index(
                    pri_info["port"])], "S")
            except (TimeoutError, OSError):
                time.sleep(0.1)
                continue
            if r.startswith("V"):
                vals = [int(x) for x in r[1:].split()]
                break
            time.sleep(0.1)
        # raw (not deduplicated): a double-applied add — the exact
        # anomaly the replay nonces exist to prevent — must show up
        # as a duplicate line and diff against the oracle
        for v in sorted(vals):
            yield f"(v={v})"
        yield "[commit] rc 0"

    return workload
