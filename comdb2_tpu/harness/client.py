"""Client protocol — applies operations to a system under test
(``jepsen/client.clj:4-20``)."""

from __future__ import annotations


class Client:
    """Three-method SUT client. ``setup`` returns a client specialized to
    a node; ``invoke`` turns an invocation op-dict into a completion
    op-dict (same f/process, type ok/fail/info); ``teardown`` releases
    resources."""

    def setup(self, test: dict, node) -> "Client":
        return self

    def invoke(self, test: dict, op: dict) -> dict:
        raise NotImplementedError

    def teardown(self, test: dict) -> None:
        pass


class Noop(Client):
    """Acknowledges everything (``client.clj:15-20``)."""

    def invoke(self, test, op):
        return {**op, "type": "ok"}


class PassThrough(Client):
    """Returns ops unchanged — the noop *nemesis* (``nemesis.clj:12-17``):
    nemesis invocations are ``info`` and must complete as ``info``, never
    ``ok``, or the history pairing breaks."""

    def invoke(self, test, op):
        return dict(op)


noop = Noop()
noop_nemesis = PassThrough()
