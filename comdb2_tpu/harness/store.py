"""Results store — persistence of histories and analyses.

Mirrors ``jepsen/store.clj``: every run persists a directory tree
``store/<name>/<start-time>/`` containing ``test.edn`` (the test map
minus function-valued keys), ``history.edn``, ``results.edn``, and
``jepsen.log``; ``latest`` symlinks point at the most recent run
(``store.clj:229-295``). Tests reload via :func:`load` and **re-check
offline** — analysis is replayable from the history artifact
(``store.clj:159-165``), which is the contract the TPU checker honors.
"""

from __future__ import annotations

import logging
import os
from typing import Any, List, Optional

from ..ops.edn import write_edn
from ..ops.history import parse_history, history_to_edn
from ..ops.op import Op

log = logging.getLogger("comdb2_tpu.harness")

# keys never serialized: live objects and runtime state
# (the reference's nonserializable-keys, store.clj:146-157)
NONSERIALIZABLE = ("db", "os", "net", "client", "checker", "nemesis",
                   "generator", "model", "_clock", "sessions", "remote")


def base_dir(test: dict) -> str:
    return test.get("store-root", "store")


def path(test: dict, *more: str) -> str:
    """store/<name>/<start-time>/<more...> (``store.clj:222-227``)."""
    return os.path.join(base_dir(test), str(test.get("name", "noname")),
                        str(test.get("start-time", "notime")), *more)


def artifact_dir(test, opts=None):
    """Where a checker may drop artifacts: opts dir > test dir > the
    test's store path (when the test is named and timed); None when no
    location is known. Shared by the SVG-on-failure renderer and the
    independent checker's per-key artifact writer."""
    base = (opts or {}).get("dir") or (test or {}).get("dir")
    if base is None and (test or {}).get("name") \
            and test.get("start-time"):
        base = path(test)
    return base


def path_mkdirs(test: dict, *more: str) -> str:
    p = path(test, *more)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    return p


def _edn_safe(x: Any) -> Any:
    """Coerce arbitrary result structures to EDN-writable values."""
    if isinstance(x, Op):
        return {str(k): _edn_safe(v) for k, v in x.to_map().items()}
    if isinstance(x, dict):
        return {_edn_safe(k): _edn_safe(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_edn_safe(v) for v in x]
    if isinstance(x, (set, frozenset)):
        return {_edn_safe(v) for v in x}
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    if hasattr(x, "item") and callable(getattr(x, "item", None)):
        try:
            return x.item()       # numpy scalars
        except Exception:
            pass
    return str(x)


def serializable_test(test: dict) -> dict:
    return {k: _edn_safe(v) for k, v in test.items()
            if k not in NONSERIALIZABLE and k != "history"
            and k != "results" and not k.startswith("_")}


def save_1(test: dict) -> None:
    """Write test map + history after the run (``store.clj:272-283``)."""
    with open(path_mkdirs(test, "test.edn"), "w") as fh:
        fh.write(write_edn(serializable_test(test)))
    hist: List[Op] = test.get("history") or []
    with open(path_mkdirs(test, "history.edn"), "w") as fh:
        fh.write(history_to_edn(hist))
    update_symlinks(test)


def save_2(test: dict) -> None:
    """Write results after analysis (``store.clj:285-295``)."""
    with open(path_mkdirs(test, "results.edn"), "w") as fh:
        fh.write(write_edn(_edn_safe(test.get("results") or {})))
    update_symlinks(test)


def load(test_name: str, start_time: str,
         store_root: str = "store") -> dict:
    """Reload a persisted test for offline re-checking
    (``store.clj:159-165``)."""
    from ..ops.edn import read_edn_all

    d = os.path.join(store_root, test_name, start_time)
    out: dict = {"name": test_name, "start-time": start_time,
                 "store-root": store_root}
    tpath = os.path.join(d, "test.edn")
    if os.path.exists(tpath):
        forms = read_edn_all(open(tpath).read())
        if forms:
            out.update({str(k): v for k, v in forms[0].items()})
    hpath = os.path.join(d, "history.edn")
    if os.path.exists(hpath):
        out["history"] = parse_history(open(hpath).read())
    rpath = os.path.join(d, "results.edn")
    if os.path.exists(rpath):
        forms = read_edn_all(open(rpath).read())
        if forms:
            out["results"] = forms[0]
    return out


def tests(test_name: str, store_root: str = "store") -> List[str]:
    """All persisted start-times for a test name, sorted."""
    d = os.path.join(store_root, test_name)
    if not os.path.isdir(d):
        return []
    return sorted(e for e in os.listdir(d)
                  if e not in ("latest",)
                  and os.path.isdir(os.path.join(d, e)))


def latest(test_name: str, store_root: str = "store") -> Optional[dict]:
    """Most recent run of a test (``repl.clj:6-13``)."""
    ts = tests(test_name, store_root)
    return load(test_name, ts[-1], store_root) if ts else None


def update_symlinks(test: dict) -> None:
    """point store/<name>/latest and store/latest at this run
    (``store.clj:229-241``)."""
    target = path(test)
    if not os.path.isdir(target):
        return
    for linkdir, rel in ((os.path.join(base_dir(test),
                                       str(test.get("name"))),
                          str(test.get("start-time"))),
                         (base_dir(test),
                          os.path.join(str(test.get("name")),
                                       str(test.get("start-time"))))):
        link = os.path.join(linkdir, "latest")
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(rel, link)
        except OSError:
            pass


def save_service_status(status: dict,
                        store_root: str = "store") -> str:
    """Persist a verifier-daemon status snapshot under
    ``store/service/`` next to the test runs — the store web browser
    (:mod:`.web`) serves the whole tree, so a long-running daemon's
    queue/latency/bucket metrics are browsable like any other
    artifact. Appends one JSON line per snapshot to ``status.jsonl``
    (a run's history) and rewrites ``latest.json`` (the current
    state); returns the latest path."""
    import json

    d = os.path.join(store_root, "service")
    os.makedirs(d, exist_ok=True)
    line = json.dumps(status, sort_keys=True)
    with open(os.path.join(d, "status.jsonl"), "a") as fh:
        fh.write(line + "\n")
    latest = os.path.join(d, "latest.json")
    tmp = latest + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(line + "\n")
    os.replace(tmp, latest)
    return latest


def save_shrink(minimal_edn: str, results: dict,
                svg: Optional[str] = None,
                store_root: str = "store",
                name: str = "shrink") -> str:
    """Persist a shrink run like a test run: ``store/<name>/<ts>/``
    with ``minimal.edn`` (the 1-minimal sub-history — re-checkable
    offline via ``filetest``, the same replayability contract as
    ``history.edn``), ``results.edn`` (the minimization stats, with
    ``valid?`` so the store web index color-codes the row like any
    other run) and, when given, the re-rendered counterexample
    ``shrink.svg``. Returns the run directory."""
    import time

    ts = (time.strftime("%Y%m%dT%H%M%S")
          + f"-{time.time_ns() % 1_000_000:06d}")
    test = {"name": name, "start-time": ts, "store-root": store_root}
    with open(path_mkdirs(test, "minimal.edn"), "w") as fh:
        fh.write(minimal_edn)
    with open(path_mkdirs(test, "results.edn"), "w") as fh:
        fh.write(write_edn(_edn_safe(results)))
    if svg is not None:
        with open(path_mkdirs(test, "shrink.svg"), "w") as fh:
            fh.write(svg)
    update_symlinks(test)
    return path(test)


_handlers: dict = {}


def start_logging(test: dict) -> None:
    """File logging into the test dir (``store.clj:301-311``)."""
    p = path_mkdirs(test, "jepsen.log")
    h = logging.FileHandler(p)
    h.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(threadName)s %(message)s"))
    logger = logging.getLogger("comdb2_tpu")
    logger.addHandler(h)
    if logger.level == logging.NOTSET:
        logger.setLevel(logging.INFO)
    _handlers[id(test)] = h


def stop_logging(test: dict) -> None:
    h = _handlers.pop(id(test), None)
    if h is not None:
        logging.getLogger("comdb2_tpu").removeHandler(h)
        h.close()
