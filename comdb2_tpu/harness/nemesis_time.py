"""Precision clock fault helpers (``jepsen/nemesis/time.clj``).

The reference uploads and compiles two tiny C programs on each node —
one bumps the clock by a millisecond offset, one strobes it between two
values at high frequency — then drives them over SSH. We ship equivalent
C sources (written fresh for this framework) and the same install/drive
API."""

from __future__ import annotations

import os
import tempfile

from .. import control

# minimal C helpers; installed to /opt/comdb2_tpu/ on each node
BUMP_TIME_C = r"""
/* bump-time: shift CLOCK_REALTIME by <ms> milliseconds. */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

int main(int argc, char **argv) {
  if (argc != 2) { fprintf(stderr, "usage: %s ms\n", argv[0]); return 2; }
  long long ms = atoll(argv[1]);
  struct timespec ts;
  if (clock_gettime(CLOCK_REALTIME, &ts)) { perror("gettime"); return 1; }
  long long ns = ts.tv_nsec + (ms % 1000) * 1000000LL;
  ts.tv_sec += ms / 1000 + ns / 1000000000LL;
  ts.tv_nsec = ns % 1000000000LL;
  if (ts.tv_nsec < 0) { ts.tv_nsec += 1000000000LL; ts.tv_sec -= 1; }
  if (clock_settime(CLOCK_REALTIME, &ts)) { perror("settime"); return 1; }
  return 0;
}
"""

STROBE_TIME_C = r"""
/* strobe-time: flip CLOCK_REALTIME between now and now+<delta>ms every
   <period>ms for <duration>ms. */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s delta_ms period_ms duration_ms\n", argv[0]);
    return 2;
  }
  long long delta = atoll(argv[1]), period = atoll(argv[2]),
            duration = atoll(argv[3]);
  struct timespec t0;
  clock_gettime(CLOCK_MONOTONIC, &t0);
  int up = 0;
  for (;;) {
    struct timespec now_m;
    clock_gettime(CLOCK_MONOTONIC, &now_m);
    long long elapsed = (now_m.tv_sec - t0.tv_sec) * 1000LL
                      + (now_m.tv_nsec - t0.tv_nsec) / 1000000LL;
    if (elapsed >= duration) break;
    struct timespec ts;
    clock_gettime(CLOCK_REALTIME, &ts);
    long long d = up ? -delta : delta;
    up = !up;
    long long ns = ts.tv_nsec + (d % 1000) * 1000000LL;
    ts.tv_sec += d / 1000 + ns / 1000000000LL;
    ts.tv_nsec = ns % 1000000000LL;
    if (ts.tv_nsec < 0) { ts.tv_nsec += 1000000000LL; ts.tv_sec -= 1; }
    clock_settime(CLOCK_REALTIME, &ts);
    usleep(period * 1000);
  }
  return 0;
}
"""

INSTALL_DIR = "/opt/comdb2_tpu"


def install(install_dir: str = INSTALL_DIR) -> None:
    """Upload + compile the helpers on the current session's node
    (``nemesis/time.clj:8-24``)."""
    control.su("mkdir", "-p", install_dir)
    for name, src in (("bump-time", BUMP_TIME_C),
                      ("strobe-time", STROBE_TIME_C)):
        with tempfile.NamedTemporaryFile("w", suffix=".c",
                                         delete=False) as fh:
            fh.write(src)
            local = fh.name
        try:
            control.upload(local, f"/tmp/{name}.c")
        finally:
            os.unlink(local)
        control.su("cc", "-O2", "-o", f"{install_dir}/{name}",
                   f"/tmp/{name}.c", "-lrt")


def bump_time(ms: float, install_dir: str = INSTALL_DIR) -> None:
    """Shift the clock by ms on the current node
    (``nemesis/time.clj:32-38``)."""
    control.su(f"{install_dir}/bump-time", str(int(ms)))


def strobe_time(delta_ms: float, period_ms: float, duration_s: float,
                install_dir: str = INSTALL_DIR) -> None:
    """Strobe the clock (``nemesis/time.clj:40-48``)."""
    control.su(f"{install_dir}/strobe-time", str(int(delta_ms)),
               str(int(period_ms)), str(int(duration_s * 1000)))


def reset_time() -> None:
    """Re-sync with NTP (``nemesis/time.clj:26-30``)."""
    control.su("ntpdate", "-p", "1", "-b", "pool.ntp.org", check=False)
