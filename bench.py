#!/usr/bin/env python3
"""Headline benchmark: linearizability-check throughput on one chip.

Two metrics, one JSON line each (headline LAST so a last-line parser
records it):

1. ``batch_check_ops_per_s_256x`` — 256 independent register histories
   streamed through the fused kernel as one batch (the independent-key
   batch axis, the framework's flagship parallelism).
2. ``linear_check_ops_per_s_50k`` — a 50k-op, 5-process cas-register
   history (the north-star config from BASELINE.md: knossos-CPU times
   out at 1 h on this; target < 60 s).

vs_baseline is the speedup over the reference envelope's implied
throughput at timeout (50,000 ops / 3600 s). Each line names the
``engine`` that actually ran (a silent fallback to the XLA engines is
a ~6x cliff — round-1 Weak #4/#6).
"""

from __future__ import annotations

import json
import random
import time

N_OPS = 50_000       # operations (invoke+completion pairs)
N_EVENTS = 2 * N_OPS  # history rows: each op contributes ~2 events
N_PROCS = 5          # C register workload: 5 threads (ctest/register.c:28)
BASELINE_OPS_S = N_OPS / 3600.0

B_HISTS = 256        # batch metric: independent histories per launch
B_EVENTS = 800       # events per batched history (~102k ops total)
N_RUNS = 7           # timed runs per metric (median-of-7 headline)


def _spread(n_ops: int, dts) -> dict:
    """min/median/max ops/s + run count: the tunnel's run-to-run
    variance spans ~20% (round-2 Weak #5 — without the spread a real
    regression is indistinguishable from noise in the artifact)."""
    import statistics

    per = sorted(n_ops / dt for dt in dts)
    return {
        "runs": len(per),
        "ops_per_s_min": round(per[0], 1),
        "ops_per_s_median": round(statistics.median(per), 1),
        "ops_per_s_max": round(per[-1], 1),
    }


def _median(n_ops: int, dts) -> float:
    """Headline = MEDIAN of the timed runs, not the max: best-of-N
    flatters the tunnel's variance (round-3 Weak #1)."""
    import statistics

    return statistics.median(n_ops / dt for dt in dts)


# headline medians of previous rounds' artifacts (BENCH_r0*.json);
# r1/r2 predate the spread fields so they carry the then-reported
# value (best-of-N — labeled, not silently mixed)
TREND_50K = {"r1_best": 85226.6, "r2_best": 80267.5,
             "r3_median": 70559.3, "r4_median": 63616.2}


def main() -> None:
    # every metric runs under the compile-surface guard: observed XLA
    # lowerings must stay inside the static program inventory
    # (PROGRAMS.md) — a recompile storm fails the bench instead of
    # hiding inside a slow run. COMDB2_TPU_COMPILE_GUARD=0 keeps the
    # report but drops the hard assert.
    from comdb2_tpu.analysis.compile_surface import static_inventory
    from comdb2_tpu.utils import compile_guard

    inv = static_inventory()
    g = compile_guard.CompileGuard().start()
    try:
        _main_metrics(guard=g, inventory=inv)
    finally:
        g.stop()
    if compile_guard.enabled():
        g.assert_closed(inv)


def _main_metrics(guard=None, inventory=None) -> None:
    try:
        _bench_batch()
    except Exception as e:
        print(json.dumps({
            "metric": "batch_check_ops_per_s_256x",
            "value": 0.0, "unit": "ops/s", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
    try:
        _bench_batch_4096()
    except Exception as e:
        print(json.dumps({
            "metric": "batch_check_ops_per_s_4096x",
            "value": 0.0, "unit": "ops/s", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
    try:
        _run_bench_p10()
    except Exception as e:
        print(json.dumps({
            "metric": "linear_check_ops_per_s_50k_p10",
            "value": 0.0, "unit": "ops/s", "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
    try:
        _run_bench(guard=guard, inventory=inventory)
    except Exception as e:          # one JSON line, even on failure
        print(json.dumps({
            "metric": "linear_check_ops_per_s_50k",
            "value": 0.0,
            "unit": "ops/s",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
        raise SystemExit(1)


def _bench_batch() -> None:
    """256 independent histories, one streamed device dispatch."""
    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.checker.batch import check_batch, pack_batch
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.synth import register_history

    rng = random.Random(7)
    t0 = time.perf_counter()
    hs = [register_history(rng, n_procs=N_PROCS, n_events=B_EVENTS,
                           values=5, p_info=0.0)
          for _ in range(B_HISTS)]
    t_parse = time.perf_counter() - t0
    n_ops = sum(1 for h in hs for op in h if op.type == "invoke")
    t0 = time.perf_counter()
    batch = pack_batch(hs, cas_register())
    t_pack = time.perf_counter() - t0
    host_pack_s = t_parse + t_pack

    info: dict = {}
    status, _, _ = check_batch(batch, F=256, info=info)   # compile
    assert (status == LJ.VALID).all(), status
    dts = []
    for _ in range(N_RUNS):         # best-of-N: tunnel variance
        t0 = time.perf_counter()
        check_batch(batch, F=256, info=info)
        dts.append(time.perf_counter() - t0)
    import statistics

    ops_s = _median(n_ops, dts)
    dev_median = statistics.median(dts)
    print(json.dumps({
        "metric": "batch_check_ops_per_s_256x",
        "value": round(ops_s, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_s / BASELINE_OPS_S, 2),
        "engine": info.get("engine"),
        "histories": B_HISTS,
        "ops": n_ops,
        "host_pack_s": round(host_pack_s, 2),
        "host_pack_stages_s": {"parse": round(t_parse, 2),
                               "pack": round(t_pack, 2)},
        "end_to_end_ops_per_s": round(
            n_ops / (host_pack_s + dev_median), 1),
        **_spread(n_ops, dts),
    }))


def _bench_batch_4096() -> None:
    """BASELINE.json config 5 — the batch north-star shape: 4096
    INDEPENDENT register histories x 2k ops checked as one sharded
    launch (single chip here; the 8-device placement is validated by
    ``dryrun_multichip``). Every history is distinct (round-4 Weak #3:
    tiling 256 x16 warmed caches with duplicate data).

    The host ingest is COLUMNAR since round 6 (the per-op path
    measured ``host_pack_s = 278.2`` in BENCH_r05 against ~70 s of
    device time — 4:1 host-bound): generation + packing run as
    whole-batch array ops (``ops.synth_columnar``), segmenting and
    slot renaming as vectorized batch passes. ``host_pack_s`` reports
    the one-time host cost broken into parse(gen)/pack/segment/remap
    stages so the trend shows where the next host bottleneck is; each
    timed run (``device_run_s``) covers stream chunk packing, tunnel
    transfer, and device execution — all 4096 histories share one
    compiled program by construction. ``end_to_end_*`` additionally
    times a COLD ``check_batch`` on a fresh identical batch, where the
    pipelined dispatch overlaps the host segment pass of slice i+1
    with the device run of slice i (the acceptance target: within
    1.3x of the device-only wall time)."""
    import statistics

    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()

    import numpy as np

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.checker.batch import (_stream_segments, check_batch,
                                          pack_batch)
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops import synth_columnar as SC

    B, EVENTS = 4096, 4000                    # 2k ops per history
    # single-process on purpose: this container exposes ONE CPU
    # (mp.cpu_count() == 1 — a spawn pool measured 322 s -> 566 s,
    # pure IPC overhead); the columnar path wins by vectorizing, not
    # by parallelism
    t0 = time.perf_counter()
    cols = SC.register_batch_columns(11_000_000, B, EVENTS // 2,
                                     n_procs=N_PROCS, values=5)
    t_parse = time.perf_counter() - t0
    t0 = time.perf_counter()
    packeds = SC.pack_register_columns(cols)
    batch = pack_batch(packeds, cas_register(), build_streams=False)
    t_pack = time.perf_counter() - t0
    from comdb2_tpu.ops.op import INVOKE
    n_ops = sum(int((p.type == INVOKE).sum()) for p in packeds)
    t0 = time.perf_counter()
    for p in packeds:             # segment pass, cached per history
        p._segments_exact = LJ.make_segments(p)
    t_segment = time.perf_counter() - t0
    t0 = time.perf_counter()
    _stream_segments(batch)       # union remap + batched slot renaming
    t_remap = time.perf_counter() - t0
    host_pack_s = t_parse + t_pack + t_segment + t_remap

    info: dict = {}
    status, _, _ = check_batch(batch, F=128, info=info)   # compile
    assert (np.asarray(status) == LJ.VALID).all(), status
    dts = []
    # median-of-3: one tunnel stall (observed: a 290 s run beside two
    # 65 s ones) must not poison the headline; the min/max spread
    # fields still expose it
    for _ in range(3):
        t0 = time.perf_counter()
        check_batch(batch, F=128, info=info)
        dts.append(time.perf_counter() - t0)
    dev_median = statistics.median(dts)
    # cold end-to-end: fresh identical batch, no caches — the
    # pipelined stream path overlaps host pack with device compute
    # (programs are warm from the runs above, so this isolates the
    # ingest overlap, not compile time)
    t0 = time.perf_counter()
    packeds2 = SC.register_batch_packed(11_000_000, B, EVENTS // 2,
                                        n_procs=N_PROCS, values=5)
    batch2 = pack_batch(packeds2, cas_register(), build_streams=False)
    status2, _, _ = check_batch(batch2, F=128)
    e2e_cold_s = time.perf_counter() - t0
    assert (np.asarray(status2) == LJ.VALID).all(), status2
    ops_s = _median(n_ops, dts)
    print(json.dumps({
        "metric": "batch_check_ops_per_s_4096x",
        "value": round(ops_s, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_s / BASELINE_OPS_S, 2),
        "engine": info.get("engine"),
        "histories": B,
        "distinct_histories": B,
        "ops": n_ops,
        "host_pack_s": round(host_pack_s, 1),
        "host_pack_stages_s": {
            "parse": round(t_parse, 2), "pack": round(t_pack, 2),
            "segment": round(t_segment, 2),
            "remap": round(t_remap, 2)},
        "host_pack_s_r05_per_op": 278.2,
        "device_run_s": [round(d, 1) for d in dts],
        "end_to_end_ops_per_s": round(
            n_ops / (host_pack_s + dev_median), 1),
        "end_to_end_cold_s": round(e2e_cold_s, 1),
        "end_to_end_vs_device": round(e2e_cold_s / dev_median, 2),
        **_spread(n_ops, dts),
    }))


def _run_bench_p10() -> None:
    """The reference register test's concurrency (10 threads,
    comdb2/core.clj:567-613) at the 50k-op scale. Slot renaming
    (``remap_slots``, round 5) maps the 10 process ids onto the
    history's max concurrent open calls (max_pending 5 -> 5 slots), so
    this runs the fused kernel's fast (8,128)/2-word tier instead of
    the (16,128)/3-word one that previously made p10 ~30% slower than
    p5 (round-4 Weak #4). max_pending bounds in-flight depth the way a
    real cluster's ms-scale completions do."""
    import random as _random

    import jax

    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.checker import pallas_seg as PSEG
    from comdb2_tpu.models.memo import memo as make_memo
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.packed import pack_history
    from comdb2_tpu.ops.synth import register_history

    rng = _random.Random(1010)
    # max_pending 5: in-flight depth 6 pushes worst segments past the
    # kernel's F=128 frontier (honest UNKNOWN -> XLA fallback)
    history = register_history(rng, n_procs=10, n_events=N_EVENTS,
                               values=5, p_info=0.0, max_pending=5)
    packed = pack_history(history)
    n_ops = sum(1 for op in history if op.type == "invoke")
    mm = make_memo(cas_register(), packed)
    # production slot renaming (linear._analyze_device does the same):
    # 10 processes, <=5 concurrent open calls -> 5 slots, even-bucketed
    # to 6 -> the (8,128)/2-word kernel tier
    segs, P_eff = LJ.remap_slots(LJ.make_segments(packed))
    P = max(P_eff + (P_eff & 1), 2)
    sizes = dict(n_states=mm.n_states, n_transitions=mm.n_transitions)
    engine = {"e": None}
    use_fused = PSEG.available()

    def run():
        if use_fused:
            r = PSEG.check_device_pallas(mm.succ, segs, P=P, **sizes)
            if r is not None and r[0] != LJ.UNKNOWN:
                engine["e"] = "pallas-fused"
                return r[0]
        succ = LJ.pad_succ(mm.succ, 8, 64)
        status, _, _ = LJ.check_device_seg2(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
            F=256, Fs=32, P=P, **sizes)
        jax.block_until_ready(status)
        engine["e"] = "xla-seg2"
        return int(status)

    status = run()
    assert status == LJ.VALID, f"p10 bench misjudged: status={status}"
    if jax.default_backend() not in ("cpu",):
        assert engine["e"] == "pallas-fused", (
            f"fused kernel did not serve the p10 bench: {engine['e']}")
    dts = []
    for _ in range(N_RUNS):
        t0 = time.perf_counter()
        run()
        dts.append(time.perf_counter() - t0)
    ops_s = _median(n_ops, dts)
    # mean closure depth: the kernel's per-segment cost is ~linear in
    # the pending count, and this history's is ~24% deeper than the
    # p5 one's (3.68 vs 2.96) — the residual p10-vs-p5 gap is that
    # workload depth, not tier overhead (both run the same
    # (8,128)/2-word tier since slot renaming)
    d = segs.depth[segs.ok_proc >= 0]
    print(json.dumps({
        "metric": "linear_check_ops_per_s_50k_p10",
        "value": round(ops_s, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_s / BASELINE_OPS_S, 2),
        "engine": engine["e"],
        "effective_slots": P_eff,
        "mean_closure_depth": round(float(d.mean()), 3),
        **_spread(n_ops, dts),
    }))


def _run_bench(guard=None, inventory=None) -> None:
    import jax

    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.models.memo import memo as make_memo
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.packed import pack_history
    from comdb2_tpu.ops.synth import register_history

    rng = random.Random(42)
    history = register_history(rng, n_procs=N_PROCS, n_events=N_EVENTS,
                               values=5, p_info=0.0)
    packed = pack_history(history)
    n_ops = sum(1 for op in history if op.type == "invoke")
    mm = make_memo(cas_register(), packed)
    succ = LJ.pad_succ(mm.succ, 64, 64)
    # production slot renaming + even-bucketed slot width (see
    # linear._analyze_device) and the production engines: the fused
    # Pallas kernel (the whole segment loop in one kernel per
    # 1024-segment chunk, F=128) with the adaptive two-tier XLA engine
    # as fallback. F=128 covers this history's measured worst segment
    # (88 configs).
    segs, P_eff = LJ.remap_slots(LJ.make_segments(packed))
    F, Fs, P = 128, 32, max(P_eff + (P_eff & 1), 2)
    sizes = dict(n_states=mm.n_states, n_transitions=mm.n_transitions)

    from comdb2_tpu.checker import pallas_seg as PSEG
    use_fused = PSEG.spec_for(mm.n_states, mm.n_transitions, P,
                              segs.inv_proc.shape[1]) is not None

    engine = {"e": None}

    def run():
        if use_fused:
            r = PSEG.check_device_pallas(mm.succ, segs, P=P, **sizes)
            # overflow falls back to the XLA engine, like production
            if r is not None and r[0] != LJ.UNKNOWN:
                engine["e"] = "pallas-fused"
                return r[0]
        status, fail_seg, n = LJ.check_device_seg2(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
            F=F, Fs=Fs, P=P, **sizes)
        jax.block_until_ready(status)
        engine["e"] = "xla-seg2"
        return int(status)

    status = run()                        # compile + sanity
    assert status == LJ.VALID, f"bench history misjudged: status={status}"
    # a silent demotion to the XLA engines is a ~6x cliff; on real TPU
    # hardware that is a kernel regression and must FAIL the bench, not
    # just flip a field (round-3 Weak #5)
    if jax.default_backend() not in ("cpu",):
        assert engine["e"] == "pallas-fused", (
            f"fused kernel did not serve the bench on "
            f"{jax.default_backend()}: engine={engine['e']}")
    dts = []
    for _ in range(N_RUNS):               # spread: tunnel variance
        t0 = time.perf_counter()
        run()
        dts.append(time.perf_counter() - t0)

    ops_s = _median(n_ops, dts)
    trend = dict(TREND_50K, r5_median=round(ops_s, 1))
    d = segs.depth[segs.ok_proc >= 0]
    line = {
        "metric": "linear_check_ops_per_s_50k",
        "value": round(ops_s, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_s / BASELINE_OPS_S, 2),
        "engine": engine["e"],
        "effective_slots": P_eff,
        "mean_closure_depth": round(float(d.mean()), 3),
        "trend": trend,
        **_spread(n_ops, dts),
    }
    if guard is not None:
        # embedded here so the headline stays the LAST line (the
        # last-line parser contract) while still carrying the guard's
        # verdict over every metric that ran before it
        line["compile_guard"] = guard.summary(inventory)
    print(json.dumps(line))


if __name__ == "__main__":
    main()
