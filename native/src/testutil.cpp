#include "comdb2_tpu/testutil.h"

#include <cstdarg>
#include <ctime>

#include <sys/time.h>
#include <pthread.h>

extern "C" {

uint64_t ct_timems(void) {
    struct timeval tv;
    gettimeofday(&tv, nullptr);
    return (uint64_t)tv.tv_sec * 1000ull + tv.tv_usec / 1000;
}

uint64_t ct_timeus(void) {
    struct timeval tv;
    gettimeofday(&tv, nullptr);
    return (uint64_t)tv.tv_sec * 1000000ull + tv.tv_usec;
}

void ct_tdprintf(FILE *f, const char *fn, int line, const char *fmt, ...) {
    char prefix[128];
    struct timeval tv;
    gettimeofday(&tv, nullptr);
    struct tm tm;
    localtime_r(&tv.tv_sec, &tm);
    snprintf(prefix, sizeof prefix,
             "[%02d:%02d:%02d.%03d thd %#lx %s:%d] ", tm.tm_hour,
             tm.tm_min, tm.tm_sec, (int)(tv.tv_usec / 1000),
             (unsigned long)pthread_self(), fn, line);
    va_list ap;
    va_start(ap, fmt);
    flockfile(f);
    fputs(prefix, f);
    vfprintf(f, fmt, ap);
    funlockfile(f);
    va_end(ap);
}

}  /* extern "C" */
