#include "comdb2_tpu/testutil.h"

#include <cerrno>
#include <cstdarg>
#include <cstring>
#include <ctime>

#include <netdb.h>
#include <netinet/in.h>
#include <pthread.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

extern "C" {

uint64_t ct_timems(void) {
    struct timeval tv;
    gettimeofday(&tv, nullptr);
    return (uint64_t)tv.tv_sec * 1000ull + tv.tv_usec / 1000;
}

uint64_t ct_timeus(void) {
    struct timeval tv;
    gettimeofday(&tv, nullptr);
    return (uint64_t)tv.tv_sec * 1000000ull + tv.tv_usec;
}

void ct_tdprintf(FILE *f, const char *fn, int line, const char *fmt, ...) {
    char prefix[128];
    struct timeval tv;
    gettimeofday(&tv, nullptr);
    struct tm tm;
    localtime_r(&tv.tv_sec, &tm);
    snprintf(prefix, sizeof prefix,
             "[%02d:%02d:%02d.%03d thd %#lx %s:%d] ", tm.tm_hour,
             tm.tm_min, tm.tm_sec, (int)(tv.tv_usec / 1000),
             (unsigned long)pthread_self(), fn, line);
    va_list ap;
    va_start(ap, fmt);
    flockfile(f);
    fputs(prefix, f);
    vfprintf(f, fmt, ap);
    funlockfile(f);
    va_end(ap);
}

int ct_tcp_request(const char *host, int port, const char *line,
                   int timeout_ms, char *reply, int reply_cap) {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    char portbuf[16];
    snprintf(portbuf, sizeof portbuf, "%d", port);
    if (getaddrinfo(host, portbuf, &hints, &res) != 0 || res == nullptr)
        return -1;
    int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    int out = -1;
    if (fd >= 0) {
        timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
        setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
        if (connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
            out = -2;   /* connected: a failure past here means the
                         * request MAY have been delivered */
            size_t len = strlen(line);
            bool sent = true;
            size_t off = 0;
            while (off < len) {
                ssize_t w = write(fd, line + off, len - off);
                if (w < 0) {
                    if (errno == EINTR) continue;
                    sent = false;
                    break;
                }
                off += (size_t)w;
            }
            if (sent && write(fd, "\n", 1) == 1) {
                int n = 0;
                bool got_nl = false;
                char c;
                while (n < reply_cap - 1) {
                    ssize_t r = read(fd, &c, 1);
                    if (r < 0 && errno == EINTR) continue;
                    if (r <= 0) break;
                    if (c == '\n') {
                        got_nl = true;
                        break;
                    }
                    reply[n++] = c;
                }
                reply[n] = 0;
                /* a reply is complete only at its newline: a recv
                 * timeout, mid-line EOF, or a cap-filling line would
                 * otherwise hand back a truncated "V 12" for "V 123"
                 * as success — a fabricated wrong read under exactly
                 * the faults the harness injects. Incomplete stays -2
                 * (indeterminate: the request WAS delivered). */
                out = got_nl ? n : -2;
            }
        }
        close(fd);
    }
    freeaddrinfo(res);
    return out;
}

}  /* extern "C" */
