/* sut_node — one node of a replicated register/set SUT cluster with
 * leader election.
 *
 * The in-tree stand-in for the reference's 5-node comdb2 cluster in its
 * linearizable configuration (linearizable/linearizable.lrl:1-17):
 * a primary ships a totally-ordered, term-tagged op log to replicas
 * and, in durable mode, acknowledges a write only after a MAJORITY of
 * nodes hold it — the durable-LSN rule of bdb/rep.c:2096 ("client
 * writes aren't done until a majority has them").
 *
 * Election (the role of bdb/rep.c:408-520's vote machinery +
 * rep.c:429 is_electable): when a node stops hearing from the leader
 * for its election timeout it campaigns with term+1; peers grant one
 * vote per term and only to candidates whose (last_term, last_lsn) is
 * at least as up to date as their own log, so a new leader always
 * holds every majority-acked write. A leader that loses contact with
 * a majority for the lease window DEMOTES itself (the coherency-lease
 * role of bdb/rep.c:639-654) and, in durable mode, refuses local reads
 * once its lease is stale — a partitioned old primary can neither ack
 * writes nor serve stale reads. On winning, a leader appends a no-op
 * entry so the durable LSN can advance in its own term (entries are
 * only counted toward durability in the term that created them).
 * Divergent uncommitted suffixes on a rejoining old primary are
 * truncated by the log-matching check in the replication stream.
 *
 * Persistence (-d dir): an fsync'd append-only log + term/vote meta
 * (the berkdb txn-log role). Every entry hits disk before it is acked
 * upstream or counted toward durability, so kill -9 of an acked
 * write's entire cohort never loses the write; recovery replays the
 * log and the node rejoins as a replica (its pre-crash leadership is
 * stale until an election says otherwise).
 *
 * Negative controls:
 *   --no-durable (-N): writes acked after local apply only — a
 *     partition yields real stale reads / lost writes.
 *   --split-brain (-B): a leader that loses quorum neither demotes nor
 *     waits for majority acks — two primaries accept writes and their
 *     registers diverge; the checker must flag the history INVALID.
 *   --no-fsync (-x): log writes sit in a userspace buffer — kill -9
 *     loses the acked tail and the set/linearizable checkers must
 *     catch the loss.
 *   --bad-lease (-L): lease freshness runs on the node's scramblable
 *     wall clock instead of monotonic deltas — the K clock nemesis
 *     can then stretch a deposed leader's dead lease into serving
 *     stale reads.
 *
 * Topology: all nodes on 127.0.0.1, one port each; node 0 is the
 * initial leader (term 1) so fault-free startup needs no election.
 *
 * Client protocol (line-based, same shapes as sut_server):
 *   R [k]      -> "V <int>" | "NIL" | "UNKNOWN"   read key k (dflt 1)
 *   W [k] <v>  -> "OK <lsn>" | "UNKNOWN"          write
 *   C [k] <a> <b> -> "OK <lsn>" | "FAIL" | "UNKNOWN"   cas
 *   A <v>      -> "OK <lsn>" | "UNKNOWN"          set add
 *   M <nonce> <W|C|A ...> -> same replies         retry-safe mutation:
 *                 the nonce is logged with the entry (replicated, like
 *                 bdb blkseq), so a retried request that already
 *                 applied returns its recorded outcome instead of
 *                 re-executing; --no-dedup (-D) disables the lookup —
 *                 the negative control where a retried cas re-executes
 *                 and double-applies
 *   S          -> "V <v1> ..."                    set read (local)
 *   TB / TR / TP / TW / TI / TC / TA              transactions over
 *                 the wire: begin, committed read, predicate read,
 *                 buffered write/insert, OCC-validated commit, abort
 *                 (grammar at the handler; the comdb2 osql shape —
 *                 reads record versions, commit validates and applies
 *                 atomically at the leader, db/toblock.c:1953's role).
 *                 --buggy-txn (-T) commits WITHOUT validation — the
 *                 lost-update / G2 negative control
 *   P          -> "PONG"
 *   K [ms]     -> "OK"   set/reset this node's wall-clock offset (the
 *                 in-tree clock scrambler; harmless unless --bad-lease
 *                 (-L) makes the lease math consume the wall clock)
 *   I          -> "I <id> <role> <applied> <durable> <term> <leader>"
 *   B <peer>   -> "OK"   drop traffic with node <peer>  (partition)
 *   U <peer>   -> "OK"   heal one peer;  "U" alone heals all
 * Inter-node:
 *   F <from> <cmd...>          forwarded client op (dropped if blocked)
 *   E <from> <term> <lsn> <eterm> <pterm> <op...> -> "A <lsn>" | "N <term>"
 *   H <from> <term> <durable>  -> "A <applied>" | "N <term>"   heartbeat
 *   V <from> <term> <last_lsn> <last_term> -> "G <term> <0|1>"  vote req
 *
 * Mutation replies carry the commit LSN so HA clients can fold their
 * own acknowledged writes into the snapshot-LSN gate (the cdb2api
 * snapshot_file/snapshot_lsn role, cdb2api.c:618-656).
 *
 * SQL text surface: any line whose first word is a SQL keyword
 * (SELECT/INSERT/UPDATE/BEGIN/COMMIT/ROLLBACK/SET) is parsed
 * per-connection into these verbs by sql_front.cpp — the
 * dispatch_sql_query role (db/sqlinterfaces.c:5970); grammar and
 * reply shapes documented in comdb2_tpu/sql_front.h. The ct_sql
 * mini-shell (sql_main.cpp) drives it interactively.
 */
#include "comdb2_tpu/sql_front.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <netdb.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

long long mono_ms() {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
}

/* one write inside a transaction: 'W' reg k=a; 'I' insert (id=a,
 * val=b) into table t (0='a', 1='b') under key k */
struct SubOp {
    char kind = 'W';
    long long t = 0, k = 0, a = 0, b = 0;
};

struct LogEntry {
    long long term = 0;
    char kind = 'N';        /* 'W','C','A','N'(no-op),'T'(txn) */
    long long key = 0, a = 0, b = 0;
    unsigned long long nonce = 0;   /* client replay nonce; 0 = none */
    std::vector<SubOp> ops;         /* kind 'T' only */
};

/* the replicated state machine — two instances per node: SPECULATIVE
 * (whole log applied; feeds cas/txn-validation, which is safe because
 * log order = serial order) and COMMITTED (durable prefix only; feeds
 * reads). Versions are the lsn of the last write (registers) / the
 * row count (insert-only tables) — what OCC validation compares. */
struct StateMachine {
    std::map<long long, long long> regs;
    std::map<long long, long long> reg_ver;
    std::vector<long long> set_vals;
    std::map<std::pair<int, long long>,
             std::vector<std::pair<long long, long long>>> tables;

    void apply(const LogEntry &e, long long lsn) {
        if (e.kind == 'W') {
            regs[e.key] = e.a;
            reg_ver[e.key] = lsn;
        } else if (e.kind == 'C') {
            /* CAS entries are logged only when they applied */
            regs[e.key] = e.b;
            reg_ver[e.key] = lsn;
        } else if (e.kind == 'A') {
            set_vals.push_back(e.a);
        } else if (e.kind == 'T') {
            for (const SubOp &s : e.ops) {
                if (s.kind == 'W') {
                    regs[s.k] = s.a;
                    reg_ver[s.k] = lsn;
                } else if (s.kind == 'I') {
                    tables[{(int)s.t, s.k}].push_back({s.a, s.b});
                }
            }
        }                               /* 'N' no-op: nothing */
    }
};

enum Role { REPLICA = 0, CANDIDATE = 1, PRIMARY = 2 };

struct Node {
    int id = 0;
    bool durable = true;
    bool split_brain = false;   /* negative control: never demote */
    bool no_dedup = false;      /* negative control: replay re-executes */
    bool no_fsync = false;      /* negative control: acked writes live
                                 * in a userspace buffer only — kill -9
                                 * loses the tail (with fsync on, every
                                 * entry is on disk before it is acked
                                 * or counted toward durability) */
    bool bad_lease = false;     /* negative control: lease freshness is
                                 * computed from the node's WALL clock
                                 * (mono + settable offset) instead of
                                 * monotonic deltas — the clock
                                 * scrambler can then stretch a stale
                                 * lease and a deposed leader serves
                                 * stale reads (the coherency-lease
                                 * clock sensitivity of
                                 * bdb/rep.c:639-654) */
    long long clock_offset_ms = 0;  /* the in-tree "date -s": set by
                                     * the K verb (clock nemesis) */
    std::string dir;            /* state directory; empty = in-memory */
    FILE *log_fp = nullptr;
    /* group commit: appends only buffer the log line under the lock;
     * a syncer thread fsyncs OUTSIDE the lock and one fsync covers
     * every entry buffered while the previous one ran. Nothing is
     * acked upstream or counted toward durability past synced_lsn, so
     * the crash contract is unchanged — per-entry fsync under the
     * global mutex stalled every handler/heartbeat behind the disk
     * (round-3 review finding). */
    long long synced_lsn = 0;
    long long io_gen = 0;       /* bumped by every log rewrite: a
                                 * syncer target captured before a
                                 * rewrite must not mark the rewritten
                                 * file's buffered tail as synced */
    std::mutex io_mu;           /* guards log_fp swap (rewrite) vs the
                                 * syncer's out-of-lock flush */

    /* group commit active? (the -x control keeps its buffered-only
     * semantics: nothing syncs, and durability counting intentionally
     * ignores the disk — that's the bug the control injects) */
    bool syncing() const { return log_fp != nullptr && !no_fsync; }

    /* what this node may ack upstream: the certified prefix, clamped
     * to what is ON DISK when persistence is real */
    long long ack_locked() const {
        return syncing() ? std::min(certified_lsn, synced_lsn)
                         : certified_lsn;
    }
    int timeout_ms = 2000;      /* durable-LSN wait (lrl:17 = 2000ms) */
    int hb_ms = 40;             /* heartbeat cadence */
    int lease_ms = 350;         /* quorum-contact freshness for serving */
    int elect_ms = 600;         /* election timeout base (+150*id) */
    std::vector<int> ports;
    std::vector<std::string> hosts;         /* peer addresses ("-n") */

    std::mutex mu;
    std::condition_variable cv;

    /* raft-ish consensus state */
    Role role = REPLICA;
    long long term = 1;
    int voted_for = -1;
    int leader = -1;
    long long last_leader_contact = 0;      /* mono_ms */

    /* the replicated log; SPEC is always the full log applied —
     * uncommitted suffix included. cas preconditions and txn
     * validation run against it, which is safe because a dependent
     * entry sits after its precondition's entry in the log, so
     * truncation removes both or neither. Reads must NOT see it. */
    std::vector<LogEntry> log;
    long long applied_lsn = 0;              /* == log.size() */
    StateMachine spec;

    /* the COMMITTED prefix — what reads serve in durable mode. An
     * applied-but-unacked write must never reach an observer: if it
     * is later truncated after a failover, the read it escaped into
     * would make the history non-linearizable (observed, then gone).
     * This is the durable-LSN read gating of the lrl's
     * RETRIEVE_DURABLE_LSN_AT_BEGIN. */
    long long committed_lsn = 0;
    StateMachine committed;

    /* highest lsn VERIFIED to match the current leader's log (by the
     * log-matching induction: an entry accepted after its prev-term
     * check, or a duplicate whose term matches, certifies its whole
     * prefix). A replica may only commit up to this point: a
     * heartbeat-learned durable LSN must never commit entries from
     * our own divergent uncommitted suffix before the E-stream has
     * repaired it — committed state never rolls back, so that would
     * be permanent corruption. Resets to committed_lsn on term change. */
    long long certified_lsn = 0;
    long long certified_term = 0;

    /* lsn of this leader's election no-op: reads are served only once
     * durable_lsn reaches it (Raft's new-leader read barrier — before
     * that, this leader's durable_lsn may lag writes the OLD leader
     * already acked, and serving would read stale) */
    long long term_start_lsn = 0;

    /* leader-only: per-peer replication + liveness tracking */
    std::vector<long long> acked_upto;      /* per node id */
    std::vector<long long> last_ack;        /* mono_ms of last A reply */
    long long durable_lsn = 0;
    long long known_durable = 0;            /* replicas: from heartbeats */

    /* open client transactions (leader-only; a failover aborts them:
     * the new leader doesn't know the txid and TC replies FAIL, which
     * is safe — nothing was applied). Reads record the version of
     * what they saw; commit validates those versions against the
     * SPECULATIVE state (log order = serial order, so any newer
     * write — committed or pending — must abort the txn). */
    struct TxnRead {
        char kind;          /* 'R' register, 'P' predicate (table) */
        int tbl;
        long long key;
        long long ver;
    };
    struct Txn {
        std::vector<TxnRead> reads;
        std::vector<SubOp> writes;
        long long created_ms = 0;
    };
    std::map<long long, Txn> txns;
    long long next_txid = 1;
    bool dirty_commit = false;  /* negative control: a validation
                                 * conflict still APPLIES the txn but
                                 * tells the client FAIL — the
                                 * effects-misclassification bug the
                                 * dirty-reads workload hunts (a
                                 * failed write's value visible,
                                 * comdb2/core.clj:492-523) */
    bool buggy_txn = false;     /* negative control: commit without
                                 * validation — lost updates / G2 */

    /* replay dedup: nonce -> lsn of the entry that applied it. Lives
     * IN the log (entries carry their nonce), so every replica
     * rebuilds it on apply and it survives failover exactly as far as
     * the entry itself does — the bdb_blkseq role: a retried mutation
     * that already applied returns its recorded outcome instead of
     * re-executing (cdb2api.c:618-656 retries lean on this). */
    std::map<unsigned long long, long long> nonce_lsn;

    /* partition control: peers we drop traffic with */
    std::set<int> blocked;

    size_t majority() const { return ports.size() / 2 + 1; }
    long long last_log_term() const {
        return log.empty() ? 0 : log.back().term;
    }
    int election_timeout() const { return elect_ms + 150 * id; }

    bool blocked_peer(int peer) {
        std::lock_guard<std::mutex> g(mu);
        return blocked.count(peer) != 0;
    }

    /* caller holds mu */
    /* persistence (the berkdb txn-log role,
     * killclustertest.sh:36-84's recovery contract): one line per log
     * entry, appended and fsync'd BEFORE the entry is acked upstream
     * or counted toward durability — so a majority-acked write
     * survives kill -9 of its whole cohort. Truncations rewrite the
     * file (rare: only divergent-suffix repair). */
    void persist_append_locked(const LogEntry &e);

    void persist_rewrite_locked();

    void persist_meta_locked() {
        if (dir.empty()) return;
        std::string tmp = dir + "/meta.tmp", path = dir + "/meta";
        FILE *f = fopen(tmp.c_str(), "w");
        if (f == nullptr) return;
        fprintf(f, "%lld %d\n", term, voted_for);
        if (!no_fsync) {
            fflush(f);
            fsync(fileno(f));
        }
        fclose(f);
        rename(tmp.c_str(), path.c_str());
    }

    void apply_locked(const LogEntry &e) {
        applied_lsn = (long long)log.size();
        spec.apply(e, applied_lsn);
        if (e.nonce != 0) nonce_lsn[e.nonce] = applied_lsn;
    }

    /* fold newly durable entries into the committed state; the target
     * is what this node KNOWS is majority-held (its own durable
     * calculation as leader, heartbeat-learned as replica). Committed
     * entries can never be truncated (they are in every electable
     * candidate's log), so this only ever moves forward. */
    void advance_committed_locked() {
        long long target =
            role == PRIMARY ? durable_lsn
                            : std::min(known_durable, certified_lsn);
        if (target > (long long)log.size())
            target = (long long)log.size();
        while (committed_lsn < target) {
            committed.apply(log[(size_t)committed_lsn],
                            committed_lsn + 1);
            committed_lsn++;
        }
    }

    void append_locked(const LogEntry &e) {
        log.push_back(e);
        apply_locked(e);
        persist_append_locked(e);
    }

    /* recovery replay: apply without re-writing the file */
    void append_recovered_locked(const LogEntry &e) {
        log.push_back(e);
        apply_locked(e);
    }

    /* drop log entries past lsn and rebuild applied state by replay —
     * a rejoining old primary's uncommitted divergent suffix dies here
     * (the log-matching property; those entries were never majority-
     * acked so no client ever saw OK for them) */
    void truncate_locked(long long lsn) {
        if ((long long)log.size() <= lsn) return;
        log.resize((size_t)lsn);
        spec = StateMachine();
        nonce_lsn.clear();
        applied_lsn = 0;
        std::vector<LogEntry> entries;
        entries.swap(log);
        for (const LogEntry &e : entries) append_recovered_locked(e);
        persist_rewrite_locked();
        if (certified_lsn > (long long)log.size())
            certified_lsn = (long long)log.size();
    }

    /* caller holds mu. Durable LSN = highest lsn held by a majority
     * (self included) — but only counted in the term that wrote it
     * (Raft §5.4.2: a leader only commits entries from its own term by
     * counting; earlier-term entries commit transitively). The no-op
     * appended on election win makes this advance promptly. */
    void recompute_durable_locked() {
        std::vector<long long> pos = acked_upto;
        pos[id] = syncing()
                      ? std::min((long long)log.size(), synced_lsn)
                      : (long long)log.size();
        std::sort(pos.begin(), pos.end(), std::greater<long long>());
        long long m = pos[majority() - 1];
        if (m > (long long)log.size())  /* defensive: acks are clamped
                                         * to certified prefixes, but
                                         * never index past our log */
            m = (long long)log.size();
        if (m > durable_lsn && m >= 1 &&
            log[(size_t)m - 1].term == term) {
            durable_lsn = m;
            advance_committed_locked();
            cv.notify_all();
        }
    }

    /* the clock lease math runs on: monotonic (correct — immune to
     * wall-clock scrambling) or the node's scramblable wall clock
     * (--bad-lease control). last_ack is recorded with the same
     * clock, so a backward clock jump makes elapsed time NEGATIVE and
     * a dead lease looks fresh forever. */
    long long lease_now_locked() const {
        return bad_lease ? mono_ms() + clock_offset_ms : mono_ms();
    }

    /* caller holds mu: does this (durable-mode) leader currently hold
     * a fresh majority lease? */
    bool lease_fresh_locked() const {
        long long now = lease_now_locked();
        int fresh = 1;                      /* self */
        for (size_t p = 0; p < ports.size(); p++)
            if ((int)p != id && now - last_ack[p] <= lease_ms) fresh++;
        return fresh >= (int)majority();
    }

    void step_down_locked(long long new_term) {
        if (new_term > term) {
            term = new_term;
            voted_for = -1;
            persist_meta_locked();      /* a vote in the old term must
                                         * not resurrect after restart */
        }
        if (role != REPLICA) {
            role = REPLICA;
            leader = -1;
        }
        cv.notify_all();
    }
};

Node g_node;

/* ---------- log entry wire/file serialization --------------------- */

/* txn payload suffix: " <nops> (<kind> <t> <k> <a> <b>)*" */
std::string entry_payload(const LogEntry &e) {
    if (e.kind != 'T') return "";
    std::string s = " " + std::to_string(e.ops.size());
    for (const SubOp &o : e.ops) {
        s += " ";
        s += o.kind;
        s += " " + std::to_string(o.t) + " " + std::to_string(o.k) +
             " " + std::to_string(o.a) + " " + std::to_string(o.b);
    }
    return s;
}

/* parse the payload suffix into e->ops; false on malformed input */
bool parse_payload(const char *p, LogEntry *e) {
    char *end = nullptr;
    long long nops = strtoll(p, &end, 10);
    if (end == p || nops < 0 || nops > 4096) return false;
    p = end;
    e->ops.clear();
    for (long long i = 0; i < nops; i++) {
        while (*p == ' ') p++;
        SubOp o;
        o.kind = *p;
        if (o.kind != 'W' && o.kind != 'I') return false;
        p++;
        long long *fields[4] = {&o.t, &o.k, &o.a, &o.b};
        for (long long *f : fields) {
            *f = strtoll(p, &end, 10);
            if (end == p) return false;
            p = end;
        }
        e->ops.push_back(o);
    }
    return true;
}

/* one log-file line (same grammar as the replication payload tail) */
void fprint_entry(FILE *f, const LogEntry &e) {
    fprintf(f, "%lld %c %lld %lld %lld %llu%s\n", e.term, e.kind,
            e.key, e.a, e.b, e.nonce, entry_payload(e).c_str());
}

void Node::persist_append_locked(const LogEntry &e) {
    if (log_fp == nullptr) return;
    fprint_entry(log_fp, e);        /* buffered; the syncer fsyncs */
    cv.notify_all();                /* wake the syncer */
}

void Node::persist_rewrite_locked() {
    if (log_fp == nullptr) return;
    /* the syncer flushes log_fp without holding mu: hold io_mu across
     * the close/reopen so it never touches a dangling FILE* */
    std::lock_guard<std::mutex> io(io_mu);
    /* write-tmp-then-rename (like the meta file): an in-place "w"
     * truncation would zero the fsync'd log for the duration of the
     * rewrite, and a kill -9 in that window would lose COMMITTED
     * entries — exactly the contract this file exists to keep */
    std::string tmp = dir + "/log.tmp", path = dir + "/log";
    FILE *f = fopen(tmp.c_str(), "w");
    if (f == nullptr) abort();
    for (const LogEntry &e : log) fprint_entry(f, e);
    if (!no_fsync) {
        fflush(f);
        fsync(fileno(f));
    }
    fclose(f);
    if (rename(tmp.c_str(), path.c_str()) != 0) abort();
    fclose(log_fp);
    log_fp = fopen(path.c_str(), "a");
    if (log_fp == nullptr) abort();
    if (no_fsync)
        setvbuf(log_fp, nullptr, _IOFBF, 1 << 20);
    synced_lsn = (long long)log.size();    /* rewrite was fsync'd */
    io_gen++;
}

/* ---------- small line-protocol client (for forwarding) ----------- */

/* Resolve a "-n" peer entry once at startup (hostnames are static;
 * getaddrinfo on the election/replication hot paths would let a slow
 * resolver blow the ~150 ms election budgets). Returns the dotted
 * address, or "" on failure. */
std::string resolve_host(const std::string &host) {
    in_addr a{};
    if (inet_pton(AF_INET, host.c_str(), &a) == 1) return host;
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 ||
        res == nullptr)
        return "";
    char buf[INET_ADDRSTRLEN] = {0};
    inet_ntop(AF_INET, &((sockaddr_in *)res->ai_addr)->sin_addr, buf,
              sizeof buf);
    freeaddrinfo(res);
    return buf;
}

int dial(const std::string &host, int port, int timeout_ms) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
        close(fd);       /* hosts are pre-resolved at startup */
        return -1;
    }
    addr.sin_port = htons((uint16_t)port);
    if (connect(fd, (sockaddr *)&addr, sizeof addr) != 0) {
        close(fd);
        return -1;
    }
    return fd;
}

bool send_all(int fd, const std::string &s) {
    size_t off = 0;
    while (off < s.size()) {
        ssize_t w = write(fd, s.c_str() + off, s.size() - off);
        if (w < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        off += (size_t)w;
    }
    return true;
}

/* read one '\n'-terminated line (without the newline); false on
 * timeout/eof — a line missing its newline is NOT a reply */
bool read_line(int fd, std::string *out) {
    out->clear();
    char c;
    for (;;) {
        ssize_t r = read(fd, &c, 1);
        if (r <= 0) {
            if (r < 0 && errno == EINTR) continue;
            return false;
        }
        if (c == '\n') return true;
        out->push_back(c);
        if (out->size() > (32u << 20)) return false;  /* forwarded set
                                                       * reads can be
                                                       * large — match
                                                       * the HA client's
                                                       * 32 MB buffer */
    }
}

/* one transient request/reply to a peer; empty string = no answer */
std::string peer_request(const std::string &host, int port,
                         const std::string &line, int timeout_ms) {
    int fd = dial(host, port, timeout_ms);
    if (fd < 0) return "";
    std::string reply;
    if (!send_all(fd, line + "\n") || !read_line(fd, &reply))
        reply.clear();
    close(fd);
    return reply;
}

/* ---------- replication + heartbeat sender (leader -> one peer) ---- */

void sender_thread(int peer) {
    Node &n = g_node;
    int fd = -1;
    long long last_hb_sent = 0;
    for (;;) {
        char buf[192];
        std::string msg;
        long long t_sent = 0;
        {
            std::unique_lock<std::mutex> lk(n.mu);
            n.cv.wait_for(lk, std::chrono::milliseconds(n.hb_ms), [&] {
                return n.role == PRIMARY && n.blocked.count(peer) == 0 &&
                       n.acked_upto[peer] < (long long)n.log.size();
            });
            if (n.role != PRIMARY || n.blocked.count(peer) != 0)
                continue;
            if (n.acked_upto[peer] < (long long)n.log.size()) {
                long long next = n.acked_upto[peer] + 1;
                const LogEntry &e = n.log[(size_t)next - 1];
                long long pterm =
                    next >= 2 ? n.log[(size_t)next - 2].term : 0;
                snprintf(buf, sizeof buf,
                         "E %d %lld %lld %lld %lld %c %lld %lld %lld"
                         " %lld %llu",
                         n.id, n.term, next, e.term, pterm, e.kind,
                         e.key, e.a, e.b, n.durable_lsn, e.nonce);
                msg = buf + entry_payload(e) + "\n";
            } else if (mono_ms() - last_hb_sent >= n.hb_ms) {
                snprintf(buf, sizeof buf, "H %d %lld %lld\n", n.id,
                         n.term, n.durable_lsn);
                msg = buf;
                last_hb_sent = mono_ms();
            }
            /* lease freshness is measured from when the request was
             * SENT, not when the reply arrived: the receiver's E
             * handler can sit up to 150 ms in its fsync wait, and
             * dating the ack at receipt would stretch the effective
             * lease window past lease_ms by that skew (round-3
             * ADVICE) */
            t_sent = n.lease_now_locked();
        }
        if (msg.empty()) continue;
        if (fd < 0) fd = dial(n.hosts[peer], n.ports[peer], 200);
        if (fd < 0) {
            /* unreachable peer: back off instead of spinning the dial
             * loop at 100% CPU (loopback refusals fail in µs) */
            std::this_thread::sleep_for(std::chrono::milliseconds(50));
            continue;
        }
        std::string reply;
        if (!send_all(fd, msg) || !read_line(fd, &reply)) {
            close(fd);
            fd = -1;
            continue;
        }
        long long x = 0;
        if (sscanf(reply.c_str(), "A %lld", &x) == 1) {
            std::lock_guard<std::mutex> g(n.mu);
            if (t_sent > n.last_ack[peer]) n.last_ack[peer] = t_sent;
            if (x > n.acked_upto[peer]) {
                n.acked_upto[peer] = x;
                n.recompute_durable_locked();
            } else if (x < n.acked_upto[peer]) {
                /* the peer restarted or truncated: regress so the
                 * stream backfills from its actual position instead of
                 * offering acked+1 forever (round-2 ADVICE fix) */
                n.acked_upto[peer] = x;
            }
        } else if (sscanf(reply.c_str(), "N %lld", &x) == 1) {
            /* a peer in a newer term: this leader is stale */
            std::lock_guard<std::mutex> g(n.mu);
            if (x > n.term) n.step_down_locked(x);
        } else {
            close(fd);
            fd = -1;
        }
    }
}

/* ---------- election ---------------------------------------------- */

/* runs on every node: demotes a leader that lost quorum contact;
 * campaigns when a replica stops hearing from any leader */
void election_thread() {
    Node &n = g_node;
    for (;;) {
        std::this_thread::sleep_for(std::chrono::milliseconds(25));
        long long now = mono_ms();
        long long t, last_lsn, last_term;
        std::set<int> blocked_copy;
        {
            std::lock_guard<std::mutex> g(n.mu);
            if (n.role == PRIMARY) {
                if (!n.split_brain && !n.lease_fresh_locked()) {
                    /* coherency-lease demotion (bdb/rep.c:639-654):
                     * without majority contact this leader can't know
                     * it is still the leader */
                    n.step_down_locked(n.term);
                }
                continue;
            }
            if (now - n.last_leader_contact < n.election_timeout())
                continue;
            /* campaign */
            n.term++;
            n.voted_for = n.id;
            n.persist_meta_locked();
            n.role = CANDIDATE;
            n.leader = -1;
            n.last_leader_contact = now;    /* back off before retry */
            t = n.term;
            last_lsn = (long long)n.log.size();
            last_term = n.last_log_term();
            blocked_copy = n.blocked;
        }
        char req[96];
        snprintf(req, sizeof req, "V %d %lld %lld %lld", n.id, t,
                 last_lsn, last_term);
        int votes = 1;
        for (int p = 0; p < (int)n.ports.size(); p++) {
            if (p == n.id || blocked_copy.count(p)) continue;
            std::string r =
                peer_request(n.hosts[p], n.ports[p], req, 150);
            long long gt = 0;
            int granted = 0;
            if (sscanf(r.c_str(), "G %lld %d", &gt, &granted) == 2) {
                if (gt > t) {
                    std::lock_guard<std::mutex> g(n.mu);
                    n.step_down_locked(gt);
                    votes = -1000;
                    break;
                }
                if (granted) votes++;
            }
        }
        std::lock_guard<std::mutex> g(n.mu);
        if (n.term == t && n.role == CANDIDATE &&
            votes >= (int)n.majority()) {
            n.role = PRIMARY;
            n.leader = n.id;
            long long nw = n.lease_now_locked();
            for (size_t p = 0; p < n.ports.size(); p++) {
                n.acked_upto[p] = 0;        /* senders re-probe; acks
                                             * fast-forward/regress */
                n.last_ack[p] = nw;         /* lease grace period */
            }
            /* the election no-op: lets durable_lsn advance in this
             * term, transitively committing inherited entries; reads
             * are barred until it commits (term_start_lsn) */
            n.append_locked({t, 'N', 0, 0, 0, 0, {}});
            n.term_start_lsn = (long long)n.log.size();
            n.recompute_durable_locked();
            n.cv.notify_all();
        } else if (n.role == CANDIDATE) {
            n.role = REPLICA;               /* lost/split: retry after
                                             * another timeout */
        }
    }
}

/* group-commit syncer: one fsync covers every entry buffered while
 * the previous fsync ran; durability/acks advance only behind it */
void syncer_thread() {
    Node &n = g_node;
    for (;;) {
        long long target, gen;
        {
            std::unique_lock<std::mutex> lk(n.mu);
            n.cv.wait_for(lk, std::chrono::milliseconds(50), [&] {
                return n.synced_lsn < (long long)n.log.size();
            });
            if (n.synced_lsn >= (long long)n.log.size()) continue;
            target = (long long)n.log.size();
            gen = n.io_gen;
        }
        {
            std::lock_guard<std::mutex> io(n.io_mu);
            fflush(n.log_fp);
            fsync(fileno(n.log_fp));
        }
        {
            std::lock_guard<std::mutex> g(n.mu);
            /* a rewrite between the capture and here replaced the
             * file: this target says nothing about the NEW file's
             * buffered tail — drop it (the next iteration re-syncs) */
            if (gen == n.io_gen && target > n.synced_lsn)
                n.synced_lsn = std::min(target,
                                        (long long)n.log.size());
            n.recompute_durable_locked();
        }
        n.cv.notify_all();
    }
}

/* ---------- request handling -------------------------------------- */

/* leader-side commit: append + apply + (durable) wait for majority.
 * Returns "OK <lsn>", "FAIL" (cas precondition), or "UNKNOWN" (not
 * leader / durable wait timed out: the op may still replicate —
 * indeterminate, exactly an :info op). The cas precondition is decided
 * under the same lock as the append, so concurrent cas ops serialize. */
/* shared tail of every leader-side commit: wait until the appended
 * (or replayed) entry at ``lsn`` is covered by the durable LSN.
 * Replayed entries may commit under ANY term (inherited by a later
 * leader) — only durable coverage matters; fresh entries require the
 * leader to still be in the appending term. */
std::string commit_wait(long long lsn, long long t, bool replay) {
    Node &n = g_node;
    n.cv.notify_all();
    if (!n.durable) return "OK " + std::to_string(lsn);
    std::unique_lock<std::mutex> lk(n.mu);
    if (n.split_brain && !n.lease_fresh_locked()) {
        /* the split-brain control: a quorum-less leader acks anyway —
         * the divergent write the checker must catch */
        return "OK " + std::to_string(lsn);
    }
    if (replay) {
        bool ok = n.cv.wait_for(lk,
                                std::chrono::milliseconds(n.timeout_ms),
                                [&] {
                                    return n.durable_lsn >= lsn ||
                                           n.role != PRIMARY;
                                });
        if (ok && n.durable_lsn >= lsn)
            return "OK " + std::to_string(lsn);
        return "UNKNOWN";
    }
    bool ok = n.cv.wait_for(lk, std::chrono::milliseconds(n.timeout_ms),
                            [&] {
                                return n.durable_lsn >= lsn ||
                                       n.term != t || n.role != PRIMARY;
                            });
    if (ok && n.durable_lsn >= lsn && n.term == t)
        return "OK " + std::to_string(lsn);
    return "UNKNOWN";       /* deposed or timed out: indeterminate */
}

std::string primary_commit(const LogEntry &e0, bool is_cas = false) {
    Node &n = g_node;
    LogEntry e = e0;
    long long lsn, t;
    bool replay = false;
    {
        std::lock_guard<std::mutex> g(n.mu);
        if (n.role != PRIMARY) return "UNKNOWN";
        /* replay dedup, atomically with the append decision: a
         * retried mutation whose entry is already in the log waits on
         * THAT entry instead of applying twice. Only applied ops are
         * logged, so a precondition-FAILed cas re-executes fresh —
         * its first attempt had no effect, exactly-once holds. */
        if (e.nonce != 0 && !n.no_dedup) {
            auto it = n.nonce_lsn.find(e.nonce);
            if (it != n.nonce_lsn.end()) {
                lsn = it->second;
                t = n.log[(size_t)lsn - 1].term;
                replay = true;
            }
        }
        if (!replay) {
            if (is_cas) {
                auto it = n.spec.regs.find(e.key);
                if (it == n.spec.regs.end() || it->second != e.a)
                    return "FAIL";
            }
            e.term = t = n.term;
            n.append_locked(e);
            lsn = (long long)n.log.size();
            n.recompute_durable_locked();
        }
    }
    return commit_wait(lsn, t, replay);
}

/* commit a client transaction: validate its read versions against the
 * SPECULATIVE state (log order = serial order — any newer write to a
 * read key/predicate, committed or pending, aborts), then append ONE
 * 'T' entry with all buffered writes and wait for durability. The
 * validation, txn consumption, and append share one lock acquisition
 * with every other commit, so the serial point is the log position.
 * --buggy-txn (-T) skips validation — the lost-update / G2 control. */
std::string commit_txn(long long txid, unsigned long long nonce) {
    Node &n = g_node;
    LogEntry e;
    long long lsn = 0, t = 0;
    bool replay = false;
    bool lied = false;
    {
        std::lock_guard<std::mutex> g(n.mu);
        if (n.role != PRIMARY) return "UNKNOWN";
        if (nonce != 0 && !n.no_dedup) {
            auto it = n.nonce_lsn.find(nonce);
            if (it != n.nonce_lsn.end()) {
                lsn = it->second;
                t = n.log[(size_t)lsn - 1].term;
                replay = true;
            }
        }
        if (!replay) {
            auto it = n.txns.find(txid);
            if (it == n.txns.end())
                return "FAIL";  /* aborted / deposed / expired: clean
                                 * abort — nothing was applied */
            Node::Txn txn = std::move(it->second);
            n.txns.erase(it);
            if (!n.buggy_txn) {
                for (const Node::TxnRead &r : txn.reads) {
                    long long cur = 0;
                    if (r.kind == 'R') {
                        auto v = n.spec.reg_ver.find(r.key);
                        cur = v == n.spec.reg_ver.end() ? 0
                                                        : v->second;
                    } else {
                        auto v = n.spec.tables.find({r.tbl, r.key});
                        cur = v == n.spec.tables.end()
                                  ? 0
                                  : (long long)v->second.size();
                    }
                    if (cur != r.ver) {
                        if (!n.dirty_commit)
                            return "FAIL";              /* conflict */
                        /* --dirty-commit (-R): apply anyway, lie to
                         * the client. The write becomes visible while
                         * the client records :fail — exactly the
                         * anomaly the dirty-reads checker hunts. */
                        lied = true;
                        break;
                    }
                }
            }
            if (txn.writes.empty()) {
                /* read-only: its commit point is now; needs the same
                 * lease + read barrier as a plain read. A conflicted
                 * read-only txn under -R has nothing to dirty-apply —
                 * it must keep reporting FAIL like the default path
                 * (the -R contract alters write-txn REPORTING only;
                 * returning OK here would commit a torn read snapshot
                 * as clean — ADVICE r4) */
                if (lied)
                    return "FAIL";
                if (!n.durable ||
                    (n.lease_fresh_locked() &&
                     n.durable_lsn >= n.term_start_lsn))
                    return "OK " + std::to_string(n.durable_lsn);
                return "UNKNOWN";
            }
            e.kind = 'T';
            e.ops = std::move(txn.writes);
            e.nonce = nonce;
            e.term = t = n.term;
            n.append_locked(e);
            lsn = (long long)n.log.size();
            n.recompute_durable_locked();
        }
    }
    std::string out = commit_wait(lsn, t, replay);
    if (lied) return "FAIL";    /* the entry is in the log regardless */
    return out;
}

std::string handle(const std::string &line, bool forwarded = false);

/* forward a client op to the current leader; both this node's
 * partition state and the leader's are honored (F carries the origin
 * id). A blocked/unknown link behaves like a real partition: the
 * request HANGS until the timeout instead of failing fast — an
 * instant UNKNOWN would let clients machine-gun indeterminate ops. */
std::string forward_to_leader(const std::string &cmd) {
    Node &n = g_node;
    int ldr;
    {
        std::lock_guard<std::mutex> g(n.mu);
        ldr = n.leader;
    }
    if (ldr == n.id) return handle(cmd, /*forwarded=*/true);
    if (ldr < 0 || n.blocked_peer(ldr)) {
        std::this_thread::sleep_for(
            std::chrono::milliseconds(n.timeout_ms));
        return "UNKNOWN";
    }
    /* std::string, not a fixed buffer: a truncated command applied on
     * the leader with an OK reply would be a silent wrong-value write
     * (round-3 ADVICE) */
    std::string fwd = "F " + std::to_string(n.id) + " " + cmd;
    /* the leader's durable wait can take timeout_ms on its own */
    std::string r = peer_request(n.hosts[ldr], n.ports[ldr], fwd,
                                 n.timeout_ms + 500);
    return r.empty() ? "UNKNOWN" : r;
}

const char *role_name(Role r) {
    return r == PRIMARY ? "primary"
                        : (r == CANDIDATE ? "candidate" : "replica");
}

std::string handle(const std::string &line, bool forwarded) {
    Node &n = g_node;
    char cmd = line.empty() ? 0 : line[0];
    if (cmd == 'P') return "PONG";
    if (cmd == 'I') {
        std::lock_guard<std::mutex> g(n.mu);
        char buf[160];
        long long durable =
            n.role == PRIMARY ? n.durable_lsn : n.known_durable;
        snprintf(buf, sizeof buf, "I %d %s %lld %lld %lld %d", n.id,
                 role_name(n.role), n.applied_lsn, durable, n.term,
                 n.leader);
        return buf;
    }
    if (cmd == 'K') {
        /* the clock nemesis ("date -s" in-tree): set this node's wall
         * clock offset in ms; "K" alone resets. Harmless against the
         * correct implementation (leases run on monotonic deltas);
         * with --bad-lease the lease math consumes this clock and a
         * backward jump stretches a dead lease. */
        long long off = 0;
        sscanf(line.c_str() + 1, "%lld", &off);
        std::lock_guard<std::mutex> g(n.mu);
        n.clock_offset_ms = off;
        return "OK";
    }
    if (cmd == 'B' || cmd == 'U') {
        int peer = -1;
        bool have = sscanf(line.c_str() + 1, "%d", &peer) == 1;
        std::lock_guard<std::mutex> g(n.mu);
        if (cmd == 'B' && have)
            n.blocked.insert(peer);
        else if (cmd == 'U' && have)
            n.blocked.erase(peer);
        else if (cmd == 'U')
            n.blocked.clear();
        n.cv.notify_all();
        return "OK";
    }
    if (cmd == 'F') {
        int from = -1;
        int off = 0;
        if (sscanf(line.c_str() + 1, "%d %n", &from, &off) < 1)
            return "ERR";
        if (n.blocked_peer(from)) {     /* hang like a dropped packet */
            std::this_thread::sleep_for(
                std::chrono::milliseconds(n.timeout_ms));
            return "UNKNOWN";
        }
        return handle(line.substr(1 + (size_t)off),
                      /*forwarded=*/true);
    }
    if (cmd == 'H') {
        int from = -1;
        long long hterm = 0, hdurable = 0;
        if (sscanf(line.c_str() + 1, "%d %lld %lld", &from, &hterm,
                   &hdurable) != 3)
            return "ERR";
        if (n.blocked_peer(from)) return "ERR";
        std::lock_guard<std::mutex> g(n.mu);
        if (hterm < n.term) return "N " + std::to_string(n.term);
        n.step_down_locked(hterm);
        n.leader = from;
        n.last_leader_contact = mono_ms();
        if (hterm != n.certified_term) {
            n.certified_lsn = n.committed_lsn;
            n.certified_term = hterm;
        }
        if (hdurable > n.known_durable) {
            n.known_durable = hdurable;
            n.advance_committed_locked();
        }
        /* ack the CERTIFIED prefix, not raw applied: a rejoined node
         * with a divergent suffix must not have those entries counted
         * toward durability, and a low ack is what makes the sender
         * regress and repair the suffix entry by entry. Clamped to the
         * on-disk prefix under group commit. */
        return "A " + std::to_string(n.ack_locked());
    }
    if (cmd == 'V') {
        int from = -1;
        long long vterm = 0, vlsn = 0, vlast = 0;
        if (sscanf(line.c_str() + 1, "%d %lld %lld %lld", &from, &vterm,
                   &vlsn, &vlast) != 4)
            return "ERR";
        if (n.blocked_peer(from)) return "ERR";
        std::lock_guard<std::mutex> g(n.mu);
        if (vterm > n.term) n.step_down_locked(vterm);
        bool up_to_date =
            vlast > n.last_log_term() ||
            (vlast == n.last_log_term() &&
             vlsn >= (long long)n.log.size());
        bool grant = vterm == n.term &&
                     (n.voted_for == -1 || n.voted_for == from) &&
                     up_to_date;
        if (grant) {
            n.voted_for = from;
            n.persist_meta_locked();    /* one vote per term, even
                                         * across a crash-restart */
            n.last_leader_contact = mono_ms();  /* don't also campaign */
        }
        return "G " + std::to_string(n.term) + (grant ? " 1" : " 0");
    }
    if (cmd == 'E') {
        int from = -1;
        long long eterm = 0, lsn = 0, et = 0, pt = 0, key = 0, a = 0,
                  b = 0, edur = 0;
        unsigned long long enonce = 0;
        char kind = 0;
        int off = 0;
        if (sscanf(line.c_str() + 1,
                   "%d %lld %lld %lld %lld %c %lld %lld %lld %lld "
                   "%llu%n",
                   &from, &eterm, &lsn, &et, &pt, &kind, &key, &a, &b,
                   &edur, &enonce, &off) != 11)
            return "ERR";
        LogEntry incoming{et, kind, key, a, b, enonce, {}};
        if (kind == 'T' &&
            !parse_payload(line.c_str() + 1 + off, &incoming))
            return "ERR";
        if (lsn < 1) return "ERR";  /* log[lsn-1] below would wrap */
        if (n.blocked_peer(from)) return "ERR";
        std::unique_lock<std::mutex> g(n.mu);
        if (eterm < n.term) return "N " + std::to_string(n.term);
        n.step_down_locked(eterm);
        n.leader = from;
        n.last_leader_contact = mono_ms();
        if (eterm != n.certified_term) {
            n.certified_lsn = n.committed_lsn;
            n.certified_term = eterm;
        }
        if (edur > n.known_durable) n.known_durable = edur;
        if (lsn <= n.applied_lsn &&
            n.log[(size_t)lsn - 1].term != et) {
            /* conflicting entry from a dead term: drop our suffix */
            n.truncate_locked(lsn - 1);
        }
        if (lsn == n.applied_lsn + 1) {
            if (lsn >= 2 && n.log[(size_t)lsn - 2].term != pt) {
                /* previous entry mismatches: force the sender back */
                n.truncate_locked(lsn - 2);
            } else {
                n.append_locked(incoming);
            }
        }
        if (lsn <= n.applied_lsn &&
            n.log[(size_t)lsn - 1].term == et && lsn > n.certified_lsn) {
            /* matching index+term certifies the whole prefix (the
             * log-matching property) — commits may now cover it */
            n.certified_lsn = lsn;
        }
        /* ack the certified prefix (see the H handler), clamped to
         * the on-disk prefix: the reply may count toward durability.
         * With group commit the syncer fsyncs outside the lock — wait
         * briefly for it to cover this append so the sender doesn't
         * spin re-offering (one fsync covers everything buffered
         * meanwhile). The wait must stay BELOW the sender's 200 ms
         * socket timeout or a slow fsync turns into a reconnect storm
         * with every reply discarded. */
        if (n.syncing() && n.synced_lsn < n.applied_lsn)
            n.cv.wait_for(g, std::chrono::milliseconds(150), [&] {
                return n.synced_lsn >= n.applied_lsn;
            });
        if (eterm != n.term || eterm != n.certified_term) {
            /* the wait dropped the lock: a NEWER leader may have
             * replicated meanwhile (step_down + truncation + new
             * certification). An ack computed from that state must
             * not reach the OLD-term sender — it would count a
             * replaced entry toward the old leader's durability and
             * an acked write could be lost. */
            return "N " + std::to_string(n.term);
        }
        n.advance_committed_locked();
        return "A " + std::to_string(n.ack_locked());
    }
    if (cmd == 'R') {
        long long key = 1;                  /* "R" alone = key 1 */
        sscanf(line.c_str() + 1, "%lld", &key);
        bool am_leader, speculative;
        {
            std::lock_guard<std::mutex> g(n.mu);
            am_leader = n.role == PRIMARY;
            if (!n.durable) {
                /* no-durable control: every node serves its possibly
                 * stale, possibly uncommitted local state */
                auto it = n.spec.regs.find(key);
                return it != n.spec.regs.end()
                           ? "V " + std::to_string(it->second)
                           : "NIL";
            }
            /* leader-only: on -B replicas last_ack never refreshes, so
             * without the am_leader gate every replica would serve
             * stale local state (degenerating this control into -N) */
            speculative = am_leader && n.split_brain &&
                          !n.lease_fresh_locked();
            if (am_leader && !speculative) {
                /* durable-mode leader read: needs a fresh majority
                 * lease AND the term's no-op committed (before that,
                 * our durable_lsn may lag writes the old leader acked)
                 * — then serve the COMMITTED prefix only: an applied-
                 * but-unacked write must never escape to an observer,
                 * it could be truncated after a failover */
                if (n.lease_fresh_locked() &&
                    n.durable_lsn >= n.term_start_lsn) {
                    auto it = n.committed.regs.find(key);
                    return it != n.committed.regs.end()
                               ? "V " + std::to_string(it->second)
                               : "NIL";
                }
            } else if (speculative) {
                /* the split-brain control serves its divergent
                 * speculative state off the stale lease — the
                 * anomaly has to be client-visible */
                auto it = n.spec.regs.find(key);
                return it != n.spec.regs.end()
                           ? "V " + std::to_string(it->second)
                           : "NIL";
            }
        }
        if (!am_leader && !forwarded)
            return forward_to_leader("R " + std::to_string(key));
        /* lease-stale/barred leader (or a forward that raced a
         * deposition): hang like a partition — serving here is
         * exactly the stale read the lease prevents */
        std::this_thread::sleep_for(
            std::chrono::milliseconds(n.timeout_ms));
        return "UNKNOWN";
    }
    if (cmd == 'S') {
        /* same routing as R (the REQUEST_DURABLE_LSN_FROM_MASTER
         * shape): durable-mode set reads go to the lease-holding
         * leader and serve the COMMITTED prefix — a replica's
         * committed set lags by a heartbeat and a fresh session
         * reading it would see acked adds as lost; an uncommitted
         * element could be truncated after failover and flicker */
        bool am_leader, speculative;
        {
            std::lock_guard<std::mutex> g(n.mu);
            am_leader = n.role == PRIMARY;
            if (!n.durable) {
                std::string out = "V";
                for (long long v : n.spec.set_vals)
                    out += " " + std::to_string(v);
                return out;
            }
            speculative = am_leader && n.split_brain &&
                          !n.lease_fresh_locked();
            if (speculative ||
                (am_leader && n.lease_fresh_locked() &&
                 n.durable_lsn >= n.term_start_lsn)) {
                const std::vector<long long> &vals =
                    speculative ? n.spec.set_vals
                                : n.committed.set_vals;
                std::string out = "V";
                for (long long v : vals)
                    out += " " + std::to_string(v);
                return out;
            }
        }
        if (!am_leader && !forwarded)
            return forward_to_leader("S");
        std::this_thread::sleep_for(
            std::chrono::milliseconds(n.timeout_ms));
        return "UNKNOWN";
    }
    if (cmd == 'T' && line.size() >= 2) {
        /* transaction verbs (the begin/op/commit surface the sut.h
         * ABI lacked — VERDICT Missing #2). Txn state lives on the
         * leader; every verb forwards like a mutation, so a client
         * can drive one txn through any node. A failover aborts open
         * txns cleanly (unknown txid -> FAIL, nothing applied).
         *   TB                  -> "T <txid>"
         *   TR <txid> <k>       -> "V <v>" | "NIL"    committed read
         *   TP <txid> <a|b> <k> -> "V id:val ..."     predicate read
         *   TW <txid> <k> <v>   -> "OK"               buffer write
         *   TI <txid> <a|b> <k> <id> <v> -> "OK"      buffer insert
         *   TA <txid>           -> "OK"               abort
         *   TC <txid> [nonce]   -> "OK <lsn>" | "FAIL" | "UNKNOWN"
         */
        char sub = line[1];
        bool am_leader;
        {
            std::lock_guard<std::mutex> g(n.mu);
            am_leader = n.role == PRIMARY;
        }
        if (!am_leader) {
            if (forwarded) return "UNKNOWN";
            return forward_to_leader(line);
        }
        const char *args = line.c_str() + 2;
        if (sub == 'B') {
            std::lock_guard<std::mutex> g(n.mu);
            long long now = mono_ms();
            /* expire abandoned txns so crashed clients can't leak */
            for (auto it = n.txns.begin(); it != n.txns.end();) {
                if (now - it->second.created_ms > 60000)
                    it = n.txns.erase(it);
                else
                    ++it;
            }
            long long txid = n.next_txid++;
            n.txns[txid].created_ms = now;
            return "T " + std::to_string(txid);
        }
        if (sub == 'C') {
            long long txid = 0;
            unsigned long long nonce = 0;
            if (sscanf(args, "%lld %llu", &txid, &nonce) < 1)
                return "ERR";
            return commit_txn(txid, nonce);
        }
        if (sub == 'A') {
            long long txid = 0;
            if (sscanf(args, "%lld", &txid) != 1) return "ERR";
            std::lock_guard<std::mutex> g(n.mu);
            n.txns.erase(txid);
            return "OK";
        }
        if (sub == 'R') {
            long long txid = 0, key = 0;
            if (sscanf(args, "%lld %lld", &txid, &key) != 2)
                return "ERR";
            std::lock_guard<std::mutex> g(n.mu);
            auto it = n.txns.find(txid);
            if (it == n.txns.end()) return "FAIL";
            /* committed read (uncommitted data must never escape);
             * the version of what we read is the committed one — at
             * commit, any NEWER version (even pending) aborts */
            auto vv = n.committed.reg_ver.find(key);
            long long ver =
                vv == n.committed.reg_ver.end() ? 0 : vv->second;
            it->second.reads.push_back({'R', 0, key, ver});
            auto rv = n.committed.regs.find(key);
            return rv != n.committed.regs.end()
                       ? "V " + std::to_string(rv->second)
                       : "NIL";
        }
        if (sub == 'P') {
            long long txid = 0, key = 0;
            char tc = 0;
            if (sscanf(args, "%lld %c %lld", &txid, &tc, &key) != 3 ||
                (tc != 'a' && tc != 'b'))
                return "ERR";
            int tbl = tc == 'b' ? 1 : 0;
            std::lock_guard<std::mutex> g(n.mu);
            auto it = n.txns.find(txid);
            if (it == n.txns.end()) return "FAIL";
            auto tv = n.committed.tables.find({tbl, key});
            long long count =
                tv == n.committed.tables.end()
                    ? 0
                    : (long long)tv->second.size();
            it->second.reads.push_back({'P', tbl, key, count});
            std::string out = "V";
            if (tv != n.committed.tables.end())
                for (const auto &row : tv->second)
                    out += " " + std::to_string(row.first) + ":" +
                           std::to_string(row.second);
            return out;
        }
        if (sub == 'W') {
            long long txid = 0, key = 0, v = 0;
            if (sscanf(args, "%lld %lld %lld", &txid, &key, &v) != 3)
                return "ERR";
            std::lock_guard<std::mutex> g(n.mu);
            auto it = n.txns.find(txid);
            if (it == n.txns.end()) return "FAIL";
            /* the admission cap must stay below parse_payload's 4096
             * and the recovery line buffer: an entry the replicas or
             * recovery can't parse would wedge replication forever */
            if (it->second.writes.size() >= 512) return "ERR";
            it->second.writes.push_back({'W', 0, key, v, 0});
            return "OK";
        }
        if (sub == 'I') {
            long long txid = 0, key = 0, rid = 0, v = 0;
            char tc = 0;
            if (sscanf(args, "%lld %c %lld %lld %lld", &txid, &tc,
                       &key, &rid, &v) != 5 ||
                (tc != 'a' && tc != 'b'))
                return "ERR";
            std::lock_guard<std::mutex> g(n.mu);
            auto it = n.txns.find(txid);
            if (it == n.txns.end()) return "FAIL";
            if (it->second.writes.size() >= 512) return "ERR";
            it->second.writes.push_back(
                {'I', tc == 'b' ? 1 : 0, key, rid, v});
            return "OK";
        }
        return "ERR";
    }
    if (cmd == 'M' || cmd == 'W' || cmd == 'C' || cmd == 'A') {
        unsigned long long nonce = 0;
        std::string inner = line;
        if (cmd == 'M') {
            /* "M <nonce> <W|C|A ...>": a retry-safe mutation */
            int off = 0;
            if (sscanf(line.c_str() + 1, "%llu %n", &nonce, &off) < 1 ||
                nonce == 0)
                return "ERR";
            inner = line.substr(1 + (size_t)off);
            if (inner.empty())
                return "ERR";
            cmd = inner[0];
            if (cmd != 'W' && cmd != 'C' && cmd != 'A') return "ERR";
        }
        bool am_leader;
        {
            std::lock_guard<std::mutex> g(n.mu);
            am_leader = n.role == PRIMARY;
        }
        if (!am_leader) {
            /* a forwarded mutation that raced a deposition must not
             * bounce around the cluster: indeterminate, client retries */
            if (forwarded) return "UNKNOWN";
            return forward_to_leader(line);    /* nonce rides along */
        }
        if (cmd == 'W') {
            /* "W k v" keyed; "W v" = key 1 (sut_server compatible) */
            long long k = 0, v = 0;
            int cnt = sscanf(inner.c_str() + 1, "%lld %lld", &k, &v);
            if (cnt == 1) { v = k; k = 1; }
            else if (cnt != 2) return "ERR";
            return primary_commit({0, 'W', k, v, 0, nonce, {}});
        }
        if (cmd == 'A') {
            long long v = atoll(inner.c_str() + 1);
            return primary_commit({0, 'A', 0, v, 0, nonce, {}});
        }
        /* "C k a b" keyed; "C a b" = key 1 */
        long long k = 0, a = 0, b = 0;
        int cnt = sscanf(inner.c_str() + 1, "%lld %lld %lld", &k, &a,
                         &b);
        if (cnt == 2) { b = a; a = k; k = 1; }
        else if (cnt != 3) return "ERR";
        return primary_commit({0, 'C', k, a, b, nonce, {}},
                              /*is_cas=*/true);
    }
    return "ERR";
}

void serve_conn(int fd) {
    FILE *in = fdopen(fd, "r");
    if (in == nullptr) {
        close(fd);
        return;
    }
    /* SQL session state lives per connection, like a cdb2 appsock
     * thread's (db/sqlinterfaces.c:5768 sqlengine_work_appsock) */
    sqlfront::Session sql;
    /* dynamic line buffer: a replicated 'T' entry's E line grows with
     * its sub-ops (~5KB+ at the 512-sub-op admission cap). A fixed
     * fgets buffer would split it, parse the tail as ERR, and wedge
     * replication forever (round-3 ADVICE) */
    char *line = nullptr;
    size_t cap = 0;
    ssize_t len;
    while ((len = getline(&line, &cap, in)) != -1) {
        if (len > 32 * 1024 * 1024) break;  /* same cap as read_line */
        while (len > 0 && (line[len - 1] == '\n' || line[len - 1] == '\r'))
            line[--len] = 0;
        std::string req(line, (size_t)len);
        std::string out =
            (sqlfront::is_statement(req)
                 ? sqlfront::execute(req, sql, [](const std::string &v) {
                       return handle(v);
                   })
                 : handle(req)) +
            "\n";
        if (!send_all(fd, out)) break;
    }
    /* a dropped connection aborts its open SQL txn (comdb2 does the
     * same for an appsock that dies mid-txn) */
    if (sql.txid >= 0) handle("TA " + std::to_string(sql.txid));
    free(line);
    fclose(in);
}

}  // namespace

int main(int argc, char **argv) {
    Node &n = g_node;
    std::string peers;
    std::string pmux_spec;      /* -M <pmux_port>:<service> */
    int initial_leader = 0;
    int c;
    while ((c = getopt(argc, argv, "i:n:P:t:e:l:d:M:xLNBDTRh")) != -1) {
        switch (c) {
        case 'i': n.id = atoi(optarg); break;
        case 'n': peers = optarg; break;
        case 'M': pmux_spec = optarg; break;
        case 'P': initial_leader = atoi(optarg); break;
        case 't': n.timeout_ms = atoi(optarg); break;
        case 'e': n.elect_ms = atoi(optarg); break;
        case 'l': n.lease_ms = atoi(optarg); break;
        case 'N': n.durable = false; break;
        case 'B': n.split_brain = true; break;
        case 'D': n.no_dedup = true; break;
        case 'd': n.dir = optarg; break;
        case 'x': n.no_fsync = true; break;
        case 'T': n.buggy_txn = true; break;
        case 'R': n.dirty_commit = true; break;
        case 'L': n.bad_lease = true; break;
        default:
            fprintf(stderr,
                    "usage: %s -i id -n port0,port1,... [-P leader0] "
                    "[-t durable_timeout_ms] [-e elect_base_ms] "
                    "[-l lease_ms] [-d state_dir] "
                    "[-M pmux_port:service] "
                    "[-x (no-fsync control)] [-N (no-durable)] "
                    "[-B (split-brain control)] "
                    "[-D (no-dedup control)] "
                    "[-R (dirty-commit control)] "
                    "[-T (buggy-txn control)] "
                    "[-L (bad-lease control)]\n",
                    argv[0]);
            return 2;
        }
    }
    /* "-n" entries are "port" (localhost) or "host:port" — the
     * multi-host form the provisioning layer (harness/provision.py)
     * uses; the reference cluster runs on machines m1..m5
     * (scripts/setvars:7) */
    for (const char *p = peers.c_str(); *p != 0;) {
        const char *comma = strchr(p, ',');
        std::string entry(p, comma ? (size_t)(comma - p)
                                   : strlen(p));
        size_t colon = entry.rfind(':');
        if (colon == std::string::npos) {
            n.hosts.push_back("127.0.0.1");
            n.ports.push_back(atoi(entry.c_str()));
        } else {
            std::string resolved =
                resolve_host(entry.substr(0, colon));
            if (resolved.empty()) {
                fprintf(stderr, "sut_node: cannot resolve %s\n",
                        entry.c_str());
                return 2;
            }
            n.hosts.push_back(resolved);
            n.ports.push_back(atoi(entry.c_str() + colon + 1));
        }
        if (comma == nullptr) break;
        p = comma + 1;
    }
    if (n.ports.empty() || n.id < 0 ||
        n.id >= (int)n.ports.size()) {
        fprintf(stderr, "sut_node: bad -i/-n\n");
        return 2;
    }
    if (n.lease_ms >= n.elect_ms) {
        /* reads are only lease-safe when every leader demotes before
         * any replica can start a new election (the Raft lease-read
         * requirement) */
        fprintf(stderr, "sut_node: lease_ms must be < elect_ms\n");
        return 2;
    }
    n.acked_upto.assign(n.ports.size(), 0);
    n.last_ack.assign(n.ports.size(), mono_ms());
    /* (bad-lease mode re-records these with the node clock on the
     * first real acks; the boot values only gate the initial lease) */

    bool recovered = false;
    if (!n.dir.empty()) {
        mkdir(n.dir.c_str(), 0755);
        std::string meta_path = n.dir + "/meta";
        if (FILE *f = fopen(meta_path.c_str(), "r")) {
            long long t = 0;
            int v = -1;
            if (fscanf(f, "%lld %d", &t, &v) == 2 && t >= 1) {
                n.term = t;
                n.voted_for = v;
                recovered = true;
            }
            fclose(f);
        }
        std::string log_path = n.dir + "/log";
        if (FILE *f = fopen(log_path.c_str(), "r")) {
            char lbuf[65536];
            long good = 0;      /* offset after the last whole entry */
            while (fgets(lbuf, sizeof lbuf, f) != nullptr) {
                LogEntry e;
                int off = 0;
                size_t len = strlen(lbuf);
                if (len == 0 || lbuf[len - 1] != '\n')
                    break;      /* torn tail: not a whole line */
                if (sscanf(lbuf, "%lld %c %lld %lld %lld %llu%n",
                           &e.term, &e.kind, &e.key, &e.a, &e.b,
                           &e.nonce, &off) != 6)
                    break;
                if (e.kind == 'T' && !parse_payload(lbuf + off, &e))
                    break;
                n.append_recovered_locked(e);
                good = ftell(f);
            }
            fclose(f);
            /* drop any torn residue BEFORE reopening for append —
             * otherwise new fsync'd entries land after the garbage
             * and the NEXT recovery would stop at it and silently
             * lose them */
            if (truncate(log_path.c_str(), good) != 0 && errno != ENOENT)
                perror("truncate log");
            if (!n.log.empty()) recovered = true;
        }
        n.log_fp = fopen(log_path.c_str(), "a");
        if (n.log_fp == nullptr) {
            perror("open log");
            return 2;
        }
        if (n.no_fsync)     /* big buffer, never flushed: the tail
                             * dies with the process — the control */
            setvbuf(n.log_fp, nullptr, _IOFBF, 1 << 20);
        n.synced_lsn = (long long)n.log.size();   /* replayed prefix
                                                   * is on disk */
    }
    /* An in-memory fresh cluster boots with a static initial leader
     * (no election needed). A PERSISTENT node always boots as a
     * replica — even with an empty dir: it cannot distinguish "fresh
     * cluster" from "my state was wiped while the cluster progressed",
     * and self-appointing as term-1 primary into a progressed cluster
     * would serve committed-empty stale reads until the real leader's
     * heartbeat demotes it. The first election sorts out who leads
     * (vote gating keeps it safe). */
    if (recovered || !n.dir.empty()) {
        n.leader = -1;
        n.role = REPLICA;
    } else {
        n.leader = initial_leader;
        n.role = n.id == initial_leader ? PRIMARY : REPLICA;
    }
    n.last_leader_contact = mono_ms();
    signal(SIGPIPE, SIG_IGN);

    int srv = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    bool all_local = true;
    for (const std::string &h : n.hosts)
        if (h != "127.0.0.1" && h != "localhost") all_local = false;
    addr.sin_addr.s_addr = htonl(all_local ? INADDR_LOOPBACK
                                           : INADDR_ANY);
    addr.sin_port = htons((uint16_t)n.ports[n.id]);
    if (bind(srv, (sockaddr *)&addr, sizeof addr) != 0 ||
        listen(srv, 64) != 0) {
        perror("bind/listen");
        return 2;
    }
    /* pmux registration (-M <pmux_port>:<service>): publish this
     * node's client port with the host's port multiplexer so clients
     * can discover it by service name instead of carrying host:port
     * config — the role every comdb2 instance plays against pmux
     * (tools/pmux role; cdb2api resolves ports the same way). Retried
     * in the background so a pmux that boots moments after the node
     * still learns the port; failure is non-fatal (readiness probes
     * catch an undiscoverable node). */
    if (!pmux_spec.empty()) {
        size_t colon = pmux_spec.find(':');
        int pmux_port = colon == std::string::npos
                            ? 0 : atoi(pmux_spec.c_str());
        std::string svc = colon == std::string::npos
                              ? "" : pmux_spec.substr(colon + 1);
        if (pmux_port <= 0 || svc.empty()) {
            /* a malformed spec must fail AT STARTUP — a background
             * thread giving up after 10 s leaves a healthy-looking
             * node that is permanently undiscoverable */
            fprintf(stderr, "sut_node: -M wants <pmux_port>:<service>\n");
            return 2;
        }
        int my_port = n.ports[n.id];
        std::thread([pmux_port, svc, my_port]() {
            std::string line = "use " + svc + " " +
                               std::to_string(my_port) + "\n";
            for (int attempt = 0; attempt < 50; attempt++) {
                int fd = dial("127.0.0.1", pmux_port, 500);
                if (fd >= 0) {
                    /* dial() set SO_RCVTIMEO/SO_SNDTIMEO, so a pmux
                     * that accepts and never replies counts as a
                     * FAILED attempt (and retries) instead of parking
                     * this thread and its fd forever; send_all covers
                     * short writes and EINTR */
                    bool ok = send_all(fd, line);
                    char buf[64];
                    ok = ok && read(fd, buf, sizeof buf) > 0 &&
                         buf[0] == '0';
                    close(fd);
                    if (ok) return;
                }
                std::this_thread::sleep_for(
                    std::chrono::milliseconds(200));
            }
            fprintf(stderr, "sut_node: pmux registration failed\n");
        }).detach();
    }
    /* every node runs senders; they idle unless this node leads */
    for (int peer = 0; peer < (int)n.ports.size(); peer++)
        if (peer != n.id) std::thread(sender_thread, peer).detach();
    std::thread(election_thread).detach();
    if (n.syncing()) std::thread(syncer_thread).detach();
    fprintf(stderr, "sut_node %d (%s, %s) on 127.0.0.1:%d\n", n.id,
            role_name(n.role), n.durable ? "durable" : "no-durable",
            n.ports[n.id]);

    for (;;) {
        int fd = accept(srv, nullptr, nullptr);
        if (fd < 0) continue;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        std::thread(serve_conn, fd).detach();
    }
}
