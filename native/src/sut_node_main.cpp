/* sut_node — one node of a replicated register/set SUT cluster.
 *
 * The in-tree stand-in for the reference's 5-node comdb2 cluster in its
 * linearizable configuration (linearizable/linearizable.lrl:1-17):
 * a primary ships a totally-ordered op log to replicas and, in durable
 * mode, acknowledges a write only after a MAJORITY of nodes hold it —
 * the durable-LSN rule of bdb/rep.c:2096 ("client writes aren't done
 * until a majority has them"). `--no-durable` is the negative control:
 * writes are acknowledged after the local apply only, so a partition
 * between primary and replicas yields real stale reads / lost writes
 * that the checker must catch (round-1 Missing #3: partitions could
 * sever client<->server but never produce an anomaly in-tree).
 *
 * Topology: all nodes on 127.0.0.1, one port each; node 0 is primary
 * (static — no election; a partitioned durable primary blocks, which is
 * the honest linearizable behavior without leader change).
 *
 * Client protocol (line-based, same shapes as sut_server):
 *   R [k]      -> "V <int>" | "NIL" | "UNKNOWN"   read key k (dflt 1)
 *   W [k] <v>  -> "OK" | "UNKNOWN"                write
 *   C [k] <a> <b> -> "OK" | "FAIL" | "UNKNOWN"    cas
 *   A <v>      -> "OK" | "UNKNOWN"                set add
 *   S          -> "V <v1> ..."                    set read (local)
 *   P          -> "PONG"
 *   I          -> "I <id> <role> <applied> <durable>"  cluster info
 *                 (role: primary|replica; <durable> is meaningful on
 *                 the primary only — replicas always report 0)
 *   B <peer>   -> "OK"   drop traffic with node <peer>  (partition)
 *   U <peer>   -> "OK"   heal one peer
 *   U          -> "OK"   heal all
 * Inter-node:
 *   F <from> <cmd...>    forwarded client op (dropped when blocked)
 *   E <from> <lsn> <op...> -> "A <lsn>"        log entry (repl stream)
 *
 * Reads in durable mode forward to the primary (the role of
 * REQUEST_DURABLE_LSN_FROM_MASTER / RETRIEVE_DURABLE_LSN_AT_BEGIN in
 * the lrl); in no-durable mode every node serves its possibly-stale
 * local state.
 */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

struct LogEntry {
    char kind;          /* 'W', 'C', 'A' */
    long long key, a, b;    /* register key (the jepsen register id) */
};

struct Node {
    int id = 0;
    int primary = 0;
    bool durable = true;
    int timeout_ms = 2000;      /* durable-LSN wait (lrl:17 = 2000ms) */
    std::vector<int> ports;

    std::mutex mu;
    std::condition_variable cv;

    /* replicated state machine (applied prefix of the log): keyed
     * registers (the reference's register table rows, id -> val) */
    long long applied_lsn = 0;
    std::map<long long, long long> regs;
    std::vector<long long> set_vals;

    /* primary-only: the log + per-replica ack tracking */
    std::vector<LogEntry> log;               /* log[i] has lsn i+1 */
    std::vector<long long> acked_upto;       /* per node id */
    long long durable_lsn = 0;

    /* partition control: peers we drop traffic with */
    std::set<int> blocked;

    bool is_primary() const { return id == primary; }
    size_t majority() const { return ports.size() / 2 + 1; }

    bool blocked_peer(int peer) {
        std::lock_guard<std::mutex> g(mu);
        return blocked.count(peer) != 0;
    }

    /* apply an entry to the local state machine; caller holds mu */
    void apply_locked(const LogEntry &e) {
        if (e.kind == 'W') {
            regs[e.key] = e.a;
        } else if (e.kind == 'C') {
            /* CAS entries are logged only when they applied */
            regs[e.key] = e.b;
        } else if (e.kind == 'A') {
            set_vals.push_back(e.a);
        }
        applied_lsn++;
    }

    void recompute_durable_locked() {
        /* durable LSN = highest lsn held by a majority (self included):
         * sort per-node acked positions, take the majority-th highest —
         * the durable-LSN calculation of bdb/rep.c:2096 */
        std::vector<long long> pos = acked_upto;
        pos[id] = (long long)log.size();
        std::sort(pos.begin(), pos.end(), std::greater<long long>());
        long long d = pos[majority() - 1];
        if (d > durable_lsn) {
            durable_lsn = d;
            cv.notify_all();
        }
    }
};

Node g_node;

/* ---------- small line-protocol client (for forwarding) ----------- */

int dial(int port, int timeout_ms) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons((uint16_t)port);
    if (connect(fd, (sockaddr *)&addr, sizeof addr) != 0) {
        close(fd);
        return -1;
    }
    return fd;
}

bool send_all(int fd, const std::string &s) {
    size_t off = 0;
    while (off < s.size()) {
        ssize_t w = write(fd, s.c_str() + off, s.size() - off);
        if (w < 0) {
            if (errno == EINTR) continue;
            return false;
        }
        off += (size_t)w;
    }
    return true;
}

/* read one '\n'-terminated line (without the newline); false on
 * timeout/eof */
bool read_line(int fd, std::string *out) {
    out->clear();
    char c;
    for (;;) {
        ssize_t r = read(fd, &c, 1);
        if (r <= 0) {
            if (r < 0 && errno == EINTR) continue;
            return false;
        }
        if (c == '\n') return true;
        out->push_back(c);
        if (out->size() > 4096) return false;
    }
}

/* one transient request/reply to a peer; empty string = no answer */
std::string peer_request(int port, const std::string &line,
                         int timeout_ms) {
    int fd = dial(port, timeout_ms);
    if (fd < 0) return "";
    std::string reply;
    if (!send_all(fd, line + "\n") || !read_line(fd, &reply))
        reply.clear();
    close(fd);
    return reply;
}

/* ---------- replication sender (primary -> one replica) ----------- */

void sender_thread(int peer) {
    Node &n = g_node;
    int fd = -1;
    for (;;) {
        long long next;
        LogEntry e{};
        {
            std::unique_lock<std::mutex> lk(n.mu);
            n.cv.wait_for(lk, std::chrono::milliseconds(50), [&] {
                return n.acked_upto[peer] < (long long)n.log.size() &&
                       n.blocked.count(peer) == 0;
            });
            if (n.blocked.count(peer) != 0 ||
                n.acked_upto[peer] >= (long long)n.log.size())
                continue;
            next = n.acked_upto[peer] + 1;
            e = n.log[(size_t)next - 1];
        }
        if (fd < 0) fd = dial(n.ports[peer], 200);
        if (fd < 0) {
            /* unreachable replica: back off instead of spinning the
             * dial loop at 100% CPU (loopback refusals fail in µs) */
            std::this_thread::sleep_for(std::chrono::milliseconds(50));
            continue;
        }
        char buf[160];
        snprintf(buf, sizeof buf, "E %d %lld %c %lld %lld %lld\n",
                 n.id, next, e.kind, e.key, e.a, e.b);
        std::string reply;
        if (!send_all(fd, buf) || !read_line(fd, &reply)) {
            close(fd);
            fd = -1;
            continue;
        }
        long long acked = 0;
        if (sscanf(reply.c_str(), "A %lld", &acked) == 1) {
            std::lock_guard<std::mutex> g(n.mu);
            if (acked > n.acked_upto[peer]) {
                n.acked_upto[peer] = acked;
                n.recompute_durable_locked();
            }
        } else {
            close(fd);
            fd = -1;
        }
    }
}

/* ---------- request handling -------------------------------------- */

/* primary-side commit: append + apply + (durable) wait for majority.
 * Returns "OK", "FAIL" (cas precondition), or "UNKNOWN" (durable wait
 * timed out: the op is in the log and may still replicate —
 * indeterminate, exactly an :info op). The cas precondition is decided
 * under the same lock as the append, so concurrent cas ops serialize. */
std::string primary_commit(const LogEntry &e, bool is_cas = false) {
    Node &n = g_node;
    long long lsn;
    {
        std::lock_guard<std::mutex> g(n.mu);
        if (is_cas) {
            auto it = n.regs.find(e.key);
            if (it == n.regs.end() || it->second != e.a)
                return "FAIL";
        }
        n.log.push_back(e);
        lsn = (long long)n.log.size();
        n.apply_locked(e);
        n.recompute_durable_locked();
    }
    n.cv.notify_all();
    if (!n.durable) return "OK";
    std::unique_lock<std::mutex> lk(n.mu);
    bool ok = n.cv.wait_for(lk, std::chrono::milliseconds(n.timeout_ms),
                            [&] { return n.durable_lsn >= lsn; });
    return ok ? "OK" : "UNKNOWN";
}

std::string handle(const std::string &line);

/* forward a client op to the primary; both the partition state of this
 * node and the primary's are honored (F carries the origin id). A
 * blocked link behaves like a real partition: the request HANGS until
 * the timeout instead of failing fast — an instant UNKNOWN would let
 * clients machine-gun indeterminate ops (hundreds of forever-pending
 * ops per window make verification itself intractable; real packet
 * drops throttle clients to their timeout cadence). */
std::string forward_to_primary(const std::string &cmd) {
    Node &n = g_node;
    if (n.blocked_peer(n.primary)) {
        std::this_thread::sleep_for(
            std::chrono::milliseconds(n.timeout_ms));
        return "UNKNOWN";
    }
    char buf[160];
    snprintf(buf, sizeof buf, "F %d %s", n.id, cmd.c_str());
    std::string r = peer_request(n.ports[n.primary], buf, n.timeout_ms);
    return r.empty() ? "UNKNOWN" : r;
}

std::string handle(const std::string &line) {
    Node &n = g_node;
    char cmd = line.empty() ? 0 : line[0];
    if (cmd == 'P') return "PONG";
    if (cmd == 'I') {
        std::lock_guard<std::mutex> g(n.mu);
        char buf[128];
        snprintf(buf, sizeof buf, "I %d %s %lld %lld", n.id,
                 n.is_primary() ? "primary" : "replica", n.applied_lsn,
                 n.durable_lsn);
        return buf;
    }
    if (cmd == 'B' || cmd == 'U') {
        int peer = -1;
        bool have = sscanf(line.c_str() + 1, "%d", &peer) == 1;
        std::lock_guard<std::mutex> g(n.mu);
        if (cmd == 'B' && have)
            n.blocked.insert(peer);
        else if (cmd == 'U' && have)
            n.blocked.erase(peer);
        else if (cmd == 'U')
            n.blocked.clear();
        n.cv.notify_all();
        return "OK";
    }
    if (cmd == 'F') {
        int from = -1;
        int off = 0;
        if (sscanf(line.c_str() + 1, "%d %n", &from, &off) < 1)
            return "ERR";
        if (n.blocked_peer(from)) {     /* hang like a dropped packet */
            std::this_thread::sleep_for(
                std::chrono::milliseconds(n.timeout_ms));
            return "UNKNOWN";
        }
        return handle(line.substr(1 + (size_t)off));
    }
    if (cmd == 'E') {
        int from = -1;
        long long lsn = 0, key = 0, a = 0, b = 0;
        char kind = 0;
        if (sscanf(line.c_str() + 1, "%d %lld %c %lld %lld %lld",
                   &from, &lsn, &kind, &key, &a, &b) != 6)
            return "ERR";
        if (n.blocked_peer(from)) return "ERR";
        std::lock_guard<std::mutex> g(n.mu);
        if (lsn == n.applied_lsn + 1)
            n.apply_locked({kind, key, a, b});
        char buf[64];
        snprintf(buf, sizeof buf, "A %lld", n.applied_lsn);
        return buf;
    }
    if (cmd == 'R') {
        long long key = 1;                  /* "R" alone = key 1 */
        sscanf(line.c_str() + 1, "%lld", &key);
        if (n.durable && !n.is_primary())
            return forward_to_primary("R " + std::to_string(key));
        std::lock_guard<std::mutex> g(n.mu);
        auto it = n.regs.find(key);
        return it != n.regs.end() ? "V " + std::to_string(it->second)
                                  : "NIL";
    }
    if (cmd == 'S') {
        std::lock_guard<std::mutex> g(n.mu);
        std::string out = "V";
        for (long long v : n.set_vals) out += " " + std::to_string(v);
        return out;
    }
    if (cmd == 'W' || cmd == 'C' || cmd == 'A') {
        if (!n.is_primary()) return forward_to_primary(line);
        if (cmd == 'W') {
            /* "W k v" keyed; "W v" = key 1 (sut_server compatible) */
            long long k = 0, v = 0;
            int cnt = sscanf(line.c_str() + 1, "%lld %lld", &k, &v);
            if (cnt == 1) { v = k; k = 1; }
            else if (cnt != 2) return "ERR";
            return primary_commit({'W', k, v, 0});
        }
        if (cmd == 'A') {
            long long v = atoll(line.c_str() + 1);
            return primary_commit({'A', 0, v, 0});
        }
        /* "C k a b" keyed; "C a b" = key 1 */
        long long k = 0, a = 0, b = 0;
        int cnt = sscanf(line.c_str() + 1, "%lld %lld %lld", &k, &a, &b);
        if (cnt == 2) { b = a; a = k; k = 1; }
        else if (cnt != 3) return "ERR";
        return primary_commit({'C', k, a, b}, /*is_cas=*/true);
    }
    return "ERR";
}

void serve_conn(int fd) {
    FILE *in = fdopen(fd, "r");
    if (in == nullptr) {
        close(fd);
        return;
    }
    char line[512];
    while (fgets(line, sizeof line, in) != nullptr) {
        size_t len = strlen(line);
        while (len > 0 && (line[len - 1] == '\n' || line[len - 1] == '\r'))
            line[--len] = 0;
        std::string out = handle(line) + "\n";
        if (!send_all(fd, out)) break;
    }
    fclose(in);
}

}  // namespace

int main(int argc, char **argv) {
    Node &n = g_node;
    std::string peers;
    int c;
    while ((c = getopt(argc, argv, "i:n:P:t:Nh")) != -1) {
        switch (c) {
        case 'i': n.id = atoi(optarg); break;
        case 'n': peers = optarg; break;
        case 'P': n.primary = atoi(optarg); break;
        case 't': n.timeout_ms = atoi(optarg); break;
        case 'N': n.durable = false; break;
        default:
            fprintf(stderr,
                    "usage: %s -i id -n port0,port1,... [-P primary] "
                    "[-t durable_timeout_ms] [-N (no-durable)]\n",
                    argv[0]);
            return 2;
        }
    }
    for (const char *p = peers.c_str(); *p != 0;) {
        n.ports.push_back(atoi(p));
        const char *comma = strchr(p, ',');
        if (comma == nullptr) break;
        p = comma + 1;
    }
    if (n.ports.empty() || n.id < 0 ||
        n.id >= (int)n.ports.size()) {
        fprintf(stderr, "sut_node: bad -i/-n\n");
        return 2;
    }
    n.acked_upto.assign(n.ports.size(), 0);
    signal(SIGPIPE, SIG_IGN);

    int srv = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons((uint16_t)n.ports[n.id]);
    if (bind(srv, (sockaddr *)&addr, sizeof addr) != 0 ||
        listen(srv, 64) != 0) {
        perror("bind/listen");
        return 2;
    }
    if (n.is_primary()) {
        for (int peer = 0; peer < (int)n.ports.size(); peer++)
            if (peer != n.id)
                std::thread(sender_thread, peer).detach();
    }
    fprintf(stderr, "sut_node %d (%s, %s) on 127.0.0.1:%d\n", n.id,
            n.is_primary() ? "primary" : "replica",
            n.durable ? "durable" : "no-durable", n.ports[n.id]);

    for (;;) {
        int fd = accept(srv, nullptr, nullptr);
        if (fd < 0) continue;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        std::thread(serve_conn, fd).detach();
    }
}
