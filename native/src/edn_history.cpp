#include "comdb2_tpu/edn_history.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>

struct edn_history {
    FILE *f = nullptr;
    std::mutex mu;
};

extern "C" {

edn_history *edn_open(const char *path) {
    auto *e = new edn_history();
    if (path != nullptr) {
        e->f = fopen(path, "w");
        if (e->f == nullptr) {
            delete e;
            return nullptr;
        }
        fputs("[\n", e->f);
    }
    return e;
}

void edn_close(edn_history *e) {
    if (e == nullptr) return;
    if (e->f != nullptr) {
        fputs("]\n", e->f);
        fclose(e->f);
    }
    delete e;
}

void edn_emit(edn_history *e, const char *type, const char *f,
              const char *value_edn, int process, uint64_t time_us) {
    if (e == nullptr || e->f == nullptr) return;
    std::lock_guard<std::mutex> g(e->mu);
    fprintf(e->f,
            "{:type :%s :f :%s :value %s :process %d :time %llu}\n",
            type, f, value_edn, process, (unsigned long long)time_us);
    fflush(e->f);
}

void edn_int(char *buf, size_t cap, long long v) {
    snprintf(buf, cap, "%lld", v);
}

void edn_nil(char *buf, size_t cap) {
    snprintf(buf, cap, "nil");
}

void edn_pair(char *buf, size_t cap, long long a, long long b) {
    snprintf(buf, cap, "[%lld %lld]", a, b);
}

}  /* extern "C" */
