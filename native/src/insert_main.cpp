/* insert (set) workload driver — threads insert unique increasing
 * values, then a final read classifies every attempt.
 *
 * Role of the reference's ctest/insert.c: the per-value state machine
 * (OK/FAILED/UNKNOWN at insert time → CHECKED/RECOVERED/LOST at check
 * time, insert.c:859-871, check() at :355-437) re-built over the
 * generic SUT ABI, with the same exit contract: 0 iff nothing was lost
 * and nothing unexpected appeared. Also emits an EDN history whose
 * final :read the Python set checker (checker.clj:108-154 semantics)
 * can re-verify offline.
 */
#include "comdb2_tpu/edn_history.h"
#include "comdb2_tpu/sut.h"
#include "comdb2_tpu/testutil.h"

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

namespace {

enum class St : uint8_t { OK, FAILED, UNKNOWN };

struct Opts {
    int nthreads = 5;
    long n_inserts = 1000;      /* total across threads */
    const char *edn_path = nullptr;
    uint32_t sut_flags = SUT_F_NONE;
    unsigned seed = 0;
    /* select-stress (insert.c -s/-S/-Y/-B): verify a pre-seeded range
     * stays exactly [0, S) in order between inserts */
    long select_records = 0;
    int select_bug = 0;         /* seed the range with a record missing */
    int test_dup = 0;           /* blkseq-dup (insert.c -x) */
    const char *target = nullptr;   /* "host:port,..." = live cluster
                                     * through the HA TCP backend
                                     * (in-memory backend otherwise) */
};

void usage(const char *argv0) {
    fprintf(stderr,
            "Usage: %s [opts]\n"
            "  -T n     worker threads (default 5)\n"
            "  -i n     total inserts (default 1000)\n"
            "  -j file  EDN history output\n"
            "  -d t     SUT target \"host:port,...\" (live cluster "
            "through the HA TCP client; in-memory otherwise)\n"
            "  -F       flaky SUT backend\n"
            "  -B       buggy SUT backend (MUST be caught: exit 1)\n"
            "  -S n     select-stress: seed [0,n) and verify the range "
            "between inserts (insert.c -s/-S)\n"
            "  -Z       seed the select-stress range with a record "
            "missing — the stress MUST detect it (insert.c -B)\n"
            "  -x       blkseq-dup: re-insert each applied value and "
            "require a duplicate failure (insert.c -x)\n"
            "  -s seed  rng seed\n",
            argv0);
}

/* select-stress check: the snapshot's sub-S values must be exactly
 * 0..S-1 (the reference walks `select a from t1 order by a` asserting
 * consecutive values, insert.c:181-224). Returns error count. */
long select_stress_check(sut_handle *h, long S) {
    long long *vals = nullptr;
    size_t n = 0;
    /* a transient read failure (injected flakiness) is not a
     * consistency error — skip this round */
    if (sut_set_read(h, &vals, &n) != SUT_OK) return 0;
    std::vector<bool> seen((size_t)S, false);
    long errors = 0;
    for (size_t i = 0; i < n; i++) {
        if (vals[i] >= 0 && vals[i] < S) {
            if (seen[(size_t)vals[i]]) errors++;  /* dup in range */
            seen[(size_t)vals[i]] = true;
        }
    }
    free(vals);
    for (long v = 0; v < S; v++)
        if (!seen[(size_t)v]) errors++;           /* missing record */
    return errors;
}

/* bounded retry for SUT calls that can land in a live cluster's fault
 * window (leaderless gap, partition healing): ~10 s total budget */
template <typename Fn>
int retry_sut(Fn fn) {
    int rc = SUT_FAIL;
    for (int attempt = 0; attempt < 40; attempt++) {
        rc = fn();
        if (rc == SUT_OK) break;
        struct timespec ts = {0, 250 * 1000 * 1000};
        nanosleep(&ts, nullptr);
    }
    return rc;
}

}  // namespace

int main(int argc, char **argv) {
    Opts opt;
    int c;
    while ((c = getopt(argc, argv, "T:i:j:d:FBS:Zxs:h")) != -1) {
        switch (c) {
        case 'T': opt.nthreads = atoi(optarg); break;
        case 'i': opt.n_inserts = atol(optarg); break;
        case 'j': opt.edn_path = optarg; break;
        case 'd': opt.target = optarg; break;
        case 'F': opt.sut_flags |= SUT_F_FLAKY; break;
        case 'B': opt.sut_flags |= SUT_F_BUGGY; break;
        case 'S': opt.select_records = atol(optarg); break;
        case 'Z': opt.select_bug = 1; break;
        case 'x': opt.test_dup = 1; break;
        case 's': opt.seed = (unsigned)atol(optarg); break;
        default: usage(argv[0]); return 2;
        }
    }
    const long S = opt.select_records;

    edn_history *edn = edn_open(opt.edn_path);
    if (opt.edn_path != nullptr && edn == nullptr) {
        fprintf(stderr, "cannot open %s\n", opt.edn_path);
        return 2;
    }

    std::vector<St> state((size_t)opt.n_inserts, St::FAILED);
    std::atomic<long> next{0};
    std::atomic<long> select_errors{0};
    std::atomic<long> blkseq_violations{0};

    /* select-stress prepare: seed the range [0, S) — with -Z one
     * record is deliberately missing and the stress MUST notice (the
     * insert.c -Y/-B prepare, done inline since the in-memory backend
     * is process-local) */
    if (S > 0) {
        sut_handle *h = sut_open(opt.target, SUT_F_NONE, opt.seed);
        for (long v = 0; v < S; v++) {
            if (opt.select_bug && v == S / 2) continue;
            /* against a live cluster a seed add can land in a fault
             * window — a silently dropped seed would turn every later
             * stress check into a false consistency violation */
            if (retry_sut([&] { return sut_set_add(h, v); })
                != SUT_OK) {
                fprintf(stderr, "seeding value %ld failed\n", v);
                return 2;
            }
        }
        sut_close(h);
    }

    auto worker = [&](int tid) {
        sut_handle *h =
            sut_open(opt.target, opt.sut_flags,
                     opt.seed * 131u + (unsigned)tid);
        char val[64];
        int process = tid;
        for (;;) {
            long v = next.fetch_add(1);
            if (v >= opt.n_inserts) break;
            long stored = v + S;       /* keep clear of the stress range */
            edn_int(val, sizeof val, v);
            edn_emit(edn, "invoke", "add", val, process, ct_timeus());
            int rc = opt.test_dup ? sut_set_add_unique(h, stored)
                                  : sut_set_add(h, stored);
            if (rc == SUT_OK) {
                state[(size_t)v] = St::OK;
                edn_emit(edn, "ok", "add", val, process, ct_timeus());
                if (opt.test_dup) {
                    /* a replayed insert of an applied row MUST NOT
                     * apply — the blkseq dedup contract
                     * (insert.c:263-301). Only OK (it applied twice)
                     * is a violation: FAIL is the expected dup error
                     * and UNKNOWN is an injected indeterminacy, not a
                     * double-apply. */
                    if (sut_set_add_unique(h, stored) == SUT_OK) {
                        CT_TRACE(stderr,
                                 "blkseq: re-insert of %ld APPLIED "
                                 "instead of returning DUP\n", stored);
                        blkseq_violations.fetch_add(1);
                    }
                }
            } else if (rc == SUT_FAIL) {
                state[(size_t)v] = St::FAILED;
                edn_emit(edn, "fail", "add", val, process, ct_timeus());
            } else {
                state[(size_t)v] = St::UNKNOWN;
                edn_emit(edn, "info", "add", val, process, ct_timeus());
                process += opt.nthreads;
            }
            if (S > 0)
                select_errors.fetch_add(select_stress_check(h, S));
        }
        sut_close(h);
    };

    std::vector<std::thread> threads;
    for (int i = 0; i < opt.nthreads; i++) threads.emplace_back(worker, i);
    for (auto &t : threads) t.join();

    /* final read + classification (insert.c check(), :355-437) */
    sut_handle *h = sut_open(opt.target, SUT_F_NONE, opt.seed);
    long long *vals = nullptr;
    size_t n = 0;
    /* the reader needs a process id outside every worker's retirement
     * chain (tid + k*nthreads covers all non-negative ids) */
    const int reader = -1;
    edn_emit(edn, "invoke", "read", "nil", reader, ct_timeus());
    /* the final committed read must survive a fault window still in
     * flight (leaderless gap, partition healing) — the reference
     * heals and gates on coherency before its check; against a live
     * cluster we retry instead of failing the whole run on one
     * transient window */
    if (retry_sut([&] { return sut_set_read(h, &vals, &n); })
        != SUT_OK) {
        fprintf(stderr, "final read failed\n");
        return 2;
    }
    std::string setbuf = "[";
    std::vector<bool> present((size_t)opt.n_inserts, false);
    long unexpected = 0;
    bool first = true;
    for (size_t i = 0; i < n; i++) {
        long long v = vals[i] - S;    /* stress range lives below S */
        if (vals[i] >= 0 && vals[i] < S) continue;
        if (v < 0 || v >= opt.n_inserts) {
            unexpected++;
            continue;
        }
        if (present[(size_t)v]) continue;         /* dup read row */
        present[(size_t)v] = true;
        if (!first) setbuf += " ";
        first = false;
        setbuf += std::to_string(v);
    }
    setbuf += "]";
    free(vals);
    edn_emit(edn, "ok", "read", setbuf.c_str(), reader, ct_timeus());
    edn_close(edn);
    sut_close(h);

    long checked = 0, lost = 0, recovered = 0, failed = 0;
    for (long v = 0; v < opt.n_inserts; v++) {
        switch (state[(size_t)v]) {
        case St::OK:
            if (present[(size_t)v]) checked++;
            else lost++;
            break;
        case St::UNKNOWN:
            if (present[(size_t)v]) recovered++;
            break;
        case St::FAILED:
            if (present[(size_t)v]) unexpected++;
            else failed++;
            break;
        }
    }
    printf("{\"checked\": %ld, \"lost\": %ld, \"recovered\": %ld, "
           "\"failed\": %ld, \"unexpected\": %ld, "
           "\"select_errors\": %ld, \"blkseq_violations\": %ld}\n",
           checked, lost, recovered, failed, unexpected,
           select_errors.load(), blkseq_violations.load());
    return (lost == 0 && unexpected == 0 && select_errors.load() == 0 &&
            blkseq_violations.load() == 0) ? 0 : 1;
}
