/* insert (set) workload driver — threads insert unique increasing
 * values, then a final read classifies every attempt.
 *
 * Role of the reference's ctest/insert.c: the per-value state machine
 * (OK/FAILED/UNKNOWN at insert time → CHECKED/RECOVERED/LOST at check
 * time, insert.c:859-871, check() at :355-437) re-built over the
 * generic SUT ABI, with the same exit contract: 0 iff nothing was lost
 * and nothing unexpected appeared. Also emits an EDN history whose
 * final :read the Python set checker (checker.clj:108-154 semantics)
 * can re-verify offline.
 */
#include "comdb2_tpu/edn_history.h"
#include "comdb2_tpu/sut.h"
#include "comdb2_tpu/testutil.h"

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <map>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

namespace {

enum class St : uint8_t { OK, FAILED, UNKNOWN };

struct Opts {
    int nthreads = 5;
    long n_inserts = 1000;      /* total across threads */
    const char *edn_path = nullptr;
    uint32_t sut_flags = SUT_F_NONE;
    unsigned seed = 0;
};

void usage(const char *argv0) {
    fprintf(stderr,
            "Usage: %s [opts]\n"
            "  -T n     worker threads (default 5)\n"
            "  -i n     total inserts (default 1000)\n"
            "  -j file  EDN history output\n"
            "  -F       flaky SUT backend\n"
            "  -B       buggy SUT backend (MUST be caught: exit 1)\n"
            "  -s seed  rng seed\n",
            argv0);
}

}  // namespace

int main(int argc, char **argv) {
    Opts opt;
    int c;
    while ((c = getopt(argc, argv, "T:i:j:FBs:h")) != -1) {
        switch (c) {
        case 'T': opt.nthreads = atoi(optarg); break;
        case 'i': opt.n_inserts = atol(optarg); break;
        case 'j': opt.edn_path = optarg; break;
        case 'F': opt.sut_flags |= SUT_F_FLAKY; break;
        case 'B': opt.sut_flags |= SUT_F_BUGGY; break;
        case 's': opt.seed = (unsigned)atol(optarg); break;
        default: usage(argv[0]); return 2;
        }
    }

    edn_history *edn = edn_open(opt.edn_path);
    if (opt.edn_path != nullptr && edn == nullptr) {
        fprintf(stderr, "cannot open %s\n", opt.edn_path);
        return 2;
    }

    std::vector<St> state((size_t)opt.n_inserts, St::FAILED);
    std::atomic<long> next{0};

    auto worker = [&](int tid) {
        sut_handle *h =
            sut_open(nullptr, opt.sut_flags, opt.seed * 131u + (unsigned)tid);
        char val[64];
        int process = tid;
        for (;;) {
            long v = next.fetch_add(1);
            if (v >= opt.n_inserts) break;
            edn_int(val, sizeof val, v);
            edn_emit(edn, "invoke", "add", val, process, ct_timeus());
            int rc = sut_set_add(h, v);
            if (rc == SUT_OK) {
                state[(size_t)v] = St::OK;
                edn_emit(edn, "ok", "add", val, process, ct_timeus());
            } else if (rc == SUT_FAIL) {
                state[(size_t)v] = St::FAILED;
                edn_emit(edn, "fail", "add", val, process, ct_timeus());
            } else {
                state[(size_t)v] = St::UNKNOWN;
                edn_emit(edn, "info", "add", val, process, ct_timeus());
                process += opt.nthreads;
            }
        }
        sut_close(h);
    };

    std::vector<std::thread> threads;
    for (int i = 0; i < opt.nthreads; i++) threads.emplace_back(worker, i);
    for (auto &t : threads) t.join();

    /* final read + classification (insert.c check(), :355-437) */
    sut_handle *h = sut_open(nullptr, SUT_F_NONE, opt.seed);
    long long *vals = nullptr;
    size_t n = 0;
    /* the reader needs a process id outside every worker's retirement
     * chain (tid + k*nthreads covers all non-negative ids) */
    const int reader = -1;
    edn_emit(edn, "invoke", "read", "nil", reader, ct_timeus());
    int rc = sut_set_read(h, &vals, &n);
    if (rc != SUT_OK) {
        fprintf(stderr, "final read failed\n");
        return 2;
    }
    std::string setbuf = "[";
    std::vector<bool> present((size_t)opt.n_inserts, false);
    long unexpected = 0;
    for (size_t i = 0; i < n; i++) {
        if (vals[i] < 0 || vals[i] >= opt.n_inserts) {
            unexpected++;
            continue;
        }
        if (present[(size_t)vals[i]]) continue;   /* dup read row */
        present[(size_t)vals[i]] = true;
        if (i > 0) setbuf += " ";
        setbuf += std::to_string(vals[i]);
    }
    setbuf += "]";
    free(vals);
    edn_emit(edn, "ok", "read", setbuf.c_str(), reader, ct_timeus());
    edn_close(edn);
    sut_close(h);

    long checked = 0, lost = 0, recovered = 0, failed = 0;
    for (long v = 0; v < opt.n_inserts; v++) {
        switch (state[(size_t)v]) {
        case St::OK:
            if (present[(size_t)v]) checked++;
            else lost++;
            break;
        case St::UNKNOWN:
            if (present[(size_t)v]) recovered++;
            break;
        case St::FAILED:
            if (present[(size_t)v]) unexpected++;
            else failed++;
            break;
        }
    }
    printf("{\"checked\": %ld, \"lost\": %ld, \"recovered\": %ld, "
           "\"failed\": %ld, \"unexpected\": %ld}\n",
           checked, lost, recovered, failed, unexpected);
    return (lost == 0 && unexpected == 0) ? 0 : 1;
}
