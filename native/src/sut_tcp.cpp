/* TCP backend for the SUT client ABI — HA client semantics over the
 * replicated sut_node cluster.
 *
 * The role of cdb2api's HA machinery (cdb2api.c:618-656): the handle
 * holds a NODE LIST, opens against a random node (CDB2_RANDOM), and on
 * connection failure RETRIES ELSEWHERE; reads track the highest
 * applied LSN this handle has observed (the snapshot_file/snapshot_lsn
 * role) and are only served by nodes at or past it, so a failover
 * never sends a session backwards in time. A mutating op whose request
 * was sent but never answered is indeterminate (SUT_UNKNOWN) — without
 * the reference's cnonce/blkseq dedup a blind retry could double-apply,
 * so the honest outcome is :info, exactly the harness's rule.
 *
 * Selected by sut_open(target) when target looks like
 * "host:port[,host:port...]"; sut_mem keeps serving target == NULL.
 */
#include "comdb2_tpu/sut.h"
#include "comdb2_tpu/sut_tcp.h"
#include "comdb2_tpu/testutil.h"

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <thread>
#include <vector>

struct sut_tcp {
    std::vector<std::string> hosts;
    std::vector<int> ports;
    std::mt19937 rng;
    int timeout_ms = 1000;
    int max_retries = 5;            /* nodes tried per op */
    long long seen_lsn = 0;         /* snapshot tracking */
    size_t cur = 0;                 /* current node (sticky) */
    unsigned long long session = 0; /* high nonce bits (random) */
    unsigned long long op_seq = 0;  /* low nonce bits (per op) */
};

namespace {

/* one request against the CURRENT node; rc: 0 ok, -1 never connected
 * (safe to retry elsewhere), -2 connected-but-failed (the request MAY
 * have been delivered — mutating ops must NOT retry) */
int node_request(sut_tcp *t, const std::string &line, char *reply,
                 int cap) {
    int n = ct_tcp_request(t->hosts[t->cur].c_str(), t->ports[t->cur],
                           line.c_str(), t->timeout_ms, reply, cap);
    if (n >= 0) return 0;
    return n;      /* ct_tcp_request's -1/-2 carry the same meaning */
}

void next_node(sut_tcp *t) {
    t->cur = (t->cur + 1) % t->hosts.size();
}

/* applied LSN of the current node via the info verb; -1 unreachable */
long long node_applied(sut_tcp *t) {
    char reply[128];
    if (ct_tcp_request(t->hosts[t->cur].c_str(), t->ports[t->cur], "I",
                       t->timeout_ms, reply, sizeof reply) < 0)
        return -1;
    int id;
    char role[32];
    long long applied = -1, durable = -1;
    if (sscanf(reply, "I %d %31s %lld %lld", &id, role, &applied,
               &durable) >= 3)
        return applied;
    return -1;
}

/* mutating op, retry-safe via replay nonces (the cdb2api HA retry +
 * bdb blkseq pairing, cdb2api.c:618-656): every mutation is sent as
 * "M <nonce> <cmd>" with a session-unique nonce, so a request whose
 * outcome was lost (timeout, failover, durable-wait UNKNOWN) can be
 * RETRIED ELSEWHERE — a node that already applied it replays the
 * recorded outcome instead of double-applying. Only when the retry
 * budget exhausts with a possibly-delivered attempt outstanding does
 * the op surface as indeterminate; before nonces every such attempt
 * was an instant UNKNOWN and fault-window histories drowned in
 * forever-pending info ops.
 * An acked mutation's commit LSN (the "OK <lsn>" reply) folds into
 * the session's snapshot LSN so this session's own writes are covered
 * by the reads-never-go-backwards gate. */
int mutate(sut_tcp *t, const std::string &line) {
    char reply[192];
    unsigned long long nonce = (t->session << 24) | ++t->op_seq;
    std::string msg = "M " + std::to_string(nonce) + " " + line;
    bool maybe_delivered = false;
    for (int attempt = 0; attempt < t->max_retries; attempt++) {
        int rc = node_request(t, msg, reply, sizeof reply);
        if (rc == 0) {
            if (strncmp(reply, "OK", 2) == 0 &&
                (reply[2] == 0 || reply[2] == ' ')) {
                long long lsn = 0;
                if (sscanf(reply + 2, "%lld", &lsn) == 1 &&
                    lsn > t->seen_lsn)
                    t->seen_lsn = lsn;
                return SUT_OK;
            }
            if (strcmp(reply, "FAIL") == 0) return SUT_FAIL;
            /* UNKNOWN reply: delivered, outcome unresolved (durable
             * wait timed out / leaderless window) — safe to retry,
             * the nonce dedups */
            maybe_delivered = true;
        } else if (rc == -2) {
            maybe_delivered = true;     /* sent, no complete reply */
        }
        next_node(t);
        if (rc != -1 && attempt + 1 < t->max_retries)
            /* give a fault window time to move (skip after the
             * final attempt — the sleep would be dead latency) */
            std::this_thread::sleep_for(
                std::chrono::milliseconds(100));
    }
    return maybe_delivered ? SUT_UNKNOWN : SUT_FAIL;
}

/* read: retry elsewhere freely, but only accept an answer from a node
 * at or past this session's snapshot LSN */
int read_op(sut_tcp *t, const std::string &line, char *reply, int cap) {
    for (int attempt = 0; attempt < t->max_retries; attempt++) {
        long long applied = node_applied(t);
        if (applied < 0 || applied < t->seen_lsn) {
            next_node(t);           /* lagging/unreachable replica */
            continue;
        }
        int rc = node_request(t, line, reply, cap);
        if (rc == 0) {
            if (applied > t->seen_lsn) t->seen_lsn = applied;
            return 0;
        }
        next_node(t);
    }
    return -1;
}

}  // namespace

extern "C" {

/* comdb2db-style cluster discovery (the role of cdb2api's comdb2db
 * config lookup, cdb2api.c:780-1000): "@<path>[#<dbname>]" names a
 * config file whose lines are "<dbname> host[:port] host[:port] ..."
 * ('#' comments). With no #dbname the first entry wins. Returns the
 * flattened "host[:port],..." list (port-less entries resolve through
 * pmux at open time), or "" when the file/db is missing. ``dbname_out``
 * receives the matched database name. */
static std::string resolve_comdb2db(const char *spec,
                                    std::string *dbname_out) {
    std::string s(spec + 1);            /* past '@' */
    std::string want;
    size_t hash = s.rfind('#');
    if (hash != std::string::npos) {
        want = s.substr(hash + 1);
        s = s.substr(0, hash);
    }
    FILE *f = fopen(s.c_str(), "r");
    if (f == nullptr) return "";
    char line[1024];
    std::string out;
    while (fgets(line, sizeof line, f) != nullptr) {
        char *p = line;
        while (*p == ' ' || *p == '\t') p++;
        if (*p == '#' || *p == '\n' || *p == 0) continue;
        char name[256] = {0};
        int off = 0;
        if (sscanf(p, "%255s %n", name, &off) < 1) continue;
        if (!want.empty() && want != name) continue;
        if (dbname_out != nullptr) *dbname_out = name;
        for (char *tok = strtok(p + off, " \t\r\n"); tok != nullptr;
             tok = strtok(nullptr, " \t\r\n")) {
            if (!out.empty()) out += ",";
            out += tok;
        }
        break;
    }
    fclose(f);
    return out;
}

/* pmux port lookup (the cdb2api portmux_get role: a config entry
 * WITHOUT :port resolves through that host's port multiplexer —
 * tools/pmux serves "get <service>"). The pmux port comes from
 * COMDB2_TPU_PMUX_PORT (default 5105); the service name is
 * "sut/<dbname>". Returns -1 when pmux is unreachable or the service
 * is unregistered. */
static int pmux_get_port(const std::string &host,
                         const std::string &svc) {
    const char *env = getenv("COMDB2_TPU_PMUX_PORT");
    int pmux_port = env != nullptr ? atoi(env) : 5105;
    char reply[256];
    std::string req = "get " + svc;
    if (ct_tcp_request(host.c_str(), pmux_port, req.c_str(), 2000,
                       reply, sizeof reply) < 0)
        return -1;
    int port = atoi(reply);
    return port > 0 ? port : -1;
}

sut_tcp *sut_tcp_open(const char *target, unsigned seed) {
    std::string resolved;
    std::string dbname = "sut";
    if (target != nullptr && target[0] == '@') {
        resolved = resolve_comdb2db(target, &dbname);
        if (resolved.empty()) return nullptr;
        target = resolved.c_str();
    }
    auto *t = new sut_tcp();
    t->rng.seed(seed);
    std::string s(target);
    size_t pos = 0;
    while (pos <= s.size()) {
        size_t c = s.find(',', pos);
        if (c == std::string::npos) c = s.size();
        if (c > pos) {
            std::string node = s.substr(pos, c - pos);
            size_t colon = node.rfind(':');
            if (colon == std::string::npos) {
                /* no port: the pmux indirection — ask this host's
                 * port multiplexer where the service lives */
                int port = pmux_get_port(node, "sut/" + dbname);
                if (port < 0) {
                    delete t;
                    return nullptr;
                }
                t->hosts.push_back(node);
                t->ports.push_back(port);
            } else {
                t->hosts.push_back(node.substr(0, colon));
                t->ports.push_back(atoi(node.c_str() + colon + 1));
            }
        }
        pos = c + 1;
    }
    if (t->hosts.empty()) {
        delete t;
        return nullptr;
    }
    t->cur = t->rng() % t->hosts.size();   /* CDB2_RANDOM routing */
    t->session = ((unsigned long long)t->rng() << 8) ^ t->rng();
    return t;
}

void sut_tcp_close(sut_tcp *t) {
    delete t;
}

int sut_tcp_reg_read(sut_tcp *t, int *val, int *found) {
    char reply[128];
    if (read_op(t, "R 1", reply, sizeof reply) != 0) return SUT_FAIL;
    if (strcmp(reply, "NIL") == 0) {
        *found = 0;
        *val = 0;
        return SUT_OK;
    }
    if (reply[0] == 'V') {
        *val = atoi(reply + 1);
        *found = 1;
        return SUT_OK;
    }
    return SUT_UNKNOWN;
}

int sut_tcp_reg_write(sut_tcp *t, int val) {
    return mutate(t, "W 1 " + std::to_string(val));
}

int sut_tcp_reg_cas(sut_tcp *t, int expected, int newval) {
    return mutate(t, "C 1 " + std::to_string(expected) + " " +
                         std::to_string(newval));
}

int sut_tcp_set_add(sut_tcp *t, long long val) {
    return mutate(t, "A " + std::to_string(val));
}

int sut_tcp_set_read(sut_tcp *t, long long **vals, size_t *n) {
    /* heap buffer sized for millions of values; truncation (a line
     * that fills the buffer without its newline) is handled one layer
     * down — ct_tcp_request returns -2 for any reply missing its
     * terminating newline, so an rc==0 reply here is complete */
    const int cap = 32 << 20;
    std::vector<char> buf((size_t)cap);
    char *reply = buf.data();
    if (read_op(t, "S", reply, cap) != 0) return SUT_FAIL;
    if (reply[0] != 'V') return SUT_FAIL;
    std::vector<long long> out;
    const char *p = reply + 1;
    char *end = nullptr;
    for (;;) {
        long long v = strtoll(p, &end, 10);
        if (end == p) break;
        out.push_back(v);
        p = end;
    }
    *n = out.size();
    *vals = static_cast<long long *>(
        malloc(sizeof(long long) * (out.size() + 1)));
    memcpy(*vals, out.data(), sizeof(long long) * out.size());
    return SUT_OK;
}

}  /* extern "C" */
