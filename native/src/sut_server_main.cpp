/* sut_server — a network-reachable SUT for end-to-end harness runs.
 *
 * Wraps the in-memory backend (sut_mem.cpp) behind a line protocol on
 * TCP, so the Python harness (or the native drivers) can exercise the
 * full distributed loop: sockets, timeouts, process faults (SIGSTOP →
 * client timeouts → indeterminate ops), crash-restart.
 *
 * Protocol (one request per line, one reply per line):
 *   R            -> "V <int>" | "NIL"        (register read)
 *   W <v>        -> "OK"                     (register write)
 *   C <a> <b>    -> "OK" | "FAIL"            (cas expected new)
 *   A <v>        -> "OK"                     (set add)
 *   S            -> "V <v1> <v2> ..."        (set read)
 *   P            -> "PONG"                   (health)
 *   M <nonce> <W|C|A ...> -> same replies    (retry-safe mutation:
 *                  a nonce whose op already resolved OK/FAIL replays
 *                  the recorded reply — the single-node blkseq shape
 *                  the HA client's retries rely on)
 * Flags: -p port (default 7777), -F flaky, -B buggy, -s seed.
 */
#include "comdb2_tpu/sut.h"
#include "comdb2_tpu/testutil.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <csignal>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

/* replay dedup across ALL connections (sut_mem state is process-
 * global, so the dedup must be too): nonce -> recorded OK/FAIL reply.
 * UNKNOWN outcomes are unresolved and not recorded — their retry
 * re-executes. Held across the execute so concurrent same-nonce
 * retries serialize. */
std::mutex g_nonce_mu;
std::map<unsigned long long, std::string> g_nonce_reply;

std::string handle_cmd(sut_handle *h, const char *line) {
    char cmd = line[0];
    if (cmd == 'P') return "PONG\n";
    if (cmd == 'R') {
        int v = 0, found = 0;
        int rc = sut_reg_read(h, &v, &found);
        if (rc == SUT_OK)
            return found ? ("V " + std::to_string(v) + "\n") : "NIL\n";
        return "FAIL\n";
    }
    if (cmd == 'W') {
        int v = atoi(line + 1);
        int rc = sut_reg_write(h, v);
        return rc == SUT_OK ? "OK\n"
             : rc == SUT_FAIL ? "FAIL\n" : "UNKNOWN\n";
    }
    if (cmd == 'C') {
        int a = 0, b = 0;
        if (sscanf(line + 1, "%d %d", &a, &b) != 2) return "ERR\n";
        int rc = sut_reg_cas(h, a, b);
        return rc == SUT_OK ? "OK\n"
             : rc == SUT_FAIL ? "FAIL\n" : "UNKNOWN\n";
    }
    if (cmd == 'A') {
        long long v = atoll(line + 1);
        int rc = sut_set_add(h, v);
        return rc == SUT_OK ? "OK\n"
             : rc == SUT_FAIL ? "FAIL\n" : "UNKNOWN\n";
    }
    if (cmd == 'S') {
        long long *vals = nullptr;
        size_t n = 0;
        if (sut_set_read(h, &vals, &n) == SUT_OK) {
            std::string out = "V";
            for (size_t i = 0; i < n; i++)
                out += " " + std::to_string(vals[i]);
            out += "\n";
            free(vals);
            return out;
        }
        return "FAIL\n";
    }
    if (cmd == 'M') {
        unsigned long long nonce = 0;
        int off = 0;
        if (sscanf(line + 1, "%llu %n", &nonce, &off) < 1 ||
            nonce == 0)
            return "ERR\n";
        const char *inner = line + 1 + off;
        if (*inner != 'W' && *inner != 'C' && *inner != 'A')
            return "ERR\n";
        std::lock_guard<std::mutex> g(g_nonce_mu);
        auto it = g_nonce_reply.find(nonce);
        if (it != g_nonce_reply.end()) return it->second;
        std::string r = handle_cmd(h, inner);
        if (r == "OK\n" || r == "FAIL\n") g_nonce_reply[nonce] = r;
        return r;
    }
    return "ERR\n";
}

void serve_conn(int fd, uint32_t flags, unsigned seed) {
    sut_handle *h = sut_open(nullptr, flags, seed);
    FILE *in = fdopen(fd, "r");
    if (in == nullptr) {
        close(fd);
        sut_close(h);
        return;
    }
    char line[256];
    std::string out;
    while (fgets(line, sizeof line, in) != nullptr) {
        out = handle_cmd(h, line);
        /* loop: a short write (signal interruption, full send buffer
         * on a large set-read reply) would desync the line protocol */
        size_t off = 0;
        bool werr = false;
        while (off < out.size()) {
            ssize_t w = write(fd, out.c_str() + off, out.size() - off);
            if (w < 0) {
                if (errno == EINTR) continue;
                werr = true;
                break;
            }
            off += (size_t)w;
        }
        if (werr) break;
    }
    fclose(in);   /* closes fd */
    sut_close(h);
}

}  // namespace

int main(int argc, char **argv) {
    int port = 7777;
    uint32_t flags = SUT_F_NONE;
    unsigned seed = 0;
    int c;
    while ((c = getopt(argc, argv, "p:FBs:h")) != -1) {
        switch (c) {
        case 'p': port = atoi(optarg); break;
        case 'F': flags |= SUT_F_FLAKY; break;
        case 'B': flags |= SUT_F_BUGGY; break;
        case 's': seed = (unsigned)atol(optarg); break;
        default:
            fprintf(stderr, "usage: %s [-p port] [-F] [-B] [-s seed]\n",
                    argv[0]);
            return 2;
        }
    }
    signal(SIGPIPE, SIG_IGN);

    int srv = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons((uint16_t)port);
    if (bind(srv, (sockaddr *)&addr, sizeof addr) != 0 ||
        listen(srv, 64) != 0) {
        perror("bind/listen");
        return 2;
    }
    fprintf(stderr, "sut_server listening on 127.0.0.1:%d\n", port);

    unsigned conn_seed = seed;
    for (;;) {
        int fd = accept(srv, nullptr, nullptr);
        if (fd < 0) continue;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        std::thread(serve_conn, fd, flags, ++conn_seed).detach();
    }
}
