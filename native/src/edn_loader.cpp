/* Fast EDN history loader — the native data-loader of the framework.
 *
 * Parses the restricted op-map EDN shape the workload drivers emit
 * (ctest format: one map per line inside an optional vector):
 *
 *   {:type :invoke :f :cas :value [0 3] :process 2 :time 123 :uid 9}
 *
 * into flat arrays via a C ABI (ctypes-friendly). Values in the fast
 * subset are nil / integer / nested vectors of integers, flattened to
 * an ints pool with (offset, length, depth) per op; anything outside
 * the subset makes the loader return a "needs general parser" code so
 * the Python EDN reader takes over. ~50x the Python parse throughput.
 */
#include <cctype>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

enum {
    LOAD_OK = 0,
    LOAD_FALLBACK = 1,  /* valid EDN but outside the fast subset */
    LOAD_ERROR = 2,     /* malformed input */
};

/* value encodings */
enum { V_NIL = 0, V_INT = 1, V_VEC = 2, V_VECVEC = 3 };

struct Result {
    std::vector<int32_t> process;
    std::vector<int8_t> type;       /* 0 invoke 1 ok 2 fail 3 info */
    std::vector<int32_t> f;         /* id into f_names */
    std::vector<int64_t> time;      /* -1 if absent */
    std::vector<int8_t> val_kind;
    std::vector<int64_t> val_pool;  /* flattened ints */
    std::vector<int32_t> val_off;   /* offset into pool per op */
    std::vector<int32_t> val_len;   /* ints per op */
    std::vector<int32_t> val_split; /* V_VECVEC: index where the inner
                                       vector starts; -1 otherwise */
    std::string f_names;            /* \n-joined f keyword names */
    std::vector<std::string> f_list;
};

struct Parser {
    const char *p, *end;
    Result *r;

    void skip_ws() {
        while (p < end && (isspace((unsigned char)*p) || *p == ','))
            p++;
    }

    bool lit(const char *s) {
        size_t n = strlen(s);
        if ((size_t)(end - p) >= n && strncmp(p, s, n) == 0) {
            p += n;
            return true;
        }
        return false;
    }

    /* :keyword → string (no namespaces needed) */
    int kw(std::string &out) {
        if (p >= end || *p != ':') return LOAD_ERROR;
        p++;
        const char *s = p;
        while (p < end && (isalnum((unsigned char)*p) || *p == '-' ||
                           *p == '_' || *p == '?' || *p == '!' ||
                           *p == '.'))
            p++;
        if (p == s) return LOAD_ERROR;
        out.assign(s, p - s);
        return LOAD_OK;
    }

    int integer(long long &out) {
        const char *s = p;
        if (p < end && (*p == '-' || *p == '+')) p++;
        if (p >= end || !isdigit((unsigned char)*p)) return LOAD_FALLBACK;
        while (p < end && isdigit((unsigned char)*p)) p++;
        /* floats/ratios are outside the subset */
        if (p < end && (*p == '.' || *p == '/' || *p == 'e' ||
                        *p == 'E'))
            return LOAD_FALLBACK;
        errno = 0;
        out = strtoll(std::string(s, p - s).c_str(), nullptr, 10);
        /* out-of-range (strtoll saturates) and INT64_MIN (collides
         * with the nil-in-vector sentinel) must take the exact-bigint
         * Python path, not silently skew checker input */
        if (errno == ERANGE || out == INT64_MIN) return LOAD_FALLBACK;
        return LOAD_OK;
    }

    int f_id(const std::string &name) {
        for (size_t i = 0; i < r->f_list.size(); i++)
            if (r->f_list[i] == name) return (int)i;
        r->f_list.push_back(name);
        return (int)r->f_list.size() - 1;
    }

    /* value := nil | int | [v*] with ints and at most one inner
     * int-vector (the cas [k [a b]] shape) */
    int value(int8_t &kind, int32_t &off, int32_t &len, int32_t &split) {
        skip_ws();
        off = (int32_t)r->val_pool.size();
        len = 0;
        split = -1;
        if (lit("nil")) {
            kind = V_NIL;
            return LOAD_OK;
        }
        if (p < end && *p == '[') {
            p++;
            kind = V_VEC;
            for (;;) {
                skip_ws();
                if (p < end && *p == ']') {
                    p++;
                    return LOAD_OK;
                }
                /* the decoder assumes the inner vector is the LAST
                 * element; anything after it must fall back */
                if (split >= 0) return LOAD_FALLBACK;
                if (p < end && *p == '[') {
                    p++;
                    kind = V_VECVEC;
                    split = len;
                    for (;;) {
                        skip_ws();
                        if (p < end && *p == ']') {
                            p++;
                            break;
                        }
                        long long v;
                        int rc = integer(v);
                        if (rc != LOAD_OK) return rc ? rc : LOAD_ERROR;
                        r->val_pool.push_back(v);
                        len++;
                    }
                    continue;
                }
                if (lit("nil")) {
                    /* nil inside vectors (insert [a nil]): encode as
                       INT64_MIN sentinel */
                    r->val_pool.push_back(INT64_MIN);
                    len++;
                    continue;
                }
                long long v;
                int rc = integer(v);
                if (rc != LOAD_OK) return rc;
                r->val_pool.push_back(v);
                len++;
            }
        }
        long long v;
        int rc = integer(v);
        if (rc != LOAD_OK) return rc;
        kind = V_INT;
        r->val_pool.push_back(v);
        len = 1;
        return LOAD_OK;
    }

    int op_map() {
        if (p >= end || *p != '{') return LOAD_ERROR;
        p++;
        long long process = INT64_MIN, time_us = -1;
        int8_t type = -1;
        int f = -1;
        int8_t vkind = V_NIL;
        int32_t voff = (int32_t)r->val_pool.size(), vlen = 0, vsplit = -1;
        bool have_val = false;
        for (;;) {
            skip_ws();
            if (p < end && *p == '}') {
                p++;
                break;
            }
            std::string key;
            int rc = kw(key);
            if (rc != LOAD_OK) return rc;
            skip_ws();
            if (key == "type") {
                std::string t;
                if (kw(t) != LOAD_OK) return LOAD_ERROR;
                type = t == "invoke" ? 0 : t == "ok" ? 1
                     : t == "fail" ? 2 : t == "info" ? 3 : -1;
                if (type < 0) return LOAD_FALLBACK;
            } else if (key == "f") {
                std::string fn;
                if (kw(fn) != LOAD_OK) return LOAD_ERROR;
                f = f_id(fn);
            } else if (key == "value") {
                rc = value(vkind, voff, vlen, vsplit);
                if (rc != LOAD_OK) return rc;
                have_val = true;
            } else if (key == "process") {
                rc = integer(process);
                if (rc != LOAD_OK) return rc;
            } else if (key == "time") {
                rc = integer(time_us);
                if (rc != LOAD_OK) return rc;
            } else {
                /* unknown key (e.g. :uid, :index): int or keyword only */
                long long dummy;
                skip_ws();
                if (p < end && *p == ':') {
                    std::string d;
                    if (kw(d) != LOAD_OK) return LOAD_ERROR;
                } else if (lit("nil")) {
                } else if (integer(dummy) != LOAD_OK) {
                    return LOAD_FALLBACK;
                }
            }
        }
        if (type < 0 || f < 0 || process == INT64_MIN)
            return LOAD_FALLBACK;
        if (!have_val) vkind = V_NIL;
        r->process.push_back((int32_t)process);
        r->type.push_back(type);
        r->f.push_back(f);
        r->time.push_back(time_us);
        r->val_kind.push_back(vkind);
        r->val_off.push_back(voff);
        r->val_len.push_back(vlen);
        r->val_split.push_back(vsplit);
        return LOAD_OK;
    }

    int run() {
        skip_ws();
        bool vec = false;
        if (p < end && *p == '[') {
            vec = true;
            p++;
        }
        for (;;) {
            skip_ws();
            if (p >= end) break;
            if (vec && *p == ']') {
                p++;
                skip_ws();
                if (p < end) return LOAD_ERROR;  /* trailing junk */
                break;
            }
            int rc = op_map();
            if (rc != LOAD_OK) return rc;
        }
        for (auto &n : r->f_list) {
            r->f_names += n;
            r->f_names += '\n';
        }
        return LOAD_OK;
    }
};

}  // namespace

extern "C" {

/* Parse EDN text; returns a handle (or nullptr) and sets *rc. */
Result *edn_load(const char *text, long long len, int *rc) {
    auto *r = new Result();
    Parser ps{text, text + len, r};
    *rc = ps.run();
    if (*rc != LOAD_OK) {
        delete r;
        return nullptr;
    }
    return r;
}

void edn_load_free(Result *r) { delete r; }

long long edn_n_ops(Result *r) { return (long long)r->process.size(); }
long long edn_pool_len(Result *r) { return (long long)r->val_pool.size(); }
const char *edn_f_names(Result *r) { return r->f_names.c_str(); }

/* bulk copies into caller-allocated buffers */
void edn_copy(Result *r, int32_t *process, int8_t *type, int32_t *f,
              int64_t *time_us, int8_t *val_kind, int32_t *val_off,
              int32_t *val_len, int32_t *val_split, int64_t *pool) {
    size_t n = r->process.size();
    memcpy(process, r->process.data(), n * sizeof(int32_t));
    memcpy(type, r->type.data(), n * sizeof(int8_t));
    memcpy(f, r->f.data(), n * sizeof(int32_t));
    memcpy(time_us, r->time.data(), n * sizeof(int64_t));
    memcpy(val_kind, r->val_kind.data(), n * sizeof(int8_t));
    memcpy(val_off, r->val_off.data(), n * sizeof(int32_t));
    memcpy(val_len, r->val_len.data(), n * sizeof(int32_t));
    memcpy(val_split, r->val_split.data(), n * sizeof(int32_t));
    memcpy(pool, r->val_pool.data(),
           r->val_pool.size() * sizeof(int64_t));
}

}  /* extern "C" */
