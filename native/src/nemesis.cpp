#include "comdb2_tpu/nemesis.h"
#include "comdb2_tpu/testutil.h"

#include <algorithm>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

struct nemesis {
    std::vector<std::string> nodes;
    std::string proc;
    uint32_t flags;
    std::mt19937 rng;
    FILE *trace = stderr;
};

namespace {

void run(nemesis *n, const std::string &cmd) {
    if (n->flags & (NEMESIS_VERBOSE | NEMESIS_DRYRUN))
        fprintf(n->trace, "nemesis: %s\n", cmd.c_str());
    if (!(n->flags & NEMESIS_DRYRUN)) {
        int rc = system(cmd.c_str());
        if (rc != 0)
            CT_TRACE(stderr, "command failed rc=%d: %s\n", rc, cmd.c_str());
    }
}

std::string ssh(const std::string &node, const std::string &remote_cmd) {
    return "ssh -o StrictHostKeyChecking=no -o BatchMode=yes " + node +
           " \"" + remote_cmd + "\"";
}

}  // namespace

extern "C" {

nemesis *nemesis_open(const char *nodes_csv, const char *process_name,
                      uint32_t flags, unsigned seed) {
    if (nodes_csv == nullptr || *nodes_csv == '\0') return nullptr;
    auto *n = new nemesis();
    n->proc = process_name != nullptr ? process_name : "comdb2";
    n->flags = flags;
    n->rng.seed(seed);
    std::string s(nodes_csv);
    size_t pos = 0;
    while (pos <= s.size()) {
        size_t c = s.find(',', pos);
        if (c == std::string::npos) c = s.size();
        if (c > pos) n->nodes.push_back(s.substr(pos, c - pos));
        pos = c + 1;
    }
    if (n->nodes.empty()) {
        delete n;
        return nullptr;
    }
    return n;
}

void nemesis_close(nemesis *n) {
    delete n;
}

void nemesis_set_trace(nemesis *n, FILE *f) {
    n->trace = f;
}

void nem_breaknet(nemesis *n) {
    /* cut a random half from the rest, DROP rules on both sides of
     * every cross-component pair (shape of nemesis.c:90-144, grudge
     * math of jepsen's complete-grudge) */
    std::vector<std::string> shuffled = n->nodes;
    std::shuffle(shuffled.begin(), shuffled.end(), n->rng);
    size_t half = shuffled.size() / 2;
    for (size_t i = 0; i < shuffled.size(); i++) {
        for (size_t j = 0; j < shuffled.size(); j++) {
            bool cross = (i < half) != (j < half);
            if (!cross || i == j) continue;
            run(n, ssh(shuffled[i],
                       "iptables -A INPUT -s " + shuffled[j] +
                           " -j DROP -w"));
        }
    }
}

void nem_fixnet(nemesis *n) {
    for (const auto &node : n->nodes) {
        run(n, ssh(node, "iptables -F -w; iptables -X -w"));
    }
}

void nem_signaldb(nemesis *n, int sig, int all) {
    const char *name = sig == 19 ? "STOP" : sig == 18 ? "CONT" : nullptr;
    char buf[32];
    if (name == nullptr) {
        snprintf(buf, sizeof buf, "%d", sig);
        name = buf;
    }
    if (all) {
        for (const auto &node : n->nodes)
            run(n, ssh(node, "killall -s " + std::string(name) + " " +
                                 n->proc));
    } else {
        const std::string &node =
            n->nodes[n->rng() % n->nodes.size()];
        run(n, ssh(node,
                   "killall -s " + std::string(name) + " " + n->proc));
    }
}

void nem_breakclocks(nemesis *n, int max_skew_s) {
    for (const auto &node : n->nodes) {
        long skew = (long)(n->rng() % (2 * (unsigned)max_skew_s + 1)) -
                    max_skew_s;
        run(n, ssh(node, "date -s @$(( $(date +%s) + " +
                             std::to_string(skew) + " ))"));
    }
}

void nem_fixclocks(nemesis *n) {
    for (const auto &node : n->nodes)
        run(n, ssh(node, "ntpdate -p 1 -b pool.ntp.org || true"));
}

void nem_fixall(nemesis *n) {
    nem_fixnet(n);
    nem_signaldb(n, 18 /* SIGCONT */, 1);
}

}  /* extern "C" */
