#include "comdb2_tpu/nemesis.h"
#include "comdb2_tpu/testutil.h"

#include <algorithm>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <vector>

struct nemesis {
    std::vector<std::string> hosts;
    std::vector<int> ports;        /* 0 = unknown (no per-port rules) */
    std::string proc;
    uint32_t flags;
    std::mt19937 rng;
    FILE *trace = stderr;
    int master = -1;               /* discovered / overridden */
};

namespace {

void run(nemesis *n, const std::string &cmd) {
    if (n->flags & (NEMESIS_VERBOSE | NEMESIS_DRYRUN))
        fprintf(n->trace, "nemesis: %s\n", cmd.c_str());
    if (!(n->flags & NEMESIS_DRYRUN)) {
        int rc = system(cmd.c_str());
        if (rc != 0)
            CT_TRACE(stderr, "command failed rc=%d: %s\n", rc, cmd.c_str());
    }
}

std::string ssh(const std::string &node, const std::string &remote_cmd) {
    return "ssh -o StrictHostKeyChecking=no -o BatchMode=yes " + node +
           " \"" + remote_cmd + "\"";
}

/* DROP rules cutting node a from node b, both directions. With known
 * ports the rules are per-port like the reference's
 * (nemesis.c:125-141: "-p tcp --dport <port> -j DROP"); without, they
 * fall back to whole-host DROP. */
void cut_pair(nemesis *n, size_t a, size_t b) {
    auto rule = [&](size_t at, size_t from) {
        std::string r = "iptables -A INPUT -s " + n->hosts[from];
        if (n->ports[at] > 0)
            r += " -p tcp --dport " + std::to_string(n->ports[at]);
        r += " -j DROP -w";
        run(n, ssh(n->hosts[at], r));
    };
    rule(a, b);
    rule(b, a);
}

}  // namespace

extern "C" {

nemesis *nemesis_open(const char *nodes_csv, const char *process_name,
                      uint32_t flags, unsigned seed) {
    if (nodes_csv == nullptr || *nodes_csv == '\0') return nullptr;
    auto *n = new nemesis();
    n->proc = process_name != nullptr ? process_name : "comdb2";
    n->flags = flags;
    n->rng.seed(seed);
    std::string s(nodes_csv);
    size_t pos = 0;
    while (pos <= s.size()) {
        size_t c = s.find(',', pos);
        if (c == std::string::npos) c = s.size();
        if (c > pos) {
            std::string node = s.substr(pos, c - pos);
            size_t colon = node.rfind(':');
            if (colon != std::string::npos) {
                n->hosts.push_back(node.substr(0, colon));
                n->ports.push_back(atoi(node.c_str() + colon + 1));
            } else {
                n->hosts.push_back(node);
                n->ports.push_back(0);
            }
        }
        pos = c + 1;
    }
    if (n->hosts.empty()) {
        delete n;
        return nullptr;
    }
    return n;
}

void nemesis_close(nemesis *n) {
    delete n;
}

void nemesis_set_trace(nemesis *n, FILE *f) {
    n->trace = f;
}

void nemesis_set_master(nemesis *n, int idx) {
    /* out-of-range pins fall back to "unknown" instead of becoming an
     * out-of-bounds index in nem_breaknet */
    n->master = (idx >= 0 && idx < (int)n->hosts.size()) ? idx : -1;
}

int nem_discover(nemesis *n) {
    /* cluster/master discovery over the SUT's info verb — the role of
     * the reference's cdb2_cluster_info + sys.cmd.send('bdb cluster')
     * master scrape (nemesis.c:15-47). Nodes without a known port (or
     * not answering) are skipped. */
    for (size_t i = 0; i < n->hosts.size(); i++) {
        if (n->ports[i] <= 0) continue;
        char r[256];
        if (ct_tcp_request(n->hosts[i].c_str(), n->ports[i], "I", 500,
                           r, sizeof r) < 0)
            continue;
        int id = -1;
        char role[32] = {0};
        if (sscanf(r, "I %d %31s", &id, role) == 2 &&
            strcmp(role, "primary") == 0) {
            n->master = (int)i;
            if (n->flags & (NEMESIS_VERBOSE | NEMESIS_DRYRUN))
                fprintf(n->trace, "nemesis: discovered master %s:%d\n",
                        n->hosts[i].c_str(), n->ports[i]);
            return n->master;
        }
    }
    return n->master;
}

void nem_breaknet(nemesis *n) {
    /* master-targeted partition when the master is known/discoverable:
     * cut {master, one random other} from the rest — the reference's
     * breaknet shape (nemesis.c:90-144). Without a master, cut a
     * random half (jepsen's partition-random-halves). Rules land on
     * both sides of every cross-component pair. */
    size_t count = n->hosts.size();
    if (n->master < 0) nem_discover(n);
    std::vector<size_t> order(count);
    for (size_t i = 0; i < count; i++) order[i] = i;
    size_t side_a;
    if (n->master >= 0 && n->master < (int)count && count > 1) {
        std::swap(order[0], order[(size_t)n->master]);
        size_t pick = 1 + n->rng() % (count - 1);
        std::swap(order[1], order[pick]);
        side_a = count > 2 ? 2 : 1;
    } else {
        std::shuffle(order.begin(), order.end(), n->rng);
        side_a = count / 2;
    }
    for (size_t i = 0; i < side_a; i++)
        for (size_t j = side_a; j < count; j++)
            cut_pair(n, order[i], order[j]);
}

void nem_fixnet(nemesis *n) {
    for (const auto &node : n->hosts) {
        run(n, ssh(node, "iptables -F -w; iptables -X -w"));
    }
}

void nem_signaldb(nemesis *n, int sig, int all) {
    const char *name = sig == 19 ? "STOP" : sig == 18 ? "CONT" : nullptr;
    char buf[32];
    if (name == nullptr) {
        snprintf(buf, sizeof buf, "%d", sig);
        name = buf;
    }
    if (all) {
        for (const auto &node : n->hosts)
            run(n, ssh(node, "killall -s " + std::string(name) + " " +
                                 n->proc));
    } else {
        const std::string &node =
            n->hosts[n->rng() % n->hosts.size()];
        run(n, ssh(node,
                   "killall -s " + std::string(name) + " " + n->proc));
    }
}

void nem_breakclocks(nemesis *n, int max_skew_s) {
    for (const auto &node : n->hosts) {
        long skew = (long)(n->rng() % (2 * (unsigned)max_skew_s + 1)) -
                    max_skew_s;
        run(n, ssh(node, "date -s @$(( $(date +%s) + " +
                             std::to_string(skew) + " ))"));
    }
}

void nem_fixclocks(nemesis *n) {
    for (const auto &node : n->hosts)
        run(n, ssh(node, "ntpdate -p 1 -b pool.ntp.org || true"));
}

void nem_fixall(nemesis *n) {
    nem_fixnet(n);
    nem_signaldb(n, 18 /* SIGCONT */, 1);
}

}  /* extern "C" */
