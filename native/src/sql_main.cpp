/* ct_sql — minimal interactive SQL shell against a sut_node cluster
 * (the cdb2sql role, tools/cdb2sql in the reference).
 *
 * Usage:
 *   ct_sql host[:port][,host[:port]...] [-c "sql"]... [-t timeout_ms]
 *          [-s service]
 *
 * With -c, runs each statement and exits (exit 1 on ERR/FAIL/UNKNOWN
 * in any reply); otherwise reads one statement per line from stdin
 * and prints the server's reply. The server parses the SQL
 * (sql_front.cpp) — this shell is wire-dumb on purpose: implementation
 * diversity against the Python clients ends at the socket.
 *
 * An entry WITHOUT :port resolves through that host's port
 * multiplexer (ct_pmux; the cdb2sql/cdb2api portmux flow): the pmux
 * port comes from COMDB2_TPU_PMUX_PORT (default 5105) and the service
 * name from -s (default "sut/sut").
 *
 * Connects to the FIRST reachable node of the list and sticks to it
 * (a SQL session is per-connection: an open transaction cannot move
 * nodes — same constraint as a cdb2 appsock session).
 */
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

int dial(const std::string &host, int port, int timeout_ms) {
    struct addrinfo hints = {};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo *res = nullptr;
    char portbuf[16];
    snprintf(portbuf, sizeof portbuf, "%d", port);
    if (getaddrinfo(host.c_str(), portbuf, &hints, &res) != 0)
        return -1;
    int fd = socket(res->ai_family, res->ai_socktype, 0);
    if (fd >= 0) {
        struct timeval tv = {timeout_ms / 1000,
                             (timeout_ms % 1000) * 1000};
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof tv);
        setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof tv);
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        if (connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
            close(fd);
            fd = -1;
        }
    }
    freeaddrinfo(res);
    return fd;
}

/* one request line -> one reply line; empty string = dead link */
std::string request(int fd, const std::string &line) {
    std::string out = line + "\n";
    size_t off = 0;
    while (off < out.size()) {
        ssize_t w = send(fd, out.data() + off, out.size() - off, 0);
        if (w <= 0) return "";
        off += (size_t)w;
    }
    std::string reply;
    char c;
    for (;;) {
        ssize_t r = recv(fd, &c, 1, 0);
        if (r <= 0) return "";       /* truncated reply = indeterminate */
        if (c == '\n') return reply;
        reply += c;
    }
}

}  // namespace

int main(int argc, char **argv) {
    if (argc < 2) {
        fprintf(stderr,
                "usage: %s host[:port][,host[:port]...] [-c sql]... "
                "[-t timeout_ms] [-s service]\n"
                "  port-less hosts resolve via that host's pmux "
                "(COMDB2_TPU_PMUX_PORT, default 5105)\n",
                argv[0]);
        return 2;
    }
    std::vector<std::string> stmts;
    int timeout_ms = 2000;
    std::string service = "sut/sut";
    for (int i = 2; i < argc; ++i) {
        if (strcmp(argv[i], "-c") == 0 && i + 1 < argc)
            stmts.push_back(argv[++i]);
        else if (strcmp(argv[i], "-t") == 0 && i + 1 < argc)
            timeout_ms = atoi(argv[++i]);
        else if (strcmp(argv[i], "-s") == 0 && i + 1 < argc)
            service = argv[++i];
    }

    /* first reachable node of the comma list; port-less entries
     * resolve through the host's pmux */
    const char *pmux_env = getenv("COMDB2_TPU_PMUX_PORT");
    int pmux_port = pmux_env != nullptr ? atoi(pmux_env) : 5105;
    int fd = -1;
    std::string list = argv[1];
    size_t pos = 0;
    while (fd < 0 && pos != std::string::npos) {
        size_t comma = list.find(',', pos);
        std::string hp = list.substr(
            pos, comma == std::string::npos ? std::string::npos
                                            : comma - pos);
        pos = comma == std::string::npos ? std::string::npos : comma + 1;
        size_t colon = hp.rfind(':');
        std::string host;
        int port = -1;
        if (colon == std::string::npos) {
            host = hp;
            int pfd = dial(host, pmux_port, timeout_ms);
            if (pfd < 0) continue;
            std::string r = request(pfd, "get " + service);
            close(pfd);
            port = atoi(r.c_str());
            if (port <= 0) continue;
        } else {
            host = hp.substr(0, colon);
            port = atoi(hp.c_str() + colon + 1);
        }
        fd = dial(host, port, timeout_ms);
    }
    if (fd < 0) {
        fprintf(stderr, "ct_sql: no node reachable\n");
        return 2;
    }

    int rc = 0;
    if (!stmts.empty()) {
        for (const std::string &s : stmts) {
            std::string r = request(fd, s);
            if (r.empty()) {
                /* timeout/short write: a late reply would desync the
                 * line protocol and later statements would read the
                 * wrong answers — stop, like the interactive loop */
                printf("UNKNOWN\n");
                rc = 1;
                break;
            }
            printf("%s\n", r.c_str());
            if (r.rfind("ERR", 0) == 0 || r == "FAIL" || r == "UNKNOWN")
                rc = 1;
        }
    } else {
        char *line = nullptr;
        size_t cap = 0;
        ssize_t len;
        while ((len = getline(&line, &cap, stdin)) != -1) {
            while (len > 0 &&
                   (line[len - 1] == '\n' || line[len - 1] == '\r'))
                line[--len] = 0;
            if (len == 0) continue;
            std::string r = request(fd, std::string(line, (size_t)len));
            if (r.empty()) {
                printf("UNKNOWN\n");
                break;               /* link died; session state gone */
            }
            printf("%s\n", r.c_str());
            fflush(stdout);
        }
        free(line);
    }
    close(fd);
    return rc;
}
