/* ct_pmux — port-multiplexer / service-discovery daemon.
 *
 * The role of the reference's pmux (tools/pmux/pmux.cpp:501-647
 * command surface; :834 main loop): every comdb2 host runs one pmux;
 * databases REGISTER their service name and get (or publish) a port,
 * clients GET the port for a service name instead of carrying
 * host:port config. This is an independent thread-per-connection
 * rewrite of the same line protocol over the in-tree SUT's stack:
 *
 *   reg <svc>          -> allocate (or return) a port from the range
 *   get [/echo] <svc>  -> port, or -1 when unknown ("/echo" prefixes
 *                         the reply with the service name, like the
 *                         reference's cdb2api uses)
 *   use <svc> <port>   -> publish a fixed port for <svc>
 *   del <svc>          -> forget the assignment
 *   used | list        -> dump "port svc" assignments
 *   active             -> count of assignments
 *   hello              -> ok (liveness)
 *   help               -> usage
 *   exit               -> shut the daemon down
 *
 * Assignments persist to a state file (-f) so a pmux restart keeps
 * ports stable, like the reference's store. Mutating commands are
 * accepted from loopback peers only (pmux.cpp disallowed_write): a
 * remote can discover, never rebind.
 */

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Pmux {
    std::mutex mu;
    std::map<std::string, int> ports;   /* svc -> port */
    std::set<int> in_use;
    int lo = 19000, hi = 19999;         /* allocation range */
    std::string state_file;
    bool stop = false;
    int srv = -1;                       /* listen fd (exit wakes it) */
};

Pmux g;

/* Outstanding serve() threads. main must not return while any are
 * still running against the global Pmux state (use-after-destruction
 * during daemon shutdown: a handler could hold g.mu while the
 * destructors run). Detached threads register here; main drains the
 * count before returning. */
std::mutex g_conn_mu;
std::condition_variable g_conn_cv;
int g_conns = 0;

void save_locked() {
    if (g.state_file.empty()) return;
    std::string tmp = g.state_file + ".tmp";
    FILE *f = fopen(tmp.c_str(), "w");
    if (!f) return;
    for (const auto &kv : g.ports)
        fprintf(f, "%d %s\n", kv.second, kv.first.c_str());
    fclose(f);
    rename(tmp.c_str(), g.state_file.c_str());
}

void load() {
    if (g.state_file.empty()) return;
    FILE *f = fopen(g.state_file.c_str(), "r");
    if (!f) return;
    int port;
    char svc[512];
    while (fscanf(f, "%d %511s", &port, svc) == 2) {
        g.ports[svc] = port;
        g.in_use.insert(port);
    }
    fclose(f);
}

int alloc_locked(const std::string &svc) {
    auto it = g.ports.find(svc);
    if (it != g.ports.end()) return it->second;
    for (int p = g.lo; p <= g.hi; ++p) {
        if (!g.in_use.count(p)) {
            g.ports[svc] = p;
            g.in_use.insert(p);
            save_locked();
            return p;
        }
    }
    return -1;
}

bool local_peer(int fd) {
    sockaddr_in a{};
    socklen_t len = sizeof(a);
    if (getpeername(fd, (sockaddr *)&a, &len) != 0) return false;
    return ntohl(a.sin_addr.s_addr) == INADDR_LOOPBACK;
}

void reply(FILE *out, const std::string &s) {
    fputs(s.c_str(), out);
    fputc('\n', out);
    fflush(out);
}

void serve(int fd) {
    FILE *in = fdopen(fd, "r");
    FILE *out = fdopen(dup(fd), "w");
    if (!in || !out) {
        if (in) fclose(in); else close(fd);
        if (out) fclose(out);
        return;
    }
    bool writable = local_peer(fd);
    char *line = nullptr;
    size_t cap = 0;
    ssize_t n;
    while ((n = getline(&line, &cap, in)) > 0) {
        while (n > 0 && (line[n - 1] == '\n' || line[n - 1] == '\r'))
            line[--n] = 0;
        char *sav = nullptr;
        char *cmd = strtok_r(line, " ", &sav);
        if (!cmd) { reply(out, "-1 empty command"); continue; }
        std::string c = cmd;
        if (c == "reg" || c == "use" || c == "del" || c == "exit") {
            if (!writable) {
                reply(out, "-1 write from remote connection denied");
                continue;
            }
        }
        if (c == "reg") {
            char *svc = strtok_r(nullptr, " ", &sav);
            if (!svc) { reply(out, "-1 missing service"); continue; }
            std::lock_guard<std::mutex> l(g.mu);
            reply(out, std::to_string(alloc_locked(svc)));
        } else if (c == "get") {
            char *a = strtok_r(nullptr, " ", &sav);
            bool echo = a && strcmp(a, "/echo") == 0;
            char *svc = echo ? strtok_r(nullptr, " ", &sav) : a;
            if (!svc) { reply(out, "-1 missing service"); continue; }
            int port;
            {
                std::lock_guard<std::mutex> l(g.mu);
                auto it = g.ports.find(svc);
                port = it == g.ports.end() ? -1 : it->second;
            }
            reply(out, echo ? std::to_string(port) + " " + svc
                            : std::to_string(port));
        } else if (c == "use") {
            char *svc = strtok_r(nullptr, " ", &sav);
            char *ps = strtok_r(nullptr, " ", &sav);
            if (!svc || !ps) { reply(out, "-1 usage: use svc port"); continue; }
            int port = atoi(ps);
            if (port <= 0) { reply(out, "-1 bad port"); continue; }
            std::lock_guard<std::mutex> l(g.mu);
            /* a port published by ANOTHER service must not silently
             * alias — deleting either would free the port under the
             * survivor and a later reg would double-assign it */
            bool taken = false;
            for (const auto &kv : g.ports)
                if (kv.second == port && kv.first != svc) {
                    reply(out, "-1 port in use by " + kv.first);
                    taken = true;
                    break;
                }
            if (taken) continue;
            auto it = g.ports.find(svc);
            if (it != g.ports.end()) g.in_use.erase(it->second);
            g.ports[svc] = port;
            g.in_use.insert(port);
            save_locked();
            reply(out, "0");
        } else if (c == "del") {
            char *svc = strtok_r(nullptr, " ", &sav);
            if (!svc) { reply(out, "-1 missing service"); continue; }
            std::lock_guard<std::mutex> l(g.mu);
            auto it = g.ports.find(svc);
            if (it == g.ports.end()) { reply(out, "-1 unknown service"); }
            else {
                g.in_use.erase(it->second);
                g.ports.erase(it);
                save_locked();
                reply(out, "0");
            }
        } else if (c == "used" || c == "list") {
            std::lock_guard<std::mutex> l(g.mu);
            for (const auto &kv : g.ports)
                reply(out, std::to_string(kv.second) + " " + kv.first);
            reply(out, ".");
        } else if (c == "active") {
            std::lock_guard<std::mutex> l(g.mu);
            reply(out, std::to_string(g.ports.size()));
        } else if (c == "hello") {
            reply(out, "0 ok");
        } else if (c == "help") {
            reply(out, "reg/get [/echo]/use/del/used/active/hello/exit");
        } else if (c == "exit") {
            reply(out, "0 exiting");
            {
                std::lock_guard<std::mutex> l(g.mu);
                g.stop = true;
                /* the main thread is parked in accept(); shutting the
                 * listen socket down wakes it so the stop actually
                 * takes effect now, not at the next connection */
                if (g.srv >= 0) shutdown(g.srv, SHUT_RDWR);
            }
            break;
        } else {
            reply(out, "-1 unknown command, type 'help'");
        }
    }
    free(line);
    fclose(in);
    fclose(out);
}

void serve_tracked(int fd) {
    serve(fd);
    std::lock_guard<std::mutex> l(g_conn_mu);
    if (--g_conns == 0) g_conn_cv.notify_all();
}

}  // namespace

int main(int argc, char **argv) {
    int port = 5105;
    for (int i = 1; i < argc; ++i) {
        if (!strcmp(argv[i], "-p") && i + 1 < argc) port = atoi(argv[++i]);
        else if (!strcmp(argv[i], "-r") && i + 2 < argc) {
            g.lo = atoi(argv[++i]);
            g.hi = atoi(argv[++i]);
        } else if (!strcmp(argv[i], "-f") && i + 1 < argc) {
            g.state_file = argv[++i];
        } else {
            fprintf(stderr,
                    "usage: %s [-p port] [-r lo hi] [-f state_file]\n",
                    argv[0]);
            return 2;
        }
    }
    load();
    int srv = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in a{};
    a.sin_family = AF_INET;
    a.sin_addr.s_addr = htonl(INADDR_ANY);
    a.sin_port = htons((uint16_t)port);
    if (bind(srv, (sockaddr *)&a, sizeof(a)) != 0 ||
        listen(srv, 64) != 0) {
        perror("bind/listen");
        return 1;
    }
    {
        std::lock_guard<std::mutex> l(g.mu);
        g.srv = srv;
    }
    for (;;) {
        int fd = accept(srv, nullptr, nullptr);
        int err = errno;   /* before the lock below can clobber it */
        {
            std::lock_guard<std::mutex> l(g.mu);
            if (g.stop) {
                if (fd >= 0) close(fd);
                break;
            }
        }
        if (fd < 0) {
            /* EINTR/ECONNABORTED are transient; anything else (e.g.
             * EMFILE under fd exhaustion) is persistent and a bare
             * continue would busy-spin the CPU — back off briefly so
             * the condition can clear */
            if (err != EINTR && err != ECONNABORTED) {
                errno = err;
                perror("accept");
                std::this_thread::sleep_for(
                    std::chrono::milliseconds(50));
            }
            continue;
        }
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        {
            std::lock_guard<std::mutex> l(g_conn_mu);
            ++g_conns;
        }
        std::thread(serve_tracked, fd).detach();
    }
    close(srv);
    /* drain outstanding serve() threads before the globals are
     * destroyed (the 'exit' handler itself is one of them); a hung
     * client can't park shutdown forever — after the grace period the
     * OS reclaims everything anyway, which is no worse than the old
     * unconditional return */
    {
        std::unique_lock<std::mutex> l(g_conn_mu);
        g_conn_cv.wait_for(l, std::chrono::seconds(5),
                           [] { return g_conns == 0; });
    }
    return 0;
}
