/* SQL text -> typed-verb translation for sut_node (see sql_front.h).
 *
 * The grammar is the statement surface the reference harness actually
 * speaks (comdb2/core.clj:371-474, ctest/register.c:61-250,
 * ctest/insert.c, adya.clj:12-83), parsed with a hand-rolled
 * tokenizer — the role of db/sqlinterfaces.c:5970's dispatch, scoped
 * to the shapes the tests issue (recorded divergence: no general SQL
 * engine; PARITY.md).
 */
#include "comdb2_tpu/sql_front.h"

#include <cctype>
#include <cstdlib>
#include <vector>

namespace sqlfront {
namespace {

/* lowercased word / number / punctuation tokens; quotes stripped */
std::vector<std::string> tokenize(const std::string &s) {
    std::vector<std::string> out;
    size_t i = 0;
    while (i < s.size()) {
        char c = s[i];
        if (isspace((unsigned char)c) || c == ';') {
            ++i;
        } else if (isalpha((unsigned char)c) || c == '_') {
            std::string w;
            while (i < s.size() &&
                   (isalnum((unsigned char)s[i]) || s[i] == '_'))
                w += (char)tolower((unsigned char)s[i++]);
            out.push_back(w);
        } else if (isdigit((unsigned char)c) || c == '-') {
            std::string w;
            if (c == '-') { w += c; ++i; }
            while (i < s.size() && isdigit((unsigned char)s[i]))
                w += s[i++];
            out.push_back(w.empty() || w == "-" ? "-" : w);
        } else if (c == '\'' || c == '"') {
            char q = c;
            std::string w;
            ++i;
            while (i < s.size() && s[i] != q) w += s[i++];
            if (i < s.size()) ++i;
            out.push_back(w);
        } else {
            out.push_back(std::string(1, c));
            ++i;
        }
    }
    return out;
}

bool is_num(const std::string &t) {
    if (t.empty()) return false;
    size_t i = t[0] == '-' ? 1 : 0;
    if (i >= t.size()) return false;
    for (; i < t.size(); ++i)
        if (!isdigit((unsigned char)t[i])) return false;
    return true;
}

long long num(const std::string &t) { return atoll(t.c_str()); }

/* cursor over the token list */
struct Cur {
    const std::vector<std::string> &t;
    size_t i = 0;
    bool at(const char *w) const {
        return i < t.size() && t[i] == w;
    }
    bool eat(const char *w) {
        if (!at(w)) return false;
        ++i;
        return true;
    }
    bool done() const { return i >= t.size(); }
    const std::string *next() {
        return i < t.size() ? &t[i++] : nullptr;
    }
};

/* `<col> = <int>` with optional preceding AND; returns column name
 * via *col. */
bool eat_eq(Cur &c, std::string *col, long long *val) {
    if (c.i + 3 > c.t.size()) return false;
    if (!isalpha((unsigned char)c.t[c.i][0])) return false;
    if (c.t[c.i + 1] != "=") return false;
    if (!is_num(c.t[c.i + 2])) return false;
    *col = c.t[c.i];
    *val = num(c.t[c.i + 2]);
    c.i += 3;
    return true;
}

/* parenthesized int list `( a, b, ... )` */
bool eat_tuple(Cur &c, std::vector<long long> *vals) {
    if (!c.eat("(")) return false;
    while (!c.at(")")) {
        if (c.done()) return false;
        if (c.t[c.i] == ",") {
            ++c.i;
            continue;
        }
        if (!is_num(c.t[c.i])) return false;
        vals->push_back(num(c.t[c.i]));
        ++c.i;
    }
    ++c.i;
    return true;
}

/* column-name list `( id, val, ... )` */
bool eat_cols(Cur &c, std::vector<std::string> *cols) {
    if (!c.eat("(")) return false;
    while (!c.at(")")) {
        if (c.done()) return false;
        if (c.t[c.i] == ",") {
            ++c.i;
            continue;
        }
        cols->push_back(c.t[c.i]);
        ++c.i;
    }
    ++c.i;
    return true;
}

/* skip the select column list up to FROM */
bool skip_to_from(Cur &c) {
    while (!c.done()) {
        if (c.at("from")) {
            ++c.i;
            return true;
        }
        ++c.i;
    }
    return false;
}

/* Every statement must consume its whole token stream BEFORE its verb
 * runs: a partially-parsed WHERE clause silently dropping conjuncts
 * would demote a guarded CAS to a blind write (the reference parser
 * rejects at the grammar level, sqlinterfaces.c dispatch). Returns ""
 * when exhausted, else the ERR reply. */
std::string want_done(Cur &c, const std::string &what) {
    if (c.done()) return "";
    return "ERR " + what + ": unparsed trailing tokens";
}

/* optional ORDER BY <col> tail on selects (results are ordered by
 * construction; clients sort) */
void eat_order_by(Cur &c) {
    size_t save = c.i;
    if (c.eat("order") && c.eat("by") && c.next() != nullptr) return;
    c.i = save;
}

std::string mutate(Session &s, const VerbRunner &run,
                   const std::string &verb) {
    /* non-txn DML rides the M replay-nonce wrapper when the session
     * set a cnonce (the cdb2api cnonce/blkseq role) */
    std::string line = verb;
    if (s.cnonce != 0) {
        line = "M " + std::to_string(s.cnonce) + " " + verb;
        s.cnonce = 0;
    }
    std::string r = run(line);
    /* rowcount replies: the reference client classifies DML by
     * affected-row counts (cdb2_get_effects, register.c:157-171) */
    if (r.rfind("OK", 0) == 0) return "ROWS 1";
    if (r == "FAIL") return "ROWS 0";
    return r;              /* UNKNOWN / ERR pass through */
}

std::string sel_register(Session &s, const VerbRunner &run, Cur &c) {
    /* WHERE id = K (default key 1 when absent) */
    long long key = 1;
    if (c.eat("where")) {
        std::string col;
        if (!eat_eq(c, &col, &key) || col != "id")
            return "ERR select register: expected WHERE id = <int>";
    }
    eat_order_by(c);
    std::string err = want_done(c, "select register");
    if (!err.empty()) return err;
    if (s.txid >= 0)
        return run("TR " + std::to_string(s.txid) + " " +
                   std::to_string(key));
    return run("R " + std::to_string(key));
}

std::string sel_table(Session &s, const VerbRunner &run, Cur &c,
                      const std::string &tbl) {
    /* predicate read over a|b: txn-only (the G2 anti-dependency
     * read, adya.clj:30-47) */
    if (s.txid < 0)
        return "ERR predicate read requires a transaction";
    if (!c.eat("where"))
        return "ERR select " + tbl + ": expected WHERE k = <int>";
    std::string col;
    long long key = 0;
    if (!eat_eq(c, &col, &key) || (col != "k" && col != "key"))
        return "ERR select " + tbl + ": expected WHERE k = <int>";
    eat_order_by(c);
    std::string err = want_done(c, "select " + tbl);
    if (!err.empty()) return err;
    return run("TP " + std::to_string(s.txid) + " " + tbl + " " +
               std::to_string(key));
}

std::string do_select(Session &s, const VerbRunner &run, Cur &c) {
    if (!skip_to_from(c)) return "ERR select: missing FROM";
    const std::string *tbl = c.next();
    if (tbl == nullptr) return "ERR select: missing table";
    if (*tbl == "register") return sel_register(s, run, c);
    if (*tbl == "jepsen") {                    /* ORDER BY implicit:
                                                * the S verb returns
                                                * insertion order;
                                                * clients sort */
        eat_order_by(c);
        std::string err = want_done(c, "select jepsen");
        if (!err.empty()) return err;
        return run("S");
    }
    if (*tbl == "a" || *tbl == "b") return sel_table(s, run, c, *tbl);
    return "ERR unknown table " + *tbl;
}

std::string do_insert(Session &s, const VerbRunner &run, Cur &c) {
    if (!c.eat("into")) return "ERR insert: expected INTO";
    const std::string *tbl = c.next();
    if (tbl == nullptr) return "ERR insert: missing table";
    std::vector<std::string> cols;
    if (c.at("(") && !eat_cols(c, &cols)) return "ERR insert: bad columns";
    if (!c.eat("values")) return "ERR insert: expected VALUES";
    std::vector<long long> vals;
    if (!eat_tuple(c, &vals)) return "ERR insert: bad VALUES tuple";
    if (!cols.empty() && cols.size() != vals.size())
        return "ERR insert: column/value count mismatch";
    std::string err = want_done(c, "insert");
    if (!err.empty()) return err;

    if (*tbl == "register") {
        /* (id, val) — or positional */
        long long key = 1, v = 0;
        if (vals.size() == 1) {
            v = vals[0];
        } else if (vals.size() == 2) {
            key = vals[0];
            v = vals[1];
            if (cols.size() == 2 && cols[0] != "id")
                { key = vals[1]; v = vals[0]; }
        } else {
            return "ERR insert register: expected (id, val)";
        }
        if (s.txid >= 0) {
            std::string r = run("TW " + std::to_string(s.txid) + " " +
                                std::to_string(key) + " " +
                                std::to_string(v));
            return r == "OK" ? "ROWS 1" : r;
        }
        return mutate(s, run, "W " + std::to_string(key) + " " +
                              std::to_string(v));
    }
    if (*tbl == "jepsen") {
        if (vals.size() != 1)
            return "ERR insert jepsen: expected (value)";
        if (s.txid >= 0)
            return "ERR insert jepsen: set adds are single statements";
        return mutate(s, run, "A " + std::to_string(vals[0]));
    }
    if (*tbl == "a" || *tbl == "b") {
        /* (id, k, v) — the G2 insert (adya.clj:48-56); txn only */
        if (s.txid < 0)
            return "ERR insert " + *tbl + " requires a transaction";
        if (vals.size() != 3)
            return "ERR insert " + *tbl + ": expected (id, k, v)";
        long long rid = vals[0], key = vals[1], v = vals[2];
        if (cols.size() == 3) {     /* honor named column order */
            for (size_t i = 0; i < 3; ++i) {
                if (cols[i] == "id") rid = vals[i];
                else if (cols[i] == "k" || cols[i] == "key")
                    key = vals[i];
                else if (cols[i] == "v" || cols[i] == "value")
                    v = vals[i];
                else
                    return "ERR insert " + *tbl + ": unknown column " +
                           cols[i];
            }
        }
        std::string r = run("TI " + std::to_string(s.txid) + " " +
                            *tbl + " " + std::to_string(key) + " " +
                            std::to_string(rid) + " " +
                            std::to_string(v));
        return r == "OK" ? "ROWS 1" : r;
    }
    return "ERR unknown table " + *tbl;
}

std::string do_update(Session &s, const VerbRunner &run, Cur &c) {
    const std::string *tbl = c.next();
    if (tbl == nullptr || *tbl != "register")
        return "ERR update: only register is updatable";
    if (!c.eat("set")) return "ERR update: expected SET";
    std::string col;
    long long newv = 0;
    if (!eat_eq(c, &col, &newv) || (col != "val" && col != "value"))
        return "ERR update: expected SET val = <int>";
    long long key = 1, expect = 0;
    bool has_expect = false;
    if (c.eat("where")) {
        std::string wcol;
        long long wval = 0;
        /* every conjunct must parse and only AND may connect them —
         * a clause this grammar can't express must ERR, never demote
         * a guarded CAS into an unconditional write */
        for (;;) {
            if (!eat_eq(c, &wcol, &wval))
                return "ERR update: bad WHERE clause";
            if (wcol == "id") key = wval;
            else if (wcol == "val" || wcol == "value") {
                expect = wval;
                has_expect = true;
            } else {
                return "ERR update: unknown WHERE column " + wcol;
            }
            if (!c.eat("and")) break;
        }
    }
    std::string err = want_done(c, "update");
    if (!err.empty()) return err;
    if (s.txid < 0) {
        if (has_expect)      /* the CAS shape, comdb2/core.clj:432-474 */
            return mutate(s, run, "C " + std::to_string(key) + " " +
                                  std::to_string(expect) + " " +
                                  std::to_string(newv));
        return mutate(s, run, "W " + std::to_string(key) + " " +
                              std::to_string(newv));
    }
    /* in-txn: the committed read records the version (OCC validates
     * it at commit — a concurrent change aborts the txn), then the
     * guarded write buffers. ROWS 0 when the predicate missed. */
    if (has_expect) {
        std::string r = run("TR " + std::to_string(s.txid) + " " +
                            std::to_string(key));
        if (r == "NIL") return "ROWS 0";
        if (r.rfind("V ", 0) != 0) return r;
        if (atoll(r.c_str() + 2) != expect) return "ROWS 0";
    }
    std::string r = run("TW " + std::to_string(s.txid) + " " +
                        std::to_string(key) + " " +
                        std::to_string(newv));
    return r == "OK" ? "ROWS 1" : r;
}

std::string do_set(Session &s, Cur &c) {
    std::string err;
    if (c.eat("hasql")) {
        bool on;
        if (c.eat("on")) on = true;
        else if (c.eat("off")) on = false;
        else return "ERR set hasql: expected on|off";
        if (!(err = want_done(c, "set hasql")).empty()) return err;
        s.hasql = on;
        return "OK";
    }
    if (c.eat("transaction")) {
        /* level recorded; the wire txn surface is serializable by
         * construction (OCC validation at commit). The level may be
         * multi-word ("read committed") — every token must come from
         * the known isolation vocabulary (a typo'd level must ERR,
         * not silently run at the wrong isolation). */
        bool ser = false;
        while (const std::string *w = c.next()) {
            if (*w == "serializable") ser = true;
            else if (*w != "read" && *w != "committed" &&
                     *w != "uncommitted" && *w != "repeatable" &&
                     *w != "snapshot" && *w != "isolation" &&
                     *w != "level")
                return "ERR set transaction: unknown level token " + *w;
        }
        s.serializable = ser;
        return "OK";
    }
    if (c.eat("max_retries")) {
        const std::string *n = c.next();
        if (n == nullptr || !is_num(*n))
            return "ERR set max_retries: expected <int>";
        if (!(err = want_done(c, "set max_retries")).empty()) return err;
        s.max_retries = num(*n);
        return "OK";
    }
    if (c.eat("cnonce")) {
        const std::string *n = c.next();
        if (n == nullptr || !is_num(*n))
            return "ERR set cnonce: expected <int>";
        if (!(err = want_done(c, "set cnonce")).empty()) return err;
        s.cnonce = (unsigned long long)num(*n);
        return "OK";
    }
    return "ERR unknown SET";
}

}  // namespace

bool is_statement(const std::string &line) {
    size_t i = 0;
    while (i < line.size() && isspace((unsigned char)line[i])) ++i;
    std::string w;
    while (i < line.size() && isalpha((unsigned char)line[i]))
        w += (char)tolower((unsigned char)line[i++]);
    return w == "select" || w == "insert" || w == "update" ||
           w == "begin" || w == "commit" || w == "rollback" ||
           w == "set" || w == "delete";
}

std::string execute(const std::string &sql, Session &s,
                    const VerbRunner &run) {
    std::vector<std::string> toks = tokenize(sql);
    Cur c{toks};
    if (c.eat("set")) return do_set(s, c);
    std::string err;
    if (c.eat("begin")) {
        if (!(err = want_done(c, "begin")).empty()) return err;
        if (s.txid >= 0) return "ERR transaction already open";
        std::string r = run("TB");
        if (r.rfind("T ", 0) != 0) return r;
        s.txid = atoll(r.c_str() + 2);
        return "OK";
    }
    if (c.eat("commit")) {
        if (!(err = want_done(c, "commit")).empty()) return err;
        if (s.txid < 0) return "ERR no open transaction";
        std::string line = "TC " + std::to_string(s.txid);
        if (s.cnonce != 0) {
            line += " " + std::to_string(s.cnonce);
            s.cnonce = 0;
        }
        s.txid = -1;
        return run(line);
    }
    if (c.eat("rollback")) {
        if (!(err = want_done(c, "rollback")).empty()) return err;
        if (s.txid < 0) return "ERR no open transaction";
        std::string r = run("TA " + std::to_string(s.txid));
        s.txid = -1;
        return r;
    }
    if (c.eat("select")) return do_select(s, run, c);
    if (c.eat("insert")) return do_insert(s, run, c);
    if (c.eat("update")) return do_update(s, run, c);
    if (c.eat("delete")) return "ERR delete unsupported";
    return "ERR unparsed statement";
}

}  // namespace sqlfront
