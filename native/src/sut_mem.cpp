/* In-memory SUT backend: a genuinely linearizable register + grow-only
 * set behind one mutex, with optional injected flakiness and an optional
 * deliberate consistency bug (negative control for the checker).
 *
 * This fills the role of the reference's atom-backed fake SUT
 * (jepsen/tests.clj:27-56) for the *native* drivers: it validates the
 * driver ↔ EDN ↔ checker pipeline without a cluster.
 */
#include "comdb2_tpu/sut.h"
#include "comdb2_tpu/sut_tcp.h"

#include <algorithm>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <random>
#include <vector>

namespace {

/* process-wide shared state: every handle in this process sees the same
 * register/set, like every client connecting to one database */
struct Shared {
    std::mutex mu;
    int reg_val = 0;
    bool reg_written = false;
    std::vector<long long> set_vals;
    /* buggy mode: writes are dropped with probability 1/4 *after*
     * reporting OK (lost update), and reads return a stale snapshot
     * with probability 1/4 */
    int stale_val = 0;
    bool stale_written = false;
};

Shared &shared() {
    static Shared s;
    return s;
}

}  // namespace

struct sut_handle {
    uint32_t flags;
    std::mt19937 rng;
    unsigned bug_n = 0;
    sut_tcp *tcp = nullptr;     /* non-null: ops route over TCP */

    explicit sut_handle(uint32_t fl, unsigned seed) : flags(fl), rng(seed) {}

    /* pre-commit fault: FAIL means the op definitely did not run */
    bool flaky_fail() {
        return (flags & SUT_F_FLAKY) && rng() % 8 == 0;
    }
    /* post-commit fault: the op ran but the client never heard back */
    bool flaky_unknown() {
        return (flags & SUT_F_FLAKY) && rng() % 8 == 0;
    }
    /* deterministic: every 4th roll fires, so a buggy backend reliably
     * misbehaves within a handful of ops (the negative controls must
     * not flake) */
    bool bug_roll() {
        return (flags & SUT_F_BUGGY) && (bug_n++ % 4 == 3);
    }
};

extern "C" {

sut_handle *sut_open(const char *target, uint32_t flags, unsigned seed) {
    auto *h = new sut_handle(flags, seed);
    /* "@file[#dbname]" = comdb2db-style discovery (sut_tcp.cpp);
     * "host:port,..." = explicit node list; NULL/other = in-memory */
    if (target != nullptr &&
        (target[0] == '@' || strchr(target, ':') != nullptr)) {
        h->tcp = sut_tcp_open(target, seed);
        if (h->tcp == nullptr) {
            delete h;
            return nullptr;
        }
    }
    return h;
}

void sut_close(sut_handle *h) {
    if (h->tcp != nullptr) sut_tcp_close(h->tcp);
    delete h;
}

int sut_reg_read(sut_handle *h, int *val, int *found) {
    if (h->tcp != nullptr) return sut_tcp_reg_read(h->tcp, val, found);
    if (h->flaky_fail()) return SUT_FAIL;
    Shared &s = shared();
    std::lock_guard<std::mutex> g(s.mu);
    if (h->bug_roll() && s.stale_written) {
        *val = s.stale_val;        /* stale read: consistency bug */
        *found = 1;
    } else {
        *val = s.reg_val;
        *found = s.reg_written ? 1 : 0;
    }
    return SUT_OK;
}

int sut_reg_write(sut_handle *h, int val) {
    if (h->tcp != nullptr) return sut_tcp_reg_write(h->tcp, val);
    if (h->flaky_fail()) return SUT_FAIL;
    Shared &s = shared();
    {
        std::lock_guard<std::mutex> g(s.mu);
        s.stale_val = s.reg_val;
        s.stale_written = s.reg_written;
        if (!h->bug_roll()) {      /* buggy mode may drop the write */
            s.reg_val = val;
            s.reg_written = true;
        }
    }
    if (h->flaky_unknown()) return SUT_UNKNOWN;
    return SUT_OK;
}

int sut_reg_cas(sut_handle *h, int expected, int newval) {
    if (h->tcp != nullptr)
        return sut_tcp_reg_cas(h->tcp, expected, newval);
    if (h->flaky_fail()) return SUT_FAIL;
    Shared &s = shared();
    int applied;
    {
        std::lock_guard<std::mutex> g(s.mu);
        if (s.reg_written && s.reg_val == expected) {
            s.stale_val = s.reg_val;
            s.stale_written = s.reg_written;
            if (!h->bug_roll()) {
                s.reg_val = newval;
            }
            applied = 1;
        } else {
            applied = 0;
        }
    }
    if (applied && h->flaky_unknown()) return SUT_UNKNOWN;
    return applied ? SUT_OK : SUT_FAIL;
}

int sut_set_add(sut_handle *h, long long val) {
    if (h->tcp != nullptr) return sut_tcp_set_add(h->tcp, val);
    if (h->flaky_fail()) return SUT_FAIL;
    Shared &s = shared();
    {
        std::lock_guard<std::mutex> g(s.mu);
        if (!h->bug_roll()) {      /* buggy mode loses inserts */
            s.set_vals.push_back(val);
        }
    }
    if (h->flaky_unknown()) return SUT_UNKNOWN;
    return SUT_OK;
}

int sut_set_add_unique(sut_handle *h, long long val) {
    if (h->tcp != nullptr) return SUT_FAIL;   /* no wire verb (yet) */
    if (h->flaky_fail()) return SUT_FAIL;
    Shared &s = shared();
    int dup;
    {
        std::lock_guard<std::mutex> g(s.mu);
        dup = std::find(s.set_vals.begin(), s.set_vals.end(), val) !=
              s.set_vals.end();
        if (!dup && !h->bug_roll())    /* buggy mode loses inserts */
            s.set_vals.push_back(val);
    }
    if (dup) return SUT_FAIL;
    if (h->flaky_unknown()) return SUT_UNKNOWN;
    return SUT_OK;
}

int sut_set_read(sut_handle *h, long long **vals, size_t *n) {
    if (h->tcp != nullptr) return sut_tcp_set_read(h->tcp, vals, n);
    if (h->flaky_fail()) return SUT_FAIL;
    Shared &s = shared();
    std::lock_guard<std::mutex> g(s.mu);
    *n = s.set_vals.size();
    *vals = static_cast<long long *>(malloc(sizeof(long long) * (*n + 1)));
    memcpy(*vals, s.set_vals.data(), sizeof(long long) * *n);
    return SUT_OK;
}

}  /* extern "C" */
