/* register workload driver — N concurrent single-threaded processes
 * doing read/write/cas against a SUT, emitting a Jepsen-format EDN
 * history for the TPU checker.
 *
 * Role of the reference's ctest/register.c (5 threads, op = rand()%3,
 * EDN via -j, mid-run nemesis events at runtime/2) with two deliberate
 * departures: (1) the SUT is reached through the generic ABI in sut.h
 * instead of cdb2api, and (2) an indeterminate outcome emits an :info op
 * and retires the process id (the harness rule, jepsen/core.clj:178-200)
 * instead of aborting the run (register.c:329-332 exits on rc -105).
 */
#include "comdb2_tpu/edn_history.h"
#include "comdb2_tpu/nemesis.h"
#include "comdb2_tpu/sut.h"
#include "comdb2_tpu/testutil.h"

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

#include <unistd.h>

namespace {

struct Opts {
    int nthreads = 5;
    double runtime_s = 10.0;
    long max_ops = -1;           /* per thread; -1 = time-bound only */
    const char *target = nullptr; /* "host:port,..." = TCP HA client */
    const char *edn_path = nullptr;
    const char *nodes = nullptr; /* enable nemesis when set */
    const char *proc = "comdb2";
    uint32_t sut_flags = SUT_F_NONE;
    uint32_t nem_flags = 0;
    unsigned seed = 0;
    int values = 5;
    int events = 0;              /* bitmask: 1 partition 2 sigstop 4 clock */
};

void usage(const char *argv0) {
    fprintf(stderr,
            "Usage: %s [opts]\n"
            "  -T n        worker threads (default 5)\n"
            "  -r secs     runtime (default 10)\n"
            "  -i n        max ops per thread\n"
            "  -j file     EDN history output\n"
            "  -d target   SUT target: host:port[,host:port...] — the\n"
            "              HA TCP client over a replicated cluster\n"
            "              (cdb2api node-list routing; default: the\n"
            "              in-memory backend)\n"
            "  -n csv      node list; enables nemesis events\n"
            "  -P name     SUT process name for sigstop events\n"
            "  -G ev       add nemesis event: partition|sigstop|clock\n"
            "  -F          flaky SUT backend (random fail/indeterminate)\n"
            "  -B          buggy SUT backend (MUST yield invalid history)\n"
            "  -s seed     rng seed\n"
            "  -D          nemesis dry-run (print commands only)\n",
            argv0);
}

struct Driver {
    Opts opt;
    edn_history *edn;
    std::atomic<long> total_ops{0};
    std::atomic<int> workers_ok{0};

    void thread_main(int tid) {
        std::mt19937 rng(opt.seed * 7919u + (unsigned)tid + 1);
        sut_handle *h = sut_open(opt.target, opt.sut_flags,
                                 opt.seed * 31u + (unsigned)tid);
        if (h == nullptr) {
            CT_TRACE(stderr, "bad SUT target %s\n",
                     opt.target != nullptr ? opt.target : "(null)");
            return;
        }
        workers_ok.fetch_add(1);
        uint64_t deadline =
            ct_timems() + (uint64_t)(opt.runtime_s * 1000);
        int process = tid;
        long ops = 0;
        char val[64];
        while (ct_timems() < deadline &&
               (opt.max_ops < 0 || ops < opt.max_ops)) {
            int op = (int)(rng() % 3);
            int newval = (int)(rng() % (unsigned)opt.values);
            int curval = (int)(rng() % (unsigned)opt.values);
            int rc;
            if (op == 0) {                               /* read */
                edn_nil(val, sizeof val);
                edn_emit(edn, "invoke", "read", val, process, ct_timeus());
                int got = 0, found = 0;
                rc = sut_reg_read(h, &got, &found);
                if (rc == SUT_OK) {
                    if (found) edn_int(val, sizeof val, got);
                    else edn_nil(val, sizeof val);
                    edn_emit(edn, "ok", "read", val, process, ct_timeus());
                } else if (rc == SUT_FAIL) {
                    edn_emit(edn, "fail", "read", val, process,
                             ct_timeus());
                } else {
                    edn_emit(edn, "info", "read", val, process,
                             ct_timeus());
                    process += opt.nthreads;   /* retire the process id */
                }
            } else if (op == 1) {                        /* write */
                edn_int(val, sizeof val, newval);
                edn_emit(edn, "invoke", "write", val, process,
                         ct_timeus());
                rc = sut_reg_write(h, newval);
                if (rc == SUT_OK) {
                    edn_emit(edn, "ok", "write", val, process, ct_timeus());
                } else if (rc == SUT_FAIL) {
                    edn_emit(edn, "fail", "write", val, process,
                             ct_timeus());
                } else {
                    edn_emit(edn, "info", "write", val, process,
                             ct_timeus());
                    process += opt.nthreads;
                }
            } else {                                     /* cas */
                edn_pair(val, sizeof val, curval, newval);
                edn_emit(edn, "invoke", "cas", val, process, ct_timeus());
                rc = sut_reg_cas(h, curval, newval);
                if (rc == SUT_OK) {
                    edn_emit(edn, "ok", "cas", val, process, ct_timeus());
                } else if (rc == SUT_FAIL) {
                    edn_emit(edn, "fail", "cas", val, process,
                             ct_timeus());
                } else {
                    edn_emit(edn, "info", "cas", val, process,
                             ct_timeus());
                    process += opt.nthreads;
                }
            }
            ops++;
        }
        total_ops += ops;
        sut_close(h);
    }
};

}  // namespace

int main(int argc, char **argv) {
    Opts opt;
    int c;
    while ((c = getopt(argc, argv, "T:r:i:j:d:n:P:G:FBs:Dh")) != -1) {
        switch (c) {
        case 'T': opt.nthreads = atoi(optarg); break;
        case 'r': opt.runtime_s = atof(optarg); break;
        case 'i': opt.max_ops = atol(optarg); break;
        case 'j': opt.edn_path = optarg; break;
        case 'n': opt.nodes = optarg; break;
        case 'P': opt.proc = optarg; break;
        case 'd': opt.target = optarg; break;
        case 'G':
            if (strcmp(optarg, "partition") == 0) opt.events |= 1;
            else if (strcmp(optarg, "sigstop") == 0) opt.events |= 2;
            else if (strcmp(optarg, "clock") == 0) opt.events |= 4;
            else { usage(argv[0]); return 2; }
            break;
        case 'F': opt.sut_flags |= SUT_F_FLAKY; break;
        case 'B': opt.sut_flags |= SUT_F_BUGGY; break;
        case 's': opt.seed = (unsigned)atol(optarg); break;
        case 'D': opt.nem_flags |= NEMESIS_DRYRUN; break;
        default: usage(argv[0]); return 2;
        }
    }

    Driver d;
    d.opt = opt;
    d.edn = edn_open(opt.edn_path);
    if (opt.edn_path != nullptr && d.edn == nullptr) {
        fprintf(stderr, "cannot open %s\n", opt.edn_path);
        return 2;
    }

    nemesis *nem = nullptr;
    if (opt.nodes != nullptr && opt.events != 0) {
        nem = nemesis_open(opt.nodes, opt.proc, opt.nem_flags, opt.seed);
        if (nem == nullptr) {
            fprintf(stderr, "bad node list\n");
            return 2;
        }
        nem_fixall(nem);
    }

    std::vector<std::thread> threads;
    threads.reserve(opt.nthreads);
    for (int i = 0; i < opt.nthreads; i++)
        threads.emplace_back([&d, i] { d.thread_main(i); });

    if (nem != nullptr) {
        /* fire faults at runtime/2, heal before the end
         * (register.c:575-598) */
        usleep((useconds_t)(opt.runtime_s * 1e6 / 2));
        if (opt.events & 1) nem_breaknet(nem);
        if (opt.events & 2) nem_signaldb(nem, 19, 0);
        if (opt.events & 4) nem_breakclocks(nem, 60);
        usleep((useconds_t)(opt.runtime_s * 1e6 / 4));
        if (opt.events & 1) nem_fixnet(nem);
        if (opt.events & 2) nem_signaldb(nem, 18, 1);
        if (opt.events & 4) nem_fixclocks(nem);
    }

    for (auto &t : threads) t.join();
    edn_close(d.edn);
    if (nem != nullptr) {
        nem_fixall(nem);
        nemesis_close(nem);
    }
    fprintf(stderr, "register driver: %ld ops across %d threads\n",
            d.total_ops.load(), opt.nthreads);
    if (d.workers_ok.load() == 0) {
        fprintf(stderr, "no worker could open the SUT — empty history "
                        "would pass vacuously\n");
        return 2;
    }
    return 0;
}
