/* SUT client ABI — the seam between native workload drivers and any
 * system under test.
 *
 * The reference's drivers (ctest/register.c, ctest/insert.c) are welded
 * to cdb2api; this framework's drivers speak a small C ABI instead so a
 * backend can be an in-memory model (self-test), a socket bridge, or a
 * real database client library. Outcomes are tri-state, mirroring the
 * harness's ok / fail / info(indeterminate) op types.
 */
#ifndef COMDB2_TPU_SUT_H
#define COMDB2_TPU_SUT_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

enum {
    SUT_OK = 0,       /* definitely applied */
    SUT_FAIL = 1,     /* definitely not applied */
    SUT_UNKNOWN = 2,  /* indeterminate (timeout / crash): op may have
                         applied — becomes an :info op in the history */
};

/* backend behavior flags */
enum {
    SUT_F_NONE = 0,
    /* inject random FAIL/UNKNOWN outcomes (fault tolerance testing of
       the drivers themselves) */
    SUT_F_FLAKY = 1u << 0,
    /* deliberately buggy: lost updates + stale reads. Histories from a
       buggy backend MUST be judged invalid by the checker — the
       negative control for the whole pipeline */
    SUT_F_BUGGY = 1u << 1,
};

typedef struct sut_handle sut_handle;

sut_handle *sut_open(const char *target, uint32_t flags, unsigned seed);
void sut_close(sut_handle *h);

/* single register (the jepsen `register` table: one row, id/val):
 * reads set *found=0 when no value was ever written */
int sut_reg_read(sut_handle *h, int *val, int *found);
int sut_reg_write(sut_handle *h, int val);
/* cas applies iff current == expected; SUT_FAIL when it doesn't match */
int sut_reg_cas(sut_handle *h, int expected, int newval);

/* grow-only set (the jepsen `jepsen(id,value)` table) */
int sut_set_add(sut_handle *h, long long val);
/* unique add: SUT_FAIL when val is already present — the duplicate-key
 * commit error the reference's blkseq-dup test relies on
 * (ctest/insert.c:263-301: a replayed insert MUST return DUP) */
int sut_set_add_unique(sut_handle *h, long long val);
/* snapshot read; caller frees *vals with free() */
int sut_set_read(sut_handle *h, long long **vals, size_t *n);

#ifdef __cplusplus
}
#endif
#endif
