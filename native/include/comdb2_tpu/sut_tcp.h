/* TCP HA backend of the SUT client ABI (sut_tcp.cpp) — node-list
 * routing, retry-elsewhere, snapshot-LSN read tracking over a
 * replicated sut_node cluster (the cdb2api HA role,
 * cdb2api.c:618-656). Normally reached through sut_open("h:p,...");
 * this header exists so sut_mem.cpp's dispatch and the backend stay
 * in one signature. */
#ifndef COMDB2_TPU_SUT_TCP_H
#define COMDB2_TPU_SUT_TCP_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct sut_tcp sut_tcp;

sut_tcp *sut_tcp_open(const char *target, unsigned seed);
void sut_tcp_close(sut_tcp *t);
int sut_tcp_reg_read(sut_tcp *t, int *val, int *found);
int sut_tcp_reg_write(sut_tcp *t, int val);
int sut_tcp_reg_cas(sut_tcp *t, int expected, int newval);
int sut_tcp_set_add(sut_tcp *t, long long val);
int sut_tcp_set_read(sut_tcp *t, long long **vals, size_t *n);

#ifdef __cplusplus
}
#endif
#endif
