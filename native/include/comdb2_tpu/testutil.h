/* Timing + thread-prefixed tracing for the native workload drivers.
 * Role of the reference's ctest/testutil.{h,c} (tdprintf, timems/timeus),
 * re-designed for the SUT-agnostic driver ABI. */
#ifndef COMDB2_TPU_TESTUTIL_H
#define COMDB2_TPU_TESTUTIL_H

#include <stdint.h>
#include <stdio.h>

#ifdef __cplusplus
extern "C" {
#endif

/* wall-clock in ms / us since the epoch */
uint64_t ct_timems(void);
uint64_t ct_timeus(void);

/* fprintf prefixed with "[time thread-id fn:line]" — the tracing shape
 * of testutil.c:14-48 (cnonce/snapshot-LSN fields are cdb2-specific and
 * have no analog in the generic ABI) */
void ct_tdprintf(FILE *f, const char *fn, int line, const char *fmt, ...);

#define CT_TRACE(f, ...) ct_tdprintf((f), __func__, __LINE__, __VA_ARGS__)

/* one line-protocol request/reply over TCP (connect, send line+\n,
 * read reply up to \n). Returns reply length >= 0; -1 when the
 * connection was never established (safe to retry elsewhere); -2 when
 * the failure happened after connecting (the request MAY have been
 * delivered — mutating callers must treat the op as indeterminate).
 * Shared by the nemesis discovery and any driver that talks to a
 * line-protocol SUT. */
int ct_tcp_request(const char *host, int port, const char *line,
                   int timeout_ms, char *reply, int reply_cap);

#ifdef __cplusplus
}
#endif
#endif
