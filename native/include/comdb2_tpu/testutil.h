/* Timing + thread-prefixed tracing for the native workload drivers.
 * Role of the reference's ctest/testutil.{h,c} (tdprintf, timems/timeus),
 * re-designed for the SUT-agnostic driver ABI. */
#ifndef COMDB2_TPU_TESTUTIL_H
#define COMDB2_TPU_TESTUTIL_H

#include <stdint.h>
#include <stdio.h>

#ifdef __cplusplus
extern "C" {
#endif

/* wall-clock in ms / us since the epoch */
uint64_t ct_timems(void);
uint64_t ct_timeus(void);

/* fprintf prefixed with "[time thread-id fn:line]" — the tracing shape
 * of testutil.c:14-48 (cnonce/snapshot-LSN fields are cdb2-specific and
 * have no analog in the generic ABI) */
void ct_tdprintf(FILE *f, const char *fn, int line, const char *fmt, ...);

#define CT_TRACE(f, ...) ct_tdprintf((f), __func__, __LINE__, __VA_ARGS__)

#ifdef __cplusplus
}
#endif
#endif
