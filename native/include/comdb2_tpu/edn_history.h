/* Thread-safe EDN history emitter.
 *
 * Writes the interchange format the TPU checker ingests — the same
 * shape ctest/register.c:282-375 emits under a mutex with -j:
 *   {:type :invoke :f :cas :value [0 3] :process 2 :time 123456}
 * One op map per line inside a top-level vector.
 */
#ifndef COMDB2_TPU_EDN_HISTORY_H
#define COMDB2_TPU_EDN_HISTORY_H

#include <stdint.h>
#include <stdio.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct edn_history edn_history;

/* NULL path -> no-op emitter (drivers can run without recording) */
edn_history *edn_open(const char *path);
/* closes the vector and the file */
void edn_close(edn_history *e);

/* type: "invoke" | "ok" | "fail" | "info"; value strings are raw EDN
 * fragments ("nil", "3", "[0 3]", "#{1 2}") composed by the caller */
void edn_emit(edn_history *e, const char *type, const char *f,
              const char *value_edn, int process, uint64_t time_us);

/* helpers for composing value fragments */
void edn_int(char *buf, size_t cap, long long v);
void edn_nil(char *buf, size_t cap);
void edn_pair(char *buf, size_t cap, long long a, long long b);

#ifdef __cplusplus
}
#endif
#endif
