/* Native nemesis — fault injection over ssh from a workload driver.
 *
 * Role of the reference's ctest/nemesis.{h,c} (breaknet/fixnet/
 * signaldb/breakclocks/fixclocks/fixall). Nodes are "host[:port]"
 * (comma-separated); with ports the nemesis can DISCOVER the cluster
 * master over the SUT's info verb (the cdb2_cluster_info +
 * sys.cmd.send('bdb cluster') role, nemesis.c:15-47), target partitions
 * at {master, +1} (nemesis.c:90-144), and generate per-port iptables
 * rules. The target process name is a parameter instead of hardcoded
 * comdb2 pidfiles.
 *
 * Topology assumption: ONE NODE PER HOST. The per-port iptables rules
 * match on source host + destination port only ("-s <host> --dport
 * <port>"), so on a co-hosted cluster (several nodes sharing one host,
 * e.g. the localhost sut_node cluster) a rule drops ALL of that host's
 * traffic to the port — clients included — and cannot single out one
 * peer. Co-hosted deployments should partition through the SUT's own
 * B/U control verbs instead (what the Python ClusterControl does).
 */
#ifndef COMDB2_TPU_NEMESIS_H
#define COMDB2_TPU_NEMESIS_H

#include <stdint.h>
#include <stdio.h>

#ifdef __cplusplus
extern "C" {
#endif

enum {
    NEMESIS_VERBOSE = 1u << 0,
    /* print the shell commands to the trace stream instead of running
       them — lets tests assert on exact fault actions */
    NEMESIS_DRYRUN = 1u << 1,
};

typedef struct nemesis nemesis;

nemesis *nemesis_open(const char *nodes_csv, const char *process_name,
                      uint32_t flags, unsigned seed);
void nemesis_close(nemesis *n);

/* where DRYRUN/VERBOSE output goes (default stderr) */
void nemesis_set_trace(nemesis *n, FILE *f);

/* pin the master index (skips discovery); -1 = unknown */
void nemesis_set_master(nemesis *n, int idx);

/* query each node's SUT info verb for the primary; returns its index
 * or -1. Called implicitly by nem_breaknet when no master is pinned. */
int nem_discover(nemesis *n);

/* partition {master, one random other} from the rest when the master
 * is known/discoverable (per-port DROP rules at both sides); falls
 * back to a random half/half split otherwise */
void nem_breaknet(nemesis *n);
/* flush all DROP rules everywhere */
void nem_fixnet(nemesis *n);
/* SIGSTOP/SIGCONT the SUT process on a random node (all=0) or all
 * nodes (all=1) */
void nem_signaldb(nemesis *n, int sig, int all);
/* skew every node's clock by a random offset within ±max_skew_s */
void nem_breakclocks(nemesis *n, int max_skew_s);
/* re-sync clocks via ntpdate */
void nem_fixclocks(nemesis *n);
/* undo everything */
void nem_fixall(nemesis *n);

#ifdef __cplusplus
}
#endif
#endif
