/* SQL text front end for sut_node — the query-language surface of the
 * reference harness (round-4 VERDICT Missing #1).
 *
 * The reference drives everything as SQL text: session controls
 * ("set hasql on", "set transaction serializable", "set max_retries
 * 100000" — linearizable/jepsen/src/comdb2/core.clj:371-375), typed
 * statements parsed server-side (db/sqlinterfaces.c:5970
 * dispatch_sql_query), and a cdb2sql shell. This front end parses the
 * same statement shapes into sut_node's existing typed verbs
 * per-connection, so the register / set / G2 workloads can be driven
 * as SQL text over the wire with identical semantics (and identical
 * negative-control detectability).
 *
 * Statement surface (case-insensitive keywords; one statement per
 * line):
 *   SET hasql on|off / SET transaction <level> / SET max_retries N
 *   SET cnonce N            -- replay nonce for the next mutation or
 *                              commit (the cdb2api cnonce role)
 *   BEGIN / COMMIT / ROLLBACK
 *   SELECT <cols> FROM register WHERE id = K
 *   SELECT <cols> FROM jepsen [ORDER BY value]
 *   SELECT <cols> FROM a|b WHERE k|key = K          (txn only)
 *   INSERT INTO register (id, val) VALUES (K, V)
 *   INSERT INTO jepsen (value) VALUES (V)
 *   INSERT INTO a|b (id, k|key, v|value) VALUES (R, K, V)  (txn only)
 *   UPDATE register SET val = V WHERE id = K
 *   UPDATE register SET val = B WHERE id = K AND val = A   (the CAS
 *       shape the reference register client issues,
 *       comdb2/core.clj:432-474)
 *
 * Replies stay single-line (the wire protocol is line-based):
 *   selects: "V ..." | "NIL" | "UNKNOWN"  (same shapes as the verbs)
 *   DML:     "ROWS <n>" | "UNKNOWN" — rowcount is how the reference
 *            client classifies ok/fail (cdb2_get_effects,
 *            ctest/register.c:157-171)
 *   session/txn control: "OK" | "FAIL" | "UNKNOWN" | "ERR <msg>"
 */
#ifndef COMDB2_TPU_SQL_FRONT_H
#define COMDB2_TPU_SQL_FRONT_H

#include <functional>
#include <string>

namespace sqlfront {

struct Session {
    bool hasql = false;
    bool serializable = false;
    long long max_retries = 0;
    unsigned long long cnonce = 0;   /* consumed by next mutation/commit */
    long long txid = -1;             /* open wire transaction, or -1 */
};

/* Executes one typed-verb line against the node, returns its reply
 * line (sut_node passes its own handle()). */
using VerbRunner = std::function<std::string(const std::string &)>;

/* True when the line starts with a SQL keyword (SELECT/INSERT/UPDATE/
 * BEGIN/COMMIT/ROLLBACK/SET/DELETE) rather than a typed verb. Typed
 * verbs are 1-2 uppercase letters, SQL keywords >= 3 chars, so the
 * two surfaces share one port without ambiguity. */
bool is_statement(const std::string &line);

/* Parse + execute one SQL statement in this session. */
std::string execute(const std::string &sql, Session &s,
                    const VerbRunner &run);

}  // namespace sqlfront

#endif
