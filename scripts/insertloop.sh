#!/bin/bash
# Loop the native set-insert workload; the driver self-verifies its
# per-value state machine (lost/unexpected => nonzero), and the Python
# set checker re-verifies the emitted history — the role of the
# reference's linearizable/ctest/insertloop.sh.
#
# Usage: scripts/insertloop.sh [runs] [driver-args...]
set -u

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
INSERT="${INSERT:-$ROOT/native/build/ct_insert}"
RUNS="${1:-0}"
shift 2>/dev/null || true

[ -x "$INSERT" ] || {
    cmake -S "$ROOT/native" -B "$ROOT/native/build" >/dev/null \
        && cmake --build "$ROOT/native/build" >/dev/null || exit 2
}

n=0
while [ "$RUNS" -eq 0 ] || [ "$n" -lt "$RUNS" ]; do
    n=$((n + 1))
    hist="$(mktemp /tmp/insert-hist-XXXX.edn)"
    echo "=== run $n" >&2
    "$INSERT" -j "$hist" "$@"
    rc=$?
    if [ $rc -eq 1 ]; then
        echo "insert driver detected loss; history at $hist" >&2
        exit 1
    elif [ $rc -ne 0 ]; then
        echo "insert driver crashed (rc=$rc)" >&2
        exit 3
    fi
    PYTHONPATH="$ROOT" python -m comdb2_tpu.filetest "$hist" \
        --checker set || {
        echo "set checker disagrees; history at $hist" >&2
        exit 1
    }
    rm -f "$hist"
done
echo "all $n runs valid" >&2
