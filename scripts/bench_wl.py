#!/usr/bin/env python3
"""Bench the device workload-checker families (ISSUE 20).

Usage: PYTHONPATH=$AXON_SITE:. python scripts/bench_wl.py \
           [--json BENCH_wl.json] [--quick]
(real TPU; CPU works for smoke via JAX_PLATFORMS=cpu.)

Three sections, one JSON line:

- ``families``: per family (bank / sets / dirty), a batch-size sweep.
  Every (family, B) cell HARD-ASSERTS verdict parity against the
  demoted host oracle — valid batch and seeded-violation twin both —
  before any timing counts. Timed: the ONE-dispatch device batch vs
  the per-history host loop; the dispatch count is asserted on the
  ``wl.batch.DISPATCHES`` delta (one per pow2 chunk).
- ``amortization``: the serving-plane claim. A dispatch+readback
  round-trip costs ~100 ms over the tunnel (CLAUDE.md), so verdicts
  per round-trip IS the metric a naive per-history loop loses: B
  histories dispatched one-by-one pay B round-trips where the batch
  pays one. Modeled wall = measured compute + round_trip_ms * trips.
- ``stream``: bank megabatch — N sessions advanced per beat, solo
  (N programs) vs fused (1), with the same modeled round-trip.

The run's compile-guard summary is embedded (observed lowerings ⊆
PROGRAMS.md; COMDB2_TPU_COMPILE_GUARD=0 makes the assert report-only).
"""
from __future__ import annotations

import argparse
import json
import time

#: tunnel dispatch+readback round-trip (measured, CLAUDE.md)
ROUND_TRIP_MS = 100.0


def _time(fn, reps=3):
    out = fn()
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        ts.append(time.perf_counter() - t0)
    return min(ts), out


def families_section(quick: bool) -> list:
    from comdb2_tpu.checker import wl as W
    from comdb2_tpu.checker.wl import batch as WLB
    from comdb2_tpu.checker.wl.batch import _host_fallback

    sizes = (8, 64) if quick else (8, 64, 512)
    gens = {
        "bank": lambda s, b, v: W.bank_batch(s, b, violation=v),
        "sets": lambda s, b, v: (W.sets_batch(s, b, violation=v),
                                 None),
        "dirty": lambda s, b, v: (W.dirty_batch(s, b, violation=v),
                                  None),
    }
    viols = {"bank": "total", "sets": "lost", "dirty": "dirty"}
    rows = []
    for family, gen in gens.items():
        for B in sizes:
            row = {"family": family, "B": B}
            for key, viol in (("valid", None),
                              ("violation", viols[family])):
                hists, model = gen(1000 + B, B, viol)
                n_ops = sum(len(h) for h in hists)

                # parity gate BEFORE timing: device == oracle lane
                # by lane on the verdict
                dev = W.check_wl_batch(hists, family, model)
                host = _host_fallback(hists, family, model)
                for i, (d, h) in enumerate(zip(dev, host)):
                    assert d["valid?"] == h["valid?"], \
                        (family, B, key, i, d, h)
                want = viol is None
                assert all(d["valid?"] is want for d in dev), \
                    (family, B, key)

                d0 = WLB.DISPATCHES
                dev_t, _ = _time(
                    lambda: W.check_wl_batch(hists, family, model))
                # one program per pow2 bucket, per timed rep (+1
                # parity run above = reps + 1 warmup... the gate ran
                # once, _time runs 1 + 3): counted at the entry
                per_run = (WLB.DISPATCHES - d0) // 4
                assert per_run == 1, (family, B, WLB.DISPATCHES - d0)
                host_t, _ = _time(
                    lambda: _host_fallback(hists, family, model))
                row[key] = {
                    "ops": n_ops,
                    "device_batch_s": round(dev_t, 4),
                    "host_loop_s": round(host_t, 4),
                    "device_ops_per_s": round(n_ops / dev_t, 1),
                    "host_ops_per_s": round(n_ops / host_t, 1),
                }
            rows.append(row)
            print(f"{family:5s} B={B:3d} device "
                  f"{row['valid']['device_ops_per_s']:10.0f} ops/s  "
                  f"host {row['valid']['host_ops_per_s']:10.0f} ops/s",
                  flush=True)
    return rows


def amortization_section(quick: bool) -> dict:
    """B verdicts per tunnel round-trip: batch=1 trip, loop=B trips."""
    from comdb2_tpu.checker import wl as W
    from comdb2_tpu.checker.wl import batch as WLB

    B = 16 if quick else 64
    hists, model = W.bank_batch(77, B)

    d0 = WLB.DISPATCHES
    batch_t, out = _time(lambda: W.check_wl_batch(hists, "bank",
                                                  model))
    assert (WLB.DISPATCHES - d0) // 4 == 1
    assert all(v["valid?"] is True for v in out)

    d0 = WLB.DISPATCHES
    loop_t, _ = _time(lambda: [
        W.check_wl_batch([h], "bank", model) for h in hists])
    assert (WLB.DISPATCHES - d0) // 4 == B, "loop pays B dispatches"

    batch_wall = batch_t * 1e3 + ROUND_TRIP_MS
    loop_wall = loop_t * 1e3 + ROUND_TRIP_MS * B
    out = {
        "B": B,
        "round_trip_ms": ROUND_TRIP_MS,
        "batch_compute_ms": round(batch_t * 1e3, 2),
        "loop_compute_ms": round(loop_t * 1e3, 2),
        "batch_modeled_wall_ms": round(batch_wall, 1),
        "loop_modeled_wall_ms": round(loop_wall, 1),
        "modeled_speedup": round(loop_wall / batch_wall, 1),
    }
    print(f"amortization B={B}: modeled wall {loop_wall:.0f} ms "
          f"(loop) -> {batch_wall:.0f} ms (batch), "
          f"{out['modeled_speedup']}x", flush=True)
    return out


def stream_section(quick: bool) -> dict:
    """Megabatched session advance: N beats per round-trip."""
    import numpy as np

    from comdb2_tpu.checker import wl as W
    from comdb2_tpu.stream import engine as SE
    from comdb2_tpu.stream import wl as SW

    N = 4 if quick else 8
    hists, model = W.bank_batch(88, N)

    def solo():
        sess = [SW.make_session("wl-bank", model) for _ in range(N)]
        for s, h in zip(sess, hists):
            s.append(h)
        return sess

    def fused():
        sess = [SW.make_session("wl-bank", model) for _ in range(N)]
        coll = SE.MegaBatch()
        fins = [s.append_stage(h, collector=coll)
                for s, h in zip(sess, hists)]
        coll.flush()
        [f() for f in fins]
        return sess

    d0 = SE.DISPATCHES
    solo_t, solo_sess = _time(solo)
    solo_d = (SE.DISPATCHES - d0) // 4
    d0 = SE.DISPATCHES
    fused_t, fused_sess = _time(fused)
    fused_d = (SE.DISPATCHES - d0) // 4
    assert solo_d == N and fused_d == 1, (solo_d, fused_d)
    # bit parity: fused carries == solo carries
    for a, b in zip(solo_sess, fused_sess):
        assert np.array_equal(np.asarray(a._balance),
                              np.asarray(b._balance))
        a.close()
        b.close()

    solo_wall = solo_t * 1e3 + ROUND_TRIP_MS * N
    fused_wall = fused_t * 1e3 + ROUND_TRIP_MS
    out = {
        "sessions": N,
        "solo_dispatches": solo_d,
        "fused_dispatches": fused_d,
        "solo_modeled_wall_ms": round(solo_wall, 1),
        "fused_modeled_wall_ms": round(fused_wall, 1),
        "modeled_speedup": round(solo_wall / fused_wall, 1),
    }
    print(f"stream N={N}: {solo_d} solo dispatches -> {fused_d} "
          f"fused, modeled {out['modeled_speedup']}x", flush=True)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="BENCH_wl.json")
    ap.add_argument("--quick", action="store_true",
                    help="small sweep (CPU smoke)")
    args = ap.parse_args()

    from comdb2_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    import jax

    from comdb2_tpu.analysis.compile_surface import static_inventory
    from comdb2_tpu.utils import compile_guard

    inv = static_inventory()
    with compile_guard.guard() as g:
        fam = families_section(args.quick)
        amort = amortization_section(args.quick)
        stream = stream_section(args.quick)
    out = {
        "backend": jax.default_backend(),
        "quick": bool(args.quick),
        "families": fam,
        "amortization": amort,
        "stream": stream,
        "compile_guard": g.summary(inv),
    }
    with open(args.json, "w") as fh:
        fh.write(json.dumps(out) + "\n")
    print("artifact written:", args.json, flush=True)
    if compile_guard.enabled():
        g.assert_closed(inv)


if __name__ == "__main__":
    main()
