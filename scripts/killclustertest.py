#!/usr/bin/env python3
"""Kill-cluster diff-oracle runner — the reference's
``killcluster/killclustertest.sh`` as a CLI over
:mod:`comdb2_tpu.harness.killcluster`.

Runs the scripted deterministic transaction against the in-memory SUT
(or any backend via --chaos knobs), optionally disrupting mid-flight,
and diffs the transcript against the generated oracle. Exit 0 iff the
transcript matches exactly.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from comdb2_tpu.harness import killcluster               # noqa: E402
from comdb2_tpu.workloads.sqlish import MemDB            # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("-n", "--rows", type=int, default=2_000_000,
                   help="oracle transaction size (reference: 2M rows)")
    p.add_argument("--chaos-fail", type=float, default=0.0)
    p.add_argument("--chaos-unknown", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    db = MemDB(chaos_fail=args.chaos_fail,
               chaos_unknown=args.chaos_unknown, seed=args.seed)
    r = killcluster.run(
        {}, lambda: killcluster.scripted_workload(db.connect(),
                                                  args.rows),
        killcluster.oracle(args.rows))
    out = {"valid?": r["valid?"], "lines": r["lines"],
           "expected-lines": r["expected-lines"]}
    if r["diff"]:
        out["first-diff"] = r["diff"][0]
    if "error" in r:
        out["error"] = r["error"]
    print(json.dumps(out))
    if r["valid?"] is True:
        return 0
    return 2 if r["valid?"] == "unknown" else 1


if __name__ == "__main__":
    sys.exit(main())
