#!/usr/bin/env python3
"""Bench the streaming-session subsystem: per-append cost vs
re-checking the full prefix from scratch.

Usage: PYTHONPATH=$AXON_SITE:. python scripts/bench_stream.py \
           [--json BENCH_stream.json] [--quick]
(real TPU or CPU smoke via JAX_PLATFORMS=cpu.)

The headline is the INCREMENTAL WIN: a session's append dispatches
only the delta's new segments against the device-resident carry, so
per-append wall time is independent of how much history the session
has accumulated — where a post-hoc re-check of the full prefix
(pack + segment + one-shot dispatch, what every pre-stream surface
does) grows linearly with it. Both sides are measured at every
checkpoint and the flatness/growth ratios are asserted.

The ~100 ms tunnel dispatch+readback round-trip (CLAUDE.md) is
DECLARED in the artifact, not injected: on the tunneled TPU both an
append and a scratch re-check pay one round-trip per dispatch, so the
modeled numbers add 100 ms x dispatch count to each side — the
incremental win survives the model because both sides pay one
round-trip while only scratch pays the O(history) scan + host pack.

Verdict parity between the session and every scratch re-check is
HARD-ASSERTED before any timing counts, and the run's compile-guard
summary is embedded (observed lowerings ⊆ PROGRAMS.md).
"""
from __future__ import annotations

import argparse
import json
import random
import time

#: the measured tunnel dispatch+readback round-trip this container's
#: TPU link pays (CLAUDE.md) — declared in the artifact model
TUNNEL_ROUNDTRIP_MS = 100.0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="BENCH_stream.json")
    ap.add_argument("--quick", action="store_true",
                    help="small shape (CI smoke)")
    ap.add_argument("--events", type=int, default=4096)
    ap.add_argument("--delta", type=int, default=256)
    args = ap.parse_args()
    if args.quick:
        args.events, args.delta = 960, 96

    from comdb2_tpu.checker.batch import check_batch, pack_batch
    from comdb2_tpu.models.model import MODELS
    from comdb2_tpu.ops.packed import pack_history
    from comdb2_tpu.ops.synth import register_history
    from comdb2_tpu.stream import StreamSession
    from comdb2_tpu.stream import engine as ENG
    from comdb2_tpu.utils import compile_guard

    h = register_history(random.Random(13), n_procs=4,
                         n_events=args.events, values=3,
                         p_info=0.0, max_pending=2)
    model = MODELS["cas-register"]()

    def scratch(prefix):
        t0 = time.perf_counter()
        b = pack_batch([pack_history(list(prefix))], model)
        st, fa, nf = check_batch(b, F=1024)
        return ((time.perf_counter() - t0) * 1e3,
                (int(st[0]), int(fa[0]), int(nf[0])))

    n_deltas = -(-args.events // args.delta)
    checkpoint_sizes = [(k + 1) * args.delta
                        for k in range(n_deltas) if k % 4 == 3]

    with compile_guard.guard() as g:
        # warm both paths' programs so timings measure dispatch, not
        # compile (the service primes the same way at boot): EVERY
        # scratch checkpoint prefix crosses its own pow2 segment
        # bucket and must compile before the timed region, or
        # scratch_ms inflates with first-time compiles and the
        # incremental win overstates
        for size in checkpoint_sizes:
            scratch(h[:size])
        warm = StreamSession("cas-register")
        for i in range(0, 3 * args.delta, args.delta):
            warm.append(h[i:i + args.delta])
        warm.close()

        s = StreamSession("cas-register")
        append_ms = []
        scratch_ms = []
        checkpoints = []
        d0 = ENG.DISPATCHES
        for i in range(0, args.events, args.delta):
            t0 = time.perf_counter()
            out = s.append(h[i:i + args.delta])
            append_ms.append((time.perf_counter() - t0) * 1e3)
            if (i // args.delta) % 4 == 3:
                sm, verdict = scratch(h[:i + args.delta])
                scratch_ms.append(sm)
                checkpoints.append(i + args.delta)
                final = s.poll()
                assert verdict[0] == {True: 0, False: 1,
                                      "unknown": 2}[final["valid"]], \
                    (verdict, final)
        out = s.finalize_input()
        n_disp = ENG.DISPATCHES - d0
        assert out["valid"] is True, out

        # --- megabatch phase (round 13): N sessions, ONE program per
        # beat. Identical per-session streams keep every lane in one
        # shape class, so each beat's appends fuse into a single
        # launched program — dispatches/beat ~= 1 is the tentpole
        # claim, counter-asserted below. A solo twin fed the same
        # beats is the per-session baseline the tunnel model divides.
        n_lanes = 4
        mb_beats = 4 if args.quick else 8
        mb_delta = args.delta // 2
        mh = register_history(random.Random(17), n_procs=3,
                              n_events=mb_beats * mb_delta, values=3,
                              p_info=0.0, max_pending=2)
        # warm the fused program ladder on throwaway lanes through
        # the SAME beat trajectory as the timed run: each memo pow2
        # bucket crossing is a distinct fused program, and the timed
        # beats must measure dispatch, not first-time compiles (the
        # solo programs were warmed the same way by the phase above)
        warm_mb = [StreamSession("cas-register")
                   for _ in range(n_lanes)]
        warm_solo = StreamSession("cas-register")
        for i in range(0, len(mh), mb_delta):
            coll = ENG.MegaBatch()
            fins = [w.append_stage(mh[i:i + mb_delta],
                                   collector=coll)
                    for w in warm_mb]
            coll.flush()
            [f() for f in fins]
            warm_solo.append(mh[i:i + mb_delta])
        for w in warm_mb:
            w.close()
        warm_solo.close()

        lanes = [StreamSession("cas-register")
                 for _ in range(n_lanes)]
        solo_tw = StreamSession("cas-register")
        per_beat_disp = []
        beat_ms = []
        solo_ms = []
        mb0 = ENG.MEGABATCHES
        for i in range(0, len(mh), mb_delta):
            beat = mh[i:i + mb_delta]
            db = ENG.DISPATCHES
            coll = ENG.MegaBatch()
            t0 = time.perf_counter()
            fins = [ln.append_stage(beat, collector=coll)
                    for ln in lanes]
            coll.flush()
            mb_outs = [f() for f in fins]
            beat_ms.append((time.perf_counter() - t0) * 1e3)
            per_beat_disp.append(ENG.DISPATCHES - db)
            t0 = time.perf_counter()
            solo_out = solo_tw.append(beat)
            solo_ms.append((time.perf_counter() - t0) * 1e3)
        n_mb = ENG.MEGABATCHES - mb0
        # one launched program advances all N lanes, every beat (a 0
        # is a watermark-held beat whose rows ride the next one)
        assert max(per_beat_disp) <= 1, per_beat_disp
        assert sum(per_beat_disp) >= len(per_beat_disp) - 2, \
            per_beat_disp
        assert n_mb == sum(per_beat_disp), (n_mb, per_beat_disp)
        # fused lanes report the SAME verdict as the solo twin
        for o in mb_outs:
            assert o["valid"] == solo_out["valid"], (o, solo_out)
        for ln in lanes:
            ln.close()
        solo_tw.close()

    n = len(append_ms)
    head = sum(append_ms[:4]) / 4
    tail = sum(append_ms[-4:]) / 4
    # the claim: per-append cost independent of accumulated history —
    # the last appends may not cost more than ~2x the first (noise
    # floor on one CPU), while scratch grows with the prefix
    flat = tail <= 2.0 * max(head, 1.0)
    growth = (scratch_ms[-1] / max(scratch_ms[0], 1e-9)
              if len(scratch_ms) >= 2 else None)
    result = {
        "bench": "stream",
        "backend": __import__("jax").default_backend(),
        "events": args.events,
        "delta": args.delta,
        "appends": n,
        "dispatches": n_disp,
        "append_ms": {"head4": round(head, 3),
                      "tail4": round(tail, 3),
                      "mean": round(sum(append_ms) / n, 3),
                      "max": round(max(append_ms), 3)},
        "per_append_flat": flat,
        "scratch_checkpoints": checkpoints,
        "scratch_ms": [round(x, 3) for x in scratch_ms],
        "scratch_growth": round(growth, 2) if growth else None,
        "incremental_win_at_end": round(
            scratch_ms[-1] / max(tail, 1e-9), 2),
        "tunnel_model": {
            "dispatch_roundtrip_ms": TUNNEL_ROUNDTRIP_MS,
            "modeled_append_ms": round(tail + TUNNEL_ROUNDTRIP_MS, 3),
            "modeled_scratch_ms": round(
                scratch_ms[-1] + TUNNEL_ROUNDTRIP_MS, 3),
            "note": "both sides pay one ~100 ms tunnel round-trip "
                    "per dispatch on the real TPU; only scratch "
                    "pays the O(history) host pack + device scan",
        },
        "session": {"replays": out["replays"],
                    "frontier_capacity": out.get("frontier_capacity"),
                    "segments": out["segments"]},
        "megabatch": {
            "sessions": n_lanes,
            "beats": len(per_beat_disp),
            "delta": mb_delta,
            "dispatches": sum(per_beat_disp),
            "dispatches_per_beat": round(
                sum(per_beat_disp) / len(per_beat_disp), 3),
            "megabatches": n_mb,
            "beat_ms_mean": round(sum(beat_ms) / len(beat_ms), 3),
            "per_session_beat_ms": round(
                sum(beat_ms) / len(beat_ms) / n_lanes, 3),
            "solo_append_ms_mean": round(
                sum(solo_ms) / len(solo_ms), 3),
            "tunnel_model": {
                # the fused beat pays ONE ~100 ms round-trip for all
                # N lanes; N solo appends pay N — amortization is the
                # round-trip divided by lanes plus the (shared) fused
                # host+device beat cost
                "solo_per_append_ms": round(
                    sum(solo_ms) / len(solo_ms)
                    + TUNNEL_ROUNDTRIP_MS, 3),
                "fused_per_session_ms": round(
                    (sum(beat_ms) / len(beat_ms)
                     + TUNNEL_ROUNDTRIP_MS) / n_lanes, 3),
                "amortization_x": round(
                    (sum(solo_ms) / len(solo_ms)
                     + TUNNEL_ROUNDTRIP_MS)
                    / ((sum(beat_ms) / len(beat_ms)
                        + TUNNEL_ROUNDTRIP_MS) / n_lanes), 2),
            },
        },
        "compile_guard": g.summary(),
    }
    line = json.dumps(result)
    print(line)
    with open(args.json, "w") as fh:
        fh.write(line + "\n")
    assert flat, (
        f"per-append cost grew with history: head4={head:.1f} ms "
        f"tail4={tail:.1f} ms")
    mbm = result["megabatch"]["tunnel_model"]
    assert mbm["fused_per_session_ms"] < mbm["solo_per_append_ms"], \
        mbm
    if compile_guard.enabled():
        g.assert_closed()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
