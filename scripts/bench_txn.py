#!/usr/bin/env python3
"""Bench the txn cycle engines: device matrix closure vs host SCC.

Usage: PYTHONPATH=$AXON_SITE:. python scripts/bench_txn.py [--json out]
(real TPU; CPU works for smoke via JAX_PLATFORMS=cpu).

For each pow2 txn count N in 64..4096 two graph shapes are timed:

- ``sparse``  — ww/wr/rw edges only (~4 edges/txn, the shape a plain
  serializability check sees),
- ``dense``   — the same plus realtime edges (strict
  serializability: every committed pair ordered in real time gets an
  edge, E ~ N^2/2 — the shape where host SCC's Python edge scans
  drown and the MXU closure pays off).

The device path is asserted to be ONE dispatch per check (the
``closure_jax.DISPATCHES`` counter — the per-item-dispatch rule made
measurable), and both engines must agree on every graph. Emits one
JSON line (BENCH_txn.json schema) with per-N ops/s and speedups.
"""
from __future__ import annotations

import argparse
import json
import random
import time

import numpy as np


def make_graph(rng: random.Random, n: int, dense: bool) -> np.ndarray:
    """(4, n, n) bool planes of a plausible dependency graph: a serial
    order with local ww/wr/rw edges (acyclic — valid histories are
    the common case and the closure still runs to full depth), plus
    the dense realtime plane when asked."""
    adj = np.zeros((4, n, n), dtype=bool)
    for i in range(n):
        for _ in range(2):
            j = i + rng.randint(1, 6)
            if j < n:
                adj[rng.randrange(3), i, j] = True
        # a long-range anti-dependency now and then
        if rng.random() < 0.1:
            j = rng.randrange(n)
            if j > i:
                adj[2, i, j] = True
    if dense:
        # realtime: txn i completed before j began for ~half the pairs
        ends = np.cumsum(rng.choices([1, 2], k=n))
        starts = ends - rng.choices([1, 3, 8], k=n)
        adj[3] = starts[None, :] > ends[:, None]
        np.fill_diagonal(adj[3], False)
    return adj


def bench_host(adj: np.ndarray, realtime: bool) -> tuple:
    from comdb2_tpu.txn.scc import cyclic_layers_host

    t0 = time.perf_counter()
    diag = cyclic_layers_host(adj, realtime=realtime)
    return time.perf_counter() - t0, diag


def bench_device(adj: np.ndarray, realtime: bool) -> tuple:
    from comdb2_tpu.txn import closure_jax as CJ

    a = adj.copy()
    if not realtime:
        a[3] = False
    padded = a  # N is already pow2 here
    # warm the program, then time the steady state
    CJ.closure_diag(padded)
    times = []
    for _ in range(2):
        n0 = CJ.DISPATCHES
        t0 = time.perf_counter()
        # a timing loop over one graph, not per-item serving traffic
        diag = CJ.closure_diag(padded)  # analysis: ignore[per-item-dispatch]
        times.append(time.perf_counter() - t0)
        assert CJ.DISPATCHES == n0 + 1, \
            "closure must be ONE device dispatch"  # single-dispatch rule
    return min(times), diag


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="BENCH_txn.json")
    ap.add_argument("--sizes", default="64,256,1024,4096")
    args = ap.parse_args()

    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()
    import jax

    rng = random.Random(7)
    out = {"backend": jax.default_backend(),
           "device": str(jax.devices()[0]), "shapes": {}}
    if out["backend"] != "tpu":
        out["note"] = ("non-TPU backend: no MXU, so the closure's "
                       "matmuls run on host vector units — crossover "
                       "numbers are only meaningful vs the tunnel+MXU "
                       "model (docs/serializability.md)")
    from comdb2_tpu.analysis.compile_surface import static_inventory
    from comdb2_tpu.utils import compile_guard

    inv = static_inventory()
    with compile_guard.guard() as g:
        for n in (int(s) for s in args.sizes.split(",")):
            for dense in (False, True):
                shape = f"{'dense' if dense else 'sparse'}-n{n}"
                adj = make_graph(rng, n, dense)
                host_s, dh = bench_host(adj, realtime=dense)
                dev_s, dd = bench_device(adj, realtime=dense)
                assert np.array_equal(dh, dd), \
                    f"engine mismatch at {shape}"
                edges = int(adj[:3].sum()
                            + (adj[3].sum() if dense else 0))
                out["shapes"][shape] = {
                    "txns": n, "edges": edges,
                    "host_s": round(host_s, 5),
                    "device_s": round(dev_s, 5),
                    "speedup": round(host_s / dev_s, 3)
                    if dev_s else None,
                }
                print(f"{shape:16s} E={edges:9d}  host {host_s:8.4f}s"
                      f"  device {dev_s:8.4f}s  x{host_s / dev_s:7.2f}",
                      flush=True)
    # observed closure programs must stay inside the static inventory
    # (one per pow2 N bucket) — a recompile storm fails the bench
    out["compile_guard"] = g.summary(inv)
    with open(args.json, "w") as fh:
        fh.write(json.dumps(out) + "\n")
    print(f"wrote {args.json}")
    if compile_guard.enabled():
        g.assert_closed(inv)


if __name__ == "__main__":
    main()
