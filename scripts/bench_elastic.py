#!/usr/bin/env python3
"""Elastic-fleet nemesis gate: the checker pointed at its own serving
layer (docs/service.md "Elastic fleet").

The paper's thesis is that a distributed system earns trust only by
surviving injected faults while a checker watches — and our serving
layer is now a distributed system (supervised pmux-registered
daemons, ring-version epochs, checkpoint-migrating sessions). This
bench subjects it to its own medicine:

1. **kill-a-daemon-under-burst** — SIGKILL one of two daemons mid-
   burst (the harshest leave: no drain, no deregistration). The
   routed client must fail over (blacklist the corpse, refresh on
   the supervisor's stale-entry cleanup + epoch bump), the
   supervisor must reap the corpse (no zombies — no init reaper
   here) and respawn to the fleet floor.
2. **join-under-burst** — spawn an extra daemon mid-burst; the epoch
   bump must refresh the client ring and remap ≈1/N of the shape
   classes onto the newcomer (measured and gated — consistent
   hashing, never a reshuffle).
3. **session migration** — a streaming session's daemon is drained
   (`kind:"drain"`); the client hands the session off by checkpoint
   (O(carry)) and post-handoff appends must stay O(delta): dispatch
   deltas gated, zero replays.

Every client-observed request is recorded as an op pair — process =
request id, `invoke write [key 1]` at submission, `ok` at reply —
and the resulting fleet history is fed BACK through the surviving
fleet as a keyed check. The gate: every request answered exactly
once (a drop leaves a dangling invoke counted client-side; a
double-serve is a malformed second completion the checker itself
rejects), and the history checks VALID.

Honest accounting (CLAUDE.md): everything shares this container's
one CPU, so wall-clock is reported, never gated — the gates are
counts (answers, remaps, dispatches, zombies).

Usage: PYTHONPATH=/root/.axon_site:. python scripts/bench_elastic.py
       [--requests-per-class 6] [--quick] [--out BENCH_elastic.json]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

from bench_routing import (find_ct_pmux, free_port,  # noqa: E402
                           start_pmux)

SIZE_CLASSES = (10, 18, 30, 60, 140, 180)

DAEMON_ARGS = ["--backend", "cpu", "--no-prime", "--frontier", "64",
               "--fill-ms", "5"]


def zombies() -> int:
    """Unreaped children of THIS process. Scoped to our own pid
    because the gate means "the bench reaped everything it spawned" —
    a system-wide Z count is racy (LeakSanitizer's exit-time tracer
    briefly shows as a Z child of the dying ASan ct_pmux, which is
    the sanitizer runtime's corpse to collect, not ours)."""
    me = str(os.getpid())
    out = subprocess.run(["ps", "-eo", "ppid=,stat="],
                         capture_output=True, text=True).stdout
    return sum(1 for ln in out.splitlines()
               if ln.split()[:1] == [me]
               and ln.split()[1].startswith("Z"))


def req_history(i: int):
    """One request's op pair: its own key, one write — exactly-once
    serving is exactly one completion per invocation."""
    from comdb2_tpu.ops import op as O

    return (O.invoke(i, "write", (i, 1)), O.ok(i, "write", (i, 1)))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests-per-class", type=int, default=6)
    ap.add_argument("--quick", action="store_true",
                    help="small run (the check.sh elastic stage)")
    ap.add_argument("--out",
                    default=os.path.join(REPO, "BENCH_elastic.json"))
    ap.add_argument("--max-remap", type=float, default=0.7,
                    help="gate on the join's remapped shape-class "
                         "fraction (expected ~1/3 at N=2->3)")
    args = ap.parse_args()
    if args.quick:
        args.requests_per_class = min(args.requests_per_class, 2)

    # backend discipline: every spawned daemon passes --backend cpu
    # (DAEMON_ARGS), which switches platforms through the config API
    # — the authoritative path; env vars after import do nothing
    # (CLAUDE.md). check.sh additionally exports JAX_PLATFORMS=cpu
    # for the subprocess tree.
    from comdb2_tpu.ops.history import history_to_edn
    from comdb2_tpu.ops.synth import register_history
    from comdb2_tpu.service.client import RoutedClient, ServiceError
    from comdb2_tpu.service.supervisor import Supervisor

    z0 = zombies()
    pmux_port = free_port()
    pmux = start_pmux(find_ct_pmux(), pmux_port)
    sup = Supervisor(pmux_port=pmux_port, min_daemons=2,
                     max_daemons=4, daemon_args=DAEMON_ARGS,
                     drain_grace_s=5.0, scale_cooldown_s=1e9)
    fleet_ops = []            # the client-observed serving history
    answered: dict = {}       # req id -> reply count
    failures: dict = {}       # req id -> error string
    out: dict = {"bench": "elastic", "backend": "cpu",
                 "size_classes": list(SIZE_CLASSES)}
    rc = None
    try:
        sup.spawn()
        sup.spawn()
        rc = RoutedClient.discover(pmux_port=pmux_port,
                                   timeout_s=300.0, retries=1,
                                   backoff_s=0.05)
        assert len(rc.clients) == 2, rc.clients
        epoch0 = rc.epoch

        texts = []
        for ci, n_events in enumerate(SIZE_CLASSES):
            for j in range(args.requests_per_class):
                h = register_history(
                    random.Random(9000 + 37 * ci + j), n_procs=3,
                    n_events=n_events, p_info=0.0)
                texts.append(history_to_edn(h))
        n = len(texts)

        def drive(i: int, text: str, route: str = "shape") -> None:
            """One request, recorded as the fleet history sees it."""
            inv, ok = req_history(i)
            fleet_ops.append(inv)
            try:
                r = rc.check(text, route=route)
            except (OSError, ServiceError) as e:
                # ServiceError: the whole walk ended overloaded /
                # shutting-down (overload_retries=0 under discover) —
                # record it as a gate failure, don't crash the bench
                failures[i] = str(e)
                return
            if r.get("ok"):
                answered[i] = answered.get(i, 0) + 1
                fleet_ops.append(ok)
            else:
                failures[i] = r.get("error", "?")

        # --- phase 1: kill a daemon mid-burst (SIGKILL nemesis) ----
        kill_at = n // 3
        victim = sup.children[0]
        served_before_kill = None
        for i, text in enumerate(texts):
            if i == kill_at:
                # the nemesis: SIGKILL with no drain and no reap HERE
                # — the supervisor's beat() poll()s and reaps the
                # corpse; waiting here would serialize the fault with
                # the burst we are measuring under
                victim.proc.kill()        # no drain, no deregister  # analysis: ignore[wait-after-kill]
                served_before_kill = dict(rc.served)
            drive(i, text)
            if i % 4 == 3:
                sup.beat()                # reap + stale cleanup +
                                          # respawn to the floor
        deadline = time.monotonic() + 30
        while len(sup.children) < 2 and time.monotonic() < deadline:
            sup.beat()
            time.sleep(0.2)
        out["kill"] = {
            "victim": victim.service,
            "killed_at_request": kill_at,
            "failovers": rc.failovers,
            "ring_refreshes": rc.refreshes,
            "stale_cleanups": sup.stale_cleanups,
            "deaths_reaped": sup.deaths,
            "respawned_to_floor": len(sup.children) >= 2,
        }
        # the survivor picked up the victim's classes: traffic kept
        # being served after the kill by SOMEONE else
        survivor = next(name for name in served_before_kill
                        if name != victim.service)
        assert rc.served[survivor] > served_before_kill[survivor], \
            "survivor served nothing after the kill"

        # --- phase 2: join under burst -----------------------------
        rc.maybe_refresh(force=True)
        # the remap bound is measured over a dense synthetic key set
        # (the live workload has only ~6 distinct shape classes —
        # far too few to estimate a fraction)
        probes = [f"probe|{i}" for i in range(512)]
        owners_before = {k: rc.ring.nodes_for(k)[0] for k in probes}
        joined = sup.spawn()              # registers + bumps epoch
        extra = []
        for ci, n_events in enumerate(SIZE_CLASSES):
            for j in range(args.requests_per_class):
                h = register_history(
                    random.Random(5000 + 31 * ci + j), n_procs=3,
                    n_events=n_events, p_info=0.0)
                extra.append(history_to_edn(h))
        for k, text in enumerate(extra):
            drive(n + k, text)
        n_total = n + len(extra)
        assert rc.epoch != epoch0, (epoch0, rc.epoch)
        owners_after = {k: rc.ring.nodes_for(k)[0] for k in probes}
        moved_keys = [k for k in probes
                      if owners_before[k] != owners_after[k]]
        remap_frac = len(moved_keys) / len(probes)
        # every moved key landed ON the newcomer (join never
        # shuffles keys between survivors)
        join_clean = all(owners_after[k] == joined.service
                         for k in moved_keys)
        # drive the newcomer for real: payload routing gives a dense
        # key space, so some recorded request provably hashes to it
        newcomer_serves = rc.served.get(joined.service, 0)
        probe_texts = [t for t in extra
                       if rc.ring.nodes_for(RoutedClient.route_key(
                           t, route="payload"))[0] == joined.service]
        for t in probe_texts[:4]:
            drive(n_total, t, route="payload")
            n_total += 1
        newcomer_serves = rc.served.get(joined.service, 0)
        out["join"] = {
            "service": joined.service,
            "epoch_before": epoch0, "epoch_after": rc.epoch,
            "remapped_fraction": round(remap_frac, 3),
            "moved_only_to_newcomer": join_clean,
            "max_remap_gate": args.max_remap,
            "newcomer_served": newcomer_serves,
        }

        # --- phase 3: stream-session migration via drain -----------
        sh = register_history(random.Random(77), n_procs=3,
                              n_events=96, p_info=0.0, max_pending=2)
        stream = rc.stream_open()
        cut = len(sh) // 2
        r1 = stream.append(sh[:cut])
        assert r1.get("ok") and r1["valid"] is True, r1
        d_half = r1["dispatches"]
        pinned = stream.node
        # drain the pinned daemon: the next append migrates by
        # checkpoint instead of replaying the retained deltas
        rc.clients[pinned].drain()
        time.sleep(0.3)                   # let its loop enter drain
        r2 = stream.append(sh[cut:])
        assert r2.get("ok") and r2["valid"] is True, r2
        closed = stream.close()
        out["stream"] = {
            "pinned": pinned, "migrated_to": stream.node,
            "migrations": stream.migrations,
            "replays_after_handoff": r2.get("replays", -1),
            "dispatches_first_half": d_half,
            "dispatches_total": r2["dispatches"],
            "final_valid": closed.get("valid"),
        }
        migration_ok = (
            stream.migrations == 1 and stream.node != pinned
            and r2.get("replays") == 0
            # O(delta): the second half costs about the first half —
            # a replay would re-dispatch the whole prefix on top
            and r2["dispatches"] - d_half <= d_half + 2
            and closed.get("valid") is True)
        # the drained daemon exits on its own; reap it and refill
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sup.beat()
            if all(c.proc.poll() is None
                   for c in sup.children.values()) \
                    and len(sup.children) >= 2:
                break
            time.sleep(0.2)

        # --- the self-check gate -----------------------------------
        exactly_once = (len(answered) == n_total
                        and all(v == 1 for v in answered.values())
                        and not failures)
        edn = history_to_edn(fleet_ops)
        verdict = rc.check(edn, keyed=True,
                           raise_on_error=False)
        out["self_check"] = {
            "requests": n_total,
            "answered_exactly_once": exactly_once,
            "dropped": sorted(set(range(n_total)) - set(answered)),
            "failures": failures,
            "double_served": sorted(k for k, v in answered.items()
                                    if v > 1),
            "fleet_history_ops": len(fleet_ops),
            "fleet_history_valid": verdict.get("valid"),
            "checker_engine": verdict.get("engine"),
        }
        out["supervisor"] = {
            "spawned": sup.spawned, "retired": sup.retired,
            "deaths": sup.deaths,
            "stale_cleanups": sup.stale_cleanups,
        }
        gate_ok = (exactly_once
                   and verdict.get("valid") is True
                   and 0 < remap_frac <= args.max_remap
                   and join_clean
                   and newcomer_serves > 0
                   and migration_ok
                   and out["kill"]["respawned_to_floor"])
    finally:
        if rc is not None:
            rc.close()
        sup.shutdown()
        try:
            import socket as _s
            s = _s.create_connection(("127.0.0.1", pmux_port),
                                     timeout=2)
            s.sendall(b"exit\n")
            s.close()
        except OSError:
            pass
        pmux.terminate()
        pmux.wait(timeout=30)

    out["zombies_delta"] = zombies() - z0
    out["note"] = ("1-CPU container: all daemons share the host CPU, "
                   "so no wall-clock gates; the gates are counts — "
                   "every client request answered exactly once "
                   "across a SIGKILL and a join, the client-observed "
                   "fleet history checks VALID through the fleet "
                   "itself, the join remapped ~1/N of the shape "
                   "classes, and the migrated session's appends "
                   "stayed O(delta) (no replay) after the "
                   "checkpoint handoff")
    out["gate_ok"] = bool(gate_ok) and out["zombies_delta"] <= 0
    line = json.dumps(out)
    print(line)
    with open(args.out, "w") as fh:
        fh.write(line + "\n")
    if not out["gate_ok"]:
        print("FAIL: elastic gate", file=sys.stderr)
        return 1
    # artifact hygiene: the supervised fleet wrote stores/registrations
    # all over the tree — the static-analysis verdict must stay clean
    # post-run (subprocess so the verdict is independent of this
    # process's jax/import state)
    r = subprocess.run(
        [sys.executable, "-m", "comdb2_tpu.analysis", "--no-trace"],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        print("FAIL: static analysis not clean post-run:\n"
              f"{r.stdout}{r.stderr}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
