#!/usr/bin/env python3
"""Bench the counterexample minimizer: batched ddmin vs the serial
one-candidate-per-dispatch control.

Usage: PYTHONPATH=$AXON_SITE:. python scripts/bench_shrink.py
       [--json BENCH_shrink.json] [--inject-dispatch-latency-ms 100]

Shapes:

- ``register-{2k,10k}-{stale-read,lost-update}`` — synthetic injected-
  anomaly histories (``ops.synth.inject_anomaly`` over write-only /
  read-only register bases): known ground-truth minima of 1-2 pairs
  buried in 2k/10k events.
- ``txn-T-write-skew`` — the ``-T`` buggy-txn cluster-failure
  signature (G2-item write skew): an 8-txn rw ring embedded in a
  clean list-append run.
- ``txn-R-dirty-commit`` — the ``-R`` dirty-commit signature: the
  same ring with one FAIL txn whose append is observed by the audit
  read (G1a + a cycle THROUGH the dirty txn).

Both paths run the SAME ddmin rounds with the SAME verdicts; only the
dispatch shape differs — the batched path tests a round's candidates
in ONE ``check_batch``/``closure_diag_batch`` per pow2 bucket, the
serial control pays one device round-trip per candidate (the
``per-item-dispatch`` bug, suppressed here because measuring it is
the point). ``--inject-dispatch-latency-ms`` (default 100, the
measured tunnel dispatch+readback round-trip) is slept per dispatch
on BOTH paths and declared in the JSON, so the amortization shows up
in wall clock on CPU the way it does on the real link.

Asserts: the batched path wins every shape on both dispatches and
wall; every minimization certifies 1-minimality; the 10k-event seeded
failure minimizes to <= 20 ops with the certificate re-derived
against the host oracle.
"""
from __future__ import annotations

import argparse
import json
import random
import time

import numpy as np


def make_ring(k: int, dirty: bool, dp: int = 500, dk: int = 500):
    """A write-skew rw ring of ``k`` sequential txns (t_i reads key_i
    empty, appends to key_{i+1}) + an audit read of every key. With
    ``dirty``, one ring txn FAILS but its append is observed by the
    audit read — the -R dirty-commit signature (G1a + a cycle through
    the dirty txn); without, the -T write-skew signature."""
    from comdb2_tpu.ops import op as O

    h = []
    for i in range(k):
        mops = (("r", dk + i, None), ("append", dk + (i + 1) % k, 1))
        done = (("r", dk + i, ()), ("append", dk + (i + 1) % k, 1))
        typ = "fail" if dirty and i == 0 else "ok"
        h.append(O.invoke(dp + i, "txn", mops))
        h.append(O.Op(dp + i, typ, "txn", done))
    audit = tuple(("r", dk + i, (1,)) for i in range(k))
    h.append(O.invoke(dp + k, "txn",
                      tuple(("r", dk + i, None) for i in range(k))))
    h.append(O.Op(dp + k, "ok", "txn", audit))
    return h


def register_seed(n_events: int, kind: str):
    from comdb2_tpu.ops.synth import inject_anomaly, register_history

    fs = ("read",) if kind == "lost-update" else ("write",)
    base = register_history(random.Random(7), n_procs=3,
                            n_events=n_events, fs=fs, p_info=0.0,
                            max_pending=2)
    return inject_anomaly(base, kind)


def txn_seed(kind: str, n_txns: int = 400):
    from comdb2_tpu.ops.synth import list_append_history

    clean = list_append_history(random.Random(11), n_procs=3,
                                n_txns=n_txns, n_keys=4)
    return list(clean) + make_ring(8, dirty=(kind == "R")), None


def serial_linear(h, F):
    """The serial control: same ddmin, one dispatch per candidate."""
    from comdb2_tpu.shrink import Shrinker
    from comdb2_tpu.shrink.verdicts import check_candidate

    class SerialShrinker(Shrinker):
        def _statuses(self, cand_sets):
            out = []
            for s in cand_sets:
                out.append(check_candidate(  # analysis: ignore[per-item-dispatch]
                    self.packed, self.mask_of(s), self.memo, F=self.F,
                    engine=self.engine, counters=self.counters))
            return np.asarray(out, np.int32)

    return SerialShrinker(h, "cas-register", F=F)


def serial_txn(h):
    from comdb2_tpu.shrink import TxnShrinker
    from comdb2_tpu.txn.edges import TXN_N_FLOOR
    from comdb2_tpu.utils import next_pow2

    class SerialTxnShrinker(TxnShrinker):
        def _test(self, cand_sets):
            from comdb2_tpu.txn.closure_jax import closure_diag

            out = np.zeros(len(cand_sets), bool)
            self.counters["candidates"] += len(cand_sets)
            for i, ids in enumerate(cand_sets):
                if len(ids) < 2:
                    continue
                n_pad = next_pow2(len(ids), TXN_N_FLOOR)
                d = closure_diag(  # analysis: ignore[per-item-dispatch]
                    self._sub_adj(ids, n_pad))
                out[i] = bool(np.asarray(d).any())
                self.counters["dispatches"] += 1
            return out

    return SerialTxnShrinker(h)


def run_job(job, latency_s: float):
    """Drive a shrinker to completion, sleeping the injected tunnel
    round-trip per DISPATCH (both paths pay it identically)."""
    t0 = time.perf_counter()
    seen = 0
    while not job.step():
        d = job.counters["dispatches"] - seen
        seen = job.counters["dispatches"]
        if latency_s:
            time.sleep(d * latency_s)
    if latency_s:
        time.sleep((job.counters["dispatches"] - seen) * latency_s)
    wall = time.perf_counter() - t0
    assert job.error is None, job.error
    return job.result(), wall


def time_path(make_job, latency_s: float):
    """Run a path twice with fresh jobs and keep the WARM wall (the
    paths compile different program sets — batched B>1 vs serial B=1
    — so whichever runs first would otherwise eat every cold compile
    and the comparison would measure ordering, not dispatch shape)."""
    res, walls = None, []
    for _ in range(2):
        res, w = run_job(make_job(), latency_s)
        walls.append(w)
    return res, min(walls)


def oracle_one_minimal(ops) -> bool:
    """Re-derive the certificate on the HOST engine: dropping any
    remaining atom must flip the verdict."""
    from comdb2_tpu.checker import linear
    from comdb2_tpu.models.model import MODELS
    from comdb2_tpu.ops.columnar import subset_packed
    from comdb2_tpu.ops.packed import pack_history
    from comdb2_tpu.shrink import atoms_of

    p = pack_history([op.with_() for op in ops])
    atoms, pinned = atoms_of(p)
    for k in range(len(atoms)):
        keep = pinned.copy()
        for j, a in enumerate(atoms):
            if j != k:
                keep[a] = True
        v = linear.analysis(MODELS["cas-register"](),
                            subset_packed(p, keep).ops,
                            backend="host").valid
        if v is False:
            return False
    return True


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="BENCH_shrink.json")
    ap.add_argument("--inject-dispatch-latency-ms", type=float,
                    default=100.0,
                    help="slept per device dispatch on BOTH paths "
                         "(models the tunnel round-trip; declared in "
                         "the JSON)")
    ap.add_argument("--frontier", type=int, default=64)
    args = ap.parse_args()

    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()
    import jax

    from comdb2_tpu.shrink import Shrinker, TxnShrinker

    lat = args.inject_dispatch_latency_ms / 1e3
    out = {"backend": jax.default_backend(),
           "device": str(jax.devices()[0]),
           "injected_dispatch_latency_ms":
               args.inject_dispatch_latency_ms,
           "frontier": args.frontier, "shapes": {}}
    if out["backend"] != "tpu":
        out["note"] = ("non-TPU backend: dispatch cost is modeled by "
                       "the declared injected latency; on the real "
                       "tunnel each dispatch pays ~100 ms for free")

    from comdb2_tpu.analysis.compile_surface import static_inventory
    from comdb2_tpu.utils import compile_guard

    inv = static_inventory()
    # try/finally, not a bare start(): the guard must detach (and
    # jax_log_compiles restore) even when a shape assertion below
    # fails mid-run
    g = compile_guard.CompileGuard().start()

    shapes = [
        ("register-2k-stale-read", "linear",
         lambda: register_seed(2000, "stale-read")),
        ("register-2k-lost-update", "linear",
         lambda: register_seed(2000, "lost-update")),
        ("register-10k-stale-read", "linear",
         lambda: register_seed(10000, "stale-read")),
        ("register-10k-lost-update", "linear",
         lambda: register_seed(10000, "lost-update")),
        ("txn-T-write-skew", "txn", lambda: txn_seed("T")),
        ("txn-R-dirty-commit", "txn", lambda: txn_seed("R")),
    ]
    try:
        for name, axis, make in shapes:
            h, truth = make()
            if axis == "linear":
                mk_b = lambda: Shrinker(h, "cas-register",  # noqa: E731
                                        F=args.frontier)
                mk_s = lambda: serial_linear(h, args.frontier)  # noqa: E731
            else:
                mk_b = lambda: TxnShrinker(h)               # noqa: E731
                mk_s = lambda: serial_txn(h)                # noqa: E731
            rb, wall_b = time_path(mk_b, lat)
            rs, wall_s = time_path(mk_s, lat)
            assert rb.one_minimal and not rb.partial, name
            assert rb.n_ops == rs.n_ops, \
                f"{name}: batched/serial minima differ ({rb.n_ops} vs " \
                f"{rs.n_ops}) — same rounds, same verdicts expected"
            if truth is not None:
                assert rb.n_ops == len(truth), \
                    f"{name}: missed the ground truth " \
                    f"({rb.n_ops} vs {len(truth)})"
            db = rb.dispatches
            ds = rs.dispatches
            assert ds > db, f"{name}: serial used {ds} dispatches vs " \
                            f"batched {db} — no amortization?"
            assert wall_s > wall_b, \
                f"{name}: batched did not win wall ({wall_b:.2f}s vs " \
                f"{wall_s:.2f}s)"
            entry = {
                "axis": axis, "seed_ops": rb.seed_ops,
                "minimal_ops": rb.n_ops, "rounds": rb.rounds,
                "candidates": rb.candidates,
                "dispatches_batched": db, "dispatches_serial": ds,
                "candidates_per_dispatch": round(rb.candidates / db, 2),
                "wall_batched_s": round(wall_b, 3),
                "wall_serial_s": round(wall_s, 3),
                "speedup": round(wall_s / wall_b, 3),
                "one_minimal": rb.one_minimal,
            }
            if axis == "txn":
                entry["anomaly_class"] = rb.extra.get("anomaly_class")
                entry["minimal_txns"] = len(rb.extra.get("txns", ()))
            if name == "register-10k-stale-read":
                flagship_ops = rb.ops
            out["shapes"][name] = entry
            print(f"{name:26s} {rb.seed_ops:6d} -> {rb.n_ops:3d} ops  "
                  f"rounds {rb.rounds:3d}  disp {db:3d} vs {ds:3d}  "
                  f"wall {wall_b:7.2f}s vs {wall_s:7.2f}s  "
                  f"x{wall_s / wall_b:5.2f}", flush=True)

        # the acceptance flagship: a 10k-event seeded failure minimizes to
        # <= 20 ops and the certificate survives the host oracle
        flag = out["shapes"]["register-10k-stale-read"]
        assert flag["minimal_ops"] <= 20, flag
        assert oracle_one_minimal(flagship_ops), \
            "host oracle refutes the 1-minimality certificate"
        out["flagship_oracle_one_minimal"] = True
    finally:
        g.stop()

    # every shrink round's candidate batches must ride the closed
    # pow2-bucketed program set — observed compiles ⊆ PROGRAMS.md
    out["compile_guard"] = g.summary(inv)
    with open(args.json, "w") as fh:
        fh.write(json.dumps(out) + "\n")
    print(f"wrote {args.json}")
    if compile_guard.enabled():
        g.assert_closed(inv)


if __name__ == "__main__":
    main()
