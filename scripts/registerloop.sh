#!/bin/bash
# Re-run the native register workload + offline TPU check in a loop,
# failing on the first invalid analysis — the role of the reference's
# linearizable/ctest/registerloop.sh + jepsenloop.sh outer driver
# (heal, run, grep for "Analysis invalid!", repeat).
#
# Usage: scripts/registerloop.sh [runs] [driver-args...]
#   REGISTER=path     override the driver binary
#   FILETEST="..."    override the checker command
set -u

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
REGISTER="${REGISTER:-$ROOT/native/build/ct_register}"
FILETEST="${FILETEST:-python -m comdb2_tpu.filetest}"
RUNS="${1:-0}"   # 0 = forever
shift 2>/dev/null || true

[ -x "$REGISTER" ] || {
    echo "building native drivers..." >&2
    cmake -S "$ROOT/native" -B "$ROOT/native/build" >/dev/null \
        && cmake --build "$ROOT/native/build" >/dev/null || exit 2
}

n=0
while [ "$RUNS" -eq 0 ] || [ "$n" -lt "$RUNS" ]; do
    n=$((n + 1))
    hist="$(mktemp /tmp/register-hist-XXXX.edn)"
    echo "=== run $n: $REGISTER -j $hist $*" >&2
    "$REGISTER" -j "$hist" "$@" || { echo "driver failed" >&2; exit 2; }
    PYTHONPATH="$ROOT" $FILETEST "$hist"
    rc=$?
    if [ $rc -eq 1 ]; then
        echo "Analysis invalid! history kept at $hist" >&2
        exit 1
    elif [ $rc -ne 0 ] && [ $rc -ne 2 ]; then
        echo "checker crashed (rc=$rc); history kept at $hist" >&2
        exit 3
    fi
    rm -f "$hist"
done
echo "all $n runs valid" >&2
