#!/usr/bin/env python3
"""Fuzz the MXU frontier engine — wide-P floor for checker/mxu.

Usage: PYTHONPATH=$AXON_SITE:. python scripts/fuzz_mxu.py \
           [n] [--out FUZZ_mxu.json]

Two seeded families, bucketed shapes so runs share compiled programs
(per-seed shapes recompile per seed and can OOM LLVM — CLAUDE.md):

- ``register``: small random register histories (valid + mutated)
  through the MXU engine vs the XLA seg engine AND the host oracle;
  where the fused Pallas kernel serves the shape (P <= 15, K <= 8,
  real TPU) its verdict is cross-checked too — the overlapping-P
  parity floor of the round-10 acceptance.
- ``wide-p-waves``: genuinely concurrent bounded-in-flight wave
  histories (``wide_register_batch_columns``) at P in {16, 24},
  valid + seeded-violation twins, MXU vs the XLA seg engine at a
  frontier that fits both; small free-read counts keep the host
  oracle affordable, so every seed is host-checked as well.

Verdict AND fail-segment parity are asserted (final counts on VALID
only — the cross-engine contract). ``--out`` writes a JSON artifact
with per-family counts so coverage is recorded, not scrollback.
"""
from __future__ import annotations

import json
import random
import sys
from collections import Counter


def _check_all(mm, segs, succ, P, bucket, F=1024):
    """(engine -> (status, fail_seg, n)) for every engine serving the
    bucketed shape."""
    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.checker import mxu as MXU
    from comdb2_tpu.checker import pallas_seg as PS

    sizes = dict(n_states=bucket[0], n_transitions=bucket[1])
    out = {}
    st, fa, n = LJ.check_device_seg(
        succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
        F=F, P=P, **sizes)
    out["xla-seg"] = (int(st), int(fa), int(n))
    if MXU.fits(bucket[0], bucket[1], P):
        st, fa, n = MXU.check_device_mxu(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc,
            segs.depth, F=F, P=P, **sizes)
        out["mxu"] = (int(st), int(fa), int(n))
    if PS.available():
        r = PS.check_device_pallas(succ, segs, P=P, **sizes)
        if r is not None:
            out["pallas-fused"] = tuple(int(x) for x in r)
    return out


def _assert_parity(name, seed, verdicts, host_valid, host_index,
                   seg_index):
    base = verdicts["xla-seg"]
    for eng, (st, fa, n) in verdicts.items():
        assert st == base[0], (name, seed, eng, verdicts)
        if st == 0:
            # the kernel's F is fixed at 128 — counts only compare at
            # the same frontier capacity, so VALID counts are asserted
            # between the same-F engines (xla/mxu)
            if eng != "pallas-fused":
                assert n == base[2], (name, seed, eng, verdicts)
        else:
            assert fa == base[1], (name, seed, eng, verdicts)
    if host_valid is not None and base[0] != 2:
        assert (base[0] == 0) == host_valid, (name, seed, verdicts)
        if base[0] == 1:
            assert int(seg_index[base[1]]) == host_index, \
                (name, seed, verdicts, host_index)


def main() -> None:
    from comdb2_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    import sys as _sys

    _sys.path.insert(0, "tests")

    from comdb2_tpu.checker import linear_host, linear_jax as LJ
    from comdb2_tpu.models.memo import MemoOverflow, memo as make_memo
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops import synth_columnar as SC
    from comdb2_tpu.ops.packed import pack_history
    from comdb2_tpu.ops.synth import mutate, register_history

    args = list(sys.argv[1:])
    out_path = None
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            sys.exit("usage: fuzz_mxu.py [n] [--out FILE]")
        out_path = args[i + 1]
        del args[i:i + 2]
    n = int(args[0]) if args else 60
    c = Counter()

    # --- register family: bucket (64, 8) segs, (32, 64) table ------
    for seed in range(3000, 3000 + n):
        rng = random.Random(seed)
        h = register_history(rng, n_procs=rng.randint(2, 5),
                             n_events=rng.randint(10, 60), values=3,
                             p_info=0.05)
        if rng.random() < 0.5:
            h = mutate(rng, h)
        packed = pack_history(h)
        try:
            mm = make_memo(cas_register(), packed)
        except MemoOverflow:
            c["register", "memo-skip"] += 1
            continue
        if mm.n_states > 32 or mm.n_transitions > 64:
            c["register", "skip"] += 1
            continue
        segs = LJ.make_segments(packed, s_pad=64, k_pad=8)
        segs, p_eff = LJ.remap_slots(segs)
        P = max(p_eff, 1)
        if segs.inv_proc.shape != (64, 8) or P > 15:
            c["register", "skip"] += 1
            continue
        bucket = (32, 64)
        succ = LJ.pad_succ(mm.succ, *bucket)
        verdicts = _check_all(mm, segs, succ, P, bucket, F=128)
        hr = linear_host.check(mm, packed)
        _assert_parity("register", seed, verdicts, hr.valid,
                       hr.op_index, segs.seg_index)
        c["register",
          {0: "ok", 1: "inv", 2: "unk"}[verdicts["xla-seg"][0]]] += 1
        if "pallas-fused" in verdicts:
            c["register", "kernel-crosschecked"] += 1
        if "mxu" not in verdicts:
            c["register", "mxu-nofit"] += 1
    print("register", {k[1]: v for k, v in c.items()
                       if k[0] == "register"}, flush=True)
    checked = sum(c["register", k] for k in ("ok", "inv", "unk"))
    assert checked >= (2 * n) // 3, f"register coverage {checked}/{n}"
    assert c["register", "ok"] and c["register", "inv"]
    assert c["register", "mxu-nofit"] == 0, \
        "every register bucket shape must fit the MXU engine"

    # --- wide-P wave family: P in {16, 24}, valid + violation ------
    for P in (16, 24):
        fam = f"waves-p{P}"
        for seed in range(4000, 4000 + n):
            rng = random.Random(seed)
            n_free = rng.randint(2, 6)       # host-oracle affordable
            n_chain = P - n_free
            n_waves = rng.randint(1, 3)
            violation = rng.random() < 0.5
            cols = SC.wide_register_batch_columns(
                seed, 1, n_waves, n_chain, n_free,
                values=max(16, n_chain + 2), violation=violation)
            packed = SC.pack_register_columns(cols)[0]
            mm = make_memo(cas_register(), packed)
            if mm.n_states > 32 or mm.n_transitions > 64:
                c[fam, "skip"] += 1
                continue
            segs = LJ.make_segments(packed, s_pad=128, k_pad=32)
            segs, p_eff = LJ.remap_slots(segs)
            assert p_eff == P, (p_eff, P)    # genuinely concurrent
            bucket = (32, 64)
            succ = LJ.pad_succ(mm.succ, *bucket)
            verdicts = _check_all(mm, segs, succ, P, bucket, F=1024)
            assert "mxu" in verdicts, "wave shape must fit the engine"
            hr = linear_host.check(mm, packed)
            assert hr.valid is (not violation), (fam, seed, hr.valid)
            _assert_parity(fam, seed, verdicts, hr.valid, hr.op_index,
                           segs.seg_index)
            c[fam,
              {0: "ok", 1: "inv", 2: "unk"}[verdicts["mxu"][0]]] += 1
        print(fam, {k[1]: v for k, v in c.items() if k[0] == fam},
              flush=True)
        assert c[fam, "ok"] and c[fam, "inv"], \
            f"{fam}: both verdict classes must be exercised"
        assert c[fam, "unk"] == 0, \
            f"{fam}: bounded waves must never overflow F=1024"

    if out_path:
        import jax

        families = {}
        for fam in ("register", "waves-p16", "waves-p24"):
            families[fam] = {k[1]: v for k, v in c.items()
                             if k[0] == fam}
            families[fam]["seeds"] = n
        artifact = {
            "seeds_per_family": n,
            "families": families,
            "engines": ["mxu", "xla-seg", "pallas-fused",
                        "linear-host"],
            "backend": jax.default_backend(),
            "verdict": "PASS",   # any mismatch asserts before this
        }
        with open(out_path, "w") as fh:
            json.dump(artifact, fh, indent=1)
        print("artifact written:", out_path, flush=True)


if __name__ == "__main__":
    main()
