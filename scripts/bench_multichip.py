#!/usr/bin/env python3
"""Bench the mesh-sharded batch verification path over D chips.

Usage: JAX_PLATFORMS=cpu python scripts/bench_multichip.py
       [--json BENCH_multichip.json] [--histories 256] [--events 192]

Runs ONE fixed register workload through ``check_batch`` for every
shard count D in {1, 2, 4, 8} on the forced D-visible CPU mesh and
records, per D:

- ``dispatches``       — device dispatches the sharded run issued
  (the single-dispatch-per-shard-per-slice discipline, asserted);
- ``per_shard_b``      — histories each shard's program processes
  (B_pad / D — the dispatch-width scaling claim);
- ``per_shard_device_run_s`` — MEASURED device seconds of exactly one
  shard's workload (the per-shard batch run unsharded on one device).
  This is the honest multi-chip accounting on this container: the 8
  "devices" share ONE CPU, so sharded wall clock measures host
  serialization, not ICI parallelism — what scales with D is the
  per-shard program's work, ~1/D of the D=1 total;
- verdict bit-parity with the D=1 run (hard assert).

The whole run executes under the compile guard; the summary embeds in
the JSON and offenders fail the bench (``COMDB2_TPU_COMPILE_GUARD=0``
= report-only), same contract as bench.py.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _force_cpu_mesh(n: int) -> None:
    """The dryrun's env dance (``__graft_entry__._cpu_mesh_env``):
    XLA reads the device-count flag at BACKEND creation, so updating
    the env before the platform switch works even with jax
    pre-imported — the authoritative switch is jax.config.update."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import __graft_entry__ as graft

    os.environ.update(graft._cpu_mesh_env(n))
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.default_backend() == "cpu", jax.default_backend()
    assert len(jax.devices()) >= n, jax.devices()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="BENCH_multichip.json")
    ap.add_argument("--histories", type=int, default=256)
    ap.add_argument("--events", type=int, default=192)
    ap.add_argument("--max-shards", type=int, default=8)
    args = ap.parse_args(argv)

    _force_cpu_mesh(args.max_shards)

    import numpy as np

    from comdb2_tpu.checker import pallas_seg as PSEG
    from comdb2_tpu.checker.batch import check_batch, pack_batch
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops import synth_columnar as SC
    from comdb2_tpu.service.sharding import make_mesh
    from comdb2_tpu.txn import closure_jax as CJ
    from comdb2_tpu.utils import compile_guard, next_pow2
    from comdb2_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()
    B, EV = args.histories, args.events
    packeds = SC.register_batch_packed(7_700_000, B, EV // 2,
                                       n_procs=4, values=3)
    shard_counts = [d for d in (1, 2, 4, 8) if d <= args.max_shards]
    out = {"workload": {"histories": B, "events": EV},
           "backend": "cpu",
           "note": ("forced CPU mesh — the 'devices' share ONE CPU, "
                    "so per-shard scaling is reported as measured "
                    "per-shard device work (1/D of the batch), never "
                    "as wall clock"),
           "shards": []}
    baseline = None
    base_shard_s = None
    with compile_guard.guard() as guard:
        from comdb2_tpu.checker import linear_jax as LJ

        for D in shard_counts:
            # D=1 rides a 1-device mesh so every row's dispatch count
            # is MEASURED through the same counter (the sharded keys
            # wrapper), never a structural claim
            mesh = make_mesh(D)
            batch = pack_batch(list(packeds), cas_register())
            ns = next_pow2(batch.memo.n_states)
            nt = next_pow2(batch.memo.n_transitions)
            kw = dict(F=128, engine="keys", s_pad=8, k_pad=2,
                      n_states_pad=ns, n_transitions_pad=nt)
            info: dict = {}
            d0 = LJ.DISPATCHES + PSEG.DISPATCHES
            t0 = time.monotonic()
            st, fa, nf = check_batch(batch, mesh=mesh, info=info,
                                     **kw)
            wall = time.monotonic() - t0
            n_disp = (LJ.DISPATCHES + PSEG.DISPATCHES) - d0
            assert n_disp == 1, (D, n_disp)
            b_pad = info["batch"]["b_pad"]
            per_shard_b = b_pad // D
            # measured per-shard device work: exactly one shard's
            # slice run unsharded (same program class the shard body
            # compiles — B/D lanes)
            sub = pack_batch(list(packeds[:per_shard_b]),
                             cas_register())
            check_batch(sub, **kw)          # warm the program
            shard_s = None                  # min over reps: one CPU,
            for _ in range(3):              # neighbours add noise
                t1 = time.monotonic()
                check_batch(sub, **kw)
                dt = time.monotonic() - t1
                shard_s = dt if shard_s is None else min(shard_s, dt)
            if baseline is None:
                baseline, base_shard_s = (st, fa, nf), shard_s
            else:
                assert (st == baseline[0]).all(), f"D={D} verdicts"
                assert (fa == baseline[1]).all(), f"D={D} fail_at"
                assert (nf == baseline[2]).all(), f"D={D} counts"
            row = {
                "D": D,
                "engine": info["engine"],
                "b": B, "b_pad": b_pad, "pad": info["batch"]["pad"],
                "per_shard_b": per_shard_b,
                "dispatches": n_disp,
                "sharded_wall_s": round(wall, 3),
                "per_shard_device_run_s": round(shard_s, 3),
                "per_shard_fraction_of_d1": round(
                    shard_s / base_shard_s, 3),
            }
            out["shards"].append(row)
            print(json.dumps(row), file=sys.stderr, flush=True)

        # the sharded txn closure rides the same mesh axis: time one
        # batched closure per D and assert the single-dispatch rule
        rngadj = np.random.default_rng(3)
        adjs = np.zeros((16, 4, 64, 64), bool)
        for b in range(16):
            for _ in range(80):
                i, j = rngadj.integers(0, 64, 2)
                if i != j:
                    adjs[b, int(rngadj.integers(0, 3)), i, j] = True
        txn_rows = []
        diag0 = None
        for D in shard_counts:
            mesh = make_mesh(D) if D > 1 else None
            d0 = CJ.DISPATCHES
            t0 = time.monotonic()
            diag = CJ.closure_diag_batch(adjs, mesh=mesh)
            txn_rows.append({"D": D, "dispatches": CJ.DISPATCHES - d0,
                             "wall_s": round(time.monotonic() - t0,
                                             3)})
            assert CJ.DISPATCHES - d0 == 1, "txn closure dispatches"
            if diag0 is None:
                diag0 = diag
            else:
                assert (diag == diag0).all(), f"txn D={D} verdicts"
        out["txn_closure"] = txn_rows

    out["compile_guard"] = guard.summary()
    out["mosaic_builds"] = PSEG.MOSAIC_BUILDS
    if compile_guard.enabled() and not \
            out["compile_guard"]["compile_surface_ok"]:
        print(json.dumps(out), flush=True)
        print("compile guard: observed programs escaped the "
              "inventory", file=sys.stderr)
        return 1
    with open(args.json, "w") as fh:
        json.dump(out, fh, indent=1)
        fh.write("\n")
    print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
