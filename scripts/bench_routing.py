#!/usr/bin/env python3
"""Horizontal scale-out bench: two pmux-routed daemons vs one.

Boots ``ct_pmux``, registers TWO verifier daemons under
``sut/verifier/0`` and ``sut/verifier/1`` (``--pmux-shard``), builds
a :class:`~comdb2_tpu.service.client.RoutedClient` from discovery,
and drives the same mixed-shape workload two ways:

- **single** — every request to daemon 0 alone;
- **routed** — requests split by the client's consistent-hash ring
  (shape-class keys, so each daemon owns whole bucket classes and
  batch amortization survives routing), both daemons driven
  CONCURRENTLY.

Accounting is honest for this 1-CPU container (the bench_multichip
convention): the two daemon processes share one CPU, so wall-clock
is reported but NOT gated — the scaling claim is **dispatch-count
accounting**: each daemon owns its own device (tunnel), so fleet
capacity is bounded by the most-loaded daemon's dispatch count, and

    aggregate_speedup = single_dispatches / max(per-daemon dispatches)

is gated at ``--min-agg-speedup`` (default 1.7). Shape-class routing
is what makes this scale: payload routing would scatter every bucket
across every daemon and the per-daemon dispatch count would not drop.
The compiled-program partition is also asserted: each daemon's
program count after the routed phase stays below the single daemon's
(the fleet splits the compile surface; the shared persistent compile
cache means a re-registered daemon serves its partition warm).

Also asserted: discovery found both daemons, RoutedClient round-trips
(each daemon served routed traffic), failover (a request keyed to a
stopped daemon answers from the next ring node), clean shutdown of
both daemons and the pmux with no zombies left.

Usage: PYTHONPATH=/root/.axon_site:. python scripts/bench_routing.py
       [--requests-per-class 8] [--tunnel-ms 100] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

# the daemon-driving socket helpers are bench_service's — one copy of
# the protocol/shutdown contract for both benches
from bench_service import (connect, encode, request_one,  # noqa: E402
                           status, stop_daemon)

#: event counts per size class — chosen so every class lands in its
#: own pow2 payload-size bucket (distinct ring keys) AND its own
#: server-side shape bucket, and so the md5 ring splits them evenly
#: across the two daemons (md5 is stable: this split is deterministic
#: forever; re-tune here if the class list changes)
SIZE_CLASSES = (10, 18, 30, 60, 140, 180)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def find_ct_pmux() -> str:
    """The pmux binary: a prior native build, or a direct g++ build
    (the same line scripts/check.sh falls back to)."""
    for cand in ("native/build/ct_pmux", "native/build-asan/ct_pmux"):
        p = os.path.join(REPO, cand)
        if os.path.exists(p):
            return p
    if shutil.which("g++") is None:
        raise SystemExit("no ct_pmux build and no g++ to make one")
    out = os.path.join(tempfile.mkdtemp(prefix="ct_pmux_"), "ct_pmux")
    subprocess.run(
        ["g++", "-O1", "-Wall", "-Inative/include",
         "native/src/pmux_main.cpp", "-o", out, "-lpthread"],
        cwd=REPO, check=True)
    return out


def start_pmux(binary: str, port: int):
    proc = subprocess.Popen([binary, "-p", str(port)],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=0.2).close()
            return proc
        except OSError:
            time.sleep(0.1)
    proc.kill()
    proc.wait(timeout=30)   # no init reaper: reap before raising
    raise SystemExit("ct_pmux never came up")


def spawn_daemon(pmux_port: int, shard: int, tunnel_ms: float):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "comdb2_tpu.service", "--port", "0",
         "--backend", "cpu", "--no-prime", "--frontier", "64",
         # same formation window as bench_service: long enough that
         # a whole burst admits before any launch budget fires, so
         # launch waves are whole-bucket and dispatch counts are
         # deterministic
         "--fill-ms", "150", "--pmux", str(pmux_port),
         "--pmux-shard", str(shard),
         "--inject-dispatch-latency-ms", str(tunnel_ms)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO, env=env)
    ready = json.loads(proc.stdout.readline())
    assert ready.get("ready"), ready
    assert ready["pmux_service"] == f"sut/verifier/{shard}", ready
    return proc, ready["port"]


def make_workload(per_class: int):
    from comdb2_tpu.ops.history import history_to_edn
    from comdb2_tpu.ops.synth import register_history

    texts = []
    for ci, n_events in enumerate(SIZE_CLASSES):
        for j in range(per_class):
            h = register_history(random.Random(7000 + 37 * ci + j),
                                 n_procs=3, n_events=n_events,
                                 p_info=0.0)
            texts.append(history_to_edn(h))
    return texts


def burst(port_payloads):
    """Concurrent burst across daemons: one connection per request,
    ALL sends before any read — the two daemons' device work (and
    injected tunnel latency) overlaps for real, they are separate
    processes."""
    conns = []
    t0 = time.perf_counter()
    for port, payload in port_payloads:
        s, f = connect(port)
        s.sendall(payload)
        conns.append((s, f))
    replies = []
    for s, f in conns:
        line = f.readline()
        assert line.endswith(b"\n"), "truncated reply"
        replies.append(json.loads(line))
        s.close()
    dt = time.perf_counter() - t0
    for r in replies:
        assert r.get("ok"), r
    return dt, replies


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests-per-class", type=int, default=8)
    ap.add_argument("--tunnel-ms", type=float, default=100.0,
                    help="injected per-dispatch latency on each "
                         "daemon (the per-daemon device model; 0 = "
                         "raw CPU numbers)")
    ap.add_argument("--min-agg-speedup", type=float, default=1.7,
                    help="gate on single_dispatches / "
                         "max(per-daemon dispatches) (0 disables)")
    ap.add_argument("--quick", action="store_true",
                    help="small run, structural assertions only")
    ap.add_argument("--out",
                    default=os.path.join(REPO, "BENCH_routing.json"))
    args = ap.parse_args()
    if args.quick:
        args.requests_per_class = min(args.requests_per_class, 2)
        args.tunnel_ms = 0.0
        args.min_agg_speedup = 0.0

    from comdb2_tpu.service.client import RoutedClient

    pmux_port = free_port()
    pmux = start_pmux(find_ct_pmux(), pmux_port)
    procs = []
    try:
        d0, port0 = spawn_daemon(pmux_port, 0, args.tunnel_ms)
        procs.append((d0, port0))
        d1, port1 = spawn_daemon(pmux_port, 1, args.tunnel_ms)
        procs.append((d1, port1))

        rc = RoutedClient.discover(pmux_port=pmux_port,
                                   timeout_s=300.0)
        assert set(rc.clients) == {"sut/verifier/0",
                                   "sut/verifier/1"}, rc.clients
        ports = {"sut/verifier/0": port0, "sut/verifier/1": port1}

        texts = make_workload(args.requests_per_class)
        n = len(texts)
        plan = [rc.ring.nodes_for(RoutedClient.route_key(t))[0]
                for t in texts]
        split = {name: plan.count(name) for name in rc.clients}
        assert all(split.values()), (
            f"degenerate ring split {split} — re-tune SIZE_CLASSES")

        # the RoutedClient round-trip itself (and per-daemon serve
        # counts) — one request per size class, the same path the
        # check.sh routing stage drives
        for t in texts[::args.requests_per_class]:
            r = rc.check(t)
            assert r.get("ok"), r
        assert all(v > 0 for v in rc.served.values()), rc.served

        # warm every program class on both daemons so the timed
        # phases compare steady-state serving, not compile time
        burst([(ports[name], encode(i, t))
               for i, (name, t) in enumerate(zip(plan, texts))])
        burst([(port0, encode(i, t)) for i, t in enumerate(texts)])

        s0a, s1a = status(port0), status(port1)
        single_s, _ = burst([(port0, encode(i, t))
                             for i, t in enumerate(texts)])
        s0b = status(port0)
        routed_s, _ = burst([(ports[name], encode(i, t))
                             for i, (name, t)
                             in enumerate(zip(plan, texts))])
        s0c, s1c = status(port0), status(port1)

        single_disp = s0b["dispatches"] - s0a["dispatches"]
        routed_disp = {
            "sut/verifier/0": s0c["dispatches"] - s0b["dispatches"],
            "sut/verifier/1": s1c["dispatches"] - s1a["dispatches"],
        }
        # dispatch-count accounting (see module docstring): each
        # daemon owns its own device, so the fleet's capacity is set
        # by its most-loaded member
        agg_speedup = single_disp / max(max(routed_disp.values()), 1)
        # program-space partition: daemon 1 only ever served its ring
        # slice, so its program count must stay below daemon 0's
        # single-phase count (daemon 0 served EVERY class there) —
        # the fleet splits the compile surface, it does not replicate
        # it
        programs = {"single": s0b["programs"],
                    "routed_0": s0c["programs"],
                    "routed_1": s1c["programs"]}
        assert s1c["programs"] < s0b["programs"], (
            f"program space did not partition: {programs}")

        # failover: stop daemon 1, a request keyed to it must answer
        # from daemon 0 — either via the ring walk (a connect error
        # blacklists the corpse) or, since round 12, via the epoch
        # bump the withdrawing daemon published (the client refreshes
        # its ring BEFORE ever dialing the dead node)
        victim = next(t for t, name in zip(texts, plan)
                      if name == "sut/verifier/1")
        stop_daemon(d1, port1)
        procs.remove((d1, port1))
        r = rc.check(victim)
        assert r.get("ok"), f"failover failed: {r}"
        assert rc.failovers >= 1 or rc.refreshes >= 1, \
            (rc.failovers, rc.refreshes)
    finally:
        for proc, port in procs:
            stop_daemon(proc, port)
        try:
            request_one(pmux_port, {})  # nudge; pmux speaks lines
        except Exception:
            pass
        pmux.terminate()
        pmux.wait(timeout=30)

    out = {
        "bench": "routing", "backend": "cpu",
        "daemons": 2, "requests": n,
        "size_classes": list(SIZE_CLASSES),
        "tunnel_ms_injected": args.tunnel_ms,
        "ring_split": split,
        "single_s": round(single_s, 4),
        "routed_s": round(routed_s, 4),
        "single_req_per_s": round(n / single_s, 1),
        "routed_req_per_s": round(n / routed_s, 1),
        "single_dispatches": single_disp,
        "routed_dispatches": routed_disp,
        "aggregate_speedup_dispatch": round(agg_speedup, 2),
        "min_agg_speedup": args.min_agg_speedup,
        "programs": programs,
        "failovers": rc.failovers,
        "note": "1-CPU container: the two daemons share the host "
                "CPU, so wall clock is reported, not gated; the "
                "scaling claim is dispatch-count accounting — each "
                "daemon drives its own device/tunnel (injected "
                "latency declared above), and shape-class routing "
                "partitions the bucket space so the most-loaded "
                "daemon dispatches ~1/N of the single-daemon count",
    }
    line = json.dumps(out)
    print(line)
    with open(args.out, "w") as fh:
        fh.write(line + "\n")
    if args.min_agg_speedup and agg_speedup < args.min_agg_speedup:
        print(f"FAIL: aggregate dispatch speedup {agg_speedup:.2f} "
              f"< {args.min_agg_speedup}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
