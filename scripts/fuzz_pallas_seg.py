#!/usr/bin/env python3
"""TPU fuzz: the fused Pallas segment engine vs the XLA seg engine.

Usage: PYTHONPATH=$AXON_SITE:. python scripts/fuzz_pallas_seg.py [n]
Runs n seeded random register histories (valid + mutated-invalid,
with process retirement via :info ops) through both engines and
asserts identical verdicts, fail indices, and — for valid runs —
final frontier counts. On UNKNOWN only the verdict and fail segment
are compared: the post-abort frontier count is a truncation
diagnostic and legitimately differs between engines.
"""
from __future__ import annotations

import random
import sys
from collections import Counter


def main() -> None:
    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()

    from comdb2_tpu.checker import pallas_seg as PS
    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.models.memo import memo as make_memo
    from comdb2_tpu.models import model as M
    from comdb2_tpu.ops.packed import pack_history
    from comdb2_tpu.ops.synth import register_history, mutate

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 120
    c = Counter()
    for seed in range(500, 500 + n):
        rng = random.Random(seed)
        h = register_history(rng, n_procs=rng.randint(2, 5),
                             n_events=rng.randint(10, 60),
                             values=3, p_info=0.05)
        if rng.random() < 0.5:
            h = mutate(rng, h)
        packed = pack_history(h)
        mm = make_memo(M.cas_register(), packed)
        P = len(packed.process_table)
        segs = LJ.make_segments(packed, s_pad=64, k_pad=8)
        if P > 7 or segs.inv_proc.shape != (64, 8) or mm.n_states > 8 \
           or mm.n_transitions > 32:
            c["skip"] += 1
            continue
        succ = LJ.pad_succ(mm.succ, 8, 32)
        r = PS.check_device_pallas(succ, segs, n_states=8,
                                   n_transitions=32, P=P)
        if r is None:
            c["nofit"] += 1
            continue
        st, fa, n_f = r
        st2, fa2, n2 = LJ.check_device_seg(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
            F=128, P=P, n_states=8, n_transitions=32)
        st2, fa2, n2 = int(st2), int(fa2), int(n2)
        assert st == st2, f"seed={seed}: pallas {r} xla {(st2, fa2, n2)}"
        if st != 0:
            assert fa == fa2, f"seed={seed}: fail {fa} vs {fa2}"
        else:
            assert n_f == n2, f"seed={seed}: n {n_f} vs {n2}"
        c["ok" if st == 0 else ("inv" if st == 1 else "unk")] += 1
    print(dict(c))
    assert c["ok"] and c["inv"], "fuzz must exercise both verdicts"


if __name__ == "__main__":
    main()
