#!/usr/bin/env python3
"""TPU fuzz: the fused Pallas segment engine vs the XLA seg engine.

Usage: PYTHONPATH=$AXON_SITE:. python scripts/fuzz_pallas_seg.py \
           [n] [--out FUZZ.json]
Runs n seeded random histories PER MODEL FAMILY (valid +
mutated-invalid, with process retirement via :info ops) through both
engines and asserts identical verdicts, fail indices, and — for valid
runs — final frontier counts. On UNKNOWN only the verdict and fail
segment are compared: the post-abort frontier count is a truncation
diagnostic and legitimately differs between engines.

With ``--out`` the run writes a JSON artifact (per-family seed/verdict
counts, stream-stage coverage, overall pass/fail) so fuzz coverage is
recorded instead of living in a terminal scrollback (round-1 Weak #5).
"""
from __future__ import annotations

import json
import random
import sys
from collections import Counter


def _register_case(rng):
    from comdb2_tpu.models import model as M
    from comdb2_tpu.ops.synth import register_history, mutate

    h = register_history(rng, n_procs=rng.randint(2, 5),
                         n_events=rng.randint(10, 60),
                         values=3, p_info=0.05)
    if rng.random() < 0.5:
        h = mutate(rng, h)
    return M.cas_register(), h


def _cross_model_cases():
    """(name, case_fn) pairs incl. the cross-model generators the CPU
    suite uses (tests/test_engine_cross_model.py), with occasional
    corruption to produce invalid histories."""
    import sys as _sys

    _sys.path.insert(0, "tests")
    import test_engine_cross_model as X

    def corrupt(rng, h):
        """Model-agnostic corruption (same scheme as the CPU
        cross-model test): flip a fail->ok, else falsify an ok value."""
        h = list(h)
        fails = [i for i, op in enumerate(h) if op.type == "fail"]
        oks = [i for i, op in enumerate(h)
               if op.type == "ok" and op.value is not None]
        if fails:
            i = rng.choice(fails)
            h[i] = h[i].with_(type="ok")
        elif oks:
            i = rng.choice(oks)
            v = h[i].value
            if isinstance(v, tuple) and v and isinstance(v[0], tuple):
                mf, k, mv = v[0]
                h[i] = h[i].with_(value=((mf, k, (mv or 0) + 7),)
                                  + v[1:])
            else:
                h[i] = h[i].with_(value=999)
        return h

    def mk(mk_model, mk_hist):
        def case(rng):
            h = mk_hist(rng, rng.randint(2, 4), rng.randint(10, 50))
            if rng.random() < 0.4:
                h = corrupt(rng, h)
            return mk_model(), h
        return case

    def bounded_queue_case(rng):
        """Queue histories whose memoized state space FITS the fused
        kernel's 4096-entry table: the cross-model generator enqueues
        globally unique values, whose multiset state space blows past
        every bucket (round-2 Weak #1: 10/120 queue seeds device-
        checked). The memo closure applies each distinct transition up
        to the depth bound regardless of how often the history invokes
        it, so the state count is ~multisets over the alphabet with
        total <= invocations — a 2-value alphabet with 10-16 events
        keeps states <= 64 (measured: 60/60 fit) while still
        exercising multiset semantics (duplicate values in flight)."""
        from comdb2_tpu.models import model as M

        h = _bounded_queue_history(rng, rng.randint(2, 4),
                                   rng.randint(10, 16))
        if rng.random() < 0.4:
            h = corrupt(rng, h)
        return M.unordered_queue(), h

    return ([("register", _register_case)] +
            [(name,
              bounded_queue_case if name == "unordered-queue"
              else mk(mkm, mkh))
             for name, mkm, mkh in X.CASES])


def _bounded_queue_history(rng, n_procs, n_events):
    """Valid unordered-queue execution over alphabet {0,1}."""
    import collections

    from comdb2_tpu.ops.op import Op

    def invoke(p, f, v):
        return Op(process=p, type="invoke", f=f, value=v, time=0)

    def ok(p, f, v):
        return Op(process=p, type="ok", f=f, value=v, time=0)

    def fail(p, f, v):
        return Op(process=p, type="fail", f=f, value=v, time=0)

    q = collections.deque()
    procs = {i: None for i in range(n_procs)}
    h = []
    while len(h) < n_events:
        p = rng.randrange(n_procs)
        if procs[p] is None:
            if rng.random() < 0.5:
                v = rng.randrange(2)
                procs[p] = ("enqueue", v)
                h.append(invoke(p, "enqueue", v))
            else:
                procs[p] = ("dequeue", None)
                h.append(invoke(p, "dequeue", None))
        else:
            f, v = procs[p]
            procs[p] = None
            if f == "enqueue":
                q.append(v)
                h.append(ok(p, f, v))
            elif q:
                got = q.popleft() if rng.random() < 0.5 else q.pop()
                h.append(ok(p, f, got))
            else:
                h.append(fail(p, f, None))
    return h


def main() -> None:
    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()

    from comdb2_tpu.checker import pallas_seg as PS
    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.models.memo import MemoOverflow, memo as make_memo
    from comdb2_tpu.ops.packed import pack_history

    args = list(sys.argv[1:])
    out_path = None
    if "--out" in args:
        i = args.index("--out")
        if i + 1 >= len(args):
            sys.exit("usage: fuzz_pallas_seg.py [n] [--out FILE]")
        out_path = args[i + 1]
        del args[i:i + 2]
    n = int(args[0]) if args else 120
    c = Counter()
    cases = _cross_model_cases()
    names = [nm for nm, _ in cases]
    # accumulate per-(bucket, P) groups for the stream-engine stage:
    # the streamed kernel must reproduce the single-history verdicts
    stream_groups: dict = {}
    for name, case in cases:
        for seed in range(500, 500 + n):
            rng = random.Random(seed)
            model, h = case(rng)
            packed = pack_history(h)
            try:
                mm = make_memo(model, packed)
            except MemoOverflow:
                c[name, "memo-skip"] += 1
                continue
            P = len(packed.process_table)
            segs = LJ.make_segments(packed, s_pad=64, k_pad=8)
            # shape buckets (few compiled specs); the top tier uses
            # the 64-row (8192-entry) table added in round 4 to close
            # the round-3 fuzz skips (8 queue + 2 register seeds)
            if mm.n_states <= 8 and mm.n_transitions <= 32:
                bucket = (8, 32)
            elif mm.n_states <= 16 and mm.n_transitions <= 64:
                bucket = (16, 64)
            elif mm.n_states <= 64 and mm.n_transitions <= 64:
                bucket = (64, 64)
            elif mm.n_states <= 128 and mm.n_transitions <= 64:
                bucket = (128, 64)
            elif mm.n_states <= 256 and mm.n_transitions <= 8:
                # tall-narrow tier: queue memos grow states (multisets)
                # far faster than transitions (tiny alphabet); a square
                # bucket would pad past the table budget
                bucket = (256, 8)
            else:
                c[name, "skip"] += 1
                continue
            # P <= 15 rides the (16,128)/3-word tier (round-3
            # VERDICT #2); beyond that the XLA engines own the shape
            if P > 15 or segs.inv_proc.shape != (64, 8):
                c[name, "skip"] += 1
                continue
            succ = LJ.pad_succ(mm.succ, *bucket)
            r = PS.check_device_pallas(succ, segs, n_states=bucket[0],
                                       n_transitions=bucket[1], P=P)
            if r is None:
                c[name, "nofit"] += 1
                continue
            st, fa, n_f = r
            st2, fa2, n2 = LJ.check_device_seg(
                succ, segs.inv_proc, segs.inv_tr, segs.ok_proc,
                segs.depth, F=128, P=P, n_states=bucket[0],
                n_transitions=bucket[1])
            st2, fa2, n2 = int(st2), int(fa2), int(n2)
            assert st == st2, \
                f"{name} seed={seed}: pallas {r} xla {(st2, fa2, n2)}"
            if st != 0:
                assert fa == fa2, f"{name} seed={seed}: {fa} vs {fa2}"
            else:
                assert n_f == n2, f"{name} seed={seed}: {n_f} vs {n2}"
            c[name, "ok" if st == 0
              else ("inv" if st == 1 else "unk")] += 1
            # renamed-slots stage: production always routes through
            # slot renaming (round 5) — the kernel on REMAPPED
            # segments must reproduce the raw-segment verdict exactly
            # (renaming is a pure relabeling). The spec choice MUST
            # mirror the driver exactly (linear._analyze_device:
            # even-bucket only while the (8,128) tier serves it, raw
            # count in the (16,128) tier) so the fuzz covers the
            # production configs, odd P included.
            segs_r, p_eff = LJ.remap_slots(segs)
            p_eff = max(p_eff, 1)
            P2 = max(p_eff + (p_eff & 1), 2)
            P_r = P2 if P2 <= PS.ROWS - 1 else p_eff
            if P_r <= 2 * PS.ROWS - 1:
                rr = PS.check_device_pallas(
                    succ, segs_r, n_states=bucket[0],
                    n_transitions=bucket[1], P=P_r)
                if rr is not None:
                    assert rr[0] == st, \
                        f"{name} seed={seed} renamed: {rr} vs {r}"
                    if st != 0:
                        # INVALID *and* UNKNOWN compare fail segments
                        # (the script's contract): a renaming bug that
                        # moves the overflow point must not hide
                        # behind a matching unk verdict
                        assert rr[1] == fa, \
                            f"{name} seed={seed} renamed fail index"
                    else:
                        assert rr[2] == n_f, \
                            f"{name} seed={seed} renamed count"
                    c[name, "renamed"] += 1
            if st == 2:
                # re-check UNKNOWNs through the XLA ladder at a wider
                # frontier: a kernel bug masquerading as an F=128
                # overflow must not hide behind the unk verdict
                # (round-2 Weak #6). Definitive resolution recorded;
                # a still-unk at F=1024 would be unexplained.
                st3, _, _ = LJ.check_device_seg(
                    succ, segs.inv_proc, segs.inv_tr, segs.ok_proc,
                    segs.depth, F=1024, P=P, n_states=bucket[0],
                    n_transitions=bucket[1])
                st3 = int(st3)
                c[name, {0: "unk-resolved-valid",
                         1: "unk-resolved-invalid",
                         2: "unk-unexplained"}[st3]] += 1
                assert st3 != 2, \
                    f"{name} seed={seed}: unk persists at F=1024"
            stream_groups.setdefault((bucket, P), []).append(
                (succ, segs, r))
        print(name, {k[1]: v for k, v in c.items() if k[0] == name},
              flush=True)
    assert any(c[nm, "ok"] for nm in names)
    assert any(c[nm, "inv"] for nm in names)
    # queue-family coverage floor (round-2 Weak #1: 10/120): the
    # bounded-alphabet generator must put the vast majority of queue
    # seeds THROUGH the device kernel instead of skipping on shape
    q_checked = sum(c["unordered-queue", k]
                    for k in ("ok", "inv", "unk"))
    assert q_checked >= (2 * n) // 3, \
        f"unordered-queue device coverage {q_checked}/{n}"

    # --- stream stage: batched verdicts must match single-history ----
    n_streamed = 0
    for (bucket, P), group in stream_groups.items():
        # entries in a group share the bucketed succ shape, but the
        # TABLE CONTENTS differ per history's model/memo — a stream
        # shares one table, so only group histories with identical
        # tables
        by_table: dict = {}
        for succ_g, segs, r in group:
            by_table.setdefault(succ_g.tobytes(), []).append(
                (succ_g, segs, r))
        for sub in by_table.values():
            if len(sub) < 2:
                continue
            succ_g = sub[0][0]
            segs_list = [s for _, s, _ in sub]
            rs = PS.check_device_pallas_stream(
                succ_g, segs_list, n_states=bucket[0],
                n_transitions=bucket[1], P=P)
            assert rs is not None
            for b, (_, segs, want) in enumerate(sub):
                st, fa, n_f = rs[b]
                assert st == want[0], \
                    f"stream b={b}: {rs[b]} vs single {want}"
                if st == 1:
                    assert fa == want[1], f"stream fail {fa}!={want[1]}"
                elif st == 0:
                    assert n_f == want[2], f"stream n {n_f}!={want[2]}"
                n_streamed += 1
    print("stream stage:", n_streamed, "histories cross-checked",
          flush=True)
    # the coverage floor scales with the requested seed count (small
    # runs legitimately form few shared-table groups)
    assert n_streamed > n // 3
    # renamed-slots coverage floor, PER FAMILY: a remap/spec change
    # that silently drops one family out of the stage must fail the
    # fuzz, not emit a PASS artifact advertising coverage it no longer
    # has (the old global floor let the register family mask a queue
    # regression). Remapping only lowers P_eff, so nearly every
    # device-checked seed stays tier-eligible after renaming; the only
    # legitimate losses are spec_for rejecting the driver-mirrored
    # even-rounded P — bounded well under a third of any family.
    renamed_by_family = {}
    for nm in names:
        fam_renamed = c[nm, "renamed"]
        fam_device = sum(c[nm, k] for k in ("ok", "inv", "unk"))
        renamed_by_family[nm] = {"device_checked": fam_device,
                                 "renamed": fam_renamed}
        assert fam_renamed >= (2 * fam_device) // 3, \
            (f"{nm}: renamed-slots coverage {fam_renamed}/{fam_device}"
             " — remapped seeds fell out of the kernel tier")
    n_renamed = sum(c[nm, "renamed"] for nm in names)

    if out_path:
        import jax

        families = {}
        for nm in names:
            fam = {k[1]: v for k, v in c.items() if k[0] == nm}
            fam["seeds"] = n
            families[nm] = fam
        artifact = {
            "seeds_per_family": n,
            "families": families,
            "total_cross_checked": int(sum(
                c[nm, k] for nm in names
                for k in ("ok", "inv", "unk"))),
            "renamed_slots_cross_checked": int(n_renamed),
            # per-family renamed coverage so a drop is visible in
            # review, not just a global total (ADVICE round 5)
            "renamed_slots_by_family": renamed_by_family,
            "stream_histories_cross_checked": n_streamed,
            "engines": ["pallas-fused", "xla-seg",
                        "pallas-fused-stream",
                        "pallas-fused-renamed-slots"],
            "backend": jax.default_backend(),
            "verdict": "PASS",   # any mismatch asserts before this
        }
        with open(out_path, "w") as fh:
            json.dump(artifact, fh, indent=1)
        print("artifact written:", out_path, flush=True)


if __name__ == "__main__":
    main()
