#!/usr/bin/env bash
# Repo smoke check: the static invariant checker, a sanitizer-wired
# native configure/build, native static analysis (clang-tidy or GCC
# -fanalyzer), and the ct_pmux/txn/shrink/service smokes
# (docs/static_analysis.md). Exits non-zero on any violation.
#
# --json: one machine-readable line per stage on stdout
# ({"stage": ..., "ok": true|false, "secs": N}) so automation can gate
# per stage; human banners are suppressed.
set -euo pipefail

cd "$(dirname "$0")/.."

JSON_MODE=0
if [ "${1:-}" = "--json" ]; then
    JSON_MODE=1
    shift
fi

# APPEND to PYTHONPATH — overriding it drops the axon plugin (CLAUDE.md)
export PYTHONPATH="${PYTHONPATH:+$PYTHONPATH:}$PWD"

CURRENT_STAGE=""
STAGE_START=$SECONDS
CLEANUP_PIDS=""

json_line() {
    printf '{"stage": "%s", "ok": %s, "secs": %s}\n' "$1" "$2" "$3"
}

stage_end_ok() {
    if [ -n "$CURRENT_STAGE" ] && [ "$JSON_MODE" = 1 ]; then
        json_line "$CURRENT_STAGE" true $((SECONDS - STAGE_START))
    fi
    CURRENT_STAGE=""
}

stage() {            # stage <id> <human banner...>
    stage_end_ok
    CURRENT_STAGE="$1"
    STAGE_START=$SECONDS
    shift
    if [ "$JSON_MODE" = 0 ]; then
        echo "== $* =="
    fi
}

on_exit() {
    rc=$?
    for pid in $CLEANUP_PIDS; do
        kill "$pid" 2>/dev/null || true
    done
    if [ "$rc" -ne 0 ] && [ -n "$CURRENT_STAGE" ] \
            && [ "$JSON_MODE" = 1 ]; then
        json_line "$CURRENT_STAGE" false $((SECONDS - STAGE_START))
    fi
}
trap on_exit EXIT

# In JSON mode stage output moves to stderr (the JSON lines ARE the
# stdout contract) — findings and diagnostics stay visible either way.
run() {
    if [ "$JSON_MODE" = 1 ]; then
        "$@" 1>&2
    else
        "$@"
    fi
}

zombie_count() {
    ps -eo stat= | grep -c '^Z' || true
}

# The zombie accounting is system-wide, so it can TRANSIENTLY exceed
# the stage baseline for reasons outside the process tree under test —
# LeakSanitizer's exit-time tracer briefly shows as a Z child of a
# dying ASan ct_pmux until init lazily reaps the orphan. A leak is a
# count that STAYS elevated: give the table a settle window before
# declaring one. Prints the settled count; exits non-zero if still
# above baseline after ~5s.
zombies_settled() {    # zombies_settled BASELINE
    local base=$1 now=0
    for _ in $(seq 50); do
        now=$(zombie_count)
        if [ "$now" -le "$base" ]; then
            echo "$now"
            return 0
        fi
        sleep 0.1
    done
    echo "$now"
    return 1
}

stage analysis "static invariant checker"
run python -m comdb2_tpu.analysis

stage pack-parity "pack parity smoke (legacy vs columnar ingest)"
# one fixture per corpus family; any segment-stream diff fails CI
# before the slow tier ever runs
run env JAX_PLATFORMS=cpu python scripts/pack_parity_smoke.py

stage asan-build "native configure/build with ASan"
if command -v cmake >/dev/null; then
    cmake -DCT_SANITIZE=address -S native -B native/build-asan \
        >/dev/null
    cmake --build native/build-asan -j"$(nproc)" >/dev/null
else
    # containers without cmake: same flags CT_SANITIZE=address wires
    [ "$JSON_MODE" = 1 ] || \
        echo "cmake not found — direct g++ ASan build of ct_pmux"
    mkdir -p native/build-asan
    g++ -fsanitize=address -fno-omit-frame-pointer -g -Wall -Wextra \
        -Inative/include native/src/pmux_main.cpp \
        -o native/build-asan/ct_pmux -lpthread
fi

stage native-static-analysis \
    "native static analysis (clang-tidy or GCC -fanalyzer)"
# clang-tidy findings fail the build itself (warnings-as-errors);
# -fanalyzer emits warnings, so the build log is grepped — any
# -Wanalyzer finding in ct_pmux/sut_node/client sources fails here
TIDY_LOG=$(mktemp)
if command -v cmake >/dev/null; then
    cmake -DCT_STATIC_ANALYZER=ON -S native -B native/build-tidy \
        >/dev/null
    if ! cmake --build native/build-tidy -j"$(nproc)" \
            >"$TIDY_LOG" 2>&1; then
        tail -40 "$TIDY_LOG" >&2
        echo "native static analysis build failed" >&2
        rm -f "$TIDY_LOG"
        exit 1
    fi
else
    : >"$TIDY_LOG"
    for src in native/src/*.cpp; do
        if ! g++ -fanalyzer -Wall -Wextra -Inative/include -c "$src" \
                -o /tmp/ct_analyze.o >>"$TIDY_LOG" 2>&1; then
            tail -40 "$TIDY_LOG" >&2
            echo "native static analysis: $src failed to compile" >&2
            rm -f "$TIDY_LOG" /tmp/ct_analyze.o
            exit 1
        fi
    done
    rm -f /tmp/ct_analyze.o
fi
if grep -E '\-Wanalyzer|warning:.*\[(bugprone|clang-analyzer|performance)' \
        "$TIDY_LOG" >&2; then
    echo "native static analysis found issues (log above)" >&2
    rm -f "$TIDY_LOG"
    exit 1
fi
rm -f "$TIDY_LOG"

stage pmux-smoke "ct_pmux start/exit under ASan"
PMUX=native/build-asan/ct_pmux
PORT=${CT_CHECK_PMUX_PORT:-15105}
# halt_on_error so a shutdown race fails the script, not just logs
ASAN_OPTIONS=halt_on_error=1 "$PMUX" -p "$PORT" &
PMUX_PID=$!
CLEANUP_PIDS="$PMUX_PID"
for _ in $(seq 50); do
    if bash -c "true >/dev/tcp/127.0.0.1/$PORT" 2>/dev/null; then
        break
    fi
    sleep 0.1
done
exec 3<>"/dev/tcp/127.0.0.1/$PORT"
printf 'hello\nexit\n' >&3
cat <&3 >/dev/null || true
exec 3<&- 3>&-
wait "$PMUX_PID"   # non-zero (ASan abort) fails the check
CLEANUP_PIDS=""

stage tsan "ct_pmux start/serve/shutdown under TSan"
# the other half of CT_SANITIZE: the registry serves every connection
# on its own thread, so the races ASan can't see (registry map vs
# serve threads, shutdown drain vs in-flight handlers) only surface
# under TSan — and only with CONCURRENT clients, so the smoke drives
# eight at once before the shutdown
if command -v cmake >/dev/null; then
    cmake -DCT_SANITIZE=thread -S native -B native/build-tsan \
        >/dev/null
    cmake --build native/build-tsan -j"$(nproc)" >/dev/null
else
    [ "$JSON_MODE" = 1 ] || \
        echo "cmake not found — direct g++ TSan build of ct_pmux"
    mkdir -p native/build-tsan
    g++ -fsanitize=thread -fno-omit-frame-pointer -g -Wall -Wextra \
        -Inative/include native/src/pmux_main.cpp \
        -o native/build-tsan/ct_pmux -lpthread
fi
TSAN_PMUX=native/build-tsan/ct_pmux
TSAN_PORT=${CT_CHECK_TSAN_PMUX_PORT:-15107}
TSAN_STATE=$(mktemp)
# halt_on_error: a reported race aborts non-zero and fails the wait
TSAN_OPTIONS=halt_on_error=1 "$TSAN_PMUX" -p "$TSAN_PORT" \
    -f "$TSAN_STATE" &
TSAN_PID=$!
CLEANUP_PIDS="$TSAN_PID"
for _ in $(seq 50); do
    if bash -c "true >/dev/tcp/127.0.0.1/$TSAN_PORT" 2>/dev/null; then
        break
    fi
    sleep 0.1
done
TSAN_CLIENTS=""
for i in $(seq 8); do
    (
        exec 3<>"/dev/tcp/127.0.0.1/$TSAN_PORT"
        printf "reg sut/tsan%s\nget sut/tsan%s\ndel sut/tsan%s\n" \
            "$i" "$i" "$i" >&3
        head -3 <&3 >/dev/null
        exec 3<&- 3>&-
    ) &
    TSAN_CLIENTS="$TSAN_CLIENTS $!"
done
for pid in $TSAN_CLIENTS; do
    wait "$pid"
done
exec 3<>"/dev/tcp/127.0.0.1/$TSAN_PORT"
printf 'exit\n' >&3
cat <&3 >/dev/null || true
exec 3<&- 3>&-
wait "$TSAN_PID"   # non-zero (TSan race report) fails the check
CLEANUP_PIDS=""
rm -f "$TSAN_STATE"

stage txn-smoke "txn serializability checker smoke (host engine)"
# the seeded G2 write-skew fixture MUST be caught (exit 1 = invalid);
# a miss (exit 0) or a give-up (exit 2) fails the repo check — and
# the clean twin must pass, so the detector can't cheat by flagging
# everything
set +e
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --txn --backend host \
    tests/fixtures/txn/g2_item.edn >/dev/null
RC_BAD=$?
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --txn --backend host \
    tests/fixtures/txn/clean.edn >/dev/null
RC_CLEAN=$?
set -e
if [ "$RC_BAD" -ne 1 ]; then
    echo "txn checker MISSED the seeded G2-item cycle (rc=$RC_BAD)" >&2
    exit 1
fi
if [ "$RC_CLEAN" -ne 0 ]; then
    echo "txn checker flagged the clean fixture (rc=$RC_CLEAN)" >&2
    exit 1
fi

stage shrink-smoke "shrink smoke (seeded stale-read fixture)"
# the fixture plants a single stale read into a write-only history
# (known minimum: ONE read pair); the minimizer must reach it and the
# minimal history must still be INVALID on offline re-check
SHRINK_STORE=$(mktemp -d)
set +e
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --shrink \
    --store "$SHRINK_STORE" tests/fixtures/shrink/stale_read.edn \
    >/dev/null
RC_SHRINK=$?
set -e
if [ "$RC_SHRINK" -ne 1 ]; then
    echo "shrink seed fixture not INVALID (rc=$RC_SHRINK)" >&2
    exit 1
fi
MINIMAL=$(ls "$SHRINK_STORE"/shrink/*/minimal.edn 2>/dev/null | head -1)
if [ -z "$MINIMAL" ]; then
    echo "shrink wrote no minimal.edn" >&2
    exit 1
fi
OPS=$(grep -c ':process' "$MINIMAL")
if [ "$OPS" -gt 2 ]; then
    echo "shrink left $OPS ops (known minimum is 2)" >&2
    exit 1
fi
set +e
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --backend host \
    "$MINIMAL" >/dev/null
RC_MIN=$?
set -e
if [ "$RC_MIN" -ne 1 ]; then
    echo "minimal.edn re-check rc=$RC_MIN (must still be INVALID)" >&2
    exit 1
fi
rm -rf "$SHRINK_STORE"

stage wl "workload-family checkers smoke (bank/sets/dirty)"
# ISSUE-20 gate, three layers: (1) the checked-in EDN fixtures
# through the filetest CLI — every seeded violation must be caught
# (exit 1) and every clean twin must pass (exit 0), so the detector
# can't cheat in either direction; (2) bench_wl --quick, which
# hard-asserts device/oracle verdict parity per (family, B) cell and
# one dispatch per pow2 bucket before timing, and closes the compile
# guard over every wl program; (3) a daemon kind:"wl" round trip.
WL_FIX=tests/fixtures/wl
WL_BANK_ARGS="--checker bank --wl-n 8 --wl-total 160"
set +e
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest $WL_BANK_ARGS \
    "$WL_FIX/bank_valid.edn" >/dev/null
RC_BV=$?
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest $WL_BANK_ARGS \
    "$WL_FIX/bank_wrong_total.edn" >/dev/null
RC_BB=$?
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --checker sets \
    "$WL_FIX/sets_valid.edn" >/dev/null
RC_SV=$?
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --checker sets \
    "$WL_FIX/sets_lost.edn" >/dev/null
RC_SB=$?
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --checker dirty \
    "$WL_FIX/dirty_valid.edn" >/dev/null
RC_DV=$?
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --checker dirty \
    "$WL_FIX/dirty_dirty.edn" >/dev/null
RC_DB=$?
set -e
if [ "$RC_BV$RC_SV$RC_DV" != "000" ]; then
    echo "wl clean fixture flagged (bank=$RC_BV sets=$RC_SV" \
         "dirty=$RC_DV)" >&2
    exit 1
fi
if [ "$RC_BB$RC_SB$RC_DB" != "111" ]; then
    echo "wl seeded violation MISSED (bank=$RC_BB sets=$RC_SB" \
         "dirty=$RC_DB)" >&2
    exit 1
fi
run env JAX_PLATFORMS=cpu python scripts/bench_wl.py --quick \
    --json /tmp/bench_wl_smoke.json

# daemon round trip: kind:"wl" rides the same continuous batching
ZOMBIES_BEFORE=$(zombie_count)
WL_LOG=$(mktemp)
JAX_PLATFORMS=cpu python -m comdb2_tpu.service --port 0 \
    --backend cpu --no-prime --frontier 64 >"$WL_LOG" 2>&1 &
WL_PID=$!
CLEANUP_PIDS="$WL_PID"
for _ in $(seq 200); do
    grep -q '"ready"' "$WL_LOG" 2>/dev/null && break
    sleep 0.1
done
grep -q '"ready"' "$WL_LOG" || { echo "wl daemon never ready" >&2; \
    cat "$WL_LOG" >&2; exit 1; }
WL_LOG="$WL_LOG" python - <<'EOF'
import json, os
from comdb2_tpu.checker import wl as W
from comdb2_tpu.ops.history import history_to_edn
from comdb2_tpu.service.client import ServiceClient

port = None
with open(os.environ["WL_LOG"]) as fh:
    for line in fh:
        if '"ready"' in line:
            port = json.loads(line)["port"]
            break
assert port is not None, "no ready line in daemon log"
c = ServiceClient("127.0.0.1", port, timeout_s=300.0, retries=5,
                  backoff_s=0.5)
good, model = W.bank_batch(61, 1)
bad, _ = W.bank_batch(61, 1, violation="total")
r = c.check_wl(history_to_edn(list(good[0])), "bank", wl=model)
assert r["ok"] and r["valid"] is True, r
assert r["kind"] == "wl" and r["family"] == "bank", r
r = c.check_wl(history_to_edn(list(bad[0])), "bank", wl=model)
assert r["ok"] and r["valid"] is False and r["bad-reads"], r
assert r["engine"] == "wl-device", r
assert c.shutdown()
EOF
wait "$WL_PID"
CLEANUP_PIDS=""
rm -f "$WL_LOG"
if pgrep -f "comdb2_tpu\.service" >/dev/null 2>&1; then
    echo "wl daemon left a process behind" >&2
    exit 1
fi
if ! ZOMBIES_AFTER=$(zombies_settled "$ZOMBIES_BEFORE"); then
    echo "wl daemon left a zombie" \
         "($ZOMBIES_BEFORE -> $ZOMBIES_AFTER)" >&2
    exit 1
fi

stage mxu-smoke "MXU frontier engine smoke (wide-P valid + violation)"
# the round-10 engine end to end through the driver ladder: a
# genuinely concurrent P=16 bounded-in-flight history must come back
# VALID and its seeded-violation twin INVALID, BOTH attributed to the
# mxu-frontier engine (wide P is exactly the shape every other engine
# either rejects or answers UNKNOWN on)
run env JAX_PLATFORMS=cpu python - <<'EOF'
import jax

jax.config.update("jax_platforms", "cpu")
from comdb2_tpu.checker import analysis
from comdb2_tpu.models.model import cas_register
from comdb2_tpu.ops import synth_columnar as SC

for violation, want in ((False, True), (True, False)):
    h = SC.wide_register_batch_packed(
        101, 1, n_waves=2, n_chain=13, n_free=3, values=16,
        violation=violation)[0]
    a = analysis(cas_register(), h, backend="device",
                 host_threshold=1)
    assert a.valid is want, (violation, a.valid, a.info)
    assert a.info.get("engine") == "mxu-frontier", a.info
print("mxu smoke: wide-P valid VALID, seeded violation INVALID, "
      "engine=mxu-frontier")
EOF

stage multichip "multichip dryrun (8-device CPU mesh, interpret kernel)"
# the full sharded checking step on the forced 8-device CPU mesh:
# shard_map stream path (fused kernel in interpret mode), kernel/XLA
# bit-parity, escalation on one shard, in-place ladder, wide-P — the
# same gate MULTICHIP_r0N.json records (runs in a subprocess so the
# corrected env lands before any jax import)
run env JAX_PLATFORMS=cpu python -c \
    "import __graft_entry__ as g; g.dryrun_multichip(8)"

stage service-smoke "verifier service smoke (CPU backend)"
# zombie baseline BEFORE the daemon runs: the post-shutdown check
# below must catch NEW zombies (a reaped child can't show Z, so the
# meaningful assertion is "no more Z states than before, and no
# surviving service process")
ZOMBIES_BEFORE=$(zombie_count)
SVC_LOG=$(mktemp)
JAX_PLATFORMS=cpu python -m comdb2_tpu.service --port 0 \
    --backend cpu --no-prime --frontier 64 >"$SVC_LOG" 2>&1 &
SVC_PID=$!
CLEANUP_PIDS="$SVC_PID"
for _ in $(seq 200); do     # the ready line carries the chosen port
    grep -q '"ready"' "$SVC_LOG" 2>/dev/null && break
    sleep 0.1
done
grep -q '"ready"' "$SVC_LOG" || { echo "daemon never became ready" >&2; \
    cat "$SVC_LOG" >&2; exit 1; }
SVC_LOG="$SVC_LOG" python - <<'EOF'
import json, os
from comdb2_tpu.ops import op as O
from comdb2_tpu.service.client import ServiceClient

# the log merges stdout+stderr, and jax/absl init noise may precede
# the ready line — scan for it instead of assuming line 1
port = None
with open(os.environ["SVC_LOG"]) as fh:
    for line in fh:
        if '"ready"' in line:
            port = json.loads(line)["port"]
            break
assert port is not None, "no ready line in daemon log"
c = ServiceClient("127.0.0.1", port, timeout_s=300.0, retries=5,
                  backoff_s=0.5)
h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
     O.invoke(1, "read", None), O.Op(1, "ok", "read", 1)]
r = c.check(h)
assert r.get("ok") and r.get("valid") is True, r
st = c.status()["status"]
assert st["completed"] >= 1 and st["dispatches"] >= 1, st
assert c.shutdown()
EOF
wait "$SVC_PID"            # clean exit 0, and the wait reaps it
CLEANUP_PIDS=""
# the daemon itself is reaped by the wait above — what must NOT
# remain is any surviving service process or a NEW zombie it left
# behind (ps -o stat= per CLAUDE.md: pkill'd daemons linger as Z)
if pgrep -f "comdb2_tpu\.service" >/dev/null 2>&1; then
    echo "verifier daemon left a process behind" >&2
    exit 1
fi
if ! ZOMBIES_AFTER=$(zombies_settled "$ZOMBIES_BEFORE"); then
    echo "verifier daemon left a zombie" \
         "($ZOMBIES_BEFORE -> $ZOMBIES_AFTER)" >&2
    exit 1
fi

stage stream "streaming verification sessions smoke (kind:\"stream\")"
# the live-history path end to end (docs/streaming.md): open a
# session, append a clean delta (valid-so-far), append a violating
# delta (INVALID latches — later appends answer immediately), two
# concurrent sessions sharing ONE megabatched dispatch, close,
# clean shutdown, no zombies. --fill-ms 50 widens the coalescing
# window so the concurrent appends deterministically share a beat
ZOMBIES_BEFORE=$(zombie_count)
STRM_LOG=$(mktemp)
JAX_PLATFORMS=cpu python -m comdb2_tpu.service --port 0 \
    --backend cpu --no-prime --frontier 256 \
    --max-sessions 4 --fill-ms 50 >"$STRM_LOG" 2>&1 &
STRM_PID=$!
CLEANUP_PIDS="$STRM_PID"
for _ in $(seq 200); do
    grep -q '"ready"' "$STRM_LOG" 2>/dev/null && break
    sleep 0.1
done
grep -q '"ready"' "$STRM_LOG" || { echo "stream daemon never became ready" >&2; \
    cat "$STRM_LOG" >&2; exit 1; }
STRM_LOG="$STRM_LOG" python - <<'EOF'
import json, os
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.history import history_to_edn
from comdb2_tpu.service.client import ServiceClient

port = None
with open(os.environ["STRM_LOG"]) as fh:
    for line in fh:
        if '"ready"' in line:
            port = json.loads(line)["port"]
            break
assert port is not None, "no ready line in daemon log"
c = ServiceClient("127.0.0.1", port, timeout_s=300.0, retries=5,
                  backoff_s=0.5)
r = c.stream_open()
assert r.get("ok") and r.get("session"), r
sid = r["session"]
clean = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(1, "read", None), O.Op(1, "ok", "read", 1)]
r = c.stream_append(sid, history_to_edn(clean))
assert r.get("ok") and r.get("valid") is True, r
assert r.get("checked_through") == 4, r
bad = [O.invoke(1, "read", None), O.Op(1, "ok", "read", 9)]
r = c.stream_append(sid, history_to_edn(bad))
assert r.get("ok") and r.get("valid") is False, r
# the latch: a third append answers immediately, no device work
r = c.stream_append(sid, history_to_edn(clean))
assert r.get("ok") and r.get("valid") is False and r.get("latched"), r
r = c.stream_close(sid)
assert r.get("ok") and r.get("valid") is False, r
# megabatched advance (docs/streaming.md "Megabatched advance"): two
# sessions appending in one beat share ONE launched program — the
# barrier puts both requests inside the daemon's coalescing window
import threading
ca = ServiceClient("127.0.0.1", port, timeout_s=300.0, retries=5,
                   backoff_s=0.5)
cb = ServiceClient("127.0.0.1", port, timeout_s=300.0, retries=5,
                   backoff_s=0.5)
sa = ca.stream_open()["session"]
sb = cb.stream_open()["session"]
fused = False
for attempt in range(3):
    mb0 = c.status()["status"]["stream_megabatches"]
    delta = [O.invoke(0, "write", attempt), O.ok(0, "write", attempt),
             O.invoke(1, "read", None), O.Op(1, "ok", "read", attempt)]
    bar = threading.Barrier(2)
    res = {}
    def go(cli, sid, key):
        bar.wait()
        res[key] = cli.stream_append(sid, history_to_edn(delta))
    ts = [threading.Thread(target=go, args=(ca, sa, "a")),
          threading.Thread(target=go, args=(cb, sb, "b"))]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert res["a"].get("valid") is True, res
    assert res["b"].get("valid") is True, res
    if c.status()["status"]["stream_megabatches"] > mb0:
        fused = True
        break
assert fused, "concurrent same-class appends never shared a dispatch"
ca.stream_close(sa); cb.stream_close(sb)
ca.close(); cb.close()
st = c.status()["status"]
assert st["stream_opens"] >= 3 and st["stream_appends"] >= 5, st
assert st["stream"]["sessions"] == 0, st
m = c.metrics()
assert "stream_sessions_active" in m["prometheus"]
assert "sessions_per_dispatch" in m["prometheus"]
assert c.shutdown()
EOF
wait "$STRM_PID"
CLEANUP_PIDS=""
if pgrep -f "comdb2_tpu\.service" >/dev/null 2>&1; then
    echo "stream daemon left a process behind" >&2
    exit 1
fi
if ! ZOMBIES_AFTER=$(zombies_settled "$ZOMBIES_BEFORE"); then
    echo "stream daemon left a zombie" \
         "($ZOMBIES_BEFORE -> $ZOMBIES_AFTER)" >&2
    exit 1
fi
run python scripts/bench_stream.py --quick --json /tmp/bench_stream_smoke.json

stage routing "pmux-routed two-daemon fleet smoke"
# the horizontal-scale path end to end: two daemons register under
# ct_pmux (sut/verifier/0, sut/verifier/1), the consistent-hash
# client discovers them and routes 8 mixed-shape requests — BOTH
# daemons must serve traffic, and everything must shut down clean
# with no zombies (docs/service.md "Horizontal scale-out")
ZOMBIES_BEFORE=$(zombie_count)
RT_PMUX_PORT=${CT_CHECK_ROUTING_PMUX_PORT:-15106}
ASAN_OPTIONS=halt_on_error=1 "$PMUX" -p "$RT_PMUX_PORT" &
RT_PMUX_PID=$!
RT_LOG0=$(mktemp); RT_LOG1=$(mktemp)
CLEANUP_PIDS="$RT_PMUX_PID"
for _ in $(seq 50); do
    if bash -c "true >/dev/tcp/127.0.0.1/$RT_PMUX_PORT" 2>/dev/null; then
        break
    fi
    sleep 0.1
done
JAX_PLATFORMS=cpu python -m comdb2_tpu.service --port 0 \
    --backend cpu --no-prime --frontier 64 \
    --pmux "$RT_PMUX_PORT" --pmux-shard 0 >"$RT_LOG0" 2>&1 &
RT_PID0=$!
CLEANUP_PIDS="$RT_PMUX_PID $RT_PID0"
JAX_PLATFORMS=cpu python -m comdb2_tpu.service --port 0 \
    --backend cpu --no-prime --frontier 64 \
    --pmux "$RT_PMUX_PORT" --pmux-shard 1 >"$RT_LOG1" 2>&1 &
RT_PID1=$!
CLEANUP_PIDS="$RT_PMUX_PID $RT_PID0 $RT_PID1"
for LOG in "$RT_LOG0" "$RT_LOG1"; do
    for _ in $(seq 200); do
        grep -q '"ready"' "$LOG" 2>/dev/null && break
        sleep 0.1
    done
    grep -q '"ready"' "$LOG" || { echo "routing daemon never ready" >&2; \
        cat "$LOG" >&2; exit 1; }
done
RT_PMUX_PORT="$RT_PMUX_PORT" python - <<'EOF'
import os, random
from comdb2_tpu.ops.history import history_to_edn
from comdb2_tpu.ops.synth import register_history
from comdb2_tpu.service.client import RoutedClient

rc = RoutedClient.discover(pmux_port=int(os.environ["RT_PMUX_PORT"]),
                           timeout_s=300.0, retries=5, backoff_s=0.5)
assert set(rc.clients) == {"sut/verifier/0", "sut/verifier/1"}, \
    sorted(rc.clients)
# 8 requests across enough size classes that the shape-class ring
# provably touches both daemons (class->daemon is deterministic md5)
for i, n_events in enumerate((10, 18, 30, 60, 10, 18, 30, 60)):
    h = register_history(random.Random(100 + i), 3, n_events,
                         p_info=0.0)
    r = rc.check(history_to_edn(h))
    assert r.get("ok") and r.get("valid") is True, r
assert all(v > 0 for v in rc.served.values()), \
    f"a daemon served nothing: {rc.served}"
sts = rc.statuses()
assert len(sts) == 2 and \
    all(st["completed"] >= 1 for st in sts.values()), sts
for c in rc.clients.values():
    assert c.shutdown()
EOF
wait "$RT_PID0"
wait "$RT_PID1"
exec 3<>"/dev/tcp/127.0.0.1/$RT_PMUX_PORT"
printf 'exit\n' >&3
cat <&3 >/dev/null || true
exec 3<&- 3>&-
wait "$RT_PMUX_PID"
CLEANUP_PIDS=""
rm -f "$RT_LOG0" "$RT_LOG1"
if pgrep -f "comdb2_tpu\.service" >/dev/null 2>&1; then
    echo "routing smoke left a daemon behind" >&2
    exit 1
fi
if ! ZOMBIES_AFTER=$(zombies_settled "$ZOMBIES_BEFORE"); then
    echo "routing smoke left a zombie" \
         "($ZOMBIES_BEFORE -> $ZOMBIES_AFTER)" >&2
    exit 1
fi

stage elastic "elastic fleet smoke (supervisor, SIGKILL nemesis, join, migration)"
# the round-12 gate, quick form: the supervisor boots a 2-daemon
# pmux-registered fleet, one daemon is SIGKILLed mid-traffic (the
# survivor must serve its remapped classes; the supervisor reaps the
# corpse, deletes its stale registration, bumps the ring epoch and
# respawns to the floor), a third daemon joins under burst (~1/N
# shape-class remap, gated), a streaming session migrates off a
# draining daemon by checkpoint (O(delta) afterward — no replay),
# and the client-observed fleet history is checked VALID by the
# fleet itself. Zombie accounting shell-side too: the supervisor
# must reap every child (no init reaper in this container).
ZOMBIES_BEFORE=$(zombie_count)
run env JAX_PLATFORMS=cpu python scripts/bench_elastic.py --quick \
    --out /tmp/bench_elastic_smoke.json
if pgrep -f "comdb2_tpu\.service" >/dev/null 2>&1; then
    echo "elastic smoke left a daemon behind" >&2
    exit 1
fi
if ! ZOMBIES_AFTER=$(zombies_settled "$ZOMBIES_BEFORE"); then
    echo "elastic smoke left a zombie" \
         "($ZOMBIES_BEFORE -> $ZOMBIES_AFTER)" >&2
    exit 1
fi

stage obs "tracing + metrics plane smoke (daemon --trace --store)"
# boot with tracing on, run one check + one shrink, scrape the
# metrics (kind:"metrics"), then assert the shutdown trace artifact
# is non-empty valid Perfetto JSON and the scrape carried nonzero
# dispatch + queue-wait histograms (docs/observability.md)
OBS_STORE=$(mktemp -d)
OBS_LOG=$(mktemp)
JAX_PLATFORMS=cpu python -m comdb2_tpu.service --port 0 \
    --backend cpu --no-prime --frontier 64 --trace \
    --store "$OBS_STORE" >"$OBS_LOG" 2>&1 &
OBS_PID=$!
CLEANUP_PIDS="$OBS_PID"
for _ in $(seq 200); do
    grep -q '"ready"' "$OBS_LOG" 2>/dev/null && break
    sleep 0.1
done
grep -q '"ready"' "$OBS_LOG" || { echo "obs daemon never ready" >&2; \
    cat "$OBS_LOG" >&2; exit 1; }
OBS_LOG="$OBS_LOG" python - <<'EOF'
import json, os
from comdb2_tpu.ops import op as O
from comdb2_tpu.service.client import ServiceClient

port = None
with open(os.environ["OBS_LOG"]) as fh:
    for line in fh:
        if '"ready"' in line:
            port = json.loads(line)["port"]
            break
assert port is not None, "no ready line in daemon log"
c = ServiceClient("127.0.0.1", port, timeout_s=300.0, retries=5,
                  backoff_s=0.5)
bad = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
       O.invoke(1, "read", None), O.Op(1, "ok", "read", 2)]
r = c.check(bad)
assert r["ok"] and r["valid"] is False, r
assert r.get("stages"), r            # the per-stage attribution
r = c.shrink(bad)
assert r["ok"] and r["valid"] is False, r
m = c.metrics()
assert m["ok"] and m["kind"] == "metrics", m
snap = m["metrics"]
qw = sum(s["count"] for s in snap["service_queue_wait_ms"]["series"])
dev = sum(s["count"] for s in snap["service_device_ms"]["series"])
assert qw > 0 and dev > 0, (qw, dev)
assert snap["service_dispatches_total"]["series"][0]["value"] > 0
assert "service_queue_wait_ms_bucket" in m["prometheus"]
assert c.shutdown()
EOF
wait "$OBS_PID"
CLEANUP_PIDS=""
TRACE="$OBS_STORE/service/trace.json"
[ -s "$TRACE" ] || { echo "obs daemon wrote no trace artifact" >&2; \
    exit 1; }
python - "$TRACE" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
ev = doc["traceEvents"]
assert ev, "trace artifact is empty"
names = {e["name"] for e in ev}
assert {"admission", "device", "request"} <= names, names
EOF
[ -s "$OBS_STORE/service/timeline.svg" ] || \
    { echo "obs daemon wrote no timeline.svg" >&2; exit 1; }
rm -rf "$OBS_STORE" "$OBS_LOG"

stage_end_ok
if [ "$JSON_MODE" = 0 ]; then
    echo "OK: checker clean, ASan build clean, native static" \
         "analysis clean, ct_pmux shutdown clean under ASan and TSan" \
         "(8 concurrent clients), txn smoke caught" \
         "the seeded cycle, shrink smoke reached the known minimum," \
         "wl smoke caught every seeded family violation with" \
         "device/oracle parity and a clean daemon round trip," \
         "mxu smoke answered both wide-P fixtures," \
         "multichip dryrun bit-identical across the mesh," \
         "verifier service shutdown clean, two-daemon pmux routing" \
         "served on both shards, elastic smoke survived the SIGKILL" \
         "nemesis + join + checkpoint migration with the fleet" \
         "history VALID, obs smoke traced a check+shrink" \
         "with populated histograms"
fi
