#!/usr/bin/env bash
# Repo smoke check: the static invariant checker plus a sanitizer-wired
# native configure/build and a ct_pmux start/exit run under ASan
# (docs/static_analysis.md). Exits non-zero on any violation.
set -euo pipefail

cd "$(dirname "$0")/.."

# APPEND to PYTHONPATH — overriding it drops the axon plugin (CLAUDE.md)
export PYTHONPATH="${PYTHONPATH:+$PYTHONPATH:}$PWD"

echo "== static invariant checker =="
python -m comdb2_tpu.analysis

echo "== native configure/build with ASan =="
if command -v cmake >/dev/null; then
    cmake -DCT_SANITIZE=address -S native -B native/build-asan \
        >/dev/null
    cmake --build native/build-asan -j"$(nproc)" >/dev/null
else
    # containers without cmake: same flags CT_SANITIZE=address wires
    echo "cmake not found — direct g++ ASan build of ct_pmux"
    mkdir -p native/build-asan
    g++ -fsanitize=address -fno-omit-frame-pointer -g -Wall -Wextra \
        -Inative/include native/src/pmux_main.cpp \
        -o native/build-asan/ct_pmux -lpthread
fi

echo "== ct_pmux start/exit under ASan =="
PMUX=native/build-asan/ct_pmux
PORT=${CT_CHECK_PMUX_PORT:-15105}
# halt_on_error so a shutdown race fails the script, not just logs
ASAN_OPTIONS=halt_on_error=1 "$PMUX" -p "$PORT" &
PMUX_PID=$!
trap 'kill "$PMUX_PID" 2>/dev/null || true' EXIT
for _ in $(seq 50); do
    if bash -c "true >/dev/tcp/127.0.0.1/$PORT" 2>/dev/null; then
        break
    fi
    sleep 0.1
done
exec 3<>"/dev/tcp/127.0.0.1/$PORT"
printf 'hello\nexit\n' >&3
cat <&3 >/dev/null || true
exec 3<&- 3>&-
wait "$PMUX_PID"   # non-zero (ASan abort) fails the check
trap - EXIT

echo "OK: checker clean, ASan build clean, ct_pmux shutdown clean"
