#!/usr/bin/env bash
# Repo smoke check: the static invariant checker plus a sanitizer-wired
# native configure/build and a ct_pmux start/exit run under ASan
# (docs/static_analysis.md). Exits non-zero on any violation.
set -euo pipefail

cd "$(dirname "$0")/.."

# APPEND to PYTHONPATH — overriding it drops the axon plugin (CLAUDE.md)
export PYTHONPATH="${PYTHONPATH:+$PYTHONPATH:}$PWD"

echo "== static invariant checker =="
python -m comdb2_tpu.analysis

echo "== pack parity smoke (legacy vs columnar ingest) =="
# one fixture per corpus family; any segment-stream diff fails CI
# before the slow tier ever runs
JAX_PLATFORMS=cpu python scripts/pack_parity_smoke.py

echo "== native configure/build with ASan =="
if command -v cmake >/dev/null; then
    cmake -DCT_SANITIZE=address -S native -B native/build-asan \
        >/dev/null
    cmake --build native/build-asan -j"$(nproc)" >/dev/null
else
    # containers without cmake: same flags CT_SANITIZE=address wires
    echo "cmake not found — direct g++ ASan build of ct_pmux"
    mkdir -p native/build-asan
    g++ -fsanitize=address -fno-omit-frame-pointer -g -Wall -Wextra \
        -Inative/include native/src/pmux_main.cpp \
        -o native/build-asan/ct_pmux -lpthread
fi

echo "== ct_pmux start/exit under ASan =="
PMUX=native/build-asan/ct_pmux
PORT=${CT_CHECK_PMUX_PORT:-15105}
# halt_on_error so a shutdown race fails the script, not just logs
ASAN_OPTIONS=halt_on_error=1 "$PMUX" -p "$PORT" &
PMUX_PID=$!
trap 'kill "$PMUX_PID" 2>/dev/null || true' EXIT
for _ in $(seq 50); do
    if bash -c "true >/dev/tcp/127.0.0.1/$PORT" 2>/dev/null; then
        break
    fi
    sleep 0.1
done
exec 3<>"/dev/tcp/127.0.0.1/$PORT"
printf 'hello\nexit\n' >&3
cat <&3 >/dev/null || true
exec 3<&- 3>&-
wait "$PMUX_PID"   # non-zero (ASan abort) fails the check
trap - EXIT

echo "== txn serializability checker smoke (host engine) =="
# the seeded G2 write-skew fixture MUST be caught (exit 1 = invalid);
# a miss (exit 0) or a give-up (exit 2) fails the repo check — and
# the clean twin must pass, so the detector can't cheat by flagging
# everything
set +e
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --txn --backend host \
    tests/fixtures/txn/g2_item.edn >/dev/null
RC_BAD=$?
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --txn --backend host \
    tests/fixtures/txn/clean.edn >/dev/null
RC_CLEAN=$?
set -e
if [ "$RC_BAD" -ne 1 ]; then
    echo "txn checker MISSED the seeded G2-item cycle (rc=$RC_BAD)"
    exit 1
fi
if [ "$RC_CLEAN" -ne 0 ]; then
    echo "txn checker flagged the clean fixture (rc=$RC_CLEAN)"
    exit 1
fi

echo "== shrink smoke (seeded stale-read fixture) =="
# the fixture plants a single stale read into a write-only history
# (known minimum: ONE read pair); the minimizer must reach it and the
# minimal history must still be INVALID on offline re-check
SHRINK_STORE=$(mktemp -d)
set +e
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --shrink \
    --store "$SHRINK_STORE" tests/fixtures/shrink/stale_read.edn \
    >/dev/null
RC_SHRINK=$?
set -e
if [ "$RC_SHRINK" -ne 1 ]; then
    echo "shrink seed fixture not INVALID (rc=$RC_SHRINK)"; exit 1
fi
MINIMAL=$(ls "$SHRINK_STORE"/shrink/*/minimal.edn 2>/dev/null | head -1)
if [ -z "$MINIMAL" ]; then
    echo "shrink wrote no minimal.edn"; exit 1
fi
OPS=$(grep -c ':process' "$MINIMAL")
if [ "$OPS" -gt 2 ]; then
    echo "shrink left $OPS ops (known minimum is 2)"; exit 1
fi
set +e
JAX_PLATFORMS=cpu python -m comdb2_tpu.filetest --backend host \
    "$MINIMAL" >/dev/null
RC_MIN=$?
set -e
if [ "$RC_MIN" -ne 1 ]; then
    echo "minimal.edn re-check rc=$RC_MIN (must still be INVALID)"
    exit 1
fi
rm -rf "$SHRINK_STORE"

echo "== verifier service smoke (CPU backend) =="
# zombie baseline BEFORE the daemon runs: the post-shutdown check
# below must catch NEW zombies (a reaped child can't show Z, so the
# meaningful assertion is "no more Z states than before, and no
# surviving service process")
ZOMBIES_BEFORE=$(ps -eo stat= | grep -c '^Z' || true)
SVC_LOG=$(mktemp)
JAX_PLATFORMS=cpu python -m comdb2_tpu.service --port 0 \
    --backend cpu --no-prime --frontier 64 >"$SVC_LOG" 2>&1 &
SVC_PID=$!
trap 'kill "$SVC_PID" 2>/dev/null || true' EXIT
for _ in $(seq 200); do     # the ready line carries the chosen port
    grep -q '"ready"' "$SVC_LOG" 2>/dev/null && break
    sleep 0.1
done
grep -q '"ready"' "$SVC_LOG" || { echo "daemon never became ready"; \
    cat "$SVC_LOG"; exit 1; }
SVC_LOG="$SVC_LOG" python - <<'EOF'
import json, os
from comdb2_tpu.ops import op as O
from comdb2_tpu.service.client import ServiceClient

# the log merges stdout+stderr, and jax/absl init noise may precede
# the ready line — scan for it instead of assuming line 1
port = None
with open(os.environ["SVC_LOG"]) as fh:
    for line in fh:
        if '"ready"' in line:
            port = json.loads(line)["port"]
            break
assert port is not None, "no ready line in daemon log"
c = ServiceClient("127.0.0.1", port, timeout_s=300.0, retries=5,
                  backoff_s=0.5)
h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
     O.invoke(1, "read", None), O.Op(1, "ok", "read", 1)]
r = c.check(h)
assert r.get("ok") and r.get("valid") is True, r
st = c.status()["status"]
assert st["completed"] >= 1 and st["dispatches"] >= 1, st
assert c.shutdown()
EOF
wait "$SVC_PID"            # clean exit 0, and the wait reaps it
trap - EXIT
# the daemon itself is reaped by the wait above — what must NOT
# remain is any surviving service process or a NEW zombie it left
# behind (ps -o stat= per CLAUDE.md: pkill'd daemons linger as Z)
if pgrep -f "comdb2_tpu\.service" >/dev/null 2>&1; then
    echo "verifier daemon left a process behind"; exit 1
fi
ZOMBIES_AFTER=$(ps -eo stat= | grep -c '^Z' || true)
if [ "$ZOMBIES_AFTER" -gt "$ZOMBIES_BEFORE" ]; then
    echo "verifier daemon left a zombie" \
         "($ZOMBIES_BEFORE -> $ZOMBIES_AFTER)"; exit 1
fi

echo "OK: checker clean, ASan build clean, ct_pmux shutdown clean," \
     "txn smoke caught the seeded cycle, shrink smoke reached the" \
     "known minimum, verifier service shutdown clean"
