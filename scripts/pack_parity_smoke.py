#!/usr/bin/env python3
"""Pack-parity smoke for scripts/check.sh: one fixture per corpus
family through BOTH ingest paths (legacy per-op vs columnar); the
diff of packed arrays, segment streams, and renamed slots must be
EMPTY. Catches packer drift in seconds without the slow tier —
the exhaustive sweep lives in tests/test_columnar_parity.py.

Exit 0 = bit-identical everywhere; exit 1 = drift (differences named).
"""

from __future__ import annotations

import random
import sys


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.checker.independent import wrap_keyed_history
    from comdb2_tpu.ops import op as O
    from comdb2_tpu.ops.columnar import pack_history_columnar
    from comdb2_tpu.ops.packed import pack_history_legacy
    from comdb2_tpu.ops.synth import (list_append_history,
                                      pinned_wide_history,
                                      register_history)

    rng = random.Random(77)
    keyed = []
    for _ in range(20):
        k, p, v = rng.randrange(3), rng.randrange(3), rng.randrange(3)
        keyed += [O.invoke(p, "write", (k, v)), O.ok(p, "write", (k, v))]
    families = {
        "register": register_history(rng, n_procs=5, n_events=200,
                                     values=5, p_info=0.0),
        "cas-p10": register_history(rng, n_procs=10, n_events=200,
                                    values=5, p_info=0.0,
                                    max_pending=5),
        "crash-heavy": register_history(rng, n_procs=4, n_events=200,
                                        values=3, p_info=0.3),
        "keyed": wrap_keyed_history(keyed),
        "wide-p-pinned": pinned_wide_history(18),
        "txn-list-append": list_append_history(rng, n_procs=3,
                                               n_txns=30),
    }
    bad = 0
    for name, hist in families.items():
        legacy = pack_history_legacy(hist)
        col = pack_history_columnar(hist)
        diffs = []
        for f in ("process", "type", "f", "value", "trans", "pair",
                  "fails", "time"):
            if not np.array_equal(getattr(legacy, f), getattr(col, f)):
                diffs.append(f)
        for f in ("process_table", "f_table", "value_table",
                  "transition_table"):
            if getattr(legacy, f) != getattr(col, f):
                diffs.append(f)
        ls = LJ.make_segments_legacy(legacy)
        cs = LJ.make_segments(col)
        for f in ls._fields:
            if not np.array_equal(getattr(ls, f), getattr(cs, f)):
                diffs.append(f"segments.{f}")
        lr, lp = LJ.remap_slots(ls)
        (cr,), (cp,) = LJ.remap_slots_batch([cs])
        if lp != cp:
            diffs.append("p_eff")
        for f in lr._fields:
            if not np.array_equal(getattr(lr, f), getattr(cr, f)):
                diffs.append(f"remap.{f}")
        if diffs:
            bad += 1
            print(f"DRIFT {name}: {', '.join(diffs)}")
        else:
            print(f"ok {name}")
    if bad:
        print(f"FAIL: {bad} family/families drifted")
        return 1
    print("OK: columnar ingest bit-identical to the legacy packer on "
          f"{len(families)} families")
    return 0


if __name__ == "__main__":
    sys.exit(main())
