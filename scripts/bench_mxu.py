#!/usr/bin/env python3
"""Bench the MXU frontier engine across the kernel/MXU crossover.

Usage: PYTHONPATH=$AXON_SITE:. python scripts/bench_mxu.py \
           [--json BENCH_mxu.json] [--quick]
(real TPU; CPU works for smoke via JAX_PLATFORMS=cpu — the fused-
kernel rows are then unavailable and recorded null.)

Two sections, one JSON line:

- ``sweep``: genuinely concurrent bounded-in-flight wave histories
  (``ops.synth_columnar.wide_register_batch_columns``) at P from the
  fused kernel's territory (<= 15) across the crossover into MXU
  territory (16..30). Each P times every engine that serves the shape
  (fused kernel, XLA seg2, MXU) and HARD-ASSERTS verdict parity —
  valid history and seeded-violation twin both, fail segments
  included — before any timing counts.
- ``conversion``: the workload-class headline. A P=17 wave history
  with 16 free reads peaks at a 2^16 + chain frontier: the XLA
  ladder's top rung (65536) overflows to honest UNKNOWN, the MXU
  engine's top rung (131072) returns a definite verdict. Both runs
  are timed and the statuses asserted.

The MXU path's dispatch discipline is asserted on the
``mxu.DISPATCHES`` delta, and the run's compile-guard summary is
embedded (observed lowerings ⊆ PROGRAMS.md; COMDB2_TPU_COMPILE_GUARD=0
makes the assert report-only).
"""
from __future__ import annotations

import argparse
import json
import time


def _prep(packed, model, s_pad, k_pad):
    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.models.memo import memo as make_memo
    from comdb2_tpu.utils import next_pow2

    mm = make_memo(model, packed)
    segs = LJ.make_segments(packed, s_pad=s_pad, k_pad=k_pad)
    segs, p_eff = LJ.remap_slots(segs)
    succ = LJ.pad_succ(mm.succ, next_pow2(mm.n_states),
                       next_pow2(mm.n_transitions))
    return mm, segs, succ, max(p_eff, 1)


def _time(fn, reps=2):
    import jax

    out = fn()
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return min(ts), tuple(int(x) for x in out)


def sweep_section(quick: bool) -> list:
    """P sweep with per-engine timings + hard verdict parity."""
    import jax

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.checker import mxu as MXU
    from comdb2_tpu.checker import pallas_seg as PSEG
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops import synth_columnar as SC
    from comdb2_tpu.utils import next_pow2

    ps = (6, 10, 14, 16, 24) if quick else (6, 10, 14, 16, 20, 24, 30)
    n_waves = 4 if quick else 8
    rows = []
    for P in ps:
        # bounded frontier (4 free reads) keeps the sweep about
        # ENGINE throughput, not search blow-up; the conversion
        # section owns the wide-frontier story
        n_free = min(4, P - 2)
        n_chain = P - n_free
        row = {"P": P, "engines": {}, "verdicts": {}}
        for violation in (False, True):
            cols = SC.wide_register_batch_columns(
                900 + P, 1, n_waves, n_chain, n_free,
                values=max(16, n_chain + 2), violation=violation)
            packed = SC.pack_register_columns(cols)[0]
            n_inv = int(((packed.type == 1) & ~packed.fails).sum())
            mm, segs, succ, p_eff = _prep(
                packed, cas_register(), s_pad=next_pow2(n_waves * P),
                k_pad=next_pow2(P))
            sizes = dict(n_states=mm.n_states,
                         n_transitions=mm.n_transitions)
            key = "violation" if violation else "valid"
            row["events"] = 2 * n_waves * P
            verdicts = {}

            dt, r = _time(lambda: LJ.check_device_seg(
                succ, segs.inv_proc, segs.inv_tr, segs.ok_proc,
                segs.depth, F=1024, P=p_eff, **sizes))
            verdicts["xla-seg2"] = r
            row["engines"].setdefault("xla-seg2", {})[key] = \
                round(n_inv / dt, 1)

            if MXU.fits(mm.n_states, mm.n_transitions, p_eff):
                n0 = MXU.DISPATCHES
                dt, r = _time(lambda: MXU.check_device_mxu(
                    succ, segs.inv_proc, segs.inv_tr, segs.ok_proc,
                    segs.depth, F=1024, P=p_eff, **sizes))
                # dispatch discipline: _time's 1 warmup + 2 reps are
                # exactly 3 engine dispatches — no hidden escalation
                # or retry inside the entry (counted at the entry
                # itself, mxu.DISPATCHES)
                assert MXU.DISPATCHES == n0 + 3, (MXU.DISPATCHES, n0)
                verdicts["mxu"] = r
                row["engines"].setdefault("mxu", {})[key] = \
                    round(n_inv / dt, 1)

            # the fused kernel serves P <= 15 AND K <= 8; a wave
            # history's first-completion segment carries P invokes,
            # so only the small-P rungs are kernel-eligible
            kr = None
            if PSEG.available():
                kr = PSEG.check_device_pallas(
                    mm.succ, segs, P=p_eff, **sizes)
            if kr is not None:
                dt, r = _time(lambda: PSEG.check_device_pallas(
                    mm.succ, segs, P=p_eff, **sizes))
                verdicts["pallas-fused"] = r
                row["engines"].setdefault("pallas-fused", {})[key] = \
                    round(n_inv / dt, 1)

            # HARD parity across every engine that answered: status
            # always; fail segment on non-valid; count on valid
            want_status = 1 if violation else 0
            for name, (st, fa, n) in verdicts.items():
                assert st == want_status, \
                    (P, key, name, (st, fa, n))
            base = verdicts["xla-seg2"]
            for name, (st, fa, n) in verdicts.items():
                if st == 0:
                    assert n == base[2], (P, key, name, n, base)
                else:
                    assert fa == base[1], (P, key, name, fa, base)
            row["verdicts"][key] = {
                k: ("valid" if v[0] == 0 else "invalid")
                for k, v in verdicts.items()}
        rows.append(row)
        print(f"P={P:2d} " + "  ".join(
            f"{k} {v.get('valid', 0):9.0f} ops/s"
            for k, v in row["engines"].items()), flush=True)
    return rows


def conversion_section(n_free: int) -> dict:
    """The headline: a 2^n_free + chain frontier that overflows the
    XLA ladder's top rung but fits the MXU engine's."""
    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.checker import mxu as MXU
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops import synth_columnar as SC
    from comdb2_tpu.utils import next_pow2

    cols = SC.wide_register_batch_columns(1009, 1, 1, 1, n_free,
                                          values=16)
    packed = SC.pack_register_columns(cols)[0]
    P = 1 + n_free
    mm, segs, succ, p_eff = _prep(packed, cas_register(),
                                  s_pad=next_pow2(P),
                                  k_pad=next_pow2(P))
    sizes = dict(n_states=mm.n_states,
                 n_transitions=mm.n_transitions)
    assert p_eff == P, (p_eff, P)
    xla_cap = 1 << max(n_free, 4)        # the rung the frontier beats
    t0 = time.perf_counter()
    st_x, _, _ = LJ.check_device_seg(
        succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
        F=xla_cap, P=p_eff, **sizes)
    xla_t = time.perf_counter() - t0
    mxu_cap = next((f for f in MXU.CAPACITIES if f > (1 << n_free)),
                   MXU.CAPACITIES[-1])
    n0 = MXU.DISPATCHES
    t0 = time.perf_counter()
    st_m, _, n_m = MXU.check_device_mxu(
        succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
        F=mxu_cap, P=p_eff, **sizes)
    mxu_t = time.perf_counter() - t0
    # ONE engine dispatch produced the conversion verdict — no ladder
    # retries hidden in the timing (counted at the engine entry)
    assert MXU.DISPATCHES == n0 + 1, (MXU.DISPATCHES, n0)
    out = {
        "P": P, "free_reads": n_free,
        "frontier_peak_lower_bound": (1 << n_free) + 1,
        "xla_capacity": xla_cap, "xla_status": int(st_x),
        "xla_time_s": round(xla_t, 3),
        "mxu_capacity": mxu_cap, "mxu_status": int(st_m),
        "mxu_time_s": round(mxu_t, 3),
        "mxu_final_count": int(n_m),
    }
    # the acceptance assertion: UNKNOWN before, definite verdict now
    assert int(st_x) == LJ.UNKNOWN, out
    assert int(st_m) == LJ.VALID, out
    print(f"conversion P={P} free={n_free}: xla@{xla_cap} UNKNOWN "
          f"({xla_t:.2f}s) -> mxu@{mxu_cap} VALID ({mxu_t:.2f}s)",
          flush=True)
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="BENCH_mxu.json")
    ap.add_argument("--quick", action="store_true",
                    help="smaller sweep + 2^12 conversion frontier "
                         "(CPU smoke)")
    args = ap.parse_args()

    from comdb2_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    import jax

    from comdb2_tpu.analysis.compile_surface import static_inventory
    from comdb2_tpu.checker import mxu as MXU
    from comdb2_tpu.utils import compile_guard

    inv = static_inventory()
    d0 = MXU.DISPATCHES
    with compile_guard.guard() as g:
        sweep = sweep_section(args.quick)
        # --quick keeps the overflow rung affordable on CPU: 2^12
        # beats a 4096 XLA rung the same way 2^16 beats 65536
        conv = conversion_section(12 if args.quick else 16)
    out = {
        "backend": jax.default_backend(),
        "quick": bool(args.quick),
        "sweep": sweep,
        "conversion": conv,
        "mxu_dispatches": MXU.DISPATCHES - d0,
        "engines": ["pallas-fused", "xla-seg2", "mxu"],
        "compile_guard": g.summary(inv),
    }
    if out["backend"] != "tpu":
        out["note"] = ("non-TPU backend: no MXU hardware and no "
                       "Mosaic kernel — xla/mxu rows are CPU "
                       "lowerings, kernel rows null")
    with open(args.json, "w") as fh:
        fh.write(json.dumps(out) + "\n")
    print("artifact written:", args.json, flush=True)
    if compile_guard.enabled():
        g.assert_closed(inv)


if __name__ == "__main__":
    main()
