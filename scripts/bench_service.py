#!/usr/bin/env python3
"""Bench the verifier daemon: the overload BURST is the headline.

Boots a daemon (CPU backend by default — run with ``--backend tpu``
manually on a real chip), drives it with C concurrent single-history
clients at mixed history sizes, and emits ONE JSON line
(``BENCH_service.json``). Phases:

- **serial**    — one client, one request in flight at a time: every
  request is its own device dispatch (the round-trip-bound antipattern
  the ``per-item-dispatch`` analysis rule flags).
- **burst**     — all C clients submit concurrently (the overload
  shape continuous batching exists for): requests slot into their
  buckets as they arrive, full/due batches launch through the
  in-flight ring. The HEADLINE metrics come from this phase's own
  replies: latency p50/p99 (gate: **p99 <= 2x p50** — the tail must
  belong to the work, not the admission queue) and the per-reply
  queue-wait p99 (gate: <= ``--max-queue-wait-p99-ms``, default 965 =
  the pre-rework 4825 ms baseline / 5).

Amortization gates are derived from the MEASURED run, not fixed
constants (the old 5.0x floor and the per-bucket ceil bound predated
the P_eff/K bucket-axis growth and idle-launch waves, and were flaky
on this 1-CPU container):

- dispatch amortization: burst requests per dispatch must be >=
  ``max(2, requests / buckets_touched / 4)`` — each launch wave may
  split a bucket, but a burst must still amortize several requests
  per dispatch (the JSON records the derived floor and the
  launch-reason counters full/deadline/idle that explain the waves);
- wall-clock speedup vs serial: asserted against
  ``0.5 * ideal`` where ``ideal = serial_s / (serial_s - saved)``
  and ``saved = (serial_dispatches - burst_dispatches) * tunnel``
  — the round-trips the scheduler provably removed; the 0.5 haircut
  covers single-CPU pack serialization. With no injected tunnel the
  floor is disabled (XLA-CPU per-history compute scales with B; the
  dispatch counts stay the ground truth).

Also asserted, backend-independent: disconnect survival, explicit
``overload`` replies carrying ``retry_after_ms`` under an
over-capacity burst, per-reply stage breakdowns tiling the measured
wall within 10%, populated per-stage histograms (``stages_ms``), a
non-empty rid-correlated Perfetto trace artifact, and a CLOSED
program set across the timed phases (compile guard).

The tunnel model: the link is ASYNC — the daemon's injected latency
is charged from DISPATCH time, so staged buckets absorb each other's
round-trips exactly like the real link (CLAUDE.md: ~100 ms
dispatch+readback). ``--tunnel-ms 0`` reports raw CPU numbers;
``--quick`` (the test suite) shrinks the run, drops the injection and
keeps the structural assertions.

Usage: PYTHONPATH=/root/.axon_site:. python scripts/bench_service.py
       [--requests 64] [--tunnel-ms 100] [--quick]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def spawn_daemon(backend, extra=()):
    env = {**os.environ}
    if backend == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "comdb2_tpu.service", "--port", "0",
         "--backend", backend, "--no-prime", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO, env=env)
    ready = json.loads(proc.stdout.readline())
    assert ready.get("ready"), ready
    return proc, ready["port"]


def make_requests(n):
    """Mixed shapes: two size classes -> (at least) two buckets."""
    from comdb2_tpu.ops.history import history_to_edn
    from comdb2_tpu.ops.synth import register_history

    texts = []
    for i in range(n):
        n_events = 16 if i % 2 == 0 else 48
        h = register_history(random.Random(1000 + i), n_procs=3,
                             n_events=n_events, p_info=0.0)
        texts.append(history_to_edn(h))
    return texts


def encode(i, text):
    return (json.dumps({"op": "check", "id": i, "history": text},
                       separators=(",", ":")) + "\n").encode()


def read_reply(f):
    line = f.readline()
    assert line.endswith(b"\n"), "truncated reply"
    return json.loads(line)


def connect(port, timeout_s=600.0):
    s = socket.create_connection(("127.0.0.1", port),
                                 timeout=timeout_s)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s, s.makefile("rb")


def run_serial(port, payloads):
    s, f = connect(port)
    t0 = time.perf_counter()
    for p in payloads:
        s.sendall(p)
        r = read_reply(f)
        assert r["ok"], r
    dt = time.perf_counter() - t0
    s.close()
    return dt


def run_burst(port, payloads):
    """All clients submit concurrently — the overload-burst shape."""
    conns = [connect(port) for _ in payloads]
    t0 = time.perf_counter()
    for (s, _), p in zip(conns, payloads):
        s.sendall(p)
    replies = [read_reply(f) for _, f in conns]
    dt = time.perf_counter() - t0
    for s, _ in conns:
        s.close()
    for r in replies:
        assert r["ok"], r
    return dt, replies


def burst_metrics(replies):
    """Headline numbers from the burst phase's OWN replies (the
    scrape's histograms span every phase; the burst gates must see
    only burst traffic): latency p50/p99 + ratio, and the per-reply
    queue-wait quantiles — the SAME nearest-rank percentile the
    daemon's status reports use."""
    from comdb2_tpu.service.core import _percentile

    lats = sorted(r["latency_ms"] for r in replies)
    qw = sorted(r.get("stages", {}).get("queue_wait_ms", 0.0)
                for r in replies)
    p50, p99 = _percentile(lats, 0.50), _percentile(lats, 0.99)
    return {
        "latency_p50_ms": round(p50, 3),
        "latency_p99_ms": round(p99, 3),
        "p99_over_p50": round(p99 / p50, 3) if p50 > 0 else 0.0,
        "queue_wait_p50_ms": round(_percentile(qw, 0.50), 3),
        "queue_wait_p99_ms": round(_percentile(qw, 0.99), 3),
    }


def assert_stages_tile_wall(replies):
    """Per request: the stage breakdown (queue-wait / host-pack /
    device / finalize) must sum to within 10% of the measured wall —
    the attribution contract that makes the histograms trustworthy.
    A small absolute floor absorbs scheduler jitter on quick CPU runs
    where total latency is single-digit ms."""
    checked = 0
    for r in replies:
        stages = r.get("stages")
        if not stages:
            continue
        total = sum(stages.values())
        lat = r["latency_ms"]
        tol = max(0.1 * lat, 5.0)
        assert abs(total - lat) <= tol, (
            f"stage sum {total:.3f} ms vs wall {lat:.3f} ms "
            f"(> {tol:.3f} ms apart): {stages}")
        checked += 1
    assert checked, "no reply carried a stage breakdown"
    return checked


def stage_quantiles(metrics_snapshot):
    """{stage: {p50,p95,p99,count}} from a kind:"metrics" scrape."""
    out = {}
    for stage in ("queue_wait", "host_pack", "device", "finalize"):
        series = metrics_snapshot[f"service_{stage}_ms"]["series"][0]
        out[stage] = {k: series[k]
                      for k in ("p50", "p95", "p99", "count")}
    return out


def load_trace(store_dir):
    """The daemon's Perfetto export (written at shutdown): must load,
    be non-empty, and carry the correlated span pipeline — admission
    through per-request rows plus device spans with transfer-byte
    attribution."""
    path = os.path.join(store_dir, "service", "trace.json")
    with open(path) as fh:
        doc = json.load(fh)
    events = doc["traceEvents"]
    assert events, "trace artifact is empty"
    names = {e["name"] for e in events}
    assert {"admission", "stage", "device", "finalize",
            "request"} <= names, names
    dev = [e for e in events if e["name"] == "device"]
    assert any(e["args"].get("bytes_h2d", 0) > 0 for e in dev), \
        "no device span carries transfer-byte attribution"
    assert any("rid" in e["args"] for e in events), \
        "no span is request-id correlated"
    return path, len(events)


def request_one(port, obj):
    s, f = connect(port)
    s.sendall((json.dumps(obj) + "\n").encode())
    r = read_reply(f)
    s.close()
    return r


def status(port):
    return request_one(port, {"op": "status"})["status"]


def stop_daemon(proc, port):
    try:
        request_one(port, {"op": "shutdown"})
        proc.wait(timeout=60)
    except Exception:
        proc.kill()               # never leak a daemon
        proc.wait(timeout=30)
        raise


def check_disconnect_survival(port, text):
    """Send a check and hang up before the reply: the daemon must keep
    serving (the batch runs; the reply is dropped, not wedged)."""
    s, _ = connect(port)
    s.sendall(encode(0, text))
    s.close()
    time.sleep(0.2)
    r = request_one(port, {"op": "check", "id": 1, "history": text})
    assert r["ok"], f"daemon broken after client disconnect: {r}"
    return True


def check_overload_burst(backend, text):
    """A burst past a tiny admission queue must draw explicit overload
    replies carrying a retry_after_ms backoff hint — and every
    connection still gets an answer."""
    proc, port = spawn_daemon(backend, ("--max-queue", "4",
                                        "--fill-ms", "50",
                                        "--frontier", "64"))
    try:
        n = 16
        conns = [connect(port) for _ in range(n)]
        for i, (s, _) in enumerate(conns):
            s.sendall(encode(i, text))
        replies = [read_reply(f) for _, f in conns]
        for s, _ in conns:
            s.close()
        overloads = [r for r in replies
                     if not r.get("ok") and r.get("error") == "overload"]
        served = [r for r in replies if r.get("ok")]
        assert len(replies) == n, "a connection got no reply"
        assert overloads, "over-capacity burst drew no overload replies"
        for r in overloads:
            assert 25 <= r.get("retry_after_ms", 0) <= 5000, (
                "overload reply lacks a usable retry_after_ms hint", r)
        assert served, "overload shed everything, served nothing"
        assert request_one(port, {"op": "ping"}).get("pong")
        return len(overloads)
    finally:
        stop_daemon(proc, port)


def _analysis_clean() -> bool:
    """Artifact hygiene: the bench spawns daemons that write into the
    store dir and the repo tree — the static-analysis verdict must
    stay clean POST-run, so a finding introduced by generated files
    fails the bench loudly instead of rotting until the next tier-1
    run. Subprocess: the checker's verdict must not depend on this
    process's jax/import state."""
    r = subprocess.run(
        [sys.executable, "-m", "comdb2_tpu.analysis", "--no-trace"],
        cwd=REPO, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        print("FAIL: static analysis not clean post-run:\n"
              f"{r.stdout}{r.stderr}", file=sys.stderr)
        return False
    return True


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--backend", default="cpu",
                    choices=["cpu", "tpu", "auto"])
    ap.add_argument("--batch-cap", type=int, default=64)
    ap.add_argument("--frontier", type=int, default=64)
    ap.add_argument("--max-p99-over-p50", type=float, default=2.0,
                    help="burst-phase latency tail gate: p99 must "
                         "stay within this multiple of p50 (0 "
                         "disables)")
    ap.add_argument("--max-queue-wait-p99-ms", type=float,
                    default=965.0,
                    help="burst-phase queue-wait p99 gate (default = "
                         "the pre-continuous-batching 4825 ms "
                         "baseline / 5; 0 disables)")
    ap.add_argument("--tunnel-ms", type=float, default=None,
                    help="injected per-dispatch latency modeling the "
                         "TPU tunnel on CPU (default: 100 on cpu, 0 "
                         "elsewhere; 0 = raw numbers)")
    ap.add_argument("--fill-ms", type=float, default=150.0,
                    help="the daemon's batch-formation cap. The "
                         "default is sized so the 1-CPU admission "
                         "thread finishes admitting the whole burst "
                         "before any launch budget fires — "
                         "whole-bucket launches, deterministic "
                         "program classes; shorter windows trade "
                         "formation latency for arrival-timed wave "
                         "splits (launch counters in the JSON show "
                         "which you got)")
    ap.add_argument("--quick", action="store_true",
                    help="small run, structural assertions only "
                         "(what the test suite uses)")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_service.json"))
    ap.add_argument("--store-dir", default=None, metavar="DIR",
                    help="store root the daemon writes its obs "
                         "artifacts into (trace.json/timeline.svg; "
                         "default: ./store, a tmpdir under --quick)")
    args = ap.parse_args()
    if args.store_dir is None:
        args.store_dir = (tempfile.mkdtemp(prefix="bench_service_")
                          if args.quick
                          else os.path.join(REPO, "store"))
    # a persistent store dir may hold a PREVIOUS run's trace: delete
    # it up front so load_trace can only ever validate THIS run's
    # artifact (the daemon's artifact-write failures are log-only)
    stale = os.path.join(args.store_dir, "service", "trace.json")
    if os.path.exists(stale):
        os.unlink(stale)
    if args.tunnel_ms is None:
        args.tunnel_ms = 100.0 if args.backend == "cpu" else 0.0
    if args.quick:
        args.requests = min(args.requests, 16)
        args.tunnel_ms = 0.0
        args.max_p99_over_p50 = 0.0
        args.max_queue_wait_p99_ms = 0.0

    texts = make_requests(args.requests)
    payloads = [encode(i, t) for i, t in enumerate(texts)]
    proc, port = spawn_daemon(args.backend,
                              ("--batch-cap", str(args.batch_cap),
                               "--frontier", str(args.frontier),
                               "--max-queue",
                               str(max(256, 2 * args.requests)),
                               "--fill-ms", str(args.fill_ms),
                               "--inject-dispatch-latency-ms",
                               str(args.tunnel_ms),
                               # the obs plane rides the benched run:
                               # the trace artifact lands in the
                               # store dir at shutdown, and the <2%
                               # budget means tracing on does not
                               # move the headline numbers
                               "--trace", "--store", args.store_dir))
    try:
        # warm the program classes the timed phases can touch: every
        # bucket's B=1 serial program, the full-burst classes, AND the
        # wave-split classes — continuous batching launches on arrival
        # timing, so a bucket that fills across two selector rounds
        # dispatches as two SMALLER pow2 batches; bursting prefix
        # ladders (n, n/2, n/4, n/8) walks each bucket through its
        # lower b_prog rungs so a timed-phase wave split lands on a
        # warm program instead of a fresh lowering
        run_serial(port, payloads)
        for frac in (1, 2, 4, 8):
            run_burst(port, payloads[:max(len(payloads) // frac, 1)])
        run_burst(port, payloads)
        run_serial(port, payloads[:2])

        st0 = status(port)
        serial_s = run_serial(port, payloads)
        st1 = status(port)
        burst_s, burst_replies = run_burst(port, payloads)
        st2 = status(port)
        # the per-stage attribution contract, per request, from the
        # timed burst phase's own replies
        stage_checked = assert_stages_tile_wall(burst_replies)
        burst = burst_metrics(burst_replies)
        scrape = request_one(port, {"op": "metrics"})
        assert scrape["ok"] and scrape["kind"] == "metrics", scrape
        stages = stage_quantiles(scrape["metrics"])
        assert stages["queue_wait"]["count"] > 0, stages
        assert stages["device"]["count"] > 0, stages
        assert "service_queue_wait_ms_bucket" in scrape["prometheus"]

        n = args.requests
        serial_tp = n / serial_s
        burst_tp = n / burst_s
        speedup = burst_tp / serial_tp

        # dispatch accounting per bucket, from the daemon's own metrics
        def per_bucket(a, b, field):
            return {k: b["buckets"][k][field]
                    - a["buckets"].get(k, {}).get(field, 0)
                    for k in b["buckets"]}

        def launches(a, b):
            return {r: b[f"launch_{r}"] - a[f"launch_{r}"]
                    for r in ("full", "deadline", "idle")}

        serial_disp = per_bucket(st0, st1, "dispatches")
        co_disp = per_bucket(st1, st2, "dispatches")
        co_req = per_bucket(st1, st2, "requests")
        burst_disp = sum(co_disp.values())
        buckets_touched = sum(1 for d in co_disp.values() if d > 0)
        # derived amortization floor (see module docstring): launch
        # waves may split a bucket, but a one-shot burst must still
        # amortize several requests per dispatch
        amortization = (sum(co_req.values()) / burst_disp
                        if burst_disp else 0.0)
        amort_floor = max(2.0, n / max(buckets_touched, 1) / 4)
        if not args.quick:
            assert amortization >= amort_floor, (
                f"burst amortization {amortization:.2f} req/dispatch "
                f"< derived floor {amort_floor:.2f} "
                f"({n} requests over {buckets_touched} buckets, "
                f"{burst_disp} dispatches) — slot-filling failed")
        else:
            assert burst_disp <= sum(co_req.values()), co_disp
        # derived wall-clock floor: half the tunnel round-trips the
        # scheduler provably removed (dispatch counts x tunnel)
        saved_s = max(sum(serial_disp.values()) - burst_disp, 0) \
            * args.tunnel_ms / 1e3
        ideal = (serial_s / max(serial_s - saved_s, 1e-9)
                 if args.tunnel_ms > 0 else 0.0)
        speedup_floor = 0.5 * ideal if args.tunnel_ms > 0 else 0.0
        survived = check_disconnect_survival(port, texts[0])
        lat = st2["latency_ms"]
        ring = {"depth": st2["ring_depth"],
                "launches": launches(st1, st2),
                "carry_reuses": st2["carry_reuses"]}
    finally:
        stop_daemon(proc, port)

    trace_path, trace_events = load_trace(args.store_dir)
    overloads = check_overload_burst(args.backend, texts[0])

    out = {
        "bench": "service", "backend": args.backend,
        "requests": n, "batch_cap": args.batch_cap,
        "frontier": args.frontier,
        "tunnel_ms_injected": args.tunnel_ms,
        "burst": burst,
        "burst_gates": {
            "max_p99_over_p50": args.max_p99_over_p50,
            "max_queue_wait_p99_ms": args.max_queue_wait_p99_ms,
            "baseline_queue_wait_p99_ms": 4825.7,
        },
        "serial_s": round(serial_s, 4),
        "burst_s": round(burst_s, 4),
        "serial_req_per_s": round(serial_tp, 1),
        "burst_req_per_s": round(burst_tp, 1),
        "speedup": round(speedup, 2),
        "speedup_floor_derived": round(speedup_floor, 2),
        "amortization_req_per_dispatch": round(amortization, 2),
        "amortization_floor_derived": round(amort_floor, 2),
        "serial_dispatches": sum(serial_disp.values()),
        "burst_dispatches": burst_disp,
        "burst_dispatches_per_bucket": co_disp,
        "requests_per_bucket": co_req,
        "ring": ring,
        "latency_ms": lat,
        "stages_ms": stages,
        "stage_sum_checked": stage_checked,
        "trace": {"path": trace_path, "events": trace_events},
        "overload_replies": overloads,
        "survived_disconnect": survived,
        "programs_after_warm": st0["programs"],
        "programs_after_timed": st2["programs"],
    }
    line = json.dumps(out)
    print(line)
    with open(args.out, "w") as fh:
        fh.write(line + "\n")
    # compile-surface closure, via the daemon's own program-key
    # metrics (the daemon is a subprocess, so the in-process compile
    # guard can't see it): after the warm phase every program class
    # this traffic can need exists, so the TIMED phases must compile
    # nothing new — program-set growth in steady state is exactly the
    # recompile storm the bucketed admission exists to prevent.
    # Asserted AFTER the artifact write so a failing run still leaves
    # the diagnostic JSON behind (same order as bench_txn/bench_shrink)
    from comdb2_tpu.utils import compile_guard
    if compile_guard.enabled() and st2["programs"] != st0["programs"]:
        print(f"FAIL: daemon compiled "
              f"{st2['programs'] - st0['programs']} new program(s) "
              "during the timed phases — the bucket ladder is not "
              "closed over this traffic", file=sys.stderr)
        return 1
    rc = 0
    if args.max_p99_over_p50 and \
            burst["p99_over_p50"] > args.max_p99_over_p50:
        print(f"FAIL: burst latency p99/p50 {burst['p99_over_p50']} "
              f"> {args.max_p99_over_p50}", file=sys.stderr)
        rc = 1
    if args.max_queue_wait_p99_ms and \
            burst["queue_wait_p99_ms"] > args.max_queue_wait_p99_ms:
        print(f"FAIL: burst queue-wait p99 "
              f"{burst['queue_wait_p99_ms']} ms > "
              f"{args.max_queue_wait_p99_ms} ms", file=sys.stderr)
        rc = 1
    if speedup_floor and speedup < speedup_floor:
        print(f"FAIL: speedup {speedup:.2f} < derived floor "
              f"{speedup_floor:.2f} (ideal {ideal:.2f})",
              file=sys.stderr)
        rc = 1
    if not _analysis_clean():
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
