#!/usr/bin/env python3
"""Bench the verifier daemon: coalesced vs per-request serial.

Boots a daemon (CPU backend by default — run with ``--backend tpu``
manually on a real chip), drives it with C concurrent single-history
clients at mixed history sizes, and emits ONE JSON line
(``BENCH_service.json``) comparing:

- **serial**    — one client, one request in flight at a time: every
  request is its own device dispatch (the round-trip-bound antipattern
  the ``per-item-dispatch`` analysis rule flags).
- **coalesced** — all C clients submit concurrently; the daemon's
  admission queue groups them per shape bucket and each bucket rides
  ONE device dispatch per tick.

Also asserts the serving guarantees that are backend-independent:

- coalesced dispatch count per bucket <= ceil(requests / batch cap);
- the daemon survives a client disconnect mid-request;
- an over-capacity burst gets explicit ``overload`` replies, not
  hangs;
- every reply's per-stage breakdown (queue-wait / host-pack / device /
  finalize) sums to within 10% of its measured wall, the scrape's
  per-stage histograms are populated (``stages_ms`` in the JSON), and
  the daemon's shutdown trace artifact (``--trace --store``) is a
  non-empty Perfetto-loadable span export with request-id correlation
  and transfer-byte attribution (docs/observability.md).

The throughput ratio is asserted against ``--min-speedup`` (default
5.0, the acceptance bar). The ratio is a per-dispatch-overhead
phenomenon: the coalescer amortizes whatever one dispatch costs over
the whole batch. On the real TPU that cost is the ~100 ms tunnel
dispatch+readback round-trip (CLAUDE.md: 1.5k ops/s per-item vs 93k
streamed); on CPU there is no tunnel and XLA's per-history compute
actually SCALES with the batch (measured 0.84x warm), so CPU runs
model the tunnel explicitly with the daemon's
``--inject-dispatch-latency-ms`` knob (default ``--tunnel-ms 100``
here, matching the measured link; ``--tunnel-ms 0`` reports the raw
CPU numbers). The injection is declared in the daemon's status and in
this bench's JSON — the dispatch COUNTS are the scheduling ground
truth either way, and on ``--backend tpu`` no injection is applied.
``--quick`` (used by the test suite) shrinks the run, drops the
injection, and skips the speedup floor, keeping the structural
assertions.

Usage: PYTHONPATH=/root/.axon_site:. python scripts/bench_service.py
       [--requests 64] [--min-speedup 5] [--tunnel-ms 100] [--quick]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import random
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def spawn_daemon(backend, extra=()):
    env = {**os.environ}
    if backend == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "comdb2_tpu.service", "--port", "0",
         "--backend", backend, "--no-prime", *extra],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO, env=env)
    ready = json.loads(proc.stdout.readline())
    assert ready.get("ready"), ready
    return proc, ready["port"]


def make_requests(n):
    """Mixed shapes: two size classes -> (at least) two buckets."""
    from comdb2_tpu.ops.history import history_to_edn
    from comdb2_tpu.ops.synth import register_history

    texts = []
    for i in range(n):
        n_events = 16 if i % 2 == 0 else 48
        h = register_history(random.Random(1000 + i), n_procs=3,
                             n_events=n_events, p_info=0.0)
        texts.append(history_to_edn(h))
    return texts


def encode(i, text):
    return (json.dumps({"op": "check", "id": i, "history": text},
                       separators=(",", ":")) + "\n").encode()


def read_reply(f):
    line = f.readline()
    assert line.endswith(b"\n"), "truncated reply"
    return json.loads(line)


def connect(port, timeout_s=600.0):
    s = socket.create_connection(("127.0.0.1", port),
                                 timeout=timeout_s)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s, s.makefile("rb")


def run_serial(port, payloads):
    s, f = connect(port)
    t0 = time.perf_counter()
    for p in payloads:
        s.sendall(p)
        r = read_reply(f)
        assert r["ok"], r
    dt = time.perf_counter() - t0
    s.close()
    return dt


def run_coalesced(port, payloads):
    conns = [connect(port) for _ in payloads]
    t0 = time.perf_counter()
    for (s, _), p in zip(conns, payloads):
        s.sendall(p)
    replies = [read_reply(f) for _, f in conns]
    dt = time.perf_counter() - t0
    for s, _ in conns:
        s.close()
    for r in replies:
        assert r["ok"], r
    return dt, replies


def assert_stages_tile_wall(replies):
    """Per request: the stage breakdown (queue-wait / host-pack /
    device / finalize) must sum to within 10% of the measured wall —
    the attribution contract that makes the histograms trustworthy.
    A small absolute floor absorbs scheduler jitter on quick CPU runs
    where total latency is single-digit ms."""
    checked = 0
    for r in replies:
        stages = r.get("stages")
        if not stages:
            continue
        total = sum(stages.values())
        lat = r["latency_ms"]
        tol = max(0.1 * lat, 5.0)
        assert abs(total - lat) <= tol, (
            f"stage sum {total:.3f} ms vs wall {lat:.3f} ms "
            f"(> {tol:.3f} ms apart): {stages}")
        checked += 1
    assert checked, "no reply carried a stage breakdown"
    return checked


def stage_quantiles(metrics_snapshot):
    """{stage: {p50,p95,p99,count}} from a kind:"metrics" scrape."""
    out = {}
    for stage in ("queue_wait", "host_pack", "device", "finalize"):
        series = metrics_snapshot[f"service_{stage}_ms"]["series"][0]
        out[stage] = {k: series[k]
                      for k in ("p50", "p95", "p99", "count")}
    return out


def load_trace(store_dir):
    """The daemon's Perfetto export (written at shutdown): must load,
    be non-empty, and carry the correlated span pipeline — admission
    through per-request rows plus device spans with transfer-byte
    attribution."""
    path = os.path.join(store_dir, "service", "trace.json")
    with open(path) as fh:
        doc = json.load(fh)
    events = doc["traceEvents"]
    assert events, "trace artifact is empty"
    names = {e["name"] for e in events}
    assert {"admission", "stage", "device", "finalize",
            "request"} <= names, names
    dev = [e for e in events if e["name"] == "device"]
    assert any(e["args"].get("bytes_h2d", 0) > 0 for e in dev), \
        "no device span carries transfer-byte attribution"
    assert any("rid" in e["args"] for e in events), \
        "no span is request-id correlated"
    return path, len(events)


def request_one(port, obj):
    s, f = connect(port)
    s.sendall((json.dumps(obj) + "\n").encode())
    r = read_reply(f)
    s.close()
    return r


def status(port):
    return request_one(port, {"op": "status"})["status"]


def stop_daemon(proc, port):
    try:
        request_one(port, {"op": "shutdown"})
        proc.wait(timeout=60)
    except Exception:
        proc.kill()               # never leak a daemon
        proc.wait(timeout=30)
        raise


def check_disconnect_survival(port, text):
    """Send a check and hang up before the reply: the daemon must keep
    serving (the batch runs; the reply is dropped, not wedged)."""
    s, _ = connect(port)
    s.sendall(encode(0, text))
    s.close()
    time.sleep(0.2)
    r = request_one(port, {"op": "check", "id": 1, "history": text})
    assert r["ok"], f"daemon broken after client disconnect: {r}"
    return True


def check_overload_burst(backend, text):
    """A burst past a tiny admission queue must draw explicit overload
    replies — and every connection still gets an answer."""
    proc, port = spawn_daemon(backend, ("--max-queue", "4",
                                        "--coalesce-ms", "50",
                                        "--frontier", "64"))
    try:
        n = 16
        conns = [connect(port) for _ in range(n)]
        for i, (s, _) in enumerate(conns):
            s.sendall(encode(i, text))
        replies = [read_reply(f) for _, f in conns]
        for s, _ in conns:
            s.close()
        overloads = [r for r in replies
                     if not r.get("ok") and r.get("error") == "overload"]
        served = [r for r in replies if r.get("ok")]
        assert len(replies) == n, "a connection got no reply"
        assert overloads, "over-capacity burst drew no overload replies"
        assert served, "overload shed everything, served nothing"
        assert request_one(port, {"op": "ping"}).get("pong")
        return len(overloads)
    finally:
        stop_daemon(proc, port)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--backend", default="cpu",
                    choices=["cpu", "tpu", "auto"])
    ap.add_argument("--batch-cap", type=int, default=64)
    ap.add_argument("--frontier", type=int, default=64)
    ap.add_argument("--min-speedup", type=float, default=5.0,
                    help="fail below this coalesced/serial ratio "
                         "(0 disables)")
    ap.add_argument("--tunnel-ms", type=float, default=None,
                    help="injected per-dispatch latency modeling the "
                         "TPU tunnel on CPU (default: 100 on cpu, 0 "
                         "elsewhere; 0 = raw numbers)")
    ap.add_argument("--quick", action="store_true",
                    help="small run, structural assertions only "
                         "(what the test suite uses)")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "BENCH_service.json"))
    ap.add_argument("--store-dir", default=None, metavar="DIR",
                    help="store root the daemon writes its obs "
                         "artifacts into (trace.json/timeline.svg; "
                         "default: ./store, a tmpdir under --quick)")
    args = ap.parse_args()
    if args.store_dir is None:
        args.store_dir = (tempfile.mkdtemp(prefix="bench_service_")
                          if args.quick
                          else os.path.join(REPO, "store"))
    # a persistent store dir may hold a PREVIOUS run's trace: delete
    # it up front so load_trace can only ever validate THIS run's
    # artifact (the daemon's artifact-write failures are log-only)
    stale = os.path.join(args.store_dir, "service", "trace.json")
    if os.path.exists(stale):
        os.unlink(stale)
    if args.tunnel_ms is None:
        args.tunnel_ms = 100.0 if args.backend == "cpu" else 0.0
    if args.quick:
        args.requests = min(args.requests, 16)
        args.min_speedup = 0.0
        args.tunnel_ms = 0.0

    texts = make_requests(args.requests)
    payloads = [encode(i, t) for i, t in enumerate(texts)]
    proc, port = spawn_daemon(args.backend,
                              ("--batch-cap", str(args.batch_cap),
                               "--frontier", str(args.frontier),
                               "--max-queue",
                               str(max(256, 2 * args.requests)),
                               "--coalesce-ms", "25",
                               "--inject-dispatch-latency-ms",
                               str(args.tunnel_ms),
                               # the obs plane rides the benched run:
                               # the trace artifact lands in the
                               # store dir at shutdown, and the <2%
                               # budget means tracing on does not
                               # move the headline numbers
                               "--trace", "--store", args.store_dir))
    try:
        # warm BOTH program classes fully (every bucket's B=1 serial
        # program and every pow2-B coalesced program) so the timed
        # phases compare steady-state serving, not compile time
        run_serial(port, payloads)
        run_coalesced(port, payloads)
        run_serial(port, payloads[:2])

        st0 = status(port)
        serial_s = run_serial(port, payloads)
        st1 = status(port)
        coalesced_s, co_replies = run_coalesced(port, payloads)
        st2 = status(port)
        # the per-stage attribution contract, per request, from the
        # timed coalesced phase's own replies
        stage_checked = assert_stages_tile_wall(co_replies)
        scrape = request_one(port, {"op": "metrics"})
        assert scrape["ok"] and scrape["kind"] == "metrics", scrape
        stages = stage_quantiles(scrape["metrics"])
        assert stages["queue_wait"]["count"] > 0, stages
        assert stages["device"]["count"] > 0, stages
        assert "service_queue_wait_ms_bucket" in scrape["prometheus"]

        n = args.requests
        serial_tp = n / serial_s
        coalesced_tp = n / coalesced_s
        speedup = coalesced_tp / serial_tp

        # dispatch accounting per bucket, from the daemon's own metrics
        def per_bucket(a, b, field):
            return {k: b["buckets"][k][field]
                    - a["buckets"].get(k, {}).get(field, 0)
                    for k in b["buckets"]}

        serial_disp = per_bucket(st0, st1, "dispatches")
        co_disp = per_bucket(st1, st2, "dispatches")
        co_req = per_bucket(st1, st2, "requests")
        for bucket, d in co_disp.items():
            if d == 0:
                continue
            bound = math.ceil(co_req[bucket] / args.batch_cap)
            assert d <= bound, (
                f"bucket {bucket}: {d} coalesced dispatches for "
                f"{co_req[bucket]} requests (bound {bound}) — "
                "coalescing failed")
        survived = check_disconnect_survival(port, texts[0])
        lat = st2["latency_ms"]
    finally:
        stop_daemon(proc, port)

    trace_path, trace_events = load_trace(args.store_dir)
    overloads = check_overload_burst(args.backend, texts[0])

    out = {
        "bench": "service", "backend": args.backend,
        "requests": n, "batch_cap": args.batch_cap,
        "frontier": args.frontier,
        "tunnel_ms_injected": args.tunnel_ms,
        "serial_s": round(serial_s, 4),
        "coalesced_s": round(coalesced_s, 4),
        "serial_req_per_s": round(serial_tp, 1),
        "coalesced_req_per_s": round(coalesced_tp, 1),
        "speedup": round(speedup, 2),
        "serial_dispatches": sum(serial_disp.values()),
        "coalesced_dispatches": sum(co_disp.values()),
        "coalesced_dispatches_per_bucket": co_disp,
        "requests_per_bucket": co_req,
        "latency_ms": lat,
        "stages_ms": stages,
        "stage_sum_checked": stage_checked,
        "trace": {"path": trace_path, "events": trace_events},
        "overload_replies": overloads,
        "survived_disconnect": survived,
        "programs_after_warm": st0["programs"],
        "programs_after_timed": st2["programs"],
    }
    line = json.dumps(out)
    print(line)
    with open(args.out, "w") as fh:
        fh.write(line + "\n")
    # compile-surface closure, via the daemon's own program-key
    # metrics (the daemon is a subprocess, so the in-process compile
    # guard can't see it): after the warm phase every program class
    # this traffic can need exists, so the TIMED phases must compile
    # nothing new — program-set growth in steady state is exactly the
    # recompile storm the bucketed admission exists to prevent.
    # Asserted AFTER the artifact write so a failing run still leaves
    # the diagnostic JSON behind (same order as bench_txn/bench_shrink)
    from comdb2_tpu.utils import compile_guard
    if compile_guard.enabled() and st2["programs"] != st0["programs"]:
        print(f"FAIL: daemon compiled "
              f"{st2['programs'] - st0['programs']} new program(s) "
              "during the timed phases — the bucket ladder is not "
              "closed over this traffic", file=sys.stderr)
        return 1
    if args.min_speedup and speedup < args.min_speedup:
        print(f"FAIL: speedup {speedup:.2f} < {args.min_speedup}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
