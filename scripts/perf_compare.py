#!/usr/bin/env python3
"""Compare single-history device engines on the bench shape (real TPU)
plus the host-ingest paths (legacy per-op vs columnar; CPU-only work).

Usage: PYTHONPATH=$AXON_SITE:. python scripts/perf_compare.py [n_ops]
Reports ops/s for each engine on the 50k-op register history; asserts
every engine reaches the known-correct verdict. The host-ingest
section runs the legacy per-op packer (the ``COMDB2_TPU_LEGACY_PACK=1``
path) against the columnar packer on 3 shapes, asserting bit-identical
streams before trusting either timing.
"""
from __future__ import annotations

import random
import sys
import time


def host_ingest_section() -> None:
    """Legacy per-op vs columnar ingest (pack -> segments -> remap) on
    3 shapes; outputs must match bit-for-bit before timings count."""
    import numpy as np

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.ops.columnar import pack_history_columnar
    from comdb2_tpu.ops.packed import pack_history_legacy
    from comdb2_tpu.ops.synth import register_history

    print("-- host ingest: legacy per-op vs columnar "
          "(pack+segment+remap) --", flush=True)
    for B, events in ((64, 400), (32, 2000), (8, 8000)):
        hs = [register_history(random.Random(9000 + i), n_procs=5,
                               n_events=events, values=5, p_info=0.0)
              for i in range(B)]
        n_inv = sum(1 for h in hs for op in h if op.type == "invoke")

        t0 = time.perf_counter()
        pl = [pack_history_legacy(h) for h in hs]
        sl = [LJ.make_segments_legacy(p) for p in pl]
        rl = [LJ.remap_slots(s) for s in sl]
        dt_legacy = time.perf_counter() - t0

        t0 = time.perf_counter()
        pc = [pack_history_columnar(h) for h in hs]
        sc = [LJ.make_segments(p) for p in pc]
        rc, pes = LJ.remap_slots_batch(sc)
        dt_col = time.perf_counter() - t0

        for (ls, lpe), cs, cpe in zip(rl, rc, pes):
            assert lpe == cpe
            for f in ls._fields:
                assert np.array_equal(getattr(ls, f), getattr(cs, f))
        print(f"ingest {B}x{events:<5d} legacy {n_inv / dt_legacy:9.0f}"
              f" ops/s   columnar {n_inv / dt_col:9.0f} ops/s   "
              f"x{dt_legacy / dt_col:.1f}", flush=True)

    # the bench path goes further: whole-batch columnar GENERATION
    # straight into packed arrays (no Op objects at all)
    from comdb2_tpu.ops import synth_columnar as SC

    t0 = time.perf_counter()
    ps = SC.register_batch_packed(9000, 32, 1000, n_procs=5, values=5)
    segs = [LJ.make_segments(p) for p in ps]
    LJ.remap_slots_batch(segs)
    dt = time.perf_counter() - t0
    n_inv = 32 * 1000
    print(f"ingest 32x2000 columnar-gen {n_inv / dt:9.0f} ops/s   "
          "(arrays end-to-end, the 4096x bench path)", flush=True)


def main() -> None:
    import jax

    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()

    host_ingest_section()

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.models.memo import memo as make_memo
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.packed import pack_history
    from comdb2_tpu.ops.synth import register_history

    n_ops = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    rng = random.Random(42)
    history = register_history(rng, n_procs=5, n_events=2 * n_ops,
                               values=5, p_info=0.0)
    packed = pack_history(history)
    n_inv = sum(1 for op in history if op.type == "invoke")
    mm = make_memo(cas_register(), packed)
    succ = LJ.pad_succ(mm.succ, 64, 64)
    segs = LJ.make_segments(packed)
    S, K = segs.inv_proc.shape
    F, P = 128, 6
    sizes = dict(n_states=mm.n_states, n_transitions=mm.n_transitions)

    def bench(name, fn, check):
        st = fn()
        jax.block_until_ready(st)
        assert check(st), f"{name} misjudged: {st}"
        ts = []
        for _ in range(2):
            t0 = time.perf_counter()
            st = fn()
            jax.block_until_ready(st)
            ts.append(time.perf_counter() - t0)
        dt = min(ts)
        print(f"{name:24s} {n_inv / dt:10.1f} ops/s   ({dt:.3f} s)",
              flush=True)

    def single(st):
        return int(st) == LJ.VALID

    def lane0(st):
        return int(st[0]) == LJ.VALID

    bench("seg", lambda: LJ.check_device_seg(
        succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
        F=F, P=P, **sizes)[0], single)

    for fs in (16, 32, 48):
        bench(f"seg2 Fs={fs}", lambda fs=fs: LJ.check_device_seg2(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
            F=F, Fs=fs, P=P, **sizes)[0], single)

    # B=1 flat engines: seg arrays reshaped to (S, 1, K) / (S, 1)
    ip = segs.inv_proc.reshape(S, 1, K)
    it = segs.inv_tr.reshape(S, 1, K)
    op = segs.ok_proc.reshape(S, 1)
    bench("keys B=1", lambda: LJ.check_device_keys(
        succ, ip, it, op, segs.depth, B=1, F=F, P=P, **sizes)[0], lane0)

    bench("flat B=1", lambda: LJ.check_device_flat(
        succ, ip, it, op, segs.depth, B=1, F=F, P=P, **sizes)[0], lane0)

    # the MXU frontier engine row: owns P >= 16 in the driver ladder
    # (scripts/bench_mxu.py sweeps the crossover); timed at the bench
    # shape so its narrow-P overhead is ON RECORD next to the engines
    # that serve narrow P — its matmul step is P-independent, the win
    # arrives with width (docs/architecture.md "The engine ladder")
    from comdb2_tpu.checker import mxu as MXU

    if MXU.fits(sizes["n_states"], sizes["n_transitions"], P):
        # F rides the engine's declared CAPACITIES rungs — the
        # bench's shared F would compile an off-inventory program
        F_mxu = MXU.bucket_F(F)
        bench("mxu B=1", lambda: MXU.check_device_mxu(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc,
            segs.depth, F=F_mxu, P=P, **sizes)[0], single)
    else:
        print("mxu                     outside the table caps for "
              "this shape", flush=True)

    # the production path: the fused Pallas kernel on slot-renamed
    # segments, at the driver's exact tier choice (even-bucket only
    # while the (8,128) tier serves it — linear._analyze_device)
    from comdb2_tpu.checker import pallas_seg as PSEG

    segs_r, p_eff = LJ.remap_slots(segs)
    p_eff = max(p_eff, 1)
    P2 = max(p_eff + (p_eff & 1), 2)
    P_k = P2 if P2 <= PSEG.ROWS - 1 else p_eff
    fused_ok = (PSEG.available()
                and PSEG.spec_for(sizes["n_states"],
                                  sizes["n_transitions"], P_k, K)
                is not None)
    if fused_ok:
        bench("pallas-fused (renamed)",
              lambda: PSEG.check_device_pallas(
                  mm.succ, segs_r, P=P_k, **sizes)[0], single)
    else:
        print("pallas-fused            unavailable for this "
              "backend/shape", flush=True)

    # the txn closure engine on the serializability axis: one strict-
    # serializability (dense realtime) graph at the 1024 bucket,
    # device closure vs host Tarjan (scripts/bench_txn.py sweeps the
    # full ladder and writes BENCH_txn.json)
    import numpy as np

    from bench_txn import make_graph
    from comdb2_tpu.txn import closure_jax as CJ
    from comdb2_tpu.txn.scc import cyclic_layers_host

    adj = make_graph(random.Random(7), 1024, dense=True)
    CJ.closure_diag(adj)                       # warm the program
    t0 = time.perf_counter()
    dd = CJ.closure_diag(adj)
    dt_dev = time.perf_counter() - t0
    t0 = time.perf_counter()
    dh = cyclic_layers_host(adj, realtime=True)
    dt_host = time.perf_counter() - t0
    assert np.array_equal(dh, dd), "txn engines disagree"
    print(f"{'txn-closure n1024':24s} {dt_dev:10.4f} s   "
          f"(host SCC {dt_host:.4f} s, x{dt_host / dt_dev:.1f})",
          flush=True)


if __name__ == "__main__":
    main()
