#!/usr/bin/env python3
"""Compare single-history device engines on the bench shape (real TPU).

Usage: PYTHONPATH=$AXON_SITE:. python scripts/perf_compare.py [n_ops]
Reports ops/s for each engine on the 50k-op register history; asserts
every engine reaches the known-correct verdict.
"""
from __future__ import annotations

import random
import sys
import time


def main() -> None:
    import jax

    from comdb2_tpu.utils.platform import enable_compile_cache
    enable_compile_cache()

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.models.memo import memo as make_memo
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.packed import pack_history
    from comdb2_tpu.ops.synth import register_history

    n_ops = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    rng = random.Random(42)
    history = register_history(rng, n_procs=5, n_events=2 * n_ops,
                               values=5, p_info=0.0)
    packed = pack_history(history)
    n_inv = sum(1 for op in history if op.type == "invoke")
    mm = make_memo(cas_register(), packed)
    succ = LJ.pad_succ(mm.succ, 64, 64)
    segs = LJ.make_segments(packed)
    S, K = segs.inv_proc.shape
    F, P = 128, 6
    sizes = dict(n_states=mm.n_states, n_transitions=mm.n_transitions)

    def bench(name, fn, check):
        st = fn()
        jax.block_until_ready(st)
        assert check(st), f"{name} misjudged: {st}"
        ts = []
        for _ in range(2):
            t0 = time.perf_counter()
            st = fn()
            jax.block_until_ready(st)
            ts.append(time.perf_counter() - t0)
        dt = min(ts)
        print(f"{name:24s} {n_inv / dt:10.1f} ops/s   ({dt:.3f} s)",
              flush=True)

    def single(st):
        return int(st) == LJ.VALID

    def lane0(st):
        return int(st[0]) == LJ.VALID

    bench("seg", lambda: LJ.check_device_seg(
        succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
        F=F, P=P, **sizes)[0], single)

    for fs in (16, 32, 48):
        bench(f"seg2 Fs={fs}", lambda fs=fs: LJ.check_device_seg2(
            succ, segs.inv_proc, segs.inv_tr, segs.ok_proc, segs.depth,
            F=F, Fs=fs, P=P, **sizes)[0], single)

    # B=1 flat engines: seg arrays reshaped to (S, 1, K) / (S, 1)
    ip = segs.inv_proc.reshape(S, 1, K)
    it = segs.inv_tr.reshape(S, 1, K)
    op = segs.ok_proc.reshape(S, 1)
    bench("keys B=1", lambda: LJ.check_device_keys(
        succ, ip, it, op, segs.depth, B=1, F=F, P=P, **sizes)[0], lane0)

    bench("flat B=1", lambda: LJ.check_device_flat(
        succ, ip, it, op, segs.depth, B=1, F=F, P=P, **sizes)[0], lane0)

    # the production path: the fused Pallas kernel on slot-renamed
    # segments, at the driver's exact tier choice (even-bucket only
    # while the (8,128) tier serves it — linear._analyze_device)
    from comdb2_tpu.checker import pallas_seg as PSEG

    segs_r, p_eff = LJ.remap_slots(segs)
    p_eff = max(p_eff, 1)
    P2 = max(p_eff + (p_eff & 1), 2)
    P_k = P2 if P2 <= PSEG.ROWS - 1 else p_eff
    fused_ok = (PSEG.available()
                and PSEG.spec_for(sizes["n_states"],
                                  sizes["n_transitions"], P_k, K)
                is not None)
    if fused_ok:
        bench("pallas-fused (renamed)",
              lambda: PSEG.check_device_pallas(
                  mm.succ, segs_r, P=P_k, **sizes)[0], single)
    else:
        print("pallas-fused            unavailable for this "
              "backend/shape", flush=True)

    # the txn closure engine on the serializability axis: one strict-
    # serializability (dense realtime) graph at the 1024 bucket,
    # device closure vs host Tarjan (scripts/bench_txn.py sweeps the
    # full ladder and writes BENCH_txn.json)
    import numpy as np

    from bench_txn import make_graph
    from comdb2_tpu.txn import closure_jax as CJ
    from comdb2_tpu.txn.scc import cyclic_layers_host

    adj = make_graph(random.Random(7), 1024, dense=True)
    CJ.closure_diag(adj)                       # warm the program
    t0 = time.perf_counter()
    dd = CJ.closure_diag(adj)
    dt_dev = time.perf_counter() - t0
    t0 = time.perf_counter()
    dh = cyclic_layers_host(adj, realtime=True)
    dt_host = time.perf_counter() - t0
    assert np.array_equal(dh, dd), "txn engines disagree"
    print(f"{'txn-closure n1024':24s} {dt_dev:10.4f} s   "
          f"(host SCC {dt_host:.4f} s, x{dt_host / dt_dev:.1f})",
          flush=True)


if __name__ == "__main__":
    main()
