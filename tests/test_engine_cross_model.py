"""Cross-model engine agreement: device, host, WGL, and brute engines
must agree on models beyond cas-register (mutex, multi-register,
unordered queue)."""

import random

import pytest

from comdb2_tpu.checker import analysis, brute, linear_host, wgl
from comdb2_tpu.models import model as M
from comdb2_tpu.models.memo import memo as make_memo
from comdb2_tpu.ops.op import invoke, ok, fail, info
from comdb2_tpu.ops.packed import pack_history


def _mutex_history(rng, n_procs, n_events):
    """Concurrent acquire/release attempts against a real lock."""
    locked_by = None
    procs = {i: None for i in range(n_procs)}   # in-flight op
    h = []
    while len(h) < n_events:
        p = rng.randrange(n_procs)
        if procs[p] is None:
            f = rng.choice(["acquire", "release"])
            procs[p] = f
            h.append(invoke(p, f, None))
        else:
            f = procs[p]
            procs[p] = None
            if f == "acquire":
                if locked_by is None:
                    locked_by = p
                    h.append(ok(p, f, None))
                else:
                    h.append(fail(p, f, None))
            else:
                if locked_by == p:
                    locked_by = None
                    h.append(ok(p, f, None))
                else:
                    h.append(fail(p, f, None))
    return h


def _queue_history(rng, n_procs, n_events):
    """enqueue/dequeue against a real unordered queue."""
    import collections

    q = collections.deque()
    procs = {i: None for i in range(n_procs)}
    counter = iter(range(10**6))
    h = []
    while len(h) < n_events:
        p = rng.randrange(n_procs)
        if procs[p] is None:
            if rng.random() < 0.5:
                v = next(counter)
                procs[p] = ("enqueue", v)
                h.append(invoke(p, "enqueue", v))
            else:
                procs[p] = ("dequeue", None)
                h.append(invoke(p, "dequeue", None))
        else:
            f, v = procs[p]
            procs[p] = None
            if f == "enqueue":
                q.append(v)
                h.append(ok(p, f, v))
            else:
                if q:
                    got = q.popleft() if rng.random() < 0.5 else q.pop()
                    h.append(ok(p, f, got))
                else:
                    h.append(fail(p, f, None))
    return h


def _multireg_history(rng, n_procs, n_events):
    state = {}
    procs = {i: None for i in range(n_procs)}
    h = []
    keys = ["x", "y"]
    while len(h) < n_events:
        p = rng.randrange(n_procs)
        if procs[p] is None:
            micro = []
            for _ in range(rng.randint(1, 2)):
                k = rng.choice(keys)
                if rng.random() < 0.5:
                    micro.append(("write", k, rng.randrange(3)))
                else:
                    micro.append(("read", k, None))
            procs[p] = micro
            h.append(invoke(p, "txn", tuple(tuple(m) for m in micro)))
        else:
            micro = procs[p]
            procs[p] = None
            filled = []
            for mf, k, v in micro:
                if mf == "write":
                    state[k] = v
                    filled.append(("write", k, v))
                else:
                    filled.append(("read", k, state.get(k)))
            h.append(ok(p, "txn", tuple(filled)))
    return h


CASES = [
    ("mutex", M.mutex, _mutex_history),
    ("unordered-queue", M.unordered_queue, _queue_history),
    ("multi-register", M.multi_register, _multireg_history),
]


@pytest.mark.parametrize("name,mk_model,mk_hist",
                         CASES, ids=[c[0] for c in CASES])
def test_engines_agree_on_valid_histories(name, mk_model, mk_hist):
    for seed in range(6):
        rng = random.Random(9_000 + seed)
        h = mk_hist(rng, 3, 24)
        model = mk_model()
        a_dev = analysis(model, h, backend="device")
        a_host = analysis(model, h, backend="host")
        r_wgl = wgl.analysis(model, h)
        assert a_host.valid is True, (name, seed, a_host.to_map())
        assert a_dev.valid is True, (name, seed)
        assert r_wgl["valid?"] is True, (name, seed)


@pytest.mark.parametrize("name,mk_model,mk_hist",
                         CASES, ids=[c[0] for c in CASES])
def test_engines_agree_on_corrupted_histories(name, mk_model, mk_hist):
    """Corrupt completions; all engines must render the same verdict
    (brute is the oracle on these tiny histories)."""
    corrupted = 0
    for seed in range(8):
        rng = random.Random(17_000 + seed)
        h = mk_hist(rng, 3, 14)
        # corruption: flip a fail->ok when one exists, else falsify an
        # ok completion's observed value (multi-register histories have
        # no fails — a read result is altered instead)
        fails = [i for i, op in enumerate(h) if op.type == "fail"]
        oks = [i for i, op in enumerate(h)
               if op.type == "ok" and op.value is not None]
        if fails:
            i = rng.choice(fails)
            h[i] = h[i].with_(type="ok")
            corrupted += 1
        elif oks:
            i = rng.choice(oks)
            v = h[i].value
            if isinstance(v, tuple) and v and isinstance(v[0], tuple):
                # txn micro-ops: falsify the first micro-op's value
                mf, k, mv = v[0]
                bad = (mf, k, (mv or 0) + 7)
                h[i] = h[i].with_(value=(bad,) + v[1:])
            else:
                h[i] = h[i].with_(value=999)
            corrupted += 1
        model = mk_model()
        want = brute.brute_valid(model, h)
        a_dev = analysis(model, h, backend="device",
                         capacities=(1024,))
        a_host = analysis(model, h, backend="host")
        r_wgl = wgl.analysis(model, h)
        assert a_host.valid == want, (name, seed)
        assert r_wgl["valid?"] == want, (name, seed)
        if a_dev.valid != "unknown":
            assert a_dev.valid == want, (name, seed)
    assert corrupted >= 6, "corruption path barely exercised"
