"""Device workload-checker families (ISSUE 20): post-hoc surface.

Golden twins per family (device verdict bit-agrees with the demoted
host oracle on the seeded-violation generators), one dispatch per pow2
bucket, the over-ladder host route, the DirtyReadsChecker robustness
regressions, the filetest CLI over the checked-in EDN fixtures, and a
compile-guard closure over every wl program the suite launches.
"""

import os

import numpy as np
import pytest

from comdb2_tpu.checker import wl as W
from comdb2_tpu.checker.wl import batch as WLB
from comdb2_tpu.checker.wl.batch import _host_fallback
from comdb2_tpu.ops.op import Op, invoke, ok

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "wl")


# --- golden twins: device == oracle on every seeded generator ---------------

def test_bank_golden_twins():
    for viol in (None, "total", "n"):
        hists, model = W.bank_batch(7, 3, violation=viol)
        dev = W.check_wl_batch(hists, "bank", model)
        host = _host_fallback(hists, "bank", model)
        for d, h, hist in zip(dev, host, hists):
            assert d["valid?"] == h["valid?"], (viol, d, h)
            # same bad reads: the device cites the op INDEX where the
            # oracle embeds the Op itself
            assert len(d["bad-reads"]) == len(h["bad-reads"])
            for db, hb in zip(d["bad-reads"], h["bad-reads"]):
                assert db["type"] == hb["type"]
                assert db["expected"] == hb["expected"]
                assert db["found"] == hb["found"]
                assert hist[db["index"]].value == hb["op"].value
            if viol is not None:
                assert d["valid?"] is False


def test_bank_snapshot_plane_is_diagnostic_only():
    """A fractured-but-balancing read trips the snapshot plane without
    flipping valid? — the oracle has no such plane, so parity demands
    it stays diagnostic."""
    hists, model = W.bank_batch(9, 2, violation="snapshot")
    dev = W.check_wl_batch(hists, "bank", model)
    host = _host_fallback(hists, "bank", model)
    for d, h in zip(dev, host):
        assert d["valid?"] is True and h["valid?"] is True, (d, h)
        assert d["snapshot-inconsistent"], d


def test_sets_golden_twins():
    for viol in (None, "lost", "phantom"):
        hists = W.sets_batch(7, 3, violation=viol)
        dev = W.check_wl_batch(hists, "sets")
        host = _host_fallback(hists, "sets", None)
        for d, h in zip(dev, host):
            assert d["valid?"] == h["valid?"], (viol, d, h)
            # interval-set strings + fractions, bit-identical
            for key in ("ok", "lost", "unexpected", "recovered"):
                assert d[key] == h[key], (viol, key, d, h)
                assert d[f"{key}-frac"] == h[f"{key}-frac"]
            if viol is not None:
                assert d["valid?"] is False
                key = "lost" if viol == "lost" else "unexpected"
                assert d[key] != "#{}", (viol, d)


def test_dirty_golden_twins():
    from comdb2_tpu.checker.checkers import UNKNOWN

    for viol in (None, "dirty", "disagree", "malformed"):
        hists = W.dirty_batch(7, 3, violation=viol)
        dev = W.check_wl_batch(hists, "dirty")
        host = _host_fallback(hists, "dirty", None)
        for d, h in zip(dev, host):
            assert d["valid?"] == h["valid?"], (viol, d, h)
            assert sorted(d["dirty-reads"]) == \
                sorted(tuple(r) for r in h["dirty-reads"])
            assert sorted(d["inconsistent-reads"]) == \
                sorted(tuple(r) for r in h["inconsistent-reads"])
            if viol == "dirty":
                assert d["valid?"] is False and d["dirty-reads"]
            if viol == "disagree":
                # per-node disagreement is diagnostic, not a failure
                assert d["inconsistent-reads"]
            if viol == "malformed":
                assert d["valid?"] is UNKNOWN
                assert d["malformed-reads"] == h["malformed-reads"]


# --- DirtyReadsChecker robustness regressions (satellite 1) -----------------

def test_dirty_oracle_list_payload_no_typeerror():
    """A raw-list read payload (unhashable) used to raise TypeError out
    of the oracle's set build; both engines must now verdict it."""
    hist = [invoke(0, "write", [1, 2]), Op(process=0, type="fail",
                                           f="write", value=[1, 2]),
            ok(1, "read", [[1, 2], [1, 2]])]
    dev = W.check_wl_batch([hist], "dirty")[0]
    host = _host_fallback([hist], "dirty", None)[0]
    assert dev["valid?"] is False and host["valid?"] is False
    assert dev["dirty-reads"] == [tuple(map(tuple, [[1, 2], [1, 2]]))]
    assert dev["dirty-reads"] == \
        [tuple(r) for r in host["dirty-reads"]]


@pytest.mark.parametrize("payload", ["abc", 7])
def test_dirty_oracle_scalar_and_str_reads_are_malformed(payload):
    """A str read would silently iterate per CHARACTER, a scalar not at
    all — both must answer UNKNOWN with the op index, not a verdict."""
    from comdb2_tpu.checker.checkers import UNKNOWN

    hist = [invoke(0, "write", 1), ok(0, "write", 1),
            ok(1, "read", payload)]
    dev = W.check_wl_batch([hist], "dirty")[0]
    host = _host_fallback([hist], "dirty", None)[0]
    assert dev["valid?"] is UNKNOWN and host["valid?"] is UNKNOWN
    assert dev["malformed-reads"] == host["malformed-reads"] == [2]


# --- dispatch accounting ----------------------------------------------------

def test_one_dispatch_per_bucket():
    hists, model = W.bank_batch(19, 6)
    d0 = WLB.DISPATCHES
    out = W.check_wl_batch(hists, "bank", model)
    assert WLB.DISPATCHES - d0 == 1, "6 lanes must share one program"
    assert len(out) == 6 and all(v["valid?"] is True for v in out)

    hists = W.sets_batch(19, 9)
    d0 = WLB.DISPATCHES
    out = W.check_wl_batch(hists, "sets")
    assert WLB.DISPATCHES - d0 == 1, "9 lanes bucket to B=64, one program"
    assert len(out) == 9


def test_over_top_batch_must_chunk():
    with pytest.raises(ValueError, match="chunk first"):
        W.stage_wl_batch([[]] * (WLB.WL_BATCH[-1] + 1), "sets")


def test_host_route_past_ladder():
    """> WL_NODES top node views: the pre-scan returns no dims and the
    finalize routes through the host oracle (same verdict, engine
    attribution)."""
    hist = [invoke(0, "write", 1), ok(0, "write", 1),
            ok(1, "read", tuple([1] * (WLB.WL_NODES[-1] + 4)))]
    assert WLB.wl_dims([hist], "dirty") is None
    d0 = WLB.DISPATCHES
    out = W.check_wl_batch([hist], "dirty")[0]
    assert WLB.DISPATCHES == d0, "host route must not dispatch"
    assert out["engine"] == "host" and out["valid?"] is True, out


def test_bad_args():
    with pytest.raises(ValueError, match="unknown wl family"):
        W.check_wl_batch([[]], "nope")
    with pytest.raises(ValueError, match="bank needs"):
        W.check_wl_batch([[]], "bank")


# --- filetest over the checked-in EDN fixtures ------------------------------

def test_filetest_wl_fixtures():
    from comdb2_tpu import filetest

    bank = ["--checker", "bank", "--wl-n", "8", "--wl-total", "160"]
    cases = [("bank_valid.edn", bank, 0),
             ("bank_wrong_total.edn", bank, 1),
             ("sets_valid.edn", ["--checker", "sets"], 0),
             ("sets_lost.edn", ["--checker", "sets"], 1),
             ("dirty_valid.edn", ["--checker", "dirty"], 0),
             ("dirty_dirty.edn", ["--checker", "dirty"], 1)]
    for name, argv, want in cases:
        path = os.path.join(FIXDIR, name)
        assert filetest.main([path] + argv) == want, name
    # --backend host runs the oracle, same exit codes
    assert filetest.main(
        [os.path.join(FIXDIR, "bank_wrong_total.edn"),
         "--backend", "host"] + bank) == 1
    assert filetest.main(
        [os.path.join(FIXDIR, "dirty_dirty.edn"), "--backend", "host",
         "--checker", "dirty"]) == 1


# --- compile guard closes over the wl programs ------------------------------

def test_wl_programs_in_inventory():
    """Every program this subsystem launches — the three post-hoc
    families plus bank/sets stream solo and fused advances — lowers to
    a PROGRAMS.md-inventoried shape."""
    from comdb2_tpu.stream import wl as SWL
    from comdb2_tpu.stream.engine import MegaBatch
    from comdb2_tpu.utils import compile_guard

    with compile_guard.guard() as g:
        hists, m = W.bank_batch(3, 6)
        W.check_wl_batch(hists, "bank", m)
        W.check_wl_batch(W.sets_batch(3, 6), "sets")
        W.check_wl_batch(W.dirty_batch(3, 6), "dirty")
        s1, s2 = (SWL.make_session("wl-bank", m) for _ in range(2))
        mb = MegaBatch()
        fins = [s.append_stage(list(h), collector=mb)
                for s, h in zip((s1, s2), hists)]
        mb.flush()
        [f() for f in fins]
        s1.append(list(hists[2]))                        # solo
        t1, t2 = (SWL.make_session("wl-sets") for _ in range(2))
        sh = W.sets_batch(5, 3)
        mb2 = MegaBatch()
        fins = [t.append_stage(list(h), collector=mb2)
                for t, h in zip((t1, t2), sh)]
        mb2.flush()
        [f() for f in fins]
        t1.append(list(sh[2]))                           # solo
    offenders = g.offenders()
    assert not offenders, \
        [f"{r.name}: {r.shapes}" for r in offenders]


# --- batch verdict structure ------------------------------------------------

def test_bank_verdict_shape():
    hists, model = W.bank_batch(23, 1, violation="total")
    v = W.check_wl_batch(hists, "bank", model)[0]
    assert v["valid?"] is False
    assert v["first-bad-read"] >= 0
    # the flagged op really disagrees with the model total
    bad = v["bad-reads"][0]
    assert bad["type"] == "wrong-total"
    assert sum(hists[0][bad["index"]].value) == bad["found"]
    assert bad["found"] != int(model["total"])


def test_sets_verdict_shape():
    hists = W.sets_batch(23, 1, violation="phantom")
    v = W.check_wl_batch(hists, "sets")[0]
    assert v["valid?"] is False and v["unexpected"] != "#{}", v
    assert v["lost"] == "#{}", v
