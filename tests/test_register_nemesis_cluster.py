"""The reference's headline test, end to end: ``register-test-nemesis``
is the ONE active deftest in the vendored suite
(``jepsen/test/comdb2/core_test.clj:38-39`` — assert
``(:valid? (:results (jepsen/run! ...)))``), run by ``jepsenloop.sh``
forever on a healed cluster. This is its full in-tree analog:

  provision (SutNodeDB) → 5-node replicated cluster → register workload
  at concurrency 10 ([w cas cas r], core.clj:567-613) with the
  master+1 breaknet nemesis cycling → history → independent-keyed
  linearizable check on the DEVICE engines → perf/timeline artifacts —
  and the verdict must be VALID.
"""

import os
import socket

import pytest

from comdb2_tpu.control.remote import LocalRemote
from comdb2_tpu.harness import core
from comdb2_tpu.harness import generator as G
from comdb2_tpu.harness.provision import SutNodeDB, local_layout
from comdb2_tpu.workloads import comdb2 as W
from comdb2_tpu.workloads.tcp import (ClusterControl,
                                      ClusterPartitioner,
                                      TcpClusterRegisterClient)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_node")

pytestmark = pytest.mark.skipif(not os.path.exists(BINARY),
                                reason="sut_node not built")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_register_tester_nemesis_end_to_end(tmp_path):
    nodes = ["m1", "m2", "m3", "m4", "m5"]     # the reference's fleet
    ports = _free_ports(5)
    db = SutNodeDB(LocalRemote(), BINARY, local_layout(nodes, ports),
                   base_dir=str(tmp_path / "sut"), timeout_ms=300,
                   elect_ms=500, lease_ms=300)
    ctl = ClusterControl(ports)
    # the linearizable check runs the HOST engine here: the history's
    # process width varies run to run (partition-window retirements),
    # so the device path would compile a fresh program every run
    # (CLAUDE.md: per-seed shapes recompile). Device-engine
    # correctness has its own coverage (wide-P host cross-checks,
    # interpret parity, the TPU fuzz); this test is the full
    # provision→cluster→nemesis→verdict loop.
    from comdb2_tpu.checker import checkers as C
    from comdb2_tpu.checker import independent as I
    from comdb2_tpu.report import Timeline, perf_checker

    checker = C.compose({
        "perf": perf_checker(),
        "timeline": Timeline(),
        "linearizable": I.checker(
            C.Linearizable(host_threshold=1 << 20)),
    })
    # the reference cycle is 10 s on / 10 s off over 300 s; compress to
    # two ~1.2 s partition windows in a ~6 s run so CI stays fast while
    # the history still spans faults and failovers
    nemesis_steps = [G.sleep(0.8), {"type": "info", "f": "start"},
                     G.sleep(1.0), {"type": "info", "f": "stop"},
                     G.sleep(0.8), {"type": "info", "f": "start"},
                     G.sleep(1.0), {"type": "info", "f": "stop"}]
    # generous client timeout + retry budget = the reference's
    # ``set max_retries 100000`` (core.clj:92): indeterminate ops stay
    # rare, so the checker's pending set — every :info pends forever —
    # stays searchable (a stingy 0.5 s/3-retry client turned this
    # history into a >4M-config closure)
    t = W.register_tester_nemesis(opts={
        "nodes": nodes,
        "db": db,
        "store-root": str(tmp_path / "store"),
        "client": TcpClusterRegisterClient(ports, timeout_s=1.0,
                                           mutate_retries=8),
        "nemesis": ClusterPartitioner(ctl, isolate_primary=True),
        "checker": checker,
        "generator": G.phases(
            G.nemesis(
                G.seq(nemesis_steps),
                G.time_limit(6.0, G.stagger(0.02, G.clients(
                    G.mix([W.w, W.cas, W.cas, W.r]))))),
            G.log("quiesce"),
            G.sleep(1.0)),
    })
    result = core.run(t)
    ctl.heal()
    res = result["results"]
    assert res["valid?"] is True, res
    assert res["linearizable"]["valid?"] is True, res["linearizable"]
    history = result["history"]
    oks = [op for op in history
           if op.type == "ok" and op.process != "nemesis"]
    infos = [op for op in history
             if op.type == "info" and op.process != "nemesis"]
    # the run must have real throughput AND really have been hurt by
    # the partitions (indeterminate ops / retired processes), like the
    # reference's nemesis runs
    assert len(oks) >= 80, len(oks)
    starts = [op for op in history
              if op.process == "nemesis" and op.f == "start"]
    assert len(starts) >= 2, "nemesis never fired"
    # perf/timeline artifacts rendered alongside the verdict
    assert res["perf"]["valid?"] is True
    assert res["timeline"]["valid?"] is True
