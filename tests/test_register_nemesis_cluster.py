"""The reference's headline test, end to end: ``register-test-nemesis``
is the ONE active deftest in the vendored suite
(``jepsen/test/comdb2/core_test.clj:38-39`` — assert
``(:valid? (:results (jepsen/run! ...)))``), run by ``jepsenloop.sh``
forever on a healed cluster. This is its full in-tree analog:

  provision (SutNodeDB) → 5-node replicated cluster → register workload
  at concurrency 10 ([w cas cas r], core.clj:567-613) with the
  master+1 breaknet nemesis cycling → history → independent-keyed
  linearizable check on the DEVICE engines → perf/timeline artifacts —
  and the verdict must be VALID.
"""

import os
import socket

import pytest

from comdb2_tpu.control.remote import LocalRemote
from comdb2_tpu.harness import core
from comdb2_tpu.harness import generator as G
from comdb2_tpu.harness.provision import SutNodeDB, local_layout
from comdb2_tpu.workloads import comdb2 as W
from comdb2_tpu.workloads.tcp import (ClusterControl,
                                      ClusterPartitioner,
                                      TcpClusterRegisterClient)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_node")

pytestmark = pytest.mark.skipif(not os.path.exists(BINARY),
                                reason="sut_node not built")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_register_tester_nemesis_end_to_end(tmp_path):
    nodes = ["m1", "m2", "m3", "m4", "m5"]     # the reference's fleet
    ports = _free_ports(5)
    db = SutNodeDB(LocalRemote(), BINARY, local_layout(nodes, ports),
                   base_dir=str(tmp_path / "sut"), timeout_ms=300,
                   elect_ms=500, lease_ms=300)
    ctl = ClusterControl(ports)
    # the linearizable verdict comes from the DEVICE engine (round-4
    # Weak #5: this loop had only ever ended in a host verdict). The
    # per-run shape variance that used to force host — process width
    # moves with partition-window retirements — is gone: slot renaming
    # (LJ.remap_slots) caps the slot axis at max concurrent open
    # calls, and the driver's pow2/even shape buckets bound the
    # remaining compile variety (persistent-cached across runs).
    from comdb2_tpu.checker import checkers as C
    from comdb2_tpu.checker import independent as I
    from comdb2_tpu.report import Timeline, perf_checker

    checker = C.compose({
        "perf": perf_checker(),
        "timeline": Timeline(),
        "linearizable": I.checker(C.Linearizable()),
    })
    # the reference cycle is 10 s on / 10 s off over 300 s; compress to
    # two ~1.2 s partition windows in a ~6 s run so CI stays fast while
    # the history still spans faults and failovers
    nemesis_steps = [G.sleep(0.8), {"type": "info", "f": "start"},
                     G.sleep(1.0), {"type": "info", "f": "stop"},
                     G.sleep(0.8), {"type": "info", "f": "start"},
                     G.sleep(1.0), {"type": "info", "f": "stop"}]
    # generous client timeout + retry budget = the reference's
    # ``set max_retries 100000`` (core.clj:92): indeterminate ops stay
    # rare, so the checker's pending set — every :info pends forever —
    # stays searchable (a stingy 0.5 s/3-retry client turned this
    # history into a >4M-config closure)
    t = W.register_tester_nemesis(opts={
        "nodes": nodes,
        "db": db,
        "store-root": str(tmp_path / "store"),
        "client": TcpClusterRegisterClient(ports, timeout_s=1.0,
                                           mutate_retries=8),
        "nemesis": ClusterPartitioner(ctl, isolate_primary=True),
        "checker": checker,
        "generator": G.phases(
            G.nemesis(
                G.seq(nemesis_steps),
                G.time_limit(6.0, G.stagger(0.02, G.clients(
                    G.mix([W.w, W.cas, W.cas, W.r]))))),
            G.log("quiesce"),
            G.sleep(1.0)),
    })
    result = core.run(t)
    ctl.heal()
    res = result["results"]
    assert res["valid?"] is True, res
    assert res["linearizable"]["valid?"] is True, res["linearizable"]
    # the flagship verdict really ended on the device engine
    (key_res,) = res["linearizable"]["results"].values()
    assert key_res.get("backend") == "device", key_res
    assert key_res.get("engine") in ("xla-seg2", "pallas-fused"), key_res
    assert key_res.get("effective_slots", 99) <= 16, key_res
    history = result["history"]
    oks = [op for op in history
           if op.type == "ok" and op.process != "nemesis"]
    infos = [op for op in history
             if op.type == "info" and op.process != "nemesis"]
    # the run must have real throughput AND really have been hurt by
    # the partitions (indeterminate ops / retired processes), like the
    # reference's nemesis runs
    assert len(oks) >= 80, len(oks)
    starts = [op for op in history
              if op.process == "nemesis" and op.f == "start"]
    assert len(starts) >= 2, "nemesis never fired"
    # perf/timeline artifacts rendered alongside the verdict
    assert res["perf"]["valid?"] is True
    assert res["timeline"]["valid?"] is True

    # the PRODUCTION kernel agrees: re-check the flagship history
    # through the fused Pallas kernel in interpret mode at a FIXED
    # padded spec — segments to a pow2 bucket, K to 8, slots to 14,
    # the successor table to (8, 48) — so the compiled program is
    # byte-identical across runs regardless of history variance (the
    # interpret compile is paid once ever, then rides the persistent
    # cache). Fault-window closures can legitimately exceed the
    # kernel's fixed F=128 (the production driver escalates those to
    # the XLA ladder — the primary verdict above), so the parity
    # contract is the fuzz one: kernel vs the XLA engine AT THE SAME
    # CAPACITY, bit-identical status + fail segment (+ count when
    # VALID). Skipped only when a fault window packed more than 8
    # invokes into one segment (the kernel's K bound).
    import numpy as np

    from comdb2_tpu.checker import independent as I2
    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.checker import pallas_seg as PSEG
    from comdb2_tpu.models import model as M
    from comdb2_tpu.models.memo import memo as make_memo
    from comdb2_tpu.ops.packed import pack_history

    sub = I2.subhistory(1, history)     # client values are KVTuples
    packed = pack_history([op for op in sub if op.process != "nemesis"])
    mm = make_memo(M.cas_register(), packed)
    segs = LJ.make_segments(packed)
    from comdb2_tpu.utils import next_pow2
    K_real = segs.inv_proc.shape[1]
    S_real = segs.ok_proc.shape[0]
    # S cap 1024, not the kernel's 2048: the cross-check pads to pow2
    # buckets so the interpret compile is paid once per bucket, and
    # the 512/1024 buckets compile in ~30 s — but the 2048-bucket
    # interpret program measured >17 CPU-MINUTES and ~14.5 GB RSS to
    # compile (the LLVM blowup regime), which can never fit the tier-1
    # budget. A >1024-ok single-key history only happens on an idle
    # machine's fastest runs; those skip the cross-check exactly like
    # the K>8 fault-window case (the primary device verdict above
    # still covers them).
    runnable = K_real <= 8 and S_real <= 1024
    print(f"[flagship] kernel cross-check: K={K_real} S={S_real} "
          f"{'RUN' if runnable else 'SKIP (over kernel bounds)'}")
    if runnable:
        segs = LJ.make_segments(packed,
                                s_pad=next_pow2(S_real, 512), k_pad=8)
        segs, P_eff2 = LJ.remap_slots(segs)
        assert P_eff2 <= 14, P_eff2
        assert mm.n_states <= 8 and mm.n_transitions <= 48, (
            mm.n_states, mm.n_transitions)
        succ_pad = np.full((8, 48), -1, np.int32)
        succ_pad[:mm.n_states, :mm.n_transitions] = mm.succ
        PSEG.use_interpret(True)
        try:
            r = PSEG.check_device_pallas(succ_pad, segs, n_states=8,
                                         n_transitions=48, P=14)
        finally:
            PSEG.use_interpret(False)
        assert r is not None, "fixed spec must be kernel-eligible"
        x = LJ.check_device_seg2(
            LJ.pad_succ(succ_pad, 8, 64), segs.inv_proc, segs.inv_tr,
            segs.ok_proc, segs.depth, F=128, Fs=32, P=14,
            n_states=8, n_transitions=48)
        x = tuple(int(v) for v in x)
        print(f"[flagship] kernel={r} xla@128={x}")
        assert r[0] == x[0], (r, x)
        assert r[1] == x[1], (r, x)            # same fail segment
        if r[0] == LJ.VALID:
            assert r[2] == x[2], (r, x)
        else:
            # overflow is legitimate under fault windows, but the
            # HISTORY itself is linearizable — the primary device
            # verdict at the escalated capacity said so above
            assert r[0] == LJ.UNKNOWN, r
