"""Transactions over the wire: bank and G2 against the replicated SUT.

Round-2 VERDICT Missing #2: the flagship serializability workloads
(bank transfers, Adya G2) only ever ran against the in-memory sqlish
backend — they never crossed a network or met a partition. sut_node now
speaks a begin/read/predicate/write/insert/commit transaction surface
with server-side OCC validation at commit (the db/toblock.c:1953 role:
reads record versions, the commit validates them against the log-order
state and applies all writes as one atomic entry). ``--buggy-txn`` (-T)
commits WITHOUT validation — the lost-update / G2-anomaly control the
bank and G2 checkers must catch."""

import os
import socket
import time

import pytest

from comdb2_tpu.checker.workloads import bank_checker, g2_checker
from comdb2_tpu.harness import core, fake
from comdb2_tpu.harness import generator as G
from comdb2_tpu.ops.op import Op
from comdb2_tpu.workloads import comdb2 as W
from comdb2_tpu.workloads.tcp import (BankTcpClient, ClusterControl,
                                      ClusterPartitioner, ClusterTxn,
                                      G2TcpClient, SutConnection,
                                      spawn_cluster)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_node")

pytestmark = pytest.mark.skipif(not os.path.exists(BINARY),
                                reason="sut_node not built")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _kill(procs):
    for p in procs:
        p.kill()
    for p in procs:
        p.wait()


def _conn(port, timeout=2.0):
    c = SutConnection("127.0.0.1", port, timeout_s=timeout)
    c.connect()
    return c


def test_txn_commit_applies_atomically():
    """begin / read / write / commit; both writes land atomically and
    are visible to plain reads and later txns."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800)
    conn = _conn(ports[0])
    try:
        t = ClusterTxn(conn)
        t.begin()
        assert t.read(1) is None
        t.write(1, 10)
        t.write(2, 20)
        assert t.commit() == "ok"
        assert conn.request("R 1") == "V 10"
        assert conn.request("R 2") == "V 20"
        t2 = ClusterTxn(conn)
        t2.begin()
        assert t2.read(1) == 10
        assert t2.read(2) == 20
        assert t2.commit() == "ok"       # read-only commit point
    finally:
        conn.close()
        _kill(procs)


def test_txn_occ_conflict_aborts_second():
    """Two interleaved txns reading the same key: the first commit
    wins, the second fails validation (its read version moved) — the
    write-write/read-write conflict rule that keeps transfers
    serializable. With -T (buggy) BOTH commit: the lost update."""
    for buggy in (False, True):
        ports = _free_ports(3)
        procs = spawn_cluster(BINARY, ports, durable=True,
                              timeout_ms=800,
                              flags=["-T"] if buggy else [])
        conn = _conn(ports[0])
        try:
            t0 = ClusterTxn(conn)
            t0.begin()
            t0.write(1, 100)
            assert t0.commit() == "ok"

            t1 = ClusterTxn(conn)
            t1.begin()
            b1 = t1.read(1)
            t2 = ClusterTxn(conn)
            t2.begin()
            b2 = t2.read(1)
            assert b1 == b2 == 100
            t1.write(1, b1 - 30)
            t2.write(1, b2 - 50)
            assert t1.commit() == "ok"
            second = t2.commit()
            if buggy:
                assert second == "ok"        # lost update committed
                assert conn.request("R 1") == "V 50"
            else:
                assert second == "fail"      # validation caught it
                assert conn.request("R 1") == "V 70"
        finally:
            conn.close()
            _kill(procs)


def test_txn_predicate_phantom_detected():
    """G2's dangerous interleaving at the protocol level: two txns
    predicate-read (a, k) and (b, k) as empty, both insert. With
    validation the second commit fails (the predicate's version
    moved — phantom detection); with -T both commit and the G2 checker
    flags the key."""
    for buggy in (False, True):
        ports = _free_ports(3)
        procs = spawn_cluster(BINARY, ports, durable=True,
                              timeout_ms=800,
                              flags=["-T"] if buggy else [])
        conn = _conn(ports[0])
        try:
            k = 7
            t1 = ClusterTxn(conn)
            t1.begin()
            assert t1.predicate("a", k) == []
            assert t1.predicate("b", k) == []
            t2 = ClusterTxn(conn)
            t2.begin()
            assert t2.predicate("a", k) == []
            assert t2.predicate("b", k) == []
            t1.insert("a", k, 1, 30)
            t2.insert("b", k, 2, 30)
            assert t1.commit() == "ok"
            second = t2.commit()

            outcomes = [("ok" if second == "ok" else "fail")]
            history = [
                Op(process=0, type="invoke", f="insert",
                   value=(k, (1, None)), time=0),
                Op(process=0, type="ok", f="insert",
                   value=(k, (1, None)), time=1),
                Op(process=1, type="invoke", f="insert",
                   value=(k, (None, 2)), time=2),
                Op(process=1, type=outcomes[0], f="insert",
                   value=(k, (None, 2)), time=3),
            ]
            res = g2_checker.check(None, None, history)
            if buggy:
                assert second == "ok"
                assert res["valid?"] is False, res
            else:
                assert second == "fail"
                assert res["valid?"] is True, res
        finally:
            conn.close()
            _kill(procs)


def _bank_test(tmp_path, ports, name, n=5, **kw):
    t = fake.noop_test()
    t.update({
        "nodes": [], "concurrency": 5, "name": name,
        "store-root": str(tmp_path / "store"),
        "client": BankTcpClient(ports, n=n, timeout_s=0.6),
        "model": {"n": n, "total": n * 10},
        "_bank_n": n,
        "generator": G.clients(G.time_limit(4.0, G.stagger(
            0.01, G.mix([W.bank_read, W.bank_diff_transfer])))),
        "checker": bank_checker,
    })
    t.update(kw)
    return t


def test_bank_over_cluster_valid(tmp_path):
    """Total balance holds over the durable cluster with no faults."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500)
    try:
        t = _bank_test(tmp_path, ports, "bank-cluster")
        result = core.run(t)
        assert result["results"]["valid?"] is True, result["results"]
        reads = [op for op in result["history"]
                 if op.type == "ok" and op.f == "read"]
        xfers = [op for op in result["history"]
                 if op.type == "ok" and op.f == "transfer"]
        assert len(reads) >= 20 and len(xfers) >= 10, \
            (len(reads), len(xfers))
    finally:
        _kill(procs)


def test_bank_over_cluster_valid_under_partition(tmp_path):
    """The VERDICT #2 done-criterion: the bank total-balance invariant
    holds over the durable cluster under partition windows that force
    failovers — conflicted/raced transfers abort or go indeterminate,
    never half-apply."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=300,
                          elect_ms=500, lease_ms=300)
    try:
        ctl = ClusterControl(ports)
        nemesis_steps = [G.sleep(0.5), {"type": "info", "f": "start"},
                         G.sleep(1.2), {"type": "info", "f": "stop"},
                         G.sleep(0.6), {"type": "info", "f": "start"},
                         G.sleep(1.2), {"type": "info", "f": "stop"}]
        t = _bank_test(
            tmp_path, ports, "bank-cluster-nemesis",
            nemesis=ClusterPartitioner(ctl, isolate_primary=True),
            generator=G.nemesis(
                G.seq(nemesis_steps),
                G.time_limit(5.5, G.stagger(
                    0.01, G.mix([W.bank_read, W.bank_diff_transfer])))))
        result = core.run(t)
        ctl.heal()
        assert result["results"]["valid?"] is True, result["results"]
        reads = [op for op in result["history"]
                 if op.type == "ok" and op.f == "read"]
        assert len(reads) >= 10, len(reads)
    finally:
        _kill(procs)


def test_bank_buggy_txn_control_detected(tmp_path):
    """-T control end to end: commits skip validation, concurrent
    transfers race and lose updates, and reads observe totals drifting
    off the invariant — the bank checker must flag it. The harness run
    races real threads, so drive the deterministic interleaving too."""
    # deterministic: two transfers sharing exactly ONE account (0->1
    # and 1->2). Both read account 1 at the same snapshot; without
    # validation the second commit blindly overwrites account 1 with
    # its stale computation and the cluster-wide total drifts — two
    # transfers over the SAME pair would each rewrite a self-consistent
    # pair and the sum invariant could never see the lost update.
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800,
                          flags=["-T"])
    conn = _conn(ports[0])
    try:
        init = ClusterTxn(conn)
        init.begin()
        for i in range(3):
            init.write(i, 10)
        assert init.commit() == "ok"
        t1 = ClusterTxn(conn)
        t1.begin()
        a0, a1 = t1.read(0), t1.read(1)
        t2 = ClusterTxn(conn)
        t2.begin()
        b1, b2 = t2.read(1), t2.read(2)
        assert a1 == b1 == 10
        t1.write(0, a0 - 5)
        t1.write(1, a1 + 5)          # account 1 -> 15
        t2.write(1, b1 - 3)          # stale: 10 - 3, clobbers the 15
        t2.write(2, b2 + 3)
        assert t1.commit() == "ok"
        assert t2.commit() == "ok"       # buggy: no validation
        rd = ClusterTxn(conn)
        rd.begin()
        balances = tuple(rd.read(i) for i in range(3))
        rd.commit()
        history = [
            Op(process=0, type="invoke", f="read", value=None, time=0),
            Op(process=0, type="ok", f="read", value=balances, time=1),
        ]
        res = bank_checker.check(None, {"n": 3, "total": 30}, history)
        assert sum(balances) != 30, balances
        assert res["valid?"] is False, (balances, res)
    finally:
        conn.close()
        _kill(procs)


def test_g2_over_cluster_valid(tmp_path):
    """The real G2 workload (concurrent keys, two inserts per key)
    over the wire: at most one insert commits per key."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500)
    try:
        t = fake.noop_test()
        t.update({
            "nodes": [], "concurrency": 6, "name": "g2-cluster",
            "store-root": str(tmp_path / "store"),
            "client": G2TcpClient(ports, timeout_s=0.6),
            "model": None,
            "generator": G.clients(G.time_limit(4.0, W.g2_gen())),
            "checker": g2_checker,
        })
        result = core.run(t)
        res = result["results"]
        assert res["valid?"] is True, res
        # the checker must have actually COUNTED committed inserts —
        # a valid verdict over zero counted keys is vacuous (an ok op
        # whose value was dropped would silently skip the count)
        assert res["legal-count"] >= 5, res
    finally:
        _kill(procs)


# --- dirty reads over the cluster (round-3 VERDICT #5) ----------------------
#
# Before this round DirtyReadsClient only ever drove the in-memory
# MemConn backend (workloads/comdb2.py:213-274); the cluster had the
# txn verbs all along. -R (dirty-commit) is the matching negative
# control: a validation conflict still applies the txn but reports
# FAIL — the effects-misclassification bug the reference's dirty-reads
# test exists to catch (a failed write's value visible,
# comdb2/core.clj:492-523).

from comdb2_tpu.checker.workloads import dirty_reads_checker
from comdb2_tpu.checker.checkers import counter as counter_checker
from comdb2_tpu.workloads.tcp import (CounterTcpClient,
                                      DirtyReadsTcpClient)


def test_dirty_reads_over_cluster_valid(tmp_path):
    """Correct cluster: no failed write's value is ever read, and all
    committed reads are uniform (OCC validation aborts torn reads)."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500)
    try:
        t = fake.noop_test()
        t.update({
            "nodes": [], "concurrency": 5, "name": "dirty-cluster",
            "store-root": str(tmp_path / "store"),
            "client": DirtyReadsTcpClient(ports, n=4, timeout_s=0.6),
            "model": None,
            "generator": G.clients(G.time_limit(4.0, G.stagger(
                0.01, G.mix([W.dirty_reads_read, W._DirtyWrites()])))),
            "checker": dirty_reads_checker,
        })
        result = core.run(t)
        res = result["results"]
        assert res["valid?"] is True, res
        assert res["inconsistent-reads"] == [], res
        reads = [op for op in result["history"]
                 if op.type == "ok" and op.f == "read"]
        assert len(reads) >= 10, len(reads)
    finally:
        _kill(procs)


def test_dirty_reads_dirty_commit_control_detected():
    """-R end to end, deterministic interleaving: writer W2 conflicts
    with W1, the server applies W2's rows anyway and reports FAIL; a
    read then observes the failed write's value — the checker must
    flag it."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800,
                          flags=["-R"])
    conn = _conn(ports[0])
    try:
        base, n = 10_000, 3
        init = ClusterTxn(conn)
        init.begin()
        for i in range(n):
            init.write(base + i, -1)
        assert init.commit() == "ok"

        t1 = ClusterTxn(conn)
        t1.begin()
        t2 = ClusterTxn(conn)
        t2.begin()
        for i in range(n):
            t1.read(base + i)
            t2.read(base + i)
        for i in range(n):
            t1.write(base + i, 7)
            t2.write(base + i, 8)
        assert t1.commit() == "ok"
        second = t2.commit()
        assert second == "fail"          # the lie: it actually applied

        rd = ClusterTxn(conn)
        rd.begin()
        seen = tuple(rd.read(base + i) for i in range(n))
        rd.commit()
        assert seen == (8, 8, 8), seen   # failed write visible

        history = [
            Op(process=0, type="invoke", f="write", value=7, time=0),
            Op(process=0, type="ok", f="write", value=7, time=1),
            Op(process=1, type="invoke", f="write", value=8, time=2),
            Op(process=1, type="fail", f="write", value=8, time=3),
            Op(process=2, type="invoke", f="read", value=None, time=4),
            Op(process=2, type="ok", f="read", value=seen, time=5),
        ]
        res = dirty_reads_checker.check(None, None, history)
        assert res["valid?"] is False, res
        assert res["dirty-reads"], res

        # -R alters WRITE-txn reporting only: a conflicted READ-ONLY
        # txn has nothing to dirty-apply and must keep failing cleanly
        # instead of committing a torn read snapshot as OK (ADVICE r4)
        w = ClusterTxn(conn)
        ro = ClusterTxn(conn)
        ro.begin()
        ro.read(base)                    # records version
        w.begin()
        w.write(base, 9)
        assert w.commit() == "ok"        # bumps the version under ro
        assert ro.commit() == "fail"
    finally:
        conn.close()
        _kill(procs)


def _dirty_interleave(conn, base=10_000, n=3):
    """The deterministic -R interleaving: W1 and W2 conflict, the
    second commit's reported verdict + the rows a follow-up read sees
    are returned (the dirty-commit lie shows up as ('fail', the
    LOSER's values))."""
    init = ClusterTxn(conn)
    init.begin()
    for i in range(n):
        init.write(base + i, -1)
    assert init.commit() == "ok"
    t1 = ClusterTxn(conn)
    t1.begin()
    t2 = ClusterTxn(conn)
    t2.begin()
    for i in range(n):
        t1.read(base + i)
        t2.read(base + i)
    for i in range(n):
        t1.write(base + i, 7)
        t2.write(base + i, 8)
    assert t1.commit() == "ok"
    second = t2.commit()
    rd = ClusterTxn(conn)
    rd.begin()
    seen = tuple(rd.read(base + i) for i in range(n))
    rd.commit()
    return second, seen


def _dirty_wl_history(second, seen):
    return [
        Op(process=0, type="invoke", f="write", value=7, time=0),
        Op(process=0, type="ok", f="write", value=7, time=1),
        Op(process=1, type="invoke", f="write", value=8, time=2),
        Op(process=1, type=("ok" if second == "ok" else "fail"),
           f="write", value=8, time=3),
        Op(process=2, type="invoke", f="read", value=None, time=4),
        Op(process=2, type="ok", f="read", value=seen, time=5),
    ]


def test_dirty_commit_through_wl_family_end_to_end():
    """ISSUE-20 satellite: the cluster's -R dirty-commit control
    detected by the DEVICE dirty-reads family (kind the service
    serves), not just the host oracle — and the healthy cluster's
    twin run checks VALID through the same path. Device and oracle
    must bit-agree on both."""
    from comdb2_tpu.checker.wl import check_wl_batch
    from comdb2_tpu.checker.workloads import dirty_reads_checker

    # -R cluster: the conflicted write reports FAIL but applies
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800,
                          flags=["-R"])
    conn = _conn(ports[0])
    try:
        second, seen = _dirty_interleave(conn)
        assert second == "fail" and seen == (8, 8, 8), (second, seen)
        history = _dirty_wl_history(second, seen)
        dev = check_wl_batch([history], "dirty")[0]
        host = dirty_reads_checker.check(None, None, history)
        assert dev["valid?"] is False, dev
        assert dev["dirty-reads"], dev
        assert dev["valid?"] == host["valid?"]
        assert sorted(dev["dirty-reads"]) == \
            sorted(tuple(r) for r in host["dirty-reads"])
    finally:
        conn.close()
        _kill(procs)

    # healthy twin: OCC really aborts the loser — same probe, VALID
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800)
    conn = _conn(ports[0])
    try:
        second, seen = _dirty_interleave(conn, base=20_000)
        assert second == "fail" and seen == (7, 7, 7), (second, seen)
        history = _dirty_wl_history(second, seen)
        dev = check_wl_batch([history], "dirty")[0]
        host = dirty_reads_checker.check(None, None, history)
        assert dev["valid?"] is True, dev
        assert host["valid?"] is True, host
        assert dev["dirty-reads"] == [] \
            and dev["inconsistent-reads"] == []
    finally:
        conn.close()
        _kill(procs)


# --- counter over the cluster (round-3 VERDICT #5) --------------------------

def _counter_add(test=None, process=None):
    import random as _random

    return {"type": "invoke", "f": "add",
            "value": _random.randint(1, 5)}


def _counter_read(test=None, process=None):
    return {"type": "invoke", "f": "read", "value": None}


def test_counter_over_cluster_valid(tmp_path):
    """checker.clj:220-272 semantics over the wire: every committed
    read falls within [sum of acked adds at invoke, sum of attempted
    adds at completion]."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500)
    try:
        t = fake.noop_test()
        t.update({
            "nodes": [], "concurrency": 5, "name": "counter-cluster",
            "store-root": str(tmp_path / "store"),
            "client": CounterTcpClient(ports, timeout_s=0.6),
            "model": None,
            "generator": G.clients(G.time_limit(4.0, G.stagger(
                0.01, G.mix([_counter_add, _counter_read])))),
            "checker": counter_checker,
        })
        result = core.run(t)
        res = result["results"]
        assert res["valid?"] is True, res
        assert len(res["reads"]) >= 10, res
        adds = [op for op in result["history"]
                if op.type == "ok" and op.f == "add"]
        assert len(adds) >= 10, len(adds)
    finally:
        _kill(procs)


def test_counter_buggy_txn_lost_update_detected():
    """-T end to end, deterministic: two adds read the same snapshot,
    both commit (no validation), one increment is lost; a later read
    sits below the checker's lower bound."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800,
                          flags=["-T"])
    conn = _conn(ports[0])
    try:
        key = CounterTcpClient.KEY
        t1 = ClusterTxn(conn)
        t1.begin()
        a = t1.read(key) or 0
        t2 = ClusterTxn(conn)
        t2.begin()
        b = t2.read(key) or 0
        t1.write(key, a + 5)
        t2.write(key, b + 5)
        assert t1.commit() == "ok"
        assert t2.commit() == "ok"       # -T: lost update commits
        rd = ClusterTxn(conn)
        rd.begin()
        v = rd.read(key)
        rd.commit()
        assert v == 5, v                 # one add lost

        history = [
            Op(process=0, type="invoke", f="add", value=5, time=0),
            Op(process=0, type="ok", f="add", value=5, time=1),
            Op(process=1, type="invoke", f="add", value=5, time=2),
            Op(process=1, type="ok", f="add", value=5, time=3),
            Op(process=2, type="invoke", f="read", value=None, time=4),
            Op(process=2, type="ok", f="read", value=v, time=5),
        ]
        res = counter_checker.check(None, None, history)
        assert res["valid?"] is False, res
    finally:
        conn.close()
        _kill(procs)


# --- list-append + dependency-graph checker (the txn/ subsystem) ------------
#
# The graph checker sees what the bespoke per-flag checkers cannot:
# one engine classifies ANY ww/wr/rw cycle (G0 / G1c / G2-item) and
# the direct anomalies (G1a, duplicates), so the -T and -R negative
# controls get their verdicts from first principles. Per CLAUDE.md
# the interleavings are driven exactly — no stochastic retries.

from comdb2_tpu.checker.checkers import Serializable
from comdb2_tpu.checker.workloads import (dirty_reads_composed,
                                          g2_composed)
from comdb2_tpu.txn import check_txn
from comdb2_tpu.workloads.tcp import ListAppendTcpClient


def test_list_append_over_cluster_valid(tmp_path):
    """Clean -e 500 -l 300 cluster: the harness list-append workload
    passes the dependency-graph checker (acceptance criterion)."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500,
                          elect_ms=500, lease_ms=300)
    try:
        from comdb2_tpu.workloads import comdb2 as W

        t = fake.noop_test()
        t.update({
            "nodes": [], "concurrency": 5, "name": "la-cluster",
            "store-root": str(tmp_path / "store"),
            "client": ListAppendTcpClient(ports, timeout_s=0.6),
            "model": None,
            "generator": G.clients(G.time_limit(4.0, G.stagger(
                0.01, W.list_append_gen(n_keys=3)))),
            "checker": Serializable(backend="host"),
        })
        result = core.run(t)
        res = result["results"]
        assert res["valid?"] is True, res
        assert res["txn-count"] >= 20, res
        assert res["edge-count"] >= 10, res
    finally:
        _kill(procs)


def test_buggy_txn_control_yields_g2_cycle():
    """-T end to end, deterministic write skew: both txns read the
    other's key as empty, both append, both commit (validation
    skipped). The graph checker must find the rw/rw cycle and class
    it G2-item; the same interleaving on a correct cluster must
    abort one txn and check valid."""
    for buggy in (True, False):
        ports = _free_ports(3)
        procs = spawn_cluster(BINARY, ports, durable=True,
                              timeout_ms=800,
                              flags=["-T"] if buggy else [])
        conn = _conn(ports[0])
        try:
            t1 = ClusterTxn(conn)
            t1.begin()
            r1 = tuple(v for _r, v in t1.predicate(
                "a", ListAppendTcpClient.BASE + 0))
            t2 = ClusterTxn(conn)
            t2.begin()
            r2 = tuple(v for _r, v in t2.predicate(
                "a", ListAppendTcpClient.BASE + 1))
            assert r1 == r2 == ()
            t1.insert("a", ListAppendTcpClient.BASE + 1, 1, 1)
            t2.insert("a", ListAppendTcpClient.BASE + 0, 2, 2)
            assert t1.commit() == "ok"
            second = t2.commit()

            rd = ClusterTxn(conn)
            rd.begin()
            fx = tuple(v for _r, v in rd.predicate(
                "a", ListAppendTcpClient.BASE + 0))
            fy = tuple(v for _r, v in rd.predicate(
                "a", ListAppendTcpClient.BASE + 1))
            rd.commit()

            hist = [
                Op(0, "invoke", "txn", (("r", 0, None),
                                        ("append", 1, 1))),
                Op(0, "ok", "txn", (("r", 0, r1), ("append", 1, 1))),
                Op(1, "invoke", "txn", (("r", 1, None),
                                        ("append", 0, 2))),
                Op(1, "ok" if second == "ok" else "fail", "txn",
                   (("r", 1, r2), ("append", 0, 2))),
                Op(2, "invoke", "txn", (("r", 0, None),
                                        ("r", 1, None))),
                Op(2, "ok", "txn", (("r", 0, fx), ("r", 1, fy))),
            ]
            res = check_txn(hist, backend="host")
            if buggy:
                assert second == "ok"
                assert fx == (2,) and fy == (1,)
                assert res["valid?"] is False, res
                assert res["counterexample"]["class"] == "G2-item", res
                types = {s["edge"]["type"]
                         for s in res["counterexample"]["cycle"]}
                assert types == {"rw"}
            else:
                assert second == "fail"      # validation caught it
                assert res["valid?"] is True, res
        finally:
            conn.close()
            _kill(procs)


def test_dirty_commit_control_yields_g1a_and_cycle():
    """-R end to end, deterministic: t2 conflicts with t1, the server
    applies t2's append anyway while reporting FAIL; a later read
    observes it. The graph checker must flag G1a (aborted read) AND
    the lost-update cycle through the dirty txn (ww + rw = G2-item,
    the strongest cycle an atomic-commit OCC server can produce —
    docs/serializability.md explains why honest G1c cannot arise
    here)."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=800,
                          flags=["-R"])
    conn = _conn(ports[0])
    try:
        k = ListAppendTcpClient.BASE + 5
        t1 = ClusterTxn(conn)
        t1.begin()
        r1 = tuple(v for _r, v in t1.predicate("a", k))
        t2 = ClusterTxn(conn)
        t2.begin()
        r2 = tuple(v for _r, v in t2.predicate("a", k))
        assert r1 == r2 == ()
        t1.insert("a", k, 1, 1)
        t2.insert("a", k, 2, 2)
        assert t1.commit() == "ok"
        assert t2.commit() == "fail"     # the lie: it actually applied

        rd = ClusterTxn(conn)
        rd.begin()
        seen = tuple(v for _r, v in rd.predicate("a", k))
        rd.commit()
        assert seen == (1, 2), seen      # failed append visible

        hist = [
            Op(0, "invoke", "txn", (("r", 5, None), ("append", 5, 1))),
            Op(0, "ok", "txn", (("r", 5, r1), ("append", 5, 1))),
            Op(1, "invoke", "txn", (("r", 5, None), ("append", 5, 2))),
            Op(1, "fail", "txn", (("r", 5, r2), ("append", 5, 2))),
            Op(2, "invoke", "txn", (("r", 5, None),)),
            Op(2, "ok", "txn", (("r", 5, seen),)),
        ]
        res = check_txn(hist, backend="host")
        assert res["valid?"] is False, res
        assert any(a["name"] == "G1a" for a in res["anomalies"]), res
        assert res["counterexample"] is not None, res
        assert res["counterexample"]["class"] == "G2-item", res
        # the cycle runs THROUGH the dirty txn
        statuses = {s["status"] for s in res["counterexample"]["cycle"]}
        assert "fail (dirty)" in statuses, statuses
    finally:
        conn.close()
        _kill(procs)


def test_second_opinions_agree_on_seeded_controls():
    """Cross-wiring satellite: the composed (bespoke + graph)
    checkers agree on the seeded -T G2 interleaving and the -R
    dirty-read interleaving, for both the anomalous and healthy
    variants."""
    g2_hist_bad = [
        Op(0, "invoke", "insert", (7, (1, None))),
        Op(0, "ok", "insert", (7, (1, None))),
        Op(1, "invoke", "insert", (7, (None, 2))),
        Op(1, "ok", "insert", (7, (None, 2))),
    ]
    g2_hist_good = [op.with_(type="fail") if i == 3 else op
                    for i, op in enumerate(g2_hist_bad)]
    checker = g2_composed()
    for hist, expect in ((g2_hist_bad, False), (g2_hist_good, True)):
        res = checker.check(None, None, hist)
        assert res["valid?"] is expect, res
        assert res["adya"]["valid?"] is expect
        assert res["graph"]["valid?"] is expect

    dirty_bad = [
        Op(0, "invoke", "write", 7), Op(0, "ok", "write", 7),
        Op(1, "invoke", "write", 8), Op(1, "fail", "write", 8),
        Op(2, "invoke", "read", None), Op(2, "ok", "read", (8, 8)),
    ]
    dirty_good = [op.with_(value=(7, 7)) if i == 5 else op
                  for i, op in enumerate(dirty_bad)]
    checker = dirty_reads_composed()
    for hist, expect in ((dirty_bad, False), (dirty_good, True)):
        res = checker.check(None, None, hist)
        assert res["valid?"] is expect, res
        assert res["dirty"]["valid?"] is expect
        assert res["graph"]["valid?"] is expect
