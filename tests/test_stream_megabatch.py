"""Megabatched session advance (docs/streaming.md "Megabatched
advance"): N same-shape-class sessions advance in ONE device dispatch
per pump beat, bit-identical to the per-session path.

The load-bearing claims, counter-asserted on
``stream.engine.DISPATCHES`` (launched PROGRAMS, not lanes) and
``MEGABATCHES``:

- a fused beat's carries are BIT-equal to B solo dispatches across
  all three rungs, including mixed per-lane delta sizes (group-max
  padding: dead ``ok_proc=-1`` segments select the old carry);
- a latched lane never joins a batch (and never blocks one);
- a mid-batch escalation re-routes that lane SOLO on the widened
  pre-delta carry, leaving its batchmates' verdicts untouched;
- a lane checkpointed out of a fused advance restores bit-exact;
- the service groups a beat's appends per shape class into one
  launch, with per-session reply ``stages`` still tiling
  ``latency_ms``.
"""

import random

import numpy as np
import pytest

from comdb2_tpu.checker.batch import check_batch, pack_batch
from comdb2_tpu.models.model import MODELS
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.packed import pack_history
from comdb2_tpu.ops.synth import pinned_wide_history, register_history
from comdb2_tpu.stream import StreamSession
from comdb2_tpu.stream import engine as ENG

V = {True: 0, False: 1, "unknown": 2}


def _oneshot(h, model="cas-register", F=1024):
    b = pack_batch([pack_history(list(h))], MODELS[model]())
    st, fa, nf = check_batch(b, F=F)
    return int(st[0]), int(fa[0]), int(nf[0])


def _assert_verdict(exp, out):
    got = (V[out["valid"]], out["op_index"], out["final_count"])
    assert exp[0] == got[0] and exp[1] == got[1], (exp, got)
    if exp[0] == 0:            # counts compare on VALID only
        assert exp[2] == got[2], (exp, got)


def _fused_beat(sessions, deltas):
    """Stage every (session, delta) into ONE collector, flush, and
    finalize — one service pump beat's worth of fused advance."""
    coll = ENG.MegaBatch()
    fins = [s.append_stage(d, collector=coll)
            for s, d in zip(sessions, deltas)]
    coll.flush()
    return [f() for f in fins], coll


def _assert_state_equal(a, b, path=""):
    """Recursive bit-exact compare of engine checkpoint trees."""
    assert type(a) is type(b), (path, type(a), type(b))
    if isinstance(a, dict):
        assert a.keys() == b.keys(), path
        for k in a:
            _assert_state_equal(a[k], b[k], f"{path}.{k}")
    elif isinstance(a, (tuple, list)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_state_equal(x, y, f"{path}[{i}]")
    elif isinstance(a, np.ndarray):
        np.testing.assert_array_equal(a, b, err_msg=path)
    else:
        assert a == b, (path, a, b)


def _assert_session_parity(fused, solo):
    """A fused lane is indistinguishable from its solo twin: same
    verdict map (incl. per-lane dispatch count) and bit-equal engine
    carry."""
    fo, so = fused.poll(), solo.poll()
    assert fo == so, (fo, so)
    assert fused.dispatches == solo.dispatches
    _assert_state_equal(fused.checkpoint()["eng"],
                        solo.checkpoint()["eng"])


# --- bit parity, fused vs solo ---------------------------------------------

def test_xla_fused_bit_parity_mixed_deltas():
    """Three XLA-rung lanes with DIFFERENT per-beat delta sizes fuse
    into one program per beat; carries and verdicts are bit-equal to
    three solo sessions fed identically."""
    hs = [register_history(random.Random(s), n_procs=3, n_events=36,
                           p_info=0.0, max_pending=2)
          for s in (21, 22, 23)]
    cuts = [24, 12, 30]                  # mixed deltas in each beat
    fused = [StreamSession("cas-register", engine="xla") for _ in hs]
    solo = [StreamSession("cas-register", engine="xla") for _ in hs]
    for part in range(2):
        beats = [h[:c] if part == 0 else h[c:]
                 for h, c in zip(hs, cuts)]
        d0, m0 = ENG.DISPATCHES, ENG.MEGABATCHES
        outs, coll = _fused_beat(fused, beats)
        if coll.fused_launches:
            # one launched program advanced every fused lane
            assert ENG.DISPATCHES - d0 == len(coll.lane_counts)
            assert ENG.MEGABATCHES - m0 == coll.fused_launches
        for s, b in zip(solo, beats):
            s.append(b)
    # 3 real lanes pad to the B=4 rung: one duplicated lane, masked
    assert max(coll.lane_counts) == 3, coll.lane_counts
    assert coll.masked_lanes >= 1
    for f, s, h in zip(fused, solo, hs):
        _assert_session_parity(f, s)
        exp = _oneshot(h)
        _assert_verdict(exp, f.finalize_input())
        _assert_verdict(exp, s.finalize_input())


@pytest.fixture()
def interpret_kernel():
    from comdb2_tpu.checker import pallas_seg as PS

    PS.use_interpret(True)
    PS.available.cache_clear()      # pick_rung probes through it
    yield
    PS.use_interpret(False)
    PS.available.cache_clear()


def test_kernel_fused_bit_parity(interpret_kernel):
    """Two kernel-rung lanes (exact kernel as XLA ops) share ONE
    fused launch per beat — the Mosaic chunk program is invoked per
    lane inside one jit — and stay bit-equal to solo twins."""
    def hist(v1, v2):
        # the second beat interns its new transition WITHIN the
        # first beat's pow2 buckets (reused values) — a bucket
        # crossing would re-route solo by design, which is a
        # different (replay) path than the fused advance under test
        return ([O.invoke(0, "write", v1), O.ok(0, "write", v1),
                 O.invoke(1, "write", v2), O.ok(1, "write", v2),
                 O.invoke(0, "read", None), O.ok(0, "read", v2)],
                [O.invoke(1, "write", v1), O.ok(1, "write", v1),
                 O.invoke(0, "read", None), O.ok(0, "read", v1)])

    ha, hb = hist(1, 2), hist(2, 1)
    fused = [StreamSession("cas-register", engine="kernel")
             for _ in (0, 1)]
    solo = [StreamSession("cas-register", engine="kernel")
            for _ in (0, 1)]
    for part in range(2):
        beats = [ha[part], hb[part]]
        d0 = ENG.DISPATCHES
        outs, coll = _fused_beat(fused, beats)
        assert coll.fused_launches == 1, coll.lane_counts
        assert ENG.DISPATCHES - d0 == 1      # one program, two lanes
        for s, b in zip(solo, beats):
            s.append(b)
    assert all(s._rung == "kernel" for s in fused + solo)
    for f, s, h in zip(fused, solo, (ha, hb)):
        _assert_session_parity(f, s)
        exp = _oneshot(h[0] + h[1])
        _assert_verdict(exp, f.finalize_input())
        _assert_verdict(exp, s.finalize_input())


def test_mxu_fused_bit_parity():
    """Two wide-P lanes on the MXU rung advance in one fused launch,
    bit-equal to solo twins (the packed-word carries stack losslessly
    and the vmapped chunk scan is elementwise-identical)."""
    wide = pinned_wide_history(18)
    tail = [O.invoke(0, "write", 2), O.ok(0, "write", 2),
            O.invoke(1, "read", None), O.ok(1, "read", 2)]
    fused = [StreamSession("cas-register", engine="mxu")
             for _ in (0, 1)]
    solo = [StreamSession("cas-register", engine="mxu")
            for _ in (0, 1)]
    for s in fused + solo:               # wide prefix: solo appends
        s.append(wide)
    assert all(s._rung == "mxu" for s in fused + solo)
    d0, m0 = ENG.DISPATCHES, ENG.MEGABATCHES
    outs, coll = _fused_beat(fused, [list(tail), list(tail)])
    assert ENG.DISPATCHES - d0 == 1 and ENG.MEGABATCHES - m0 == 1
    assert coll.lane_counts == [2]
    for s in solo:
        s.append(tail)
    for f, s in zip(fused, solo):
        _assert_session_parity(f, s)
        assert f.poll()["valid"] is True


# --- batch-local failure modes ---------------------------------------------

def test_mid_batch_latch():
    """A lane whose fused delta is non-linearizable latches INVALID
    without touching its batchmate, and a latched lane never joins a
    later batch (zero dispatches, the beat's other lane goes solo)."""
    good = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
            O.invoke(1, "read", None), O.ok(1, "read", 1)]
    bad = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
           O.invoke(1, "read", None), O.ok(1, "read", 9)]
    sa = StreamSession("cas-register", engine="xla")
    sb = StreamSession("cas-register", engine="xla")
    outs, coll = _fused_beat([sa, sb], [bad, list(good)])
    assert coll.fused_launches == 1
    assert outs[0]["valid"] is False      # latched IN the fused run
    assert outs[1]["valid"] is True
    # beat 2: the latched lane answers at stage time, no dispatch;
    # its batchmate advances alone (solo fallback, still one program)
    more = [O.invoke(2, "write", 2), O.ok(2, "write", 2),
            O.invoke(0, "read", None), O.ok(0, "read", 2)]
    d0 = ENG.DISPATCHES
    da0 = sa.dispatches
    outs, coll = _fused_beat([sa, sb], [list(more), list(more)])
    assert outs[0]["valid"] is False and outs[0].get("latched")
    assert outs[1]["valid"] is True
    assert sa.dispatches == da0 and ENG.DISPATCHES - d0 == 1
    assert coll.lane_counts == [1] and coll.fused_launches == 0


def test_mid_batch_escalation_reroutes_solo():
    """A concurrency burst overflowing the first frontier rung inside
    a fused advance escalates THAT lane in place (widened pre-delta
    carry, solo re-run) while its batchmate's verdict and carry come
    straight from the fused program."""
    burst = []
    for p in range(8):
        burst.append(O.invoke(p, "write", p))
    tail = [O.ok(p, "write", p) for p in range(8)]
    tail += [O.invoke(0, "read", None), O.ok(0, "read", 7)]
    calm = register_history(random.Random(31), n_procs=3,
                            n_events=20, p_info=0.0, max_pending=2)
    cut = 12
    sa = StreamSession("cas-register", engine="xla")
    sb = StreamSession("cas-register", engine="xla")
    solo_b = StreamSession("cas-register", engine="xla")
    _fused_beat([sa, sb], [burst, calm[:cut]])
    solo_b.append(calm[:cut])
    outs, coll = _fused_beat([sa, sb], [tail, calm[cut:]])
    solo_b.append(calm[cut:])
    exp_a = _oneshot(burst + tail, F=8192)
    out_a = sa.finalize_input()
    _assert_verdict(exp_a, out_a)
    assert out_a["frontier_capacity"] > ENG.STREAM_CAPACITIES[0]
    assert out_a["replays"] == 0         # in place, not a replay
    _assert_session_parity(sb, solo_b)
    _assert_verdict(_oneshot(calm), sb.finalize_input())


def test_lane_checkpoint_restore_out_of_fused_beat():
    """A session checkpointed right after a fused advance restores
    bit-exact and keeps advancing (fused or solo) to the one-shot
    verdict — migration composes with megabatching."""
    hs = [register_history(random.Random(s), n_procs=3, n_events=32,
                           p_info=0.0, max_pending=2)
          for s in (41, 42)]
    cut = 16
    ss = [StreamSession("cas-register", engine="xla") for _ in hs]
    _fused_beat(ss, [h[:cut] for h in hs])
    ck = ss[0].checkpoint()
    moved = StreamSession.restore(ck)
    _assert_state_equal(ck["eng"], moved.checkpoint()["eng"])
    outs, coll = _fused_beat([moved, ss[1]],
                             [h[cut:] for h in hs])
    assert coll.fused_launches == 1
    for s, h in zip((moved, ss[1]), hs):
        _assert_verdict(_oneshot(h), s.finalize_input())


# --- the serving plane ------------------------------------------------------

def test_service_fuses_same_class_appends_per_beat():
    """Two sessions' appends in one service beat share one launched
    program: `stream_megabatches` counts it, the amortization metrics
    surface it, and each reply's stages still tile latency_ms."""
    from comdb2_tpu.obs import trace as obs
    from comdb2_tpu.ops.history import history_to_edn
    from comdb2_tpu.service.core import VerifierCore

    h = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
         O.invoke(1, "read", None), O.ok(1, "read", 1)]
    core = VerifierCore(batch_cap=8, max_sessions=4)
    sids = []
    for i in (1, 2):
        _, r = core.submit({"kind": "stream", "verb": "open",
                            "id": i}, obs.monotonic())
        sids.append(r["session"])
    now = obs.monotonic()
    for i, sid in enumerate(sids):
        core.submit({"kind": "stream", "verb": "append",
                     "id": 10 + i, "session": sid,
                     "history": history_to_edn(h)}, now)
    d0 = ENG.DISPATCHES
    done = core.tick()
    assert ENG.DISPATCHES - d0 == 1      # ONE program, two sessions
    assert core.m["stream_megabatches"] >= 1
    assert len(done) == 2
    for _p, rep in done:
        assert rep["valid"] is True, rep
        assert abs(sum(rep["stages"].values())
                   - rep["latency_ms"]) < 1.0
    prom = core.metrics_reply()["prometheus"]
    assert "sessions_per_dispatch" in prom
    assert "stream_megabatch_lanes" in prom


def test_compile_guard_closed_over_fused_beats():
    """Fused advance stays inside the declared inventory (the
    session_B ladder of PROGRAMS.md stream-delta)."""
    from comdb2_tpu.utils import compile_guard

    hs = [register_history(random.Random(s), n_procs=3, n_events=24,
                           p_info=0.0, max_pending=2)
          for s in (51, 52)]
    with compile_guard.guard() as g:
        ss = [StreamSession("cas-register", engine="xla")
              for _ in hs]
        for part in range(2):
            mid = [len(h) // 2 for h in hs]
            beats = [h[:m] if part == 0 else h[m:]
                     for h, m in zip(hs, mid)]
            _fused_beat(ss, beats)
        for s in ss:
            s.finalize_input()
    g.assert_closed()
