"""Native driver integration: build the C++ components, run the
register/insert workloads against the in-memory SUT, and verify the
emitted EDN histories with the Python/TPU checker — the full offline
pipeline (SURVEY §3.6)."""

import json
import os
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(ROOT, "native")
BUILD = os.path.join(NATIVE, "build")


@pytest.fixture(scope="session")
def native_build():
    if not os.path.exists(os.path.join(BUILD, "ct_register")):
        subprocess.run(["cmake", "-S", NATIVE, "-B", BUILD],
                       check=True, capture_output=True)
        subprocess.run(["cmake", "--build", BUILD], check=True,
                       capture_output=True)
    return BUILD


def _run(args, **kw):
    return subprocess.run(args, capture_output=True, text=True, **kw)


def test_register_driver_emits_valid_history(native_build, tmp_path):
    out = tmp_path / "reg.edn"
    p = _run([os.path.join(native_build, "ct_register"),
              "-T", "5", "-i", "80", "-r", "30", "-j", str(out),
              "-s", "42"])
    assert p.returncode == 0, p.stderr

    from comdb2_tpu.checker import analysis
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.history import parse_history

    h = parse_history(out.read_text())
    assert len(h) == 800
    a = analysis(cas_register(), h)
    assert a.valid is True


def test_register_driver_flaky_history_checks_out(native_build, tmp_path):
    """Flaky outcomes (fail + indeterminate info ops with process
    retirement) must still produce a linearizable history."""
    out = tmp_path / "regf.edn"
    p = _run([os.path.join(native_build, "ct_register"),
              "-T", "4", "-i", "60", "-r", "30", "-F", "-j", str(out),
              "-s", "3"])
    assert p.returncode == 0, p.stderr

    from comdb2_tpu.checker import analysis
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.history import parse_history

    h = parse_history(out.read_text())
    assert any(op.type == "info" for op in h)
    a = analysis(cas_register(), h)
    assert a.valid is True


def test_register_driver_buggy_history_flagged_invalid(native_build,
                                                       tmp_path):
    """The negative control: a backend with lost updates/stale reads
    must produce a history the checker rejects."""
    out = tmp_path / "regb.edn"
    p = _run([os.path.join(native_build, "ct_register"),
              "-T", "5", "-i", "120", "-r", "30", "-B", "-j", str(out),
              "-s", "11"])
    assert p.returncode == 0, p.stderr

    from comdb2_tpu.checker import analysis
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.history import parse_history

    h = parse_history(out.read_text())
    a = analysis(cas_register(), h)
    assert a.valid is False


def test_insert_driver_classification(native_build, tmp_path):
    out = tmp_path / "ins.edn"
    p = _run([os.path.join(native_build, "ct_insert"),
              "-T", "5", "-i", "400", "-j", str(out), "-s", "7"])
    assert p.returncode == 0, p.stderr
    summary = json.loads(p.stdout)
    assert summary["checked"] == 400
    assert summary["lost"] == 0

    # re-verify the emitted history with the Python set checker
    from comdb2_tpu.checker.checkers import set_checker
    from comdb2_tpu.ops.history import parse_history

    h = parse_history(out.read_text())
    r = set_checker.check({}, None, h)
    assert r["valid?"] is True


def test_insert_driver_buggy_detected_by_both(native_build, tmp_path):
    out = tmp_path / "insb.edn"
    p = _run([os.path.join(native_build, "ct_insert"),
              "-T", "5", "-i", "400", "-B", "-j", str(out), "-s", "7"])
    assert p.returncode == 1                      # driver self-check
    summary = json.loads(p.stdout)
    assert summary["lost"] > 0

    from comdb2_tpu.checker.checkers import set_checker
    from comdb2_tpu.ops.history import parse_history

    h = parse_history(out.read_text())
    r = set_checker.check({}, None, h)            # python checker agrees
    assert r["valid?"] is False
    assert r["lost"] != "#{}"


def test_insert_flaky_recovered(native_build, tmp_path):
    out = tmp_path / "insf.edn"
    p = _run([os.path.join(native_build, "ct_insert"),
              "-T", "5", "-i", "400", "-F", "-j", str(out), "-s", "9"])
    assert p.returncode == 0, p.stdout + p.stderr
    summary = json.loads(p.stdout)
    assert summary["recovered"] > 0

    from comdb2_tpu.checker.checkers import set_checker
    from comdb2_tpu.ops.history import parse_history

    h = parse_history(out.read_text())
    r = set_checker.check({}, None, h)
    assert r["valid?"] is True
    assert r["recovered"] != "#{}"


def test_nemesis_dryrun_commands(native_build, tmp_path):
    """Partition events in dry-run mode print the iptables/ssh plan."""
    out = tmp_path / "nem.edn"
    p = _run([os.path.join(native_build, "ct_register"),
              "-T", "2", "-i", "10", "-r", "1", "-j", str(out),
              "-n", "m1,m2,m3,m4,m5", "-G", "partition", "-G", "sigstop",
              "-D", "-s", "5"])
    assert p.returncode == 0, p.stderr
    assert "iptables -A INPUT -s" in p.stderr
    assert "-j DROP" in p.stderr
    assert "killall -s STOP" in p.stderr
    assert "killall -s CONT" in p.stderr
    # heal commands flush rules on every node
    assert p.stderr.count("iptables -F") >= 5


def test_filetest_cli(native_build, tmp_path):
    out = tmp_path / "ft.edn"
    _run([os.path.join(native_build, "ct_register"),
          "-T", "3", "-i", "40", "-r", "30", "-j", str(out), "-s", "1"])
    from comdb2_tpu import filetest
    assert filetest.main([str(out)]) == 0
    assert filetest.main([str(out), "--backend", "host"]) == 0

    bad = tmp_path / "ftb.edn"
    _run([os.path.join(native_build, "ct_register"),
          "-T", "5", "-i", "120", "-r", "30", "-B", "-j", str(bad),
          "-s", "11"])
    assert filetest.main([str(bad)]) == 1


def test_insert_flaky_history_is_process_well_formed(native_build,
                                                     tmp_path):
    """Retired process ids and the final reader id must never collide —
    history.complete() enforces the single-threaded process rule."""
    out = tmp_path / "insf2.edn"
    _run([os.path.join(native_build, "ct_insert"),
          "-T", "5", "-i", "400", "-F", "-j", str(out), "-s", "9"])

    from comdb2_tpu.ops.history import complete, parse_history

    h = parse_history(out.read_text())
    complete(h)     # raises if any process id is reused while pending


def test_filetest_keyed_histories(tmp_path):
    """EDN [k v] values re-tag as keyed tuples for the comdb2 model."""
    edn = """
{:type :invoke :f :write :value [7 3] :process 0 :time 1}
{:type :ok :f :write :value [7 3] :process 0 :time 2}
{:type :invoke :f :cas :value [7 [3 4]] :process 1 :time 3}
{:type :ok :f :cas :value [7 [3 4]] :process 1 :time 4}
{:type :invoke :f :read :value [7 4] :process 0 :time 5}
{:type :ok :f :read :value [7 4] :process 0 :time 6}
"""
    p = tmp_path / "keyed.edn"
    p.write_text(edn)
    from comdb2_tpu import filetest
    assert filetest.main([str(p), "--model", "cas-register-comdb2"]) == 0


def test_filetest_set_checker(native_build, tmp_path):
    out = tmp_path / "fts.edn"
    _run([os.path.join(native_build, "ct_insert"),
          "-T", "3", "-i", "200", "-j", str(out), "-s", "2"])
    from comdb2_tpu import filetest
    assert filetest.main([str(out), "--checker", "set"]) == 0


def test_nemesis_master_discovery_and_targeted_partition(native_build,
                                                         tmp_path):
    """The native nemesis discovers the cluster's primary over the SUT
    info verb and generates master-targeted per-port DROP rules — the
    reference's breaknet shape (nemesis.c:15-47, 90-144)."""
    import socket

    from comdb2_tpu.workloads.tcp import spawn_cluster

    socks, ports = [], []
    for _ in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    procs = spawn_cluster(os.path.join(native_build, "sut_node"), ports)
    try:
        nodes = ",".join(f"127.0.0.1:{p}" for p in ports)
        out = tmp_path / "nem2.edn"
        p = _run([os.path.join(native_build, "ct_register"),
                  "-T", "2", "-i", "10", "-r", "1", "-j", str(out),
                  "-n", nodes, "-G", "partition", "-D", "-s", "5"])
        assert p.returncode == 0, p.stderr
        # discovery found node 0 (the primary)
        assert f"discovered master 127.0.0.1:{ports[0]}" in p.stderr
        # rules are per-port and the primary participates in the cut
        assert f"--dport {ports[0]} -j DROP" in p.stderr
        # the cut is {master, +1} vs {remaining}: the lone cut-off
        # replica receives DROP rules from BOTH side-a members (its
        # port appears twice), while each side-a port appears once
        counts = {q: p.stderr.count(f"--dport {q} -j DROP")
                  for q in ports}
        assert counts[ports[0]] == 1, (counts, p.stderr)
        assert sorted(counts[q] for q in ports[1:]) == [1, 2], \
            (counts, p.stderr)
    finally:
        for pr in procs:
            pr.kill()
        for pr in procs:
            pr.wait()


def test_nemesis_fallback_random_halves_without_ports(native_build,
                                                      tmp_path):
    """Bare hostnames (no ports): no discovery, whole-host rules, random
    halves — the pre-discovery behavior stays available."""
    out = tmp_path / "nem3.edn"
    p = _run([os.path.join(native_build, "ct_register"),
              "-T", "2", "-i", "10", "-r", "1", "-j", str(out),
              "-n", "m1,m2,m3,m4,m5", "-G", "partition", "-D", "-s", "5"])
    assert p.returncode == 0, p.stderr
    assert "iptables -A INPUT -s" in p.stderr
    assert "--dport" not in p.stderr


def test_insert_select_stress_mode(native_build, tmp_path):
    """insert.c -s/-S parity: the select-stress range [0,S) is verified
    between inserts; a deliberately-broken seed (-Z, one record missing)
    must be detected."""
    binary = os.path.join(native_build, "ct_insert")
    p = _run([binary, "-T", "4", "-i", "200", "-S", "50", "-s", "3"])
    assert p.returncode == 0, p.stdout + p.stderr
    r = json.loads(p.stdout)
    assert r["select_errors"] == 0 and r["checked"] == 200

    p = _run([binary, "-T", "4", "-i", "200", "-S", "50", "-Z",
              "-s", "3"])
    assert p.returncode == 1, p.stdout
    assert json.loads(p.stdout)["select_errors"] > 0


def test_insert_blkseq_dup_mode(native_build, tmp_path):
    """insert.c -x parity: re-inserting an applied row must fail as a
    duplicate; a backend that loses the original insert (buggy mode)
    lets the replay apply and MUST be flagged."""
    binary = os.path.join(native_build, "ct_insert")
    p = _run([binary, "-T", "4", "-i", "200", "-x", "-s", "3"])
    assert p.returncode == 0, p.stdout + p.stderr
    assert json.loads(p.stdout)["blkseq_violations"] == 0

    p = _run([binary, "-T", "4", "-i", "200", "-x", "-B", "-s", "3"])
    assert p.returncode == 1, p.stdout
    assert json.loads(p.stdout)["blkseq_violations"] > 0


def test_register_driver_ha_tcp_cluster(native_build, tmp_path):
    """cdb2api HA-semantics parity (cdb2api.c:618-656): ct_register -d
    host:port,... drives the replicated cluster through the TCP HA
    client — node-list routing, retry-elsewhere on dead nodes,
    snapshot-LSN read tracking — and the histories stay linearizable
    even with a replica down."""
    import socket

    from comdb2_tpu.checker import analysis
    from comdb2_tpu.models.model import cas_register
    from comdb2_tpu.ops.history import parse_history
    from comdb2_tpu.workloads.tcp import spawn_cluster

    socks, ports = [], []
    for _ in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    procs = spawn_cluster(os.path.join(native_build, "sut_node"), ports,
                          durable=True, timeout_ms=500)
    try:
        nodes = ",".join(f"127.0.0.1:{p}" for p in ports)
        out = tmp_path / "ha.edn"
        p = _run([os.path.join(native_build, "ct_register"), "-T", "4",
                  "-r", "2", "-i", "40", "-d", nodes, "-j", str(out),
                  "-s", "2"], timeout=120)
        assert p.returncode == 0, p.stderr
        h = parse_history(out.read_text())
        assert len(h) >= 100
        assert analysis(cas_register(), h, backend="host").valid is True

    finally:
        for pr in procs:
            pr.kill()
        for pr in procs:
            pr.wait()

    # fresh cluster (the register carries state across runs, like the
    # reference's jepsenloop clearing tables between iterations), one
    # replica killed up front: retry-elsewhere keeps every op flowing
    procs = spawn_cluster(os.path.join(native_build, "sut_node"), ports,
                          durable=True, timeout_ms=500)
    try:
        procs[2].kill()
        procs[2].wait()
        out2 = tmp_path / "ha2.edn"
        p = _run([os.path.join(native_build, "ct_register"), "-T", "4",
                  "-r", "2", "-i", "40", "-d", nodes, "-j", str(out2),
                  "-s", "4"], timeout=120)
        assert p.returncode == 0, p.stderr
        h2 = parse_history(out2.read_text())
        assert len(h2) >= 100
        assert analysis(cas_register(), h2,
                        backend="host").valid is True
    finally:
        for pr in procs:
            pr.kill()
        for pr in procs:
            pr.wait()


def _serve_once(payload, linger=0.0):
    """One-shot fake server: accept, read the request, write ``payload``
    (possibly partial / stalled), close. Returns the port."""
    import socket
    import threading
    import time

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def run():
        c, _ = srv.accept()
        c.recv(4096)
        if payload:
            c.sendall(payload)
        if linger:
            time.sleep(linger)
        c.close()
        srv.close()

    threading.Thread(target=run, daemon=True).start()
    return port


def test_tcp_request_truncated_reply_is_indeterminate(native_build):
    """ct_tcp_request completes a reply only at its newline: a mid-line
    EOF, a recv timeout, or a cap-filling line must come back -2
    (indeterminate), never a truncated "V 12" for "V 123" success —
    that would fabricate a wrong read under exactly the faults the
    harness injects (round-2 ADVICE medium)."""
    import ctypes
    import socket

    lib = ctypes.CDLL(os.path.join(native_build, "libct_sut.so"))
    lib.ct_tcp_request.restype = ctypes.c_int
    lib.ct_tcp_request.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int]

    def req(port, timeout_ms=500, cap=128):
        buf = ctypes.create_string_buffer(cap)
        rc = lib.ct_tcp_request(b"127.0.0.1", port, b"R", timeout_ms,
                                buf, cap)
        return rc, buf.value

    rc, val = req(_serve_once(b"V 123\n"))
    assert (rc, val) == (5, b"V 123")          # complete reply
    rc, _ = req(_serve_once(b"V 12"))
    assert rc == -2                            # mid-line EOF
    rc, _ = req(_serve_once(b"V 1", linger=1.5), timeout_ms=300)
    assert rc == -2                            # recv timeout mid-line
    rc, _ = req(_serve_once(b"V " + b"9" * 300 + b"\n"), cap=16)
    assert rc == -2                            # line overflows the cap
    dead = socket.socket()
    dead.bind(("127.0.0.1", 0))
    port = dead.getsockname()[1]
    dead.close()
    rc, _ = req(port, timeout_ms=300)
    assert rc == -1                            # never connected


def test_python_sut_connection_rejects_truncated_reply():
    """The Python SutConnection has the same contract: a reply missing
    its newline (server died mid-write) raises TimeoutError instead of
    handing the workload a fabricated value."""
    from comdb2_tpu.workloads.tcp import SutConnection

    port = _serve_once(b"V 12")     # partial: real reply was "V 123\n"
    conn = SutConnection("127.0.0.1", port, timeout_s=1.0)
    conn.connect()
    with pytest.raises(TimeoutError, match="truncated"):
        conn.request("R")
    conn.close()


def test_insert_driver_ha_cluster_under_partitions(native_build, tmp_path):
    """ct_insert -d over a partitioned durable cluster: the HA client's
    nonce retries keep adds exactly-once through failovers, the final
    committed read loses nothing, and the emitted history passes the
    Python set checker — the insert.c state machine
    (OK->CHECKED / UNKNOWN->RECOVERED|LOST) against a REAL cluster."""
    import socket
    import threading

    from comdb2_tpu.workloads.tcp import ClusterControl, spawn_cluster

    ports = []
    for _ in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        s.close()
    nodes = ",".join(f"127.0.0.1:{p}" for p in ports)
    procs = spawn_cluster(os.path.join(native_build, "sut_node"), ports,
                          durable=True, timeout_ms=400, elect_ms=500,
                          lease_ms=300)
    ctl = ClusterControl(ports)
    stop = threading.Event()

    def nemesis():
        while not stop.wait(0.7):
            pri = ctl.primary()
            if pri is None:
                continue
            ctl.partition([pri], [i for i in range(3) if i != pri])
            if stop.wait(1.0):
                break
            ctl.heal()

    th = threading.Thread(target=nemesis)
    th.start()
    out = tmp_path / "ha_insert.edn"
    try:
        p = _run([os.path.join(native_build, "ct_insert"),
                  "-T", "4", "-i", "2000", "-d", nodes,
                  "-j", str(out), "-s", "9"], timeout=180)
    finally:
        stop.set()
        th.join()
        ctl.heal()
        for pr in procs:
            pr.kill()
        for pr in procs:
            pr.wait()
    # exit contract: 0 iff nothing lost / nothing unexpected
    assert p.returncode == 0, (p.stdout, p.stderr)
    import json as _json

    verdict = _json.loads(p.stdout.strip().splitlines()[-1])
    assert verdict["lost"] == 0 and verdict["unexpected"] == 0, verdict
    assert verdict["checked"] >= 1000, verdict

    from comdb2_tpu.checker import checkers as C
    from comdb2_tpu.ops.history import parse_history

    h = parse_history(out.read_text())
    res = C.set_checker.check(None, None, h)
    assert res["valid?"] is True, res
