"""Pallas bitonic sort-pairs kernel tests (run on hardware that Mosaic
supports; skipped on the CPU test mesh)."""

import numpy as np
import pytest

from comdb2_tpu.checker import pallas_sort as PS


requires_pallas = pytest.mark.skipif(
    not PS.sort_pairs_available(),
    reason="Pallas/Mosaic unavailable on this backend")


@requires_pallas
def test_sort_pairs_matches_lexsort():
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    B, N = 16, 512
    hi = rng.integers(0, 1 << 20, (B, N)).astype(np.int32)
    lo = rng.integers(0, 1 << 30, (B, N)).astype(np.int32)
    h, l = PS.sort_pairs(jnp.asarray(hi), jnp.asarray(lo))
    h, l = np.asarray(h), np.asarray(l)
    for b in range(B):
        order = np.lexsort((lo[b], hi[b]))
        assert (h[b] == hi[b][order]).all()
        assert (l[b] == lo[b][order]).all()


@requires_pallas
def test_sort_pairs_duplicates_and_sentinels():
    import jax.numpy as jnp

    hi = np.array([[5, 5, 1, 1, 7, 0, 5, 1]], np.int32)
    lo = np.array([[2, 1, 3, 3, 0, 9, 1, 0]], np.int32)
    h, l = PS.sort_pairs(jnp.asarray(hi), jnp.asarray(lo),
                         lanes_per_block=1)
    order = np.lexsort((lo[0], hi[0]))
    assert (np.asarray(h)[0] == hi[0][order]).all()
    assert (np.asarray(l)[0] == lo[0][order]).all()


def test_keys_engine_with_pallas_flag_matches(monkeypatch, request):
    """With the flag forced on, the dedup falls back gracefully when
    Mosaic is unavailable, or produces identical verdicts when it is."""
    import random

    from comdb2_tpu.checker import linear_jax as LJ
    from comdb2_tpu.checker import linear_host
    from comdb2_tpu.checker.batch import pack_batch, check_batch
    from comdb2_tpu.models import model as M
    from comdb2_tpu.models.memo import memo as make_memo
    from comdb2_tpu.ops.packed import pack_history
    from tests import histgen

    if not PS.sort_pairs_available():
        pytest.skip("Pallas unavailable; engine uses the XLA sort")
    monkeypatch.setattr(LJ, "_USE_PALLAS_SORT", True)
    # flag isn't part of the jit static key: drop cached executables so
    # the Pallas path really traces, and drop them again afterwards so
    # flag-off callers don't reuse the Pallas-compiled executable
    LJ.check_device_keys.clear_cache()
    request.addfinalizer(LJ.check_device_keys.clear_cache)
    model = M.cas_register()
    hs, want = [], []
    for seed in range(8):
        rng = random.Random(800 + seed)
        h = histgen.register_history(rng, n_procs=3,
                                     n_events=rng.randint(6, 20))
        if seed % 2:
            h = histgen.mutate(rng, h)
        hs.append(h)
        p = pack_history(h)
        want.append(linear_host.check(make_memo(model, p), p).valid)
    batch = pack_batch(hs, model)
    st, _, _ = check_batch(batch, F=128, engine="keys")
    got = [bool(s == 0) if s != 2 else "unknown" for s in st]
    assert got == want
