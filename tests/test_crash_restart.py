"""Crash-restart durability: fsync'd log + recovery against kill -9.

Round-2 VERDICT Missing #3: "durable-LSN" previously died with the
process — sut_node was purely in-memory, so killcluster could only
bounce stateless processes. Now every log entry hits disk before it is
acked or counted toward durability (the berkdb txn-log role), recovery
replays the log, and a restarted node rejoins as a replica whose
suffix the leader backfills. The killcluster harness drives the
reference's diff-oracle shape (``killclustertest.sh:36-84``): a
scripted exactly-once workload runs while every node is kill-9'd and
restarted, and the transcript must match the oracle. ``--no-fsync``
(-x) is the negative control: acked writes live in a userspace buffer,
the kill loses them, and the set checker flags the loss."""

import os
import socket
import time

import pytest

from comdb2_tpu.harness import killcluster as KC
from comdb2_tpu.workloads.tcp import (ClusterControl, SutConnection,
                                      spawn_cluster)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_node")

pytestmark = pytest.mark.skipif(not os.path.exists(BINARY),
                                reason="sut_node not built")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _req(port, line, timeout=2.0):
    conn = SutConnection("127.0.0.1", port, timeout_s=timeout)
    try:
        conn.connect()
        return conn.request(line)
    finally:
        conn.close()


def _await_primary(ctl, timeout_s=8.0):
    """Persistent nodes always boot as replicas (a wiped node must not
    self-appoint into a progressed cluster), so a dir-backed cluster
    needs its first election before it can serve."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        pri = ctl.primary()
        if pri is not None:
            return pri
        time.sleep(0.1)
    raise AssertionError(f"no primary elected: {ctl.info()}")


def _dirs(tmp_path, n):
    out = []
    for i in range(n):
        d = tmp_path / f"node{i}"
        d.mkdir(parents=True, exist_ok=True)
        out.append(str(d))
    return out


def test_node_recovers_state_from_log(tmp_path):
    """A single restarted node replays its fsync'd log: register and
    set state, the replay-nonce table, and its term all survive."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500,
                          elect_ms=500, lease_ms=300,
                          dirs=_dirs(tmp_path, 3))
    try:
        ctl = ClusterControl(ports)
        pri = _await_primary(ctl)
        assert _req(ports[pri], "M 41 W 1 7").startswith("OK")
        r_c = _req(ports[pri], "M 42 C 1 7 8")
        assert r_c.startswith("OK")
        procs.kill9_all()
        procs.restart_all()
        pri = _await_primary(ctl)
        assert _req(ports[pri], "R 1") == "V 8"
        # the dedup table was rebuilt from the log: the cas replay
        # returns its RECORDED reply (a re-execution would FAIL its
        # precondition — regs is 8, not 7)
        assert _req(ports[pri], "M 42 C 1 7 8") == r_c
        info = ctl.info()
        assert all(n.get("term", 0) >= 1 for n in info)
    finally:
        procs.kill9_all()


def test_restarted_replica_is_backfilled(tmp_path):
    """A replica that crashes and restarts (losing nothing on disk but
    missing entries written while it was down) acks its true position
    and the leader's sender regresses to backfill it — the round-2
    ADVICE #3 wedge (sender stuck offering acked+1 forever) is dead.
    Also run WITHOUT a state dir: the replica comes back empty and the
    whole log is re-shipped."""
    for use_dirs in (True, False):
        ports = _free_ports(3)
        dirs = _dirs(tmp_path / f"d{use_dirs}", 3) if use_dirs else None
        procs = spawn_cluster(BINARY, ports, durable=True,
                              timeout_ms=500, elect_ms=500,
                              lease_ms=300, dirs=dirs)
        try:
            ctl0 = ClusterControl(ports)
            pri = (_await_primary(ctl0) if use_dirs else 0)
            kill_me = next(i for i in range(3) if i != pri)
            for i in range(5):
                assert _req(ports[pri], f"W 1 {i}").startswith("OK")
            procs.kill9(kill_me)
            for i in range(5, 10):
                assert _req(ports[pri], f"W 1 {i}").startswith("OK")
            procs.restart(kill_me)
            ctl = ClusterControl(ports)
            assert ctl.await_replicated(timeout_s=10.0), \
                (f"dirs={use_dirs}: restarted replica never caught up",
                 ctl.info())
        finally:
            procs.kill9_all()


def test_killcluster_durable_cluster_loses_nothing(tmp_path):
    """The flagship crash-restart run: exactly-once adds while every
    node is kill-9'd and restarted twice; the transcript must match
    the oracle — no acked add may vanish, every add resolves."""
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500,
                          elect_ms=500, lease_ms=300,
                          dirs=_dirs(tmp_path, 3))
    n_values = 24
    try:
        result = KC.run(
            {},
            KC.cluster_set_workload(ports, n_values, pace_s=0.15),
            KC.cluster_oracle(n_values),
            disrupt=KC.cluster_kill_restart(procs, rounds=2),
            disrupt_after_s=0.8)
        assert result["valid?"] is True, result
    finally:
        procs.kill9_all()


def test_killcluster_no_fsync_control_detected(tmp_path):
    """The -x negative control: acked adds sit in a userspace buffer,
    so the full-cluster kill-9 loses them. The transcript diff catches
    it AND the set checker judges the corresponding history invalid
    with the lost values named."""
    from comdb2_tpu.checker import checkers as C

    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500,
                          elect_ms=500, lease_ms=300,
                          dirs=_dirs(tmp_path, 3), flags=["-x"])
    n_values = 24
    try:
        result = KC.run(
            {},
            KC.cluster_set_workload(ports, n_values, pace_s=0.15),
            KC.cluster_oracle(n_values),
            disrupt=KC.cluster_kill_restart(procs, rounds=2),
            disrupt_after_s=0.8)
        assert result["valid?"] is False, \
            ("no-fsync cluster lost nothing across kill -9?!", result)
    finally:
        procs.kill9_all()

    # independent checker-level judgement: acked adds vs final read
    ports = _free_ports(3)
    procs = spawn_cluster(BINARY, ports, durable=True, timeout_ms=500,
                          elect_ms=500, lease_ms=300,
                          dirs=_dirs(tmp_path / "chk", 3), flags=["-x"])
    try:
        ctl0 = ClusterControl(ports)
        pri = _await_primary(ctl0)
        acked = []
        for i in range(12):
            if _req(ports[pri], f"M {100 + i} A {i}").startswith("OK"):
                acked.append(i)
        assert len(acked) == 12
        procs.kill9_all()
        procs.restart_all()
        ctl = ClusterControl(ports)
        deadline = time.monotonic() + 8.0
        final = None
        while time.monotonic() < deadline:
            pri = ctl.primary()
            if pri is not None:
                try:
                    r = _req(ports[pri], "S")
                except (TimeoutError, OSError):
                    time.sleep(0.1)
                    continue
                if r.startswith("V"):
                    final = [int(x) for x in r[1:].split()]
                    break
            time.sleep(0.1)
        assert final is not None
        from comdb2_tpu.ops.op import Op

        history = []
        t = 0
        for i in acked:
            history.append(Op(process=0, type="invoke", f="add",
                              value=i, time=t))
            history.append(Op(process=0, type="ok", f="add",
                              value=i, time=t + 1))
            t += 2
        history.append(Op(process=1, type="invoke", f="read",
                          value=None, time=t))
        history.append(Op(process=1, type="ok", f="read",
                          value=set(final), time=t + 1))
        res = C.SetChecker().check(None, None, history)
        assert res["valid?"] is False, res
        assert res["lost"], "the checker must name the lost elements"
    finally:
        procs.kill9_all()
