"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the
multi-chip path; `bench.py` runs on the real chip).

The ambient environment may attach JAX to a real TPU through a tunnel
(an interpreter-startup hook can pre-import jax and register the plugin
BEFORE this file runs, so setting JAX_PLATFORMS here is too late).
``jax.config.update`` works after import as long as no backend has been
initialized, so we force the CPU platform through the config API and
verify we actually got it.
"""

import os

# env vars still matter for subprocesses and not-yet-imported jax
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# persistent compile cache: the frontier-search programs are expensive to
# compile and shape-stable across runs. Env vars alone are NOT enough —
# the ambient startup hook imports jax before this file runs and jax
# reads them at import — so go through jax.config (same reason
# jax.config.update is used for the platform below).
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      "/tmp/jax-cache-comdb2tpu")

import jax  # noqa: E402

from comdb2_tpu.utils.platform import enable_compile_cache  # noqa: E402

enable_compile_cache(os.environ["JAX_COMPILATION_CACHE_DIR"])
jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", (
    f"tests must run on the CPU mesh, got {jax.default_backend()!r} — "
    "a backend was initialized before conftest could force the platform")
assert len(jax.devices()) == 8, jax.devices()
