"""killcluster diff-oracle + faketime wrapper tests."""

import os

from comdb2_tpu import control
from comdb2_tpu.control.remote import LocalRemote, RecordingRemote
from comdb2_tpu.harness import faketime, killcluster
from comdb2_tpu.workloads.sqlish import MemDB


def test_oracle_shape():
    lines = list(killcluster.oracle(3))
    assert lines == ["[set transaction serializable] rc 0",
                     "[begin] rc 0", "(a=0)", "(a=1)", "(a=2)",
                     "[commit] rc 0"]


def test_killcluster_clean_run_matches_oracle():
    db = MemDB()
    r = killcluster.run(
        {}, lambda: killcluster.scripted_workload(db.connect(), 500),
        killcluster.oracle(500))
    assert r["valid?"] is True, r["diff"]


def test_killcluster_disruption_with_retries_still_matches():
    """Chaos aborts force retries mid-transaction; the committed
    transcript must still equal the oracle exactly."""
    db = MemDB(chaos_fail=0.3, seed=3)
    disrupted = []
    r = killcluster.run(
        {}, lambda: killcluster.scripted_workload(db.connect(), 300),
        killcluster.oracle(300),
        disrupt=lambda: disrupted.append(True),
        disrupt_after_s=0.0)
    assert r["valid?"] is True, r["diff"]


def test_killcluster_detects_lost_rows():
    db = MemDB()

    def lossy_workload():
        yield "[set transaction serializable] rc 0"
        yield "[begin] rc 0"
        conn = db.connect()
        with conn.transaction() as t:
            for i in range(100):
                if i != 50:               # row 50 silently lost
                    t.insert("killcluster", {"a": i})
        for row in sorted(r["a"] for r in conn.select("killcluster")):
            yield f"(a={row})"
        yield "[commit] rc 0"

    r = killcluster.run({}, lossy_workload, killcluster.oracle(100))
    assert r["valid?"] is False
    assert r["diff"][0]["expected"] == "(a=50)"


def test_kill_restart_all_commands():
    rec = RecordingRemote()
    test = {"nodes": ["n1", "n2"], "remote": rec}
    killcluster.kill_restart_all(test, "mydb",
                                 restart_cmd="systemctl start mydb",
                                 stagger_s=0)
    cmds = [c for _, c in rec.commands]
    assert any("pkill -KILL -f mydb" in c for c in cmds)
    assert any("systemctl start mydb" in c for c in cmds)


def test_faketime_script_and_wrap(tmp_path):
    s = faketime.script("/usr/bin/myapp", -30, 1.5)
    assert 'faketime -m -f "-30s x1.5" /usr/bin/myapp "$@"' in s

    target = tmp_path / "app"
    target.write_text("#!/bin/bash\necho real\n")
    target.chmod(0o755)
    sess = control.Session("localhost", LocalRemote(),
                           root=os.geteuid() == 0)
    with control.with_session(sess):
        faketime.wrap(str(target), 10, 2.0)
        assert (tmp_path / "app.no-faketime").exists()
        body = target.read_text()
        assert "faketime" in body and "app.no-faketime" in body
        # idempotent: wrapping again keeps the original
        faketime.wrap(str(target), 10, 2.0)
        assert "echo real" in (tmp_path / "app.no-faketime").read_text()
        faketime.unwrap(str(target))
        assert target.read_text().endswith("echo real\n")
