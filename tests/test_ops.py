"""Phase 0 tests: op/history core and EDN io.

Mirrors the observable behavior of knossos/history.clj (complete/index/
pairs) and the filetest EDN interchange format."""

import numpy as np
import pytest

from comdb2_tpu.ops import (
    invoke, ok, fail, info, complete, index, pairs, pair_index,
    read_edn, read_edn_all, write_edn, kw, pack_history,
)
from comdb2_tpu.ops.history import parse_history, history_to_edn, op_from_map


def test_complete_backfills_ok_value():
    h = [invoke(0, "read", None), ok(0, "read", 2)]
    h2 = complete(h)
    assert h2[0].value == 2
    assert not h2[0].fails


def test_complete_marks_fails():
    h = [invoke(0, "write", 3), fail(0, "write", 3)]
    h2 = complete(h)
    assert h2[0].fails and h2[1].fails
    assert h2[0].value == 3


def test_complete_fail_takes_known_value():
    h = [invoke(0, "read", None), fail(0, "read", 7)]
    h2 = complete(h)
    assert h2[0].value == 7 and h2[0].fails


def test_complete_interleaved_processes():
    h = [invoke(0, "read", None),
         invoke(1, "write", 5),
         ok(1, "write", 5),
         ok(0, "read", 5)]
    h2 = complete(h)
    assert h2[0].value == 5      # read invocation back-filled
    assert h2[0].process == 0


def test_complete_rejects_concurrent_same_process():
    h = [invoke(0, "read", None), invoke(0, "write", 1)]
    with pytest.raises(RuntimeError):
        complete(h)


def test_info_passes_through_and_stays_pending():
    h = [invoke(0, "write", 1), info(0, "write", 1), info("nemesis", "start")]
    h2 = complete(h)
    assert [op.type for op in h2] == ["invoke", "info", "info"]
    assert h2[0].value == 1


def test_index_and_pairs():
    h = index(complete([invoke(0, "read", None),
                        invoke(1, "write", 5),
                        ok(0, "read", None),
                        info("nemesis", "start"),
                        ok(1, "write", 5)]))
    assert [op.index for op in h] == [0, 1, 2, 3, 4]
    pi = pair_index(h)
    assert pi[0] == 2 and pi[2] == 0
    assert pi[1] == 4 and pi[4] == 1
    assert pi[3] is None
    ps = pairs(h)
    assert [(a.index, b.index if b else None) for a, b in ps] == [
        (0, 2), (3, None), (1, 4)]


def test_edn_roundtrip():
    s = '{:type :invoke, :f :cas, :value [0 3], :process 1, :time 1234}'
    m = read_edn(s)
    assert m[kw("type")] == kw("invoke")
    assert m[kw("value")] == [0, 3]
    out = write_edn(m)
    assert read_edn(out) == m


def test_edn_various_forms():
    assert read_edn("nil") is None
    assert read_edn("true") is True
    assert read_edn("[1 2.5 \"hi\" :a nil]") == [1, 2.5, "hi", kw("a"), None]
    assert read_edn("#{1 2}") == {1, 2}
    assert read_edn("; comment\n42") == 42
    assert read_edn("#inst \"2016\"") == "2016"  # tag dropped
    assert read_edn_all("{:a 1}\n{:a 2}") == [{kw("a"): 1}, {kw("a"): 2}]


def test_parse_history_ctest_format():
    # format emitted by the reference's ctest/register.c -j flag
    text = """[
      {:type :invoke :f :write :value 3 :process 0 :time 10}
      {:type :ok :f :write :value 3 :process 0 :time 20}
      {:type :invoke :f :read :value nil :process 1 :time 30}
      {:type :ok :f :read :value 3 :process 1 :time 40}
    ]"""
    h = parse_history(text)
    assert len(h) == 4
    assert h[0].f == "write" and h[0].value == 3
    assert h[3].value == 3
    # cas values come through as tuples
    m = read_edn("{:type :invoke :f :cas :value [1 2] :process 0}")
    assert op_from_map(m).value == (1, 2)


def test_history_to_edn_roundtrip():
    h = index(complete([invoke(0, "write", 3), ok(0, "write", 3)]))
    text = history_to_edn(h)
    h2 = parse_history(text)
    assert [(o.process, o.type, o.f, o.value) for o in h2] == [
        (0, "invoke", "write", 3), (0, "ok", "write", 3)]


def test_plain_normalizes_sets_and_maps():
    # EDN sets/maps must intern as hashable values, not repr strings
    h = parse_history(
        "[{:type :invoke :f :read :value nil :process 0}"
        " {:type :ok :f :read :value #{1 2} :process 0}"
        " {:type :invoke :f :txn :value {:x 1} :process 1}"
        " {:type :ok :f :txn :value {:x 1} :process 1}]")
    p = pack_history(h)
    assert frozenset({1, 2}) in p.value_table
    assert (("x", 1),) in p.value_table


def test_pack_history():
    h = [invoke(0, "write", 3), ok(0, "write", 3),
         invoke(1, "read", None), ok(1, "read", 3),
         invoke(0, "cas", (3, 4)), fail(0, "cas", (3, 4)),
         info("nemesis", "start", None)]
    p = pack_history(h)
    assert len(p) == 7
    assert list(p.type) == [0, 1, 0, 1, 0, 2, 3]
    assert p.pair[0] == 1 and p.pair[1] == 0
    assert p.pair[6] == -1
    assert p.fails[4] and p.fails[5]
    # read invocation's transition uses the back-filled value 3
    read_t = p.trans[2]
    fid, vid = p.transition_table[read_t]
    assert p.f_table[fid] == "read" and p.value_table[vid] == 3
    # distinct transitions: write 3, read 3 — the failing cas never
    # linearizes, so its transition is not interned (trans stays -1)
    assert p.n_transitions == 2
    assert p.trans[4] == -1
    assert p.process_table[p.process[6]] == "nemesis"
