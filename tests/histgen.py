"""Test-local alias for the framework's synthetic history generator
(promoted to :mod:`comdb2_tpu.ops.synth` so benches can use it too)."""

from comdb2_tpu.ops.synth import register_history, mutate  # noqa: F401
