"""Checker-layer tests (jepsen/checker.clj semantics)."""

import pytest

from comdb2_tpu.checker import checkers as C
from comdb2_tpu.checker import independent as I
from comdb2_tpu.checker import workloads as W
from comdb2_tpu.models import model as M
from comdb2_tpu.ops.op import invoke, ok, fail, info, Op
from comdb2_tpu.utils.intervals import integer_interval_set_str, fraction


TEST = {"name": "t"}


# --- merge-valid / compose --------------------------------------------------

def test_merge_valid_priority():
    assert C.merge_valid([True, True]) is True
    assert C.merge_valid([True, "unknown"]) == "unknown"
    assert C.merge_valid([True, "unknown", False]) is False
    assert C.merge_valid([]) is True


def test_compose_runs_all_and_merges():
    class Always:
        def __init__(self, v):
            self.v = v

        def check(self, test, model, history, opts=None):
            return {"valid?": self.v}

    c = C.compose({"a": Always(True), "b": Always(False),
                   "c": Always("unknown")})
    r = c.check(TEST, None, [])
    assert r["valid?"] is False
    assert r["a"]["valid?"] is True
    assert r["b"]["valid?"] is False


def test_check_safe_wraps_exceptions():
    class Boom(C.Checker):
        def check(self, test, model, history, opts=None):
            raise RuntimeError("kaboom")

    r = C.check_safe(Boom(), TEST, None, [])
    assert r["valid?"] == "unknown"
    assert "kaboom" in r["error"]


# --- linearizable -----------------------------------------------------------

def test_linearizable_checker_valid():
    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "read", 1), ok(1, "read", 1)]
    r = C.linearizable.check(TEST, M.register(), h)
    assert r["valid?"] is True


def test_linearizable_checker_invalid():
    h = [invoke(0, "write", 1), ok(0, "write", 1),
         invoke(1, "read", None), ok(1, "read", 2)]
    r = C.linearizable.check(TEST, M.register(), h)
    assert r["valid?"] is False
    assert len(r["configs"]) <= 10


# --- set --------------------------------------------------------------------

def _set_history(adds_ok, adds_fail, adds_info, read):
    h = []
    for v in adds_ok:
        h += [invoke(0, "add", v), ok(0, "add", v)]
    for v in adds_fail:
        h += [invoke(0, "add", v), fail(0, "add", v)]
    for v in adds_info:
        h += [invoke(0, "add", v), info(0, "add", v)]
    h += [invoke(1, "read", None), ok(1, "read", frozenset(read))]
    return h


def test_set_checker_ok():
    r = C.set_checker.check(TEST, None, _set_history([1, 2], [3], [], [1, 2]))
    assert r["valid?"] is True
    assert r["ok"] == "#{1..2}"
    assert r["lost"] == "#{}"


def test_set_checker_lost_and_unexpected():
    r = C.set_checker.check(TEST, None, _set_history([1, 2], [], [], [2, 9]))
    assert r["valid?"] is False
    assert r["lost"] == "#{1}"
    assert r["unexpected"] == "#{9}"


def test_set_checker_recovered():
    # indeterminate add that shows up in the read: recovered, valid
    r = C.set_checker.check(TEST, None, _set_history([1], [], [5], [1, 5]))
    assert r["valid?"] is True
    assert r["recovered"] == "#{5}"
    assert r["recovered-frac"] == fraction(1, 2)


def test_set_checker_never_read():
    r = C.set_checker.check(TEST, None, [invoke(0, "add", 1),
                                         ok(0, "add", 1)])
    assert r["valid?"] == "unknown"


# --- queue / total-queue ----------------------------------------------------

def test_queue_checker_valid():
    h = [invoke(0, "enqueue", 1), ok(0, "enqueue", 1),
         invoke(1, "dequeue", 1), ok(1, "dequeue", 1)]
    r = C.queue.check(TEST, M.unordered_queue(), h)
    assert r["valid?"] is True


def test_queue_checker_dequeue_from_nowhere():
    h = [invoke(1, "dequeue", None), ok(1, "dequeue", 9)]
    r = C.queue.check(TEST, M.unordered_queue(), h)
    assert r["valid?"] is False


def test_total_queue_lost_and_unexpected():
    h = [invoke(0, "enqueue", 1), ok(0, "enqueue", 1),       # lost
         invoke(0, "enqueue", 2), ok(0, "enqueue", 2),
         invoke(1, "dequeue", None), ok(1, "dequeue", 2),
         invoke(1, "dequeue", None), ok(1, "dequeue", 7)]    # unexpected
    r = C.total_queue.check(TEST, None, h)
    assert r["valid?"] is False
    assert r["lost"] == {1: 1}
    assert r["unexpected"] == {7: 1}


def test_total_queue_duplicated_and_recovered():
    h = [invoke(0, "enqueue", 1), ok(0, "enqueue", 1),
         invoke(0, "enqueue", 3), info(0, "enqueue", 3),     # indeterminate
         invoke(1, "dequeue", None), ok(1, "dequeue", 1),
         invoke(1, "dequeue", None), ok(1, "dequeue", 1),    # duplicate
         invoke(1, "dequeue", None), ok(1, "dequeue", 3)]    # recovered
    r = C.total_queue.check(TEST, None, h)
    assert r["duplicated"] == {1: 1}
    assert r["recovered"] == {3: 1}


# --- counter ----------------------------------------------------------------

def test_counter_in_bounds():
    h = [invoke(0, "add", 1), ok(0, "add", 1),
         invoke(1, "read", None), ok(1, "read", 1),
         invoke(0, "add", 2), info(0, "add", 2),   # maybe applied
         invoke(1, "read", None), ok(1, "read", 3),
         invoke(2, "read", None), ok(2, "read", 1)]
    r = C.counter.check(TEST, None, h)
    assert r["valid?"] is True
    assert (1, 1, 1) in r["reads"]


def test_counter_out_of_bounds():
    h = [invoke(0, "add", 1), ok(0, "add", 1),
         invoke(1, "read", None), ok(1, "read", 5)]
    r = C.counter.check(TEST, None, h)
    assert r["valid?"] is False
    assert r["errors"] == [(1, 5, 1)]


# --- independent ------------------------------------------------------------

def _keyed(k, v):
    return I.tuple_(k, v)


def test_subhistory_unwraps_and_keeps_unkeyed():
    h = [invoke(0, "write", _keyed(1, 5)), ok(0, "write", _keyed(1, 5)),
         info("nemesis", "start", None),
         invoke(1, "write", _keyed(2, 7)), ok(1, "write", _keyed(2, 7))]
    sub = I.subhistory(1, h)
    assert [op.value for op in sub] == [5, 5, None]
    assert I.history_keys(h) == [1, 2]


def test_independent_checker_all_valid():
    h = []
    for k in range(4):
        h += [invoke(k, "write", _keyed(k, 1)), ok(k, "write", _keyed(k, 1)),
              invoke(k, "read", None), ok(k, "read", _keyed(k, 1))]
    c = I.checker(C.Linearizable())
    r = c.check(TEST, M.register(), h)
    assert r["valid?"] is True
    assert r["failures"] == []
    assert set(r["results"]) == {0, 1, 2, 3}


def test_independent_checker_finds_bad_key():
    h = []
    for k in range(3):
        h += [invoke(k, "write", _keyed(k, 1)), ok(k, "write", _keyed(k, 1))]
    # key 2 reads a value never written
    h += [invoke(3, "read", None), ok(3, "read", _keyed(2, 9))]
    c = I.checker(C.Linearizable())
    r = c.check(TEST, M.register(), h)
    assert r["valid?"] is False
    assert r["failures"] == [2]
    assert r["results"][2]["valid?"] is False
    assert r["results"][0]["valid?"] is True


def test_independent_checker_unknown_is_not_failure():
    class AlwaysUnknown(C.Checker):
        def check(self, test, model, history, opts=None):
            return {"valid?": "unknown"}

    h = [invoke(0, "write", _keyed(1, 5)), ok(0, "write", _keyed(1, 5))]
    r = I.checker(AlwaysUnknown()).check(TEST, None, h)
    assert r["valid?"] == "unknown"
    assert r["failures"] == []


def test_wrap_keyed_history():
    h = [invoke(0, "write", (1, 5))]
    w = I.wrap_keyed_history(h)
    assert I.is_tuple(w[0].value)
    assert w[0].value.key == 1


# --- workloads --------------------------------------------------------------

def test_bank_checker():
    model = {"n": 2, "total": 10}
    good = [invoke(0, "read", None), ok(0, "read", (4, 6))]
    bad = [invoke(0, "read", None), ok(0, "read", (4, 5))]
    assert W.bank_checker.check(TEST, model, good)["valid?"] is True
    r = W.bank_checker.check(TEST, model, bad)
    assert r["valid?"] is False
    assert r["bad-reads"][0]["type"] == "wrong-total"
    short = [invoke(0, "read", None), ok(0, "read", (10,))]
    assert W.bank_checker.check(TEST, model, short)["bad-reads"][0]["type"] \
        == "wrong-n"


def test_dirty_reads_checker():
    h = [invoke(0, "write", 3), fail(0, "write", 3),
         invoke(1, "read", None), ok(1, "read", (3, 3, 3))]
    r = W.dirty_reads_checker.check(TEST, None, h)
    assert r["valid?"] is False
    assert r["dirty-reads"] == [(3, 3, 3)]
    h2 = [invoke(0, "write", 3), ok(0, "write", 3),
          invoke(1, "read", None), ok(1, "read", (3, 4, 3))]
    r2 = W.dirty_reads_checker.check(TEST, None, h2)
    assert r2["valid?"] is True
    assert r2["inconsistent-reads"] == [(3, 4, 3)]


def test_g2_checker():
    h = [invoke(0, "insert", _keyed(1, (10, None))),
         ok(0, "insert", _keyed(1, (10, None))),
         invoke(1, "insert", _keyed(1, (None, 11))),
         fail(1, "insert", _keyed(1, (None, 11))),
         invoke(0, "insert", _keyed(2, (12, None))),
         ok(0, "insert", _keyed(2, (12, None))),
         invoke(1, "insert", _keyed(2, (None, 13))),
         ok(1, "insert", _keyed(2, (None, 13)))]
    r = W.g2_checker.check(TEST, None, h)
    assert r["valid?"] is False
    assert r["illegal"] == {2: 2}
    assert r["key-count"] == 2
    assert r["legal-count"] == 1


# --- intervals --------------------------------------------------------------

def test_integer_interval_set_str():
    assert integer_interval_set_str({1, 2, 3, 5, 9, 10}) == "#{1..3 5 9..10}"
    assert integer_interval_set_str(set()) == "#{}"
    assert integer_interval_set_str({7}) == "#{7}"
