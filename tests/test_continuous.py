"""Continuous-batching admission (round 9): slot-filling launches
(full / deadline-budget / idle), per-bucket fairness under a
hot-bucket flood, the bounded in-flight ring with mixed request
kinds, donated-carry bit-parity (stream kernel and mesh-sharded
closure), overload retry_after_ms + jittered client backoff, and the
consistent-hash routing layer (ring math + failover)."""

import random
import time

import numpy as np
import pytest

from comdb2_tpu.obs import trace as obs
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.history import history_to_edn
from comdb2_tpu.ops.synth import register_history, txn_anomaly_history
from comdb2_tpu.service import VerifierCore


def _core(**kw):
    kw.setdefault("F", 64)
    kw.setdefault("batch_cap", 8)
    return VerifierCore(**kw)


def _submit(core, h, now=None, **fields):
    return core.submit({"op": "check",
                        "history": history_to_edn(list(h)),
                        **fields},
                       obs.monotonic() if now is None else now)


def _histories(seed0, n, n_events=40):
    return [register_history(random.Random(seed0 + i), 3, n_events,
                             p_info=0.0) for i in range(n)]


# --- launch policy -----------------------------------------------------------

def test_full_batch_launches_at_submit():
    """A bucket that reaches the cap dispatches inside submit itself
    — no scheduler beat, no fill window (the slot-filling contract).
    The same history twice guarantees one shared bucket."""
    core = _core(batch_cap=2, fill_window_s=10.0)
    h = _histories(11, 1)[0]
    p1, r1 = _submit(core, h)
    assert core.inflight() == 0 and r1 is None
    p2, r2 = _submit(core, h)
    assert p1.bucket == p2.bucket        # identical text, same bucket
    assert core.m["launch_full"] == 1
    assert core.inflight() == 1          # staged, not yet finalized
    assert core.queue_depth() == 0
    done = core.tick()                   # drain the ring
    assert len(done) == 2
    for _, reply in done:
        assert reply["valid"] is True


def test_deadline_derived_launch_budget():
    """A request's launch budget is deadline-derived: with a huge
    fill window, a 100 ms deadline still launches within ~50 ms
    (half the headroom stays reserved for the dispatch)."""
    core = _core(fill_window_s=10.0)
    t0 = obs.monotonic()
    p, r = _submit(core, _histories(21, 1)[0], now=t0,
                   deadline_ms=100)
    assert r is None
    assert p.t_budget <= t0 + 0.051
    # before the budget: a non-idle pump must NOT launch
    done = core.pump(now=t0 + 0.01)
    assert core.m["launch_deadline"] == 0 and core.queue_depth() == 1
    # after the budget: launched for deadline reasons, then served
    done += core.pump(now=t0 + 0.06)
    assert core.m["launch_deadline"] == 1
    done += core.tick()
    assert len(done) == 1 and done[0][1]["valid"] is True
    assert core.m["deadline_expired"] == 0


def test_hot_bucket_flood_cold_bucket_launches_within_budget():
    """Per-bucket fairness: a flood filling one bucket's batches must
    not hold a cold bucket's lone request past its launch budget."""
    core = _core(batch_cap=4, fill_window_s=0.02)
    hot_h = _histories(31, 1, n_events=40)[0]
    cold = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
            O.invoke(1, "read", None), O.Op(1, "ok", "read", 2)]
    t0 = obs.monotonic()
    pc, _ = _submit(core, cold, now=t0)
    for _ in range(12):                   # 3 full same-bucket batches
        _submit(core, hot_h, now=t0)
    assert core.m["launch_full"] == 3      # the flood launched itself
    # the cold bucket launches once its budget expires — without
    # waiting for the hot bucket to go quiet (reason: deadline, never
    # a whole-queue drain round)
    done = core.pump(now=t0 + 0.021)
    assert core.m["launch_deadline"] >= 1
    done += core.tick()
    cold_reply = next(r for p, r in done if p is pc)
    assert cold_reply["valid"] is False    # the stale-read repro
    assert len(done) == 13


def test_idle_launch_answers_serial_callers():
    core = _core(fill_window_s=10.0)
    _submit(core, _histories(41, 1)[0])
    done = core.pump(idle=True)            # quiet wire -> launch+drain
    assert core.m["launch_idle"] == 1
    assert len(done) == 1 and done[0][1]["valid"] is True


# --- the in-flight ring ------------------------------------------------------

def test_ring_bounds_staged_dispatches():
    """More launchable buckets than ring slots: the ring finalizes
    oldest-first on overflow, every reply still arrives, and the
    occupancy gauge ends at zero."""
    core = _core(batch_cap=8, ring_depth=2)
    sizes = (16, 40, 88, 150)             # 4 distinct shape buckets
    for i, n_events in enumerate(sizes):
        _submit(core, register_history(random.Random(51 + i), 3,
                                       n_events, p_info=0.0))
    done = core.tick()
    assert len(done) == 4
    assert {r["valid"] for _, r in done} == {True}
    assert core.m["dispatches"] >= 3       # distinct buckets staged
    assert core.inflight() == 0
    snap = core.metrics_reply()["metrics"]
    assert snap["service_inflight_ring"]["series"][0]["value"] == 0
    assert snap["service_launch_idle_total"]["series"][0]["value"] \
        >= 1


def test_ring_drains_on_busy_pump_when_nothing_forms():
    """Non-queuing traffic (status/ping polls) keeps the daemon's
    got_bytes true forever — a staged dispatch must still finalize on
    a NON-idle pump once no batch is forming, or its reply defers
    indefinitely (review regression)."""
    core = _core(batch_cap=1, fill_window_s=10.0)
    _submit(core, _histories(45, 1)[0])    # cap 1 -> launches at
    assert core.inflight() == 1            # submit, staged in ring
    done = core.pump(idle=False)           # busy beat, nothing forms
    assert core.inflight() == 0
    assert len(done) == 1 and done[0][1]["valid"] is True


def test_mixed_kinds_interleave_in_ring():
    """check + txn dispatches ride the same ring; a shrink job's
    rounds interleave between them — one pump serves all three
    kinds."""
    core = _core()
    _submit(core, _histories(61, 1)[0])
    core.submit({"op": "check", "kind": "txn",
                 "history": history_to_edn(
                     list(txn_anomaly_history("g2-item")))},
                obs.monotonic())
    bad = [O.invoke(0, "write", 1), O.ok(0, "write", 1),
           O.invoke(1, "read", None), O.Op(1, "ok", "read", 2)]
    core.submit({"op": "check", "kind": "shrink",
                 "history": history_to_edn(bad)}, obs.monotonic())
    deadline = time.monotonic() + 120
    done = []
    while len(done) < 3 and time.monotonic() < deadline:
        done += core.tick()
    kinds = sorted(r.get("kind", "check") for _, r in done)
    assert kinds == ["check", "shrink", "txn"]
    shrink_reply = next(r for _, r in done
                        if r.get("kind") == "shrink")
    assert shrink_reply["valid"] is False
    assert shrink_reply["minimal_ops"] <= 4
    txn_reply = next(r for _, r in done if r.get("kind") == "txn")
    assert txn_reply["anomaly_class"] == "G2-item"


# --- donated carries ---------------------------------------------------------

def test_donated_carry_parity_stream():
    """Bit-parity of the donated stream-kernel path on the
    interpret-mode kernel: donated + pooled (the rerun must HIT the
    carry pool) vs the plain path must agree exactly. The closure
    kernels deliberately do not donate — their packed upload can
    never alias the smaller diagonal output (closure_jax docstring),
    and mesh closure parity is covered by test_mesh_parity."""
    from comdb2_tpu.checker import batch as B
    from comdb2_tpu.checker import pallas_seg as PS
    from comdb2_tpu.models import model as M

    hs = _histories(71, 4, n_events=24)
    model = M.cas_register()
    PS.use_interpret(True)
    try:
        assert PS.donation_active()
        r_don = B.check_batch(B.pack_batch(hs, model), F=64,
                              engine="stream")
        reuses0 = PS.CARRY_REUSES
        r_don2 = B.check_batch(B.pack_batch(hs, model), F=64,
                               engine="stream")
        assert PS.CARRY_REUSES > reuses0   # the pool served a rerun
        PS.use_carry_donation(False)
        r_plain = B.check_batch(B.pack_batch(hs, model), F=64,
                                engine="stream")
    finally:
        PS.use_carry_donation(True)
        PS.use_interpret(False)
    for a, b in ((r_don, r_plain), (r_don2, r_plain)):
        for x, y in zip(a, b):
            assert (np.asarray(x) == np.asarray(y)).all()


# --- overload backoff --------------------------------------------------------

def test_overload_reply_has_drain_derived_retry_after():
    core = _core(max_queue=2, fill_window_s=0.001)
    hs = _histories(81, 3)
    _submit(core, hs[0])
    core.tick()                            # builds drain history
    _submit(core, hs[0])
    _submit(core, hs[1])
    _, reply = _submit(core, hs[2])
    assert reply["error"] == "overload"
    assert 25 <= reply["retry_after_ms"] <= 5000


def test_client_backs_off_on_overload(monkeypatch):
    """The client honors retry_after_ms with jitter (never a fixed
    interval) and retries the request instead of surfacing the first
    overload."""
    from comdb2_tpu.service.client import ServiceClient

    c = ServiceClient.__new__(ServiceClient)
    c.overload_retries = 2
    c._rng = random.Random(3)
    replies = [{"ok": False, "error": "overload",
                "retry_after_ms": 200},
               {"ok": False, "error": "overload",
                "retry_after_ms": 200},
               {"ok": True, "valid": True}]
    calls = {"n": 0}

    def fake_request(obj):
        out = replies[calls["n"]]
        calls["n"] += 1
        return out

    slept = []
    monkeypatch.setattr(c, "_request", fake_request)
    monkeypatch.setattr("comdb2_tpu.service.client.time.sleep",
                        slept.append)
    out = c._request_shedding({"op": "check"})
    assert out["ok"] is True and calls["n"] == 3
    assert len(slept) == 2
    for s in slept:                        # jittered around the hint
        assert 0.1 <= s <= 0.3
    assert slept[0] != slept[1]            # not a fixed interval


# --- consistent-hash routing -------------------------------------------------

def test_hash_ring_balance_and_minimal_remap():
    from comdb2_tpu.service.client import HashRing

    two = HashRing(["sut/verifier/0", "sut/verifier/1"])
    owners = [two.nodes_for(f"k{i}")[0] for i in range(400)]
    share = owners.count("sut/verifier/0") / 400
    assert 0.3 <= share <= 0.7             # balanced-ish
    # failover chain covers every distinct node, owner first
    chain = two.nodes_for("some-key")
    assert len(chain) == 2 and set(chain) == set(two.nodes)
    # adding a node only moves keys TO the new node
    three = HashRing(["sut/verifier/0", "sut/verifier/1",
                      "sut/verifier/2"])
    for i in range(400):
        a, b = two.nodes_for(f"k{i}")[0], three.nodes_for(f"k{i}")[0]
        assert b == a or b == "sut/verifier/2"


def test_routed_client_shape_affinity_and_failover():
    from comdb2_tpu.service.client import RoutedClient

    class Stub:
        def __init__(self, fail=False):
            self.fail = fail
            self.calls = 0

        def check(self, history, **kw):
            self.calls += 1
            if self.fail:
                raise OSError("down")
            return {"ok": True, "valid": True}

        def close(self):
            pass

    a, b = Stub(), Stub()
    rc = RoutedClient({"sut/verifier/0": a, "sut/verifier/1": b})
    h_small = history_to_edn(_histories(91, 1, n_events=10)[0])
    h_big = history_to_edn(_histories(92, 1, n_events=60)[0])
    # same shape class -> same daemon, every time (program affinity)
    owners = {rc.ring.nodes_for(
        RoutedClient.route_key(h_small))[0] for _ in range(3)}
    assert len(owners) == 1
    for _ in range(3):
        assert rc.check(h_small)["ok"]
        assert rc.check(h_big)["ok"]
    assert a.calls + b.calls == 6
    # kill the owner of h_small: requests fail over, none are lost
    owner = rc.ring.nodes_for(RoutedClient.route_key(h_small))[0]
    rc.clients[owner].fail = True
    assert rc.check(h_small)["ok"]
    assert rc.failovers == 1


def test_routed_discover_requires_registrations():
    from comdb2_tpu.service.client import RoutedClient

    with pytest.raises(ValueError):
        RoutedClient({})
