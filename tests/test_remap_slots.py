"""Slot renaming (``linear_jax.remap_slots``) — the round-5 transform
that maps process ids onto a minimal pool of reusable slots so every
engine's slot axis scales with max CONCURRENT open calls instead of
process count (the fused kernel's tier gate, round-4 Weak #4).

Renaming is a pure relabeling of a segment stream: verdicts, fail
segments, and frontier sizes must be bit-identical through any engine.
The reference's ``ArrayProcesses`` packs per-process cells densely but
never reuses them (``knossos/linear/config.clj:157-295``).
"""

import random

import numpy as np
import pytest

import comdb2_tpu.checker.linear_jax as LJ
import comdb2_tpu.models.model as M
from comdb2_tpu.checker import linear_host
from comdb2_tpu.checker.linear import analysis
from comdb2_tpu.models.memo import memo as make_memo
from comdb2_tpu.ops import op as O
from comdb2_tpu.ops.packed import pack_history

import histgen


def _segs(h, **kw):
    return LJ.make_segments(pack_history(h), **kw)


def test_peff_tracks_concurrency_not_process_count():
    """10 processes, <=3 calls in flight -> 3 slots."""
    rng = random.Random(7)
    h = histgen.register_history(rng, n_procs=10, n_events=400,
                                 p_info=0.0, max_pending=3)
    segs = _segs(h)
    segs2, p_eff = LJ.remap_slots(segs)
    assert p_eff <= 3
    assert segs2.inv_proc.max() < p_eff
    assert segs2.ok_proc.max() < p_eff
    # untouched fields ride through
    assert segs2.seg_index is segs.seg_index
    assert segs2.depth is segs.depth
    assert segs2.inv_tr is segs.inv_tr


def test_remap_is_idempotent():
    rng = random.Random(11)
    h = histgen.register_history(rng, n_procs=8, n_events=300,
                                 p_info=0.1, max_pending=4)
    s1, p1 = LJ.remap_slots(_segs(h))
    s2, p2 = LJ.remap_slots(s1)
    assert p1 == p2
    np.testing.assert_array_equal(s1.inv_proc, s2.inv_proc)
    np.testing.assert_array_equal(s1.ok_proc, s2.ok_proc)


def test_info_invokes_pin_their_slot():
    """:info ops never complete: their slot must stay allocated (the
    process retired — reusing the slot would let a later invoke
    corrupt the still-maybe-pending op)."""
    h = [O.invoke(0, "w", 1), O.info(0, "w", 1),      # p0 crashes
         O.invoke(1, "w", 2), O.ok(1, "w", 2),
         O.invoke(2, "w", 3), O.ok(2, "w", 3)]
    segs2, p_eff = LJ.remap_slots(_segs(h))
    # p0 holds slot 0 forever; p1 gets slot 1, frees it; p2 reuses 1
    assert p_eff == 2
    ok = segs2.ok_proc[segs2.ok_proc >= 0]
    assert list(ok) == [1, 1]


def test_ok_without_open_invocation_stays_invalid():
    """A defensive path: an ok with no open call previously filtered
    on an IDLE process slot (frontier empties -> INVALID); the renamed
    stream must preserve that by mapping it to a free slot."""
    segs = LJ.SegmentStream(
        inv_proc=np.full((2, 1), -1, np.int32),
        inv_tr=np.zeros((2, 1), np.int32),
        ok_proc=np.array([0, -1], np.int32),     # ok, no invoke
        seg_index=np.zeros(2, np.int64),
        depth=np.zeros(2, np.int32))
    segs2, p_eff = LJ.remap_slots(segs)
    assert p_eff == 1
    mm = make_memo(M.register(), pack_history(
        [O.invoke(0, "w", 1), O.ok(0, "w", 1)]))
    status, fail, _ = LJ.check_device_seg2(
        LJ.pad_succ(mm.succ, 8, 8), segs2.inv_proc, segs2.inv_tr,
        segs2.ok_proc, segs2.depth, F=8, Fs=4, P=2,
        n_states=mm.n_states, n_transitions=mm.n_transitions)
    assert int(status) == LJ.INVALID
    assert int(fail) == 0


def test_double_pending_invoke_rejected():
    segs = LJ.SegmentStream(
        inv_proc=np.array([[0], [0]], np.int32),
        inv_tr=np.zeros((2, 1), np.int32),
        ok_proc=np.full(2, -1, np.int32),
        seg_index=np.zeros(2, np.int64),
        depth=np.zeros(2, np.int32))
    with pytest.raises(ValueError, match="still open"):
        LJ.remap_slots(segs)


def test_owner_maps_track_allocation():
    rng = random.Random(3)
    h = histgen.register_history(rng, n_procs=6, n_events=200,
                                 p_info=0.1, max_pending=3)
    segs = _segs(h)
    segs2, p_eff, owners = LJ.remap_slots(segs, with_maps=True)
    S, K = segs.inv_proc.shape
    alloc = {}
    for s in range(S):
        for k in range(K):
            p, sl = segs.inv_proc[s, k], segs2.inv_proc[s, k]
            if p >= 0:
                alloc[int(sl)] = int(p)
        if segs.ok_proc[s] >= 0:
            del alloc[int(segs2.ok_proc[s])]
        for q in range(p_eff):
            assert owners[s, q] == alloc.get(q, -1), (s, q)


@pytest.mark.parametrize("seed", range(40))
def test_verdict_parity_xla_engine(seed):
    """Renamed stream through the XLA seg engine == original stream ==
    host engine, across valid/invalid/info-heavy histories."""
    rng = random.Random(900 + seed)
    h = histgen.register_history(
        rng, n_procs=rng.choice([4, 8, 12]),
        n_events=rng.choice([60, 200]),
        p_info=rng.choice([0.0, 0.15]),
        max_pending=rng.choice([2, 3, 4]))
    if rng.random() < 0.5:
        h = histgen.mutate(rng, h)
    packed = pack_history(h)
    mm = make_memo(M.cas_register(), packed)
    segs = LJ.make_segments(packed, s_pad=128, k_pad=8)
    if segs.inv_proc.shape != (128, 8):
        pytest.skip("segment shape over bucket")
    segs2, p_eff = LJ.remap_slots(segs)
    # info ops pin slots forever, so the bound is max_pending plus the
    # number of crashed (info) invocations — not max_pending alone
    assert p_eff <= len(packed.process_table)
    succ = LJ.pad_succ(mm.succ, 64, 64)
    sizes = dict(n_states=mm.n_states, n_transitions=mm.n_transitions)
    P_orig = max(len(packed.process_table), 2)
    r1 = LJ.check_device_seg2(succ, segs.inv_proc, segs.inv_tr,
                              segs.ok_proc, segs.depth, F=64, Fs=8,
                              P=P_orig + (P_orig & 1), **sizes)
    r2 = LJ.check_device_seg2(succ, segs2.inv_proc, segs2.inv_tr,
                              segs2.ok_proc, segs2.depth, F=64, Fs=8,
                              P=max(p_eff + (p_eff & 1), 2), **sizes)
    assert [int(x) for x in r1] == [int(x) for x in r2]
    if int(r1[0]) != LJ.UNKNOWN:
        hr = linear_host.check(mm, packed, max_configs=1 << 18)
        assert (int(r1[0]) == LJ.VALID) == hr.valid


def test_analysis_wide_p_low_concurrency_invalid_counterexample():
    """End to end: 12 processes / concurrency 3, corrupted history.
    The driver renames slots (info reports the effective count) and
    the counterexample decodes back to ORIGINAL process ids."""
    rng = random.Random(21)
    for attempt in range(20):
        h = histgen.register_history(rng, n_procs=12, n_events=240,
                                     p_info=0.0, max_pending=3)
        h = histgen.mutate(rng, h)
        a = analysis(M.cas_register(), h, backend="device")
        if a.valid is False:
            break
    else:
        pytest.fail("no invalid mutation found")
    assert a.info.get("effective_slots", 99) <= 3
    # counterexample configs name real processes from the history
    procs = {op.process for op in h}
    for cfg in a.configs:
        assert set(cfg.get("pending", {})) <= procs
    for path in a.info.get("paths", []):
        for step in path:
            opd = step["op"]
            if isinstance(opd, dict):
                assert opd["process"] in procs


def test_segment_batch_accepts_prebuilt_renamed_streams():
    """The keys/flat fallback reuses the stream path's already-built
    (union-remapped, slot-renamed) streams instead of re-running the
    O(total-ops) segment pass — verdicts must match the from-scratch
    SegmentBatch through the keys engine."""
    from comdb2_tpu.checker.batch import (_stream_segments, pack_batch,
                                          segment_batch)

    rng = random.Random(17)
    hs = []
    for i in range(12):
        h = histgen.register_history(rng, n_procs=rng.randint(2, 6),
                                     n_events=rng.randint(20, 60),
                                     p_info=0.0)
        if i % 3 == 0:
            h = histgen.mutate(rng, h)
        hs.append(h)
    batch = pack_batch(hs, M.cas_register())
    streams, _ = _stream_segments(batch)
    succ = LJ.pad_succ(batch.memo.succ, 64, 64)
    sizes = dict(n_states=batch.memo.n_states,
                 n_transitions=batch.memo.n_transitions)
    P = max(batch.P + (batch.P & 1), 2)
    outs = []
    for sb in (segment_batch(batch), segment_batch(batch,
                                                   streams=streams)):
        st, fs, n = LJ.check_device_keys(
            succ, sb.inv_proc, sb.inv_tr, sb.ok_proc, sb.depth,
            B=len(batch), F=64, P=P, **sizes)
        fail_at = [int(sb.seg_index[b, int(fs[b])]) if int(fs[b]) >= 0
                   else -1 for b in range(len(batch))]
        outs.append((np.asarray(st).tolist(), fail_at,
                     np.asarray(n).tolist()))
    assert outs[0] == outs[1]


def test_pinned_slots_drive_multiword_packplan():
    """Slot renaming collapses wide-but-shallow histories, so the
    multi-word PackPlan dedup needs genuinely wide OPEN-call
    concurrency — crashed cas ops with an unreachable expected value
    pin slots forever at zero frontier cost. The device engine must
    agree with host at effective_slots ~19 (4 packed words)."""
    from comdb2_tpu.ops.synth import pinned_wide_history

    packed = pack_history(pinned_wide_history(18, with_reads=False))
    mm = make_memo(M.cas_register(), packed)
    hr = linear_host.check(mm, packed, max_configs=1 << 16)
    a = analysis(M.cas_register(), packed, backend="device")
    assert a.valid is True and hr.valid is True
    assert a.final_count == hr.final_count
    p_eff = a.info["effective_slots"]
    assert p_eff >= 18
    plan = LJ.make_pack_plan(mm.n_states, mm.n_transitions,
                             p_eff + (p_eff & 1))
    assert plan is not None and plan.n_words >= 3, plan


def test_analysis_valid_wide_p():
    rng = random.Random(5)
    h = histgen.register_history(rng, n_procs=16, n_events=300,
                                 p_info=0.0, max_pending=4)
    a = analysis(M.cas_register(), h, backend="device")
    assert a.valid is True
    assert a.info.get("effective_slots", 99) <= 5
