"""Mesh-vs-single-device bit-parity suite (8-device CPU mesh).

The shard-placement axis must be INVISIBLE in every verdict: sharded
and unsharded runs of the same batch return identical (status,
fail_at, n_final) across the register/cas, keyed, txn-closure and
shrink surfaces — including B not divisible by D (sentinel padding),
kernel escalation mid-batch on one shard, and the compile guard
proving observed lowerings stay inside the shard-extended
PROGRAMS.md inventory. The fused kernel's sharded semantics run here
through Pallas interpret mode (exact kernel as XLA ops; Mosaic is
TPU-only) — the real-chip twin is ``scripts/bench_multichip.py`` and
the ``multichip`` stage of ``check.sh``.
"""

import random

import numpy as np
import pytest

import histgen
from comdb2_tpu.checker import batch as CB
from comdb2_tpu.checker import linear_jax as LJ
from comdb2_tpu.checker import pallas_seg as PSEG
from comdb2_tpu.checker.batch import check_batch, pack_batch
from comdb2_tpu.models import model as M


def _mesh(n=8):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:n]), ("batch",))


def _mixed_histories(n, seed=72_000, keyed=False):
    hs = []
    for i in range(n):
        rng = random.Random(seed + i)
        h = histgen.register_history(
            rng, n_procs=rng.randint(2, 4),
            n_events=rng.randint(6, 28),
            p_info=0.1 if i % 3 == 0 else 0.0)
        if i % 2:
            h = histgen.mutate(rng, h)
        hs.append(h)
    return hs


# --- pure planning helpers ---------------------------------------------


def test_plan_shard_slices_layout():
    # 16 histories over 8 shards, cap 512: one slice, shard d owns
    # [2d, 2d+2)
    assert PSEG.plan_shard_slices(16, 8) == [(0, 16)]
    # per-shard cap 2 -> two slices, each 8*2 wide
    assert PSEG.plan_shard_slices(32, 8, max_stream_b=2) == \
        [(0, 16), (16, 32)]
    with pytest.raises(ValueError):
        PSEG.plan_shard_slices(10, 8)       # not a multiple of D


def test_merge_stream_shards_reassembles_slice_order():
    D, g = 4, 3
    res = np.zeros((D, 8, 3), np.int32)
    starts = []
    want = []
    k = 0
    for d in range(D):
        st = np.arange(g, dtype=np.int64) * 5
        for i in range(g):
            res[d, i] = (k % 3, (k % 2) * 2 + st[i] if k % 2 else -1,
                         k)
            want.append((k % 3, 2 if k % 2 else -1, k))
            k += 1
        starts.append(st)
    out = PSEG.merge_stream_shards(res[:, :, :], starts, D * g, D)
    assert out == want


# --- register/cas + keyed parity over the XLA sharded engines ----------


@pytest.mark.parametrize("engine", ["keys", "flat"])
def test_register_parity_b_not_divisible(engine):
    """13 mixed valid/invalid/info histories over 8 shards: verdicts
    bit-identical with the single-device engine, pads invisible."""
    batch = pack_batch(_mixed_histories(13), M.cas_register())
    solo = check_batch(batch, F=64, engine=engine)
    info: dict = {}
    st, fa, n = check_batch(batch, F=64, engine=engine, mesh=_mesh(),
                            info=info)
    assert info["engine"] == f"{engine}-sharded"
    assert info["batch"] == {"b": 13, "b_pad": 16, "pad": 3,
                             "shards": 8}
    assert st.shape == (13,)            # pads can never surface
    np.testing.assert_array_equal(st, solo[0])
    np.testing.assert_array_equal(fa, solo[1])
    np.testing.assert_array_equal(n, solo[2])


def test_register_parity_vs_vmap_oracle():
    """The retired vmap-sharded route survives as a TEST ORACLE — an
    independent sharded lowering the production engines must agree
    with (the round-7 contract for keeping it)."""
    batch = pack_batch(_mixed_histories(8, seed=81_000),
                       M.cas_register())
    succ = LJ.pad_succ(batch.memo.succ,
                       1 << (batch.memo.n_states - 1).bit_length(),
                       1 << (batch.memo.n_transitions - 1).bit_length())
    P = max(batch.P, 2) + (max(batch.P, 2) & 1)
    st_o, _, n_o = (np.asarray(x) for x in LJ.check_sharded(
        _mesh(), succ, batch.kind, batch.proc, batch.tr, F=64, P=P,
        n_states=batch.memo.n_states,
        n_transitions=batch.memo.n_transitions))
    st, _, n = check_batch(batch, F=64, engine="keys", mesh=_mesh())
    np.testing.assert_array_equal(st, st_o)
    ok = st == LJ.VALID
    np.testing.assert_array_equal(n[ok], n_o[ok])


def test_keyed_parity():
    """Keyed (independent per-key) histories through the mesh: the
    keyed wrap splits one multi-key history into per-key
    sub-histories — exactly the batch axis the mesh shards."""
    from comdb2_tpu.checker.independent import (history_keys,
                                                subhistory,
                                                wrap_keyed_history)
    from comdb2_tpu.ops import op as O

    rng = random.Random(4242)
    ops = []
    for i in range(120):
        k = rng.randrange(6)
        p = rng.randrange(3)
        v = rng.randrange(3)
        ops.append(O.invoke(p, "write", (k, v)))
        ops.append(O.ok(p, "write", (k, v)))
    wrapped = wrap_keyed_history(ops)
    subs = [subhistory(k, wrapped) for k in history_keys(wrapped)]
    assert len(subs) >= 4
    batch = pack_batch(subs, M.cas_register())
    solo = check_batch(batch, F=64, engine="keys")
    st, fa, n = check_batch(batch, F=64, engine="keys", mesh=_mesh())
    np.testing.assert_array_equal(st, solo[0])
    np.testing.assert_array_equal(fa, solo[1])
    np.testing.assert_array_equal(n, solo[2])


def test_all_shard_sizes_match():
    """D in {1, 2, 4, 8}: every mesh width returns the same verdicts
    (dispatch-width scaling changes shapes, never answers)."""
    batch = pack_batch(_mixed_histories(11, seed=90_000),
                       M.cas_register())
    solo = check_batch(batch, F=64, engine="keys")
    for d in (1, 2, 4, 8):
        st, fa, n = check_batch(batch, F=64, engine="keys",
                                mesh=_mesh(d))
        np.testing.assert_array_equal(st, solo[0], err_msg=f"D={d}")
        np.testing.assert_array_equal(fa, solo[1], err_msg=f"D={d}")
        np.testing.assert_array_equal(n, solo[2], err_msg=f"D={d}")


def test_non_pow2_mesh_rejected():
    with pytest.raises(ValueError, match="power of two"):
        check_batch(pack_batch(_mixed_histories(4), M.cas_register()),
                    F=64, engine="keys", mesh=_mesh(3))


# --- txn closure parity ------------------------------------------------


def test_txn_closure_parity():
    from comdb2_tpu.txn import closure_jax as CJ
    from comdb2_tpu.txn.scc import cyclic_layers_host

    rng = np.random.default_rng(11)
    B, N = 5, 32
    adjs = np.zeros((B, 4, N, N), bool)
    for b in range(B):
        n_edges = int(rng.integers(4, 40))
        for _ in range(n_edges):
            i, j = rng.integers(0, N, 2)
            if i != j:
                adjs[b, int(rng.integers(0, 3)), i, j] = True
    solo = CJ.closure_diag_batch(adjs)
    d0 = CJ.DISPATCHES
    sharded = CJ.closure_diag_batch(adjs, mesh=_mesh())
    assert CJ.DISPATCHES - d0 == 1          # ONE dispatch, all shards
    assert sharded.shape == (B, 3, N)       # pads sliced off
    np.testing.assert_array_equal(sharded, solo)
    # host oracle agrees per graph
    for b in range(B):
        host = cyclic_layers_host(adjs[b], realtime=True)
        np.testing.assert_array_equal(sharded[b], host)


def test_txn_shrink_parity():
    """Txn-granularity minimal-cycle shrink with the verdict buckets
    sharded: same minimal txn set, same certificate. Seed: a write-
    skew rw ring of 8 txns plus an audit read (the -T signature)."""
    from comdb2_tpu.ops import op as O
    from comdb2_tpu.shrink import TxnShrinker

    k = 8
    h = []
    for i in range(k):
        mops = (("r", i, None), ("append", (i + 1) % k, 1))
        done = (("r", i, ()), ("append", (i + 1) % k, 1))
        h.append(O.invoke(i, "txn", mops))
        h.append(O.Op(i, "ok", "txn", done))
    audit = tuple(("r", i, (1,)) for i in range(k))
    h.append(O.invoke(k, "txn",
                      tuple(("r", i, None) for i in range(k))))
    h.append(O.Op(k, "ok", "txn", audit))

    def run(mesh):
        job = TxnShrinker(h, mesh=mesh)
        while not job.step():
            pass
        assert job.error is None
        return job.result()

    solo, sharded = run(None), run(_mesh())
    assert solo.valid is False and sharded.valid is False
    assert sharded.extra["txns"] == solo.extra["txns"]
    assert sharded.one_minimal and solo.one_minimal
    assert sharded.n_ops == solo.n_ops


# --- shrink (linear axis) parity ---------------------------------------


def test_shrink_parity_mesh():
    """Completion-pair ddmin with candidate verdict buckets sharded
    over the mesh: identical minimal history and certificate."""
    from comdb2_tpu.ops.synth import inject_anomaly, register_history
    from comdb2_tpu.shrink import Shrinker

    rng = random.Random(17)
    base = register_history(rng, n_procs=3, n_events=60, p_info=0.0)
    seed, _ = inject_anomaly(base, "stale-read")

    def run(mesh):
        job = Shrinker(seed, "cas-register", F=64, engine="keys",
                       mesh=mesh)
        while not job.step():
            pass
        assert job.error is None
        return job.result()

    solo, sharded = run(None), run(_mesh())
    assert solo.valid is False and sharded.valid is False
    assert sharded.n_ops == solo.n_ops
    assert sharded.one_minimal and solo.one_minimal
    assert [(o.process, o.type, o.f, o.value) for o in sharded.ops] \
        == [(o.process, o.type, o.f, o.value) for o in solo.ops]


# --- sentinel-pad exclusion (satellite: D|B padding accounting) --------


def test_pads_never_surface_anywhere():
    """3 histories over 8 shards: 5 sentinel pads are dispatched but
    can never surface — verdict arrays stay length 3, fail indices
    stay in-history, and the info accounting names the pad factor."""
    hs = _mixed_histories(3, seed=55_000)
    batch = pack_batch(hs, M.cas_register())
    info: dict = {}
    st, fa, n = check_batch(batch, F=64, engine="keys", mesh=_mesh(),
                            info=info)
    assert info["batch"] == {"b": 3, "b_pad": 8, "pad": 5,
                             "shards": 8}
    assert st.shape == fa.shape == n.shape == (3,)
    for b in range(3):
        assert -1 <= fa[b] < len(batch.packeds[b])


def test_shrink_candidates_exclude_pads():
    """Shrink verdict buckets under the mesh: the status array aligns
    with the requested masks exactly (pad candidates vanish)."""
    from comdb2_tpu.models.memo import memoize_model, transitions_of
    from comdb2_tpu.ops.packed import pack_history
    from comdb2_tpu.ops.synth import inject_anomaly, register_history
    from comdb2_tpu.shrink.verdicts import check_candidates

    rng = random.Random(23)
    seed, _ = inject_anomaly(
        register_history(rng, n_procs=3, n_events=40, p_info=0.0),
        "stale-read")
    parent = pack_history(seed)
    memo = memoize_model(M.cas_register(), transitions_of(parent),
                         max_depth=len(seed))
    full = np.ones(len(parent), bool)
    masks = [full.copy() for _ in range(3)]
    st = check_candidates(parent, masks, memo, F=64, engine="keys",
                          mesh=_mesh())
    assert st.shape == (3,)
    assert (st == LJ.INVALID).all()


# --- the fused kernel on the mesh (interpret mode) ---------------------


@pytest.fixture()
def interpret_kernel():
    PSEG.use_interpret(True)
    yield
    PSEG.use_interpret(False)


def test_stream_sharded_single_dispatch_counters(interpret_kernel):
    """One fused dispatch per slice covering all shards — and the
    Mosaic/XLA program count must NOT scale with D (the per-shard
    body is the same compiled kernel scan)."""
    rng = random.Random(909)
    hs = [histgen.register_history(rng, n_procs=4, n_events=40,
                                   values=3, p_info=0.0)
          for _ in range(4)]
    hs.append(histgen.mutate(rng, hs[0]))
    hs = hs * 2                                     # 10 histories
    batch = pack_batch(hs, M.cas_register())
    d0, m0 = PSEG.DISPATCHES, PSEG.MOSAIC_BUILDS
    info: dict = {}
    st_s, fa_s, n_s = check_batch(batch, F=PSEG.F, mesh=_mesh(),
                                  engine="stream", info=info)
    assert info["engine"] == "stream-sharded"
    assert PSEG.DISPATCHES - d0 == 1        # one slice -> ONE dispatch
    builds_first = PSEG.MOSAIC_BUILDS - m0
    # a second run at another D must reuse the per-shard program
    batch2 = pack_batch(hs, M.cas_register())
    m1 = PSEG.MOSAIC_BUILDS
    st2, fa2, n2 = check_batch(batch2, F=PSEG.F, mesh=_mesh(4),
                               engine="stream")
    assert PSEG.MOSAIC_BUILDS - m1 <= builds_first
    np.testing.assert_array_equal(st_s, st2)
    np.testing.assert_array_equal(fa_s, fa2)
    # keys parity (counts compare on VALID only)
    st_k, fa_k, n_k = check_batch(batch, F=PSEG.F, mesh=_mesh(),
                                  engine="keys")
    np.testing.assert_array_equal(st_s, st_k)
    np.testing.assert_array_equal(fa_s, fa_k)
    ok = st_s == LJ.VALID
    np.testing.assert_array_equal(n_s[ok], n_k[ok])


def test_escalation_mid_batch_on_one_shard(interpret_kernel):
    """One shard's history overflows the kernel's fixed F=128 while
    the other shards stay clean: exactly that history re-runs through
    the XLA sharded engine at the caller's F and every verdict stays
    bit-identical with the all-XLA run."""
    from comdb2_tpu.ops import op as O

    def overflow_history(k):
        h = [O.invoke(p, "write", p) for p in range(k)]
        h += [O.ok(p, "write", p) for p in range(k)]
        return h

    rng = random.Random(13)
    hs = [histgen.register_history(rng, n_procs=4, n_events=24,
                                   p_info=0.0) for _ in range(7)]
    hs.append(overflow_history(6))          # 193-config closure
    batch = pack_batch(hs, M.cas_register())
    info: dict = {}
    st, fa, n = check_batch(batch, F=256, mesh=_mesh(),
                            engine="stream", info=info)
    assert info["engine"] == "stream-sharded"
    esc = info.get("escalated")
    assert esc and esc["count"] == 1 and esc["engine"], info
    solo = check_batch(pack_batch(hs, M.cas_register()), F=256,
                       engine="keys")
    np.testing.assert_array_equal(st, solo[0])
    np.testing.assert_array_equal(fa, solo[1])


# --- compile guard over the shard-extended inventory -------------------


def test_guard_closed_over_mesh_workload():
    """Mixed sharded check/txn/shrink traffic under the guard:
    observed lowerings ⊆ the shard-extended PROGRAMS.md inventory."""
    from comdb2_tpu.analysis import compile_surface as CS
    from comdb2_tpu.txn import closure_jax as CJ
    from comdb2_tpu.utils import compile_guard as CG
    from comdb2_tpu.utils import next_pow2

    inv = CS.static_inventory()
    mesh = _mesh()
    with CG.guard() as g:
        for n_ev, B in ((24, 5), (48, 13)):
            hs = _mixed_histories(B, seed=30_000 + n_ev)
            batch = pack_batch(hs, M.cas_register())
            ns = next_pow2(batch.memo.n_states)
            nt = next_pow2(batch.memo.n_transitions)
            for engine in ("keys", "flat"):
                check_batch(batch, F=64, engine=engine, mesh=mesh,
                            s_pad=8, k_pad=2, n_states_pad=ns,
                            n_transitions_pad=nt)
        CJ.closure_diag_batch(np.zeros((3, 4, 32, 32), bool),
                              mesh=mesh)
    off = g.offenders(inv)
    assert off == [], [r.format() for r in off]
    g.assert_closed(inv)
    names = {r.name for r in g.records}
    assert "check_device_keys_sharded" in names \
        or not g.records            # warm persistent cache: no logs?
