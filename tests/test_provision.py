"""Provisioning end-to-end: SutNodeDB installs/configures/cycles the
SUT through a Remote transport during ``harness.run`` itself — nothing
pre-arranged by the test (round-3 VERDICT Missing #4 / Next #8; the
``scripts/newdb``/``setvars`` role, ``jepsen/db.clj:4-25``)."""

import os
import socket

import pytest

from comdb2_tpu.checker.workloads import bank_checker
from comdb2_tpu.control.remote import LocalRemote, RecordingRemote
from comdb2_tpu.harness import core, fake
from comdb2_tpu.harness import generator as G
from comdb2_tpu.harness.provision import (NodeLayout, SutNodeDB,
                                          local_layout)
from comdb2_tpu.workloads import comdb2 as W
from comdb2_tpu.workloads.tcp import BankTcpClient

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(ROOT, "native", "build", "sut_node")

pytestmark = pytest.mark.skipif(not os.path.exists(BINARY),
                                reason="sut_node not built")


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_provisioned_cluster_end_to_end(tmp_path):
    """harness.run provisions a 3-node cluster from a bare base dir
    (upload + config + daemon start + readiness + primary gate), runs
    the bank workload over it, snarfs the SUT logs, and tears the
    daemons down — the jepsenloop shape with provisioning inside the
    run."""
    nodes = ["n1", "n2", "n3"]
    ports = _free_ports(3)
    base = str(tmp_path / "sut")
    db = SutNodeDB(LocalRemote(), BINARY, local_layout(nodes, ports),
                   base_dir=base, timeout_ms=500, elect_ms=500,
                   lease_ms=300)
    n = 4
    t = fake.noop_test()
    t.update({
        "nodes": nodes, "concurrency": 4, "name": "provisioned-bank",
        "store-root": str(tmp_path / "store"),
        "db": db,
        "client": BankTcpClient(ports, n=n, timeout_s=0.6),
        "model": {"n": n, "total": n * 10},
        "_bank_n": n,
        "generator": G.clients(G.time_limit(3.0, G.stagger(
            0.01, G.mix([W.bank_read, W.bank_diff_transfer])))),
        "checker": bank_checker,
    })
    result = core.run(t)
    try:
        assert result["results"]["valid?"] is True, result["results"]
        reads = [op for op in result["history"]
                 if op.type == "ok" and op.f == "read"]
        assert len(reads) >= 10, len(reads)
        # the provisioner's artifacts exist: config + logs per node
        for node in nodes:
            assert os.path.exists(f"{base}/{node}/config")
            assert os.path.getsize(f"{base}/{node}/sut.log") > 0
        # teardown actually killed the daemons: pidfiles removed and
        # the ports refuse connections
        for node, port in zip(nodes, ports):
            assert not os.path.exists(f"{base}/{node}/pid")
            s = socket.socket()
            s.settimeout(0.5)
            try:
                rc = s.connect_ex(("127.0.0.1", port))
            finally:
                s.close()
            assert rc != 0, f"{node} still listening on {port}"
    finally:
        # belt-and-braces: never leak daemons on assertion failure
        for node in nodes:
            db.teardown(t, node)


def test_provision_cycle_wipes_state(tmp_path):
    """db.cycle (teardown+setup) gives a FRESH cluster: state written
    before the cycle is gone after (the newdb/recreatedb role)."""
    nodes = ["a", "b", "c"]
    ports = _free_ports(3)
    db = SutNodeDB(LocalRemote(), BINARY, local_layout(nodes, ports),
                   base_dir=str(tmp_path / "sut"))
    test = {"nodes": nodes}
    try:
        for node in nodes:
            db.setup(test, node)
        db.setup_primary(test, nodes[0])
        from comdb2_tpu.workloads.tcp import SutConnection
        # write through whichever node forwards to the leader
        c = SutConnection("127.0.0.1", ports[0], timeout_s=2.0)
        c.connect()
        assert c.request("M 1 W 5 42").startswith(("OK", "V"))
        assert c.request("R 5") == "V 42"
        c.close()
        from comdb2_tpu.harness import db as db_ns
        for node in nodes:
            db_ns.cycle(db, test, node)
        db.setup_primary(test, nodes[0])
        c = SutConnection("127.0.0.1", ports[1], timeout_s=2.0)
        c.connect()
        assert c.request("R 5") == "NIL"        # state wiped
        c.close()
    finally:
        for node in nodes:
            db.teardown(test, node)


def test_provision_ssh_command_shape():
    """The SSHRemote path issues the same command stream (recorded
    transport): install, config artifact, daemon start with pidfile,
    readiness probes — per host, no pre-arranged state."""
    rec = RecordingRemote()
    from comdb2_tpu.control.remote import ExecResult

    def responder(host, cmd):
        if "/dev/tcp" in cmd:
            return ExecResult(0, "PONG\n" if "printf \"P" in cmd
                              else "I 0 primary 0 0 1 0\n", "")
        return ExecResult(0, "", "")

    rec.responder = responder
    nodes = ["m1", "m2", "m3"]
    layout = {n: NodeLayout(n, 19000) for n in nodes}   # real hosts
    db = SutNodeDB(rec, "/bin/true", layout, base_dir="/opt/sut")
    test = {"nodes": nodes}
    for n in nodes:
        db.setup(test, n)
    db.setup_primary(test, nodes[0])
    hosts = {h for h, _ in rec.commands}
    assert hosts == set(nodes)
    assert [u[0] for u in rec.uploads] == nodes          # binary per host
    joined = "\n".join(c for _, c in rec.commands)
    assert "mkdir -p /opt/sut/m1" in joined
    assert "-n m1:19000,m2:19000,m3:19000" in joined     # host:port mesh
    assert "> /opt/sut/m2/config" in joined
    assert "echo $! > /opt/sut/m3/pid" in joined
    for n in nodes:
        db.teardown(test, n)
    assert any("kill -9" in c for _, c in rec.commands)
